"""Snowflake destination: real Snowpipe Streaming REST + keypair JWT.

Reference parity: crates/etl-destinations/src/snowflake/ (6.2k LoC):
  - Snowpipe Streaming wire protocol — hostname discovery, per-table
    channels under `pipes/{table}-STREAMING`, continuation-token chaining,
    zstd NDJSON row bodies, offset-token dedup and commit proof — lives in
    `snowpipe.py` (streaming/{rest_client,channel,batch,offset_token}.rs);
  - JWT keypair auth (auth.rs): RS256 tokens with the
    account.user.SHA256:fingerprint issuer convention, invalidated and
    re-signed when the API reports auth expiry;
  - SQL client for DDL (sql_client.rs) via the statements REST API;
  - CDC metadata columns `_cdc_operation` / `_cdc_sequence_number`
    (schema.rs:6-7, encoding.rs CdcMeta).

Durability model: the reference defers commit proof behind Accepted acks
and waits at pipeline barriers (core.rs:260-275). Here each write call
runs its own barrier before acking durable — the 64-batch/256 MB copy
window still amortizes status polls across the many batches of one call,
and the ack never claims durability Snowflake hasn't proven.
"""

from __future__ import annotations

import asyncio
import base64
import json
import time
from dataclasses import dataclass
from typing import Sequence

import aiohttp

from ..models.errors import ErrorKind, EtlError
from ..models.event import (ChangeType, DeleteEvent, Event, InsertEvent,
                            SchemaChangeEvent, TruncateEvent, UpdateEvent)
from ..models.pgtypes import CellKind
from ..models.schema import ReplicatedTableSchema, TableId
from ..models.table_row import ColumnarBatch
from .base import CommitRange, Destination, WriteAck, expand_batch_events
from ..models.default_expression import column_default_sql
from .bigquery import encode_value  # same JSON value encoding rules
from ..analysis.annotations import transactional_commit
from .snowpipe import (ZERO_OFFSET, AcceptedBatch, ChannelHandle,
                       RestStreamClient, RowBatch, RowBatchBuilder,
                       decode_offset_token, offset_token)
from .util import (DestinationRetryPolicy, count_egress_write,
                   escaped_table_name, classify_http_error,
                   require_full_batch, require_full_row,
                   sequential_event_program, with_retries)

# CDC metadata column names (reference schema.rs:6-7)
CDC_OPERATION_COLUMN = "_cdc_operation"
CDC_SEQUENCE_COLUMN = "_cdc_sequence_number"

_SF_TYPES: dict[CellKind, str] = {
    CellKind.BOOL: "BOOLEAN", CellKind.I16: "NUMBER(5,0)",
    CellKind.I32: "NUMBER(10,0)", CellKind.U32: "NUMBER(10,0)",
    CellKind.I64: "NUMBER(19,0)", CellKind.F32: "FLOAT",
    CellKind.F64: "FLOAT", CellKind.NUMERIC: "VARCHAR",
    CellKind.DATE: "DATE", CellKind.TIME: "TIME",
    CellKind.TIMESTAMP: "TIMESTAMP_NTZ",
    CellKind.TIMESTAMPTZ: "TIMESTAMP_TZ", CellKind.UUID: "VARCHAR(36)",
    CellKind.JSON: "VARIANT", CellKind.BYTES: "BINARY",
    CellKind.STRING: "VARCHAR", CellKind.ARRAY: "VARIANT",
    CellKind.INTERVAL: "VARCHAR",
}

_OP_LABEL = {ChangeType.INSERT: "insert", ChangeType.UPDATE: "update",
             ChangeType.DELETE: "delete"}


# -- columnar NDJSON encoding (egress hot path) -------------------------------

import numpy as np
from json.encoder import encode_basestring  # what json.dumps uses inside

from ..analysis.annotations import hot_loop
from ..models.table_row import Column


def offset_token_batch(commit_lsns, tx_ordinals) -> list[str]:
    """Vectorized `offset_token` for a batch: `{lsn:016x}/{ord:016x}`
    per row off one fixed-width hex buffer (the sequence_number_buffer
    idiom), no per-row format calls."""
    from .util import _hex16

    commit_lsns = np.asarray(commit_lsns, dtype=np.uint64)
    n = len(commit_lsns)
    buf = np.empty((n, 33), dtype=np.uint8)
    _hex16(commit_lsns, buf[:, 0:16])
    buf[:, 16] = ord("/")
    _hex16(np.asarray(tx_ordinals, dtype=np.uint64), buf[:, 17:33])
    return [s.decode() for s in buf.reshape(-1).view("S33").tolist()]


@hot_loop
def _column_json_texts(col: Column) -> list:
    """One column's JSON value literals (str per row, "null" for SQL
    NULL), rendered column-at-a-time: one kind dispatch per column,
    dense numpy data stringified without boxing into Python objects.
    Byte-identical to `json.dumps(encode_value(col.value(i), kind),
    separators=(",", ":"), ensure_ascii=False, allow_nan=False)` per
    row. @hot_loop: per column per CDC flush (etl-lint rule 13)."""
    n = len(col)
    kind = col.schema.kind
    valid = col.validity
    if col.toast_unchanged is not None:
        valid = valid & ~col.toast_unchanged
    out: list = ["null"] * n
    present = np.flatnonzero(valid)
    if present.size == 0:
        return out
    if col.is_dense and kind is CellKind.BOOL:
        data = col.data
        for i in present.tolist():
            out[i] = "true" if data[i] else "false"
        return out
    if col.is_dense and kind in (CellKind.I16, CellKind.I32, CellKind.U32,
                                 CellKind.I64):
        texts = col.data.astype("U21")  # same digits as str(int)
        for i in present.tolist():
            out[i] = texts[i]
        return out
    if col.is_dense and kind in (CellKind.F32, CellKind.F64):
        if not np.isfinite(col.data[present]).all():
            # reference encoding.rs rejects non-finite floats — the row
            # path raises the same way at push_row (allow_nan=False)
            raise EtlError(
                ErrorKind.DESTINATION_FAILED,
                "snowpipe: row not JSON-encodable: Out of range float "
                "values are not allowed")
        data = col.data.tolist()  # Python floats: repr == json.dumps
        for i in present.tolist():
            out[i] = repr(data[i])
        return out
    if col.is_arrow and kind is CellKind.STRING and col.lazy_text_oid is None:
        vals = col.data.to_pylist()
        for i in present.tolist():
            out[i] = encode_basestring(vals[i])
        return out
    # generic fallback (NUMERIC/temporal/JSON/bytes/arrays/lazy-text):
    # box the value, reuse the row path's exact encoding
    for i in present.tolist():
        out[i] = json.dumps(encode_value(col.value(i), kind),
                            separators=(",", ":"), ensure_ascii=False,
                            allow_nan=False)
    return out


def _encode_cdc_batch(schema: ReplicatedTableSchema,
                      cb) -> "RowBatchBuilder":
    """Render one CoalescedBatch into a RowBatchBuilder: vectorized op
    labels + offset tokens, columnar NDJSON lines. Pure CPU work, kept
    out of the async write path (etl-lint rule 2; the @hot_loop markers
    live on the per-column/per-batch encoders below — this wrapper's
    np.asarray is a host-side label array, not a device fetch)."""
    cts = np.asarray(cb.change_types)
    labels = np.where(
        cts == int(ChangeType.DELETE), "delete",
        np.where(cts == int(ChangeType.UPDATE), "update",
                 "insert")).tolist()
    seqs = offset_token_batch(cb.commit_lsns, cb.tx_ordinals)
    builder = RowBatchBuilder()
    try:
        lines, used_device = encode_batch_ndjson_fast(
            schema, cb.batch, labels, seqs, egress=cb.egress)
        count_egress_write(used_device)
    except EtlError:
        raise  # typed rejections (non-finite floats) are the contract
    except Exception:  # assembly bug → fall back, never fail the write
        lines = encode_batch_ndjson(schema, cb.batch, labels, seqs)
    for line, seq in zip(lines, seqs):
        builder.push_encoded_line(line, seq)
    return builder


@hot_loop
def encode_batch_ndjson(schema: ReplicatedTableSchema, batch: ColumnarBatch,
                        ops, seqs) -> list[bytes]:
    """Whole-batch NDJSON: column-at-a-time value rendering + one join
    per row — each returned line (newline included) is byte-identical to
    the row path's `json.dumps(_doc(...), separators=(",", ":"),
    ensure_ascii=False, allow_nan=False) + "\\n"`. `ops`/`seqs` are
    per-row strs or one shared str (the copy path). @hot_loop: the
    Snowpipe egress hot path (etl-lint rule 13)."""
    n = batch.num_rows
    keys = [encode_basestring(c.schema.name) + ":" for c in batch.columns]
    cols = [_column_json_texts(c) for c in batch.columns]
    op_key = encode_basestring(CDC_OPERATION_COLUMN) + ":"
    seq_key = encode_basestring(CDC_SEQUENCE_COLUMN) + ":"
    if isinstance(ops, str):
        ops = [encode_basestring(ops)] * n
    else:
        ops = [encode_basestring(o) for o in ops]
    if isinstance(seqs, str):
        seqs = [encode_basestring(seqs)] * n
    else:
        seqs = [encode_basestring(s) for s in seqs]
    lines = []
    for i in range(n):
        fields = [k + c[i] for k, c in zip(keys, cols)]
        fields.append(op_key + ops[i])
        fields.append(seq_key + seqs[i])
        lines.append(("{" + ",".join(fields) + "}\n").encode())
    return lines


_JSON_FIXED_KINDS = (CellKind.BOOL, CellKind.I16, CellKind.I32,
                     CellKind.U32, CellKind.I64)


@hot_loop
def encode_batch_ndjson_fast(schema: ReplicatedTableSchema,
                             batch: ColumnarBatch, ops, seqs,
                             egress=None) -> "tuple[list[bytes], bool]":
    """Whole-batch NDJSON via byte-piece assembly: int/bool fields come
    from device-rendered egress buffers when attached (numpy twins
    otherwise, NULLs patched to `null`), every other kind reuses
    `_column_json_texts` verbatim, and untrusted rows are overridden
    with the per-row oracle line. One scatter builds the body; lines are
    sliced back out for the Snowpipe compressor. Byte-identical to
    `encode_batch_ndjson` (gated). Returns (lines, used_device).
    @hot_loop: the Snowpipe egress hot path (etl-lint rule 13)."""
    from ..ops import egress as eg

    n = batch.num_rows
    oracle_rows: set = set()
    if egress is not None and egress.untrusted.size:
        oracle_rows.update(egress.untrusted.tolist())
    comma = eg.const_piece(b",")
    pieces = [eg.const_piece(b"{")]
    used_device = False
    # per-column value-text source, kept for the override rows: either the
    # oracle texts list or the (col, valid) pair the dense renderer used
    sources: list = []
    for j, col in enumerate(batch.columns):
        pieces.append(eg.const_piece(
            (encode_basestring(col.schema.name) + ":").encode()))
        kind = col.schema.kind
        dev = egress.field(j) if egress is not None else None
        if col.is_dense and kind in _JSON_FIXED_KINDS:
            valid = col.validity
            if col.toast_unchanged is not None:
                valid = valid & ~col.toast_unchanged
            nulls = np.flatnonzero(~valid)
            if dev is not None:
                buf, lens = eg.patch_rows_fixed(dev[0], dev[1], nulls,
                                                b"null")
                used_device = True
            else:
                buf, lens = eg.bool_text_fixed(col.data) \
                    if kind is CellKind.BOOL \
                    else eg.int_text_fixed(col.data)
                buf, lens = eg.patch_rows_fixed(buf, lens, nulls, b"null")
            pieces.append(eg.fixed_piece(buf, lens))
            sources.append((col, valid))
        else:
            # the oracle's own column renderer — identity by construction
            # (raises the same non-finite-float EtlError the row path does)
            texts = _column_json_texts(col)
            pieces.append(eg.var_from_texts(
                [str(t).encode() for t in texts]))
            sources.append(texts)
        pieces.append(comma)
    pieces.append(eg.const_piece(
        (encode_basestring(CDC_OPERATION_COLUMN) + ":").encode()))
    if isinstance(ops, str):
        pieces.append(eg.const_piece(encode_basestring(ops).encode()))
    else:
        pieces.append(eg.var_from_texts(
            [encode_basestring(o).encode() for o in ops]))
    pieces.append(comma)
    pieces.append(eg.const_piece(
        (encode_basestring(CDC_SEQUENCE_COLUMN) + ":").encode()))
    if isinstance(seqs, str):
        pieces.append(eg.const_piece(encode_basestring(seqs).encode()))
    else:
        pieces.append(eg.var_from_texts(
            [encode_basestring(s).encode() for s in seqs]))
    pieces.append(eg.const_piece(b"}\n"))
    override = None
    if oracle_rows:

        def _text(src, i):
            if isinstance(src, list):
                return str(src[i])
            col, valid = src
            if not valid[i]:
                return "null"
            if col.schema.kind is CellKind.BOOL:
                return "true" if col.data[i] else "false"
            return str(int(col.data[i]))  # same digits as the U21 twin

        override = {}
        keys = [encode_basestring(c.schema.name) + ":"
                for c in batch.columns]
        for i in sorted(oracle_rows):
            fields = [k + _text(src, i)
                      for k, src in zip(keys, sources)]
            fields.append(encode_basestring(CDC_OPERATION_COLUMN) + ":"
                          + encode_basestring(
                              ops if isinstance(ops, str) else ops[i]))
            fields.append(encode_basestring(CDC_SEQUENCE_COLUMN) + ":"
                          + encode_basestring(
                              seqs if isinstance(seqs, str) else seqs[i]))
            override[i] = ("{" + ",".join(fields) + "}\n").encode()
    out, starts = eg.assemble_rows(n, pieces, override)
    body = out.tobytes()
    return ([body[starts[i]:starts[i + 1]] for i in range(n)],
            used_device)


@dataclass(frozen=True)
class SnowflakeConfig:
    base_url: str  # account REST endpoint (fake server in tests)
    account: str
    user: str
    database: str
    schema: str = "PUBLIC"
    private_key_pem: str = ""  # PKCS#8 RSA key for JWT; "" = no auth header
    pipeline_id: int = 0  # channel names embed it (channel.rs:251)
    commit_poll_interval_s: float = 0.5  # channel.rs:22
    commit_wait_timeout_s: float = 180.0  # channel.rs:28


def make_jwt(config: SnowflakeConfig, lifetime_s: int = 3600) -> str:
    """RS256 keypair JWT (reference auth.rs)."""
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding

    key = serialization.load_pem_private_key(
        config.private_key_pem.encode(), password=None)
    pub = key.public_key().public_bytes(
        serialization.Encoding.DER,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    import hashlib

    fp = base64.b64encode(hashlib.sha256(pub).digest()).decode()
    qualified = f"{config.account.upper()}.{config.user.upper()}"
    now = int(time.time())
    header = {"alg": "RS256", "typ": "JWT"}
    claims = {"iss": f"{qualified}.SHA256:{fp}", "sub": qualified,
              "iat": now, "exp": now + lifetime_s}

    def b64(d: dict) -> bytes:
        return base64.urlsafe_b64encode(
            json.dumps(d, separators=(",", ":")).encode()).rstrip(b"=")

    signing_input = b64(header) + b"." + b64(claims)
    sig = key.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
    return (signing_input + b"."
            + base64.urlsafe_b64encode(sig).rstrip(b"=")).decode()


class _KeyPairTokenProvider:
    """Caches the signed JWT until near expiry; `invalidate_token` forces a
    re-sign on the next request (reference auth.rs TokenProvider)."""

    def __init__(self, config: SnowflakeConfig):
        self.config = config
        self._cached: tuple[str, float] | None = None

    async def get_token(self) -> str:
        if not self.config.private_key_pem:
            return ""
        now = time.time()
        if self._cached is None or now > self._cached[1] - 60:
            self._cached = (make_jwt(self.config), now + 3600)
        return self._cached[0]

    def invalidate_token(self) -> None:
        self._cached = None


class SnowflakeDestination(Destination):
    egress_encoder = "json"  # device-rendered NDJSON fields (ops/egress.py)

    def __init__(self, config: SnowflakeConfig,
                 retry: DestinationRetryPolicy | None = None):
        self.config = config
        self.retry = retry or DestinationRetryPolicy()
        self.auth = _KeyPairTokenProvider(config)
        self._session: aiohttp.ClientSession | None = None
        self._stream = RestStreamClient(config.base_url, self.auth,
                                        self._get_session, self.retry)
        self._created: dict[TableId, ReplicatedTableSchema] = {}
        self._names: dict[TableId, str] = {}
        self._channels: dict[TableId, ChannelHandle] = {}
        # ChannelHandle mirrors the Rust original's &mut self methods: it
        # is NOT safe under concurrent callers (continuation tokens chain
        # across awaits). Parallel copy partitions hit the same table's
        # channel, so every channel interaction holds this per-table lock.
        self._table_locks: dict[TableId, asyncio.Lock] = {}
        # exactly-once seam: DLQ replays route through dedicated `rp0`
        # channels — their rows sit BELOW the live channel's committed
        # offset, and the server's offset dedup would silently drop them
        # there (see write_event_batches_committed)
        self._replay_channels: dict[TableId, ChannelHandle] = {}
        self._replay_mode = False

    def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        return self._session

    # -- SQL statements API (sql_client.rs) ------------------------------------

    async def _sql(self, statement: str) -> dict:
        async def attempt() -> dict:
            headers = {}
            token = await self.auth.get_token()
            if token:
                headers["Authorization"] = f"Bearer {token}"
                headers["X-Snowflake-Authorization-Token-Type"] = \
                    "KEYPAIR_JWT"
            async with self._get_session().post(
                    f"{self.config.base_url}/api/v2/statements",
                    json={"statement": statement,
                          "database": self.config.database,
                          "schema": self.config.schema},
                    headers=headers) as resp:
                text = await resp.text()
                if resp.status == 401:
                    # auth expiry is transient once re-signed: invalidate
                    # the cached JWT and retry (reference auth.rs)
                    self.auth.invalidate_token()
                if resp.status >= 400:
                    if resp.status == 401:
                        # transient once re-signed (kept out of the
                        # shared map: the JWT invalidation above makes
                        # the retry meaningful)
                        raise EtlError(
                            ErrorKind.DESTINATION_THROTTLED,
                            f"snowflake 401 statements: {text[:300]}")
                    raise classify_http_error(
                        "snowflake", resp.status,
                        f"statements: {text[:300]}")
                return json.loads(text) if text else {}

        def retryable(e: BaseException) -> bool:
            if isinstance(e, EtlError):
                return e.kind is ErrorKind.DESTINATION_THROTTLED
            return isinstance(e, (aiohttp.ClientError, OSError))

        return await with_retries(attempt, self.retry, retryable)

    async def startup(self) -> None:
        await self._sql(
            f'CREATE SCHEMA IF NOT EXISTS "{self.config.schema}"')

    # -- table DDL -------------------------------------------------------------

    def _table_name(self, schema: ReplicatedTableSchema) -> str:
        return self._names.setdefault(
            schema.id, escaped_table_name(schema.name).upper())

    async def _ensure_table(self, schema: ReplicatedTableSchema) -> str:
        name = self._table_name(schema)
        if self._created.get(schema.id) == schema:
            return name
        for c in schema.replicated_columns:
            # reference schema.rs validate_no_cdc_collisions
            if c.name in (CDC_OPERATION_COLUMN, CDC_SEQUENCE_COLUMN):
                raise EtlError(
                    ErrorKind.CONFIG_INVALID,
                    f"snowflake: source column {c.name!r} collides with a "
                    f"CDC metadata column")
        identity = {c.name for c in schema.identity_columns()}
        # non-identity columns stay nullable: key-only DELETE rows carry
        # nulls for them
        def spec(c):
            s = f'"{c.name}" {_SF_TYPES.get(c.kind, "VARCHAR")}'
            default = column_default_sql(c, "snowflake")
            if default is not None:
                s += f" DEFAULT {default}"
            if not c.nullable and c.name in identity:
                s += " NOT NULL"
            return s

        cols = [spec(c) for c in schema.replicated_columns]
        cols.append(f'"{CDC_OPERATION_COLUMN}" VARCHAR NOT NULL')
        cols.append(f'"{CDC_SEQUENCE_COLUMN}" VARCHAR NOT NULL')
        await self._sql(f'CREATE TABLE IF NOT EXISTS "{name}" '
                        f'({", ".join(cols)})')
        self._created[schema.id] = schema
        return name

    # -- channels --------------------------------------------------------------

    def _channel(self, schema: ReplicatedTableSchema) -> ChannelHandle:
        table = self._replay_channels if self._replay_mode \
            else self._channels
        handle = table.get(schema.id)
        if handle is None:
            name = self._table_name(schema)
            suffix = "rp0" if self._replay_mode else "ch0"
            handle = ChannelHandle(
                self._stream, self.config.database, self.config.schema,
                name,
                channel=(f"etl_{self.config.pipeline_id}_"
                         f"{self.config.schema}_{name}_{suffix}"),
                poll_interval_s=self.config.commit_poll_interval_s,
                wait_timeout_s=self.config.commit_wait_timeout_s)
            table[schema.id] = handle
        return handle

    def _lock_for(self, table_id: TableId) -> asyncio.Lock:
        return self._table_locks.setdefault(table_id, asyncio.Lock())

    async def _open_channel(self, schema: ReplicatedTableSchema
                            ) -> ChannelHandle:
        handle = self._channel(schema)
        if not handle.is_open:
            await handle.open()
        return handle

    # -- row encoding ----------------------------------------------------------

    def _doc(self, schema: ReplicatedTableSchema, row, op: str,
             sequence: str) -> dict:
        doc = {c.name: encode_value(v, c.kind)
               for c, v in zip(schema.replicated_columns, row.values)}
        doc[CDC_OPERATION_COLUMN] = op
        doc[CDC_SEQUENCE_COLUMN] = sequence
        return doc

    # -- columnar encoding (egress hot path) -----------------------------------

    async def _stream_batches(self, schema: ReplicatedTableSchema,
                              batches: "list[RowBatch]") -> None:
        """Shared CDC tail of the row and columnar paths: accept the
        request bodies on the table's channel and wait out the
        aggregated commit proof (see _write_cdc_run for why the proof
        must cover EVERY accepted batch of the run)."""
        if not batches:
            return
        async with self._lock_for(schema.id):
            handle = await self._open_channel(schema)
            accepted = await handle.accept_streaming_batches(batches)
            if accepted:
                total = AcceptedBatch(
                    target_offset=accepted[-1].target_offset,
                    rows=sum(a.rows for a in accepted),
                    bytes=sum(a.bytes for a in accepted),
                    baseline_rows_inserted=
                        accepted[0].baseline_rows_inserted,
                    baseline_rows_error_count=
                        accepted[0].baseline_rows_error_count)
                await handle.wait_for_offsets_committed(
                    total.target_offset, total)

    # -- copy path -------------------------------------------------------------

    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        await self._ensure_table(schema)
        builder = RowBatchBuilder()
        for i in range(batch.num_rows):
            doc = {c.schema.name: encode_value(c.value(i), c.schema.kind)
                   for c in batch.columns}
            doc[CDC_OPERATION_COLUMN] = "insert"
            doc[CDC_SEQUENCE_COLUMN] = ZERO_OFFSET
            builder.push_row(doc, ZERO_OFFSET)
        return await self._finish_copy(schema, builder)

    async def write_table_batch(self, schema: ReplicatedTableSchema,
                                batch: ColumnarBatch) -> WriteAck:
        """Columnar COPY path: NDJSON lines rendered column-at-a-time —
        byte-identical to write_table_rows' per-row dict + json.dumps —
        then pushed pre-encoded through the same compressor."""
        await self._ensure_table(schema)
        builder = RowBatchBuilder()
        try:
            lines, used_device = encode_batch_ndjson_fast(
                schema, batch, "insert", ZERO_OFFSET,
                egress=getattr(batch, "device_egress", None))
            count_egress_write(used_device)
        except EtlError:
            raise
        except Exception:  # fall back — the write must never fail here
            lines = encode_batch_ndjson(schema, batch, "insert",
                                        ZERO_OFFSET)
        for line in lines:
            builder.push_encoded_line(line, ZERO_OFFSET)
        return await self._finish_copy(schema, builder)

    async def _finish_copy(self, schema: ReplicatedTableSchema,
                           builder: RowBatchBuilder) -> WriteAck:
        batches = builder.finish()
        if batches:
            async with self._lock_for(schema.id):
                handle = await self._open_channel(schema)
                await handle.accept_table_copy_batches(batches)
                await handle.wait_for_table_copy_durability()
        return WriteAck.durable()

    # -- CDC path --------------------------------------------------------------

    async def write_event_batches(self, events: Sequence[Event]) -> WriteAck:
        """CDC path, columnar: simple decoded batch runs render NDJSON
        column-at-a-time; old-tuple/TOAST batches and per-row events
        drop to the row path in place (sequential_batch_program
        preserves WAL order) — the same stance as the ClickHouse and
        BigQuery encoders."""
        from .base import sequential_batch_program

        for op in sequential_batch_program(events):
            if op[0] == "batch":
                _, schema, cb = op
                await self._write_cdc_batch(schema, cb)
            elif op[0] == "rows":
                _, schema, evs = op
                await self._write_cdc_run(schema, evs)
            elif op[0] == "truncate":
                for sch in op[1].schemas:
                    self._table_name(sch)
                    self._created.setdefault(sch.id, sch)
                    await self.truncate_table(sch.id)
            else:
                await self._apply_ddl(op[1])
        return WriteAck.durable()

    async def _write_cdc_batch(self, schema: ReplicatedTableSchema,
                               cb) -> None:
        await self._ensure_table(schema)
        require_full_batch("snowflake", schema, cb.batch, cb.change_types)
        builder = _encode_cdc_batch(schema, cb)
        await self._stream_batches(schema, builder.finish())

    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        for op in sequential_event_program(expand_batch_events(events)):
            if op[0] == "rows":
                _, schema, evs = op
                await self._write_cdc_run(schema, evs)
            elif op[0] == "truncate":
                for sch in op[1].schemas:
                    # register the mapping first: after a restart the
                    # truncate would otherwise silently no-op
                    self._table_name(sch)
                    self._created.setdefault(sch.id, sch)
                    await self.truncate_table(sch.id)
            else:
                await self._apply_ddl(op[1])
        return WriteAck.durable()

    async def _write_cdc_run(self, schema: ReplicatedTableSchema,
                             evs: list) -> None:
        await self._ensure_table(schema)
        builder = RowBatchBuilder()
        for e in evs:
            off = offset_token(int(e.commit_lsn), e.tx_ordinal)
            if isinstance(e, DeleteEvent):
                row, ct = e.old_row, ChangeType.DELETE
            else:
                row, ct = e.row, (ChangeType.UPDATE
                                  if isinstance(e, UpdateEvent)
                                  else ChangeType.INSERT)
                require_full_row("snowflake", schema, row)
            builder.push_row(self._doc(schema, row, _OP_LABEL[ct], off),
                             off)
        # durability barrier: don't ack until Snowflake proves the last
        # offset committed (_stream_batches aggregates EVERY accepted
        # batch of this run — validating only the last batch would let
        # rows silently dropped from an earlier batch pass the check
        # that exists to catch them)
        await self._stream_batches(schema, builder.finish())

    # -- transactional seam (docs/destinations.md exactly-once contract) ------
    #
    # Snowpipe Streaming IS a transactional sink: every insert ships its
    # WAL-coordinate offset-token range on the query string, the server
    # dedups re-streamed rows at-or-below the channel's committed offset,
    # and `wait_for_offsets_committed` is the atomic data+coordinate
    # commit. The seam therefore adds only (a) the replay channel split
    # and (b) reading the committed offsets back at recovery.

    def supports_transactional_commit(self) -> bool:
        return True

    @transactional_commit
    async def write_event_batches_committed(
            self, events: Sequence[Event], commit: CommitRange) -> WriteAck:
        """Committed CDC write. Streamed flushes take the normal path —
        the offset tokens already carried by every insert ARE the
        transactional coordinates. DLQ replays (`commit.replay`) route
        through per-table `rp0` channels: their rows sit below the live
        channel's committed offset and would be silently dropped by the
        server's dedup there, while the fresh replay channel accepts
        them once and dedups an identical re-run replay."""
        if not commit.replay:
            return await self.write_event_batches(events)
        self._replay_mode = True
        try:
            return await self.write_event_batches(events)
        finally:
            self._replay_mode = False

    async def recover_high_water(self) -> "CommitRange | None":
        """Max committed offset token across this destination's live
        channels (reopening each reads the server's persisted progress).
        With no channels yet — a cold process that has not streamed —
        there is nothing to ask; the caller degrades to the progress
        store and the per-channel offset dedup still bounds duplicates."""
        best: "tuple[int, int] | None" = None
        for tid in list(self._channels):
            handle = self._channels[tid]
            async with self._lock_for(tid):
                if not handle.is_open:
                    await handle.open()
            tok = handle.committed_offset
            if tok and tok != ZERO_OFFSET:
                coord = decode_offset_token(tok)
                if best is None or coord > best:
                    best = coord
        if best is None:
            return None
        return CommitRange(high=best)

    # -- DDL / lifecycle -------------------------------------------------------

    async def _apply_ddl(self, ev: SchemaChangeEvent) -> None:
        from ..models.schema import SchemaDiff

        old = self._created.get(ev.table_id)
        new = ev.new_schema
        assert new is not None
        if old is None:
            await self._ensure_table(new)
            return
        name = self._table_name(new)
        diff = SchemaDiff.between(old.table_schema, new.table_schema)
        for col in diff.added:
            ddl = (f'ALTER TABLE "{name}" ADD COLUMN IF NOT EXISTS '
                   f'"{col.name}" {_SF_TYPES.get(col.kind, "VARCHAR")}')
            default = column_default_sql(col, "snowflake")
            if default is not None:
                ddl += f" DEFAULT {default}"
            await self._sql(ddl)
        for col in diff.dropped:
            await self._sql(f'ALTER TABLE "{name}" DROP COLUMN IF EXISTS '
                            f'"{col.name}"')
        self._created[ev.table_id] = new

    async def drop_table(self, table_id: TableId,
                         schema: ReplicatedTableSchema | None = None) -> None:
        if table_id not in self._names and schema is not None:
            # restart recovery: rebuild the name mapping so the drop (and
            # the channel drop, which clears server-side offsets) happens
            self._table_name(schema)
            self._created.setdefault(table_id, schema)
        name = self._names.get(table_id)
        if name is not None:
            async with self._lock_for(table_id):
                stored = self._created.get(table_id)
                handle = self._channels.pop(table_id, None)
                if handle is None and stored is not None:
                    handle = self._channel(stored)
                    self._channels.pop(table_id, None)
                if handle is not None:
                    await handle.drop()
                await self._sql(f'DROP TABLE IF EXISTS "{name}"')
                self._created.pop(table_id, None)

    async def truncate_table(self, table_id: TableId) -> None:
        name = self._names.get(table_id)
        if name is not None:
            async with self._lock_for(table_id):
                await self._sql(f'TRUNCATE TABLE IF EXISTS "{name}"')
                # the table restarts empty: reset the channel so its
                # server-side committed offsets don't dedup the re-copied
                # rows — always, not only when locally open: a restarted
                # process must clear offsets a previous incarnation
                # committed
                schema = self._created.get(table_id)
                if schema is not None:
                    await self._channel(schema).reset()

    async def shutdown(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
