"""Snowflake destination: Snowpipe-Streaming-style REST + keypair JWT.

Reference parity: crates/etl-destinations/src/snowflake/ (6.2k LoC):
  - streaming row batches through channel-scoped REST calls with offset
    tokens (streaming/: RowBatch, OffsetToken, StreamClient) — the offset
    token carries the batch's max sequence key so re-deliveries after a
    crash are server-side deduplicated;
  - JWT keypair auth (auth.rs): RS256 tokens with the
    account.user.SHA256:fingerprint issuer convention;
  - SQL client for DDL (sql_client.rs) via the statements REST API;
  - CDC metadata columns (encoding.rs CdcMeta/CdcOperation).
"""

from __future__ import annotations

import base64
import datetime as dt
import json
import time
from dataclasses import dataclass
from typing import Sequence

import aiohttp

from ..models.errors import ErrorKind, EtlError
from ..models.event import (ChangeType, DeleteEvent, Event, InsertEvent,
                            SchemaChangeEvent, TruncateEvent, UpdateEvent)
from ..models.pgtypes import CellKind
from ..models.schema import ReplicatedTableSchema, TableId
from ..models.table_row import ColumnarBatch
from .base import Destination, WriteAck, expand_batch_events
from ..models.default_expression import column_default_sql
from .bigquery import encode_value  # same JSON value encoding rules
from .util import (CHANGE_SEQUENCE_COLUMN, CHANGE_TYPE_COLUMN,
                   DestinationRetryPolicy, change_type_label,
                   escaped_table_name, http_status_retryable,
                   require_full_row, sequential_event_program,
                   with_retries)

_SF_TYPES: dict[CellKind, str] = {
    CellKind.BOOL: "BOOLEAN", CellKind.I16: "NUMBER(5,0)",
    CellKind.I32: "NUMBER(10,0)", CellKind.U32: "NUMBER(10,0)",
    CellKind.I64: "NUMBER(19,0)", CellKind.F32: "FLOAT",
    CellKind.F64: "FLOAT", CellKind.NUMERIC: "VARCHAR",
    CellKind.DATE: "DATE", CellKind.TIME: "TIME",
    CellKind.TIMESTAMP: "TIMESTAMP_NTZ",
    CellKind.TIMESTAMPTZ: "TIMESTAMP_TZ", CellKind.UUID: "VARCHAR(36)",
    CellKind.JSON: "VARIANT", CellKind.BYTES: "BINARY",
    CellKind.STRING: "VARCHAR", CellKind.ARRAY: "VARIANT",
    CellKind.INTERVAL: "VARCHAR",
}


@dataclass(frozen=True)
class SnowflakeConfig:
    base_url: str  # account REST endpoint (fake server in tests)
    account: str
    user: str
    database: str
    schema: str = "PUBLIC"
    private_key_pem: str = ""  # PKCS#8 RSA key for JWT; "" = no auth header


def make_jwt(config: SnowflakeConfig, lifetime_s: int = 3600) -> str:
    """RS256 keypair JWT (reference auth.rs)."""
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding

    key = serialization.load_pem_private_key(
        config.private_key_pem.encode(), password=None)
    pub = key.public_key().public_bytes(
        serialization.Encoding.DER,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    import hashlib

    fp = base64.b64encode(hashlib.sha256(pub).digest()).decode()
    qualified = f"{config.account.upper()}.{config.user.upper()}"
    now = int(time.time())
    header = {"alg": "RS256", "typ": "JWT"}
    claims = {"iss": f"{qualified}.SHA256:{fp}", "sub": qualified,
              "iat": now, "exp": now + lifetime_s}

    def b64(d: dict) -> bytes:
        return base64.urlsafe_b64encode(
            json.dumps(d, separators=(",", ":")).encode()).rstrip(b"=")

    signing_input = b64(header) + b"." + b64(claims)
    sig = key.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
    return (signing_input + b"."
            + base64.urlsafe_b64encode(sig).rstrip(b"=")).decode()


class SnowflakeDestination(Destination):
    def __init__(self, config: SnowflakeConfig,
                 retry: DestinationRetryPolicy | None = None):
        self.config = config
        self.retry = retry or DestinationRetryPolicy()
        self._session: aiohttp.ClientSession | None = None
        self._created: dict[TableId, ReplicatedTableSchema] = {}
        self._names: dict[TableId, str] = {}
        self._offsets: dict[TableId, str] = {}  # channel offset tokens
        self._jwt: tuple[str, float] | None = None  # (token, expiry)

    async def _api(self, method: str, path: str,
                   body: dict | None = None) -> dict:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        headers = {}
        if self.config.private_key_pem:
            # cache the signed token until near expiry: PEM parse +
            # fingerprint + RSA sign per request would tax the hot path
            now = time.time()
            if self._jwt is None or now > self._jwt[1] - 60:
                self._jwt = (make_jwt(self.config), now + 3600)
            headers["Authorization"] = f"Bearer {self._jwt[0]}"
            headers["X-Snowflake-Authorization-Token-Type"] = "KEYPAIR_JWT"

        async def attempt() -> dict:
            async with self._session.request(
                    method, f"{self.config.base_url}{path}", json=body,
                    headers=headers) as resp:
                text = await resp.text()
                if resp.status >= 400:
                    raise EtlError(
                        ErrorKind.DESTINATION_THROTTLED
                        if http_status_retryable(resp.status)
                        else ErrorKind.DESTINATION_FAILED,
                        f"snowflake {resp.status} {path}: {text[:300]}")
                return json.loads(text) if text else {}

        def retryable(e: BaseException) -> bool:
            if isinstance(e, EtlError):
                return e.kind is ErrorKind.DESTINATION_THROTTLED
            return isinstance(e, (aiohttp.ClientError, OSError))

        return await with_retries(attempt, self.retry, retryable)

    async def _sql(self, statement: str) -> dict:
        return await self._api("POST", "/api/v2/statements", {
            "statement": statement, "database": self.config.database,
            "schema": self.config.schema})

    async def startup(self) -> None:
        await self._sql(
            f'CREATE SCHEMA IF NOT EXISTS "{self.config.schema}"')

    def _table_name(self, schema: ReplicatedTableSchema) -> str:
        return self._names.setdefault(
            schema.id, escaped_table_name(schema.name).upper())

    async def _ensure_table(self, schema: ReplicatedTableSchema) -> str:
        name = self._table_name(schema)
        if self._created.get(schema.id) == schema:
            return name
        identity = {c.name for c in schema.identity_columns()}
        # non-identity columns stay nullable: key-only DELETE rows carry
        # nulls for them
        def spec(c):
            s = f'"{c.name}" {_SF_TYPES.get(c.kind, "VARCHAR")}'
            default = column_default_sql(c, "snowflake")
            if default is not None:
                s += f" DEFAULT {default}"
            if not c.nullable and c.name in identity:
                s += " NOT NULL"
            return s

        cols = [spec(c) for c in schema.replicated_columns]
        cols.append(f'"{CHANGE_TYPE_COLUMN}" VARCHAR(6)')
        cols.append(f'"{CHANGE_SEQUENCE_COLUMN}" VARCHAR(64)')
        await self._sql(f'CREATE TABLE IF NOT EXISTS "{name}" '
                        f'({", ".join(cols)})')
        self._created[schema.id] = schema
        return name

    def _channel_path(self, name: str) -> str:
        return (f"/v2/streaming/databases/{self.config.database}/schemas/"
                f"{self.config.schema}/tables/{name}/channels/etl")

    async def _insert_rows(self, schema: ReplicatedTableSchema, name: str,
                           rows: list[dict], offset_token: str) -> None:
        prev = self._offsets.get(schema.id, "")
        if offset_token and prev and offset_token <= prev:
            return  # offset-token dedup on re-delivery
        await self._api("POST", f"{self._channel_path(name)}/rows",
                        {"rows": rows, "offset_token": offset_token})
        if offset_token:
            self._offsets[schema.id] = offset_token

    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        name = await self._ensure_table(schema)
        rows = []
        for i in range(batch.num_rows):
            doc = {c.schema.name: encode_value(c.value(i), c.schema.kind)
                   for c in batch.columns}
            doc[CHANGE_TYPE_COLUMN] = "UPSERT"
            doc[CHANGE_SEQUENCE_COLUMN] = f"{i:016x}"
            rows.append(doc)
        if rows:
            await self._insert_rows(schema, name, rows, "")
        return WriteAck.durable()

    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        for op in sequential_event_program(expand_batch_events(events)):
            if op[0] == "rows":
                _, schema, evs = op
                await self._write_cdc_run(schema, evs)
            elif op[0] == "truncate":
                for sch in op[1].schemas:
                    await self.truncate_table(sch.id)
            else:
                await self._apply_ddl(op[1])
        return WriteAck.durable()

    async def _write_cdc_run(self, schema: ReplicatedTableSchema,
                             evs: list) -> None:
        name = await self._ensure_table(schema)
        rows = []
        max_seq = ""
        for i, e in enumerate(evs):
            seq = e.sequence_key.with_ordinal(i)
            max_seq = max(max_seq, seq)
            row = e.old_row if isinstance(e, DeleteEvent) else e.row
            ct = ChangeType.DELETE if isinstance(e, DeleteEvent) \
                else ChangeType.INSERT
            if ct is not ChangeType.DELETE:
                require_full_row("snowflake", schema, row)
            doc = {c.name: encode_value(v, c.kind)
                   for c, v in zip(schema.replicated_columns, row.values)}
            doc[CHANGE_TYPE_COLUMN] = change_type_label(ct)
            doc[CHANGE_SEQUENCE_COLUMN] = seq
            rows.append(doc)
        await self._insert_rows(schema, name, rows, max_seq)

    async def _apply_ddl(self, ev: SchemaChangeEvent) -> None:
        from ..models.schema import SchemaDiff

        old = self._created.get(ev.table_id)
        new = ev.new_schema
        assert new is not None
        if old is None:
            await self._ensure_table(new)
            return
        name = self._table_name(new)
        diff = SchemaDiff.between(old.table_schema, new.table_schema)
        for col in diff.added:
            ddl = (f'ALTER TABLE "{name}" ADD COLUMN IF NOT EXISTS '
                   f'"{col.name}" {_SF_TYPES.get(col.kind, "VARCHAR")}')
            default = column_default_sql(col, "snowflake")
            if default is not None:
                ddl += f" DEFAULT {default}"
            await self._sql(ddl)
        for col in diff.dropped:
            await self._sql(f'ALTER TABLE "{name}" DROP COLUMN IF EXISTS '
                            f'"{col.name}"')
        self._created[ev.table_id] = new

    async def drop_table(self, table_id: TableId) -> None:
        name = self._names.get(table_id)
        if name is not None:
            await self._sql(f'DROP TABLE IF EXISTS "{name}"')
            self._created.pop(table_id, None)
            self._offsets.pop(table_id, None)

    async def truncate_table(self, table_id: TableId) -> None:
        name = self._names.get(table_id)
        if name is not None:
            await self._sql(f'TRUNCATE TABLE IF EXISTS "{name}"')
            self._offsets.pop(table_id, None)

    async def shutdown(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
