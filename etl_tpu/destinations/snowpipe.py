"""Snowpipe Streaming wire client: the REAL REST surface Snowflake speaks.

Reference parity (behavioral, re-designed in async Python):
- hostname discovery, channel PUT/DELETE, zstd-NDJSON row POST with
  continuationToken/startOffsetToken/endOffsetToken query params, and
  `pipes/{table}-STREAMING:bulk-channel-status`
  (crates/etl-destinations/src/snowflake/streaming/rest_client.rs:47-418);
- offset tokens `{commit_lsn:016x}/{tx_ordinal:016x}` whose lexicographic
  order IS WAL order (streaming/offset_token.rs:7-40);
- compressed row batches split below the 4 MB API body limit
  (streaming/batch.rs:13-42);
- channel lifecycle: continuation-token chaining, stale-continuation
  reopen-and-recover, committed-offset dedup, uncommitted-rows wait loops,
  synthetic `0/N` table-copy offsets behind a durability barrier
  (streaming/channel.rs:22-634);
- error classification and retry decisions (snowflake/error.rs:64-131,
  rest_client.rs:420-450).
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass
from typing import Callable, Protocol

import aiohttp

from ..models.errors import ErrorKind, EtlError
from .util import DestinationRetryPolicy, with_retries

# -- offset tokens (offset_token.rs) ------------------------------------------

ZERO_OFFSET = "0000000000000000/0000000000000000"


def offset_token(commit_lsn: int, tx_ordinal: int) -> str:
    """`{lsn:016x}/{ordinal:016x}` — fixed width, so string order == WAL
    order and Snowflake's server-side `>=` dedup agrees with ours."""
    return f"{commit_lsn:016x}/{tx_ordinal:016x}"


def decode_offset_token(tok: str) -> tuple[int, int]:
    lsn_hex, sep, ord_hex = tok.partition("/")
    if sep != "/" or len(lsn_hex) != 16 or len(ord_hex) != 16:
        raise EtlError(ErrorKind.DESTINATION_FAILED,
                       f"snowpipe: invalid offset token format: {tok!r}")
    try:
        return int(lsn_hex, 16), int(ord_hex, 16)
    except ValueError:
        raise EtlError(ErrorKind.DESTINATION_FAILED,
                       f"snowpipe: invalid offset token hex: {tok!r}")


# -- row batches (batch.rs) ----------------------------------------------------

# Snowflake Streaming API hard limit on the compressed HTTP request body.
MAX_COMPRESSED_BYTES = 4 * 1024 * 1024
# Split when compressed output reaches this threshold (200 KB headroom
# covers up to MAX_UNFLUSHED_BYTES of input that arrives between checks).
BATCH_SPLIT_THRESHOLD = 3_800_000
# Max bytes written to the compressor between block flushes.
MAX_UNFLUSHED_BYTES = 128 * 1024
# Max serialized (uncompressed) size of a single row — rejects degenerate
# TOAST rows before they enter the encoder.
MAX_UNCOMPRESSED_ROW_BYTES = 2 * 1024 * 1024
ZSTD_COMPRESSION_LEVEL = 3


@dataclass
class RowBatch:
    """One compressed NDJSON request body with its inclusive offset range."""

    data: bytes
    row_count: int
    start_offset: str
    end_offset: str

    @property
    def size(self) -> int:
        return len(self.data)

    def with_request_offset(self, offset: str) -> "RowBatch":
        """Copy batches are encoded before the channel reserves their
        attempt-local offset; both request-range endpoints become `offset`
        while the encoded `_cdc_sequence_number`s stay unchanged
        (batch.rs:103-112)."""
        return RowBatch(self.data, self.row_count, offset, offset)


class RowBatchBuilder:
    """Builds compressed row batches with streaming zstd compression,
    splitting under the API body limit (batch.rs:114-248)."""

    def __init__(self) -> None:
        import zstandard

        self._zstd = zstandard.ZstdCompressor(level=ZSTD_COMPRESSION_LEVEL)
        self._flush_block = zstandard.COMPRESSOBJ_FLUSH_BLOCK
        self._new_encoder()
        self.batches: list[RowBatch] = []

    def _new_encoder(self) -> None:
        self._enc = self._zstd.compressobj()
        self._chunks: list[bytes] = []
        self._row_count = 0
        self._range: tuple[str, str] | None = None
        self._input_since_flush = 0

    def _compressed_size(self) -> int:
        return sum(len(c) for c in self._chunks)

    def push_row(self, doc: dict, offset: str) -> None:
        """Append one NDJSON row. `doc` already carries the CDC metadata
        columns; `offset` extends the batch's inclusive offset range."""
        try:
            line = (json.dumps(doc, separators=(",", ":"),
                               ensure_ascii=False, allow_nan=False)
                    + "\n").encode()
        except ValueError as e:
            # reference encoding.rs rejects non-finite floats
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           f"snowpipe: row not JSON-encodable: {e}")
        self.push_encoded_line(line, offset)

    def push_encoded_line(self, line: bytes, offset: str) -> None:
        """Append one PRE-ENCODED NDJSON line (newline included) — the
        columnar egress path (snowflake.encode_batch_ndjson) renders
        whole batches column-at-a-time and streams the finished lines
        here, so the compressor/split bookkeeping is shared byte-for-byte
        with the row path."""
        if len(line) > MAX_UNCOMPRESSED_ROW_BYTES:
            raise EtlError(
                ErrorKind.DESTINATION_FAILED,
                f"snowpipe: single row exceeds {MAX_UNCOMPRESSED_ROW_BYTES}B "
                f"limit ({len(line)}B uncompressed)")
        if self._input_since_flush + len(line) >= MAX_UNFLUSHED_BYTES:
            self._chunks.append(self._enc.flush(self._flush_block))
            self._input_since_flush = 0
            if (self._row_count > 0 and
                    self._compressed_size() + len(line)
                    > BATCH_SPLIT_THRESHOLD):
                self._finish_current()
        self._chunks.append(self._enc.compress(line))
        self._input_since_flush += len(line)
        self._row_count += 1
        if self._range is None:
            self._range = (offset, offset)
        else:
            self._range = (self._range[0], offset)

    def _finish_current(self) -> None:
        self._chunks.append(self._enc.flush())
        data = b"".join(self._chunks)
        if len(data) > MAX_COMPRESSED_BYTES:
            raise EtlError(
                ErrorKind.DESTINATION_FAILED,
                f"snowpipe: compressed batch exceeds {MAX_COMPRESSED_BYTES}B "
                f"API limit ({len(data)}B)")
        assert self._range is not None
        self.batches.append(RowBatch(data, self._row_count,
                                     self._range[0], self._range[1]))
        self._new_encoder()

    def finish(self) -> list[RowBatch]:
        if self._row_count > 0:
            self._finish_current()
        return self.batches


# -- error classification (error.rs) -------------------------------------------


class SnowpipeWireError(Exception):
    """Classified Snowpipe Streaming API failure. `kind` is one of:
    stale_continuation | uncommitted_rows | channel_not_found |
    auth_expired | api_status | http."""

    def __init__(self, kind: str, status: int, message: str,
                 api_code: int | None = None):
        super().__init__(f"snowpipe {kind} (HTTP {status}): {message[:300]}")
        self.kind = kind
        self.status = status
        self.api_code = api_code

    @classmethod
    def from_response(cls, status: int, body: str) -> "SnowpipeWireError":
        """Mirrors SnowpipeError::from_response (error.rs:95-124): numeric
        `status_code` in the body wins (3=auth expired, 4=stale), then the
        string `code`, then the HTTP status."""
        doc: dict = {}
        try:
            parsed = json.loads(body)
            if isinstance(parsed, dict):
                doc = parsed
        except ValueError:
            pass
        api_code = doc.get("status_code")
        if isinstance(api_code, int):
            if api_code == 3:
                return cls("auth_expired", status, body, api_code)
            if api_code == 4:
                return cls("stale_continuation", status, body, api_code)
            return cls("api_status", status, body, api_code)
        code = doc.get("code")
        if status == 400 and code == "STALE_CONTINUATION_TOKEN_SEQUENCER":
            return cls("stale_continuation", status, body)
        if status == 409 and code == "ERR_CHANNEL_HAS_UNCOMMITTED_DATA":
            return cls("uncommitted_rows", status, body)
        if status == 404:
            return cls("channel_not_found", status, body)
        return cls("http", status, body)

    @property
    def retryable(self) -> bool:
        """rest_client.rs:420-450 should_retry: auth expiry retries (the
        token provider refreshes), stale/uncommitted/not-found surface to
        the channel lifecycle, API codes 0|2|4 stop, 401/408/429/5xx
        retry."""
        if self.kind == "auth_expired":
            return True
        if self.kind in ("stale_continuation", "uncommitted_rows",
                         "channel_not_found"):
            return False
        if self.kind == "api_status":
            return self.api_code not in (0, 2, 4)
        return self.status in (401, 408, 429) or self.status >= 500


# -- REST client (rest_client.rs) ----------------------------------------------


class TokenProvider(Protocol):
    async def get_token(self) -> str: ...

    def invalidate_token(self) -> None: ...


@dataclass
class ChannelStatus:
    """Parsed channel status (rest_client.rs ChannelStatusDetail /
    BulkStatusChannel — both field spellings accepted)."""

    channel: str
    status_code: str
    offset_token: str | None
    rows_inserted: int
    rows_parsed: int
    rows_error_count: int
    last_error_message: str | None = None

    @classmethod
    def from_doc(cls, doc: dict, fallback_channel: str) -> "ChannelStatus":
        tok = doc.get("last_committed_offset_token") or None
        if tok is not None:
            decode_offset_token(tok)  # validate canonical form
        return cls(
            channel=doc.get("channel_name") or fallback_channel,
            status_code=doc.get("channel_status_code") or "",
            offset_token=tok,
            rows_inserted=int(doc.get("rows_inserted", 0)),
            rows_parsed=int(doc.get("rows_parsed", 0)),
            # Open Channel documents `rows_error_count`, Bulk Get Channel
            # Status documents `rows_errors` — accept both
            rows_error_count=int(doc.get("rows_error_count",
                                         doc.get("rows_errors", 0))),
            last_error_message=doc.get("last_error_message"))


def _pipe_name(table: str) -> str:
    return f"{table}-STREAMING"


USER_AGENT = "etl-tpu/0.1.0"


class RestStreamClient:
    """Snowpipe Streaming REST driver. Discovers the ingest host once,
    chains continuation tokens per channel, retries with backoff, and
    invalidates the auth token on 401 so the provider re-signs."""

    def __init__(self, account_url: str, auth: TokenProvider,
                 session_factory: Callable[[], aiohttp.ClientSession],
                 retry: DestinationRetryPolicy | None = None):
        self.account_url = account_url.rstrip("/")
        self.auth = auth
        self._session_factory = session_factory
        self.retry = retry or DestinationRetryPolicy()
        self._ingest_host: str | None = None

    async def _headers(self) -> dict[str, str]:
        token = await self.auth.get_token()
        h = {"User-Agent": USER_AGENT}
        if token:
            h["Authorization"] = f"Bearer {token}"
            h["X-Snowflake-Authorization-Token-Type"] = "KEYPAIR_JWT"
        return h

    async def _request(self, method: str, url: str, *,
                       params: dict | None = None,
                       json_body: dict | None = None,
                       data: bytes | None = None,
                       headers: dict[str, str] | None = None) -> bytes:
        async def attempt() -> bytes:
            h = await self._headers()
            if headers:
                h.update(headers)
            session = self._session_factory()
            async with session.request(method, url, params=params,
                                       json=json_body, data=data,
                                       headers=h) as resp:
                body = await resp.text()
                if resp.status != 200:
                    err = SnowpipeWireError.from_response(resp.status, body)
                    if resp.status == 401 or err.kind == "auth_expired":
                        # rest_client.rs:144-147,240-246: a 401 or an
                        # auth-expired API code invalidates the cached
                        # token; the retry re-signs
                        self.auth.invalidate_token()
                    raise err
                return body.encode()

        def retryable(e: BaseException) -> bool:
            if isinstance(e, SnowpipeWireError):
                return e.retryable
            return isinstance(e, (aiohttp.ClientError, OSError))

        return await with_retries(attempt, self.retry, retryable)

    async def discover_ingest_host(self) -> str:
        """GET /v2/streaming/hostname — the actual server returns plain
        text even with Accept: application/json (rest_client.rs:67-71);
        accept both shapes and default the scheme to https."""
        if self._ingest_host is not None:
            return self._ingest_host
        body = (await self._request(
            "GET", f"{self.account_url}/v2/streaming/hostname")).decode()
        hostname = body.strip()
        try:
            parsed = json.loads(body)
            if isinstance(parsed, dict) and parsed.get("hostname"):
                hostname = str(parsed["hostname"]).strip()
        except ValueError:
            pass
        if not hostname:
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           "snowpipe: hostname discovery returned empty "
                           "hostname")
        if not hostname.startswith(("http://", "https://")):
            hostname = f"https://{hostname}"
        self._ingest_host = hostname
        return hostname

    def _channel_url(self, db: str, schema: str, table: str,
                     channel: str, host: str) -> str:
        return (f"{host}/v2/streaming/databases/{db}/schemas/{schema}"
                f"/pipes/{_pipe_name(table)}/channels/{channel}")

    async def open_channel(self, db: str, schema: str, table: str,
                           channel: str) -> tuple[str, ChannelStatus]:
        """PUT the channel; returns (continuation_token, status). A
        non-OK channel_status_code is surfaced as an error
        (rest_client.rs:155-168)."""
        host = await self.discover_ingest_host()
        body = await self._request(
            "PUT", self._channel_url(db, schema, table, channel, host),
            json_body={"fail_on_uncommitted_rows": True})
        doc = json.loads(body)
        status_doc = doc.get("channel_status")
        if not isinstance(status_doc, dict):
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           "snowpipe: open_channel response missing "
                           "channel_status")
        code = status_doc.get("channel_status_code")
        if code is not None and code not in ("SUCCESS", "ACTIVE", "0"):
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           f"snowpipe: open_channel returned unexpected "
                           f"status: {code}")
        return (doc["next_continuation_token"],
                ChannelStatus.from_doc(status_doc, channel))

    async def insert_rows(self, db: str, schema: str, table: str,
                          channel: str, batch: RowBatch,
                          continuation_token: str) -> str:
        """POST one compressed NDJSON body; returns the next continuation
        token. The offset range rides the query string so the server can
        dedup without decompressing (rest_client.rs:182-260)."""
        host = await self.discover_ingest_host()
        url = (f"{host}/v2/streaming/data/databases/{db}/schemas/{schema}"
               f"/pipes/{_pipe_name(table)}/channels/{channel}/rows")
        body = await self._request(
            "POST", url,
            params={"continuationToken": continuation_token,
                    "startOffsetToken": batch.start_offset,
                    "endOffsetToken": batch.end_offset},
            data=batch.data,
            headers={"Content-Type": "application/x-ndjson",
                     "Content-Encoding": "zstd"})
        return json.loads(body)["next_continuation_token"]

    async def drop_channel(self, db: str, schema: str, table: str,
                           channel: str) -> None:
        host = await self.discover_ingest_host()
        await self._request(
            "DELETE", self._channel_url(db, schema, table, channel, host),
            json_body={"fail_on_uncommitted_rows": True})

    async def channel_status(self, db: str, schema: str, table: str,
                             channel: str) -> ChannelStatus:
        """POST pipes/{pipe}:bulk-channel-status for one channel
        (rest_client.rs:320-387)."""
        host = await self.discover_ingest_host()
        url = (f"{host}/v2/streaming/databases/{db}/schemas/{schema}"
               f"/pipes/{_pipe_name(table)}:bulk-channel-status")
        body = await self._request("POST", url,
                                   json_body={"channel_names": [channel]})
        statuses = json.loads(body).get("channel_statuses", {})
        for name, doc in statuses.items():
            return ChannelStatus.from_doc(doc, name)
        raise EtlError(ErrorKind.DESTINATION_FAILED,
                       "snowpipe: channel not found in status response")


# -- channel lifecycle (channel.rs) --------------------------------------------

# Maximum pending table-copy row batches / compressed bytes before a
# durability wait (channel.rs:30-40).
COPY_PENDING_MAX_ROW_BATCHES = 64
COPY_PENDING_MAX_BYTES = 256 * 1024 * 1024


@dataclass
class AcceptedBatch:
    """Row batch accepted by a channel but not yet proven committed, with
    the status baseline needed to detect server-side row rejections."""

    target_offset: str
    rows: int
    bytes: int
    baseline_rows_inserted: int
    baseline_rows_error_count: int


@dataclass
class _PendingCopyTarget:
    """Collapsed durability target: committed offsets are cumulative, so
    many accepted batches reduce to the latest offset + aggregates."""

    target_offset: str
    rows: int
    bytes: int
    row_batches: int
    baseline_rows_inserted: int
    baseline_rows_error_count: int

    def record(self, b: AcceptedBatch) -> None:
        self.target_offset = b.target_offset
        self.rows += b.rows
        self.bytes += b.bytes
        self.row_batches += 1

    def would_exceed_limits(self, batch_bytes: int) -> bool:
        return (self.row_batches + 1 > COPY_PENDING_MAX_ROW_BATCHES
                or self.bytes + batch_bytes > COPY_PENDING_MAX_BYTES)

    def as_accepted(self) -> AcceptedBatch:
        return AcceptedBatch(self.target_offset, self.rows, self.bytes,
                             self.baseline_rows_inserted,
                             self.baseline_rows_error_count)


def validate_committed_status(status: ChannelStatus,
                              accepted: AcceptedBatch) -> None:
    """Commit proof must not hide rejected rows (channel.rs:638-664): any
    new row errors past the baseline, or a committed offset that covers
    the range without the expected insert count, fails the pipeline
    closed rather than silently dropping data."""
    if status.rows_error_count > accepted.baseline_rows_error_count:
        raise EtlError(
            ErrorKind.DESTINATION_FAILED,
            f"snowpipe: channel {status.channel} rejected rows while "
            f"committing offset {accepted.target_offset}"
            + (f": {status.last_error_message}"
               if status.last_error_message else ""))
    if (status.offset_token is not None
            and status.offset_token >= accepted.target_offset):
        expected = accepted.baseline_rows_inserted + accepted.rows
        if status.rows_inserted < expected:
            raise EtlError(
                ErrorKind.DESTINATION_FAILED,
                f"snowpipe: channel {status.channel} committed offset "
                f"{accepted.target_offset} without inserting all accepted "
                f"rows: expected >= {expected}, got {status.rows_inserted}")


class ChannelHandle:
    """State and lifecycle of one Snowpipe Streaming channel: progress
    cache, continuation-token chaining, stale-token recovery, and the
    table-copy durability barrier (channel.rs:189-634).

    NOT safe under concurrent callers — the continuation token chains
    across awaits (the Rust original enforces single ownership with
    `&mut self`). Callers hold a per-channel lock; SnowflakeDestination
    keeps one per table."""

    def __init__(self, client: RestStreamClient, database: str,
                 schema: str, table: str, channel: str,
                 poll_interval_s: float = 0.5,
                 wait_timeout_s: float = 180.0):
        self.client = client
        self.database = database
        self.schema = schema
        self.table = table
        self.channel = channel
        self.poll_interval_s = poll_interval_s
        self.wait_timeout_s = wait_timeout_s
        # progress cache (channel.rs ChannelProgress)
        self.committed_offset: str | None = None
        self.rows_inserted = 0
        self.rows_error_count = 0
        self._continuation: str | None = None
        # table-copy state
        self._copy_offset_ordinal: int | None = None
        self._copy_barrier_pending = False
        self._copy_target: _PendingCopyTarget | None = None

    @property
    def is_open(self) -> bool:
        return self._continuation is not None

    def _observe(self, status: ChannelStatus) -> None:
        self.committed_offset = status.offset_token
        self.rows_inserted = status.rows_inserted
        self.rows_error_count = status.rows_error_count

    def is_offset_committed(self, offset: str) -> bool:
        return (self.committed_offset is not None
                and self.committed_offset >= offset)

    async def open(self) -> ChannelStatus:
        """Open or reopen without discarding uncommitted rows: an
        uncommitted-rows refusal waits for the server to commit instead of
        destructively reopening (channel.rs:269-298)."""
        deadline = time.monotonic() + self.wait_timeout_s
        while True:
            try:
                ct, status = await self.client.open_channel(
                    self.database, self.schema, self.table, self.channel)
            except SnowpipeWireError as e:
                if e.kind != "uncommitted_rows" \
                        or time.monotonic() >= deadline:
                    raise
                # poll status while waiting: commit progress is observed
                # (and some servers only advance commits on a status
                # read), then retry the safe open
                try:
                    await self.refresh_status()
                except (SnowpipeWireError, EtlError):
                    pass  # the PUT retry below is the real gate
                await asyncio.sleep(self.poll_interval_s)
                continue
            self._observe(status)
            self._continuation = ct
            return status

    async def drop(self) -> None:
        deadline = time.monotonic() + self.wait_timeout_s
        while True:
            try:
                await self.client.drop_channel(
                    self.database, self.schema, self.table, self.channel)
            except SnowpipeWireError as e:
                if e.kind == "channel_not_found":
                    break
                if e.kind != "uncommitted_rows" \
                        or time.monotonic() >= deadline:
                    raise
                try:
                    await self.refresh_status()
                except (SnowpipeWireError, EtlError):
                    pass
                await asyncio.sleep(self.poll_interval_s)
                continue
            break
        self.committed_offset = None
        self.rows_inserted = 0
        self.rows_error_count = 0
        self._continuation = None
        self._copy_offset_ordinal = None
        self._copy_barrier_pending = False
        self._copy_target = None

    async def reset(self) -> None:
        """Drop and reopen, clearing server-side offsets — the table-copy
        precondition (channel.rs:335-340)."""
        await self.drop()
        await self.open()

    async def refresh_status(self) -> ChannelStatus:
        status = await self.client.channel_status(
            self.database, self.schema, self.table, self.channel)
        self._observe(status)
        return status

    # -- streaming path --------------------------------------------------------

    async def accept_streaming_batches(
            self, batches: list[RowBatch]) -> list[AcceptedBatch]:
        """Send batches when no copy barrier is pending; committed batches
        are skipped, a committed offset INSIDE a batch fails closed
        (channel.rs:426-446)."""
        if self._copy_barrier_pending or self._copy_target is not None:
            raise EtlError(
                ErrorKind.DESTINATION_FAILED,
                "snowpipe: streaming cannot start before the table-copy "
                "durability barrier")
        self._copy_offset_ordinal = None
        accepted = []
        for batch in batches:
            got = await self._accept_batch(batch)
            if got is not None:
                accepted.append(got)
        return accepted

    async def wait_for_offsets_committed(self, target_offset: str,
                                         accepted: AcceptedBatch) -> None:
        """Streaming durability barrier: poll channel status until the
        committed offset covers `target_offset`, validating commit proof
        (channel.rs:495-522 applied to the streaming window)."""
        deadline = time.monotonic() + self.wait_timeout_s
        while True:
            if self.is_offset_committed(target_offset):
                return
            status = await self.refresh_status()
            validate_committed_status(status, accepted)
            if (status.offset_token is not None
                    and status.offset_token >= target_offset):
                return
            if time.monotonic() >= deadline:
                raise EtlError(
                    ErrorKind.DESTINATION_FAILED,
                    f"snowpipe: timed out waiting for offset "
                    f"{target_offset} to commit on {self.channel}")
            await asyncio.sleep(self.poll_interval_s)

    # -- table-copy path -------------------------------------------------------

    def _reserve_copy_offset(self) -> str:
        """Next attempt-local `0/N` synthetic offset; a copy may only
        start on a reset channel (channel.rs:450-473)."""
        if self._copy_offset_ordinal is None:
            if self.committed_offset is not None:
                raise EtlError(
                    ErrorKind.DESTINATION_FAILED,
                    "snowpipe: table copy must start from a reset channel")
            ordinal = 1
        else:
            self._validate_copy_committed()
            ordinal = self._copy_offset_ordinal + 1
        self._copy_offset_ordinal = ordinal
        self._copy_barrier_pending = True
        return offset_token(0, ordinal)

    def _validate_copy_committed(self) -> None:
        """A committed offset must belong to the live `0/1..0/N` copy
        sequence — anything else means the channel saw foreign writes
        (channel.rs:477-491)."""
        if self.committed_offset is None:
            return
        last = self._copy_offset_ordinal
        if last is None:
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           "snowpipe: table copy has no live offset "
                           "sequence")
        lsn, ordinal = decode_offset_token(self.committed_offset)
        if lsn != 0 or ordinal == 0 or ordinal > last:
            raise EtlError(
                ErrorKind.DESTINATION_FAILED,
                f"snowpipe: committed offset {self.committed_offset} does "
                f"not belong to the current table-copy attempt")

    async def accept_table_copy_batches(self,
                                        batches: list[RowBatch]) -> None:
        """Bounded deferred-durability window: before a batch would exceed
        the pending batch/byte limits, wait for the current cumulative
        target to commit (channel.rs:368-392)."""
        for batch in batches:
            if (self._copy_target is not None
                    and self._copy_target.would_exceed_limits(batch.size)):
                await self._wait_pending_copy_durability()
            off = self._reserve_copy_offset()
            got = await self._accept_batch(batch.with_request_offset(off))
            if got is None:
                continue
            if self._copy_target is None:
                self._copy_target = _PendingCopyTarget(
                    got.target_offset, got.rows, got.bytes, 1,
                    got.baseline_rows_inserted,
                    got.baseline_rows_error_count)
            else:
                self._copy_target.record(got)

    async def wait_for_table_copy_durability(self) -> None:
        """Terminal copy barrier; success permits streaming
        (channel.rs:401-419)."""
        if self._copy_offset_ordinal is not None:
            self._validate_copy_committed()
        elif self.committed_offset is not None:
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           "snowpipe: table copy must start from a reset "
                           "channel")
        await self._wait_pending_copy_durability()
        self._copy_barrier_pending = False

    async def _wait_pending_copy_durability(self) -> None:
        if self._copy_target is None:
            return
        deadline = time.monotonic() + self.wait_timeout_s
        accepted = self._copy_target.as_accepted()
        while True:
            status = await self.refresh_status()
            if status.offset_token is not None:
                self._validate_copy_committed()
            validate_committed_status(status, accepted)
            if (status.offset_token is not None
                    and status.offset_token >= self._copy_target.target_offset):
                self._copy_target = None
                return
            if time.monotonic() >= deadline:
                raise EtlError(
                    ErrorKind.DESTINATION_FAILED,
                    "snowpipe: timed out waiting for table-copy rows to "
                    "commit")
            await asyncio.sleep(self.poll_interval_s)

    # -- shared send path ------------------------------------------------------

    async def _accept_batch(self, batch: RowBatch) -> AcceptedBatch | None:
        """Send one batch unless progress already covers it; a stale
        continuation token reopens the channel and decides between
        already-committed, fail-closed overlap, and resend
        (channel.rs:524-619). Returns None when already committed."""
        if self._copy_barrier_pending:
            self._validate_copy_committed()
        if self.is_offset_committed(batch.end_offset):
            return None
        if self.is_offset_committed(batch.start_offset):
            raise EtlError(
                ErrorKind.DESTINATION_FAILED,
                f"snowpipe: batch {batch.start_offset}..{batch.end_offset} "
                f"overlaps committed offset {self.committed_offset}; replay "
                f"filtering should remove committed rows before batching")
        baseline_rows = self.rows_inserted
        baseline_errs = self.rows_error_count
        try:
            await self._append(batch)
        except SnowpipeWireError as e:
            if e.kind not in ("stale_continuation", "channel_not_found"):
                raise
            from ..telemetry.metrics import (
                ETL_SNOWPIPE_CHANNEL_RECOVERIES_TOTAL, registry)

            registry.counter_inc(ETL_SNOWPIPE_CHANNEL_RECOVERIES_TOTAL)
            status = await self.open()
            if self._copy_barrier_pending and status.offset_token:
                self._validate_copy_committed()
            if (status.offset_token is not None
                    and status.offset_token >= batch.end_offset):
                accepted = AcceptedBatch(batch.end_offset, batch.row_count,
                                         batch.size, baseline_rows,
                                         baseline_errs)
                validate_committed_status(status, accepted)
                return None
            if (status.offset_token is not None
                    and status.offset_token >= batch.start_offset):
                raise EtlError(
                    ErrorKind.DESTINATION_FAILED,
                    f"snowpipe: stale-channel recovery found committed "
                    f"offset {status.offset_token} inside batch "
                    f"{batch.start_offset}..{batch.end_offset}; failing "
                    f"closed for upstream replay")
            baseline_rows = self.rows_inserted
            baseline_errs = self.rows_error_count
            await self._append(batch)
        return AcceptedBatch(batch.end_offset, batch.row_count, batch.size,
                             baseline_rows, baseline_errs)

    async def _append(self, batch: RowBatch) -> None:
        if self._continuation is None:
            raise EtlError(ErrorKind.DESTINATION_FAILED,
                           "snowpipe: append on channel without "
                           "continuation token (open it first)")
        self._continuation = await self.client.insert_rows(
            self.database, self.schema, self.table, self.channel, batch,
            self._continuation)
