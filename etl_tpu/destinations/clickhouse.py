"""ClickHouse destination: HTTP inserts into ReplacingMergeTree CDC tables.

Reference parity: crates/etl-destinations/src/clickhouse/ — per-table CDC
tables keyed by `_CHANGE_SEQUENCE_NUMBER` with a ReplacingMergeTree-family
engine selectable via config (core.rs:19 ClickHouseEngine), `_current`
views collapsing to live rows (schema.rs create_current_view_sql), DDL for
schema diffs, HTTP-interface inserts (RowBinary in the reference; TSV here
— both stream row batches over one POST).

TPU-first: row batches arrive as ColumnarBatches from the device decode
path and are rendered column-at-a-time into TSV without building per-row
Python objects for dense columns.
"""

from __future__ import annotations

import asyncio
import datetime as dt
import enum
import json
from dataclasses import dataclass, field
from typing import Sequence
from urllib.parse import urlencode

import aiohttp

from ..models.cell import (JSON_NULL, PgInterval, PgNumeric, PgSpecialDate,
                           PgSpecialTimestamp, PgTimeTz, TOAST_UNCHANGED)
from ..models.errors import ErrorKind, EtlError
from ..models.event import (BeginEvent, ChangeType, CommitEvent,
                            DecodedBatchEvent, DeleteEvent, Event,
                            InsertEvent, RelationEvent, SchemaChangeEvent,
                            TruncateEvent, UpdateEvent)
from ..models.pgtypes import CellKind
from ..models.default_expression import column_default_sql
from ..models.schema import (ReplicatedTableSchema, SchemaDiff, TableId,
                             TableName)
from ..models.table_row import ColumnarBatch
from ..analysis.annotations import transactional_commit
from .base import CommitRange, Destination, WriteAck
from .base import expand_batch_events
from .util import (CDC_DELETE, CDC_UPSERT, CHANGE_SEQUENCE_COLUMN,
                   CHANGE_TYPE_COLUMN, DestinationRetryPolicy,
                   change_type_label, escaped_table_name,
                   classify_http_error, require_full_batch,
                   require_full_row, sequential_event_program,
                   with_retries)


class ClickHouseEngine(enum.Enum):
    REPLACING_MERGE_TREE = "ReplacingMergeTree"
    REPLICATED_REPLACING_MERGE_TREE = "ReplicatedReplacingMergeTree"


@dataclass(frozen=True)
class ClickHouseConfig:
    url: str  # http endpoint, e.g. http://localhost:8123
    database: str = "default"
    username: str = "default"
    password: str = ""
    engine: ClickHouseEngine = ClickHouseEngine.REPLACING_MERGE_TREE
    create_current_views: bool = True


_CH_TYPES: dict[CellKind, str] = {
    CellKind.BOOL: "Bool",
    CellKind.I16: "Int16",
    CellKind.I32: "Int32",
    CellKind.U32: "UInt32",
    CellKind.I64: "Int64",
    CellKind.F32: "Float32",
    CellKind.F64: "Float64",
    CellKind.NUMERIC: "String",  # exact text (Arrow stance, table_row.py)
    CellKind.DATE: "Date32",
    CellKind.TIME: "String",
    CellKind.TIMETZ: "String",
    CellKind.TIMESTAMP: "DateTime64(6)",
    CellKind.TIMESTAMPTZ: "DateTime64(6, 'UTC')",
    CellKind.UUID: "UUID",
    CellKind.JSON: "String",
    CellKind.BYTES: "String",
    CellKind.STRING: "String",
    CellKind.ARRAY: "String",
    CellKind.INTERVAL: "String",
}


def clickhouse_type(kind: CellKind, nullable: bool) -> str:
    base = _CH_TYPES.get(kind, "String")
    return f"Nullable({base})" if nullable else base


def create_table_sql(database: str, table: str,
                     schema: ReplicatedTableSchema,
                     engine: ClickHouseEngine) -> str:
    cols = []
    identity = {c.name for c in schema.identity_columns()}
    for c in schema.replicated_columns:
        # CDC tables must accept key-only DELETE rows: every non-identity
        # column is nullable at the destination regardless of source schema
        nullable = c.nullable or c.name not in identity
        spec = f"`{c.name}` {clickhouse_type(c.kind, nullable)}"
        default = column_default_sql(c, "clickhouse")
        if default is not None:
            spec += f" DEFAULT {default}"
        cols.append(spec)
    cols.append(f"`{CHANGE_TYPE_COLUMN}` String")
    cols.append(f"`{CHANGE_SEQUENCE_COLUMN}` String")
    pk = [c.name for c in schema.identity_columns()] or \
        [c.name for c in schema.replicated_columns]
    order = ", ".join(f"`{c}`" for c in pk)
    return (f"CREATE TABLE IF NOT EXISTS `{database}`.`{table}` "
            f"({', '.join(cols)}) ENGINE = {engine.value}"
            f"(`{CHANGE_SEQUENCE_COLUMN}`) ORDER BY ({order})")


def create_current_view_sql(database: str, table: str,
                            schema: ReplicatedTableSchema) -> str:
    """Live-rows view over the CDC table (reference
    clickhouse/schema.rs create_current_view_sql)."""
    cols = ", ".join(f"`{c.name}`" for c in schema.replicated_columns)
    return (f"CREATE OR REPLACE VIEW `{database}`.`{table}_current` AS "
            f"SELECT {cols} FROM `{database}`.`{table}` FINAL "
            f"WHERE `{CHANGE_TYPE_COLUMN}` != '{CDC_DELETE}'")


def _tsv_escape(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\t", "\\t")
             .replace("\n", "\\n").replace("\r", "\\r"))


def render_value(v, kind: CellKind) -> str:
    r""""One TSV field. ClickHouse TSV uses \N for NULL."""
    if v is None or v is TOAST_UNCHANGED:
        return "\\N"
    if v is JSON_NULL:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, PgNumeric):
        return v.pg_text()
    if isinstance(v, (PgTimeTz, PgInterval, PgSpecialDate,
                      PgSpecialTimestamp)):
        return _tsv_escape(v.pg_text())
    if isinstance(v, dt.datetime):
        # explicit zero-padded year: glibc strftime('%Y') renders year 99
        # as '99', diverging from the columnar bulk renderer
        # (np.datetime_as_string) and from what ClickHouse parses —
        # '0099-…' is the form both sides agree on
        return (f"{v.year:04d}-{v.month:02d}-{v.day:02d} "
                f"{v.hour:02d}:{v.minute:02d}:{v.second:02d}."
                f"{v.microsecond:06d}")
    if isinstance(v, dt.date):
        return v.isoformat()
    if isinstance(v, dt.time):
        return v.isoformat()
    if isinstance(v, bytes):
        return _tsv_escape(v.decode("utf-8", "backslashreplace"))
    if isinstance(v, (dict, list)):
        return _tsv_escape(json.dumps(v))
    return _tsv_escape(str(v))


# -- columnar TSV rendering (egress hot path) ---------------------------------

# dense timestamp/date sentinels/bounds — the SAME objects _from_dense
# decodes with, so detection can never drift from Column.value()
from ..models.table_row import (DATE_INFINITY_DAYS as _DATE_INF,
                                DATE_NEG_INFINITY_DAYS as _DATE_NEG_INF,
                                MAX_DATE_DAYS as _MAX_DATE_DAYS,
                                MAX_TS_US as _MAX_TS_US,
                                MIN_DATE_DAYS as _MIN_DATE_DAYS,
                                MIN_TS_US as _MIN_TS_US,
                                TS_INFINITY_US as _TS_INF,
                                TS_NEG_INFINITY_US as _TS_NEG_INF)

import numpy as np


from ..analysis.annotations import hot_loop


@hot_loop
def _column_texts(col) -> list:
    """One column's TSV field texts (str per present row, None = NULL →
    `\\N`), rendered column-at-a-time: one kind dispatch per column, dense
    numpy data stringified without boxing into datetime/Decimal objects.
    Byte-identical to `render_value(col.value(i), kind)` per row.
    @hot_loop: per column per CDC flush (etl-lint rule 13)."""
    n = len(col)
    kind = col.schema.kind
    valid = col.validity
    if col.toast_unchanged is not None:
        valid = valid & ~col.toast_unchanged
    out: list = [None] * n
    present = np.flatnonzero(valid)
    if present.size == 0:
        return out
    if col.is_dense and kind is CellKind.BOOL:
        data = col.data
        for i in present.tolist():
            out[i] = "true" if data[i] else "false"
        return out
    if col.is_dense and kind in (CellKind.I16, CellKind.I32, CellKind.U32,
                                 CellKind.I64):
        # decimal text straight from numpy (same digits as str(int))
        texts = col.data.astype("U21")
        for i in present.tolist():
            out[i] = texts[i]
        return out
    if col.is_dense and kind in (CellKind.F32, CellKind.F64):
        data = col.data.tolist()  # Python floats: str() matches row path
        for i in present.tolist():
            out[i] = str(data[i])
        return out
    if col.is_dense and kind in (CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ):
        data = col.data
        sel = data[present]
        ok = ((sel != _TS_INF) & (sel != _TS_NEG_INF)
              & (sel >= _MIN_TS_US) & (sel <= _MAX_TS_US))
        # bulk path: epoch-µs → 'YYYY-MM-DD HH:MM:SS.ffffff' (matches
        # strftime('%Y-%m-%d %H:%M:%S.%f') — both always emit 6 digits)
        texts = np.char.replace(
            np.datetime_as_string(data.astype("M8[us]"), unit="us"),
            "T", " ")
        for i in present.tolist():
            out[i] = texts[i]
        if not ok.all():
            for i in (present[~ok]).tolist():
                out[i] = render_value(col.value(i), kind)  # specials
        return out
    if col.is_dense and kind is CellKind.DATE:
        data = col.data
        sel = data[present]
        ok = ((sel != _DATE_INF) & (sel != _DATE_NEG_INF)
              & (sel >= _MIN_DATE_DAYS) & (sel <= _MAX_DATE_DAYS))
        texts = np.datetime_as_string(data.astype("M8[D]"), unit="D")
        for i in present.tolist():
            out[i] = texts[i]
        if not ok.all():
            for i in (present[~ok]).tolist():
                out[i] = render_value(col.value(i), kind)
        return out
    if col.is_arrow and kind is CellKind.STRING and col.lazy_text_oid is None:
        vals = col.data.to_pylist()
        for i in present.tolist():
            out[i] = _tsv_escape(vals[i])
        return out
    # generic fallback (NUMERIC/TIME/JSON/bytes/arrays/lazy-text columns):
    # box the value, reuse the row-path renderer
    for i in present.tolist():
        out[i] = render_value(col.value(i), kind)
    return out


@hot_loop
def render_batch_tsv_columnar(schema: ReplicatedTableSchema, batch,
                              change_types, seqs) -> bytes:
    """Whole-batch TSV: column-at-a-time field rendering + one join —
    byte-identical to the per-row `render_value` path. `change_types` /
    `seqs` are per-row strs (or one shared str for the copy path).
    @hot_loop: the ClickHouse egress hot path (etl-lint rule 13)."""
    n = batch.num_rows
    cols = [_column_texts(c) for c in batch.columns]
    if isinstance(change_types, str):
        change_types = [change_types] * n
    lines = []
    for i in range(n):
        fields = [c[i] if c[i] is not None else "\\N" for c in cols]
        fields.append(change_types[i])
        fields.append(seqs[i])
        lines.append("\t".join(fields))
    body = "\n".join(lines)
    return (body + "\n").encode() if lines else b""


_TSV_NULL = b"\\N"
_TSV_ESCAPE_BYTES = (9, 10, 13, 92)  # \t \n \r backslash


def _count_egress_write(used_device: bool) -> None:
    from .util import count_egress_write

    count_egress_write(used_device)


def _column_piece_tsv(col, dev, oracle_rows: set):
    """One column's TSV field bytes as an assembly piece (ops/egress.py
    piece protocol). Sources, in order: the device-rendered buffer
    (`dev`), the numpy host twin, a zero-copy Arrow slice, or the
    per-value renderer. Rows neither source can render verbatim
    (temporal specials, strings needing escapes go per-value inside the
    piece; whole-row cases land in `oracle_rows`). Returns
    (piece, used_device)."""
    from ..ops import egress as eg

    n = len(col)
    kind = col.schema.kind
    valid = col.validity
    if col.toast_unchanged is not None:
        valid = valid & ~col.toast_unchanged
    nulls = np.flatnonzero(~valid)
    fixed_kinds = (CellKind.BOOL, CellKind.I16, CellKind.I32, CellKind.U32,
                   CellKind.I64, CellKind.DATE, CellKind.TIMESTAMP,
                   CellKind.TIMESTAMPTZ)
    if col.is_dense and kind in fixed_kinds:
        data = col.data
        if kind in (CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ):
            specials = valid & ((data == _TS_INF) | (data == _TS_NEG_INF)
                                | (data < _MIN_TS_US) | (data > _MAX_TS_US))
            oracle_rows.update(np.flatnonzero(specials).tolist())
        elif kind is CellKind.DATE:
            specials = valid & ((data == _DATE_INF) | (data == _DATE_NEG_INF)
                                | (data < _MIN_DATE_DAYS)
                                | (data > _MAX_DATE_DAYS))
            oracle_rows.update(np.flatnonzero(specials).tolist())
        if dev is not None:
            buf, lens = eg.patch_rows_fixed(dev[0], dev[1], nulls, _TSV_NULL)
            return eg.fixed_piece(buf, lens), True
        if kind is CellKind.BOOL:
            buf, lens = eg.bool_text_fixed(data)
        elif kind is CellKind.DATE:
            buf, lens = eg.date_text_fixed(data)
        elif kind in (CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ):
            buf, lens = eg.timestamp_text_fixed(data)
        else:
            buf, lens = eg.int_text_fixed(data)
        buf, lens = eg.patch_rows_fixed(buf, lens, nulls, _TSV_NULL)
        return eg.fixed_piece(buf, lens), False
    if col.is_dense and kind in (CellKind.F32, CellKind.F64):
        data = col.data.tolist()  # Python floats: str() matches row path
        items = [_TSV_NULL] * n
        for i in np.flatnonzero(valid).tolist():
            items[i] = str(data[i]).encode()
        return eg.var_from_texts(items), False
    if col.is_arrow and kind is CellKind.STRING \
            and col.lazy_text_oid is None and col.data.offset == 0:
        bufs = col.data.buffers()
        offs = np.frombuffer(bufs[1], dtype=np.int32, count=n + 1) \
            if bufs[1] is not None else np.zeros(n + 1, dtype=np.int32)
        vals = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] is not None \
            else np.zeros(0, dtype=np.uint8)
        region = vals[offs[0]:offs[n]]
        clean = True
        for b in _TSV_ESCAPE_BYTES:
            if (region == b).any():
                clean = False
                break
        if clean:
            piece = ("var", vals, offs.astype(np.int64))
            if nulls.size:
                out, starts = eg.assemble_rows(
                    n, [piece], {int(i): _TSV_NULL for i in nulls})
                piece = ("var", out, starts)
            return piece, False
        texts = col.data.to_pylist()
        items = [_TSV_NULL] * n
        for i in np.flatnonzero(valid).tolist():
            items[i] = _tsv_escape(texts[i]).encode()
        return eg.var_from_texts(items), False
    # generic fallback (NUMERIC/TIME/JSON/bytes/arrays/lazy-text): box the
    # value, reuse the row-path renderer — same stance as _column_texts
    items = [_TSV_NULL] * n
    for i in np.flatnonzero(valid).tolist():
        items[i] = render_value(col.value(i), kind).encode()
    return eg.var_from_texts(items), False


@hot_loop
def render_batch_tsv_fast(schema: ReplicatedTableSchema, batch,
                          change_types, seq_buf,
                          egress=None) -> "tuple[bytes, bool]":
    """Vectorized whole-batch TSV assembly: per-column byte pieces
    (device egress buffers when attached, numpy host twins otherwise)
    scattered into one contiguous body — no per-row join, no per-row
    Python except the oracle-spliced rows. Byte-identical to
    `render_batch_tsv_columnar` (the identity is gated, ops/egress.py
    module docstring). `change_types` is a shared str (copy path) or the
    `change_type_batch` S6 array; `seq_buf` the (n, 50) uint8
    `sequence_number_buffer`. Returns (body, used_device_buffers).
    @hot_loop: the ClickHouse egress hot path (etl-lint rule 13)."""
    from ..ops import egress as eg

    n = batch.num_rows
    oracle_rows: set = set()
    if egress is not None and egress.untrusted.size:
        oracle_rows.update(egress.untrusted.tolist())
    tab = eg.const_piece(b"\t")
    pieces = []
    used_device = False
    for j, col in enumerate(batch.columns):
        dev = egress.field(j) if egress is not None else None
        piece, used = _column_piece_tsv(col, dev, oracle_rows)
        used_device |= used
        pieces.append(piece)
        pieces.append(tab)
    if isinstance(change_types, str):
        pieces.append(eg.const_piece(change_types.encode()))
    else:
        ct_buf = np.frombuffer(change_types.tobytes(), dtype=np.uint8) \
            .reshape(n, change_types.dtype.itemsize)
        pieces.append(eg.fixed_piece(
            ct_buf, np.full(n, ct_buf.shape[1], dtype=np.int64)))
    pieces.append(tab)
    pieces.append(eg.fixed_piece(seq_buf, np.full(n, seq_buf.shape[1],
                                                  dtype=np.int64)))
    pieces.append(eg.const_piece(b"\n"))
    override = None
    if oracle_rows:
        override = {}
        for i in sorted(oracle_rows):
            fields = [render_value(c.value(i), c.schema.kind)
                      for c in batch.columns]
            ct = change_types if isinstance(change_types, str) \
                else change_types[i].decode()
            seq = seq_buf[i].tobytes().decode()
            override[i] = ("\t".join(fields + [ct, seq]) + "\n").encode()
    out, _ = eg.assemble_rows(n, pieces, override)
    return out.tobytes(), used_device


class ClickHouseDestination(Destination):
    egress_encoder = "tsv"  # device-rendered TSV fields (ops/egress.py)

    def __init__(self, config: ClickHouseConfig,
                 retry: DestinationRetryPolicy | None = None):
        self.config = config
        self.retry = retry or DestinationRetryPolicy()
        self._session: aiohttp.ClientSession | None = None
        self._created_tables: dict[TableId, ReplicatedTableSchema] = {}
        self._names: dict[TableId, str] = {}
        # exactly-once seam state: `_dedup_token` is attached (suffixed
        # with a per-INSERT ordinal) to every data INSERT issued inside
        # one committed write, so a re-streamed duplicate flush is
        # collapsed by ClickHouse's insert_deduplication_token window
        self._dedup_token: str | None = None
        self._dedup_seq = 0
        self._commit_log_ready = False

    # -- http ------------------------------------------------------------------

    async def _execute(self, sql: str, body: bytes = b"") -> str:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        params = {"database": self.config.database, "query": sql}
        if self._dedup_token is not None and sql.startswith("INSERT INTO"):
            # one token per INSERT within the committed write: identical
            # token on two inserts into the SAME table would make
            # ClickHouse silently drop the second block, so suffix with
            # the (deterministic) per-call ordinal — a re-streamed
            # duplicate flush replays the same program order and lands
            # on the same tokens
            params["insert_deduplication_token"] = \
                f"{self._dedup_token}/{self._dedup_seq}"
            self._dedup_seq += 1

        async def attempt() -> str:
            async with self._session.post(
                    f"{self.config.url}/?{urlencode(params)}", data=body,
                    auth=aiohttp.BasicAuth(self.config.username,
                                           self.config.password)) as resp:
                text = await resp.text()
                if resp.status != 200:
                    # shared HTTP status → ErrorKind map
                    # (util.classify_http_error): throttle/5xx =
                    # transient, permanent 4xx = the poison-trigger
                    # kinds the isolation protocol bisects on
                    raise classify_http_error("clickhouse", resp.status,
                                              text[:300])
                return text

        return await with_retries(attempt, self.retry)

    # -- Destination ------------------------------------------------------------

    async def startup(self) -> None:
        await self._execute(
            f"CREATE DATABASE IF NOT EXISTS `{self.config.database}`")

    def _table_name(self, schema: ReplicatedTableSchema) -> str:
        return self._names.setdefault(schema.id,
                                      escaped_table_name(schema.name))

    async def _ensure_table(self, schema: ReplicatedTableSchema) -> str:
        name = self._table_name(schema)
        known = self._created_tables.get(schema.id)
        if known is not None and known == schema:
            return name
        await self._execute(create_table_sql(
            self.config.database, name, schema, self.config.engine))
        if self.config.create_current_views:
            await self._execute(create_current_view_sql(
                self.config.database, name, schema))
        self._created_tables[schema.id] = schema
        return name

    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        name = await self._ensure_table(schema)
        body = self._render_batch_tsv(schema, batch, change_type=CDC_UPSERT,
                                      seqs=None)
        cols = [c.name for c in schema.replicated_columns] + \
            [CHANGE_TYPE_COLUMN, CHANGE_SEQUENCE_COLUMN]
        col_list = ", ".join(f"`{c}`" for c in cols)
        await self._execute(
            f"INSERT INTO `{self.config.database}`.`{name}` ({col_list}) "
            f"FORMAT TabSeparated", body)
        return WriteAck.durable()

    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        """Sequential program: row runs flush BEFORE any truncate/DDL
        barrier that follows them in WAL order (reference per-table
        batching between barriers, core.rs:956-978)."""
        for op in sequential_event_program(expand_batch_events(events)):
            if op[0] == "rows":
                _, schema, evs = op
                await self._write_row_events(schema, evs)
            elif op[0] == "truncate":
                for sch in op[1].schemas:
                    await self.truncate_table(sch.id)
            else:
                await self._apply_schema_change(op[1])
        return WriteAck.durable()

    # -- columnar seam --------------------------------------------------------

    async def write_table_batch(self, schema: ReplicatedTableSchema,
                                batch) -> WriteAck:
        """Copy path, columnar: TSV rendered column-at-a-time (no
        Column.value boxing), same bytes as `write_table_rows`."""
        from .util import sequence_number_batch, sequence_number_buffer

        name = await self._ensure_table(schema)
        require_full_batch("clickhouse", schema, batch)
        n = batch.num_rows
        zeros = np.zeros(n, dtype=np.uint64)
        ords = np.arange(n, dtype=np.uint64)
        try:
            seq_buf = sequence_number_buffer(zeros, zeros, ords)
            body, used_device = render_batch_tsv_fast(
                schema, batch, CDC_UPSERT, seq_buf,
                egress=getattr(batch, "device_egress", None))
            _count_egress_write(used_device)
        except Exception:  # never fail a write on the fast path — fall back
            seqs = [s.decode() for s in sequence_number_batch(
                zeros, zeros, ords)]
            body = render_batch_tsv_columnar(schema, batch, CDC_UPSERT, seqs)
        await self._insert_tsv(name, schema, body)
        return WriteAck.durable()

    async def write_event_batches(self, events: Sequence[Event]) -> WriteAck:
        """CDC path, columnar: simple decoded batch runs render column-at-
        a-time; old-tuple/TOAST batches and per-row events drop to the row
        path in place (sequential_batch_program preserves WAL order)."""
        from .base import sequential_batch_program
        from .util import (change_type_batch, sequence_number_batch,
                           sequence_number_buffer)

        for op in sequential_batch_program(events):
            if op[0] == "batch":
                _, schema, cb = op
                name = await self._ensure_table(schema)
                require_full_batch("clickhouse", schema, cb.batch,
                                   cb.change_types)
                # row path renders with_ordinal(0): constant third key
                zeros = np.zeros(cb.num_rows, dtype=np.uint64)
                try:
                    seq_buf = sequence_number_buffer(
                        cb.commit_lsns, cb.tx_ordinals, zeros)
                    body, used_device = render_batch_tsv_fast(
                        schema, cb.batch,
                        change_type_batch(cb.change_types), seq_buf,
                        egress=cb.egress)
                    _count_egress_write(used_device)
                except Exception:  # fall back — write must never fail here
                    labels = [t.decode() for t in
                              change_type_batch(cb.change_types).tolist()]
                    seqs = [s.decode() for s in sequence_number_batch(
                        cb.commit_lsns, cb.tx_ordinals, zeros)]
                    body = render_batch_tsv_columnar(schema, cb.batch,
                                                     labels, seqs)
                await self._insert_tsv(name, schema, body)
            elif op[0] == "rows":
                _, schema, evs = op
                await self._write_row_events(schema, evs)
            elif op[0] == "truncate":
                for sch in op[1].schemas:
                    await self.truncate_table(sch.id)
            else:
                await self._apply_schema_change(op[1])
        return WriteAck.durable()

    # -- transactional seam (docs/destinations.md exactly-once contract) ------

    _COMMIT_LOG = "_etl_commit_log"

    def supports_transactional_commit(self) -> bool:
        return True

    async def _ensure_commit_log(self) -> None:
        if self._commit_log_ready:
            return
        await self._execute(
            f"CREATE TABLE IF NOT EXISTS "
            f"`{self.config.database}`.`{self._COMMIT_LOG}` ("
            f"token String, commit_lsn UInt64, tx_ordinal UInt64, "
            f"commit_end_lsn UInt64, replay UInt8) "
            f"ENGINE = ReplacingMergeTree ORDER BY (commit_lsn, "
            f"tx_ordinal, token)")
        self._commit_log_ready = True

    @transactional_commit
    async def write_event_batches_committed(
            self, events: Sequence[Event], commit: CommitRange) -> WriteAck:
        """Committed CDC write: every data INSERT carries an
        `insert_deduplication_token` derived from the flush's WAL range
        (ClickHouse collapses re-streamed duplicate blocks inside its
        dedup window), and the range lands in `_etl_commit_log` AFTER
        the data — recovery reads the log's maximum, so a crash between
        data and log re-streams a flush the tokens then absorb."""
        await self._ensure_commit_log()
        if commit.replay:
            # replay-mode: exact-token dedup against the log, never
            # advancing the streaming high-water (replay rows sit BELOW
            # it by construction)
            seen = await self._execute(
                f"SELECT count() FROM "
                f"`{self.config.database}`.`{self._COMMIT_LOG}` "
                f"WHERE token = '{commit.token()}' AND replay = 1 "
                f"FORMAT TabSeparated")
            if int(seen.strip() or 0):
                return WriteAck.durable()
        self._dedup_token = commit.token()
        self._dedup_seq = 0
        try:
            ack = await self.write_event_batches(events)
        finally:
            self._dedup_token = None
        lsn, ordinal = commit.high
        await self._execute(
            f"INSERT INTO `{self.config.database}`.`{self._COMMIT_LOG}` "
            f"(token, commit_lsn, tx_ordinal, commit_end_lsn, replay) "
            f"FORMAT TabSeparated",
            f"{commit.token()}\t{lsn}\t{ordinal}\t"
            f"{commit.commit_end_lsn or 0}\t"
            f"{1 if commit.replay else 0}\n".encode())
        return ack

    async def recover_high_water(self) -> "CommitRange | None":
        await self._ensure_commit_log()
        text = await self._execute(
            f"SELECT commit_lsn, tx_ordinal, commit_end_lsn FROM "
            f"`{self.config.database}`.`{self._COMMIT_LOG}` "
            f"WHERE replay = 0 "
            f"ORDER BY commit_lsn DESC, tx_ordinal DESC LIMIT 1 "
            f"FORMAT TabSeparated")
        line = text.strip()
        if not line:
            return None
        lsn, ordinal, end = (int(v) for v in line.split("\t"))
        return CommitRange(high=(lsn, ordinal),
                           commit_end_lsn=end or None)

    async def _insert_tsv(self, name: str, schema: ReplicatedTableSchema,
                          body: bytes) -> None:
        cols = [c.name for c in schema.replicated_columns] + \
            [CHANGE_TYPE_COLUMN, CHANGE_SEQUENCE_COLUMN]
        col_list = ", ".join(f"`{c}`" for c in cols)
        await self._execute(
            f"INSERT INTO `{self.config.database}`.`{name}` ({col_list}) "
            f"FORMAT TabSeparated", body)

    async def _write_row_events(self, schema: ReplicatedTableSchema,
                                evs: list) -> None:
        items = []
        for e in evs:
            if isinstance(e, DeleteEvent):
                items.append(("row", e.old_row, ChangeType.DELETE, e))
            else:
                items.append(("row", e.row,
                              ChangeType.UPDATE if isinstance(e, UpdateEvent)
                              else ChangeType.INSERT, e))
        await self._write_run(schema, items)

    async def _write_run(self, schema: ReplicatedTableSchema,
                         items: list[tuple]) -> None:
        name = await self._ensure_table(schema)
        lines: list[bytes] = []
        for item in items:
            _, row, ct, ev = item
            if ct is not ChangeType.DELETE:
                require_full_row("clickhouse", schema, row)
            seq = ev.sequence_key.with_ordinal(0)
            fields = [render_value(v, c.kind) for v, c in
                      zip(row.values, schema.replicated_columns)]
            fields += [change_type_label(ct), seq]
            lines.append(("\t".join(fields) + "\n").encode())
        cols = [c.name for c in schema.replicated_columns] + \
            [CHANGE_TYPE_COLUMN, CHANGE_SEQUENCE_COLUMN]
        col_list = ", ".join(f"`{c}`" for c in cols)
        await self._execute(
            f"INSERT INTO `{self.config.database}`.`{name}` ({col_list}) "
            f"FORMAT TabSeparated", b"".join(lines))

    def _render_batch_tsv(self, schema: ReplicatedTableSchema,
                          batch: ColumnarBatch, *, change_type: str | None,
                          seqs: DecodedBatchEvent | None) -> bytes:
        require_full_batch("clickhouse", schema, batch,
                           seqs.change_types if seqs is not None else None)
        cols = schema.replicated_columns
        out = []
        for i in range(batch.num_rows):
            fields = [render_value(c.value(i), c.schema.kind)
                      for c in batch.columns]
            if seqs is not None:
                ct = change_type_label(ChangeType(int(seqs.change_types[i])))
                seq = (f"{int(seqs.commit_lsns[i]):016x}/"
                       f"{int(seqs.tx_ordinals[i]):016x}/"
                       f"{i:016x}")
            else:
                ct = change_type or CDC_UPSERT
                seq = f"{0:016x}/{0:016x}/{i:016x}"
            fields += [ct, seq]
            out.append("\t".join(fields) + "\n")
        return "".join(out).encode()

    async def _apply_schema_change(self, ev: SchemaChangeEvent) -> None:
        """SchemaDiff → ALTER TABLE DDL (reference clickhouse DDL for
        schema diffs)."""
        old = self._created_tables.get(ev.table_id)
        new = ev.new_schema
        assert new is not None
        if old is None:
            self._created_tables.pop(ev.table_id, None)
            await self._ensure_table(new)
            return
        diff = SchemaDiff.between(old.table_schema, new.table_schema)
        name = self._table_name(new)
        identity = {c.name for c in new.identity_columns()}
        for col in diff.added:
            # same forced-nullable rule as create_table_sql: non-identity
            # columns must accept the NULLs key-only DELETE rows carry
            nullable = col.nullable or col.name not in identity
            # classified portable defaults travel into the ADD COLUMN DDL
            # (reference default_expression.rs); non-portable ones
            # (nextval/now()/expressions) are omitted — rows carry
            # explicit values, the column backfills NULL
            ddl = (f"ALTER TABLE `{self.config.database}`.`{name}` "
                   f"ADD COLUMN IF NOT EXISTS `{col.name}` "
                   f"{clickhouse_type(col.kind, nullable)}")
            default = column_default_sql(col, "clickhouse")
            if default is not None:
                ddl += f" DEFAULT {default}"
            await self._execute(ddl)
        for col in diff.dropped:
            await self._execute(
                f"ALTER TABLE `{self.config.database}`.`{name}` DROP COLUMN "
                f"IF EXISTS `{col.name}`")
        for mod in diff.modified:
            await self._execute(
                f"ALTER TABLE `{self.config.database}`.`{name}` MODIFY "
                f"COLUMN `{mod.name}` "
                f"{clickhouse_type(mod.new.kind, mod.new.nullable)}")
        self._created_tables[ev.table_id] = new
        if self.config.create_current_views:
            await self._execute(create_current_view_sql(
                self.config.database, name, new))

    async def drop_table(self, table_id: TableId,
                         schema: ReplicatedTableSchema | None = None) -> None:
        if table_id not in self._names and schema is not None:
            self._table_name(schema)  # restart recovery: rebuild the mapping
        name = self._names.get(table_id)
        if name is None:
            return
        await self._execute(
            f"DROP TABLE IF EXISTS `{self.config.database}`.`{name}`")
        if self.config.create_current_views:
            await self._execute(
                f"DROP VIEW IF EXISTS "
                f"`{self.config.database}`.`{name}_current`")
        self._created_tables.pop(table_id, None)

    async def truncate_table(self, table_id: TableId) -> None:
        name = self._names.get(table_id)
        if name is not None:
            await self._execute(
                f"TRUNCATE TABLE IF EXISTS "
                f"`{self.config.database}`.`{name}`")

    async def shutdown(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
