"""Destination trait and write-acknowledgement semantics.

Reference parity: `Destination` trait (crates/etl/src/destination/base.rs:27)
and `AsyncResult` Accepted/Durable (destination/async_result.rs:22-66):
`write_*` may return a *durable* ack (data is crash-safe at the destination)
or an *accepted* ack (handed off; durability signalled later through the
attached future). The apply loop advances durable progress — and therefore
the replication slot — only on durable acks at commit boundaries.

TPU-first: `write_table_rows` and `write_events` accept ColumnarBatch /
DecodedBatchEvent payloads straight from the device engine; the
`expand_batch_events` helper converts batch events to per-row events for
row-oriented writers.
"""

from __future__ import annotations

import abc
import asyncio
from dataclasses import dataclass, field
from typing import Iterable, Sequence

import numpy as np

from ..models.event import (ChangeType, DecodedBatchEvent, DeleteEvent, Event,
                            InsertEvent, UpdateEvent)
from ..models.lsn import Lsn
from ..models.schema import ReplicatedTableSchema, TableId
from ..models.table_row import ColumnarBatch, TableRow


class WriteAck:
    """Acknowledgement of a write. `durable` may be True immediately;
    otherwise await `wait_durable()` (resolves when the destination reports
    crash-safety, or raises if the write ultimately failed)."""

    __slots__ = ("_fut",)

    def __init__(self, fut: "asyncio.Future[None]"):
        self._fut = fut

    @classmethod
    def durable(cls) -> "WriteAck":
        fut = asyncio.get_event_loop().create_future()
        fut.set_result(None)
        return cls(fut)

    @classmethod
    def accepted(cls) -> "tuple[WriteAck, asyncio.Future[None]]":
        fut = asyncio.get_event_loop().create_future()
        return cls(fut), fut

    @property
    def is_durable(self) -> bool:
        return self._fut.done() and self._fut.exception() is None

    async def wait_durable(self) -> None:
        await asyncio.shield(self._fut)


class Destination(abc.ABC):
    """Where decoded rows and CDC events land. Implementations must be
    idempotent under at-least-once delivery (SURVEY §5 checkpoint/resume)."""

    @abc.abstractmethod
    async def startup(self) -> None: ...

    @abc.abstractmethod
    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        """Initial-copy path: append-only rows for one table."""

    @abc.abstractmethod
    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        """CDC path: ordered events (possibly spanning tables)."""

    @abc.abstractmethod
    async def drop_table(self, table_id: TableId) -> None:
        """Drop destination table before a (re)copy
        (reference table_sync/mod.rs:184-220 crash-consistency)."""

    @abc.abstractmethod
    async def truncate_table(self, table_id: TableId) -> None: ...

    async def shutdown(self) -> None:  # optional
        return None


@dataclass(slots=True)
class _RowChange:
    change: ChangeType
    key: tuple
    row: TableRow | None


def expand_batch_events(events: Iterable[Event]) -> list[Event]:
    """Expand DecodedBatchEvents into per-row Insert/Update/Delete events
    (helper for row-oriented destinations; columnar-native ones consume the
    batch directly)."""
    out: list[Event] = []
    for e in events:
        if not isinstance(e, DecodedBatchEvent):
            out.append(e)
            continue
        rows = e.batch.to_rows()
        for i, row in enumerate(rows):
            ct = ChangeType(int(e.change_types[i]))
            commit = Lsn(int(e.commit_lsns[i]))
            ordinal = int(e.tx_ordinals[i])
            if ct is ChangeType.INSERT:
                out.append(InsertEvent(e.start_lsn, commit, ordinal,
                                       e.schema, row))
            elif ct is ChangeType.UPDATE:
                out.append(UpdateEvent(e.start_lsn, commit, ordinal,
                                       e.schema, row))
            else:
                out.append(DeleteEvent(e.start_lsn, commit, ordinal,
                                       e.schema, row))
    return out
