"""Destination trait and write-acknowledgement semantics.

Reference parity: `Destination` trait (crates/etl/src/destination/base.rs:27)
and `AsyncResult` Accepted/Durable (destination/async_result.rs:22-66):
`write_*` may return a *durable* ack (data is crash-safe at the destination)
or an *accepted* ack (handed off; durability signalled later through the
attached future). The apply loop advances durable progress — and therefore
the replication slot — only on durable acks at commit boundaries.

TPU-first: `write_table_rows` and `write_events` accept ColumnarBatch /
DecodedBatchEvent payloads straight from the device engine; the
`expand_batch_events` helper converts batch events to per-row events for
row-oriented writers.
"""

from __future__ import annotations

import abc
import asyncio
from dataclasses import dataclass, field
from typing import Iterable, Sequence

import numpy as np

from ..chaos import failpoints
from ..models.event import (ChangeType, DecodedBatchEvent, DeleteEvent, Event,
                            InsertEvent, UpdateEvent)
from ..models.lsn import Lsn
from ..models.schema import ReplicatedTableSchema, TableId
from ..models.table_row import ColumnarBatch, TableRow


class WriteAck:
    """Acknowledgement of a write. `durable` may be True immediately;
    otherwise await `wait_durable()` (resolves when the destination reports
    crash-safety, or raises if the write ultimately failed).

    Chaos sites (chaos/failpoints.py): every destination constructs its
    ack through `durable()`/`accepted()`, so DESTINATION_WRITE armed
    there fires AFTER the write applied — the lost-response ambiguity —
    and DESTINATION_FLUSH fires on the durability wait, regardless of
    which destination implementation is under test."""

    __slots__ = ("_fut",)

    def __init__(self, fut: "asyncio.Future[None]"):
        self._fut = fut

    @classmethod
    def durable(cls) -> "WriteAck":
        failpoints.fail_point(failpoints.DESTINATION_WRITE)
        fut = asyncio.get_event_loop().create_future()
        fut.set_result(None)
        return cls(fut)

    @classmethod
    def accepted(cls) -> "tuple[WriteAck, asyncio.Future[None]]":
        failpoints.fail_point(failpoints.DESTINATION_WRITE)
        fut = asyncio.get_event_loop().create_future()
        return cls(fut), fut

    @property
    def is_durable(self) -> bool:
        return self._fut.done() and self._fut.exception() is None

    async def wait_durable(self) -> None:
        failpoints.fail_point(failpoints.DESTINATION_FLUSH)
        # chaos stall mode: a flush that never acks (SupervisedDestination
        # bounds this await; the watchdog sees frozen apply progress)
        await failpoints.stall_point(failpoints.DESTINATION_FLUSH)
        await asyncio.shield(self._fut)


class Destination(abc.ABC):
    """Where decoded rows and CDC events land. Implementations must be
    idempotent under at-least-once delivery (SURVEY §5 checkpoint/resume)."""

    @abc.abstractmethod
    async def startup(self) -> None: ...

    @abc.abstractmethod
    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        """Initial-copy path: append-only rows for one table."""

    @abc.abstractmethod
    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        """CDC path: ordered events (possibly spanning tables)."""

    @abc.abstractmethod
    async def drop_table(self, table_id: TableId,
                         schema: ReplicatedTableSchema | None = None) -> None:
        """Drop destination table before a (re)copy
        (reference table_sync/mod.rs:184-220 crash-consistency).

        `schema` is the prior stored schema, passed so a freshly restarted
        process — whose in-memory table-name mappings are empty — can still
        resolve which destination table (and channel, for Snowpipe) to
        drop. The reference resolves this through its schema store;
        destinations here rebuild the mapping from the hint."""

    @abc.abstractmethod
    async def truncate_table(self, table_id: TableId) -> None: ...

    async def shutdown(self) -> None:  # optional
        return None


@dataclass(slots=True)
class _RowChange:
    change: ChangeType
    key: tuple
    row: TableRow | None


def expand_batch_events(events: Iterable[Event]) -> list[Event]:
    """Expand DecodedBatchEvents into per-row Insert/Update/Delete events
    (helper for row-oriented destinations; columnar-native ones consume the
    batch directly).

    Emits events identical to the CPU codec path (codec/event.py): update
    old tuples become TableRow ('O') or identity-masked PartialTableRow
    ('K'), full old tuples back-fill TOAST-unchanged new values, and 'K'
    deletes yield PartialTableRow — reference codec/event.rs:28-50."""
    from ..models.cell import TOAST_UNCHANGED
    from ..models.table_row import PartialTableRow

    out: list[Event] = []
    for e in events:
        if not isinstance(e, DecodedBatchEvent):
            out.append(e)
            continue
        rows = e.batch.to_rows()
        old_batch = e.old_batch
        old_rows_list = old_batch.to_rows() if old_batch is not None else []
        old_by_row = {int(r): j for j, r in enumerate(e.old_rows)}
        identity = e.schema.identity_mask
        idx = e.schema.replicated_indices
        present = [identity[idx[i]] for i in range(len(idx))]

        def partial(row: TableRow) -> PartialTableRow:
            return PartialTableRow(row.values, list(present))

        for i, row in enumerate(rows):
            ct = ChangeType(int(e.change_types[i]))
            commit = Lsn(int(e.commit_lsns[i]))
            ordinal = int(e.tx_ordinals[i])
            if ct is ChangeType.INSERT:
                out.append(InsertEvent(e.start_lsn, commit, ordinal,
                                       e.schema, row))
            elif ct is ChangeType.UPDATE:
                old = None
                j = old_by_row.get(i)
                if j is not None:
                    old_row = old_rows_list[j]
                    if e.old_is_key[j]:
                        old = partial(old_row)
                    else:
                        old = old_row
                        # TOAST merge: unchanged columns take the full old
                        # tuple's values (codec/event.py decode_update)
                        values = row.values
                        for k, v in enumerate(values):
                            if v is TOAST_UNCHANGED:
                                values[k] = old_row.values[k]
                out.append(UpdateEvent(e.start_lsn, commit, ordinal,
                                       e.schema, row, old))
            else:
                old = partial(row) if e.delete_is_key is not None \
                    and e.delete_is_key[i] else row
                out.append(DeleteEvent(e.start_lsn, commit, ordinal,
                                       e.schema, old))
    return out
