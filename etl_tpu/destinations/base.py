"""Destination trait and write-acknowledgement semantics.

Reference parity: `Destination` trait (crates/etl/src/destination/base.rs:27)
and `AsyncResult` Accepted/Durable (destination/async_result.rs:22-66):
`write_*` may return a *durable* ack (data is crash-safe at the destination)
or an *accepted* ack (handed off; durability signalled later through the
attached future). The apply loop advances durable progress — and therefore
the replication slot — only on durable acks at commit boundaries.

TPU-first: `write_table_rows` and `write_events` accept ColumnarBatch /
DecodedBatchEvent payloads straight from the device engine; the
`expand_batch_events` helper converts batch events to per-row events for
row-oriented writers.
"""

from __future__ import annotations

import abc
import asyncio
from dataclasses import dataclass, field
from typing import Iterable, Sequence

import numpy as np

from ..analysis.annotations import hot_loop
from ..chaos import failpoints
from ..models.event import (ChangeType, DecodedBatchEvent, DeleteEvent, Event,
                            InsertEvent, UpdateEvent)
from ..models.lsn import Lsn
from ..models.schema import ReplicatedTableSchema, TableId
from ..models.table_row import ColumnarBatch, TableRow


class WriteAck:
    """Acknowledgement of a write. `durable` may be True immediately;
    otherwise await `wait_durable()` (resolves when the destination reports
    crash-safety, or raises if the write ultimately failed).

    Chaos sites (chaos/failpoints.py): every destination constructs its
    ack through `durable()`/`accepted()`, so DESTINATION_WRITE armed
    there fires AFTER the write applied — the lost-response ambiguity —
    and DESTINATION_FLUSH fires on the durability wait, regardless of
    which destination implementation is under test."""

    __slots__ = ("_fut",)

    def __init__(self, fut: "asyncio.Future[None]"):
        self._fut = fut

    @classmethod
    def durable(cls) -> "WriteAck":
        failpoints.fail_point(failpoints.DESTINATION_WRITE)
        fut = asyncio.get_event_loop().create_future()
        fut.set_result(None)
        return cls(fut)

    @classmethod
    def accepted(cls) -> "tuple[WriteAck, asyncio.Future[None]]":
        failpoints.fail_point(failpoints.DESTINATION_WRITE)
        fut = asyncio.get_event_loop().create_future()
        return cls(fut), fut

    @property
    def is_durable(self) -> bool:
        return self._fut.done() and self._fut.exception() is None

    async def wait_durable(self) -> None:
        failpoints.fail_point(failpoints.DESTINATION_FLUSH)
        # chaos stall mode: a flush that never acks (SupervisedDestination
        # bounds this await; the watchdog sees frozen apply progress)
        await failpoints.stall_point(failpoints.DESTINATION_FLUSH)
        await asyncio.shield(self._fut)


@dataclass(frozen=True, slots=True)
class CommitRange:
    """The WAL coordinate range one transactional write covers.

    `high` is the lexicographic max `(commit_lsn, tx_ordinal)` across the
    rows shipped — the per-row dedup key the sinks already speak
    (`EventSequenceKey`, `offset_token_batch`, the DLQ's identity).
    `commit_end_lsn` is the commit watermark the flush may claim for
    durable progress (the ack window's `covered`); None for a
    mid-transaction prefix flush, whose rows still dedup by `high`.
    `replay` marks a DLQ re-delivery: the sink dedups those by EXACT row
    key (MERGE semantics) and must NOT advance its streaming high-water
    mark — replayed rows sit below it by construction (they were parked,
    not delivered, while the stream moved on)."""

    high: "tuple[int, int]"
    commit_end_lsn: "int | None" = None
    replay: bool = False

    def token(self) -> str:
        """Wire token for sinks that record the range as an opaque string
        (ClickHouse insert-dedup ids, Snowpipe offsets): same hex shape
        as `EventSequenceKey.offset_token`."""
        return f"{self.high[0]:016x}/{self.high[1]:016x}"

    @classmethod
    def from_events(cls, events: "Iterable[Event]",
                    commit_end_lsn: "Lsn | int | None" = None,
                    replay: bool = False) -> "CommitRange | None":
        """Derive the covered range from a WAL-ordered flush payload.
        Returns None when nothing in `events` carries row coordinates
        (schema/relation-only flushes have nothing to dedup)."""
        high: "tuple[int, int] | None" = None
        for e in events:
            if isinstance(e, DecodedBatchEvent):
                if len(e.commit_lsns) == 0:
                    continue
                lsns = np.asarray(e.commit_lsns, dtype=np.uint64)
                ords = np.asarray(e.tx_ordinals, dtype=np.uint64)
                top = int(lsns.max())
                cand = (top, int(ords[lsns == top].max()))
            else:
                lsn = getattr(e, "commit_lsn", None)
                ordinal = getattr(e, "tx_ordinal", None)
                if lsn is None or ordinal is None:
                    continue
                cand = (int(lsn), int(ordinal))
            if high is None or cand > high:
                high = cand
        if high is None:
            return None
        end = int(commit_end_lsn) if commit_end_lsn is not None else None
        return cls(high=high, commit_end_lsn=end, replay=replay)


def event_coordinate(e: Event) -> "tuple[int, int] | None":
    """The `(commit_lsn, tx_ordinal)` identity of one row-granular event,
    None for controls without row identity (Begin/Commit/Relation)."""
    lsn = getattr(e, "commit_lsn", None)
    ordinal = getattr(e, "tx_ordinal", None)
    if lsn is None or ordinal is None:
        return None
    return (int(lsn), int(ordinal))


class Destination(abc.ABC):
    """Where decoded rows and CDC events land. Implementations must be
    idempotent under at-least-once delivery (SURVEY §5 checkpoint/resume)."""

    #: wire-encoder name (ops/egress.py ENCODER_*) when this destination
    #: consumes device-rendered text buffers — the runtime binds it into
    #: each DeviceDecoder so decoded batches arrive with `device_egress`
    #: wire bytes attached (docs/destinations.md seam contract). None =
    #: the destination encodes host-side only.
    egress_encoder: "str | None" = None

    @abc.abstractmethod
    async def startup(self) -> None: ...

    @abc.abstractmethod
    async def write_table_rows(self, schema: ReplicatedTableSchema,
                               batch: ColumnarBatch) -> WriteAck:
        """Initial-copy path: append-only rows for one table."""

    @abc.abstractmethod
    async def write_events(self, events: Sequence[Event]) -> WriteAck:
        """CDC path: ordered events (possibly spanning tables)."""

    # -- columnar write seam (ROADMAP item 2) ---------------------------------
    #
    # The decode engine emits ColumnarBatches; these entry points let them
    # reach the wire without materializing Python TableRow objects. Both
    # default to the legacy row-oriented path so third-party / in-memory
    # destinations keep working unchanged — columnar-native writers
    # (BigQuery proto, ClickHouse TSV, lake/Iceberg Parquet) override them.

    async def write_table_batch(self, schema: ReplicatedTableSchema,
                                batch: ColumnarBatch) -> WriteAck:
        """Initial-copy path, columnar seam: append one decoded batch.
        Default: the existing `write_table_rows` implementation (which may
        row-expand internally — the compatibility shim)."""
        return await self.write_table_rows(schema, batch)

    async def write_event_batches(self, events: Sequence[Event]) -> WriteAck:
        """CDC path, columnar seam: ordered events where row changes may
        arrive as `DecodedBatchEvent`s. Default: hand the events to the
        legacy `write_events` path unchanged (destinations there expand
        batches to per-row events themselves — the compatibility shim)."""
        return await self.write_events(events)

    # -- transactional commit seam (ROADMAP item 1, exactly-once) -------------
    #
    # A destination that can record the acked WAL coordinate range
    # ATOMICALLY alongside the data opts in by returning True from the
    # capability probe and overriding the two methods below. The apply
    # loop then ships every CDC flush through
    # `write_event_batches_committed` with its CommitRange, and restart
    # recovery calls `recover_high_water` to trim the re-stream window to
    # exactly the unacked suffix — hard-kill anywhere, dup budget == 0.
    # Destinations that stay out keep today's at-least-once contract
    # bit-for-bit: the defaults below never change behavior.

    def supports_transactional_commit(self) -> bool:
        """Capability probe. True = this destination atomically persists
        each write's CommitRange with the data, dedups re-delivered rows
        by coordinate, and can answer `recover_high_water` after a crash.
        Wrappers delegate dynamically so the probe reflects the wrapped
        sink, never the wrapper."""
        return False

    async def write_event_batches_committed(
            self, events: Sequence[Event],
            commit: "CommitRange | None") -> WriteAck:
        """CDC path, transactional seam: ship `events` AND record `commit`
        in the same atomic unit (one MERGE / one insert with its dedup
        token / one snapshot commit). Rows at coordinates ≤ the sink's
        recorded high-water are duplicates of a blind re-stream and must
        not double-apply; `commit.replay` ranges dedup by exact row key
        instead (DLQ re-delivery). Default: the at-least-once compat shim
        — destinations that don't opt in ignore the range."""
        return await self.write_event_batches(events)

    async def recover_high_water(self) -> "CommitRange | None":
        """Restart recovery: the high-water CommitRange of the last
        transactional write this sink made durable, None when the sink
        has never committed one (fresh sink, or a non-transactional
        destination). Must be read-only and idempotent — recovery may be
        killed and re-run mid-query. Failures must surface as typed
        EtlErrors; the caller retries and degrades to a blind re-stream
        (sink-side dedup still holds the exactly-once invariant)."""
        return None

    @abc.abstractmethod
    async def drop_table(self, table_id: TableId,
                         schema: ReplicatedTableSchema | None = None) -> None:
        """Drop destination table before a (re)copy
        (reference table_sync/mod.rs:184-220 crash-consistency).

        `schema` is the prior stored schema, passed so a freshly restarted
        process — whose in-memory table-name mappings are empty — can still
        resolve which destination table (and channel, for Snowpipe) to
        drop. The reference resolves this through its schema store;
        destinations here rebuild the mapping from the hint."""

    @abc.abstractmethod
    async def truncate_table(self, table_id: TableId) -> None: ...

    async def shutdown(self) -> None:  # optional
        return None


@dataclass(slots=True)
class _RowChange:
    change: ChangeType
    key: tuple
    row: TableRow | None


def batch_event_columnar_ok(e: DecodedBatchEvent) -> bool:
    """True when a batch event can be encoded column-at-a-time with row-path
    semantics preserved: no old tuples (TOAST back-fill and the
    key-changing-update split both need the old image, expand_batch_events
    territory) and no TOAST-unchanged cells (which become column-wise PATCH
    rows on the row path). Resolves the lazy decode — the consumer needs
    the batch either way."""
    if len(e.old_rows) > 0 or e.old_batch is not None:
        return False
    for c in e.batch.columns:
        if c.toast_unchanged is not None and c.toast_unchanged.any():
            return False
    return True


class CoalescedBatch:
    """A contiguous same-table run of simple DecodedBatchEvents merged into
    ONE columnar write: concatenated batch + per-row CDC identity arrays.
    The unit the columnar destination encoders consume."""

    __slots__ = ("schema", "batch", "change_types", "commit_lsns",
                 "tx_ordinals", "egress")

    def __init__(self, events: "list[DecodedBatchEvent]"):
        self.schema = events[0].schema
        self.batch = ColumnarBatch.concat([e.batch for e in events]) \
            if len(events) > 1 else events[0].batch
        # device-rendered wire buffers (ops/egress.py DeviceEgress),
        # merged across the run. All-or-nothing: one event without
        # buffers drops the merged fast path — correctness never depends
        # on egress being present.
        parts = [getattr(e.batch, "device_egress", None) for e in events]
        if len(events) == 1:
            self.egress = parts[0]
        else:
            from ..ops.egress import DeviceEgress

            self.egress = DeviceEgress.concat(parts)
        if len(events) == 1:
            self.change_types = events[0].change_types
            self.commit_lsns = events[0].commit_lsns
            self.tx_ordinals = events[0].tx_ordinals
        else:
            self.change_types = np.concatenate(
                [e.change_types for e in events])
            self.commit_lsns = np.concatenate(
                [np.asarray(e.commit_lsns, dtype=np.uint64) for e in events])
            self.tx_ordinals = np.concatenate(
                [np.asarray(e.tx_ordinals, dtype=np.uint64) for e in events])

    @property
    def num_rows(self) -> int:
        return self.batch.num_rows


@hot_loop
def sequential_batch_program(events: Iterable[Event]):
    """Order-preserving destination program over the columnar seam: yields
    ("batch", schema, CoalescedBatch) for runs of consecutive same-table
    simple DecodedBatchEvents, plus whatever the legacy program yields for
    everything in between — ("rows", schema, [row events…]) runs and
    ("truncate", ev) / ("schema_change", ev) barriers. Events that cannot
    take the columnar fast path (old tuples, TOAST-unchanged cells,
    per-row events from the CPU engine) drop to the row path in place, so
    WAL order is preserved across the two encodings.

    @hot_loop: one call per CDC flush — etl-lint rule 13 keeps row
    materialization out of it except the sanctioned fallback below."""
    from .util import sequential_event_program

    legacy: list[Event] = []
    run: list[DecodedBatchEvent] = []

    def flush_legacy():
        if legacy:
            yield from sequential_event_program(
                expand_batch_events(legacy))  # etl-lint: ignore[hot-loop-row-materialization] — the sanctioned compatibility shim: events that CANNOT encode columnar (old tuples / TOAST / per-row) take the row path here by design
            legacy.clear()

    def flush_run():
        if run:
            yield ("batch", run[0].schema, CoalescedBatch(run))
            run.clear()

    for e in events:
        if isinstance(e, DecodedBatchEvent) and batch_event_columnar_ok(e):
            if run and (run[0].schema.id != e.schema.id
                        or run[0].schema != e.schema):
                yield from flush_run()
            yield from flush_legacy()
            run.append(e)
        else:
            yield from flush_run()
            legacy.append(e)
    yield from flush_run()
    yield from flush_legacy()


def expand_batch_events(events: Iterable[Event]) -> list[Event]:
    """Expand DecodedBatchEvents into per-row Insert/Update/Delete events
    (helper for row-oriented destinations; columnar-native ones consume the
    batch directly).

    Emits events identical to the CPU codec path (codec/event.py): update
    old tuples become TableRow ('O') or identity-masked PartialTableRow
    ('K'), full old tuples back-fill TOAST-unchanged new values, and 'K'
    deletes yield PartialTableRow — reference codec/event.rs:28-50."""
    from ..models.cell import TOAST_UNCHANGED
    from ..models.table_row import PartialTableRow

    out: list[Event] = []
    for e in events:
        if not isinstance(e, DecodedBatchEvent):
            out.append(e)
            continue
        rows = e.batch.to_rows()
        old_batch = e.old_batch
        old_rows_list = old_batch.to_rows() if old_batch is not None else []
        old_by_row = {int(r): j for j, r in enumerate(e.old_rows)}
        identity = e.schema.identity_mask
        idx = e.schema.replicated_indices
        present = [identity[idx[i]] for i in range(len(idx))]

        def partial(row: TableRow) -> PartialTableRow:
            return PartialTableRow(row.values, list(present))

        for i, row in enumerate(rows):
            ct = ChangeType(int(e.change_types[i]))
            commit = Lsn(int(e.commit_lsns[i]))
            ordinal = int(e.tx_ordinals[i])
            if ct is ChangeType.INSERT:
                out.append(InsertEvent(e.start_lsn, commit, ordinal,
                                       e.schema, row))
            elif ct is ChangeType.UPDATE:
                old = None
                j = old_by_row.get(i)
                if j is not None:
                    old_row = old_rows_list[j]
                    if e.old_is_key[j]:
                        old = partial(old_row)
                    else:
                        old = old_row
                        # TOAST merge: unchanged columns take the full old
                        # tuple's values (codec/event.py decode_update)
                        values = row.values
                        for k, v in enumerate(values):
                            if v is TOAST_UNCHANGED:
                                values[k] = old_row.values[k]
                out.append(UpdateEvent(e.start_lsn, commit, ordinal,
                                       e.schema, row, old))
            else:
                old = partial(row) if e.delete_is_key is not None \
                    and e.delete_is_key[i] else row
                out.append(DeleteEvent(e.start_lsn, commit, ordinal,
                                       e.schema, old))
    return out
