"""Destination implementations."""

from .base import (CommitRange, Destination, WriteAck, event_coordinate,
                   expand_batch_events)
from .delay import DelayedAckDestination
from .memory import (FaultAction, FaultInjectingDestination, FaultKind,
                     MemoryDestination, PoisonRejectingDestination,
                     TransactionalMemoryDestination)
from .registry import build_destination
