"""Destination implementations."""

from .base import Destination, WriteAck, expand_batch_events
from .delay import DelayedAckDestination
from .memory import (FaultAction, FaultInjectingDestination, FaultKind,
                     MemoryDestination, PoisonRejectingDestination)
from .registry import build_destination
