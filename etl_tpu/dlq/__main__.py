"""CLI: `python -m etl_tpu.dlq` — operate the dead-letter store.

    python -m etl_tpu.dlq --sqlite state.db --pipeline-id 1 list
    python -m etl_tpu.dlq --sqlite state.db --pipeline-id 1 inspect 3
    python -m etl_tpu.dlq --sqlite state.db --pipeline-id 1 \
        replay --destination-json dest.json [--table 16384] [--ids 1 2]
    python -m etl_tpu.dlq --sqlite state.db --pipeline-id 1 discard 3 4
    python -m etl_tpu.dlq --sqlite state.db --pipeline-id 1 \
        compact [--older-than-s 604800]
    python -m etl_tpu.dlq --sqlite state.db --pipeline-id 1 quarantined
    python -m etl_tpu.dlq --sqlite state.db --pipeline-id 1 \
        unquarantine 16384

`--postgres "host=.. port=.. dbname=.. user=.. password=.."` targets the
shared PostgresStore instead of a sqlite file. `replay` pushes entries
through the REAL destination seam (`destinations.registry
.build_destination` on the given JSON config → `write_event_batches`,
durably awaited) in WAL order and marks them `replayed`; it is
idempotent — replayed entries are skipped on a re-run, and re-pushed
rows are at-least-once duplicates destinations already collapse. The
runbook (docs/dead-letter.md): fix the root cause → replay → verify →
unquarantine — a running replicator adopts the lift live at its next
quarantine poll (PoisonConfig.quarantine_poll_s, default 30 s).
`compact` expires terminal (replayed/discarded) entries past the
retention window; `dead` entries never expire.

Output is one JSON document (sorted keys) per invocation; exit 0 on
success, 1 on a typed failure.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ..models.errors import EtlError


def _parse_pg_dsn(dsn: str):
    from ..config import PgConnectionConfig

    fields = {}
    for part in dsn.split():
        k, _, v = part.partition("=")
        fields[k] = v
    return PgConnectionConfig(
        host=fields.get("host", "localhost"),
        port=int(fields.get("port", 5432)),
        name=fields.get("dbname", fields.get("name", "postgres")),
        username=fields.get("user", fields.get("username", "postgres")),
        password=fields.get("password"))


async def _open_store(args):
    if args.sqlite:
        from ..store import SqliteStore

        store = SqliteStore(args.sqlite, args.pipeline_id)
        await store.connect()
        return store
    from ..store import PostgresStore

    store = PostgresStore(_parse_pg_dsn(args.postgres), args.pipeline_id)
    await store.connect()
    return store


async def _run(args) -> dict:
    from . import DeadLetterQueue

    store = await _open_store(args)
    try:
        dlq = DeadLetterQueue(store)
        if args.cmd == "list":
            status = None if args.status == "all" else args.status
            entries = await dlq.list(table_id=args.table, status=status)
            return {"entries": [e.describe() for e in entries],
                    "count": len(entries)}
        if args.cmd == "inspect":
            return await dlq.inspect(args.entry_id)
        if args.cmd == "replay":
            from ..destinations import build_destination

            with open(args.destination_json) as f:
                dest = build_destination(json.load(f))
            await dest.startup()
            try:
                return await dlq.replay(
                    dest, entry_ids=args.ids or None,
                    table_id=args.table,
                    include_replayed=args.include_replayed)
            finally:
                await dest.shutdown()
        if args.cmd == "discard":
            return {"discarded": await dlq.discard(args.entry_ids)}
        if args.cmd == "compact":
            return await dlq.compact(args.older_than_s,
                                     statuses=args.status or None)
        if args.cmd == "quarantined":
            records = await dlq.quarantined()
            return {"quarantined": [r.to_json()
                                    for r in records.values()]}
        if args.cmd == "unquarantine":
            lifted = await dlq.unquarantine(args.table_id)
            return {"table_id": args.table_id, "lifted": lifted}
        raise AssertionError(args.cmd)
    finally:
        close = getattr(store, "close", None)
        if close is not None:
            await close()


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m etl_tpu.dlq",
        description="inspect / replay / discard dead-lettered rows and "
                    "manage table quarantine (docs/dead-letter.md)")
    store_group = parser.add_mutually_exclusive_group(required=True)
    store_group.add_argument("--sqlite", metavar="PATH",
                             help="sqlite state-store file")
    store_group.add_argument("--postgres", metavar="DSN",
                             help='Postgres store, "host=.. port=.. '
                                  'dbname=.. user=.. password=.."')
    parser.add_argument("--pipeline-id", type=int, required=True)
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_list = sub.add_parser("list", help="list dead-letter entries")
    p_list.add_argument("--table", type=int, default=None)
    p_list.add_argument("--status", default="dead",
                        choices=["dead", "replayed", "discarded", "all"])

    p_inspect = sub.add_parser("inspect",
                               help="one entry with decoded payload")
    p_inspect.add_argument("entry_id", type=int)

    p_replay = sub.add_parser(
        "replay", help="re-deliver entries through the destination seam "
                       "(idempotent), then mark them replayed")
    p_replay.add_argument("--destination-json", required=True,
                          metavar="FILE",
                          help="destination config JSON "
                               '({"type": "bigquery", ...} — '
                               "destinations/registry.py)")
    p_replay.add_argument("--ids", type=int, nargs="*", default=None)
    p_replay.add_argument("--table", type=int, default=None)
    p_replay.add_argument("--include-replayed", action="store_true",
                          help="re-push entries already marked replayed")

    p_discard = sub.add_parser(
        "discard", help="mark entries discarded (kept for audit)")
    p_discard.add_argument("entry_ids", type=int, nargs="+")

    from ..config.pipeline import PoisonConfig

    p_compact = sub.add_parser(
        "compact", help="TTL expiry of replayed/discarded entries "
                        "older than the retention window (`dead` "
                        "entries never expire)")
    p_compact.add_argument(
        "--older-than-s", type=float,
        default=PoisonConfig().dlq_retention_s,
        help="retention window in seconds (default: "
             "PoisonConfig.dlq_retention_s, 7 days)")
    p_compact.add_argument(
        "--status", action="append", default=None,
        choices=["replayed", "discarded"],
        help="restrict expiry to these terminal statuses "
             "(repeatable; default: both)")

    sub.add_parser("quarantined", help="list quarantined tables")

    p_unq = sub.add_parser(
        "unquarantine", help="lift a table's quarantine (replay first; "
                             "a running replicator adopts the lift "
                             "live at its next quarantine poll)")
    p_unq.add_argument("table_id", type=int)

    args = parser.parse_args(argv)
    try:
        out = asyncio.run(_run(args))
    except EtlError as e:
        print(json.dumps({"error": str(e)}, sort_keys=True))
        return 1
    print(json.dumps(out, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
