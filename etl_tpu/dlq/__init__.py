"""Operator surface of the durable dead-letter store.

`python -m etl_tpu.dlq` (see `__main__.py`) and the programmatic
`DeadLetterQueue` wrap the `StateStore` dead-letter/quarantine surface
(store/base.py) with the operator verbs:

  list          — entries (optionally per table / per status)
  inspect       — one entry with its decoded payload
  replay        — re-deliver entries through the DESTINATION SEAM
                  (`Destination.write_event_batches`, the same entry
                  point the apply loop uses) in WAL order, durably, then
                  mark them `replayed`. IDEMPOTENT: already-replayed
                  entries are skipped, and a crash mid-replay re-runs
                  safely because CDC delivery is keyed by
                  (commit_lsn, tx_ordinal) — destinations collapse the
                  duplicate exactly like any at-least-once redelivery.
  discard       — mark entries `discarded` (kept for audit)
  compact       — TTL expiry: delete replayed/discarded entries older
                  than the retention window (`dead` entries are the
                  zero-loss ledger and never expire)
  unquarantine  — lift a table's quarantine record; a RUNNING
                  replicator adopts the lift live at its next
                  quarantine poll (PoisonConfig.quarantine_poll_s,
                  default 30 s) — no restart needed

The zero-loss invariant this surface completes:
`delivered ∪ dead-lettered == committed truth` (docs/dead-letter.md) —
replay moves rows from the right side of the union to the left.
"""

from __future__ import annotations

from ..models.errors import ErrorKind, EtlError
from ..store.base import (DLQ_STATUS_DEAD, DLQ_STATUS_DISCARDED,
                          DLQ_STATUS_REPLAYED, DeadLetterEntry,
                          QuarantineRecord)
from .codec import decode_cell, decode_row_event, encode_row_event

__all__ = [
    "DeadLetterQueue",
    "DeadLetterEntry",
    "QuarantineRecord",
    "decode_cell",
    "decode_row_event",
    "encode_row_event",
]


class DeadLetterQueue:
    """Operator verbs over one pipeline's dead-letter surface. `store`
    is any PipelineStore (memory / sqlite / Postgres)."""

    def __init__(self, store):
        self.store = store

    async def list(self, table_id=None, status=DLQ_STATUS_DEAD
                   ) -> "list[DeadLetterEntry]":
        return await self.store.list_dead_letters(table_id, status)

    async def inspect(self, entry_id: int) -> dict:
        import json

        entry = await self.store.get_dead_letter(entry_id)
        if entry is None:
            raise EtlError(ErrorKind.STATE_STORE_FAILED,
                           f"no dead-letter entry {entry_id}")
        doc = entry.describe()
        payload = json.loads(entry.payload)
        doc["payload"] = payload
        schema = await self.store.get_table_schema(entry.table_id)
        if schema is not None:
            try:
                ev = decode_row_event(entry, schema)
                row = getattr(ev, "row", None) or getattr(ev, "old_row")
                doc["decoded_values"] = [repr(v) for v in row.values]
            except EtlError as e:
                doc["decode_error"] = str(e)
        return doc

    async def replay(self, destination, entry_ids=None, table_id=None,
                     include_replayed: bool = False) -> dict:
        """Re-deliver dead entries through `write_event_batches` in WAL
        order and mark them replayed once DURABLE. Returns a summary.

        Idempotent by construction: `replayed` entries are skipped
        (unless `include_replayed` forces a re-push — itself safe, CDC
        delivery is keyed by WAL coordinates), and a crash after the
        write but before the status flip re-replays rows a destination
        collapses as at-least-once duplicates. Against a transactional
        sink the replay ships a `CommitRange(replay=True)` so the
        re-run dedups by exact WAL row key with ZERO duplicates, and
        the sink's streaming high-water stays untouched."""
        from ..telemetry.metrics import ETL_DLQ_REPLAYED_TOTAL, registry

        if entry_ids is not None:
            entries = []
            for eid in entry_ids:
                e = await self.store.get_dead_letter(eid)
                if e is None:
                    raise EtlError(ErrorKind.STATE_STORE_FAILED,
                                   f"no dead-letter entry {eid}")
                entries.append(e)
        else:
            entries = await self.list(table_id=table_id, status=None)
        wanted = {DLQ_STATUS_DEAD}
        if include_replayed:
            wanted.add(DLQ_STATUS_REPLAYED)
        skipped_status: list[dict] = []
        if entry_ids is not None:
            # an explicitly-requested entry excluded by the status
            # filter must be REPORTED, not silently dropped — an
            # operator replaying `--ids 5` where 5 is discarded would
            # otherwise read empty success
            skipped_status = [
                {"entry_id": e.entry_id,
                 "reason": f"status is {e.status!r}, not replayable "
                           f"(pass --include-replayed to re-push "
                           f"replayed entries; discarded entries stay "
                           f"discarded)"}
                for e in entries if e.status not in wanted]
        entries = [e for e in entries if e.status in wanted]
        # WAL order across the whole replay set — destinations see the
        # rows in their original commit order
        entries.sort(key=lambda e: (e.commit_lsn, e.tx_ordinal,
                                    e.entry_id))
        skipped: list[dict] = list(skipped_status)
        events = []
        replayable: list[DeadLetterEntry] = []
        for e in entries:
            schema = await self.store.get_table_schema(e.table_id)
            if schema is None:
                skipped.append({"entry_id": e.entry_id,
                                "reason": f"no stored schema for table "
                                          f"{e.table_id}"})
                continue
            try:
                events.append(decode_row_event(e, schema))
            except EtlError as err:
                skipped.append({"entry_id": e.entry_id,
                                "reason": str(err)})
                continue
            replayable.append(e)
        if events:
            if destination.supports_transactional_commit():
                # replay-mode committed write: the original WAL
                # coordinates ride along so a transactional sink dedups
                # a re-run replay by EXACT row key — and `replay=True`
                # keeps the sink's streaming high-water untouched
                # (parked rows sit BELOW it; advancing it here would
                # make the live stream drop rows it never applied)
                from ..destinations.base import CommitRange

                rng = CommitRange.from_events(events, replay=True)
                if rng is not None:
                    ack = await destination \
                        .write_event_batches_committed(events, rng)
                else:  # pragma: no cover — replays always carry coords
                    ack = await destination.write_event_batches(events)
            else:
                ack = await destination.write_event_batches(events)
            if ack is not None:
                await ack.wait_durable()
        for e in replayable:
            await self.store.set_dead_letter_status(e.entry_id,
                                                    DLQ_STATUS_REPLAYED)
            registry.counter_inc(ETL_DLQ_REPLAYED_TOTAL)
        return {"replayed": [e.entry_id for e in replayable],
                "skipped": skipped}

    async def discard(self, entry_ids) -> list[int]:
        from ..telemetry.metrics import ETL_DLQ_DISCARDED_TOTAL, registry

        done = []
        for eid in entry_ids:
            e = await self.store.get_dead_letter(eid)
            if e is None:
                raise EtlError(ErrorKind.STATE_STORE_FAILED,
                               f"no dead-letter entry {eid}")
            await self.store.set_dead_letter_status(eid,
                                                    DLQ_STATUS_DISCARDED)
            registry.counter_inc(ETL_DLQ_DISCARDED_TOTAL)
            done.append(eid)
        return done

    async def compact(self, older_than_s: float,
                      statuses=None) -> dict:
        """TTL compaction: delete terminal (replayed/discarded) entries
        whose last status transition is older than `older_than_s`
        seconds. `dead` entries never expire — they are the zero-loss
        ledger — and passing "dead" in `statuses` is refused."""
        statuses = tuple(statuses) if statuses else (
            DLQ_STATUS_REPLAYED, DLQ_STATUS_DISCARDED)
        if DLQ_STATUS_DEAD in statuses:
            raise EtlError(
                ErrorKind.STATE_STORE_FAILED,
                "refusing to expire `dead` entries: they are the "
                "zero-loss ledger (replay or discard them first)")
        purged = await self.store.purge_dead_letters(older_than_s,
                                                     statuses)
        return {"purged": purged, "older_than_s": older_than_s,
                "statuses": sorted(statuses)}

    async def quarantined(self) -> dict:
        return await self.store.get_quarantined_tables()

    async def unquarantine(self, table_id: int) -> bool:
        """Lift a table's quarantine. Returns False when the table was
        not quarantined. A running replicator adopts the lift LIVE at
        its next quarantine poll (PoisonConfig.quarantine_poll_s,
        default 30 s) — docs/dead-letter.md runbook: replay first,
        then unquarantine; no pod roll required."""
        records = await self.store.get_quarantined_tables()
        if table_id not in records:
            return False
        await self.store.set_table_quarantine(table_id, None)
        return True
