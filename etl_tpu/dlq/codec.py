"""Dead-letter payload codec: decoded row events ↔ JSON.

A poison row must survive on the `StateStore` dead-letter surface in a
form an OPERATOR can inspect and a later `replay` can push back through
`Destination.write_event_batches` — after the process that isolated it
is long gone. The codec therefore round-trips the full decoded-cell
value vocabulary (models/cell.py): None, bool, int, float, str, bytes,
Decimal/PgNumeric, datetime/date/time, PgTimeTz, PgInterval,
PgSpecialDate/PgSpecialTimestamp (BC values outside Python's datetime
range), uuid.UUID, JsonNull, ToastUnchanged, dicts (JSON columns) and
lists (ARRAY columns).

Encoding: scalars that JSON represents natively AND unambiguously stay
plain (None/bool/int/float/str); everything else becomes a small tagged
list `["<tag>", ...args]` — a plain JSON list can therefore never be
mistaken for an ARRAY value, which is itself tagged. An unknown value
type degrades to `["opaque", repr(v)]` (lossy but inspectable — the
isolation protocol must park SOMETHING rather than die on an exotic
cell), decoded back as its repr string.
"""

from __future__ import annotations

import datetime as dt
import json
import uuid as uuid_mod
from decimal import Decimal

from ..models.cell import (JSON_NULL, TOAST_UNCHANGED, JsonNull, PgInterval,
                           PgNumeric, PgSpecialDate, PgSpecialTimestamp,
                           PgTimeTz, ToastUnchanged)
from ..models.errors import ErrorKind, EtlError
from ..models.event import (ChangeType, DeleteEvent, InsertEvent,
                            UpdateEvent)
from ..models.lsn import Lsn
from ..models.table_row import PartialTableRow, TableRow

PAYLOAD_VERSION = 1


def encode_cell(v) -> object:
    """One decoded cell value → a JSON-representable object."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        # json round-trips float64 exactly via repr; NaN/Inf are not
        # valid JSON, so tag them
        if v != v or v in (float("inf"), float("-inf")):
            return ["fspecial", repr(v)]
        return v
    if isinstance(v, PgNumeric):
        return ["num", v.pg_text()]
    if isinstance(v, Decimal):
        return ["dec", str(v)]
    if isinstance(v, bytes):
        return ["bytes", v.hex()]
    if isinstance(v, dt.datetime):
        return ["tstz" if v.tzinfo is not None else "ts", v.isoformat()]
    if isinstance(v, dt.date):
        return ["date", v.isoformat()]
    if isinstance(v, PgTimeTz):
        return ["timetz", v.time.isoformat(), v.offset_seconds]
    if isinstance(v, dt.time):
        return ["time", v.isoformat()]
    if isinstance(v, PgInterval):
        return ["interval", v.months, v.days, v.microseconds]
    if isinstance(v, PgSpecialDate):
        return ["sdate", v.days, v.text]
    if isinstance(v, PgSpecialTimestamp):
        return ["sts", v.micros, v.text, v.tz_aware]
    if isinstance(v, uuid_mod.UUID):
        return ["uuid", str(v)]
    if isinstance(v, JsonNull):
        return ["jsonnull"]
    if isinstance(v, ToastUnchanged):
        return ["toast"]
    if isinstance(v, dict):
        return ["json", v]
    if isinstance(v, list):
        return ["arr", [encode_cell(x) for x in v]]
    return ["opaque", repr(v)]


_DECODERS = {
    "fspecial": lambda a: float(a[0]),
    "num": lambda a: PgNumeric(a[0]),
    "dec": lambda a: Decimal(a[0]),
    "bytes": lambda a: bytes.fromhex(a[0]),
    "ts": lambda a: dt.datetime.fromisoformat(a[0]),
    "tstz": lambda a: dt.datetime.fromisoformat(a[0]),
    "date": lambda a: dt.date.fromisoformat(a[0]),
    "time": lambda a: dt.time.fromisoformat(a[0]),
    "timetz": lambda a: PgTimeTz(dt.time.fromisoformat(a[0]), int(a[1])),
    "interval": lambda a: PgInterval(int(a[0]), int(a[1]), int(a[2])),
    "sdate": lambda a: PgSpecialDate(int(a[0]), a[1]),
    "sts": lambda a: PgSpecialTimestamp(int(a[0]), a[1], bool(a[2])),
    "uuid": lambda a: uuid_mod.UUID(a[0]),
    "jsonnull": lambda a: JSON_NULL,
    "toast": lambda a: TOAST_UNCHANGED,
    "json": lambda a: a[0],
    "arr": lambda a: [decode_cell(x) for x in a[0]],
    "opaque": lambda a: a[0],
}


def decode_cell(v):
    if isinstance(v, list):
        try:
            return _DECODERS[v[0]](v[1:])
        except (KeyError, IndexError, ValueError) as e:
            raise EtlError(ErrorKind.STORE_SERIALIZATION_FAILED,
                           f"undecodable dead-letter cell {v!r}: {e}")
    return v


def encode_row_event(ev) -> tuple[int, str]:
    """A per-row event (Insert/Update/Delete) → (change_type, payload
    JSON). The payload keeps everything `decode_row_event` needs to
    rebuild the event against the CURRENT schema: new values, the old
    image (with its identity-presence mask for 'K' tuples), the start
    LSN, and the column names at isolation time (inspection aid — replay
    binds by position against the live schema)."""
    if isinstance(ev, InsertEvent):
        change, values, old = ChangeType.INSERT, ev.row.values, None
    elif isinstance(ev, UpdateEvent):
        change, values = ChangeType.UPDATE, ev.row.values
        old = ev.old_row
    elif isinstance(ev, DeleteEvent):
        change, old = ChangeType.DELETE, ev.old_row
        values = ev.old_row.values
    else:
        raise EtlError(ErrorKind.STORE_SERIALIZATION_FAILED,
                       f"not a row event: {type(ev).__name__}")
    doc = {
        "v": PAYLOAD_VERSION,
        "start_lsn": int(ev.start_lsn),
        "values": [encode_cell(v) for v in values],
        "old": None,
        "columns": [c.name for c in ev.schema.replicated_columns],
    }
    if isinstance(ev, UpdateEvent) and old is not None:
        doc["old"] = {
            "values": [encode_cell(v) for v in old.values],
            "present": list(old.present)
            if isinstance(old, PartialTableRow) else None,
        }
    elif isinstance(ev, DeleteEvent):
        doc["old"] = {
            "values": None,  # same as `values` — stored once
            "present": list(old.present)
            if isinstance(old, PartialTableRow) else None,
        }
    return int(change), json.dumps(doc, sort_keys=True)


def decode_row_event(entry, schema):
    """A stored `DeadLetterEntry` + the table's CURRENT
    ReplicatedTableSchema → the replayable event. Raises typed when the
    payload's width no longer matches the schema (DDL moved on — the
    operator must migrate or discard)."""
    doc = json.loads(entry.payload)
    values = [decode_cell(v) for v in doc["values"]]
    n_cols = schema.replicated_column_count()
    if len(values) != n_cols:
        raise EtlError(
            ErrorKind.SCHEMA_MISMATCH,
            f"dead-letter entry {entry.entry_id} has {len(values)} "
            f"columns but table {entry.table_id}'s current schema has "
            f"{n_cols}; migrate the payload or discard the entry")
    start_lsn = Lsn(int(doc.get("start_lsn", entry.commit_lsn)))
    commit = Lsn(entry.commit_lsn)
    change = ChangeType(entry.change_type)
    if change is ChangeType.INSERT:
        return InsertEvent(start_lsn, commit, entry.tx_ordinal, schema,
                           TableRow(values))
    old_doc = doc.get("old")
    if change is ChangeType.UPDATE:
        old = None
        if old_doc is not None:
            old_values = [decode_cell(v) for v in old_doc["values"]]
            present = old_doc.get("present")
            old = PartialTableRow(old_values, present) \
                if present is not None else TableRow(old_values)
        return UpdateEvent(start_lsn, commit, entry.tx_ordinal, schema,
                           TableRow(values), old)
    present = old_doc.get("present") if old_doc else None
    old_row = PartialTableRow(values, present) if present is not None \
        else TableRow(values)
    return DeleteEvent(start_lsn, commit, entry.tx_ordinal, schema, old_row)
