"""The reconciler's actuation seam: what a fleet runtime must do.

Three verbs plus enumeration — deliberately the smallest surface that
lets the reconciler converge a fleet, and every verb IDEMPOTENT by
contract (creating a pipeline that is already running at the target K,
resizing to the current K, deleting an absent pipeline: all no-ops).
Idempotence is what makes crash resume safe: a successor that cannot
tell whether the dead coordinator's actuation landed may re-drive the
verb without harm, and only skips it when the observed fleet already
shows the target (journal.py `satisfied_by`).

Implementations:
  - `OrchestratorFleetRuntime` (here): drives a real `Orchestrator`
    (K8s StatefulSets or local subprocesses) — the production path;
  - `SimulatedFleetRuntime` (sim.py): the 100-pipeline in-process
    model the chaos scenario and bench converge gate run against.
"""

from __future__ import annotations

import abc

from ..api.orchestrator import Orchestrator, ReplicatorSpec
from .spec import PipelineSpec


class FleetRuntime(abc.ABC):
    """What the reconciler actuates against. Resize takes the full
    desired `PipelineSpec` (its `shard_count` IS the target K): rolling
    a deployment needs the config document, not just the id."""

    @abc.abstractmethod
    async def list_pipelines(self) -> "dict[int, int]":
        """Observed fleet: pipeline_id -> live shard count. The
        reconciler's observe step AND the chaos leak check both
        enumerate through here — a runtime that cannot list cannot be
        reconciled."""

    @abc.abstractmethod
    async def create_pipeline(self, spec: PipelineSpec) -> None: ...

    @abc.abstractmethod
    async def resize_pipeline(self, spec: PipelineSpec) -> None: ...

    @abc.abstractmethod
    async def delete_pipeline(self, pipeline_id: int) -> None: ...


class OrchestratorFleetRuntime(FleetRuntime):
    """Fleet verbs over a real Orchestrator: create/resize both roll
    through `start_pipeline`/`scale_pipeline` (idempotent re-apply —
    the StatefulSet 409→PATCH path, the LocalOrchestrator same-spec
    no-op), delete through `delete_pipeline` (404-tolerant)."""

    def __init__(self, orchestrator: Orchestrator):
        self.orchestrator = orchestrator

    def _replicator_spec(self, spec: PipelineSpec) -> ReplicatorSpec:
        config = dict(spec.config)
        config.setdefault("pipeline_id", spec.pipeline_id)
        config.setdefault("destination", {"type": spec.destination})
        config["shard_count"] = spec.shard_count
        return ReplicatorSpec(
            pipeline_id=spec.pipeline_id, tenant_id=spec.tenant_id,
            config=config, shard_count=spec.shard_count)

    async def list_pipelines(self) -> "dict[int, int]":
        return await self.orchestrator.list_pipelines()

    async def create_pipeline(self, spec: PipelineSpec) -> None:
        await self.orchestrator.start_pipeline(self._replicator_spec(spec))

    async def resize_pipeline(self, spec: PipelineSpec) -> None:
        await self.orchestrator.scale_pipeline(
            self._replicator_spec(spec), spec.shard_count)

    async def delete_pipeline(self, pipeline_id: int) -> None:
        await self.orchestrator.delete_pipeline(pipeline_id)
