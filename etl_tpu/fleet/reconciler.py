"""The fleet control loop: observe → diff → converge.

Level-triggered reconciliation (the Kubernetes controller pattern): the
reconciler never remembers what it did — every tick re-reads the
desired `FleetSpec` from the store, re-enumerates the OBSERVED fleet
from the runtime, and computes the delta from scratch. Missed events
cannot exist because there are no events; a coordinator can be
hard-killed at any instant and its successor starts from the same two
sources of truth.

The tick body:

  1. observe  — `runtime.list_pipelines()` (pipeline_id → live K) and
                the persisted `FleetSpec`;
  2. place    — per-tenant quota clamping (`place_fleet`, pure): a
                tenant's aggregate shard ask is trimmed to its
                `TenantQuota.max_shards`, deterministically (pipeline-id
                order, every pipeline keeps ≥ 1 shard);
  3. diff     — `diff_fleet` (pure, `@control_loop`: no I/O, no clock —
                etl-lint rule 16 enforces it): the verb list that
                converges observed onto placed, deletes first (they
                free quota), then creates, then resizes, each in
                pipeline-id order;
  4. converge — per verb: persist a PENDING `ActuationRecord` to that
                pipeline's journal, actuate the runtime, settle
                APPLIED. A pipeline whose journal already holds a
                pending record is HELD this tick (single-flight per
                pipeline; `resume()` owns pendings);
  5. feed     — per-tenant SLO weights from the spec's quotas into the
                shared `AdmissionScheduler`.

Crash recovery (`resume()`): scan every pipeline's journal for pending
records. If the observed fleet already shows the record's target, the
actuation landed before the crash — settle APPLIED with NO runtime
call (zero double-actuation, the chaos scenario's journal-verified
invariant). Otherwise re-drive the verb (idempotent by the
FleetRuntime contract) and settle. A pending record whose pipeline the
CURRENT spec no longer demands is settled ABORTED — the next tick
reconciles to the new truth anyway.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field, replace

from ..analysis.annotations import control_loop, domain, handoff
from ..telemetry.metrics import (ETL_FLEET_CONVERGED,
                                 ETL_FLEET_PIPELINES_DESIRED,
                                 ETL_FLEET_PIPELINES_OBSERVED,
                                 ETL_FLEET_RECONCILE_ACTIONS_TOTAL,
                                 ETL_FLEET_RECONCILE_HOLDS_TOTAL,
                                 ETL_FLEET_RESUMES_TOTAL,
                                 ETL_FLEET_SHARDS_DESIRED,
                                 ETL_FLEET_SPEC_VERSION, registry)
from .journal import (STATUS_ABORTED, STATUS_APPLIED, VERB_CREATE,
                      VERB_DELETE, VERB_RESIZE, ActuationJournal,
                      ActuationRecord)
from .runtime import FleetRuntime
from .spec import FleetSpec, PipelineSpec

logger = logging.getLogger("etl_tpu.fleet")


@dataclass(frozen=True)
class FleetAction:
    """One diffed verb. `from_k` is the observed shard count (0 =
    absent), `to_k` the placed target (0 = delete)."""

    verb: str
    pipeline_id: int
    from_k: int
    to_k: int

    def describe(self) -> dict:
        return {"verb": self.verb, "pipeline_id": self.pipeline_id,
                "from_k": self.from_k, "to_k": self.to_k}


@control_loop
def place_fleet(spec: FleetSpec) -> "dict[int, int]":
    """Quota-clamped target shard counts: pipeline_id → K. Pure and
    deterministic: per tenant, every pipeline is first granted one
    shard (a quota can squeeze a tenant, never evict it — eviction is a
    spec edit, not a placement side effect), then the remaining budget
    is dealt in pipeline-id order up to each pipeline's ask.
    `max_shards == 0` means unlimited."""
    targets: dict[int, int] = {}
    by_tenant: dict[str, list[PipelineSpec]] = {}
    for p in spec.pipelines:
        by_tenant.setdefault(p.tenant_id, []).append(p)
    for tenant, pipes in by_tenant.items():
        pipes = sorted(pipes, key=lambda p: p.pipeline_id)
        quota = spec.quotas.get(tenant)
        budget = quota.max_shards if quota and quota.max_shards > 0 \
            else None
        if budget is None or budget >= sum(p.shard_count for p in pipes):
            for p in pipes:
                targets[p.pipeline_id] = p.shard_count
            continue
        for p in pipes:
            targets[p.pipeline_id] = 1
        remaining = budget - len(pipes)
        for p in pipes:
            if remaining <= 0:
                break
            grant = min(p.shard_count - 1, remaining)
            targets[p.pipeline_id] += grant
            remaining -= grant
    return targets


@control_loop
def diff_fleet(targets: "dict[int, int]",
               observed: "dict[int, int]") -> "tuple[FleetAction, ...]":
    """The verb list converging `observed` onto `targets`. Pure: no
    I/O, no clock, no randomness — the same two maps always yield the
    same actions in the same order (deletes, creates, resizes; each by
    pipeline_id)."""
    deletes = [FleetAction(VERB_DELETE, pid, observed[pid], 0)
               for pid in sorted(observed) if pid not in targets]
    creates = [FleetAction(VERB_CREATE, pid, 0, targets[pid])
               for pid in sorted(targets) if pid not in observed]
    resizes = [FleetAction(VERB_RESIZE, pid, observed[pid], targets[pid])
               for pid in sorted(targets)
               if pid in observed and observed[pid] != targets[pid]]
    return tuple(deletes + creates + resizes)


@dataclass
class ReconcileResult:
    """One tick's outcome."""

    spec_version: int = 0
    desired: int = 0
    observed: int = 0
    applied: list = field(default_factory=list)  # FleetAction
    held: list = field(default_factory=list)  # pipeline ids (pending)
    converged: bool = False

    def describe(self) -> dict:
        return {
            "spec_version": self.spec_version,
            "desired": self.desired,
            "observed": self.observed,
            "applied": [a.describe() for a in self.applied],
            "held": list(self.held),
            "converged": self.converged,
        }


class FleetReconciler:
    """The fleet coordinator's control loop. Singleton per fleet, like
    the autoscale controller per pipeline: the per-pipeline journals'
    single-flight check assumes one writer. Runs against the RAW store
    (never a shard view — `ShardScopedStore` refuses fleet writes)."""

    def __init__(self, *, store, runtime: FleetRuntime, bus=None,
                 scheduler=None):
        self.store = store
        self.runtime = runtime
        self.bus = bus  # optional FleetSignalBus (policy plugins)
        self._scheduler = scheduler  # AdmissionScheduler | None = global
        self.ticks = 0

    # -- journal persistence -------------------------------------------------

    async def _load_journal(self, pipeline_id: int) -> ActuationJournal:
        return ActuationJournal.from_json(
            await self.store.get_fleet_journal(pipeline_id))

    @handoff  # persist-then-actuate seam: the journal write IS the
    # happens-before edge a restarted coordinator resumes from
    async def _save_journal(self, pipeline_id: int,
                            journal: ActuationJournal) -> None:
        await self.store.update_fleet_journal(pipeline_id,
                                              journal.to_json())

    # -- observe / desired ---------------------------------------------------

    async def load_spec(self) -> FleetSpec:
        return FleetSpec.from_json(await self.store.get_fleet_spec())

    async def observe(self) -> "dict[int, int]":
        return dict(await self.runtime.list_pipelines())

    # -- SLO feed ------------------------------------------------------------

    def apply_slo_weights(self, spec: FleetSpec) -> None:
        """Per-tenant quota SLO weights into the shared admission
        scheduler (tenant names are prefixes there, so one weight covers
        every stream the tenant's pipelines register)."""
        if not spec.quotas:
            return
        scheduler = self._scheduler
        if scheduler is None:
            from ..ops.pipeline import global_admission

            scheduler = global_admission()
        for tenant, quota in sorted(spec.quotas.items()):
            scheduler.set_slo_weight(tenant, quota.slo_weight)

    # -- actuation -----------------------------------------------------------

    async def _actuate(self, action: FleetAction,
                       spec_by_id: "dict[int, PipelineSpec]") -> None:
        if action.verb == VERB_DELETE:
            await self.runtime.delete_pipeline(action.pipeline_id)
            return
        pipeline = replace(spec_by_id[action.pipeline_id],
                           shard_count=action.to_k)
        if action.verb == VERB_CREATE:
            await self.runtime.create_pipeline(pipeline)
        else:
            await self.runtime.resize_pipeline(pipeline)

    # -- the loop body -------------------------------------------------------

    @domain("coordinator")
    async def tick(self) -> ReconcileResult:
        """One reconcile turn (module docstring). Every applied action
        is journaled persist-then-actuate; a crash mid-tick leaves at
        most ONE pending record (actuation is sequential) for resume()."""
        spec = await self.load_spec()
        observed = await self.observe()
        targets = place_fleet(spec)
        actions = diff_fleet(targets, observed)
        spec_by_id = spec.by_id()
        result = ReconcileResult(
            spec_version=spec.spec_version,
            desired=len(targets), observed=len(observed))
        self.ticks += 1

        for action in actions:
            journal = await self._load_journal(action.pipeline_id)
            if journal.pending() is not None:
                # single-flight per pipeline: a pending record means a
                # crashed (or concurrent) actuation — resume() owns it
                result.held.append(action.pipeline_id)
                registry.counter_inc(ETL_FLEET_RECONCILE_HOLDS_TOTAL,
                                     labels={"reason": "pending"})
                continue
            rec = journal.open(verb=action.verb, from_k=action.from_k,
                               to_k=action.to_k,
                               spec_version=spec.spec_version)
            await self._save_journal(action.pipeline_id, journal)
            # persist-then-actuate: the crash window between these two
            # writes is exactly what resume() covers
            await self._actuate(action, spec_by_id)
            journal = await self._load_journal(action.pipeline_id)
            journal.settle(rec.decision_id, STATUS_APPLIED)
            await self._save_journal(action.pipeline_id, journal)
            result.applied.append(action)
            registry.counter_inc(ETL_FLEET_RECONCILE_ACTIONS_TOTAL,
                                 labels={"verb": action.verb})
            logger.info("fleet actuation %s pipeline %d: K=%d->%d "
                        "(spec v%d)", action.verb, action.pipeline_id,
                        action.from_k, action.to_k, spec.spec_version)

        self.apply_slo_weights(spec)
        result.converged = not actions and not result.held
        registry.gauge_set(ETL_FLEET_SPEC_VERSION, spec.spec_version)
        registry.gauge_set(ETL_FLEET_PIPELINES_DESIRED, len(targets))
        registry.gauge_set(ETL_FLEET_PIPELINES_OBSERVED, len(observed))
        registry.gauge_set(ETL_FLEET_SHARDS_DESIRED,
                           sum(targets.values()))
        registry.gauge_set(ETL_FLEET_CONVERGED,
                           1 if result.converged else 0)
        return result

    async def converge(self, max_ticks: int = 8) -> int:
        """Tick until steady (a tick that applies nothing and holds
        nothing). Returns the number of ticks that DID work; raises
        nothing on non-convergence — the caller gates on the count."""
        for i in range(max_ticks):
            result = await self.tick()
            if result.converged:
                return i
        return max_ticks

    # -- crash recovery ------------------------------------------------------

    @domain("coordinator")
    async def resume(self) -> "list[ActuationRecord]":
        """Settle every pending actuation a dead coordinator left
        behind (module docstring). Returns the settled records;
        idempotent — a second call finds nothing pending."""
        journals = await self.store.get_fleet_journals()
        pendings = [(pid, ActuationJournal.from_json(doc))
                    for pid, doc in sorted(journals.items())]
        pendings = [(pid, j, j.pending()) for pid, j in pendings
                    if j.pending() is not None]
        if not pendings:
            return []
        observed = await self.observe()
        spec = await self.load_spec()
        spec_by_id = spec.by_id()
        settled: list[ActuationRecord] = []
        for pid, journal, rec in pendings:
            observed_k = observed.get(pid, 0)
            if rec.satisfied_by(observed_k):
                # crash AFTER the actuation, before the settle write:
                # the fleet already shows the target — journal-only,
                # ZERO runtime calls (the no-double-actuation half)
                journal.settle(rec.decision_id, STATUS_APPLIED)
                await self._save_journal(pid, journal)
                settled.append(replace(rec, status=STATUS_APPLIED))
                registry.counter_inc(ETL_FLEET_RESUMES_TOTAL,
                                     labels={"mode": "settle"})
                logger.info("fleet resume: pipeline %d decision %d "
                            "already actuated — settled", pid,
                            rec.decision_id)
                continue
            if rec.verb != VERB_DELETE and pid not in spec_by_id:
                # the spec moved on while the record was pending (the
                # pipeline was removed): abort — the next tick diffs
                # against the new truth and deletes the stray if needed
                journal.settle(rec.decision_id, STATUS_ABORTED)
                await self._save_journal(pid, journal)
                settled.append(replace(rec, status=STATUS_ABORTED))
                registry.counter_inc(ETL_FLEET_RESUMES_TOTAL,
                                     labels={"mode": "abort"})
                continue
            # crash BEFORE the actuation landed: re-drive the verb
            # (idempotent by the FleetRuntime contract), then settle
            action = FleetAction(rec.verb, pid, observed_k, rec.to_k)
            await self._actuate(action, spec_by_id)
            journal = await self._load_journal(pid)
            journal.settle(rec.decision_id, STATUS_APPLIED)
            await self._save_journal(pid, journal)
            settled.append(replace(rec, status=STATUS_APPLIED))
            registry.counter_inc(ETL_FLEET_RESUMES_TOTAL,
                                 labels={"mode": "redrive"})
            logger.info("fleet resume: pipeline %d decision %d "
                        "re-driven to applied", pid, rec.decision_id)
        return settled
