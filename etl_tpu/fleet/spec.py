"""Declarative fleet desired state: what SHOULD be running.

The Kubernetes-controller stance (level-triggered, Borg/Omega lineage):
operators edit a `FleetSpec` document — pipelines × shard counts ×
destinations × tenancy profile — and submit it whole; the reconciler
(reconciler.py) owns making reality match. Nothing in here runs
anything: the spec is pure data, persisted on the StateStore fleet
surface (store/base.py `update_fleet_spec`) with a MONOTONIC
`spec_version` so a stale operator or partitioned coordinator can never
roll the fleet's desired state back.

Tenancy rides two knobs:
  - `profile`: the seeded workload-mix name (etl_tpu/workloads) that
    describes the tenant's traffic shape — the simulated fleet draws
    its per-pipeline workload from it, and operators use it to group
    capacity planning;
  - per-tenant `TenantQuota`s: a hard shard budget (placement clamps a
    tenant's aggregate shard ask to it, deterministically) and an SLO
    weight fed into `AdmissionScheduler.set_slo_weight` so a tenant's
    admission share follows the same document that sizes its fleet.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..models.errors import ErrorKind, EtlError

#: hard ceiling on a single pipeline's shard count inside a fleet spec —
#: matches the orchestrator's shard-discovery probing bound
#: (K8sOrchestrator.MAX_SHARDS); a fleet never creates what stop/status
#: could not later find
MAX_SHARDS_PER_PIPELINE = 64


@dataclass(frozen=True)
class TenantQuota:
    """One tenant's fleet-wide budget. `max_shards` caps the tenant's
    AGGREGATE shard count across all its pipelines (0 = unlimited);
    `slo_weight` is the admission-scheduler priority the reconciler
    installs for the tenant prefix."""

    max_shards: int = 0
    slo_weight: float = 1.0

    def to_json(self) -> dict:
        return {"max_shards": self.max_shards,
                "slo_weight": self.slo_weight}

    @classmethod
    def from_json(cls, doc: dict) -> "TenantQuota":
        return cls(max_shards=int(doc.get("max_shards", 0)),
                   slo_weight=float(doc.get("slo_weight", 1.0)))


@dataclass(frozen=True)
class PipelineSpec:
    """One pipeline's desired state inside the fleet."""

    pipeline_id: int
    tenant_id: str
    shard_count: int = 1
    destination: str = "memory"  # destination type name (config doc key)
    profile: str = "insert_heavy"  # workload/tenancy profile name
    config: dict = field(default_factory=dict)  # replicator config overrides

    def validate(self) -> None:
        if self.pipeline_id < 1:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           f"pipeline_id must be >= 1, got "
                           f"{self.pipeline_id}")
        if not self.tenant_id:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           f"pipeline {self.pipeline_id}: empty tenant_id")
        if not 1 <= self.shard_count <= MAX_SHARDS_PER_PIPELINE:
            raise EtlError(
                ErrorKind.CONFIG_INVALID,
                f"pipeline {self.pipeline_id}: shard_count "
                f"{self.shard_count} outside [1, "
                f"{MAX_SHARDS_PER_PIPELINE}]")

    def to_json(self) -> dict:
        return {
            "pipeline_id": self.pipeline_id,
            "tenant_id": self.tenant_id,
            "shard_count": self.shard_count,
            "destination": self.destination,
            "profile": self.profile,
            "config": dict(self.config),
        }

    @classmethod
    def from_json(cls, doc: dict) -> "PipelineSpec":
        return cls(
            pipeline_id=int(doc["pipeline_id"]),
            tenant_id=str(doc["tenant_id"]),
            shard_count=int(doc.get("shard_count", 1)),
            destination=str(doc.get("destination", "memory")),
            profile=str(doc.get("profile", "insert_heavy")),
            config=dict(doc.get("config", {})),
        )


@dataclass(frozen=True)
class FleetSpec:
    """The whole fleet's desired state, versioned. One JSON document on
    the StateStore fleet surface; every edit submits a NEW spec with
    `spec_version` bumped — the store refuses regressions."""

    spec_version: int = 0
    pipelines: tuple = ()  # tuple[PipelineSpec] sorted by pipeline_id
    quotas: dict = field(default_factory=dict)  # tenant_id -> TenantQuota

    def validate(self) -> None:
        if self.spec_version < 0:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           f"spec_version must be >= 0, got "
                           f"{self.spec_version}")
        seen: set[int] = set()
        for p in self.pipelines:
            p.validate()
            if p.pipeline_id in seen:
                raise EtlError(
                    ErrorKind.CONFIG_INVALID,
                    f"duplicate pipeline_id {p.pipeline_id} in fleet spec")
            seen.add(p.pipeline_id)
        for tenant, q in self.quotas.items():
            if q.max_shards < 0:
                raise EtlError(
                    ErrorKind.CONFIG_INVALID,
                    f"tenant {tenant}: max_shards must be >= 0")
            if q.slo_weight <= 0:
                raise EtlError(
                    ErrorKind.CONFIG_INVALID,
                    f"tenant {tenant}: slo_weight must be > 0")

    def by_id(self) -> "dict[int, PipelineSpec]":
        return {p.pipeline_id: p for p in self.pipelines}

    def with_edit(self, *, add=(), remove=(),
                  resize: "dict[int, int] | None" = None) -> "FleetSpec":
        """A new spec (version + 1) with pipelines added/removed/resized
        — the operator-edit primitive the chaos and bench scripts use."""
        from dataclasses import replace

        by_id = self.by_id()
        for pid in remove:
            by_id.pop(int(pid), None)
        for p in add:
            by_id[p.pipeline_id] = p
        for pid, k in (resize or {}).items():
            if int(pid) in by_id:
                by_id[int(pid)] = replace(by_id[int(pid)],
                                          shard_count=int(k))
        spec = FleetSpec(
            spec_version=self.spec_version + 1,
            pipelines=tuple(sorted(by_id.values(),
                                   key=lambda p: p.pipeline_id)),
            quotas=dict(self.quotas))
        spec.validate()
        return spec

    def to_json(self) -> dict:
        return {
            "spec_version": self.spec_version,
            "pipelines": [p.to_json() for p in self.pipelines],
            "quotas": {t: q.to_json() for t, q in
                       sorted(self.quotas.items())},
        }

    @classmethod
    def from_json(cls, doc: "dict | None") -> "FleetSpec":
        if doc is None:
            return cls()
        spec = cls(
            spec_version=int(doc.get("spec_version", 0)),
            pipelines=tuple(sorted(
                (PipelineSpec.from_json(p)
                 for p in doc.get("pipelines", [])),
                key=lambda p: p.pipeline_id)),
            quotas={str(t): TenantQuota.from_json(q)
                    for t, q in doc.get("quotas", {}).items()},
        )
        spec.validate()
        return spec
