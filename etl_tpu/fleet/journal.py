"""Per-pipeline actuation journal: the autoscale-journal pattern
(etl_tpu/autoscale/controller.py AutoscaleJournal) generalized from
"K→K±1 scale decisions on one pipeline" to "create/resize/delete verbs
across a fleet".

Persist-then-actuate is the whole contract: the reconciler writes a
PENDING record to the store (one journal document PER PIPELINE — two
pipelines' rolls never contend on one row) BEFORE touching the
orchestrator, actuates, then settles the record APPLIED. A coordinator
hard-killed anywhere in that window leaves a pending record its
successor finds via `get_fleet_journals()`; the successor consults the
OBSERVED fleet to tell crash-before-actuation (re-drive, the runtime
verbs are idempotent) from crash-after-actuation (settle only, no
second actuation) — that is the zero-double-actuation guarantee the
chaos scenario verifies against the runtime's actuation log.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

STATUS_PENDING = "pending"
STATUS_APPLIED = "applied"
STATUS_ABORTED = "aborted"

VERB_CREATE = "create"
VERB_RESIZE = "resize"
VERB_DELETE = "delete"


@dataclass(frozen=True)
class ActuationRecord:
    """One journaled fleet actuation. `decision_id` is monotonic per
    pipeline; `spec_version` pins which desired state demanded it;
    `from_k`/`to_k` are observed/target shard counts (0 = absent), so a
    resume can tell whether the actuation already took effect."""

    decision_id: int
    spec_version: int
    verb: str  # create | resize | delete
    from_k: int  # observed shard count when decided (0 = absent)
    to_k: int  # target shard count (0 = delete)
    status: str = STATUS_PENDING

    def to_json(self) -> dict:
        return {
            "decision_id": self.decision_id,
            "spec_version": self.spec_version,
            "verb": self.verb,
            "from_k": self.from_k,
            "to_k": self.to_k,
            "status": self.status,
        }

    @classmethod
    def from_json(cls, doc: dict) -> "ActuationRecord":
        return cls(
            decision_id=int(doc["decision_id"]),
            spec_version=int(doc["spec_version"]),
            verb=str(doc["verb"]),
            from_k=int(doc["from_k"]),
            to_k=int(doc["to_k"]),
            status=str(doc.get("status", STATUS_PENDING)),
        )

    def satisfied_by(self, observed_k: int) -> bool:
        """Does the OBSERVED shard count show this actuation already
        took effect? (0 = pipeline absent.) The successor's
        crash-after-actuation test."""
        return observed_k == self.to_k


@dataclass
class ActuationJournal:
    """One pipeline's persisted actuation history (bounded) + the id
    counter. Rewritten whole per transition; the StateStore surface
    keeps `next_id` monotonic across coordinators."""

    next_id: int = 1
    entries: list = field(default_factory=list)
    max_entries: int = 32

    def pending(self) -> "ActuationRecord | None":
        for rec in reversed(self.entries):
            if rec.status == STATUS_PENDING:
                return rec
        return None

    def open(self, *, verb: str, from_k: int, to_k: int,
             spec_version: int) -> ActuationRecord:
        rec = ActuationRecord(
            decision_id=self.next_id, spec_version=spec_version,
            verb=verb, from_k=from_k, to_k=to_k)
        self.next_id += 1
        self.entries.append(rec)
        if len(self.entries) > self.max_entries:
            del self.entries[:len(self.entries) - self.max_entries]
        return rec

    def settle(self, decision_id: int, status: str) -> None:
        self.entries = [
            replace(r, status=status) if r.decision_id == decision_id
            else r for r in self.entries]

    def applied(self) -> "list[ActuationRecord]":
        return [r for r in self.entries if r.status == STATUS_APPLIED]

    def to_json(self) -> dict:
        return {"next_id": self.next_id,
                "max_entries": self.max_entries,
                "entries": [r.to_json() for r in self.entries]}

    @classmethod
    def from_json(cls, doc: "dict | None") -> "ActuationJournal":
        if doc is None:
            return cls()
        j = cls(next_id=int(doc.get("next_id", 1)),
                max_entries=int(doc.get("max_entries", 32)))
        j.entries = [ActuationRecord.from_json(r)
                     for r in doc.get("entries", [])]
        return j
