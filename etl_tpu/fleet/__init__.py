"""etl-fleet: declarative reconciliation of hundreds of pipelines.

One coordinator, one desired-state document, level-triggered
convergence (docs/fleet.md). The package splits along the control-loop
seam the rest of the repo already uses:

  spec.py        desired state — FleetSpec / PipelineSpec / TenantQuota,
                 versioned, persisted on the StateStore fleet surface;
  journal.py     per-pipeline persist-then-actuate records (the
                 autoscale-journal pattern generalized to fleet verbs);
  reconciler.py  observe → place (quota clamp) → diff (pure) →
                 converge, plus crash resume;
  runtime.py     the actuation seam (Orchestrator-backed production
                 runtime);
  sim.py         the 100-pipeline in-process fleet for chaos + bench;
  bus.py         the shared signal bus: admission / PID lag-target /
                 adaptive ack-depth policies as plugins.
"""

from .bus import (AckDepthConfig, AdaptiveAckDepthPolicy,
                  AdmissionWeightConfig, AdmissionWeightPolicy,
                  FleetPolicyPlugin, FleetSignalBus, PidConfig,
                  PidLagPolicy, PidState)
from .journal import (STATUS_ABORTED, STATUS_APPLIED, STATUS_PENDING,
                      VERB_CREATE, VERB_DELETE, VERB_RESIZE,
                      ActuationJournal, ActuationRecord)
from .reconciler import (FleetAction, FleetReconciler, ReconcileResult,
                         diff_fleet, place_fleet)
from .runtime import FleetRuntime, OrchestratorFleetRuntime
from .sim import (REDELIVERY_WINDOW, SimulatedFleetRuntime,
                  SimulatedPipeline, seeded_fleet_spec)
from .spec import (MAX_SHARDS_PER_PIPELINE, FleetSpec, PipelineSpec,
                   TenantQuota)

__all__ = [
    "AckDepthConfig",
    "ActuationJournal",
    "ActuationRecord",
    "AdaptiveAckDepthPolicy",
    "AdmissionWeightConfig",
    "AdmissionWeightPolicy",
    "FleetAction",
    "FleetPolicyPlugin",
    "FleetReconciler",
    "FleetRuntime",
    "FleetSignalBus",
    "FleetSpec",
    "MAX_SHARDS_PER_PIPELINE",
    "OrchestratorFleetRuntime",
    "PidConfig",
    "PidLagPolicy",
    "PidState",
    "PipelineSpec",
    "REDELIVERY_WINDOW",
    "ReconcileResult",
    "STATUS_ABORTED",
    "STATUS_APPLIED",
    "STATUS_PENDING",
    "SimulatedFleetRuntime",
    "SimulatedPipeline",
    "TenantQuota",
    "VERB_CREATE",
    "VERB_DELETE",
    "VERB_RESIZE",
    "diff_fleet",
    "place_fleet",
    "seeded_fleet_spec",
]
