"""One signal bus, three control loops as policy plugins.

Before the fleet, the pipeline's three feedback loops each owned a
private sampling path: autoscale read a SignalTimeline, admission read
lag gauges inside the scheduler, and the ack-window depth was a static
config knob. The bus unifies them behind the autoscale split —
sample (I/O) → decide (pure, `@control_loop`) → apply (actuation) —
so every loop consumes the SAME per-pipeline `SignalFrame` history and
every decision is a replayable function of it.

A plugin is three methods:

  sample(pipeline_id, frame)          I/O allowed — pull whatever extra
                                      evidence the decision needs (e.g.
                                      the ack-latency histogram);
  decide(pipeline_id, frames, obs,    PURE — `@control_loop`, no I/O,
         state) -> (action, state')   no clock (etl-lint rule 16);
                                      action None = hold;
  apply(pipeline_id, action)          actuation — drive the knob.

Shipping plugins (the PR-12/13 carried leftovers land here):

  PidLagPolicy           PID on (aggregate lag − target): recommends a
                         target shard count per pipeline. The fleet
                         reconciler consumes recommendations as spec
                         resize suggestions — the PID never actuates
                         the orchestrator itself.
  AdaptiveAckDepthPolicy write-window depth from the MEASURED ack
                         latency (the etl_destination_ack_latency
                         histogram): depth ≈ mean_ack_latency /
                         flush_interval, clamped — deep enough to hide
                         the measured latency, no deeper. Applies via
                         `AckWindow.set_limit`.
  AdmissionWeightPolicy  per-tenant SLO weight = the spec quota's base
                         weight, boosted while the tenant's pipelines
                         hold backlog above a threshold — fed into
                         `AdmissionScheduler.set_slo_weight`.
"""

from __future__ import annotations

import abc
import math
from dataclasses import dataclass

from ..analysis.annotations import control_loop
from ..autoscale.signals import SignalFrame, SignalTimeline
from .spec import MAX_SHARDS_PER_PIPELINE, FleetSpec


class FleetPolicyPlugin(abc.ABC):
    """The bus's plugin contract (module docstring). `decide` MUST be
    pure — decorate it `@control_loop`; sampling and actuation live in
    the other two phases."""

    name: str = "plugin"

    def sample(self, pipeline_id: int, frame: SignalFrame):
        """Optional extra evidence (I/O allowed). Default: nothing."""
        return None

    @abc.abstractmethod
    def decide(self, pipeline_id: int, frames: "tuple[SignalFrame, ...]",
               observation, state):
        """Pure decision: (action | None, new_state)."""

    def apply(self, pipeline_id: int, action) -> None:
        """Actuate. Default: recommendations-only plugins do nothing."""


class FleetSignalBus:
    """Per-pipeline frame fan-out to every registered plugin.

    `publish` records one pipeline's frame (tick-monotonic, same
    contract as the autoscale timeline); `step` runs every plugin over
    every pipeline that has history, threading per-(plugin, pipeline)
    decision state across calls. Returns the actions taken — the chaos
    scenario and tests assert on the trace."""

    def __init__(self, *, max_frames: int = 32):
        self._timelines: "dict[int, SignalTimeline]" = {}
        self._max_frames = max_frames
        self._plugins: "list[FleetPolicyPlugin]" = []
        self._state: "dict[tuple[str, int], object]" = {}
        self._spec = FleetSpec()

    def register(self, plugin: FleetPolicyPlugin) -> None:
        self._plugins.append(plugin)

    def bind_spec(self, spec: FleetSpec) -> None:
        """Give tenancy-aware plugins the current desired state (tenant
        of each pipeline, quota base weights)."""
        self._spec = spec

    @property
    def spec(self) -> FleetSpec:
        return self._spec

    def tenant_of(self, pipeline_id: int) -> "str | None":
        p = self._spec.by_id().get(pipeline_id)
        return p.tenant_id if p is not None else None

    def publish(self, pipeline_id: int, frame: SignalFrame) -> None:
        timeline = self._timelines.get(pipeline_id)
        if timeline is None:
            timeline = SignalTimeline(max_frames=self._max_frames)
            self._timelines[pipeline_id] = timeline
        timeline.record(frame)

    def drop(self, pipeline_id: int) -> None:
        """Forget a deleted pipeline's history and plugin state."""
        self._timelines.pop(pipeline_id, None)
        for plugin in self._plugins:
            self._state.pop((plugin.name, pipeline_id), None)

    def step(self) -> "list[dict]":
        actions: "list[dict]" = []
        for pipeline_id in sorted(self._timelines):
            frames = tuple(self._timelines[pipeline_id].frames)
            if not frames:
                continue
            latest = frames[-1]
            for plugin in self._plugins:
                key = (plugin.name, pipeline_id)
                observation = plugin.sample(pipeline_id, latest)
                action, new_state = plugin.decide(
                    pipeline_id, frames, observation,
                    self._state.get(key))
                self._state[key] = new_state
                if action is None:
                    continue
                plugin.apply(pipeline_id, action)
                actions.append({"plugin": plugin.name,
                                "pipeline_id": pipeline_id,
                                "action": action})
        return actions


# -- PID lag-target policy (carried from the autoscale roadmap) --------------


@dataclass(frozen=True)
class PidConfig:
    """PID gains over the lag error in BYTES, output in shards.
    Defaults are deliberately conservative: kp sized so ~64 MiB of
    sustained excess lag asks for one extra shard, ki an order of
    magnitude softer (wind-up clamped), kd damping tick-to-tick spikes."""

    target_lag_bytes: int = 8 * 1024 * 1024
    kp: float = 1.0 / (64 * 1024 * 1024)
    ki: float = 1.0 / (640 * 1024 * 1024)
    kd: float = 0.0
    integral_clamp: float = 4.0  # |ki * integral| ceiling, in shards
    min_shards: int = 1
    max_shards: int = MAX_SHARDS_PER_PIPELINE


@dataclass(frozen=True)
class PidState:
    integral: float = 0.0
    prev_error: float = 0.0


class PidLagPolicy(FleetPolicyPlugin):
    """PID-style lag-target controller: recommends `target_k` per
    pipeline. Deliberately recommendation-only — resize authority stays
    with the spec + reconciler (a PID that actuated directly would
    bypass quotas and the actuation journal)."""

    name = "pid_lag"

    def __init__(self, config: "PidConfig | None" = None):
        self.config = config or PidConfig()
        self.recommendations: "dict[int, int]" = {}

    @control_loop
    def decide(self, pipeline_id, frames, observation, state):
        cfg = self.config
        state = state or PidState()
        frame = frames[-1]
        current_k = max(1, frame.shard_count)
        error = float(frame.aggregate_backlog_bytes
                      - cfg.target_lag_bytes)
        integral = state.integral + error
        if cfg.ki > 0:  # anti-windup: clamp the integral TERM
            bound = cfg.integral_clamp / cfg.ki
            integral = max(-bound, min(bound, integral))
        derivative = error - state.prev_error
        effort = (cfg.kp * error + cfg.ki * integral
                  + cfg.kd * derivative)
        target = max(cfg.min_shards,
                     min(cfg.max_shards,
                         current_k + int(round(effort))))
        new_state = PidState(integral=integral, prev_error=error)
        if target == current_k:
            return None, new_state
        return {"target_k": target, "from_k": current_k}, new_state

    def apply(self, pipeline_id: int, action) -> None:
        self.recommendations[pipeline_id] = action["target_k"]


# -- adaptive ack-window depth (carried from the ack-window roadmap) ---------


@dataclass(frozen=True)
class AckDepthConfig:
    """Depth = ceil(mean_ack_latency / flush_interval) + 1: just enough
    in-flight writes to cover the measured destination round-trip at the
    apply loop's flush cadence. `min_samples` gates flapping on a cold
    histogram; a change smaller than one step is held."""

    flush_interval_s: float = 0.05
    min_depth: int = 1
    max_depth: int = 64
    min_samples: int = 8


class AdaptiveAckDepthPolicy(FleetPolicyPlugin):
    """Write-window depth from the measured ack-latency histogram.

    `window_of(pipeline_id)` must return the live AckWindow (or None) —
    the fleet wires the registry lookup in; tests pass a dict. Sampling
    reads (count, sum) from the shared telemetry registry's
    `etl_destination_ack_latency_seconds` histogram."""

    name = "ack_depth"

    def __init__(self, *, window_of, config: "AckDepthConfig | None" = None,
                 histogram_read=None):
        self.config = config or AckDepthConfig()
        self._window_of = window_of
        self._histogram_read = histogram_read  # () -> (count, sum) | None
        self.applied_depths: "dict[int, int]" = {}

    def sample(self, pipeline_id: int, frame: SignalFrame):
        if self._histogram_read is not None:
            return self._histogram_read()
        from ..telemetry.metrics import (
            ETL_DESTINATION_ACK_LATENCY_SECONDS, registry)

        return registry.get_histogram(ETL_DESTINATION_ACK_LATENCY_SECONDS,
                                      labels={"path": "apply"})

    @control_loop
    def decide(self, pipeline_id, frames, observation, state):
        cfg = self.config
        if not observation:
            return None, state
        count, total_s = observation
        if count < cfg.min_samples:
            return None, state
        mean_latency_s = total_s / count
        # the epsilon absorbs binary-float fenceposts: a mean that is
        # exactly N flush intervals must yield N, not ceil(N + 1e-16)
        depth = int(math.ceil(
            mean_latency_s / cfg.flush_interval_s - 1e-9)) + 1
        depth = max(cfg.min_depth, min(cfg.max_depth, depth))
        if state == depth:  # state IS the last applied depth
            return None, state
        return {"depth": depth, "mean_latency_s": mean_latency_s}, depth

    def apply(self, pipeline_id: int, action) -> None:
        self.applied_depths[pipeline_id] = action["depth"]
        window = self._window_of(pipeline_id)
        if window is not None:
            window.set_limit(action["depth"])


# -- admission SLO weights from quotas + live lag ----------------------------


@dataclass(frozen=True)
class AdmissionWeightConfig:
    """Boost a tenant's admission weight while its pipeline holds more
    than `boost_lag_bytes` of backlog — the scheduler clamps to its own
    max_weight envelope, so the boost can never starve other tenants."""

    boost_lag_bytes: int = 64 * 1024 * 1024
    boost: float = 2.0


class AdmissionWeightPolicy(FleetPolicyPlugin):
    """Feeds per-tenant SLO weights into the shared AdmissionScheduler:
    base weight from the fleet spec's TenantQuota, times the lag boost
    while the pipeline is behind."""

    name = "admission_weight"

    def __init__(self, bus: FleetSignalBus, *, scheduler=None,
                 config: "AdmissionWeightConfig | None" = None):
        self._bus = bus
        self._scheduler = scheduler
        self.config = config or AdmissionWeightConfig()
        self.applied_weights: "dict[str, float]" = {}

    def sample(self, pipeline_id: int, frame: SignalFrame):
        tenant = self._bus.tenant_of(pipeline_id)
        if tenant is None:
            return None
        quota = self._bus.spec.quotas.get(tenant)
        base = quota.slo_weight if quota is not None else 1.0
        return {"tenant": tenant, "base_weight": base}

    @control_loop
    def decide(self, pipeline_id, frames, observation, state):
        if observation is None:
            return None, state
        cfg = self.config
        frame = frames[-1]
        weight = observation["base_weight"]
        if frame.aggregate_backlog_bytes > cfg.boost_lag_bytes:
            weight *= cfg.boost
        if state is not None and abs(state - weight) < 1e-9:
            return None, state  # state IS the last applied weight
        return {"tenant": observation["tenant"], "weight": weight}, weight

    def apply(self, pipeline_id: int, action) -> None:
        scheduler = self._scheduler
        if scheduler is None:
            from ..ops.pipeline import global_admission

            scheduler = global_admission()
        scheduler.set_slo_weight(action["tenant"], action["weight"])
        self.applied_weights[action["tenant"]] = action["weight"]
