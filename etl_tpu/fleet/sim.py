"""The 100-pipeline simulated fleet: what the chaos scenario and the
bench converge gate reconcile against.

`SimulatedFleetRuntime` implements the FleetRuntime verbs over
in-process state — no subprocesses, no sockets — so a hundred
pipelines cost a hundred dataclasses and the whole
reconcile/kill/resume story runs in milliseconds, deterministic per
seed. What it faithfully models is exactly what the reconciler's
correctness depends on:

  - idempotent verbs (create at the current K, resize to the current K,
    delete of an absent pipeline: state no-ops);
  - an ACTUATION LOG: every runtime call is appended. The chaos
    invariant "zero double-actuations" is `len(log) == total APPLIED
    journal records` — a settle-mode resume adds no call, a re-driven
    resume adds exactly the one the dead coordinator never made;
  - crash windows: optional async `pre_actuate`/`post_actuate` hooks
    awaited around the state mutation. The chaos scenario parks a
    chosen pipeline's hook on an Event and cancels the coordinator
    task there — cancel in pre = crash-BEFORE-actuation (journal
    pending, fleet unchanged), cancel in post = crash-AFTER (fleet
    changed, settle never written);
  - per-pipeline delivery ledgers: each pipeline carries a seeded
    committed-row ledger drawn from its tenancy profile, delivered on
    create; a resize ROLL re-delivers a bounded tail window (the
    restart-overlap dup model every chaos scenario uses). Invariants:
    delivered keys == committed keys (zero loss) and max dup count ≤
    1 + rolls (bounded duplication).

`seeded_fleet_spec` builds the canonical N-pipeline desired state:
tenants are workload profiles (the tenancy-profile story — one tenant
per traffic shape), shard counts and quotas drawn per seed.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from ..workloads import profile_names
from .runtime import FleetRuntime
from .spec import FleetSpec, PipelineSpec, TenantQuota

#: resize re-delivery window: a roll re-sends at most this many of the
#: ledger's newest rows (the in-flight-at-kill overlap every restart
#: scenario budgets for)
REDELIVERY_WINDOW = 16


@dataclass
class SimulatedPipeline:
    """One fleet member's in-process stand-in."""

    pipeline_id: int
    tenant_id: str
    profile: str
    shard_count: int
    committed: "list[str]" = field(default_factory=list)
    delivered: "dict[str, int]" = field(default_factory=dict)
    rolls: int = 0

    def deliver_all(self) -> None:
        for key in self.committed:
            self.delivered[key] = self.delivered.get(key, 0) + 1

    def redeliver_tail(self) -> None:
        for key in self.committed[-REDELIVERY_WINDOW:]:
            self.delivered[key] = self.delivered.get(key, 0) + 1

    def violations(self) -> "list[str]":
        out: "list[str]" = []
        missing = set(self.committed) - set(self.delivered)
        if missing:
            out.append(f"pipeline {self.pipeline_id}: "
                       f"{len(missing)} committed rows never delivered")
        extra = set(self.delivered) - set(self.committed)
        if extra:
            out.append(f"pipeline {self.pipeline_id}: "
                       f"{len(extra)} delivered rows never committed")
        if self.delivered:
            worst = max(self.delivered.values())
            if worst > 1 + self.rolls:
                out.append(
                    f"pipeline {self.pipeline_id}: max dup count {worst} "
                    f"exceeds 1 + {self.rolls} rolls")
        return out


def _ledger(seed: int, spec: PipelineSpec) -> "list[str]":
    """The pipeline's seeded committed-row ledger: size drawn from the
    tenancy profile's name hash so different traffic shapes get
    different (but per-seed stable) volumes."""
    rng = random.Random((seed << 20) ^ (spec.pipeline_id * 2654435761))
    base = 24 + (sum(spec.profile.encode()) % 5) * 12
    n = rng.randint(base, base + 24)
    return [f"{spec.profile}:{spec.pipeline_id}:{i}" for i in range(n)]


class SimulatedFleetRuntime(FleetRuntime):
    """In-process fleet (module docstring)."""

    def __init__(self, *, seed: int = 0):
        self.seed = seed
        self.pipelines: "dict[int, SimulatedPipeline]" = {}
        self.retired: "dict[int, SimulatedPipeline]" = {}
        self.actuation_log: "list[dict]" = []
        # chaos crash windows: async (verb, pipeline_id) -> None
        self.pre_actuate = None
        self.post_actuate = None

    async def _hooks(self, which, verb: str, pipeline_id: int) -> None:
        if which is not None:
            await which(verb, pipeline_id)

    async def list_pipelines(self) -> "dict[int, int]":
        return {pid: p.shard_count
                for pid, p in sorted(self.pipelines.items())}

    async def create_pipeline(self, spec: PipelineSpec) -> None:
        await self._hooks(self.pre_actuate, "create", spec.pipeline_id)
        self.actuation_log.append(
            {"verb": "create", "pipeline_id": spec.pipeline_id,
             "to_k": spec.shard_count})
        existing = self.pipelines.get(spec.pipeline_id)
        if existing is None:
            p = SimulatedPipeline(
                pipeline_id=spec.pipeline_id, tenant_id=spec.tenant_id,
                profile=spec.profile, shard_count=spec.shard_count,
                committed=_ledger(self.seed, spec))
            p.deliver_all()
            self.pipelines[spec.pipeline_id] = p
        elif existing.shard_count != spec.shard_count:
            existing.shard_count = spec.shard_count  # idempotent re-apply
        await self._hooks(self.post_actuate, "create", spec.pipeline_id)

    async def resize_pipeline(self, spec: PipelineSpec) -> None:
        await self._hooks(self.pre_actuate, "resize", spec.pipeline_id)
        self.actuation_log.append(
            {"verb": "resize", "pipeline_id": spec.pipeline_id,
             "to_k": spec.shard_count})
        p = self.pipelines.get(spec.pipeline_id)
        if p is not None and p.shard_count != spec.shard_count:
            # a roll: every pod restarts — the bounded-overlap dup model
            p.shard_count = spec.shard_count
            p.rolls += 1
            p.redeliver_tail()
        await self._hooks(self.post_actuate, "resize", spec.pipeline_id)

    async def delete_pipeline(self, pipeline_id: int) -> None:
        await self._hooks(self.pre_actuate, "delete", pipeline_id)
        self.actuation_log.append(
            {"verb": "delete", "pipeline_id": pipeline_id, "to_k": 0})
        p = self.pipelines.pop(pipeline_id, None)
        if p is not None:
            self.retired[pipeline_id] = p
        await self._hooks(self.post_actuate, "delete", pipeline_id)

    # -- invariants ----------------------------------------------------------

    def violations(self) -> "list[str]":
        out: "list[str]" = []
        for pid in sorted(self.pipelines):
            out.extend(self.pipelines[pid].violations())
        return out

    def describe(self) -> dict:
        return {
            "pipelines": len(self.pipelines),
            "shards": sum(p.shard_count
                          for p in self.pipelines.values()),
            "actuations": len(self.actuation_log),
            "retired": len(self.retired),
        }


def seeded_fleet_spec(seed: int, n_pipelines: int,
                      spec_version: int = 1) -> FleetSpec:
    """The canonical simulated fleet: `n_pipelines` pipelines spread
    over one tenant per workload profile (the tenancy-profile mix),
    shard counts 1..4 per seed, and quotas that BITE for two tenants
    (placement must visibly clamp them) plus SLO weights that differ."""
    rng = random.Random(seed)
    profiles = profile_names()
    pipelines = []
    for pid in range(1, n_pipelines + 1):
        profile = profiles[(pid - 1) % len(profiles)]
        pipelines.append(PipelineSpec(
            pipeline_id=pid,
            tenant_id=f"tenant-{profile}",
            shard_count=rng.randint(1, 4),
            destination="memory",
            profile=profile,
        ))
    quotas = {
        # the clamped tenants: fewer aggregate shards than asked
        f"tenant-{profiles[0]}": TenantQuota(max_shards=max(
            2, n_pipelines // len(profiles)), slo_weight=2.0),
        f"tenant-{profiles[1]}": TenantQuota(max_shards=max(
            2, n_pipelines // len(profiles)), slo_weight=0.5),
        # an unlimited tenant with a loud SLO weight
        f"tenant-{profiles[2]}": TenantQuota(max_shards=0,
                                             slo_weight=4.0),
    }
    spec = FleetSpec(spec_version=spec_version,
                     pipelines=tuple(pipelines), quotas=quotas)
    spec.validate()
    return spec
