"""Typed pipeline configuration.

Reference parity: `PipelineConfig`, `BatchConfig`, `MemoryBackpressureConfig`,
`PgConnectionConfig`, `TableSyncCopyConfig`, retry configs
(crates/etl-config/src/shared/pipeline.rs:11,185,239; connection.rs).
Defaults mirror the reference's tuning constants (BASELINE.md):
batch 8 MiB / 10 s fill / memory ratio 0.2; backpressure 0.85/0.75;
copy 4 partitions-per-connection / 250k rows / ≤1024 partitions.

TPU-first addition: `batch_engine` selects the decode path ("cpu" oracle or
"tpu" device engine) at the BatchConfig boundary, per the north star.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..models.errors import ErrorKind, EtlError


def _require(cond: bool, what: str) -> None:
    if not cond:
        raise EtlError(ErrorKind.CONFIG_INVALID, what)


class InvalidatedSlotBehavior(enum.Enum):
    """What to do when the replication slot was invalidated by the source
    (reference apply/worker.rs:476-527)."""

    ERROR = "error"
    RECREATE_AND_RESYNC = "recreate_and_resync"


class BatchEngine(enum.Enum):
    CPU = "cpu"
    TPU = "tpu"


@dataclass(frozen=True)
class TlsConfig:
    enabled: bool = False
    trusted_root_certs: str = ""


@dataclass(frozen=True)
class PgConnectionConfig:
    host: str = "localhost"
    port: int = 5432
    name: str = "postgres"  # database name
    username: str = "postgres"
    password: str | None = None
    tls: TlsConfig = field(default_factory=TlsConfig)
    keepalive_idle_s: int = 60
    connect_timeout_s: int = 30

    def validate(self) -> None:
        _require(1 <= self.port <= 65535, f"port out of range: {self.port}")
        _require(bool(self.host), "host must be non-empty")


@dataclass(frozen=True)
class BatchConfig:
    """Flush sizing (reference pipeline.rs:52-68)."""

    max_size_bytes: int = 8 * 1024 * 1024
    max_fill_ms: int = 10_000
    batch_engine: BatchEngine = BatchEngine.TPU
    # bounded in-flight window of the decode pipeline (ops/pipeline.py):
    # batches packed/dispatched but not yet fetched. 3 ≈ one packing, one
    # on the device, one streaming back; drops to 1 under memory pressure
    decode_window: int = 3
    # bounded destination-ack write window (runtime/ack_window.py): the
    # apply loop keeps dispatching flushes in WAL order while up to this
    # many earlier acks are still pending, advancing durable progress
    # only over the contiguous acked prefix. 4 hides one ack round-trip
    # behind three later writes on real destinations; 1 reproduces the
    # reference's one-in-flight loop exactly. Shrinks to 1 under memory
    # pressure. The copy path caps its per-partition outstanding acks
    # with the same knob.
    write_window: int = 4
    # byte cap on the window's pending payloads (0 = unbounded): mega
    # batches under backlog growth stop stacking K × 128 MiB of
    # in-flight payload; an empty window always admits one dispatch, so
    # a single over-budget batch can never deadlock
    write_window_max_bytes: int = 64 * 1024 * 1024
    # shared-capacity cap of the fair batch-admission scheduler
    # (ops/pipeline.AdmissionScheduler): maximum device/host batches in
    # flight across EVERY pipeline sharing this process's device set.
    # 0 = auto (max(4, 2 × device count)); the FIRST pipeline to start
    # fixes the process-wide value. Drops to 1 under memory pressure.
    admission_capacity: int = 0
    # AOT program cache directory (ops/program_store.py): compiled decode
    # executables persist here, keyed by canonical layout + backend +
    # versions, so a restarted replicator LOADS its programs instead of
    # re-paying the XLA builds. None = in-memory only (also honors
    # $ETL_TPU_PROGRAM_CACHE_DIR). The store is PROCESS-global, like the
    # admission scheduler's capacity: the first pipeline to configure a
    # dir fixes it for every pipeline in the process (a later pipeline
    # naming a different dir is ignored with a warning; one naming None
    # shares the configured store). Safe to share across pods on
    # identical images/machine types — see the OPERATIONS.md runbook.
    program_cache_dir: str | None = None
    # warm stored table schemas' canonical host programs at
    # Pipeline.start, before the apply loop sees traffic. None = auto
    # (prewarm iff a program cache dir is configured — without one a
    # fresh process has nothing to load and the nonblocking background
    # compiles cover first-touch); the row buckets default to
    # program_store.PREWARM_ROW_BUCKETS.
    prewarm_programs: bool | None = None
    prewarm_row_buckets: tuple | None = None
    # device-resident wire encoding (ops/egress.py): when True and the
    # destination declares an egress encoder, decode programs gain a
    # second fused stage that renders int/bool/temporal field TEXT on
    # device, and decoded batches arrive with wire-ready byte buffers
    # the destination splices instead of re-rendering host-side. Purely
    # a fast path: batches without buffers (cold program, unsupported
    # layout, filtered batches) encode host-side byte-identically.
    device_egress: bool = True

    def validate(self) -> None:
        _require(self.max_size_bytes > 0, "max_size_bytes must be > 0")
        _require(self.max_fill_ms > 0, "max_fill_ms must be > 0")
        _require(self.decode_window >= 1, "decode_window must be >= 1")
        _require(self.write_window >= 1, "write_window must be >= 1")
        _require(self.write_window_max_bytes >= 0,
                 "write_window_max_bytes must be >= 0 (0 = unbounded)")
        _require(self.admission_capacity >= 0,
                 "admission_capacity must be >= 0 (0 = auto)")
        _require(all(b > 0 for b in self.prewarm_row_buckets or ()),
                 "prewarm_row_buckets must be positive row capacities")


@dataclass(frozen=True)
class MemoryBackpressureConfig:
    """RSS hysteresis thresholds (reference pipeline.rs:199-201)."""

    activate_ratio: float = 0.85
    resume_ratio: float = 0.75
    refresh_interval_ms: int = 100
    memory_ratio: float = 0.2  # share of memory for batch budgets

    def validate(self) -> None:
        _require(0 < self.resume_ratio < self.activate_ratio <= 1.0,
                 "need 0 < resume < activate <= 1")
        _require(self.refresh_interval_ms > 0, "refresh interval must be > 0")


@dataclass(frozen=True)
class TableSyncCopyConfig:
    """CTID-partitioned parallel copy planning (reference copy.rs:54-58)."""

    max_connections: int = 4
    partitions_per_connection: int = 4
    rows_per_partition_target: int = 250_000
    max_partitions: int = 1024

    def validate(self) -> None:
        _require(self.max_connections >= 1, "need >= 1 copy connection")
        _require(self.max_partitions >= 1, "need >= 1 partition")


@dataclass(frozen=True)
class SupervisionConfig:
    """Liveness supervision (etl_tpu/supervision): heartbeat deadlines,
    escalation pacing, breaker thresholds. A component HANGS when its
    heartbeat goes stale past `hang_deadline_s`; it STALLS when it keeps
    beating with work in flight but its progress token freezes past
    `stall_deadline_s`. Deadlines must comfortably exceed the apply
    loop's keepalive pacing (60% of wal_sender_timeout) — an idle loop
    beats only once per select timeout."""

    enabled: bool = True
    check_interval_s: float = 1.0
    stall_deadline_s: float = 60.0
    hang_deadline_s: float = 120.0
    # minimum spacing between cancel-and-restart escalations of the same
    # component (the restarted worker also rides RetryPolicy backoff)
    restart_backoff_s: float = 5.0
    # device-side decode stalls before the batch engine degrades to the
    # host oracle, and for how long the degrade sticks
    device_degrade_threshold: int = 3
    device_degrade_cooldown_s: float = 60.0
    # destination circuit breaker: consecutive failures to trip OPEN, and
    # the cooldown before a HALF_OPEN trial call is admitted
    breaker_failure_threshold: int = 5
    breaker_cooldown_s: float = 15.0

    def validate(self) -> None:
        _require(self.check_interval_s > 0, "check_interval_s must be > 0")
        _require(self.stall_deadline_s > 0, "stall_deadline_s must be > 0")
        _require(self.hang_deadline_s > 0, "hang_deadline_s must be > 0")
        _require(self.breaker_failure_threshold >= 1,
                 "breaker_failure_threshold must be >= 1")
        _require(self.breaker_cooldown_s > 0, "breaker_cooldown_s must be > 0")
        _require(self.device_degrade_threshold >= 1,
                 "device_degrade_threshold must be >= 1")


@dataclass(frozen=True)
class PoisonConfig:
    """Poison-pill isolation (runtime/poison.py, docs/dead-letter.md).

    When a CDC flush fails with a PERMANENT destination error
    (models.errors.POISON_KINDS — the payload is refused, the
    destination is healthy), the apply loop bisects the failing batch
    down to the poison row(s), delivers the healthy complement in WAL
    order, and parks the poison rows on the durable dead-letter surface
    instead of dying. Tables that exceed `budget_rows` dead-lettered
    rows inside a sliding `window_s` window transition to QUARANTINE:
    their events bypass the destination (parked straight to the DLQ,
    counted) while every other table keeps replicating."""

    enabled: bool = True
    # dead-lettered rows per table per window before the table
    # quarantines (also the bisection work bound: once tripped, the
    # remaining rows of that table park without further probe writes)
    budget_rows: int = 8
    window_s: float = 300.0
    # truncate the stored error detail per entry (payloads are bounded
    # by the flush sizing already)
    max_detail_chars: int = 500
    # how often the flush path re-reads the store's quarantine records
    # so an operator `unquarantine` (another process) takes effect
    # WITHOUT a worker restart; 0 disables the poll (restart-only
    # adoption, the pre-live behavior)
    quarantine_poll_s: float = 30.0
    # age past which replayed/discarded dead-letter rows are eligible
    # for `python -m etl_tpu.dlq compact` (rows still `dead` are never
    # expired — they are the zero-loss ledger)
    dlq_retention_s: float = 7 * 24 * 3600.0

    def validate(self) -> None:
        _require(self.budget_rows >= 1, "poison budget_rows must be >= 1")
        _require(self.window_s > 0, "poison window_s must be > 0")
        _require(self.quarantine_poll_s >= 0,
                 "poison quarantine_poll_s must be >= 0")
        _require(self.dlq_retention_s > 0,
                 "poison dlq_retention_s must be > 0")


@dataclass(frozen=True)
class RetryConfig:
    max_attempts: int = 5
    initial_delay_ms: int = 1_000
    max_delay_ms: int = 60_000
    backoff_factor: float = 2.0

    def delay_ms(self, attempt: int) -> int:
        d = self.initial_delay_ms * (self.backoff_factor ** attempt)
        return int(min(d, self.max_delay_ms))


@dataclass(frozen=True)
class PipelineConfig:
    pipeline_id: int
    publication_name: str
    pg_connection: PgConnectionConfig = field(default_factory=PgConnectionConfig)
    batch: BatchConfig = field(default_factory=BatchConfig)
    backpressure: MemoryBackpressureConfig = field(
        default_factory=MemoryBackpressureConfig)
    table_sync_copy: TableSyncCopyConfig = field(
        default_factory=TableSyncCopyConfig)
    apply_retry: RetryConfig = field(default_factory=RetryConfig)
    table_retry: RetryConfig = field(default_factory=RetryConfig)
    supervision: SupervisionConfig = field(default_factory=SupervisionConfig)
    poison: PoisonConfig = field(default_factory=PoisonConfig)
    # every Destination.startup/write/flush await is bounded by this (a
    # destination that never returns surfaces as EtlError(TIMEOUT), not
    # an eternal await); 0 disables the bound
    destination_op_timeout_s: float = 60.0
    max_table_sync_workers: int = 4
    invalidated_slot_behavior: InvalidatedSlotBehavior = \
        InvalidatedSlotBehavior.ERROR
    run_source_migrations: bool = True
    wal_sender_timeout_ms: int = 60_000
    # background schema-version pruning cadence (reference hourly task,
    # apply.rs:123,423-631); 0 disables
    schema_cleanup_interval_s: float = 3600.0
    # out-of-band lag sampler cadence (reference apply.rs:579-624 polling
    # pg_current_wal_lsn on a lazy side connection); 0 disables
    lag_sample_interval_s: float = 10.0
    # horizontal scale-out (etl_tpu/sharding, docs/sharding.md): this
    # pod's shard index within a K-way split of the publication. None =
    # unsharded (the pod owns every published table, slot names carry no
    # suffix). A sharded pod filters publication tables by ShardMap
    # membership, replicates through `_s{shard}`-suffixed slots, and
    # fences its store writes against the authoritative epoch.
    shard: int | None = None
    shard_count: int = 1

    def validate(self) -> None:
        _require(self.pipeline_id >= 0, "pipeline_id must be >= 0")
        _require(bool(self.publication_name), "publication_name required")
        _require(self.shard_count >= 1, "shard_count must be >= 1")
        if self.shard is not None:
            _require(0 <= self.shard < self.shard_count,
                     f"shard must be in [0, {self.shard_count}), "
                     f"got {self.shard}")
        else:
            _require(self.shard_count == 1,
                     "shard_count > 1 requires a shard index (every pod "
                     "of a sharded deployment must know which slice it "
                     "owns)")
        _require(self.max_table_sync_workers >= 1,
                 "need >= 1 table sync worker")
        _require(self.destination_op_timeout_s >= 0,
                 "destination_op_timeout_s must be >= 0")
        self.pg_connection.validate()
        self.batch.validate()
        self.backpressure.validate()
        self.table_sync_copy.validate()
        self.supervision.validate()
        self.poison.validate()

    @property
    def keepalive_deadline_ms(self) -> int:
        """60% of wal_sender_timeout, floored at 100ms (reference
        apply.rs:94-116)."""
        return max(100, int(self.wal_sender_timeout_ms * 0.6))
