"""Config loading: YAML base + environment overlay + APP_ env vars.

Reference parity: crates/etl-config/src/load.rs — a base YAML file plus an
environment-specific overlay (`base.yaml`, `{env}.yaml`), then `APP_`-
prefixed environment variables with `__` as the nesting separator
(`APP_PG_CONNECTION__HOST=db` → pg_connection.host), highest precedence.
`Environment` (dev/staging/prod) from `APP_ENVIRONMENT`.
Secrets are wrapped in `Secret` so accidental logging shows `[REDACTED]`
(reference SerializableSecretString, etl-config/src/secret.rs).
"""

from __future__ import annotations

import enum
import os
from pathlib import Path
from typing import Any

import yaml

from ..models.errors import ErrorKind, EtlError
from .pipeline import (BatchConfig, BatchEngine, InvalidatedSlotBehavior,
                       MemoryBackpressureConfig, PgConnectionConfig,
                       PipelineConfig, RetryConfig, SupervisionConfig,
                       TableSyncCopyConfig, TlsConfig)

ENV_PREFIX = "APP_"
ENV_SEPARATOR = "__"


class Environment(enum.Enum):
    DEV = "dev"
    STAGING = "staging"
    PROD = "prod"

    @classmethod
    def current(cls) -> "Environment":
        raw = os.environ.get(f"{ENV_PREFIX}ENVIRONMENT", "dev").lower()
        try:
            return cls(raw)
        except ValueError:
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           f"unknown environment {raw!r}")


class Secret(str):
    """A string that redacts itself in repr/str contexts used for logging."""

    def __repr__(self) -> str:
        return "Secret('[REDACTED]')"

    def expose(self) -> str:
        return str.__str__(self)


def _deep_merge(base: dict, overlay: dict) -> dict:
    out = dict(base)
    for k, v in overlay.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _coerce(value: str) -> Any:
    low = value.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(value)
    except ValueError:
        pass
    try:
        return float(value)
    except ValueError:
        pass
    return value


def env_overlay(environ: dict[str, str] | None = None) -> dict:
    """APP_A__B=c → {"a": {"b": c}} (reference load.rs env source)."""
    environ = environ if environ is not None else dict(os.environ)
    out: dict = {}
    for key, value in environ.items():
        if not key.startswith(ENV_PREFIX) or key == f"{ENV_PREFIX}ENVIRONMENT":
            continue
        path = key[len(ENV_PREFIX):].lower().split(ENV_SEPARATOR)
        node = out
        for part in path[:-1]:
            nxt = node.setdefault(part, {})
            if not isinstance(nxt, dict):
                raise EtlError(
                    ErrorKind.CONFIG_INVALID,
                    f"conflicting env vars: {key} nests under a scalar "
                    f"prefix {ENV_PREFIX}{part.upper()}")
            node = nxt
        if isinstance(node.get(path[-1]), dict):
            raise EtlError(ErrorKind.CONFIG_INVALID,
                           f"conflicting env vars: {key} is a scalar but "
                           f"nested keys exist under it")
        node[path[-1]] = _coerce(value)
    return out


def load_config_dict(config_dir: str | Path | None = None,
                     environment: Environment | None = None,
                     environ: dict[str, str] | None = None) -> dict:
    environment = environment or Environment.current()
    merged: dict = {}
    if config_dir is not None:
        d = Path(config_dir)
        for name in ("base.yaml", f"{environment.value}.yaml"):
            p = d / name
            if p.exists():
                try:
                    doc = yaml.safe_load(p.read_text()) or {}
                except yaml.YAMLError as e:
                    raise EtlError(ErrorKind.CONFIG_INVALID,
                                   f"{p}: {e}")
                if not isinstance(doc, dict):
                    raise EtlError(ErrorKind.CONFIG_INVALID,
                                   f"{p}: top level must be a mapping")
                merged = _deep_merge(merged, doc)
    merged = _deep_merge(merged, env_overlay(environ))
    return merged


def _build(cls, doc: dict, **converters):
    import dataclasses

    known = {f.name for f in dataclasses.fields(cls)}
    unknown = set(doc) - known
    if unknown:
        raise EtlError(ErrorKind.CONFIG_INVALID,
                       f"{cls.__name__}: unknown keys {sorted(unknown)}")
    kwargs = {}
    for k, v in doc.items():
        conv = converters.get(k)
        kwargs[k] = conv(v) if conv else v
    return cls(**kwargs)


def pipeline_config_from_dict(doc: dict) -> PipelineConfig:
    try:
        cfg = _build(
            PipelineConfig, doc,
            pg_connection=lambda d: _build(
                PgConnectionConfig, d,
                password=lambda s: Secret(s) if s is not None else None,
                tls=lambda t: _build(TlsConfig, t)),
            batch=lambda d: _build(BatchConfig, d,
                                   batch_engine=BatchEngine),
            backpressure=lambda d: _build(MemoryBackpressureConfig, d),
            table_sync_copy=lambda d: _build(TableSyncCopyConfig, d),
            apply_retry=lambda d: _build(RetryConfig, d),
            table_retry=lambda d: _build(RetryConfig, d),
            supervision=lambda d: _build(SupervisionConfig, d),
            invalidated_slot_behavior=InvalidatedSlotBehavior,
        )
    except (TypeError, ValueError) as e:
        raise EtlError(ErrorKind.CONFIG_INVALID, str(e))
    cfg.validate()
    return cfg


def load_pipeline_config(config_dir: str | Path | None = None,
                         environment: Environment | None = None,
                         environ: dict[str, str] | None = None
                         ) -> PipelineConfig:
    return pipeline_config_from_dict(
        load_config_dict(config_dir, environment, environ))
