"""Typed configuration."""

from .pipeline import (BatchConfig, BatchEngine, InvalidatedSlotBehavior,
                       MemoryBackpressureConfig, PgConnectionConfig,
                       PipelineConfig, RetryConfig, SupervisionConfig,
                       TableSyncCopyConfig, TlsConfig)
