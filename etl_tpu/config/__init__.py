"""Typed configuration."""

from .pipeline import (BatchConfig, BatchEngine, InvalidatedSlotBehavior,
                       MemoryBackpressureConfig, PgConnectionConfig,
                       PipelineConfig, PoisonConfig, RetryConfig,
                       SupervisionConfig, TableSyncCopyConfig, TlsConfig)
