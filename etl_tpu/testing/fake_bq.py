"""Validating Storage Write fake for BigQuery destination tests.

A RecordingHttpServer responder that DECODES every `:appendRows` proto
request (etl_tpu.destinations.bq_proto wire format), validates the framing
the way a real Storage Write backend would — rows must decode against the
carried writer schema, CDC pseudo-columns must be present — records the
decoded rows, and plays scripted error responses for the retry tests
(reference test stance: bigquery/test_utils.rs + the fault-injection
cases around client.rs:317-450).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..destinations import bq_proto


@dataclass
class _Scripted:
    response: bytes
    times: int


@dataclass
class StorageWriteFake:
    """Responder for RecordingHttpServer: server.responders.append(fake)."""

    attempts: list[tuple[str, object, list[dict]]] = field(
        default_factory=list)  # every decoded request (incl. failed ones)
    appends: list[tuple[str, object, list[dict]]] = field(
        default_factory=list)  # requests answered with success
    missing_tables: set[str] = field(default_factory=set)
    _scripted: list[_Scripted] = field(default_factory=list)

    # -- scripting -----------------------------------------------------------

    def script_status(self, grpc_code: int, message: str,
                      storage_error_code: int | None = None,
                      times: int = 1) -> None:
        """Next `times` appends answer with this google.rpc.Status error."""
        self._scripted.append(_Scripted(
            bq_proto.encode_append_rows_response(
                error=bq_proto.encode_rpc_status(
                    grpc_code, message, storage_error_code)),
            times))

    def script_row_error(self, index: int, code: int, message: str) -> None:
        self._scripted.append(_Scripted(
            bq_proto.encode_append_rows_response(
                row_errors=[bq_proto.RowError(index, code, message)]), 1))

    # -- assertions ----------------------------------------------------------

    def rows_for(self, table: str) -> list[dict]:
        return [row for t, _, rows in self.appends if t == table
                for row in rows]

    # -- responder -----------------------------------------------------------

    def __call__(self, rec):
        if rec.method == "GET" and "/tables/" in rec.path \
                and not rec.path.endswith(":appendRows"):
            table = rec.path.rsplit("/tables/", 1)[-1].split("/")[0]
            if table in self.missing_tables:
                return (404, {"error": "table not found"})
            return None  # default 200 {} == exists
        if not rec.path.endswith(":appendRows"):
            return None
        table = rec.path.rsplit("/tables/", 1)[-1].split("/")[0]
        req = bq_proto.decode_append_rows_request(rec.body)
        # framing validation: every row decodes against the writer schema
        rows = req.decode_rows()
        names = {name for name, *_ in req.descriptor_fields}
        assert bq_proto.CHANGE_TYPE_FIELD in names \
            and bq_proto.CHANGE_SEQUENCE_FIELD in names, \
            "writer schema missing CDC pseudo-columns"
        for row in rows:
            assert bq_proto.CHANGE_TYPE_FIELD in row, \
                f"append row missing {bq_proto.CHANGE_TYPE_FIELD}"
            assert bq_proto.CHANGE_SEQUENCE_FIELD in row, \
                f"append row missing {bq_proto.CHANGE_SEQUENCE_FIELD}"
            assert row[bq_proto.CHANGE_TYPE_FIELD] in ("UPSERT", "DELETE")
        assert req.write_stream.endswith(f"/tables/{table}/streams/_default")
        assert req.trace_id, "append request must carry a trace id"
        self.attempts.append((table, req, rows))
        if self._scripted:
            s = self._scripted[0]
            s.times -= 1
            if s.times <= 0:
                self._scripted.pop(0)
            return (200, s.response)
        self.appends.append((table, req, rows))
        return (200, bq_proto.encode_append_rows_response(
            offset=sum(len(r) for _, _, r in self.appends)))
