"""Self-signed certificate helper for wire-client TLS tests.

Generates an in-memory RSA key + X.509 cert with SANs for 127.0.0.1 and
localhost so the client's default-verification path (hostname + chain)
exercises for real against the fake server — the reference covers this
surface with dockerized Postgres + sslmode=require (SURVEY §4.2)."""

from __future__ import annotations

import datetime as dt
import ipaddress


def make_self_signed_cert() -> tuple[bytes, bytes]:
    """(cert_pem, key_pem) for CN=etl-fake-pg, SAN 127.0.0.1/localhost."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "etl-fake-pg")])
    now = dt.datetime.now(dt.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - dt.timedelta(minutes=5))
        .not_valid_after(now + dt.timedelta(days=1))
        .add_extension(x509.SubjectAlternativeName([
            x509.IPAddress(ipaddress.IPv4Address("127.0.0.1")),
            x509.DNSName("localhost"),
        ]), critical=False)
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())
    return cert_pem, key_pem
