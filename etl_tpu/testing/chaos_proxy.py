"""Chaos TCP proxy: the NetworkChaos pod-level fault injector, in
process.

The reference drives Chaos Mesh NetworkChaos against replicator pods
(crates/xtask/src/commands/chaos/{mod,scenario}.rs — PacketLoss,
Partition, Latency with jitter). Here the same fault matrix is applied
at the one place a single-process test can: a TCP proxy between the
wire client and the (fake) Postgres server.

- latency: every forwarded chunk sleeps delay_ms ± jitter_ms first
  (tc netem delay analogue);
- corruption: every Nth server→client chunk of ≥64 bytes gets one byte
  flipped (tc netem corrupt analogue — at the application layer TCP
  checksum escapes manifest as protocol-violation parse errors the
  client must convert into typed, retryable failures);
- partition: sever() hard-closes every live connection pair
  (100% directional loss).
"""

from __future__ import annotations

import asyncio
import random


class ChaosProxy:
    def __init__(self, upstream_host: str, upstream_port: int, *,
                 delay_ms: float = 0.0, jitter_ms: float = 0.0,
                 corrupt_every: int = 0, seed: int = 7):
        self.upstream = (upstream_host, upstream_port)
        self.delay_ms = delay_ms
        self.jitter_ms = jitter_ms
        self.corrupt_every = corrupt_every
        self._rng = random.Random(seed)
        self._server: asyncio.AbstractServer | None = None
        self._writers: list[asyncio.StreamWriter] = []
        self._chunks = 0
        self.port = 0
        self.corrupted = 0  # bytes flipped (test observability)
        self.severed = 0

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, "127.0.0.1", 0)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        self.sever()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def sever(self) -> None:
        """Hard partition: close every live connection pair."""
        for w in self._writers:
            if not w.is_closing():
                w.close()
        if self._writers:
            self.severed += 1
        self._writers.clear()

    async def _handle(self, cr: asyncio.StreamReader,
                      cw: asyncio.StreamWriter) -> None:
        try:
            ur, uw = await asyncio.open_connection(*self.upstream)
        except OSError:
            cw.close()
            return
        self._writers += [cw, uw]
        # client→server never corrupted (chaos on the walsender's
        # answers is the scenario; corrupting requests just kills the
        # session before it starts)
        up = asyncio.ensure_future(self._pump(cr, uw, corrupt=False))
        # downstream corruption is gated per-chunk on corrupt_every so
        # a scenario can ARM it mid-run (e.g. only after initial copy)
        down = asyncio.ensure_future(self._pump(ur, cw, corrupt=True))
        await asyncio.wait({up, down},
                           return_when=asyncio.FIRST_COMPLETED)
        for t in (up, down):
            t.cancel()
        for w in (cw, uw):
            if not w.is_closing():
                w.close()

    async def _pump(self, r: asyncio.StreamReader,
                    w: asyncio.StreamWriter, corrupt: bool) -> None:
        try:
            while True:
                chunk = await r.read(65536)
                if not chunk:
                    break
                if self.delay_ms > 0:
                    d = self.delay_ms + self._rng.uniform(
                        -self.jitter_ms, self.jitter_ms)
                    await asyncio.sleep(max(0.0, d) / 1000)
                if corrupt and self.corrupt_every > 0 \
                        and len(chunk) >= 64:
                    self._chunks += 1
                    if self._chunks % self.corrupt_every == 0:
                        b = bytearray(chunk)
                        b[len(b) // 2] ^= 0xFF
                        chunk = bytes(b)
                        self.corrupted += 1
                w.write(chunk)
                await w.drain()
        except (ConnectionError, asyncio.CancelledError, OSError):
            pass
        finally:
            if not w.is_closing():
                w.close()
