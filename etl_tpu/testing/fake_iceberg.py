"""Protocol-enforcing fake Iceberg REST catalog.

Unlike a recording stub, this catalog VALIDATES commits the way a
conformant implementation would (reference test stance: the Rust suite
runs against a real REST catalog container, SURVEY §4.6):

- optimistic concurrency: `assert-ref-snapshot-id` requirements are
  checked against the main branch head; stale commits get 409;
- `add-snapshot` walks the whole metadata chain: the manifest LIST file
  must exist and parse (via the independent Avro reader — no code shared
  with the writer), every manifest it names must exist, parse, and agree
  on snapshot id / sequence number, every data file an entry names must
  exist, and the Parquet footer's row count must equal the entry's
  `record_count`; summary row totals must add up;
- schema evolution must arrive as add-schema + set-current-schema with
  the next schema-id;
- the legacy minimal shapes the round-3 destination used
  ("action": "append"/"set-schema"/"truncate" on a /commit route) are
  REJECTED with 400 — this catalog would not have accepted them.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from aiohttp import web

from .avro_reader import read_avro_ocf


@dataclass
class _Table:
    name: str
    schemas: list[dict] = field(default_factory=list)
    current_schema_id: int = 0
    snapshots: list[dict] = field(default_factory=list)
    refs: dict[str, int] = field(default_factory=dict)
    last_sequence_number: int = 0


class FakeIcebergCatalog:
    """aiohttp server speaking the Iceberg REST catalog subset the
    destination uses, with full metadata validation."""

    def __init__(self) -> None:
        self.namespaces: set[str] = set()
        self.tables: dict[tuple[str, str], _Table] = {}
        self.commit_log: list[dict] = []  # every accepted commit body
        self.rejections: list[str] = []  # validation failures (messages)
        self._runner: web.AppRunner | None = None
        self.port = 0

    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    async def start(self) -> None:
        app = web.Application()
        app.router.add_post("/v1/namespaces", self._create_namespace)
        app.router.add_post("/v1/namespaces/{ns}/tables",
                            self._create_table)
        app.router.add_get("/v1/namespaces/{ns}/tables",
                           self._list_tables)
        app.router.add_get("/v1/namespaces/{ns}/tables/{t}",
                           self._load_table)
        app.router.add_post("/v1/namespaces/{ns}/tables/{t}",
                            self._commit_table)
        app.router.add_delete("/v1/namespaces/{ns}/tables/{t}",
                              self._drop_table)
        app.router.add_route("*", "/{tail:.*}", self._not_found)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- helpers ---------------------------------------------------------------

    def _reject(self, msg: str) -> web.Response:
        self.rejections.append(msg)
        return web.json_response({"error": {"message": msg}}, status=400)

    def table(self, ns: str, name: str) -> _Table:
        """Test accessor."""
        return self.tables[(ns, name)]

    # -- routes ----------------------------------------------------------------

    async def _not_found(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"error": {"message": f"no route {request.path}"}}, status=404)

    async def _create_namespace(self, request: web.Request) -> web.Response:
        doc = await request.json()
        ns = ".".join(doc.get("namespace", []))
        if not ns:
            return self._reject("namespace must be a non-empty list")
        if ns in self.namespaces:
            return web.json_response(
                {"error": {"message": "namespace exists"}}, status=409)
        self.namespaces.add(ns)
        return web.json_response({"namespace": [ns]})

    async def _create_table(self, request: web.Request) -> web.Response:
        ns = request.match_info["ns"]
        if ns not in self.namespaces:
            return web.json_response(
                {"error": {"message": f"namespace {ns} missing"}},
                status=404)
        doc = await request.json()
        name = doc.get("name")
        schema = doc.get("schema")
        if not name:
            return self._reject("table name required")
        if not isinstance(schema, dict) or schema.get("type") != "struct":
            return self._reject("schema must be a struct")
        for f in schema.get("fields", []):
            if "id" not in f or "name" not in f or "type" not in f:
                return self._reject(f"field missing id/name/type: {f}")
        if (ns, name) in self.tables:
            return web.json_response(
                {"error": {"message": "table exists"}}, status=409)
        schema = dict(schema)
        schema.setdefault("schema-id", 0)
        self.tables[(ns, name)] = _Table(name=name, schemas=[schema])
        return web.json_response({"metadata": self._metadata(
            self.tables[(ns, name)])})

    def _metadata(self, t: _Table) -> dict:
        return {
            "format-version": 2,
            "current-schema-id": t.current_schema_id,
            "schemas": t.schemas,
            "snapshots": t.snapshots,
            "current-snapshot-id": t.refs.get("main"),
            "last-sequence-number": t.last_sequence_number,
            "refs": {k: {"snapshot-id": v, "type": "branch"}
                     for k, v in t.refs.items()},
        }

    async def _list_tables(self, request: web.Request) -> web.Response:
        ns = request.match_info["ns"]
        return web.json_response({"identifiers": [
            {"namespace": [n], "name": t.name}
            for (n, _), t in sorted(self.tables.items()) if n == ns]})

    async def _load_table(self, request: web.Request) -> web.Response:
        key = (request.match_info["ns"], request.match_info["t"])
        t = self.tables.get(key)
        if t is None:
            return web.json_response(
                {"error": {"message": "table missing"}}, status=404)
        return web.json_response({"metadata": self._metadata(t)})

    async def _drop_table(self, request: web.Request) -> web.Response:
        key = (request.match_info["ns"], request.match_info["t"])
        if self.tables.pop(key, None) is None:
            return web.json_response(
                {"error": {"message": "table missing"}}, status=404)
        return web.json_response({})

    async def _commit_table(self, request: web.Request) -> web.Response:
        key = (request.match_info["ns"], request.match_info["t"])
        t = self.tables.get(key)
        if t is None:
            return web.json_response(
                {"error": {"message": "table missing"}}, status=404)
        body = await request.json()
        if "updates" not in body or "requirements" not in body:
            return self._reject(
                "commit must carry requirements + updates (the legacy "
                "minimal /commit shape is not Iceberg REST)")
        # requirements: optimistic CAS
        for req in body["requirements"]:
            if req.get("type") == "assert-ref-snapshot-id":
                expect = req.get("snapshot-id")
                actual = t.refs.get(req.get("ref", "main"))
                if expect != actual:
                    return web.json_response(
                        {"error": {"message":
                                   f"CAS failure: ref at {actual}, "
                                   f"commit asserts {expect}"}},
                        status=409)
            elif req.get("type") == "assert-create":
                if t.snapshots:
                    return web.json_response(
                        {"error": {"message": "table not empty"}},
                        status=409)
            else:
                return self._reject(
                    f"unknown requirement {req.get('type')!r}")
        # all updates are STAGED and applied only after every one
        # validates — a real catalog applies the commit transactionally,
        # so a rejected multi-update body must leave no trace (a
        # half-applied add-schema would wedge the client's retry)
        staged_schemas = list(t.schemas)
        staged_current = t.current_schema_id
        staged_snapshot = None
        staged_ref: tuple[str, int] | None = None
        for up in body["updates"]:
            action = up.get("action")
            if action == "add-snapshot":
                snap = up.get("snapshot", {})
                err = self._validate_snapshot(t, snap,
                                              staged_schemas)
                if err:
                    return self._reject(err)
                staged_snapshot = snap
            elif action == "set-snapshot-ref":
                if staged_snapshot is None or \
                        up.get("snapshot-id") != \
                        staged_snapshot.get("snapshot-id"):
                    return self._reject(
                        "set-snapshot-ref must follow add-snapshot and "
                        "reference the snapshot it added")
                staged_ref = (up.get("ref-name", "main"),
                              up["snapshot-id"])
            elif action == "add-schema":
                schema = up.get("schema", {})
                want = len(staged_schemas)
                if schema.get("schema-id") != want:
                    return self._reject(
                        f"add-schema must carry schema-id {want}, got "
                        f"{schema.get('schema-id')}")
                err = self._validate_schema_ids(staged_schemas,
                                                staged_current, schema)
                if err:
                    return self._reject(err)
                staged_schemas = staged_schemas + [schema]
            elif action == "set-current-schema":
                sid = up.get("schema-id")
                if not any(s.get("schema-id") == sid
                           for s in staged_schemas):
                    return self._reject(f"unknown schema-id {sid}")
                staged_current = sid
            else:
                return self._reject(
                    f"unknown update action {action!r} (legacy minimal "
                    "shapes are rejected)")
        t.schemas = staged_schemas
        t.current_schema_id = staged_current
        if staged_ref is not None:
            t.snapshots.append(staged_snapshot)
            t.refs[staged_ref[0]] = staged_ref[1]
            t.last_sequence_number = staged_snapshot["sequence-number"]
        self.commit_log.append(body)
        return web.json_response({"metadata": self._metadata(t)})

    @staticmethod
    def _validate_schema_ids(schemas: list[dict], current_id: int,
                             new: dict) -> str | None:
        """Spec: field ids are assigned once and never reused — an
        existing column must keep its id across evolution, and a NEW
        column must not take an id any schema ever used."""
        cur = next((s for s in schemas
                    if s.get("schema-id") == current_id), None)
        prev_ids = {f["name"]: f["id"]
                    for f in (cur or {}).get("fields", [])}
        ever_used = {f["id"] for s in schemas
                     for f in s.get("fields", [])}
        seen: set[int] = set()
        for f in new.get("fields", []):
            if f["id"] in seen:
                return f"duplicate field id {f['id']} in schema"
            seen.add(f["id"])
            if f["name"] in prev_ids:
                if f["id"] != prev_ids[f["name"]]:
                    return (f"field {f['name']!r} changed id "
                            f"{prev_ids[f['name']]} → {f['id']} — ids "
                            "must be stable across evolution")
            elif f["id"] in ever_used:
                return (f"new field {f['name']!r} reuses id {f['id']} — "
                        "ids are never reused")
        return None

    # -- metadata-chain validation --------------------------------------------

    def _validate_snapshot(self, t: _Table, snap: dict,
                           schemas: list[dict] | None = None
                           ) -> str | None:
        import pyarrow.parquet as pq

        schemas = schemas if schemas is not None else t.schemas
        # the schema this snapshot was written under (field-id check)
        snap_schema = next(
            (s for s in schemas
             if s.get("schema-id") == snap.get("schema-id")), None)

        for req_field in ("snapshot-id", "sequence-number", "timestamp-ms",
                          "manifest-list", "summary"):
            if req_field not in snap:
                return f"snapshot missing {req_field}"
        if snap["sequence-number"] != t.last_sequence_number + 1:
            return (f"sequence-number must advance by 1 (have "
                    f"{t.last_sequence_number}, got "
                    f"{snap['sequence-number']})")
        parent = snap.get("parent-snapshot-id")
        if parent != t.refs.get("main"):
            return (f"parent-snapshot-id {parent} does not match branch "
                    f"head {t.refs.get('main')}")
        summary = snap["summary"]
        if summary.get("operation") not in ("append", "delete",
                                            "overwrite", "replace"):
            return f"bad summary.operation {summary.get('operation')!r}"
        # walk the manifest chain with the INDEPENDENT avro reader
        try:
            _, manifests, ml_meta = read_avro_ocf(snap["manifest-list"])
        except Exception as e:
            return f"manifest list unreadable: {e}"
        if ml_meta.get("snapshot-id") not in (None,
                                              str(snap["snapshot-id"])):
            return "manifest list metadata names a different snapshot"
        total_added = 0
        for m in manifests:
            try:
                _, entries, _ = read_avro_ocf(m["manifest_path"])
            except Exception as e:
                return f"manifest {m['manifest_path']} unreadable: {e}"
            if m["added_snapshot_id"] != snap["snapshot-id"]:
                return "manifest added_snapshot_id mismatch"
            if len([e for e in entries if e["status"] == 1]) \
                    != m["added_files_count"]:
                return "manifest added_files_count disagrees with entries"
            rows_in_manifest = 0
            for entry in entries:
                if entry["snapshot_id"] != snap["snapshot-id"]:
                    return "manifest entry snapshot_id mismatch"
                if entry["sequence_number"] != snap["sequence-number"]:
                    return "manifest entry sequence_number mismatch"
                df = entry["data_file"]
                try:
                    actual = pq.ParquetFile(df["file_path"]).metadata
                except Exception as e:
                    return f"data file {df['file_path']} unreadable: {e}"
                if actual.num_rows != df["record_count"]:
                    return (f"record_count {df['record_count']} != parquet "
                            f"rows {actual.num_rows}")
                if df["file_format"] != "PARQUET":
                    return f"bad file_format {df['file_format']!r}"
                # spec: data-file columns must resolve by FIELD ID —
                # every parquet column must carry a field_id matching
                # the snapshot's schema (name-based projection is not
                # conformant without a name mapping)
                if snap_schema is not None:
                    want = {f["name"]: f["id"]
                            for f in snap_schema.get("fields", [])}
                    arrow = pq.read_schema(df["file_path"])
                    for fld in arrow:
                        fid = (fld.metadata or {}).get(
                            b"PARQUET:field_id")
                        if fid is None:
                            return (f"data file column {fld.name!r} "
                                    "carries no parquet field_id")
                        if want.get(fld.name) != int(fid):
                            return (f"data file column {fld.name!r} "
                                    f"field_id {int(fid)} != schema id "
                                    f"{want.get(fld.name)}")
                rows_in_manifest += df["record_count"]
            if rows_in_manifest != m["added_rows_count"]:
                return "manifest added_rows_count disagrees with entries"
            total_added += rows_in_manifest
        if int(summary.get("added-records", "0")) != total_added:
            return (f"summary added-records {summary.get('added-records')} "
                    f"!= manifest total {total_added}")
        return None
