"""Recording HTTP server for destination tests.

Captures every request (method, path, query, body) and returns scriptable
responses — the emulator pattern the reference uses for BigQuery/ClickHouse
destination suites (SURVEY §4.6), reduced to what assertions need."""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from aiohttp import web


@dataclass
class RecordedRequest:
    method: str
    path: str
    query: dict[str, str]
    body: bytes
    headers: dict[str, str]

    @property
    def json(self):
        return json.loads(self.body) if self.body else None

    @property
    def text(self) -> str:
        return self.body.decode("utf-8", "replace")


Responder = Callable[[RecordedRequest], "tuple[int, dict] | None"]


class RecordingHttpServer:
    def __init__(self) -> None:
        self.requests: list[RecordedRequest] = []
        self.responders: list[Responder] = []
        self.fail_next: list[int] = []  # status codes to fail with, FIFO
        self._runner: web.AppRunner | None = None
        self.port = 0

    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    async def start(self) -> None:
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def _handle(self, request: web.Request) -> web.Response:
        body = await request.read()
        rec = RecordedRequest(
            method=request.method, path=request.path,
            query=dict(request.query), body=body,
            headers=dict(request.headers))
        self.requests.append(rec)
        if self.fail_next:
            status = self.fail_next.pop(0)
            return web.Response(status=status, text="scripted failure")
        for responder in self.responders:
            out = responder(rec)
            if out is not None:
                status, doc = out
                if isinstance(doc, (bytes, bytearray)):
                    return web.Response(
                        body=bytes(doc), status=status,
                        content_type="application/x-protobuf")
                return web.json_response(doc, status=status)
        return web.json_response({}, status=200)

    # -- assertion helpers ------------------------------------------------------

    def queries(self) -> list[str]:
        """ClickHouse-style ?query= params in arrival order."""
        return [r.query["query"] for r in self.requests if "query" in r.query]

    def paths(self) -> list[str]:
        return [f"{r.method} {r.path}" for r in self.requests]
