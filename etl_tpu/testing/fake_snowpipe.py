"""In-process Snowpipe Streaming emulator.

Validates the REAL wire surface the destination speaks (reference
rest_client.rs): hostname discovery, channel PUT/DELETE with
`fail_on_uncommitted_rows`, zstd NDJSON row POSTs with continuation-token
chaining and offset-range query params, and `:bulk-channel-status`. Enforces
the protocol (stale continuation tokens → 400 STALE_CONTINUATION_TOKEN_
SEQUENCER, uncommitted rows → 409 ERR_CHANNEL_HAS_UNCOMMITTED_DATA) so the
destination's recovery paths are exercised against a server that actually
objects, not one that accepts anything."""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from aiohttp import web

from ..destinations.snowpipe import MAX_COMPRESSED_BYTES


@dataclass
class FakeChannel:
    continuation: str
    committed: str | None = None  # last committed offset token
    pending: list[tuple[str, int]] = field(default_factory=list)
    rows_inserted: int = 0
    rows_parsed: int = 0
    rows_errors: int = 0
    epoch: int = 0  # bumped on reopen


class FakeSnowpipeServer:
    """Snowpipe Streaming + statements-API emulator.

    `commit_mode`:
      - "immediate": rows commit as each insert lands;
      - "on_poll":   rows commit when channel status is next polled —
                     exercises the client's durability barrier for real.
    """

    def __init__(self, commit_mode: str = "immediate",
                 hostname_as_json: bool = False,
                 require_auth: bool = False):
        self.commit_mode = commit_mode
        self.hostname_as_json = hostname_as_json
        self.require_auth = require_auth
        self.channels: dict[str, FakeChannel] = {}
        self.rows: dict[str, list[dict]] = {}  # pipe key -> NDJSON docs
        self.statements: list[str] = []
        self.requests: list[tuple[str, str, dict]] = []  # method, path, query
        self.fail_next: list[tuple[int, str]] = []  # (status, body) FIFO
        self.rotate_continuation_once = False  # simulate a stale client token
        self.hostname_discoveries = 0
        self.status_polls = 0
        self._ct = 0
        self._runner: web.AppRunner | None = None
        self.port = 0

    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def _next_ct(self) -> str:
        self._ct += 1
        return f"ct-{self._ct:04d}"

    async def start(self) -> None:
        # client_max_size: the API's own body bound is 4 MB compressed
        app = web.Application(client_max_size=MAX_COMPRESSED_BYTES + 1024)
        app.router.add_get("/v2/streaming/hostname", self._hostname)
        app.router.add_route(
            "*",
            "/v2/streaming/databases/{db}/schemas/{sch}/pipes/{pipe}"
            "/channels/{ch}", self._channel)
        app.router.add_post(
            "/v2/streaming/data/databases/{db}/schemas/{sch}/pipes/{pipe}"
            "/channels/{ch}/rows", self._insert)
        app.router.add_post(
            "/v2/streaming/databases/{db}/schemas/{sch}/pipes/"
            "{pipe_status}", self._bulk_status)
        app.router.add_post("/api/v2/statements", self._statement)
        # auto_decompress=False: aiohttp's parser would otherwise try (and
        # fail) to decode Content-Encoding: zstd itself — the emulator
        # must see the raw compressed body like the real service does
        self._runner = web.AppRunner(app, auto_decompress=False)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- helpers ---------------------------------------------------------------

    def _gate(self, request: web.Request) -> web.Response | None:
        self.requests.append((request.method, request.path,
                              dict(request.query)))
        if self.require_auth and \
                not request.headers.get("Authorization", "").startswith(
                    "Bearer "):
            return web.json_response({"message": "no token"}, status=401)
        if self.fail_next:
            status, body = self.fail_next.pop(0)
            return web.Response(status=status, text=body,
                                content_type="application/json")
        return None

    @staticmethod
    def _key(request: web.Request) -> str:
        i = request.match_info
        return f"{i['db']}/{i['sch']}/{i['pipe']}/{i['ch']}"

    def _status_doc(self, ch: FakeChannel, name: str) -> dict:
        return {"channel_name": name, "channel_status_code": "ACTIVE",
                "last_committed_offset_token": ch.committed,
                "rows_inserted": ch.rows_inserted,
                "rows_parsed": ch.rows_parsed,
                "rows_errors": ch.rows_errors}

    def _commit_pending(self, ch: FakeChannel) -> None:
        if ch.pending:
            ch.committed = ch.pending[-1][0]
            ch.rows_inserted += sum(n for _, n in ch.pending)
            ch.pending.clear()

    # -- endpoints -------------------------------------------------------------

    async def _hostname(self, request: web.Request) -> web.Response:
        gate = self._gate(request)
        if gate is not None:
            return gate
        self.hostname_discoveries += 1
        # the real server returns plain text even when docs say JSON
        # (rest_client.rs:67-71); both shapes are exercised
        if self.hostname_as_json:
            return web.json_response({"hostname": self.url()})
        return web.Response(text=self.url())

    async def _channel(self, request: web.Request) -> web.Response:
        gate = self._gate(request)
        if gate is not None:
            return gate
        key = self._key(request)
        body = json.loads(await request.read() or b"{}")
        fail_on_uncommitted = body.get("fail_on_uncommitted_rows", True)
        ch = self.channels.get(key)
        if request.method == "PUT":
            if ch is not None and ch.pending and fail_on_uncommitted:
                if self.commit_mode == "on_poll":
                    # an open with uncommitted rows objects; the client
                    # polls status (committing them) and retries
                    return web.json_response(
                        {"code": "ERR_CHANNEL_HAS_UNCOMMITTED_DATA"},
                        status=409)
                self._commit_pending(ch)
            if ch is None:
                ch = self.channels[key] = FakeChannel(self._next_ct())
            else:
                ch.continuation = self._next_ct()
                ch.epoch += 1
            return web.json_response({
                "next_continuation_token": ch.continuation,
                "channel_status": self._status_doc(
                    ch, request.match_info["ch"])})
        if request.method == "DELETE":
            if ch is None:
                return web.json_response({"message": "no such channel"},
                                         status=404)
            if ch.pending and fail_on_uncommitted:
                if self.commit_mode == "on_poll":
                    return web.json_response(
                        {"code": "ERR_CHANNEL_HAS_UNCOMMITTED_DATA"},
                        status=409)
                self._commit_pending(ch)
            del self.channels[key]
            return web.json_response({})
        return web.json_response({"message": "bad method"}, status=405)

    async def _insert(self, request: web.Request) -> web.Response:
        gate = self._gate(request)
        if gate is not None:
            return gate
        key = self._key(request)
        ch = self.channels.get(key)
        if ch is None:
            return web.json_response({"message": "channel not found"},
                                     status=404)
        if self.rotate_continuation_once:
            self.rotate_continuation_once = False
            ch.continuation = self._next_ct()
        if request.query.get("continuationToken") != ch.continuation:
            return web.json_response(
                {"code": "STALE_CONTINUATION_TOKEN_SEQUENCER"}, status=400)
        if request.headers.get("Content-Encoding") != "zstd":
            return web.json_response(
                {"message": "body must be zstd-compressed"}, status=400)
        if request.headers.get("Content-Type") != "application/x-ndjson":
            return web.json_response(
                {"message": "body must be NDJSON"}, status=400)
        import zstandard

        raw = zstandard.ZstdDecompressor().decompress(
            await request.read(), max_output_size=64 * 1024 * 1024)
        docs = [json.loads(line) for line in
                raw.decode().splitlines() if line]
        end = request.query.get("endOffsetToken", "")
        if not end:
            return web.json_response({"message": "missing offset range"},
                                     status=400)
        # offset tokens must advance strictly: a client replaying or
        # reordering batches within a channel would corrupt exactly-once
        # accounting (tokens are zero-padded sequence keys, so string
        # order == numeric order)
        last = ch.pending[-1][0] if ch.pending else ch.committed
        if last is not None and end <= last:
            return web.json_response(
                {"message": f"offset token {end!r} does not advance "
                            f"past {last!r}"}, status=400)
        pipe_key = key.rsplit("/", 1)[0]
        self.rows.setdefault(pipe_key, []).extend(docs)
        ch.rows_parsed += len(docs)
        ch.pending.append((end, len(docs)))
        ch.continuation = self._next_ct()
        if self.commit_mode == "immediate":
            self._commit_pending(ch)
        return web.json_response(
            {"next_continuation_token": ch.continuation})

    async def _bulk_status(self, request: web.Request) -> web.Response:
        gate = self._gate(request)
        if gate is not None:
            return gate
        tail = request.match_info["pipe_status"]
        if not tail.endswith(":bulk-channel-status"):
            return web.json_response({"message": "unknown route"},
                                     status=404)
        pipe = tail[: -len(":bulk-channel-status")]
        i = request.match_info
        self.status_polls += 1
        names = json.loads(await request.read())["channel_names"]
        out = {}
        for name in names:
            key = f"{i['db']}/{i['sch']}/{pipe}/{name}"
            ch = self.channels.get(key)
            if ch is None:
                continue
            if self.commit_mode == "on_poll":
                self._commit_pending(ch)
            out[name] = self._status_doc(ch, name)
        return web.json_response({"channel_statuses": out})

    async def _statement(self, request: web.Request) -> web.Response:
        gate = self._gate(request)
        if gate is not None:
            return gate
        self.statements.append(json.loads(await request.read())["statement"])
        return web.json_response({"resultSetMetaData": {}})
