"""Fuzz targets for the codec parsers + native framer.

Reference parity: cargo-fuzz targets `parse_copy_row`, `parse_text_cell`,
`numeric_text_roundtrip`, `parse_bytea_hex_string`
(fuzz/fuzz_targets/ + src/fuzzing.rs). No coverage-guided fuzzer exists in
this environment, so this is a seeded random byte fuzzer with structured
mutations (truncate/splice/bitflip over valid corpora), a wall-clock
budget, and crash seeds printed for replay — the same contract the
reference's fuzz entry points enforce:

  THE PARSERS MUST NEVER CRASH UNCONTROLLED. Any input either parses or
  raises a typed EtlError; the native framer must flag malformed frames
  (bad_from) or raise EtlError, never segfault or throw bare exceptions.

Run ad hoc:  python -m etl_tpu.testing.fuzz --seconds 30 [--seed N]
CI-sized runs live in tests/test_fuzz.py.
"""

from __future__ import annotations

import random
import time

from ..models.errors import EtlError
from ..models.pgtypes import Oid

# every OID the text parser dispatches on — fuzz coverage must include
# each branch
_OIDS = [Oid.BOOL, Oid.INT2, Oid.INT4, Oid.INT8, Oid.FLOAT4, Oid.FLOAT8,
         Oid.NUMERIC, Oid.TEXT, Oid.VARCHAR, Oid.BPCHAR, Oid.DATE, Oid.TIME,
         Oid.TIMETZ, Oid.TIMESTAMP, Oid.TIMESTAMPTZ, Oid.UUID, Oid.JSON,
         Oid.JSONB, Oid.BYTEA, Oid.INTERVAL]

_SEED_TEXTS = [
    "0", "-1", "12345678901234567890123456789", "+5", "-", "--", "1e309",
    "1.5", "-0.0", "NaN", "Infinity", "-Infinity", "nan", "1e", "e1", ".",
    "2024-02-29", "0001-01-01", "9999-12-31", "0044-03-15 BC", "infinity",
    "-infinity", "24:00:00", "23:59:60", "12:00:00.1234567",
    "2024-05-01 12:34:56.789+02", "2024-05-01 12:34:56-15:59:59",
    "a0eebc99-9c0b-4ef8-bb6d-6bb9bd380a11", "{}", "[1,2]", "null",
    '{"k": "v"}', "\\xdeadbeef", "\\x", "\\xg", "1 year 2 mons",
    "t", "f", "true", "", " ", "\t", "\\N", "\\", "{1,2,3}", "{NULL}",
    '{"a","b"}', "0.000000000000000012345", "9" * 40,
]

_MUT_CHARS = "0123456789-+.:eE aftTxX{}\\\"',N\x00\x7fé"


def _mutate(rng: random.Random, s: str) -> str:
    ops = rng.randint(1, 3)
    out = s
    for _ in range(ops):
        c = rng.random()
        if c < 0.25 and out:
            i = rng.randrange(len(out))
            out = out[:i] + rng.choice(_MUT_CHARS) + out[i + 1:]
        elif c < 0.5:
            i = rng.randrange(len(out) + 1)
            out = out[:i] + rng.choice(_MUT_CHARS) + out[i:]
        elif c < 0.7 and out:
            i = rng.randrange(len(out))
            out = out[:i] + out[i + 1:]
        elif c < 0.85 and out:
            i, j = sorted((rng.randrange(len(out) + 1),
                           rng.randrange(len(out) + 1)))
            other = rng.choice(_SEED_TEXTS)
            out = out[:i] + other + out[j:]
        else:
            out = out * rng.randint(1, 3)
    return out[:4096]


class FuzzFailure(AssertionError):
    def __init__(self, target: str, seed: int, case: int, detail: str):
        super().__init__(
            f"fuzz target {target} failed at seed={seed} case={case}: "
            f"{detail}\nreplay: python -m etl_tpu.testing.fuzz "
            f"--target {target} --seed {seed}")


def fuzz_parse_text_cell(rng: random.Random, _ignored=None) -> None:
    from ..postgres.codec.text import parse_cell_text

    text = _mutate(rng, rng.choice(_SEED_TEXTS))
    oid = rng.choice(_OIDS)
    try:
        parse_cell_text(text, oid)
    except EtlError:
        pass  # typed rejection is the contract


def fuzz_parse_copy_row(rng: random.Random, _ignored=None) -> None:
    from ..postgres.codec.copy_text import parse_copy_row

    n_cols = rng.randint(1, 6)
    oids = [rng.choice(_OIDS) for _ in range(n_cols)]
    fields = [_mutate(rng, rng.choice(_SEED_TEXTS))
              for _ in range(rng.randint(0, n_cols + 1))]
    line = "\t".join(fields).encode("utf-8", "surrogatepass")[:2048]
    try:
        parse_copy_row(line, oids)
    except (EtlError, UnicodeDecodeError):
        pass


def fuzz_numeric_roundtrip(rng: random.Random, _ignored=None) -> None:
    """Valid numeric text must survive parse → pg_text exactly (the
    reference numeric_text_roundtrip target); arbitrary text must parse or
    fail typed."""
    from ..models.cell import PgNumeric
    from ..postgres.codec.text import parse_cell_text

    digits = rng.randint(1, 35)
    scale = rng.randint(0, digits)
    n = rng.randint(0, 10**digits - 1)
    s = str(n).rjust(scale + 1, "0")
    text = (("-" if rng.random() < 0.5 else "")
            + (s[:-scale] + "." + s[-scale:] if scale else s))
    v = parse_cell_text(text, Oid.NUMERIC)
    assert isinstance(v, PgNumeric)
    assert v.pg_text() == text, (v.pg_text(), text)
    # and the mutated form must never crash untyped
    try:
        parse_cell_text(_mutate(rng, text), Oid.NUMERIC)
    except EtlError:
        pass


def fuzz_bytea_hex(rng: random.Random, _ignored=None) -> None:
    from ..postgres.codec.text import parse_cell_text

    body = "".join(rng.choice("0123456789abcdefABCDEFxg \\")
                   for _ in range(rng.randint(0, 64)))
    for text in (f"\\x{body}", body):
        try:
            parse_cell_text(text, Oid.BYTEA)
        except EtlError:
            pass


def fuzz_framer(rng: random.Random, _ignored=None) -> None:
    """Random bytes through the native pgoutput framer: it must return a
    FramedBatch with bad_from set, or raise EtlError — never crash the
    process or return out-of-bounds offsets."""
    import numpy as np

    from ..native import frame_pgoutput
    from ..postgres.codec import pgoutput

    msgs = []
    for _ in range(rng.randint(1, 8)):
        c = rng.random()
        if c < 0.4:  # valid insert, possibly corrupted below
            msgs.append(pgoutput.encode_insert(
                rng.randrange(1, 1 << 31),
                [str(rng.randrange(1000)).encode()
                 for _ in range(rng.randint(0, 4))]))
        elif c < 0.6:
            msgs.append(pgoutput.encode_begin(rng.randrange(1 << 40),
                                              rng.randrange(1 << 50), 7))
        else:
            msgs.append(bytes(rng.randrange(256)
                              for _ in range(rng.randint(0, 64))))
    if msgs and rng.random() < 0.5:  # corrupt one
        i = rng.randrange(len(msgs))
        b = bytearray(msgs[i])
        if b:
            b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
        msgs[i] = bytes(b)
    buf = b"".join(msgs)
    lens = np.array([len(m) for m in msgs], dtype=np.int32)
    offs = np.zeros(len(msgs), dtype=np.int64)
    np.cumsum(lens[:-1], out=offs[1:])
    n_cols = rng.randint(1, 8)
    try:
        framed, bad = frame_pgoutput(buf, offs, lens, n_cols)
    except EtlError:
        return
    upto = framed.n_msgs if bad < 0 else bad
    # offsets/lengths within bounds for every framed field
    total = len(buf)
    for arr_off, arr_len in ((framed.new_off[:upto], framed.new_len[:upto]),
                             (framed.old_off[:upto], framed.old_len[:upto])):
        ends = arr_off.astype(np.int64) + arr_len
        assert (arr_off >= 0).all() and (arr_len >= 0).all() \
                and (ends <= total).all(), \
            "framer emitted out-of-bounds field"


_AVRO_FUZZ_DIR: str | None = None  # one temp dir per process, not per case


def fuzz_avro_ocf(rng: random.Random, _ignored=None) -> None:
    """The Iceberg metadata pair: random manifest-shaped records through
    the OCF writer must round-trip EXACTLY through the independent
    reader (they share no code — VERDICT r3 #5), and bit-flipped files
    must raise cleanly (ValueError/EOF-shaped), never hang or emit
    silently-wrong records."""
    import tempfile
    from pathlib import Path

    from ..destinations.iceberg_meta import write_avro_ocf
    from .avro_reader import read_avro_ocf

    global _AVRO_FUZZ_DIR
    if _AVRO_FUZZ_DIR is None:
        _AVRO_FUZZ_DIR = tempfile.mkdtemp(prefix="avro_fuzz_")

    schema = {"type": "record", "name": "r", "fields": [
        {"name": "s", "type": "string"},
        {"name": "n", "type": "long"},
        {"name": "ob", "type": ["null", "bytes"]},
        {"name": "arr", "type": {"type": "array", "items": {
            "type": "record", "name": "kv", "fields": [
                {"name": "key", "type": "int"},
                {"name": "value", "type": "bytes"}]}}},
        {"name": "flag", "type": "boolean"},
    ]}
    records = []
    for _ in range(rng.randint(0, 6)):
        records.append({
            "s": "".join(chr(rng.randrange(32, 0x2FF))
                         for _ in range(rng.randint(0, 12))),
            "n": rng.randrange(-(1 << 62), 1 << 62),
            "ob": None if rng.random() < 0.3 else
            bytes(rng.randrange(256) for _ in range(rng.randint(0, 9))),
            "arr": [{"key": rng.randrange(1 << 20),
                     "value": bytes(rng.randrange(256) for _ in
                                    range(rng.randint(0, 5)))}
                    for _ in range(rng.randint(0, 3))],
            "flag": rng.random() < 0.5,
        })
    path = Path(_AVRO_FUZZ_DIR) / "f.avro"
    write_avro_ocf(path, schema, records)
    _, got, _ = read_avro_ocf(path)
    assert got == records, (got, records)
    # corruption: any single bit flip must raise ValueError (the
    # reader's one rejection type; UnicodeDecodeError is its subclass)
    # or KeyError/TypeError from a corrupt-but-valid-JSON schema — or
    # parse to something that simply differs. AssertionError stays
    # UNCAUGHT so consistency checks inside this block keep reporting.
    raw = bytearray(path.read_bytes())
    raw[rng.randrange(len(raw))] ^= 1 << rng.randrange(8)
    path.write_bytes(bytes(raw))
    try:
        read_avro_ocf(path)
    except (ValueError, KeyError, TypeError, RecursionError):
        pass  # typed rejection is the contract


def fuzz_pb_append_rows(rng: random.Random, _ignored=None) -> None:
    """The BigQuery protobuf pair: random AppendRowsRequest bytes decoded
    by BOTH in-repo decoders — the generic TLV one (bq_proto) and the
    spec-written independent one (pb_reader, which shares no code) —
    must agree field-for-field; bit-flipped requests must reject typed
    or parse to something that differs, never hang."""
    from ..destinations import bq_proto
    from ..models.cell import PgNumeric
    from ..models.pgtypes import Oid
    from ..models.schema import (ColumnSchema, ReplicatedTableSchema,
                                 TableName, TableSchema)
    from .pb_reader import decode_append_rows

    kinds = [(Oid.INT4, lambda: rng.randrange(-(1 << 31), 1 << 31)),
             (Oid.INT8, lambda: rng.randrange(-(1 << 62), 1 << 62)),
             (Oid.TEXT, lambda: "".join(chr(rng.randrange(32, 0x24F))
                                        for _ in range(rng.randint(0, 9)))),
             (Oid.BOOL, lambda: rng.random() < 0.5),
             (Oid.FLOAT8, lambda: rng.uniform(-1e12, 1e12)),
             (Oid.NUMERIC, lambda: PgNumeric(str(rng.randrange(10 ** 12))))]
    ncols = rng.randint(1, 5)
    chosen = [kinds[rng.randrange(len(kinds))] for _ in range(ncols)]
    schema = ReplicatedTableSchema.with_all_columns(TableSchema(
        999, TableName("public", "fz"),
        tuple(ColumnSchema(f"c{i}", oid, nullable=True,
                           primary_key_ordinal=1 if i == 0 else None)
              for i, (oid, _) in enumerate(chosen))))
    rows = []
    for r in range(rng.randint(1, 4)):
        values = [None if rng.random() < 0.25 else gen()
                  for _, gen in chosen]
        rows.append(bq_proto.encode_row(schema, values, "UPSERT",
                                        f"{r:016x}"))
    buf = bq_proto.append_rows_request(
        "projects/p/datasets/d/tables/t/streams/_default",
        bq_proto.row_descriptor(schema), rows, trace_id="fz",
        offset=rng.randrange(1 << 40) if rng.random() < 0.5 else None)
    ind = decode_append_rows(buf)
    own = bq_proto.decode_append_rows_request(buf)
    assert ind["write_stream"] == own.write_stream
    assert ind["trace_id"] == own.trace_id
    assert ind.get("offset") == own.offset
    # full descriptor agreement: (name, number, label, type) 4-tuples
    assert [(f["name"], f["number"], f["label"], f["type"])
            for f in ind["descriptor"]["fields"]] == \
        list(own.descriptor_fields)
    assert len(ind["rows"]) == len(own.serialized_rows) == len(rows)
    # row VALUES decoded by both lineages must agree field-for-field —
    # this is the assertion that actually breaks the encode/decode
    # self-confirmation loop for payloads
    assert ind["rows"] == own.decode_rows(), (ind["rows"],
                                              own.decode_rows())
    # corruption: one bit flip → typed rejection or a differing parse
    raw = bytearray(buf)
    raw[rng.randrange(len(raw))] ^= 1 << rng.randrange(8)
    try:
        decode_append_rows(bytes(raw))
    except (ValueError, KeyError):
        pass  # typed rejection is the contract


def fuzz_snowpipe_batches(rng: random.Random, _ignored=None) -> None:
    """The Snowpipe streaming-zstd batch builder: random NDJSON rows
    through RowBatchBuilder must re-decode EXACTLY (independent path:
    zstandard decompressor + stdlib json, none of the builder's chunking
    logic) with rows in order across batch splits, correct per-batch row
    counts and offset ranges, and every batch under the API body limit.
    Non-finite floats must reject typed."""
    import json as _json

    import zstandard

    from ..destinations.snowpipe import MAX_COMPRESSED_BYTES, RowBatchBuilder

    b = RowBatchBuilder()
    docs = []
    # ~5% of cases feed high-entropy megabyte rows so the compressed
    # stream passes BATCH_SPLIT_THRESHOLD and the mid-stream split path
    # (row order across batches, second batch's offset range) is REALLY
    # exercised, not vacuously skipped
    split_case = rng.random() < 0.05
    gens = [lambda: rng.randrange(-(1 << 60), 1 << 60),
            lambda: "".join(chr(rng.randrange(32, 0x2FF))
                            for _ in range(rng.randint(0, 2000))),
            lambda: None, lambda: rng.random() * 1e6,
            lambda: rng.random() < 0.5,
            lambda: {"nested": [1, "x", None]}]
    n = rng.randint(8, 12) if split_case else rng.randint(1, 40)
    for i in range(n):
        # split rows: 512KB of random bytes → 1MB hex, safely under the
        # 2MB per-row limit; ~4 bits/char entropy keeps zstd near 2:1 so
        # ~8 rows pass the 3.8MB compressed split threshold
        v = rng.randbytes(512 << 10).hex() if split_case \
            else rng.choice(gens)()
        doc = {"id": i, "v": v, "_cdc_sequence_number": f"{i:016x}"}
        b.push_row(doc, f"{i:016x}")
        docs.append(doc)
    batches = b.finish()
    if split_case:
        assert len(batches) >= 2, \
            f"split case produced {len(batches)} batch(es)"
    dctx = zstandard.ZstdDecompressor()
    got = []
    row_total = 0
    for rb in batches:
        assert len(rb.data) <= MAX_COMPRESSED_BYTES
        lines = dctx.decompressobj().decompress(rb.data).split(b"\n")
        rows = [_json.loads(l) for l in lines if l]
        assert len(rows) == rb.row_count, (len(rows), rb.row_count)
        # inclusive offset range must be exactly first/last row's token
        assert rb.start_offset == rows[0]["_cdc_sequence_number"]
        assert rb.end_offset == rows[-1]["_cdc_sequence_number"]
        row_total += rb.row_count
        got.extend(rows)
    assert row_total == n and got == docs, (row_total, n)
    # non-finite floats reject typed (encoding.rs stance)
    b2 = RowBatchBuilder()
    try:
        b2.push_row({"v": float("inf")}, "0")
    except EtlError:
        pass
    else:
        raise AssertionError("non-finite float accepted")


TARGETS = {
    "parse_text_cell": fuzz_parse_text_cell,
    "parse_copy_row": fuzz_parse_copy_row,
    "numeric_roundtrip": fuzz_numeric_roundtrip,
    "bytea_hex": fuzz_bytea_hex,
    "framer": fuzz_framer,
    "avro_ocf": fuzz_avro_ocf,
    "pb_append_rows": fuzz_pb_append_rows,
    "snowpipe_batches": fuzz_snowpipe_batches,
}


def run_target(name: str, *, seconds: float = 2.0, seed: int | None = None,
               min_cases: int = 200) -> int:
    """Run one target under a wall-clock budget; returns cases executed.
    Raises FuzzFailure with the replay seed on any contract violation."""
    fn = TARGETS[name]
    base_seed = seed if seed is not None else random.randrange(1 << 30)
    deadline = time.monotonic() + seconds
    case = 0
    while case < min_cases or time.monotonic() < deadline:
        case_seed = base_seed + case
        rng = random.Random(case_seed)
        try:
            fn(rng)
        except AssertionError as e:
            raise FuzzFailure(name, base_seed, case, str(e))
        except EtlError:
            pass
        except Exception as e:  # untyped escape = contract violation
            raise FuzzFailure(name, base_seed, case,
                              f"untyped {type(e).__name__}: {e}")
        case += 1
    return case


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="etl_tpu.testing.fuzz")
    p.add_argument("--target", choices=sorted(TARGETS), default=None)
    p.add_argument("--seconds", type=float, default=10.0)
    p.add_argument("--seed", type=int, default=None)
    args = p.parse_args(argv)
    names = [args.target] if args.target else sorted(TARGETS)
    for name in names:
        n = run_target(name, seconds=args.seconds / len(names),
                       seed=args.seed)
        print(f"{name}: {n} cases OK")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
