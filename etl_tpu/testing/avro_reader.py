"""Independent Avro Object Container File reader.

Written directly against the Avro 1.11 specification (binary encoding +
object container files) and deliberately sharing NO code with the writer
in destinations/iceberg_meta.py — this is the decode half of the
break-the-self-confirmation-loop stance (VERDICT r3 #5): if the writer
mis-encodes varints, unions, or block framing, this reader fails rather
than round-tripping the same bug.

Only the null codec is supported (all repo writers use it).
"""

from __future__ import annotations

import json
import struct
from pathlib import Path


class _Cursor:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        # n < 0 (a corrupted length varint) would move the cursor
        # BACKWARD and loop the parse forever — found by the avro_ocf
        # fuzz target's bit-flip pass
        if n < 0 or self.pos + n > len(self.buf):
            raise ValueError("avro: truncated file or negative length")
        b = self.buf[self.pos : self.pos + n]
        self.pos += n
        return b

    def remaining(self) -> int:
        return len(self.buf) - self.pos

    def varint(self) -> int:
        shift = 0
        acc = 0
        while True:
            if self.pos >= len(self.buf):
                raise ValueError("avro: truncated varint")
            byte = self.buf[self.pos]
            self.pos += 1
            acc |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
            if shift > 70:
                raise ValueError("avro: varint too long")
        # zigzag decode
        return (acc >> 1) ^ -(acc & 1)

    def count(self, min_item_size: int = 1) -> int:
        """A block/item count: bounded by the bytes left — a corrupted
        huge count must fail fast, not spin through range(10^15).
        `min_item_size` is the schema item's minimum encoded size;
        zero-byte items (null, empty records) are instead capped by an
        absolute work budget so valid files of empty values still
        parse."""
        n = self.varint()
        if n < 0:
            raise ValueError(f"avro: negative block count {n}")
        bound = self.remaining() // min_item_size if min_item_size             else 1_000_000
        if n > bound:
            raise ValueError(f"avro: block count {n} exceeds file")
        return n


def _min_size(schema) -> int:
    """Minimum encoded bytes of one value of `schema` (0 for null and
    empty records — the bound switches to a work budget there)."""
    if isinstance(schema, list):
        return 1 + min(_min_size(s) for s in schema)
    t = schema["type"] if isinstance(schema, dict) else schema
    if t == "null":
        return 0
    if t == "record":
        return sum(_min_size(f["type"]) for f in schema["fields"])
    if t == "fixed":
        return schema["size"]
    return 1  # every other type encodes to >= 1 byte


def _read_value(cur: _Cursor, schema):
    if isinstance(schema, list):  # union
        idx = cur.varint()
        if not 0 <= idx < len(schema):
            raise ValueError(f"avro: union branch {idx} out of range")
        return _read_value(cur, schema[idx])
    t = schema["type"] if isinstance(schema, dict) else schema
    if t == "null":
        return None
    if t == "boolean":
        return cur.take(1) != b"\x00"
    if t in ("int", "long"):
        return cur.varint()
    if t == "float":
        return struct.unpack("<f", cur.take(4))[0]
    if t == "double":
        return struct.unpack("<d", cur.take(8))[0]
    if t == "bytes":
        return bytes(cur.take(cur.varint()))
    if t == "string":
        return cur.take(cur.varint()).decode()
    if t == "fixed":
        return bytes(cur.take(schema["size"]))
    if t == "record":
        return {f["name"]: _read_value(cur, f["type"])
                for f in schema["fields"]}
    if t == "array":
        out = []
        while True:
            n = cur.varint()
            if n == 0:
                return out
            if n < 0:  # block with byte-size prefix
                cur.varint()
                n = -n
            m = _min_size(schema["items"])
            if n > (cur.remaining() // m if m else 1_000_000):
                raise ValueError(f"avro: array count {n} exceeds file")
            for _ in range(n):
                out.append(_read_value(cur, schema["items"]))
    if t == "map":
        out = {}
        while True:
            n = cur.varint()
            if n == 0:
                return out
            if n < 0:
                cur.varint()
                n = -n
            # map entries: >= 1-byte key + value
            if n > cur.remaining() // (1 + _min_size(schema["values"])):
                raise ValueError(f"avro: map count {n} exceeds file")
            for _ in range(n):
                k = cur.take(cur.varint()).decode()
                out[k] = _read_value(cur, schema["values"])
    raise ValueError(f"avro reader: unsupported type {t!r}")


def read_avro_ocf(path: str | Path) -> tuple[dict, list[dict], dict]:
    """Read an Avro OCF → (schema, records, file_metadata)."""
    cur = _Cursor(Path(path).read_bytes())
    if cur.take(4) != b"Obj\x01":
        raise ValueError("avro: bad magic")
    meta = _read_value(cur, {"type": "map", "values": "bytes"})
    codec = meta.get("avro.codec", b"null").decode()
    if codec != "null":
        raise ValueError(f"avro: unsupported codec {codec}")
    schema = json.loads(meta["avro.schema"].decode())
    sync = cur.take(16)
    records: list[dict] = []
    min_rec = _min_size(schema)
    while cur.pos < len(cur.buf):
        count = cur.count(min_rec)
        cur.varint()  # block byte length (null codec: redundant)
        for _ in range(count):
            records.append(_read_value(cur, schema))
        if cur.take(16) != sync:
            raise ValueError("avro: sync marker mismatch")
    file_meta = {k: v.decode("utf-8", "replace") for k, v in meta.items()}
    return schema, records, file_meta
