"""Independent protobuf wire-format reader for AppendRows verification.

Written directly against the protobuf wire spec and the public
descriptor.proto / storage.proto field numbers, and deliberately sharing
NO code with destinations/bq_proto.py (not even its generic TLV parser) —
the decode half of the break-the-self-confirmation-loop stance (VERDICT
r3 #5). It parses the DescriptorProto the request itself carries and uses
THAT to decode the serialized row messages, so a bq_proto bug in either
the descriptor or the row encoding surfaces as a mismatch here instead of
round-tripping.

Field numbers (public protos):
- AppendRowsRequest: write_stream=1, offset=2 (Int64Value.value=1),
  proto_rows=4 (AppendRowsRequest.ProtoData: writer_schema=1, rows=2),
  trace_id=6
- ProtoSchema: proto_descriptor=1 (DescriptorProto)
- ProtoRows: serialized_rows=1 (repeated bytes)
- DescriptorProto: name=1, field=2 (repeated FieldDescriptorProto)
- FieldDescriptorProto: name=1, number=3, label=4, type=5
"""

from __future__ import annotations

import struct


def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if i >= len(buf):
            raise ValueError("pb: truncated varint")
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7
        if shift > 70:
            raise ValueError("pb: varint too long")


def scan(buf: bytes):
    """Yield (field_no, wire_type, value) triples; LEN values are bytes,
    varints ints, fixed32/64 raw 4/8-byte buffers."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        field_no, wire = tag >> 3, tag & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
            yield field_no, 0, v
        elif wire == 1:
            if i + 8 > n:
                raise ValueError("pb: truncated fixed64")
            yield field_no, 1, buf[i : i + 8]
            i += 8
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            if i + ln > n:
                raise ValueError("pb: truncated LEN field")
            yield field_no, 2, buf[i : i + ln]
            i += ln
        elif wire == 5:
            if i + 4 > n:
                raise ValueError("pb: truncated fixed32")
            yield field_no, 5, buf[i : i + 4]
            i += 4
        else:
            raise ValueError(f"pb: unsupported wire type {wire}")


def _to_int64(u: int) -> int:
    return u - (1 << 64) if u >= 1 << 63 else u


def _to_int32(u: int) -> int:
    # int32 negatives arrive as 10-byte varints (64-bit two's complement)
    v = _to_int64(u)
    if not -(1 << 31) <= v < 1 << 31:
        raise ValueError(f"pb: int32 out of range: {v}")
    return v


def parse_descriptor(buf: bytes) -> dict:
    """DescriptorProto → {"name": ..., "fields": [{name, number, label,
    type}]} (nested types not needed for the flat row messages)."""
    name = ""
    fields = []
    for fno, wire, val in scan(buf):
        if fno == 1 and wire == 2:
            name = val.decode()
        elif fno == 2 and wire == 2:
            f = {"name": "", "number": 0, "label": 1, "type": 0}
            for ffno, fwire, fval in scan(val):
                if ffno == 1 and fwire == 2:
                    f["name"] = fval.decode()
                elif ffno == 3 and fwire == 0:
                    f["number"] = fval
                elif ffno == 4 and fwire == 0:
                    f["label"] = fval
                elif ffno == 5 and fwire == 0:
                    f["type"] = fval
            fields.append(f)
    return {"name": name, "fields": fields}


# FieldDescriptorProto.Type
_DOUBLE, _FLOAT, _INT64, _INT32 = 1, 2, 3, 5
_BOOL, _STRING, _BYTES, _UINT32 = 8, 9, 12, 13
_REPEATED = 3


def _decode_scalar(ftype: int, wire: int, val):
    # wire/type agreement: a corrupted tag can deliver the wrong wire
    # type for the declared field type — reject typed, never
    # AttributeError/struct.error into the caller
    expected_wire = {_DOUBLE: 1, _FLOAT: 5, _STRING: 2, _BYTES: 2}.get(
        ftype, 0)
    if wire != expected_wire:
        raise ValueError(
            f"pb: wire type {wire} mismatches declared type {ftype}")
    if ftype == _DOUBLE:
        return struct.unpack("<d", val)[0]
    if ftype == _FLOAT:
        return struct.unpack("<f", val)[0]
    if ftype == _INT64:
        return _to_int64(val)
    if ftype == _INT32:
        return _to_int32(val)
    if ftype == _BOOL:
        return bool(val)
    if ftype == _STRING:
        return val.decode()
    if ftype == _BYTES:
        return bytes(val)
    if ftype == _UINT32:
        return val
    raise ValueError(f"pb: unsupported field type {ftype}")


def decode_row(buf: bytes, descriptor: dict) -> dict:
    """Decode one serialized row message using the carried descriptor."""
    by_number = {f["number"]: f for f in descriptor["fields"]}
    row: dict = {}
    for fno, wire, val in scan(buf):
        f = by_number.get(fno)
        if f is None:
            raise ValueError(f"pb: row has unknown field {fno}")
        if f["label"] == _REPEATED:
            items = row.setdefault(f["name"], [])
            if wire == 2 and f["type"] in (_DOUBLE, _FLOAT, _INT64,
                                           _INT32, _BOOL, _UINT32):
                # packed encoding
                width = {_DOUBLE: 8, _FLOAT: 4}.get(f["type"], 0)
                if width and len(val) % width:
                    raise ValueError("pb: truncated packed payload")
                i = 0
                while i < len(val):
                    if f["type"] == _DOUBLE:
                        items.append(struct.unpack_from("<d", val, i)[0])
                        i += 8
                    elif f["type"] == _FLOAT:
                        items.append(struct.unpack_from("<f", val, i)[0])
                        i += 4
                    else:
                        u, i = _read_varint(val, i)
                        items.append(_decode_scalar(f["type"], 0, u))
            else:
                items.append(_decode_scalar(f["type"], wire, val))
        else:
            row[f["name"]] = _decode_scalar(f["type"], wire, val)
    return row


def decode_append_rows(buf: bytes) -> dict:
    """AppendRowsRequest bytes → {"write_stream", "offset", "trace_id",
    "descriptor", "rows": [decoded dicts]}."""
    out = {"write_stream": None, "offset": None, "trace_id": None,
           "descriptor": None, "rows": []}
    serialized_rows: list[bytes] = []
    for fno, wire, val in scan(buf):
        if fno == 1 and wire == 2:
            out["write_stream"] = val.decode()
        elif fno == 2 and wire == 2:  # Int64Value wrapper
            for wfno, wwire, wval in scan(val):
                if wfno == 1 and wwire == 0:
                    out["offset"] = _to_int64(wval)
        elif fno == 4 and wire == 2:  # ProtoData
            for pfno, pwire, pval in scan(val):
                if pfno == 1 and pwire == 2:  # ProtoSchema
                    for sfno, swire, sval in scan(pval):
                        if sfno == 1 and swire == 2:
                            out["descriptor"] = parse_descriptor(sval)
                elif pfno == 2 and pwire == 2:  # ProtoRows
                    for rfno, rwire, rval in scan(pval):
                        if rfno == 1 and rwire == 2:
                            serialized_rows.append(rval)
        elif fno == 6 and wire == 2:
            out["trace_id"] = val.decode()
    if out["descriptor"] is None:
        raise ValueError("pb: request carries no ProtoSchema descriptor")
    out["rows"] = [decode_row(r, out["descriptor"])
                   for r in serialized_rows]
    return out
