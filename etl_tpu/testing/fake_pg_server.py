"""Socket-level fake Postgres backend for wire-client tests.

Speaks protocol v3 over real TCP: startup (trust or SCRAM-SHA-256), the
simple-query subset the framework issues (catalog introspection, slot
management, snapshot transactions, COPY OUT), and the replication
sub-protocol (CREATE/DROP_REPLICATION_SLOT, START_REPLICATION with
CopyBoth + standby status updates). Backed by the same FakeDatabase used
by the in-process fake source, so wire-level pipelines exercise identical
semantics.

This is the analogue of the reference's dockerized test clusters
(SURVEY §4.2) for an environment with no Postgres server, and of its mock
`K8sClient` pattern — the protocol seam is faked at the lowest level the
environment allows.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
import os
import re
import sqlite3
import struct
import time
from dataclasses import dataclass

from ..models.lsn import Lsn
from ..postgres import fake as fakemod
from ..postgres.codec import pgoutput
from ..postgres.codec.copy_text import encode_copy_row
from ..postgres.fake import FakeDatabase


def _msg(tag: bytes, payload: bytes = b"") -> bytes:
    return tag + struct.pack(">i", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


def _error(code: str, message: str) -> bytes:
    payload = b"SERROR\x00" + b"C" + _cstr(code) + b"M" + _cstr(message) + b"\x00"
    return _msg(b"E", payload)


def _row_description(names: list[str], oids: list[int] | None = None) -> bytes:
    oids = oids or [25] * len(names)
    payload = struct.pack(">h", len(names))
    for name, oid in zip(names, oids):
        payload += _cstr(name) + struct.pack(">ihihih", 0, 0, oid, -1, -1, 0)
    return _msg(b"T", payload)


def _data_row(values: list[str | None]) -> bytes:
    payload = struct.pack(">h", len(values))
    for v in values:
        if v is None:
            payload += struct.pack(">i", -1)
        else:
            b = v.encode()
            payload += struct.pack(">i", len(b)) + b
    return _msg(b"D", payload)


def _command_complete(tag: str) -> bytes:
    return _msg(b"C", _cstr(tag))


READY = _msg(b"Z", b"I")


class _WireStreamHandle:
    """Chaos handle for a WIRE replication session.

    Registered in ``db.active_streams`` alongside the in-process
    ``_FakeReplicationStream`` handles so ``FakeDatabase.sever_streams()``
    (the NetworkChaos partition analogue, mirroring Chaos Mesh on
    replicator pods — reference xtask chaos) cuts TCP-backed sessions
    too: ``close()`` aborts the transport, so the client observes a hard
    connection reset mid-stream rather than a graceful CopyDone.
    """

    def __init__(self, writer: asyncio.StreamWriter):
        self._writer = writer

    async def close(self) -> None:
        transport = self._writer.transport
        if transport is not None:
            transport.abort()


@dataclass
class _Session:
    reader: asyncio.StreamReader
    writer: asyncio.StreamWriter
    replication: bool = False
    user: str = ""
    snapshot_id: str | None = None  # pinned via SET TRANSACTION SNAPSHOT
    # extended-protocol state (unnamed statement/portal only)
    ext_sql: str | None = None
    ext_params: "list[str | None]" = None  # type: ignore[assignment]
    # store-transaction ownership: the embedded store sqlite is shared
    # across sessions, so an open BEGIN..COMMIT holds the server's store
    # lock — exactly the observable serialization real PG applies to
    # same-row writers, without sqlite's shared-handle txn nesting errors
    holds_store_lock: bool = False


class FakePgServer:
    """asyncio TCP server; `await start()` then connect clients to
    `('127.0.0.1', server.port)`."""

    def __init__(self, db: FakeDatabase, *, password: str | None = None,
                 keepalive_interval_s: float = 0.05,
                 server_version: str = "16.3",
                 tls_cert: "tuple[bytes, bytes] | None" = None,
                 scram_salt: bytes | None = None,
                 scram_nonce_tail: str | None = None):
        self.db = db
        self.password = password  # None = trust auth
        self.keepalive_interval_s = keepalive_interval_s
        self.server_version = server_version
        # (cert_pem, key_pem): accept SSLRequest and upgrade; None = refuse
        self.tls_cert = tls_cert
        self._tls_ctx = None
        # fixed SCRAM parameters for golden-transcript tests (None = random)
        self.scram_salt = scram_salt
        self.scram_nonce_tail = scram_nonce_tail
        self.scram_transcript: list[tuple[str, str]] = []  # (dir, message)
        self._server: asyncio.AbstractServer | None = None
        self._store_lock = asyncio.Lock()
        self.allow_generic_sql = False  # devtools fill-table passthrough
        self.port = 0
        self.connections = 0
        self.queries: list[str] = []  # every simple-query SQL, in order
        self._writers: set[asyncio.StreamWriter] = set()

    @property
    def version_num(self) -> int:
        from ..postgres.version import parse_server_version

        return parse_server_version(self.server_version)

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, "127.0.0.1", 0)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # 3.12's wait_closed blocks until every handler exits — force
            # lingering client connections shut first
            for w in list(self._writers):
                w.close()
            await self._server.wait_closed()
            self._server = None

    # -- connection handling ---------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self.connections += 1
        self._writers.add(writer)
        sess = _Session(reader, writer)
        try:
            if not await self._startup(sess):
                return
            while True:
                header = await reader.readexactly(5)
                tag = header[:1]
                (length,) = struct.unpack(">i", header[1:5])
                payload = await reader.readexactly(length - 4)
                if tag == b"X":
                    return
                if tag == b"Q":
                    sql = payload.rstrip(b"\x00").decode()
                    await self._dispatch(sess, sql)
                elif tag in (b"P", b"B", b"D", b"E", b"H", b"S"):
                    await self._extended(sess, tag, payload)
                # CopyData outside CopyBoth: ignore
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError):
            pass
        finally:
            if sess.holds_store_lock:
                # a client that died mid-transaction must not wedge every
                # other pooled connection (PG aborts the txn on disconnect)
                sess.holds_store_lock = False
                try:
                    db = getattr(self.db, "_store_sql_db", None)
                    if db is not None and db.in_transaction:
                        db.execute("ROLLBACK")
                except Exception:
                    pass
                self._store_lock.release()
            self._writers.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass

    async def _startup(self, sess: _Session) -> bool:
        r = sess.reader
        w = sess.writer
        (length,) = struct.unpack(">i", await r.readexactly(4))
        body = await r.readexactly(length - 4)
        (version,) = struct.unpack(">i", body[:4])
        if version == 80877103:  # SSLRequest
            if self.tls_cert is None:
                w.write(b"N")  # refuse; client decides (require → error)
                await w.drain()
                return await self._startup(sess)
            w.write(b"S")
            await w.drain()
            if self._tls_ctx is None:
                import ssl as ssl_mod
                import tempfile

                ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_SERVER)
                cert_pem, key_pem = self.tls_cert
                with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
                        tempfile.NamedTemporaryFile(suffix=".pem") as kf:
                    cf.write(cert_pem)
                    cf.flush()
                    kf.write(key_pem)
                    kf.flush()
                    ctx.load_cert_chain(cf.name, kf.name)
                self._tls_ctx = ctx
            loop = asyncio.get_event_loop()
            transport = w.transport
            new_transport = await loop.start_tls(
                transport, transport.get_protocol(), self._tls_ctx,
                server_side=True)
            if new_transport is None:  # client dropped mid-handshake
                return False
            w._transport = new_transport  # type: ignore[attr-defined]
            r._transport = new_transport  # type: ignore[attr-defined]
            return await self._startup(sess)
        params: dict[str, str] = {}
        parts = body[4:].split(b"\x00")
        for k, v in zip(parts[::2], parts[1::2]):
            if k:
                params[k.decode()] = v.decode()
        sess.user = params.get("user", "")
        sess.replication = params.get("replication") == "database"
        if self.password is not None:
            if not await self._scram(sess):
                return False
        w.write(_msg(b"R", struct.pack(">i", 0)))  # AuthenticationOk
        w.write(_msg(b"S", _cstr("server_version")
                     + _cstr(self.server_version)))
        w.write(_msg(b"S", _cstr("client_encoding") + _cstr("UTF8")))
        w.write(_msg(b"K", struct.pack(">ii", os.getpid(), 12345)))
        w.write(READY)
        await w.drain()
        return True

    async def _scram(self, sess: _Session) -> bool:
        r, w = sess.reader, sess.writer
        w.write(_msg(b"R", struct.pack(">i", 10) + _cstr("SCRAM-SHA-256")
                     + b"\x00"))
        await w.drain()
        header = await r.readexactly(5)
        (length,) = struct.unpack(">i", header[1:5])
        payload = await r.readexactly(length - 4)
        mech_end = payload.index(b"\x00")
        (resp_len,) = struct.unpack(">i", payload[mech_end + 1 : mech_end + 5])
        client_first = payload[mech_end + 5 :][:resp_len].decode()
        self.scram_transcript.append(("C", client_first))
        bare = client_first.split(",", 2)[2]
        client_nonce = dict(p.split("=", 1)
                            for p in bare.split(","))["r"]
        salt = self.scram_salt if self.scram_salt is not None \
            else os.urandom(16)
        iterations = 4096
        tail = self.scram_nonce_tail \
            if self.scram_nonce_tail is not None \
            else base64.b64encode(os.urandom(9)).decode()
        server_nonce = client_nonce + tail
        server_first = (f"r={server_nonce},"
                        f"s={base64.b64encode(salt).decode()},i={iterations}")
        self.scram_transcript.append(("S", server_first))
        w.write(_msg(b"R", struct.pack(">i", 11) + server_first.encode()))
        await w.drain()
        header = await r.readexactly(5)
        (length,) = struct.unpack(">i", header[1:5])
        client_final = (await r.readexactly(length - 4)).decode()
        self.scram_transcript.append(("C", client_final))
        attrs = dict(p.split("=", 1) for p in client_final.split(","))
        salted = hashlib.pbkdf2_hmac("sha256", self.password.encode(), salt,
                                     iterations)
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored = hashlib.sha256(client_key).digest()
        without_proof = client_final.rsplit(",p=", 1)[0]
        auth_message = ",".join([bare, server_first, without_proof])
        sig = hmac.new(stored, auth_message.encode(), hashlib.sha256).digest()
        expected = bytes(a ^ b for a, b in zip(client_key, sig))
        if base64.b64decode(attrs.get("p", "")) != expected:
            w.write(_error("28P01", "password authentication failed"))
            await w.drain()
            return False
        server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        verifier = hmac.new(server_key, auth_message.encode(),
                            hashlib.sha256).digest()
        final = f"v={base64.b64encode(verifier).decode()}"
        self.scram_transcript.append(("S", final))
        w.write(_msg(b"R", struct.pack(">i", 12) + final.encode()))
        return True

    # -- extended protocol (unnamed statement/portal) ---------------------------

    async def _extended(self, sess: _Session, tag: bytes,
                        payload: bytes) -> None:
        """Parse/Bind/Describe/Execute/Sync: the server binds parameters
        SERVER-side; execution happens at Sync by substituting quoted
        literals into the parsed statement and reusing the simple-query
        dispatch (the real server plans instead — same observable
        behavior for the statement shapes the framework issues)."""
        w = sess.writer
        if tag == b"P":
            zero = payload.index(b"\x00")
            rest = payload[zero + 1:]
            sess.ext_sql = rest[: rest.index(b"\x00")].decode()
            sess.ext_params = []
        elif tag == b"B":
            pos = payload.index(b"\x00") + 1  # portal name
            pos = payload.index(b"\x00", pos) + 1  # statement name
            (n_fmt,) = struct.unpack_from(">h", payload, pos)
            pos += 2 + 2 * n_fmt
            (n_params,) = struct.unpack_from(">h", payload, pos)
            pos += 2
            params: list[str | None] = []
            for _ in range(n_params):
                (ln,) = struct.unpack_from(">i", payload, pos)
                pos += 4
                if ln < 0:
                    params.append(None)
                else:
                    params.append(payload[pos : pos + ln].decode())
                    pos += ln
            sess.ext_params = params
        elif tag == b"S":
            if sess.ext_sql is None:
                w.write(READY)
                await w.drain()
                return
            params = sess.ext_params or []

            def lit(m: re.Match) -> str:
                v = params[int(m.group(1)) - 1]
                return "NULL" if v is None \
                    else "'" + v.replace("'", "''") + "'"

            # ONE pass over the original statement: bound values containing
            # "$n" text must never be re-substituted
            sql = re.sub(r"\$(\d+)", lit, sess.ext_sql)
            w.write(_msg(b"1"))  # ParseComplete
            w.write(_msg(b"2"))  # BindComplete
            sess.ext_sql = None
            sess.ext_params = None
            await self._dispatch(sess, sql)  # rows + tag + ReadyForQuery
        # D (describe) / E (execute) / H (flush): folded into Sync

    # -- SQL dispatch ------------------------------------------------------------

    async def _dispatch(self, sess: _Session, sql: str) -> None:
        w = sess.writer
        db = self.db
        norm = " ".join(sql.split())
        self.queries.append(norm)
        if self.version_num < 150000 and ("pt.attnames" in norm
                                          or "pt.rowfilter" in norm):
            # faithful PG14: publication column lists / row filters don't
            # exist — the catalog columns are absent, queries ERROR
            w.write(_error("42703",
                           'column pt.attnames does not exist'))
            w.write(READY)
            await w.drain()
            return
        try:
            handled = await self._try_handle(sess, norm, sql)
            if not handled and self.allow_generic_sql:
                handled = await self._try_generic_sql(sess, norm, sql)
        except Exception as e:  # surface as server error, keep session alive
            w.write(_error("XX000", f"fake server error: {e!r}"))
            w.write(READY)
            await w.drain()
            return
        if not handled:
            w.write(_error("0A000", f"fake server: unhandled SQL: {norm[:120]}"))
            w.write(READY)
        await w.drain()

    async def _try_generic_sql(self, sess: _Session, norm: str,
                               sql: str) -> bool:
        """Opt-in generic DDL/DML passthrough to the embedded sqlite — the
        devtools fill-table loader needs plain CREATE TABLE / INSERT /
        SELECT against arbitrary user tables (off by default so protocol
        tests still assert unhandled-SQL errors)."""
        first = norm.split(" ", 1)[0].upper() if norm else ""
        if first not in ("CREATE", "INSERT", "SELECT", "DROP", "DELETE"):
            return False
        db = self.db
        store = getattr(db, "_generic_sql_db", None)
        if store is None:
            store = sqlite3.connect(":memory:", check_same_thread=False)
            store.isolation_level = None
            db._generic_sql_db = store
        w = sess.writer
        # no lock needed: this sqlite is separate from the store's, every
        # execute is synchronous (no await between statements), and the
        # loader speaks autocommit statements only
        try:
            cur = store.execute(sql)
        except sqlite3.Error as e:
            w.write(_error("42601", f"generic sql: {e}"))
            w.write(READY)
            return True
        if cur.description is not None:
            names = [d[0] for d in cur.description]
            rows = [[None if v is None else str(v) for v in r]
                    for r in cur.fetchall()]
            self._send_rows(w, names, rows)
        else:
            tag = {"INSERT": f"INSERT 0 {cur.rowcount}",
                   "DELETE": f"DELETE {cur.rowcount}"}.get(first, first)
            w.write(_command_complete(tag))
            w.write(READY)
        return True

    async def _try_store_sql(self, sess: _Session, norm: str,
                             sql: str) -> bool:
        """Execute `etl` store-schema statements (PostgresStore over the
        wire) against an embedded per-database sqlite — the statements are
        the store's shared dialect, so sqlite semantics match; only the
        identity-column DDL spelling differs."""
        from ..store.sql import STORE_TABLE_NAMES

        w = sess.writer
        # the Postgres dialect schema-qualifies into `etl.` (reference
        # postgres_store layout); the embedded sqlite keeps flat names —
        # reverse the SAME table list the store qualifies, no drift.
        # Quote-aware: bound parameters arrive substituted as quoted
        # literals and must NEVER be rewritten (real Postgres binds
        # server-side and would not touch them).
        def unqualify(s: str) -> str:
            parts = s.split("'")
            for i in range(0, len(parts), 2):  # even = outside quotes
                for t in STORE_TABLE_NAMES:
                    parts[i] = parts[i].replace(f"etl.{t[4:]}", t)
            return "'".join(parts)

        norm = unqualify(norm)
        sql = unqualify(sql)
        first = norm.split(" ", 1)[0].upper() if norm else ""
        is_txn = first in ("BEGIN", "COMMIT", "ROLLBACK") and " " not in norm
        # the control-plane's api_* tables (api/db.py PostgresApiDb)
        # ride the same embedded-sqlite path, flat names, no schema
        # qualification — the API owns its own database in the reference
        from ..api.db import API_TABLE_NAMES

        if not is_txn and not any(t in norm for t in STORE_TABLE_NAMES
                                  + API_TABLE_NAMES):
            return False
        if first == "ALTER" and ("SET SCHEMA etl" in norm
                                 or "RENAME TO" in norm):
            # the store's one-time legacy migration (SET SCHEMA + RENAME).
            # In the embedded sqlite's flat namespace the legacy and
            # migrated spellings coincide, so both steps are no-ops that
            # preserve seeded rows — the legacy-upgrade test pre-seeds
            # flat tables and asserts the store still reads them.
            w.write(_command_complete("ALTER TABLE"))
            w.write(READY)
            return True
        if first == "ALTER" and "ADD COLUMN" in norm.upper() \
                and any(t in norm for t in STORE_TABLE_NAMES
                        + API_TABLE_NAMES):
            # api AND store migrations use ALTER TABLE ... ADD COLUMN —
            # pass it to the embedded sqlite (same dialect),
            # duplicate-column errors surface for the client's
            # idempotence check
            pass
        elif first not in ("CREATE", "INSERT", "UPDATE", "DELETE",
                           "SELECT", "BEGIN", "COMMIT", "ROLLBACK"):
            return False
        db = self.db
        store = getattr(db, "_store_sql_db", None)
        if store is None:
            store = sqlite3.connect(":memory:", check_same_thread=False)
            store.isolation_level = None  # explicit BEGIN/COMMIT pass through
            db._store_sql_db = store
        # transaction serialization across pooled client connections: a
        # bare BEGIN holds the store lock until its COMMIT/ROLLBACK;
        # autocommit statements hold it per-statement. A failed statement
        # inside a transaction keeps the lock — the client still owns the
        # open transaction and will ROLLBACK.
        if not sess.holds_store_lock:
            await self._store_lock.acquire()
            sess.holds_store_lock = True
            release_after = first != "BEGIN"
        else:
            release_after = False
        if first in ("COMMIT", "ROLLBACK"):
            release_after = True

        def maybe_release() -> None:
            if release_after:
                sess.holds_store_lock = False
                self._store_lock.release()

        stmt = sql.replace("BIGINT GENERATED BY DEFAULT AS IDENTITY",
                           "INTEGER")
        # real Postgres supports `INSERT ... RETURNING id` everywhere;
        # the embedded sqlite only grew it in 3.35 — emulate the one
        # form the control plane uses so old runtimes stay faithful
        emulate_returning = (sqlite3.sqlite_version_info < (3, 35, 0)
                             and stmt.rstrip().lower()
                                 .endswith(" returning id"))
        if emulate_returning:
            stmt = stmt.rstrip()[:-len(" returning id")]
        try:
            cur = store.execute(stmt)
        except sqlite3.Error as e:
            maybe_release()
            w.write(_error("42601", f"store sql: {e}"))
            w.write(READY)
            return True
        maybe_release()
        if emulate_returning:
            self._send_rows(w, ["id"], [[str(cur.lastrowid)]])
        elif cur.description is not None:
            names = [d[0] for d in cur.description]
            rows = [[None if v is None else str(v) for v in r]
                    for r in cur.fetchall()]
            self._send_rows(w, names, rows)
        else:
            tag = {"INSERT": "INSERT 0 1", "UPDATE": f"UPDATE {cur.rowcount}",
                   "DELETE": f"DELETE {cur.rowcount}"}.get(first, first)
            w.write(_command_complete(tag))
            w.write(READY)
        return True

    async def _try_handle(self, sess: _Session, norm: str, sql: str) -> bool:
        w = sess.writer
        db = self.db

        if await self._try_store_sql(sess, norm, sql):
            return True

        if norm == "SELECT pg_is_in_recovery()":
            self._send_rows(w, ["pg_is_in_recovery"],
                            [["t" if db.is_standby else "f"]])
            return True
        if norm.startswith("SELECT name FROM etl.source_migrations"):
            if not db.applied_migrations:
                w.write(_error("42P01",
                               'relation "etl.source_migrations" does not '
                               "exist"))
                w.write(READY)
                return True
            self._send_rows(w, ["name"],
                            [[n] for n in sorted(db.applied_migrations)])
            return True
        if norm.startswith("CREATE SCHEMA IF NOT EXISTS etl"):
            # the source-migration SCRIPT (schema + functions + event
            # triggers, one multi-statement query) installs the trigger;
            # a bare CREATE SCHEMA (e.g. PostgresStore creating its own
            # schema) must NOT set the flag
            if "CREATE EVENT TRIGGER" in sql:
                db.ddl_trigger_installed = True
            w.write(_command_complete("CREATE SCHEMA"))
            w.write(READY)
            return True
        if norm.startswith("INSERT INTO etl.source_migrations"):
            m2 = re.search(r"VALUES \('([^']+)'\)", norm)
            if m2 and m2.group(1) not in db.applied_migrations:
                db.applied_migrations.append(m2.group(1))
            w.write(_command_complete("INSERT 0 1"))
            w.write(READY)
            return True

        m = re.match(r"SELECT 1 FROM pg_publication WHERE pubname = '([^']*)'",
                     norm)
        if m:
            rows = [["1"]] if m.group(1) in db.publications else []
            self._send_rows(w, ["?column?"], rows)
            return True

        if "FROM pg_publication_tables pt" in norm and "SELECT c.oid" in norm:
            m = re.search(r"pt\.pubname = '([^']*)'", norm)
            tids = db.publications.get(m.group(1), []) if m else []
            self._send_rows(w, ["oid"], [[str(t)] for t in sorted(tids)])
            return True

        m = re.match(r"SELECT n\.nspname, c\.relname, c\.relreplident .*"
                     r"WHERE c\.oid = (\d+)", norm)
        if m:
            t = db.tables.get(int(m.group(1)))
            rows = [[t.schema.name.schema, t.schema.name.name,
                     chr(t.replica_identity)]] if t else []
            self._send_rows(w, ["nspname", "relname", "relreplident"], rows)
            return True

        m = re.match(r"SELECT n\.nspname, c\.relname FROM pg_class c .*"
                     r"WHERE c\.oid = (\d+)", norm)
        if m:
            t = db.tables.get(int(m.group(1)))
            rows = [[t.schema.name.schema, t.schema.name.name]] if t else []
            self._send_rows(w, ["nspname", "relname"], rows)
            return True

        m = re.match(r"SELECT a\.attname FROM pg_attribute a WHERE "
                     r"a\.attrelid = (\d+)", norm)
        if m:
            t = db.tables.get(int(m.group(1)))
            rows = [[c.name] for c in t.schema.columns] if t else []
            self._send_rows(w, ["attname"], rows)
            return True

        m = re.search(r"SELECT a\.attname, a\.atttypid.*a\.attrelid = (\d+)",
                      norm)
        if m:
            t = db.tables.get(int(m.group(1)))
            rows = []
            if t:
                for c in t.schema.columns:
                    rows.append([c.name, str(c.type_oid), str(c.modifier),
                                 "t" if not c.nullable else "f",
                                 str(c.primary_key_ordinal or 0),
                                 c.default_expression])
            self._send_rows(w, ["attname", "atttypid", "atttypmod",
                                "attnotnull", "ord", "default"], rows)
            return True

        if "SELECT pc.oid, pt.rowfilter" in norm \
                and "FROM pg_publication_tables" in norm:
            pub = re.search(r"pt\.pubname = '([^']*)'", norm).group(1)
            rows = [[str(tid), sql]
                    for (p, tid), sql in db.row_filter_sql.items()
                    if p == pub and tid in db.publications.get(pub, [])]
            self._send_rows(w, ["oid", "rowfilter"], rows)
            return True

        if "SELECT pt.attnames" in norm \
                and "FROM pg_publication_tables" in norm:
            pub = re.search(r"pt\.pubname = '([^']*)'", norm).group(1)
            tid = int(re.search(r"pc\.oid = (\d+)", norm).group(1))
            filt = db.column_filters.get((pub, tid))
            attnames = "{" + ",".join(filt) + "}" if filt else None
            rowfilter = db.row_filter_sql.get((pub, tid))
            published = tid in db.publications.get(pub, [])
            rows = [[attnames, rowfilter]] if published else []
            self._send_rows(w, ["attnames", "rowfilter"], rows)
            return True

        if "FROM pg_replication_slots s" in norm and "LEFT JOIN" in norm:
            rows = []
            for slot in db.slots.values():
                rows.append([
                    slot.name, "t" if slot.active else "f",
                    "lost" if slot.invalidated else "reserved",
                    str(int(db.current_lsn) - int(slot.consistent_point)),
                    str(int(db.current_lsn) - int(slot.confirmed_flush)),
                    None, None, None, None])
            self._send_rows(w, ["slot_name", "active", "wal_status",
                                "restart_lag", "flush_lag", "safe_wal",
                                "write_ms", "flush_ms", "replay_ms"], rows)
            return True

        if norm == "SELECT pg_current_wal_lsn()":
            self._send_rows(w, ["pg_current_wal_lsn"], [[str(db.current_lsn)]])
            return True

        m = re.search(r"FROM pg_replication_slots WHERE slot_name = '([^']*)'",
                      norm)
        if m:
            s = db.slots.get(m.group(1))
            rows = []
            if s is not None:
                rows = [[str(s.confirmed_flush),
                         "t" if s.active else "f",
                         "lost" if s.invalidated else "reserved"]]
            self._send_rows(w, ["confirmed_flush_lsn", "active", "wal_status"],
                            rows)
            return True

        m = re.match(r'CREATE_REPLICATION_SLOT "([^"]+)" LOGICAL pgoutput',
                     norm)
        if m:
            name = m.group(1)
            if name in db.slots:
                w.write(_error("42710", f'slot "{name}" already exists'))
                w.write(READY)
                return True
            point = db.current_lsn
            sid = db.take_snapshot()
            db.slots[name] = fakemod._FakeSlot(
                name=name, consistent_point=point, confirmed_flush=point,
                snapshot_id=sid)
            self._send_rows(
                w, ["slot_name", "consistent_point", "snapshot_name",
                    "output_plugin"],
                [[name, str(point), sid, "pgoutput"]])
            return True

        m = re.match(r'DROP_REPLICATION_SLOT "([^"]+)"', norm)
        if m:
            if m.group(1) not in db.slots:
                w.write(_error("42704",
                               f'replication slot "{m.group(1)}" does not exist'))
                w.write(READY)
                return True
            db.slots.pop(m.group(1), None)
            w.write(_command_complete("DROP_REPLICATION_SLOT"))
            w.write(READY)
            return True

        if norm.startswith("BEGIN"):
            w.write(_command_complete("BEGIN"))
            w.write(READY)
            return True

        m = re.match(r"SET TRANSACTION SNAPSHOT '([^']*)'", norm)
        if m:
            sess.snapshot_id = m.group(1)
            w.write(_command_complete("SET"))
            w.write(READY)
            return True

        m = re.match(r"COPY \(SELECT (.+) FROM \"([^\"]+)\"\.\"([^\"]+)\""
                     r"(?: WHERE (?:ctid >= '\((\d+),0\)' AND ctid < "
                     r"'\((\d+),0\)')?(?: ?(?:AND )?\((.+)\))?)?"
                     r"\) TO STDOUT", norm)
        if m:
            await self._copy_out(sess, m)
            return True

        m = re.search(r"FROM pg_partition_tree\((\d+)\) pt", norm)
        if m:
            t = db.tables.get(int(m.group(1)))
            rows = []
            for leaf_id in (t.partition_leaves if t else []):
                leaf = db.tables[leaf_id]
                n = len(leaf.rows)
                rows.append([str(leaf_id), str(n), str(max(1, n // 64))])
            self._send_rows(w, ["oid", "greatest", "greatest"], rows)
            return True

        m = re.search(r"FROM pg_class WHERE oid = (\d+)", norm)
        if m and "reltuples" in norm:
            t = db.tables.get(int(m.group(1)))
            n = len(t.rows) if t else 0
            self._send_rows(w, ["reltuples", "relpages"],
                            [[str(n), str(max(1, n // 64))]])
            return True

        m = re.match(r'START_REPLICATION SLOT "([^"]+)" LOGICAL '
                     r"([0-9A-Fa-f]+/[0-9A-Fa-f]+) \((.*)\)", norm)
        if m:
            await self._start_replication(sess, m.group(1), Lsn(m.group(2)),
                                          m.group(3))
            return True

        return False

    def _send_rows(self, w, names: list[str],
                   rows: list[list[str | None]]) -> None:
        w.write(_row_description(names))
        for row in rows:
            w.write(_data_row(row))
        w.write(_command_complete(f"SELECT {len(rows)}"))
        w.write(READY)

    async def _copy_out(self, sess: _Session, m: re.Match) -> None:
        w = sess.writer
        db = self.db
        col_sql, schema_name, rel_name = m.group(1), m.group(2), m.group(3)
        lo = int(m.group(4)) if m.group(4) else None
        hi = int(m.group(5)) if m.group(5) else None
        table = next((t for t in db.tables.values()
                      if t.schema.name.schema == schema_name
                      and t.schema.name.name == rel_name), None)
        if table is None:
            w.write(_error("42P01", f"relation {rel_name} does not exist"))
            w.write(READY)
            return
        snap = db.snapshots.get(sess.snapshot_id or "", None)
        rows = snap.get(table.schema.id, ([], None))[0] \
            if snap is not None else table.rows
        # apply a row filter ONLY when the COPY SQL carried its predicate
        # (the walsender applies filters at send time; the snapshot COPY
        # must spell them out — a client that forgets gets unfiltered rows
        # here, so the regression is visible to tests)
        rowfilter_text = m.group(6)
        if rowfilter_text:
            pred = next(
                (fn for (pub, tid), sql_text in db.row_filter_sql.items()
                 if " ".join(sql_text.split()).lower()
                 == " ".join(rowfilter_text.split()).lower()
                 and (fn := db.row_filters.get((pub, tid))) is not None),
                None)
            if pred is None:
                w.write(_error("42601",
                               f"fake server: unknown row filter "
                               f"{rowfilter_text!r}"))
                w.write(READY)
                return
            rows = [r for r in rows if pred(r)]
        if lo is not None:
            rows = rows[lo * 64 : hi * 64]
        wanted = [c.strip().strip('"') for c in col_sql.split(",")]
        idx = [table.schema.column_index(c) for c in wanted]
        w.write(_msg(b"H", struct.pack(">bh", 0, len(idx))
                     + b"\x00\x00" * len(idx)))
        for row in rows:
            line = encode_copy_row([row[i] for i in idx]) + b"\n"
            w.write(_msg(b"d", line))
        w.write(_msg(b"c"))
        w.write(_command_complete(f"COPY {len(rows)}"))
        w.write(READY)
        await w.drain()

    async def _start_replication(self, sess: _Session, slot_name: str,
                                 start_lsn: Lsn, opts: str) -> None:
        w = sess.writer
        db = self.db
        slot = db.slots.get(slot_name)
        if slot is None:
            w.write(_error("42704", f'slot "{slot_name}" does not exist'))
            w.write(READY)
            await w.drain()
            return
        if slot.invalidated:
            w.write(_error("55000", "can no longer get changes from "
                           "replication slot (invalidated)"))
            w.write(READY)
            await w.drain()
            return
        m = re.search(r"publication_names '([^']*)'", opts)
        publication = m.group(1) if m else ""
        pub_tables = set(db.publications.get(publication, []))
        slot.active = True
        # register with the database's chaos hook: sever_streams() must
        # cut WIRE replication sessions too, not only in-process streams
        # (otherwise TCP-backed chaos scenarios partition nothing).
        # Registration happens inside the try so an early connection drop
        # (drain raising before the loop starts) still unregisters the
        # handle and resets slot.active in the finally.
        handle = _WireStreamHandle(w)
        pos = max(start_lsn, slot.confirmed_flush)
        wal_index = 0
        reader_task = asyncio.ensure_future(
            self._read_status_updates(sess, slot))
        try:
            db.active_streams.append(handle)
            w.write(_msg(b"W", struct.pack(">bh", 0, 0)))
            await w.drain()
            while not reader_task.done():
                sent = False
                while wal_index < len(db.wal):
                    lsn, payload, tid, row = db.wal[wal_index]
                    wal_index += 1
                    # inclusive of the requested start position (see
                    # fake.py note: BEGIN lands at the prior commit's end)
                    if lsn < pos:
                        continue
                    if not self._pub_allows(payload, pub_tables):
                        continue
                    if not db.row_filter_allows(publication, tid, row):
                        continue
                    frame = pgoutput.encode_xlog_data(
                        int(lsn), int(db.current_lsn),
                        int(time.time() * 1e6), payload)
                    w.write(_msg(b"d", frame))
                    sent = True
                if sent:
                    await w.drain()
                try:
                    async with db._wal_cond:
                        await asyncio.wait_for(
                            db._wal_cond.wait(),
                            timeout=self.keepalive_interval_s)
                except asyncio.TimeoutError:
                    if slot.invalidated:
                        return
                    ka = pgoutput.encode_primary_keepalive(
                        int(db.current_lsn), int(time.time() * 1e6), True)
                    w.write(_msg(b"d", ka))
                    await w.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            slot.active = False
            if handle in db.active_streams:
                db.active_streams.remove(handle)
            if not reader_task.done():
                reader_task.cancel()
            try:
                await reader_task
            except (asyncio.CancelledError, asyncio.IncompleteReadError,
                    ConnectionResetError):
                pass

    def _pub_allows(self, payload: bytes, pub_tables: set[int]) -> bool:
        tag = payload[0:1]
        if tag in (b"I", b"U", b"D", b"R"):
            rid = int.from_bytes(payload[1:5], "big")
            return rid in pub_tables
        if tag == b"T":
            n = int.from_bytes(payload[1:5], "big")
            rids = [int.from_bytes(payload[6 + 4 * i : 10 + 4 * i], "big")
                    for i in range(n)]
            return any(r in pub_tables for r in rids)
        return True

    async def _read_status_updates(self, sess: _Session,
                                   slot) -> None:
        """Drain incoming CopyData standby status updates ('r' frames)."""
        r = sess.reader
        while True:
            header = await r.readexactly(5)
            tag = header[:1]
            (length,) = struct.unpack(">i", header[1:5])
            payload = await r.readexactly(length - 4)
            if tag == b"d" and payload[:1] == b"r":
                upd = pgoutput.decode_standby_status_update(payload)
                if upd.flushed > slot.confirmed_flush:
                    slot.confirmed_flush = upd.flushed
            elif tag in (b"c", b"X"):
                return
