"""Property-test harness: random typed values with seed replay.

Reference parity: the wall-clock-budgeted property runner with seed replay
(crates/etl/src/test_utils/property.rs:59-96) and the value-roundtrip
differential suite (tests/value_roundtrip.rs) where Postgres renders values
and the production codec parses them back. Without a Postgres in this
environment the renderers below play the oracle's rendering side: they
format values exactly as `COPY TO`/pgoutput text output does; the
differential property is CPU-decode ≡ device-decode ≡ original value.
"""

from __future__ import annotations

import datetime as dt
import random
import time
import uuid
from dataclasses import dataclass
from typing import Callable

from ..models.pgtypes import Oid


@dataclass
class GeneratedValue:
    oid: int
    text: str | None  # Postgres text rendering (None = NULL)


def _r_int(rng: random.Random, lo: int, hi: int) -> str:
    return str(rng.randint(lo, hi))


def _r_float8(rng: random.Random) -> str:
    c = rng.random()
    if c < 0.05:
        return rng.choice(["NaN", "Infinity", "-Infinity", "0"])
    if c < 0.5:
        return repr(rng.uniform(-1e6, 1e6))  # shortest roundtrip (17 sig)
    return f"{rng.uniform(-1e9, 1e9):.6f}"


def _r_numeric(rng: random.Random) -> str:
    c = rng.random()
    if c < 0.05:
        return "NaN"
    digits = rng.randint(1, 30)
    scale = rng.randint(0, min(10, digits))
    n = rng.randint(0, 10**digits - 1)
    s = str(n).rjust(scale + 1, "0")
    out = s[:-scale] + "." + s[-scale:] if scale else s
    return ("-" if rng.random() < 0.5 else "") + out


def _r_text(rng: random.Random) -> str:
    alphabet = ("abc xyz 123 äöü 日本語 emoji🎉 quote'dq\" comma, "
                "newline\ntab\tbackslash\\ ")
    n = rng.randint(0, 40)
    return "".join(rng.choice(alphabet) for _ in range(n))


def _r_date(rng: random.Random) -> str:
    d = dt.date(1, 1, 1) + dt.timedelta(days=rng.randint(0, 3_650_000))
    return d.isoformat()


def _r_time(rng: random.Random) -> str:
    t = dt.time(rng.randint(0, 23), rng.randint(0, 59), rng.randint(0, 59),
                rng.choice([0, rng.randint(0, 999_999)]))
    s = t.isoformat()
    return s


def _r_timestamp(rng: random.Random) -> str:
    return f"{_r_date(rng)} {_r_time(rng)}"


def _r_timestamptz(rng: random.Random) -> str:
    off_h = rng.randint(-12, 14)
    off = f"{'+' if off_h >= 0 else '-'}{abs(off_h):02d}"
    if rng.random() < 0.3:
        off += f":{rng.choice([0, 30, 45]):02d}"
    # clamp away from datetime range edges so UTC conversion stays valid
    d = dt.date(1000, 1, 1) + dt.timedelta(days=rng.randint(0, 2_900_000))
    return f"{d.isoformat()} {_r_time(rng)}{off}"


def _r_bytea(rng: random.Random) -> str:
    return "\\x" + bytes(rng.randint(0, 255)
                         for _ in range(rng.randint(0, 32))).hex()


def _r_uuid(rng: random.Random) -> str:
    return str(uuid.UUID(int=rng.getrandbits(128)))


def _r_json(rng: random.Random) -> str:
    import json

    def val(depth: int):
        c = rng.random()
        if depth > 2 or c < 0.3:
            return rng.choice([None, True, False, rng.randint(-1000, 1000),
                               "str"])
        if c < 0.6:
            return [val(depth + 1) for _ in range(rng.randint(0, 3))]
        return {f"k{i}": val(depth + 1) for i in range(rng.randint(0, 3))}

    return json.dumps(val(0))


def _r_int_array(rng: random.Random) -> str:
    items = [rng.choice(["NULL", str(rng.randint(-10**6, 10**6))])
             for _ in range(rng.randint(0, 8))]
    return "{" + ",".join(items) + "}"


GENERATORS: dict[int, Callable[[random.Random], str]] = {
    Oid.BOOL: lambda r: r.choice(["t", "f"]),
    Oid.INT2: lambda r: _r_int(r, -(2**15), 2**15 - 1),
    Oid.INT4: lambda r: _r_int(r, -(2**31), 2**31 - 1),
    Oid.INT8: lambda r: _r_int(r, -(2**63), 2**63 - 1),
    Oid.FLOAT8: _r_float8,
    Oid.FLOAT4: lambda r: f"{r.uniform(-1e6, 1e6):.4f}",
    Oid.NUMERIC: _r_numeric,
    Oid.TEXT: _r_text,
    Oid.DATE: _r_date,
    Oid.TIME: _r_time,
    Oid.TIMESTAMP: _r_timestamp,
    Oid.TIMESTAMPTZ: _r_timestamptz,
    Oid.BYTEA: _r_bytea,
    Oid.UUID: _r_uuid,
    Oid.JSONB: _r_json,
    Oid.INT4_ARRAY: _r_int_array,
}


def generate_value(rng: random.Random, oid: int,
                   null_rate: float = 0.1) -> GeneratedValue:
    if rng.random() < null_rate:
        return GeneratedValue(oid, None)
    return GeneratedValue(oid, GENERATORS[oid](rng))


class PropertyRunner:
    """Wall-clock-budgeted property loop with seed replay (property.rs)."""

    def __init__(self, budget_s: float = 3.0, seed: int | None = None):
        self.budget_s = budget_s
        self.base_seed = seed if seed is not None \
            else random.SystemRandom().randint(0, 2**32)
        self.cases_run = 0

    def run(self, case: Callable[[random.Random], None]) -> None:
        deadline = time.monotonic() + self.budget_s
        i = 0
        while time.monotonic() < deadline:
            seed = (self.base_seed + i) & 0xFFFFFFFF
            rng = random.Random(seed)
            try:
                case(rng)
            except BaseException as e:
                raise AssertionError(
                    f"property failed at seed {seed} (replay with "
                    f"PropertyRunner(seed={seed}))") from e
            i += 1
        self.cases_run = i
