/* pgoutput message framer — the native host hot path.
 *
 * Walks a batch of logical-replication message payloads (concatenated in one
 * buffer) and emits, for every Insert/Update/Delete, the absolute
 * offset/length/flag of each tuple field — zero-copy: field bytes are never
 * moved, the offsets point straight into the WAL payload buffer that is then
 * uploaded to the device whole.
 *
 * This replaces the per-tuple decode loop of the reference
 * (crates/etl/src/postgres/codec/event.rs) with an index-building pass;
 * the actual parsing happens on the TPU (etl_tpu/ops). Python fallback:
 * etl_tpu/native/__init__.py.
 *
 * Build: cc -O3 -shared -fPIC framer.c -o _framer.so  (see native/__init__.py)
 */

#include <stdint.h>
#include <string.h>

#define FLAG_VALUE 0
#define FLAG_NULL 1
#define FLAG_TOAST 2
#define FLAG_BINARY 3

static inline uint32_t be32(const uint8_t *p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

static inline uint16_t be16(const uint8_t *p) {
    return ((uint16_t)p[0] << 8) | (uint16_t)p[1];
}

/* Walk one TupleData at buf[pos..end); fill n_cols entries of off/len/flag.
 * Returns new pos, or -1 on malformed input. */
static int64_t walk_tuple(const uint8_t *buf, int64_t pos, int64_t end,
                          int32_t n_cols, int64_t base,
                          int32_t *off, int32_t *len, uint8_t *flag) {
    if (pos + 2 > end) return -1;
    int32_t ncols = (int32_t)be16(buf + pos);
    pos += 2;
    if (ncols != n_cols) return -1;
    for (int32_t c = 0; c < ncols; c++) {
        if (pos + 1 > end) return -1;
        uint8_t kind = buf[pos++];
        switch (kind) {
        case 'n':
            off[c] = 0; len[c] = 0; flag[c] = FLAG_NULL;
            break;
        case 'u':
            off[c] = 0; len[c] = 0; flag[c] = FLAG_TOAST;
            break;
        case 't':
        case 'b': {
            if (pos + 4 > end) return -1;
            int32_t vlen = (int32_t)be32(buf + pos);
            pos += 4;
            if (vlen < 0 || pos + vlen > end) return -1;
            off[c] = (int32_t)(pos - base);
            len[c] = vlen;
            flag[c] = kind == 't' ? FLAG_VALUE : FLAG_BINARY;
            pos += vlen;
            break;
        }
        default:
            return -1;
        }
    }
    return pos;
}

/* Frame a batch of pgoutput messages.
 *
 * Outputs (per message i):
 *   kind_out[i]   message tag byte ('I','U','D','B','C','R','T','M','O','Y'),
 *                 0 if malformed
 *   relid_out[i]  relation oid for I/U/D, else 0
 *   old_kind[i]   0 none, 'K' key tuple, 'O' full old tuple (U/D)
 *   new_/old_ arrays: [i*n_cols + c] field offset (relative to buf start),
 *                 length, flag. For D the old tuple fills the old_ arrays.
 *
 * Returns -1 if every message framed cleanly, else the index of the first
 * malformed message (framing stops there).
 */
int64_t etl_frame_pgoutput(const uint8_t *buf, int64_t buf_len,
                           const int64_t *msg_off, const int32_t *msg_len,
                           int64_t n_msgs, int32_t n_cols,
                           uint8_t *kind_out, int32_t *relid_out,
                           uint8_t *old_kind,
                           int32_t *new_off, int32_t *new_len,
                           uint8_t *new_flag, int32_t *old_off,
                           int32_t *old_len, uint8_t *old_flag) {
    for (int64_t i = 0; i < n_msgs; i++) {
        int64_t pos = msg_off[i];
        int64_t end = pos + msg_len[i];
        if (end > buf_len || msg_len[i] < 1) return i;
        uint8_t tag = buf[pos];
        kind_out[i] = tag;
        relid_out[i] = 0;
        old_kind[i] = 0;
        int32_t *noff = new_off + i * n_cols;
        int32_t *nlen = new_len + i * n_cols;
        uint8_t *nflag = new_flag + i * n_cols;
        int32_t *ooff = old_off + i * n_cols;
        int32_t *olen = old_len + i * n_cols;
        uint8_t *oflag = old_flag + i * n_cols;
        for (int32_t c = 0; c < n_cols; c++) {
            nflag[c] = FLAG_NULL; noff[c] = 0; nlen[c] = 0;
            oflag[c] = FLAG_NULL; ooff[c] = 0; olen[c] = 0;
        }
        switch (tag) {
        case 'I': {
            if (pos + 6 > end) { kind_out[i] = 0; return i; }
            relid_out[i] = (int32_t)be32(buf + pos + 1);
            if (buf[pos + 5] != 'N') { kind_out[i] = 0; return i; }
            pos = walk_tuple(buf, pos + 6, end, n_cols, 0, noff, nlen, nflag);
            if (pos < 0) { kind_out[i] = 0; return i; }
            break;
        }
        case 'U': {
            if (pos + 6 > end) { kind_out[i] = 0; return i; }
            relid_out[i] = (int32_t)be32(buf + pos + 1);
            pos += 5;
            uint8_t marker = buf[pos];
            if (marker == 'O' || marker == 'K') {
                old_kind[i] = marker;
                pos = walk_tuple(buf, pos + 1, end, n_cols, 0, ooff, olen,
                                 oflag);
                if (pos < 0 || pos + 1 > end) { kind_out[i] = 0; return i; }
                marker = buf[pos];
            }
            if (marker != 'N') { kind_out[i] = 0; return i; }
            pos = walk_tuple(buf, pos + 1, end, n_cols, 0, noff, nlen, nflag);
            if (pos < 0) { kind_out[i] = 0; return i; }
            break;
        }
        case 'D': {
            if (pos + 6 > end) { kind_out[i] = 0; return i; }
            relid_out[i] = (int32_t)be32(buf + pos + 1);
            uint8_t marker = buf[pos + 5];
            if (marker != 'O' && marker != 'K') { kind_out[i] = 0; return i; }
            old_kind[i] = marker;
            pos = walk_tuple(buf, pos + 6, end, n_cols, 0, ooff, olen, oflag);
            if (pos < 0) { kind_out[i] = 0; return i; }
            break;
        }
        default:
            /* non-row message: host decodes it (rare) */
            break;
        }
    }
    return -1;
}

/* COPY text scan: find tab/newline delimiter positions.
 * Kept for parity with the numpy scan; the numpy version is already
 * vectorized, so this exists for callers that want a single pass without
 * numpy temporaries. Returns number of delimiters written (capped at cap). */
int64_t etl_scan_copy_delims(const uint8_t *buf, int64_t n, int64_t *out,
                             int64_t cap) {
    int64_t k = 0;
    for (int64_t i = 0; i < n && k < cap; i++) {
        uint8_t b = buf[i];
        if (b == '\t' || b == '\n') out[k++] = i;
    }
    return k;
}
