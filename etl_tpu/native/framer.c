/* pgoutput message framer — the native host hot path.
 *
 * Walks a batch of logical-replication message payloads (concatenated in one
 * buffer) and emits, for every Insert/Update/Delete, the absolute
 * offset/length/flag of each tuple field — zero-copy: field bytes are never
 * moved, the offsets point straight into the WAL payload buffer that is then
 * uploaded to the device whole.
 *
 * This replaces the per-tuple decode loop of the reference
 * (crates/etl/src/postgres/codec/event.rs) with an index-building pass;
 * the actual parsing happens on the TPU (etl_tpu/ops). Python fallback:
 * etl_tpu/native/__init__.py.
 *
 * Build: cc -O3 -shared -fPIC framer.c -o _framer.so  (see native/__init__.py)
 */

#include <stdint.h>
#include <string.h>

#define FLAG_VALUE 0
#define FLAG_NULL 1
#define FLAG_TOAST 2
#define FLAG_BINARY 3

static inline uint32_t be32(const uint8_t *p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

static inline uint16_t be16(const uint8_t *p) {
    return ((uint16_t)p[0] << 8) | (uint16_t)p[1];
}

/* Walk one TupleData at buf[pos..end); fill n_cols entries of off/len/flag.
 * Returns new pos, or -1 on malformed input. */
static int64_t walk_tuple(const uint8_t *buf, int64_t pos, int64_t end,
                          int32_t n_cols, int64_t base,
                          int32_t *off, int32_t *len, uint8_t *flag) {
    if (pos + 2 > end) return -1;
    int32_t ncols = (int32_t)be16(buf + pos);
    pos += 2;
    if (ncols != n_cols) return -1;
    for (int32_t c = 0; c < ncols; c++) {
        if (pos + 1 > end) return -1;
        uint8_t kind = buf[pos++];
        switch (kind) {
        case 'n':
            off[c] = 0; len[c] = 0; flag[c] = FLAG_NULL;
            break;
        case 'u':
            off[c] = 0; len[c] = 0; flag[c] = FLAG_TOAST;
            break;
        case 't':
        case 'b': {
            if (pos + 4 > end) return -1;
            int32_t vlen = (int32_t)be32(buf + pos);
            pos += 4;
            if (vlen < 0 || pos + vlen > end) return -1;
            off[c] = (int32_t)(pos - base);
            len[c] = vlen;
            flag[c] = kind == 't' ? FLAG_VALUE : FLAG_BINARY;
            pos += vlen;
            break;
        }
        default:
            return -1;
        }
    }
    return pos;
}

/* Frame a batch of pgoutput messages.
 *
 * Outputs (per message i):
 *   kind_out[i]   message tag byte ('I','U','D','B','C','R','T','M','O','Y'),
 *                 0 if malformed
 *   relid_out[i]  relation oid for I/U/D, else 0
 *   old_kind[i]   0 none, 'K' key tuple, 'O' full old tuple (U/D)
 *   new_/old_ arrays: [i*n_cols + c] field offset (relative to buf start),
 *                 length, flag. For D the old tuple fills the old_ arrays.
 *
 * Returns -1 if every message framed cleanly, else the index of the first
 * malformed message (framing stops there).
 */
int64_t etl_frame_pgoutput(const uint8_t *buf, int64_t buf_len,
                           const int64_t *msg_off, const int32_t *msg_len,
                           int64_t n_msgs, int32_t n_cols,
                           uint8_t *kind_out, int32_t *relid_out,
                           uint8_t *old_kind,
                           int32_t *new_off, int32_t *new_len,
                           uint8_t *new_flag, int32_t *old_off,
                           int32_t *old_len, uint8_t *old_flag) {
    for (int64_t i = 0; i < n_msgs; i++) {
        int64_t pos = msg_off[i];
        int64_t end = pos + msg_len[i];
        if (end > buf_len || msg_len[i] < 1) return i;
        uint8_t tag = buf[pos];
        kind_out[i] = tag;
        relid_out[i] = 0;
        old_kind[i] = 0;
        int32_t *noff = new_off + i * n_cols;
        int32_t *nlen = new_len + i * n_cols;
        uint8_t *nflag = new_flag + i * n_cols;
        int32_t *ooff = old_off + i * n_cols;
        int32_t *olen = old_len + i * n_cols;
        uint8_t *oflag = old_flag + i * n_cols;
        for (int32_t c = 0; c < n_cols; c++) {
            nflag[c] = FLAG_NULL; noff[c] = 0; nlen[c] = 0;
            oflag[c] = FLAG_NULL; ooff[c] = 0; olen[c] = 0;
        }
        switch (tag) {
        case 'I': {
            if (pos + 6 > end) { kind_out[i] = 0; return i; }
            relid_out[i] = (int32_t)be32(buf + pos + 1);
            if (buf[pos + 5] != 'N') { kind_out[i] = 0; return i; }
            pos = walk_tuple(buf, pos + 6, end, n_cols, 0, noff, nlen, nflag);
            if (pos < 0) { kind_out[i] = 0; return i; }
            break;
        }
        case 'U': {
            if (pos + 6 > end) { kind_out[i] = 0; return i; }
            relid_out[i] = (int32_t)be32(buf + pos + 1);
            pos += 5;
            uint8_t marker = buf[pos];
            if (marker == 'O' || marker == 'K') {
                old_kind[i] = marker;
                pos = walk_tuple(buf, pos + 1, end, n_cols, 0, ooff, olen,
                                 oflag);
                if (pos < 0 || pos + 1 > end) { kind_out[i] = 0; return i; }
                marker = buf[pos];
            }
            if (marker != 'N') { kind_out[i] = 0; return i; }
            pos = walk_tuple(buf, pos + 1, end, n_cols, 0, noff, nlen, nflag);
            if (pos < 0) { kind_out[i] = 0; return i; }
            break;
        }
        case 'D': {
            if (pos + 6 > end) { kind_out[i] = 0; return i; }
            relid_out[i] = (int32_t)be32(buf + pos + 1);
            uint8_t marker = buf[pos + 5];
            if (marker != 'O' && marker != 'K') { kind_out[i] = 0; return i; }
            old_kind[i] = marker;
            pos = walk_tuple(buf, pos + 6, end, n_cols, 0, ooff, olen, oflag);
            if (pos < 0) { kind_out[i] = 0; return i; }
            break;
        }
        default:
            /* non-row message: host decodes it (rare) */
            break;
        }
    }
    return -1;
}

/* Pack dense-column field bytes into the device byte matrix.
 *
 * bmat[r, w_off(c)..w_off(c)+min(len, width)) = field bytes, zero elsewhere;
 * lens_out[r*n_dense + j] = min(len, 255, width). The engine uploads bmat +
 * lens; this replaces a per-column numpy gather (one pass, cache-friendly).
 * bmat must be zeroed by the caller (numpy zeros) or dirty regions beyond
 * lens are never read by the device program anyway — we still zero pad up
 * to width for deterministic device inputs. */
void etl_pack_bmat(const uint8_t *data, int64_t data_len,
                   const int32_t *offsets, const int32_t *lengths,
                   int64_t n_rows, int32_t n_cols, const int32_t *col_idx,
                   const int32_t *widths, int32_t n_dense, uint8_t *bmat,
                   int32_t total_w, uint8_t *lens_out) {
    /* per-column output offsets — defensive against caller mismatch: a C
     * entry point fed from a dynamic language must never write past the
     * bmat row stride even if widths[] disagrees with total_w (found by
     * scripts/sanitize_framer.py's adversarial hammer) */
    int32_t w_off[256];
    int32_t acc = 0;
    if (n_dense > 256) n_dense = 256;
    for (int32_t j = 0; j < n_dense; j++) {
        w_off[j] = acc;
        acc += widths[j] > 0 ? widths[j] : 0;
    }
    for (int64_t r = 0; r < n_rows; r++) {
        const int32_t *row_off = offsets + r * n_cols;
        const int32_t *row_len = lengths + r * n_cols;
        uint8_t *out_row = bmat + r * total_w;
        for (int32_t j = 0; j < n_dense; j++) {
            int32_t c = col_idx[j];
            int32_t w = widths[j];
            if (w < 0) w = 0;
            if (w_off[j] >= total_w) {
                /* clamp fired: zero the length so the numpy-empty
                 * lens buffer never leaks uninitialized bytes to the
                 * device decode path */
                lens_out[r * n_dense + j] = 0;
                continue;
            }
            if (w > total_w - w_off[j]) w = total_w - w_off[j];
            int32_t len = row_len[c];
            if (len < 0) len = 0;
            if (len > w) len = w;
            int64_t off = row_off[c];
            if (off < 0 || off + len > data_len) len = 0;
            uint8_t *dst = out_row + w_off[j];
            const uint8_t *src = data + off;
            for (int32_t k = 0; k < len; k++) dst[k] = src[k];
            for (int32_t k = len; k < w; k++) dst[k] = 0;
            lens_out[r * n_dense + j] = (uint8_t)(len > 255 ? 255 : len);
        }
    }
}

/* Gather one string column into Arrow layout: contiguous values + int32
 * offsets[n_rows+1]. valid[r]==0 rows contribute zero bytes. Returns total
 * bytes written, or -1 if it would exceed cap. */
int64_t etl_gather_string(const uint8_t *data, int64_t data_len,
                          const int32_t *offsets, const int32_t *lengths,
                          const uint8_t *valid, int64_t n_rows,
                          int32_t n_cols, int32_t col,
                          int32_t *arrow_offsets, uint8_t *values,
                          int64_t cap) {
    int64_t pos = 0;
    arrow_offsets[0] = 0;
    for (int64_t r = 0; r < n_rows; r++) {
        if (valid[r]) {
            int32_t len = lengths[r * n_cols + col];
            int64_t off = offsets[r * n_cols + col];
            if (len < 0 || off < 0 || off + len > data_len) len = 0;
            if (pos + len > cap) return -1;
            const uint8_t *src = data + off;
            uint8_t *dst = values + pos;
            for (int32_t k = 0; k < len; k++) dst[k] = src[k];
            pos += len;
        }
        arrow_offsets[r + 1] = (int32_t)pos;
    }
    return pos;
}

/* Nibble-packed variant of etl_pack_bmat: two symbols per byte.
 *
 * Symbol alphabet (4 bits): 0-9 = digits, 10 '-', 11 '+', 12 '.', 13 ':',
 * 14 ' ', 15 = pad. Covers int/float(fixed)/date/time/timestamp text;
 * any other byte (e.g. 'e' exponents, NaN/Infinity) marks the row in
 * bad_rows for the CPU oracle. Halves the host→device transfer — the
 * binding resource on a tunnel/PCIe-attached accelerator.
 * widths[] must all be even; bmat has sum(widths)/2 bytes per row. */
void etl_pack_bmat_nibble(const uint8_t *data, int64_t data_len,
                          const int32_t *offsets, const int32_t *lengths,
                          int64_t n_rows, int32_t n_cols,
                          const int32_t *col_idx, const int32_t *widths,
                          int32_t n_dense, uint8_t *bmat, int32_t packed_w,
                          uint8_t *lens_out, uint8_t *bad_rows) {
    static uint8_t code_of[256];
    static int init = 0;
    if (!init) {
        for (int i = 0; i < 256; i++) code_of[i] = 0xFF;
        for (int d = 0; d < 10; d++) code_of['0' + d] = (uint8_t)d;
        code_of['-'] = 10; code_of['+'] = 11; code_of['.'] = 12;
        code_of[':'] = 13; code_of[' '] = 14;
        init = 1;
    }
    int32_t w_off[256];
    int32_t acc = 0;
    if (n_dense > 256) n_dense = 256;
    for (int32_t j = 0; j < n_dense; j++) {
        w_off[j] = acc;
        acc += widths[j] > 0 ? widths[j] / 2 : 0;
    }
    for (int64_t r = 0; r < n_rows; r++) {
        const int32_t *row_off = offsets + r * n_cols;
        const int32_t *row_len = lengths + r * n_cols;
        uint8_t *out_row = bmat + r * packed_w;
        uint8_t bad = 0;
        for (int32_t j = 0; j < n_dense; j++) {
            int32_t c = col_idx[j];
            int32_t w = widths[j];
            if (w < 0) w = 0;
            /* same caller-mismatch defense as etl_pack_bmat, in packed
             * (w/2) units */
            if (w_off[j] >= packed_w) {
                lens_out[r * n_dense + j] = 0;
                continue;
            }
            if (w / 2 > packed_w - w_off[j]) w = (packed_w - w_off[j]) * 2;
            int32_t len = row_len[c];
            if (len < 0) len = 0;
            if (len > w) len = w;
            int64_t off = row_off[c];
            if (off < 0 || off + len > data_len) len = 0;
            const uint8_t *src = data + off;
            uint8_t *dst = out_row + w_off[j];
            /* PLANAR layout: byte k holds symbol k in the high nibble and
             * symbol k + w/2 in the low nibble — the device reassembles
             * with a lane concatenation (interleave reshapes don't lower
             * under Mosaic). */
            int32_t half = w / 2;
            for (int32_t k = 0; k < half; k++) {
                uint8_t a = 0x0F, b = 0x0F;
                if (k < len) {
                    a = code_of[src[k]];
                    bad |= (uint8_t)(a >> 7);
                }
                int32_t k2 = k + half;
                if (k2 < len) {
                    b = code_of[src[k2]];
                    bad |= (uint8_t)(b >> 7);
                }
                dst[k] = (uint8_t)((a << 4) | (b & 0x0F));
            }
            lens_out[r * n_dense + j] = (uint8_t)(len > 255 ? 255 : len);
        }
        bad_rows[r] = bad ? 1 : 0;
    }
}
