"""Native host components: the pgoutput framer (C, via ctypes).

Builds `framer.c` with the system compiler on first import (cached as
`_framer-<hash>.so`); falls back to a pure-Python walker with identical
outputs when no compiler is available. `frame_pgoutput` is the entry point;
see ops/wal.py for the staging layer that consumes it.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
from pathlib import Path

import numpy as np

_DIR = Path(__file__).resolve().parent

FLAG_VALUE, FLAG_NULL, FLAG_TOAST, FLAG_BINARY = 0, 1, 2, 3

_lib = None
_build_error: str | None = None


def _load() -> ctypes.CDLL | None:
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    src = _DIR / "framer.c"
    tag = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
    # sanitizer harness hook (scripts/sanitize_framer.py): point the
    # loader at a prebuilt instrumented .so instead of the -O3 build
    override = os.environ.get("ETL_NATIVE_FRAMER_SO")
    so = Path(override) if override else _DIR / f"_framer-{tag}.so"
    try:
        if not so.exists():
            if override:
                raise FileNotFoundError(override)
            cc = os.environ.get("CC", "cc")
            subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", str(src), "-o", str(so)],
                check=True, capture_output=True, timeout=120)
        lib = ctypes.CDLL(str(so))
        lib.etl_frame_pgoutput.restype = ctypes.c_int64
        lib.etl_pack_bmat.restype = None
        lib.etl_pack_bmat.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,  # data, data_len
            ctypes.c_void_p, ctypes.c_void_p,  # offsets, lengths [R,C]
            ctypes.c_int64, ctypes.c_int32,  # n_rows, n_cols
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,  # cols,widths,n
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,  # bmat,tw,lens
        ]
        lib.etl_pack_bmat_nibble.restype = None
        lib.etl_pack_bmat_nibble.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_void_p,  # bad_rows
        ]
        lib.etl_gather_string.restype = ctypes.c_int64
        lib.etl_gather_string.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,  # off,len,valid
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,  # R, C, col
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,  # aoff,vals,cap
        ]
        lib.etl_frame_pgoutput.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,  # buf, buf_len
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,  # msg_off/len/n
            ctypes.c_int32,  # n_cols
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,  # kind/relid/oldkind
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,  # new off/len/flag
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,  # old off/len/flag
        ]
        _lib = lib
    except Exception as e:  # pragma: no cover - depends on toolchain
        _build_error = f"{type(e).__name__}: {e}"
        _lib = None
    return _lib


def native_available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray) -> ctypes.c_void_p:
    return a.ctypes.data_as(ctypes.c_void_p)


class FramedBatch:
    """Output of the framer over n messages (see framer.c doc comment)."""

    __slots__ = ("buf", "kind", "relid", "old_kind", "new_off", "new_len",
                 "new_flag", "old_off", "old_len", "old_flag", "n_msgs")

    def __init__(self, buf: np.ndarray, n_msgs: int, n_cols: int):
        self.buf = buf
        self.n_msgs = n_msgs
        self.kind = np.zeros(n_msgs, dtype=np.uint8)
        self.relid = np.zeros(n_msgs, dtype=np.int32)
        self.old_kind = np.zeros(n_msgs, dtype=np.uint8)
        shape = (n_msgs, n_cols)
        self.new_off = np.zeros(shape, dtype=np.int32)
        self.new_len = np.zeros(shape, dtype=np.int32)
        self.new_flag = np.full(shape, FLAG_NULL, dtype=np.uint8)
        self.old_off = np.zeros(shape, dtype=np.int32)
        self.old_len = np.zeros(shape, dtype=np.int32)
        self.old_flag = np.full(shape, FLAG_NULL, dtype=np.uint8)


def frame_pgoutput(buf: bytes | np.ndarray, msg_off: np.ndarray,
                   msg_len: np.ndarray, n_cols: int) -> tuple[FramedBatch, int]:
    """Frame `len(msg_off)` pgoutput messages inside `buf`.

    Returns (framed, first_bad_index) — first_bad_index is -1 when every
    message framed cleanly; otherwise framing stopped there and the caller
    falls back to the CPU decoder for the remainder.
    """
    data = np.frombuffer(buf, dtype=np.uint8) if isinstance(buf, (bytes, bytearray)) \
        else np.ascontiguousarray(buf, dtype=np.uint8)
    msg_off = np.ascontiguousarray(msg_off, dtype=np.int64)
    msg_len = np.ascontiguousarray(msg_len, dtype=np.int32)
    n = len(msg_off)
    out = FramedBatch(data, n, n_cols)
    lib = _load()
    if lib is not None:
        p = _ptr
        bad = lib.etl_frame_pgoutput(
            p(data), len(data), p(msg_off), p(msg_len), n, n_cols,
            p(out.kind), p(out.relid), p(out.old_kind),
            p(out.new_off), p(out.new_len), p(out.new_flag),
            p(out.old_off), p(out.old_len), p(out.old_flag))
        return out, int(bad)
    return _frame_py(data, msg_off, msg_len, n_cols, out)


def _frame_py(data: np.ndarray, msg_off: np.ndarray, msg_len: np.ndarray,
              n_cols: int, out: FramedBatch) -> tuple[FramedBatch, int]:
    """Pure-Python fallback with identical semantics to framer.c."""
    import struct

    buf = data.tobytes()

    def walk(pos: int, end: int, row: int, off, ln, fl) -> int:
        if pos + 2 > end:
            return -1
        ncols = struct.unpack_from(">h", buf, pos)[0]
        pos += 2
        if ncols != n_cols:
            return -1
        for c in range(ncols):
            if pos + 1 > end:
                return -1
            k = buf[pos]
            pos += 1
            if k == ord("n"):
                fl[row, c] = FLAG_NULL
            elif k == ord("u"):
                fl[row, c] = FLAG_TOAST
            elif k in (ord("t"), ord("b")):
                if pos + 4 > end:
                    return -1
                vlen = struct.unpack_from(">i", buf, pos)[0]
                pos += 4
                if vlen < 0 or pos + vlen > end:
                    return -1
                off[row, c] = pos
                ln[row, c] = vlen
                fl[row, c] = FLAG_VALUE if k == ord("t") else FLAG_BINARY
                pos += vlen
            else:
                return -1
        return pos

    for i in range(len(msg_off)):
        pos = int(msg_off[i])
        end = pos + int(msg_len[i])
        if end > len(buf) or msg_len[i] < 1:
            return out, i
        tag = buf[pos]
        out.kind[i] = tag
        if tag == ord("I"):
            if pos + 6 > end or buf[pos + 5] != ord("N"):
                out.kind[i] = 0
                return out, i
            out.relid[i] = struct.unpack_from(">I", buf, pos + 1)[0]
            if walk(pos + 6, end, i, out.new_off, out.new_len,
                    out.new_flag) < 0:
                out.kind[i] = 0
                return out, i
        elif tag == ord("U"):
            if pos + 6 > end:
                out.kind[i] = 0
                return out, i
            out.relid[i] = struct.unpack_from(">I", buf, pos + 1)[0]
            pos += 5
            marker = buf[pos]
            if marker in (ord("O"), ord("K")):
                out.old_kind[i] = marker
                pos = walk(pos + 1, end, i, out.old_off, out.old_len,
                           out.old_flag)
                if pos < 0 or pos + 1 > end:
                    out.kind[i] = 0
                    return out, i
                marker = buf[pos]
            if marker != ord("N"):
                out.kind[i] = 0
                return out, i
            if walk(pos + 1, end, i, out.new_off, out.new_len,
                    out.new_flag) < 0:
                out.kind[i] = 0
                return out, i
        elif tag == ord("D"):
            if pos + 6 > end or buf[pos + 5] not in (ord("O"), ord("K")):
                out.kind[i] = 0
                return out, i
            out.relid[i] = struct.unpack_from(">I", buf, pos + 1)[0]
            out.old_kind[i] = buf[pos + 5]
            if walk(pos + 6, end, i, out.old_off, out.old_len,
                    out.old_flag) < 0:
                out.kind[i] = 0
                return out, i
    return out, -1


def pack_bmat(data, offsets, lengths, col_idx, widths, bmat, lens_out) -> bool:
    """C fast path for the device byte-matrix pack; False if unavailable."""
    lib = _load()
    if lib is None or len(col_idx) > 256:
        return False
    p = _ptr
    R, C = offsets.shape
    cols = np.ascontiguousarray(col_idx, dtype=np.int32)
    ws = np.ascontiguousarray(widths, dtype=np.int32)
    lib.etl_pack_bmat(p(data), len(data), p(offsets), p(lengths), R, C,
                      p(cols), p(ws), len(cols), p(bmat), bmat.shape[1],
                      p(lens_out))
    return True


def gather_string(data, offsets, lengths, valid, col,
                  arrow_offsets, values) -> int:
    """C fast path for Arrow string gather; -2 if unavailable."""
    lib = _load()
    if lib is None:
        return -2
    p = _ptr
    R, C = offsets.shape
    return lib.etl_gather_string(p(data), len(data), p(offsets), p(lengths),
                                 p(valid), R, C, col, p(arrow_offsets),
                                 p(values), len(values))


def pack_bmat_nibble(data, offsets, lengths, col_idx, widths, bmat,
                     lens_out, bad_rows) -> bool:
    """C nibble pack (two symbols/byte); False if unavailable."""
    lib = _load()
    if lib is None or len(col_idx) > 256:
        return False
    p = _ptr
    R, C = offsets.shape
    cols = np.ascontiguousarray(col_idx, dtype=np.int32)
    ws = np.ascontiguousarray(widths, dtype=np.int32)
    lib.etl_pack_bmat_nibble(p(data), len(data), p(offsets), p(lengths), R, C,
                             p(cols), p(ws), len(cols), p(bmat),
                             bmat.shape[1], p(lens_out), p(bad_rows))
    return True
