"""State/schema stores."""

from .base import (DestinationTableMetadata, PipelineStore, SchemaStore,
                   StateStore)
from .memory import MemoryStore, NotifyingStore
from .sql import PostgresStore, SqliteStore
