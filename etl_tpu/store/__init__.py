"""State/schema stores."""

from .base import (DeadLetterEntry, DestinationTableMetadata, PipelineStore,
                   QuarantineRecord, SchemaStore, StateStore)
from .memory import MemoryStore, NotifyingStore
from .sql import PostgresStore, SqliteStore
