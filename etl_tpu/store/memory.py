"""In-memory store + the notifying test wrapper.

Reference parity: `MemoryStore` (crates/etl/src/store/both/memory.rs) and
`NotifyingStore` (test_utils/notifying_store.rs:27-70) — tests await
specific state transitions instead of sleeping; this is load-bearing for
deterministic tests (SURVEY §4 fixtures note).
"""

from __future__ import annotations

import asyncio
from collections import defaultdict

from ..chaos import failpoints
from ..models.errors import ErrorKind, EtlError
from ..models.lsn import Lsn
from ..models.schema import ReplicatedTableSchema, SnapshotId, TableId
from ..models.table_state import TableState, TableStateType
from ..sharding.shardmap import ShardAssignment
from .base import (DeadLetterEntry, DestinationTableMetadata, PipelineStore,
                   ProgressKey, QuarantineRecord)


class MemoryStore(PipelineStore):
    def __init__(self) -> None:
        self._states: dict[TableId, TableState] = {}
        self._progress: dict[ProgressKey, Lsn] = {}
        self._schemas: dict[TableId, list[tuple[SnapshotId, ReplicatedTableSchema]]] = \
            defaultdict(list)  # sorted by snapshot id
        self._dest_meta: dict[TableId, DestinationTableMetadata] = {}
        self._shard_assignment: ShardAssignment | None = None
        self._autoscale_journal: dict | None = None
        self._fleet_spec: dict | None = None
        self._fleet_journals: dict[int, dict] = {}
        # dead-letter surface: WAL-coordinate key -> entry (the keyed
        # upsert that makes crash-era re-appends idempotent)
        self._dead_letters: dict[tuple, DeadLetterEntry] = {}
        self._next_dlq_id = 1
        self._quarantine: dict[TableId, QuarantineRecord] = {}

    # -- StateStore ----------------------------------------------------------

    async def get_table_states(self) -> dict[TableId, TableState]:
        return dict(self._states)

    async def get_table_state(self, table_id: TableId) -> TableState | None:
        return self._states.get(table_id)

    async def update_table_state(self, table_id: TableId,
                                 state: TableState) -> None:
        if not state.is_persistent:
            raise EtlError(ErrorKind.STORE_SERIALIZATION_FAILED,
                           f"{state.type.value} is memory-only, not storable")
        failpoints.fail_point(failpoints.STORE_STATE_COMMIT)
        await failpoints.stall_point(failpoints.STORE_STATE_COMMIT)
        self._states[table_id] = state

    async def delete_table_state(self, table_id: TableId) -> None:
        self._states.pop(table_id, None)

    async def get_durable_progress(self, key: ProgressKey) -> Lsn | None:
        return self._progress.get(key)

    async def update_durable_progress(self, key: ProgressKey,
                                      lsn: Lsn) -> bool:
        failpoints.fail_point(failpoints.STORE_PROGRESS_COMMIT)
        await failpoints.stall_point(failpoints.STORE_PROGRESS_COMMIT)
        cur = self._progress.get(key)
        if cur is not None and lsn < cur:
            return False
        self._progress[key] = lsn
        return True

    async def delete_durable_progress(self, key: ProgressKey) -> None:
        self._progress.pop(key, None)

    async def get_destination_metadata(
            self, table_id: TableId) -> DestinationTableMetadata | None:
        return self._dest_meta.get(table_id)

    async def update_destination_metadata(
            self, meta: DestinationTableMetadata) -> None:
        self._dest_meta[meta.table_id] = meta

    async def delete_destination_metadata(self, table_id: TableId) -> None:
        self._dest_meta.pop(table_id, None)

    # -- shard assignment ----------------------------------------------------

    async def get_shard_assignment(self) -> ShardAssignment | None:
        return self._shard_assignment

    async def update_shard_assignment(self,
                                      assignment: ShardAssignment) -> None:
        cur = self._shard_assignment
        if cur is not None and assignment.epoch < cur.epoch:
            raise EtlError(
                ErrorKind.PROGRESS_REGRESSION,
                f"shard assignment epoch regression: {cur.epoch} -> "
                f"{assignment.epoch}")
        failpoints.fail_point(failpoints.STORE_SHARD_COMMIT)
        await failpoints.stall_point(failpoints.STORE_SHARD_COMMIT)
        self._shard_assignment = assignment

    # -- autoscale decision journal ------------------------------------------

    async def get_autoscale_journal(self) -> dict | None:
        return self._autoscale_journal

    async def update_autoscale_journal(self, journal: dict) -> None:
        cur = self._autoscale_journal
        if cur is not None and int(journal.get("next_id", 0)) \
                < int(cur.get("next_id", 0)):
            raise EtlError(
                ErrorKind.PROGRESS_REGRESSION,
                f"autoscale journal id regression: {cur.get('next_id')} "
                f"-> {journal.get('next_id')}")
        failpoints.fail_point(failpoints.STORE_AUTOSCALE_COMMIT)
        await failpoints.stall_point(failpoints.STORE_AUTOSCALE_COMMIT)
        self._autoscale_journal = journal

    # -- fleet spec / actuation journals -------------------------------------

    async def get_fleet_spec(self) -> dict | None:
        return self._fleet_spec

    async def update_fleet_spec(self, spec: dict) -> None:
        cur = self._fleet_spec
        if cur is not None and int(spec.get("spec_version", 0)) \
                < int(cur.get("spec_version", 0)):
            raise EtlError(
                ErrorKind.PROGRESS_REGRESSION,
                f"fleet spec version regression: {cur.get('spec_version')} "
                f"-> {spec.get('spec_version')}")
        failpoints.fail_point(failpoints.STORE_FLEET_COMMIT)
        await failpoints.stall_point(failpoints.STORE_FLEET_COMMIT)
        self._fleet_spec = spec

    async def get_fleet_journal(self, pipeline_id: int) -> dict | None:
        return self._fleet_journals.get(int(pipeline_id))

    async def get_fleet_journals(self) -> dict[int, dict]:
        return dict(self._fleet_journals)

    async def update_fleet_journal(self, pipeline_id: int,
                                   journal: dict) -> None:
        cur = self._fleet_journals.get(int(pipeline_id))
        if cur is not None and int(journal.get("next_id", 0)) \
                < int(cur.get("next_id", 0)):
            raise EtlError(
                ErrorKind.PROGRESS_REGRESSION,
                f"fleet journal id regression for pipeline {pipeline_id}: "
                f"{cur.get('next_id')} -> {journal.get('next_id')}")
        failpoints.fail_point(failpoints.STORE_FLEET_COMMIT)
        await failpoints.stall_point(failpoints.STORE_FLEET_COMMIT)
        self._fleet_journals[int(pipeline_id)] = journal

    # -- dead-letter / quarantine surface ------------------------------------

    async def append_dead_letters(self, entries) -> list[int]:
        from dataclasses import replace

        import time

        failpoints.fail_point(failpoints.STORE_DLQ_COMMIT)
        await failpoints.stall_point(failpoints.STORE_DLQ_COMMIT)
        now = int(time.time())  # store-stamped compaction clock
        ids = []
        for e in entries:
            cur = self._dead_letters.get(e.key())
            if cur is not None:
                # idempotent keyed upsert: a re-streamed batch that
                # re-isolates the same poison row accumulates attempts
                # instead of duplicating the entry
                merged = replace(cur, attempts=cur.attempts + e.attempts,
                                 error_kind=e.error_kind,
                                 detail=e.detail or cur.detail,
                                 columns=e.columns or cur.columns,
                                 updated_at=now)
                self._dead_letters[e.key()] = merged
                ids.append(merged.entry_id)
                continue
            stored = replace(e, entry_id=self._next_dlq_id,
                             updated_at=now)
            self._next_dlq_id += 1
            self._dead_letters[stored.key()] = stored
            ids.append(stored.entry_id)
        return ids

    async def list_dead_letters(self, table_id=None,
                                status="dead") -> list[DeadLetterEntry]:
        out = [e for e in self._dead_letters.values()
               if (table_id is None or e.table_id == table_id)
               and (status is None or e.status == status)]
        out.sort(key=lambda e: e.entry_id)
        return out

    async def get_dead_letter(self, entry_id: int) -> DeadLetterEntry | None:
        for e in self._dead_letters.values():
            if e.entry_id == entry_id:
                return e
        return None

    async def set_dead_letter_status(self, entry_id: int,
                                     status: str) -> None:
        from dataclasses import replace

        import time

        for k, e in self._dead_letters.items():
            if e.entry_id == entry_id:
                self._dead_letters[k] = replace(e, status=status,
                                                updated_at=int(time.time()))
                return
        raise EtlError(ErrorKind.STATE_STORE_FAILED,
                       f"no dead-letter entry {entry_id}")

    async def purge_dead_letters(self, older_than_s, statuses=(
            "replayed", "discarded")) -> int:
        import time

        cutoff = int(time.time() - older_than_s)
        doomed = [k for k, e in self._dead_letters.items()
                  if e.status in statuses and e.updated_at < cutoff]
        for k in doomed:
            del self._dead_letters[k]
        return len(doomed)

    async def get_quarantined_tables(self) -> dict[TableId, QuarantineRecord]:
        return dict(self._quarantine)

    async def set_table_quarantine(self, table_id: TableId,
                                   record: QuarantineRecord | None) -> None:
        failpoints.fail_point(failpoints.STORE_DLQ_COMMIT)
        await failpoints.stall_point(failpoints.STORE_DLQ_COMMIT)
        if record is None:
            self._quarantine.pop(table_id, None)
        else:
            self._quarantine[table_id] = record

    # -- SchemaStore ---------------------------------------------------------

    async def store_table_schema(self, schema: ReplicatedTableSchema,
                                 snapshot_id: SnapshotId) -> None:
        failpoints.fail_point(failpoints.STORE_SCHEMA_COMMIT)
        await failpoints.stall_point(failpoints.STORE_SCHEMA_COMMIT)
        versions = self._schemas[schema.id]
        versions[:] = [(s, v) for s, v in versions if s != snapshot_id]
        versions.append((snapshot_id, schema))
        versions.sort(key=lambda p: p[0])

    async def get_table_schema(
            self, table_id: TableId,
            at_snapshot: SnapshotId | None = None
    ) -> ReplicatedTableSchema | None:
        versions = self._schemas.get(table_id)
        if not versions:
            return None
        if at_snapshot is None:
            return versions[-1][1]
        best = None
        for s, v in versions:
            if s <= at_snapshot:
                best = v
            else:
                break
        return best

    async def get_schema_versions(self, table_id: TableId) -> list[SnapshotId]:
        return [s for s, _ in self._schemas.get(table_id, [])]

    async def get_table_ids_with_schemas(self) -> list[TableId]:
        return [tid for tid, v in self._schemas.items() if v]

    async def prune_schema_versions(self, table_id: TableId,
                                    older_than: SnapshotId) -> int:
        versions = self._schemas.get(table_id)
        if not versions:
            return 0
        keep_from = 0
        for i, (s, _) in enumerate(versions):
            if s <= older_than:
                keep_from = i
        removed = keep_from
        versions[:] = versions[keep_from:]
        return removed

    async def delete_table_schemas(self, table_id: TableId) -> None:
        self._schemas.pop(table_id, None)


class NotifyingStore(MemoryStore):
    """MemoryStore that lets tests await specific table-state transitions
    (reference NotifyingStore, notifying_store.rs:27-70)."""

    def __init__(self) -> None:
        super().__init__()
        self._waiters: list[tuple] = []  # (table_id, state_type, future)
        self.state_history: list[tuple[TableId, TableState]] = []

    async def update_table_state(self, table_id: TableId,
                                 state: TableState) -> None:
        await super().update_table_state(table_id, state)
        self.state_history.append((table_id, state))
        self._notify(table_id, state)

    def _notify(self, table_id: TableId, state: TableState) -> None:
        still = []
        for tid, st, fut in self._waiters:
            if tid == table_id and st is state.type and not fut.done():
                fut.set_result(state)
            elif not fut.done():
                still.append((tid, st, fut))
        self._waiters = still

    def notify_on(self, table_id: TableId,
                  state_type: TableStateType) -> "asyncio.Future[TableState]":
        """Future resolving when the table ENTERS the given state (resolves
        immediately if already there — no missed-wakeup, reference
        worker.rs:211-264 subscribe-under-lock)."""
        fut: asyncio.Future[TableState] = \
            asyncio.get_event_loop().create_future()
        cur = self._states.get(table_id)
        if cur is not None and cur.type is state_type:
            fut.set_result(cur)
        else:
            self._waiters.append((table_id, state_type, fut))
        return fut
