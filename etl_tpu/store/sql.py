"""Durable SQL store: the reference `etl` schema on sqlite or Postgres.

Reference parity: `PostgresStore` (crates/etl/src/store/both/postgres.rs,
829 LoC) against the `etl` schema
(migrations/postgres_store/20250827000000_base.up.sql +
20260511090000_replication_progress.up.sql):

  - `replication_state`: per-table state rows with a prev-pointer history
    chain and a partial unique `is_current` index;
  - `table_schemas`: versioned by snapshot id;
  - `table_mappings`: destination metadata;
  - `replication_progress`: monotonic per-worker durable LSN.

Cache-first reads like the reference (postgres.rs): all lookups hit an
in-memory cache warmed at `connect()`; writes go through to the database
synchronously.

Dialects share ONE statement set (`_SqlStoreBase`), so the Postgres path
cannot drift from the sqlite path:
  - `SqliteStore`: file-backed, `?` placeholders, synchronous sqlite3;
  - `PostgresStore`: executes the same statements over the from-scratch
    wire client (`postgres/wire.py`) via the EXTENDED protocol
    (Parse/Bind/Execute, server-side parameter binding) — no driver
    dependency, same connection stack the replication client uses.
"""

from __future__ import annotations

import abc
import json
import re
import sqlite3
import time
from dataclasses import dataclass
from pathlib import Path

from ..chaos import failpoints
from ..models.errors import ErrorKind, EtlError
from ..models.lsn import Lsn
from ..models.schema import ReplicatedTableSchema, SnapshotId, TableId
from ..models.table_state import TableState
from ..sharding.shardmap import ShardAssignment
from .base import (DeadLetterEntry, DestinationTableMetadata, PipelineStore,
                   ProgressKey, QuarantineRecord)

MIGRATIONS: list[tuple[str, str]] = [
    ("20250827000000_base", """
CREATE TABLE IF NOT EXISTS etl_replication_state (
    id {bigserial} PRIMARY KEY,
    pipeline_id BIGINT NOT NULL,
    table_id BIGINT NOT NULL,
    state TEXT NOT NULL,
    prev BIGINT,
    is_current INTEGER NOT NULL DEFAULT 1
);
CREATE UNIQUE INDEX IF NOT EXISTS etl_replication_state_current
    ON etl_replication_state (pipeline_id, table_id) WHERE is_current = 1;
CREATE TABLE IF NOT EXISTS etl_table_schemas (
    pipeline_id BIGINT NOT NULL,
    table_id BIGINT NOT NULL,
    snapshot_id BIGINT NOT NULL,
    schema_json TEXT NOT NULL,
    PRIMARY KEY (pipeline_id, table_id, snapshot_id)
);
CREATE TABLE IF NOT EXISTS etl_table_mappings (
    pipeline_id BIGINT NOT NULL,
    table_id BIGINT NOT NULL,
    destination_table_name TEXT NOT NULL,
    generation BIGINT NOT NULL DEFAULT 0,
    PRIMARY KEY (pipeline_id, table_id)
);
"""),
    ("20260511090000_replication_progress", """
CREATE TABLE IF NOT EXISTS etl_replication_progress (
    pipeline_id BIGINT NOT NULL,
    progress_key TEXT NOT NULL,
    lsn BIGINT NOT NULL,
    PRIMARY KEY (pipeline_id, progress_key)
);
"""),
    ("20260803000000_shard_assignment", """
CREATE TABLE IF NOT EXISTS etl_shard_assignment (
    pipeline_id BIGINT NOT NULL,
    assignment_json TEXT NOT NULL,
    PRIMARY KEY (pipeline_id)
);
"""),
    ("20260804000000_autoscale_journal", """
CREATE TABLE IF NOT EXISTS etl_autoscale_journal (
    pipeline_id BIGINT NOT NULL,
    journal_json TEXT NOT NULL,
    PRIMARY KEY (pipeline_id)
);
"""),
    ("20260805000000_dead_letter", """
CREATE TABLE IF NOT EXISTS etl_dead_letter (
    id {bigserial} PRIMARY KEY,
    pipeline_id BIGINT NOT NULL,
    table_id BIGINT NOT NULL,
    commit_lsn BIGINT NOT NULL,
    tx_ordinal BIGINT NOT NULL,
    change_type BIGINT NOT NULL,
    payload TEXT NOT NULL,
    error_kind TEXT NOT NULL,
    detail TEXT NOT NULL,
    attempts BIGINT NOT NULL DEFAULT 1,
    status TEXT NOT NULL DEFAULT 'dead'
);
CREATE UNIQUE INDEX IF NOT EXISTS etl_dead_letter_key
    ON etl_dead_letter (pipeline_id, table_id, commit_lsn, tx_ordinal,
                        change_type);
CREATE TABLE IF NOT EXISTS etl_quarantine (
    pipeline_id BIGINT NOT NULL,
    table_id BIGINT NOT NULL,
    record_json TEXT NOT NULL,
    PRIMARY KEY (pipeline_id, table_id)
);
"""),
    # per-column poison attribution + the TTL-compaction clock. One
    # ADD COLUMN per statement: sqlite's ALTER TABLE accepts exactly
    # one action, and the runner splits on ";" anyway.
    ("20260806000000_dead_letter_ttl", """
ALTER TABLE etl_dead_letter ADD COLUMN poison_columns TEXT NOT NULL DEFAULT '';
ALTER TABLE etl_dead_letter ADD COLUMN updated_at BIGINT NOT NULL DEFAULT 0;
"""),
    # fleet control plane (docs/fleet.md): one desired-state spec row
    # per fleet (keyed by the coordinator's pipeline_id) and one
    # actuation journal row PER PIPELINE so concurrent rolls never
    # contend on a single document
    ("20260807000000_fleet", """
CREATE TABLE IF NOT EXISTS etl_fleet_spec (
    pipeline_id BIGINT NOT NULL,
    spec_json TEXT NOT NULL,
    PRIMARY KEY (pipeline_id)
);
CREATE TABLE IF NOT EXISTS etl_fleet_journal (
    pipeline_id BIGINT NOT NULL,
    member_id BIGINT NOT NULL,
    journal_json TEXT NOT NULL,
    PRIMARY KEY (pipeline_id, member_id)
);
"""),
]


def _opt_int(v) -> int | None:
    return None if v is None else int(v)


class _SqlStoreBase(PipelineStore, abc.ABC):
    """Shared statements + caches; subclasses provide execution."""

    def __init__(self, pipeline_id: int):
        self.pipeline_id = pipeline_id
        # cache-first reads (reference postgres.rs cache strategy)
        self._states: dict[TableId, TableState] = {}
        self._schemas: dict[TableId, list[tuple[SnapshotId, ReplicatedTableSchema]]] = {}
        self._progress: dict[ProgressKey, Lsn] = {}
        self._meta: dict[TableId, DestinationTableMetadata] = {}
        self._shard_assignment: ShardAssignment | None = None

    # -- execution seam ------------------------------------------------------

    @abc.abstractmethod
    async def _run(self, sql: str, params: tuple = ()) -> list[tuple]:
        """Execute one statement (auto-committed); return rows."""

    @abc.abstractmethod
    async def _txn(self, statements: list[tuple[str, tuple]]) -> None:
        """Execute several statements atomically."""

    # -- lifecycle -----------------------------------------------------------

    async def _migrate_and_warm(self, bigserial: str) -> None:
        for _name, ddl in MIGRATIONS:
            for stmt in ddl.format(bigserial=bigserial).split(";"):
                if not stmt.strip():
                    continue
                try:
                    await self._run(stmt)
                except Exception as e:
                    # there is no applied-migrations ledger — every
                    # connect re-runs the list and relies on
                    # idempotency. CREATEs carry IF NOT EXISTS; ALTER
                    # TABLE ADD COLUMN has no portable spelling of
                    # that (sqlite), so a duplicate-column error IS
                    # the already-applied signal, in both dialects
                    msg = str(e).lower()
                    if stmt.lstrip().upper().startswith("ALTER TABLE") \
                            and ("duplicate column" in msg
                                 or "already exists" in msg):
                        continue
                    raise
        await self._load_caches()

    async def _load_caches(self) -> None:
        pid = self.pipeline_id
        self._states = {
            int(tid): TableState.from_json(raw) for tid, raw in await self._run(
                "SELECT table_id, state FROM etl_replication_state "
                "WHERE pipeline_id = ? AND is_current = 1", (pid,))}
        self._schemas = {}
        for tid, sid, raw in await self._run(
                "SELECT table_id, snapshot_id, schema_json FROM "
                "etl_table_schemas WHERE pipeline_id = ? "
                "ORDER BY snapshot_id", (pid,)):
            self._schemas.setdefault(int(tid), []).append(
                (int(sid), ReplicatedTableSchema.from_json(json.loads(raw))))
        self._progress = {
            key: Lsn(int(lsn)) for key, lsn in await self._run(
                "SELECT progress_key, lsn FROM etl_replication_progress "
                "WHERE pipeline_id = ?", (pid,))}
        self._meta = {
            int(tid): DestinationTableMetadata(int(tid), name, int(gen))
            for tid, name, gen in await self._run(
                "SELECT table_id, destination_table_name, generation "
                "FROM etl_table_mappings WHERE pipeline_id = ?", (pid,))}
        rows = await self._run(
            "SELECT assignment_json FROM etl_shard_assignment "
            "WHERE pipeline_id = ?", (pid,))
        self._shard_assignment = \
            ShardAssignment.from_json(json.loads(rows[0][0])) if rows \
            else None

    # -- StateStore ----------------------------------------------------------

    async def get_table_states(self) -> dict[TableId, TableState]:
        return dict(self._states)

    async def get_table_state(self, table_id: TableId) -> TableState | None:
        return self._states.get(table_id)

    async def update_table_state(self, table_id: TableId,
                                 state: TableState) -> None:
        if not state.is_persistent:
            raise EtlError(ErrorKind.STORE_SERIALIZATION_FAILED,
                           f"{state.type.value} is memory-only, not storable")
        failpoints.fail_point(failpoints.STORE_STATE_COMMIT)
        await failpoints.stall_point(failpoints.STORE_STATE_COMMIT)
        pid = self.pipeline_id
        # prev-pointer history chain (reference base.up.sql semantics)
        cur = await self._run(
            "SELECT id FROM etl_replication_state WHERE pipeline_id = ? "
            "AND table_id = ? AND is_current = 1", (pid, table_id))
        prev_id = _opt_int(cur[0][0]) if cur else None
        await self._txn([
            ("UPDATE etl_replication_state SET is_current = 0 "
             "WHERE pipeline_id = ? AND table_id = ? AND is_current = 1",
             (pid, table_id)),
            ("INSERT INTO etl_replication_state "
             "(pipeline_id, table_id, state, prev, is_current) "
             "VALUES (?, ?, ?, ?, 1)",
             (pid, table_id, state.to_json(), prev_id)),
        ])
        self._states[table_id] = state

    async def delete_table_state(self, table_id: TableId) -> None:
        await self._run(
            "DELETE FROM etl_replication_state WHERE pipeline_id = ? "
            "AND table_id = ?", (self.pipeline_id, table_id))
        self._states.pop(table_id, None)

    async def get_durable_progress(self, key: ProgressKey) -> Lsn | None:
        return self._progress.get(key)

    async def update_durable_progress(self, key: ProgressKey,
                                      lsn: Lsn) -> bool:
        failpoints.fail_point(failpoints.STORE_PROGRESS_COMMIT)
        await failpoints.stall_point(failpoints.STORE_PROGRESS_COMMIT)
        cur = self._progress.get(key)
        if cur is not None and lsn < cur:
            return False
        await self._run(
            "INSERT INTO etl_replication_progress "
            "(pipeline_id, progress_key, lsn) VALUES (?, ?, ?) "
            "ON CONFLICT (pipeline_id, progress_key) DO UPDATE SET "
            "lsn = excluded.lsn WHERE excluded.lsn >= "
            "etl_replication_progress.lsn",
            (self.pipeline_id, key, int(lsn)))
        self._progress[key] = lsn
        return True

    async def delete_durable_progress(self, key: ProgressKey) -> None:
        await self._run(
            "DELETE FROM etl_replication_progress WHERE "
            "pipeline_id = ? AND progress_key = ?",
            (self.pipeline_id, key))
        self._progress.pop(key, None)

    async def get_destination_metadata(
            self, table_id: TableId) -> DestinationTableMetadata | None:
        return self._meta.get(table_id)

    async def update_destination_metadata(
            self, meta: DestinationTableMetadata) -> None:
        await self._run(
            "INSERT INTO etl_table_mappings "
            "(pipeline_id, table_id, destination_table_name, generation) "
            "VALUES (?, ?, ?, ?) ON CONFLICT (pipeline_id, table_id) "
            "DO UPDATE SET destination_table_name = excluded."
            "destination_table_name, generation = excluded.generation",
            (self.pipeline_id, meta.table_id, meta.destination_table_name,
             meta.generation))
        self._meta[meta.table_id] = meta

    async def delete_destination_metadata(self, table_id: TableId) -> None:
        await self._run(
            "DELETE FROM etl_table_mappings WHERE pipeline_id = ? "
            "AND table_id = ?", (self.pipeline_id, table_id))
        self._meta.pop(table_id, None)

    # -- shard assignment ----------------------------------------------------

    async def get_shard_assignment(self) -> ShardAssignment | None:
        """Always read THROUGH to the database, unlike the cache-first
        table-state reads: the assignment is the one row another PROCESS
        (the coordinator) rewrites underneath a running pod, and the
        ShardScopedStore epoch fence exists precisely to observe that
        flip — a connect-time cache would never refuse a stale pod."""
        rows = await self._run(
            "SELECT assignment_json FROM etl_shard_assignment "
            "WHERE pipeline_id = ?", (self.pipeline_id,))
        self._shard_assignment = \
            ShardAssignment.from_json(json.loads(rows[0][0])) if rows \
            else None
        return self._shard_assignment

    async def update_shard_assignment(self,
                                      assignment: ShardAssignment) -> None:
        cur = await self.get_shard_assignment()  # read-through (above)
        if cur is not None and assignment.epoch < cur.epoch:
            raise EtlError(
                ErrorKind.PROGRESS_REGRESSION,
                f"shard assignment epoch regression: {cur.epoch} -> "
                f"{assignment.epoch}")
        failpoints.fail_point(failpoints.STORE_SHARD_COMMIT)
        await failpoints.stall_point(failpoints.STORE_SHARD_COMMIT)
        await self._run(
            "INSERT INTO etl_shard_assignment "
            "(pipeline_id, assignment_json) VALUES (?, ?) "
            "ON CONFLICT (pipeline_id) DO UPDATE SET "
            "assignment_json = excluded.assignment_json",
            (self.pipeline_id, json.dumps(assignment.to_json())))
        self._shard_assignment = assignment

    # -- autoscale decision journal ------------------------------------------

    async def get_autoscale_journal(self) -> dict | None:
        """Read-through like the shard assignment (not cache-first): the
        journal is rewritten by the CONTROLLER process underneath running
        pods, and a crashed controller's successor must see the latest
        persisted decision, not a connect-time snapshot."""
        rows = await self._run(
            "SELECT journal_json FROM etl_autoscale_journal "
            "WHERE pipeline_id = ?", (self.pipeline_id,))
        return json.loads(rows[0][0]) if rows else None

    async def update_autoscale_journal(self, journal: dict) -> None:
        cur = await self.get_autoscale_journal()
        if cur is not None and int(journal.get("next_id", 0)) \
                < int(cur.get("next_id", 0)):
            raise EtlError(
                ErrorKind.PROGRESS_REGRESSION,
                f"autoscale journal id regression: {cur.get('next_id')} "
                f"-> {journal.get('next_id')}")
        failpoints.fail_point(failpoints.STORE_AUTOSCALE_COMMIT)
        await failpoints.stall_point(failpoints.STORE_AUTOSCALE_COMMIT)
        await self._run(
            "INSERT INTO etl_autoscale_journal "
            "(pipeline_id, journal_json) VALUES (?, ?) "
            "ON CONFLICT (pipeline_id) DO UPDATE SET "
            "journal_json = excluded.journal_json",
            (self.pipeline_id, json.dumps(journal)))

    # -- fleet spec / actuation journals -------------------------------------
    # Read-through like the autoscale journal: the spec is rewritten by
    # the OPERATOR (API process) and the journals by the COORDINATOR,
    # both underneath whoever reads next — a hard-killed coordinator's
    # successor must see the latest persisted decision, never a
    # connect-time snapshot. `pipeline_id` here is the FLEET id (the
    # coordinator opens the store with it); `member_id` is the managed
    # pipeline's id.

    async def get_fleet_spec(self) -> dict | None:
        rows = await self._run(
            "SELECT spec_json FROM etl_fleet_spec "
            "WHERE pipeline_id = ?", (self.pipeline_id,))
        return json.loads(rows[0][0]) if rows else None

    async def update_fleet_spec(self, spec: dict) -> None:
        cur = await self.get_fleet_spec()
        if cur is not None and int(spec.get("spec_version", 0)) \
                < int(cur.get("spec_version", 0)):
            raise EtlError(
                ErrorKind.PROGRESS_REGRESSION,
                f"fleet spec version regression: {cur.get('spec_version')} "
                f"-> {spec.get('spec_version')}")
        failpoints.fail_point(failpoints.STORE_FLEET_COMMIT)
        await failpoints.stall_point(failpoints.STORE_FLEET_COMMIT)
        await self._run(
            "INSERT INTO etl_fleet_spec "
            "(pipeline_id, spec_json) VALUES (?, ?) "
            "ON CONFLICT (pipeline_id) DO UPDATE SET "
            "spec_json = excluded.spec_json",
            (self.pipeline_id, json.dumps(spec)))

    async def get_fleet_journal(self, pipeline_id: int) -> dict | None:
        rows = await self._run(
            "SELECT journal_json FROM etl_fleet_journal "
            "WHERE pipeline_id = ? AND member_id = ?",
            (self.pipeline_id, int(pipeline_id)))
        return json.loads(rows[0][0]) if rows else None

    async def get_fleet_journals(self) -> dict[int, dict]:
        rows = await self._run(
            "SELECT member_id, journal_json FROM etl_fleet_journal "
            "WHERE pipeline_id = ?", (self.pipeline_id,))
        return {int(mid): json.loads(raw) for mid, raw in rows}

    async def update_fleet_journal(self, pipeline_id: int,
                                   journal: dict) -> None:
        cur = await self.get_fleet_journal(pipeline_id)
        if cur is not None and int(journal.get("next_id", 0)) \
                < int(cur.get("next_id", 0)):
            raise EtlError(
                ErrorKind.PROGRESS_REGRESSION,
                f"fleet journal id regression for pipeline {pipeline_id}: "
                f"{cur.get('next_id')} -> {journal.get('next_id')}")
        failpoints.fail_point(failpoints.STORE_FLEET_COMMIT)
        await failpoints.stall_point(failpoints.STORE_FLEET_COMMIT)
        await self._run(
            "INSERT INTO etl_fleet_journal "
            "(pipeline_id, member_id, journal_json) VALUES (?, ?, ?) "
            "ON CONFLICT (pipeline_id, member_id) DO UPDATE SET "
            "journal_json = excluded.journal_json",
            (self.pipeline_id, int(pipeline_id), json.dumps(journal)))

    # -- dead-letter / quarantine surface ------------------------------------
    # Read-THROUGH like the shard assignment, not cache-first: the
    # operator CLI (python -m etl_tpu.dlq) mutates these rows from
    # another process while a replicator runs, and replay/discard/
    # unquarantine must be visible to whichever process reads next.

    _DLQ_COLS = ("id, table_id, commit_lsn, tx_ordinal, change_type, "
                 "payload, error_kind, detail, attempts, status, "
                 "poison_columns, updated_at")

    @staticmethod
    def _dlq_row(r) -> DeadLetterEntry:
        return DeadLetterEntry(
            entry_id=int(r[0]), table_id=int(r[1]), commit_lsn=int(r[2]),
            tx_ordinal=int(r[3]), change_type=int(r[4]), payload=r[5],
            error_kind=r[6], detail=r[7], attempts=int(r[8]), status=r[9],
            columns=r[10], updated_at=int(r[11]))

    #: rows per multi-row upsert statement: fixed-size chunks keep the
    #: `?`→`$n` placeholder rewrite cache small (≤ _DLQ_CHUNK distinct
    #: statement widths) while a quarantine parking a whole flush costs
    #: O(rows/chunk) round trips instead of 2·rows
    _DLQ_CHUNK = 64

    async def append_dead_letters(self, entries) -> list[int]:
        failpoints.fail_point(failpoints.STORE_DLQ_COMMIT)
        await failpoints.stall_point(failpoints.STORE_DLQ_COMMIT)
        pid = self.pipeline_id
        # in-batch dedup (defensive: Postgres refuses ON CONFLICT
        # affecting one row twice in a single statement) — merge
        # duplicate WAL keys, accumulating attempts like the upsert does
        merged: dict[tuple, object] = {}
        order: list[tuple] = []
        for e in entries:
            cur = merged.get(e.key())
            if cur is None:
                merged[e.key()] = e
                order.append(e.key())
            else:
                from dataclasses import replace as _replace

                merged[e.key()] = _replace(
                    cur, attempts=cur.attempts + e.attempts,
                    error_kind=e.error_kind, detail=e.detail or cur.detail)
        todo = [merged[k] for k in order]
        now = int(time.time())  # the compaction clock, store-stamped
        row_sql = "(?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)"
        for i in range(0, len(todo), self._DLQ_CHUNK):
            chunk = todo[i:i + self._DLQ_CHUNK]
            params: list = []
            for e in chunk:
                params += [pid, e.table_id, e.commit_lsn, e.tx_ordinal,
                           e.change_type, e.payload, e.error_kind,
                           e.detail, e.attempts, e.status, e.columns,
                           now]
            # idempotent keyed upsert on the WAL coordinates: a crash
            # between bisection and ack re-streams the batch and
            # re-appends the same rows — attempts accumulate, no dup row
            await self._run(
                "INSERT INTO etl_dead_letter "
                "(pipeline_id, table_id, commit_lsn, tx_ordinal, "
                "change_type, payload, error_kind, detail, attempts, "
                "status, poison_columns, updated_at) VALUES "
                + ", ".join([row_sql] * len(chunk))
                + " ON CONFLICT (pipeline_id, table_id, commit_lsn, "
                "tx_ordinal, change_type) DO UPDATE SET "
                "attempts = etl_dead_letter.attempts + excluded.attempts, "
                "error_kind = excluded.error_kind, "
                "detail = excluded.detail, "
                "poison_columns = excluded.poison_columns, "
                "updated_at = excluded.updated_at",
                tuple(params))
        if not todo:
            return []
        # ONE read-back for the assigned ids, keyed client-side (the
        # batch's commit range bounds the scan)
        lo = min(e.commit_lsn for e in todo)
        hi = max(e.commit_lsn for e in todo)
        rows = await self._run(
            "SELECT id, table_id, commit_lsn, tx_ordinal, change_type "
            "FROM etl_dead_letter WHERE pipeline_id = ? "
            "AND commit_lsn >= ? AND commit_lsn <= ?", (pid, lo, hi))
        by_key = {(int(t), int(c), int(o), int(ch)): int(i)
                  for i, t, c, o, ch in rows}
        return [by_key[e.key()] for e in entries]

    async def list_dead_letters(self, table_id=None,
                                status="dead") -> list[DeadLetterEntry]:
        sql = (f"SELECT {self._DLQ_COLS} FROM etl_dead_letter "
               f"WHERE pipeline_id = ?")
        params: list = [self.pipeline_id]
        if table_id is not None:
            sql += " AND table_id = ?"
            params.append(table_id)
        if status is not None:
            sql += " AND status = ?"
            params.append(status)
        sql += " ORDER BY id"
        return [self._dlq_row(r) for r in await self._run(sql,
                                                          tuple(params))]

    async def get_dead_letter(self, entry_id: int) -> DeadLetterEntry | None:
        rows = await self._run(
            f"SELECT {self._DLQ_COLS} FROM etl_dead_letter "
            f"WHERE pipeline_id = ? AND id = ?",
            (self.pipeline_id, entry_id))
        return self._dlq_row(rows[0]) if rows else None

    async def set_dead_letter_status(self, entry_id: int,
                                     status: str) -> None:
        rows = await self._run(
            "SELECT id FROM etl_dead_letter WHERE pipeline_id = ? "
            "AND id = ?", (self.pipeline_id, entry_id))
        if not rows:
            raise EtlError(ErrorKind.STATE_STORE_FAILED,
                           f"no dead-letter entry {entry_id}")
        await self._run(
            "UPDATE etl_dead_letter SET status = ?, updated_at = ? "
            "WHERE pipeline_id = ? AND id = ?",
            (status, int(time.time()), self.pipeline_id, entry_id))

    async def purge_dead_letters(self, older_than_s, statuses=(
            "replayed", "discarded")) -> int:
        """TTL compaction (operator CLI): delete terminal entries whose
        last transition predates the cutoff. Two statements instead of
        relying on a DELETE rowcount — the execution seam returns rows,
        not counts, and portably so."""
        cutoff = int(time.time() - older_than_s)
        marks = ", ".join(["?"] * len(statuses))
        where = (f"pipeline_id = ? AND status IN ({marks}) "
                 f"AND updated_at < ?")
        params = (self.pipeline_id, *statuses, cutoff)
        rows = await self._run(
            f"SELECT id FROM etl_dead_letter WHERE {where}", params)
        if rows:
            await self._run(
                f"DELETE FROM etl_dead_letter WHERE {where}", params)
        return len(rows)

    async def get_quarantined_tables(self) -> dict[TableId, QuarantineRecord]:
        rows = await self._run(
            "SELECT table_id, record_json FROM etl_quarantine "
            "WHERE pipeline_id = ?", (self.pipeline_id,))
        return {int(tid): QuarantineRecord.from_json(json.loads(raw))
                for tid, raw in rows}

    async def set_table_quarantine(self, table_id: TableId,
                                   record: QuarantineRecord | None) -> None:
        failpoints.fail_point(failpoints.STORE_DLQ_COMMIT)
        await failpoints.stall_point(failpoints.STORE_DLQ_COMMIT)
        if record is None:
            await self._run(
                "DELETE FROM etl_quarantine WHERE pipeline_id = ? "
                "AND table_id = ?", (self.pipeline_id, table_id))
            return
        await self._run(
            "INSERT INTO etl_quarantine (pipeline_id, table_id, "
            "record_json) VALUES (?, ?, ?) "
            "ON CONFLICT (pipeline_id, table_id) DO UPDATE SET "
            "record_json = excluded.record_json",
            (self.pipeline_id, table_id, json.dumps(record.to_json())))

    # -- SchemaStore ---------------------------------------------------------

    async def store_table_schema(self, schema: ReplicatedTableSchema,
                                 snapshot_id: SnapshotId) -> None:
        failpoints.fail_point(failpoints.STORE_SCHEMA_COMMIT)
        await failpoints.stall_point(failpoints.STORE_SCHEMA_COMMIT)
        await self._run(
            "INSERT INTO etl_table_schemas "
            "(pipeline_id, table_id, snapshot_id, schema_json) "
            "VALUES (?, ?, ?, ?) ON CONFLICT "
            "(pipeline_id, table_id, snapshot_id) DO UPDATE SET "
            "schema_json = excluded.schema_json",
            (self.pipeline_id, schema.id, snapshot_id,
             json.dumps(schema.to_json())))
        versions = self._schemas.setdefault(schema.id, [])
        versions[:] = [(s, v) for s, v in versions if s != snapshot_id]
        versions.append((snapshot_id, schema))
        versions.sort(key=lambda p: p[0])

    async def get_table_schema(
            self, table_id: TableId,
            at_snapshot: SnapshotId | None = None
    ) -> ReplicatedTableSchema | None:
        versions = self._schemas.get(table_id)
        if not versions:
            return None
        if at_snapshot is None:
            return versions[-1][1]
        best = None
        for s, v in versions:
            if s <= at_snapshot:
                best = v
            else:
                break
        return best

    async def get_schema_versions(self, table_id: TableId) -> list[SnapshotId]:
        return [s for s, _ in self._schemas.get(table_id, [])]

    async def get_table_ids_with_schemas(self) -> list[TableId]:
        return [tid for tid, v in self._schemas.items() if v]

    async def prune_schema_versions(self, table_id: TableId,
                                    older_than: SnapshotId) -> int:
        versions = self._schemas.get(table_id)
        if not versions:
            return 0
        keep_from = 0
        for i, (s, _) in enumerate(versions):
            if s <= older_than:
                keep_from = i
        removed_ids = [s for s, _ in versions[:keep_from]]
        if removed_ids:
            await self._txn([
                ("DELETE FROM etl_table_schemas WHERE pipeline_id = ? AND "
                 "table_id = ? AND snapshot_id = ?",
                 (self.pipeline_id, table_id, s)) for s in removed_ids])
        versions[:] = versions[keep_from:]
        return len(removed_ids)

    async def delete_table_schemas(self, table_id: TableId) -> None:
        await self._run(
            "DELETE FROM etl_table_schemas WHERE pipeline_id = ? "
            "AND table_id = ?", (self.pipeline_id, table_id))
        self._schemas.pop(table_id, None)

    # -- history inspection (reference prev-pointer chain) --------------------

    async def state_history(self, table_id: TableId) -> list[TableState]:
        """Oldest→newest chain of states for a table."""
        rows = await self._run(
            "SELECT state FROM etl_replication_state WHERE pipeline_id = ? "
            "AND table_id = ? ORDER BY id", (self.pipeline_id, table_id))
        return [TableState.from_json(r[0]) for r in rows]


class SqliteStore(_SqlStoreBase):
    """File-backed store. `connect()` runs migrations and warms caches."""

    def __init__(self, path: str | Path, pipeline_id: int):
        super().__init__(pipeline_id)
        self.path = str(path)
        self._db: sqlite3.Connection | None = None

    async def connect(self) -> None:
        self._db = sqlite3.connect(self.path)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        await self._migrate_and_warm(bigserial="INTEGER")
        self._db.commit()

    def _conn(self) -> sqlite3.Connection:
        if self._db is None:
            raise EtlError(ErrorKind.STATE_STORE_FAILED,
                           "store not connected")
        return self._db

    async def _run(self, sql: str, params: tuple = ()) -> list[tuple]:
        db = self._conn()
        rows = db.execute(sql, params).fetchall()
        db.commit()
        return rows

    async def _txn(self, statements: list[tuple[str, tuple]]) -> None:
        db = self._conn()
        try:
            for sql, params in statements:
                db.execute(sql, params)
            db.commit()
        except BaseException:
            db.rollback()
            raise

    async def close(self) -> None:
        if self._db is not None:
            self._db.close()
            self._db = None


import functools


# The store tables, flat (sqlite) spelling. The Postgres dialect maps
# EXACTLY these into the `etl` schema; the fake server reverses the
# same list — one source of truth, no drift.
STORE_TABLE_NAMES = ("etl_replication_state", "etl_table_schemas",
                     "etl_table_mappings", "etl_replication_progress",
                     "etl_shard_assignment", "etl_autoscale_journal",
                     "etl_fleet_spec", "etl_fleet_journal",
                     "etl_dead_letter", "etl_quarantine")

_QUALIFY_RE = re.compile(r"\b(" + "|".join(STORE_TABLE_NAMES) + r")\b")


@functools.lru_cache(maxsize=256)
def qualify_etl_schema(sql: str) -> str:
    """Move the flat `etl_*` table names into the `etl` schema for the
    Postgres dialect — the reference's postgres_store migrations create
    `etl.replication_state` etc. in a dedicated schema, and with the
    default store.connection (the SOURCE database) flat names would land
    in the customer's public schema. The sqlite dialect keeps flat names
    (sqlite has no schemas). Word-bounded and restricted to the table
    list: index names like etl_replication_state_current (which CREATE
    INDEX cannot schema-qualify) and unrelated etl_-prefixed identifiers
    pass through untouched."""
    return _QUALIFY_RE.sub(lambda m: "etl." + m.group(1)[4:], sql)


@functools.lru_cache(maxsize=256)
def to_dollar_params(sql: str, n_params: int) -> str:
    """Rewrite `?` placeholders (outside quoted segments) to `$1..$n` for
    the extended protocol. Cached: the statement set is a small fixed
    collection and the rewrite depends only on (sql, n_params)."""
    out = []
    n = 0
    in_str = False
    for ch in sql:
        if ch == "'":
            in_str = not in_str
            out.append(ch)
        elif ch == "?" and not in_str:
            n += 1
            out.append(f"${n}")
        else:
            out.append(ch)
    if n != n_params:
        raise EtlError(ErrorKind.STORE_SERIALIZATION_FAILED,
                       f"{n} placeholders for {n_params} params: {sql[:80]}")
    return "".join(out)


class _PoolSlot:
    """One pooled wire connection; `conn is None` means the slot needs a
    (re)connect at next acquire."""

    __slots__ = ("conn",)

    def __init__(self) -> None:
        self.conn = None


class PostgresStore(_SqlStoreBase):
    """The reference PostgresStore over the from-scratch wire client.

    Reference: crates/etl/src/store/both/postgres.rs + the
    migrations/postgres_store SQL, including its sqlx connection POOL:
    the apply loop and N table-sync workers each check a connection out
    of the pool instead of contending on one serialized wire connection
    (VERDICT r2 weak #5). A transaction pins one connection for its whole
    BEGIN..COMMIT, so foreign statements can never join it; a connection
    that dies mid-statement is discarded and its slot reconnects lazily
    on next acquire."""

    def __init__(self, connection_config, pipeline_id: int,
                 pool_size: int = 4):
        """connection_config: PgConnectionConfig (host/port/name/username/
        password/TLS) — the same config object the replication client
        uses."""
        import asyncio

        super().__init__(pipeline_id)
        self._config = connection_config
        self.pool_size = max(1, pool_size)
        self._free: "asyncio.Queue[_PoolSlot] | None" = None
        self._connected = False

    def _new_conn(self):
        from ..postgres.client import wire_connection_from_config

        return wire_connection_from_config(
            self._config,
            application_name=f"etl_tpu_store_{self.pipeline_id}")

    async def connect(self) -> None:
        import asyncio

        first = self._new_conn()
        await first.connect()
        # the store tables live in a dedicated `etl` schema (reference
        # migrations/postgres_store layout), never the customer's default
        # schema — create it before the table migrations run
        await first.query("CREATE SCHEMA IF NOT EXISTS etl")
        # one-time legacy migration: pre-r3 versions created the flat
        # etl_* tables in the connection's default creation schema; move
        # them (indexes follow) AND strip the etl_ prefix so they land at
        # the exact names the qualified statements use — otherwise durable
        # replication state would silently restart from empty. Unqualified
        # source name: resolves via the same search_path the old CREATE
        # TABLE used; both steps are no-ops once migrated.
        for t in STORE_TABLE_NAMES:
            await first.query(f"ALTER TABLE IF EXISTS {t} SET SCHEMA etl")
            await first.query(
                f"ALTER TABLE IF EXISTS etl.{t} RENAME TO {t[4:]}")
        self._free = asyncio.Queue()
        slot = _PoolSlot()
        slot.conn = first
        self._free.put_nowait(slot)
        # remaining slots connect lazily on first acquire — a pipeline
        # with one table never pays for 4 TCP+SCRAM handshakes
        for _ in range(self.pool_size - 1):
            self._free.put_nowait(_PoolSlot())
        self._connected = True
        await self._migrate_and_warm(
            bigserial="BIGINT GENERATED BY DEFAULT AS IDENTITY")

    async def _acquire(self) -> _PoolSlot:
        if not self._connected or self._free is None:
            raise EtlError(ErrorKind.STATE_STORE_FAILED,
                           "store not connected")
        slot = await self._free.get()
        if not self._connected:
            # close() ran while this caller waited; wake the next waiter
            # and fail typed instead of hanging on an abandoned queue
            self._free.put_nowait(slot)
            raise EtlError(ErrorKind.STATE_STORE_FAILED,
                           "store not connected")
        if slot.conn is None:
            conn = self._new_conn()
            try:
                await conn.connect()
            except BaseException:
                self._free.put_nowait(slot)  # stays reconnectable
                raise
            slot.conn = conn
        return slot

    async def _release(self, slot: _PoolSlot, broken: bool) -> None:
        if (broken or not self._connected) and slot.conn is not None:
            # broken wire, or the pool closed while this connection was
            # checked out — either way it must not outlive release
            try:
                await slot.conn.close()
            except Exception:
                pass
            slot.conn = None
        if self._free is not None:
            self._free.put_nowait(slot)

    @staticmethod
    def _is_broken(e: BaseException) -> bool:
        """Connection-level failures poison the wire framing; PG error
        responses leave the connection reusable. CANCELLATION is broken
        too: a task cancelled mid-query abandons unread response frames
        on the socket, and the next query on that connection would read
        the stale ReadyForQuery and take the old query's rows."""
        import asyncio as aio

        return isinstance(e, (OSError, ConnectionError, EOFError,
                              aio.IncompleteReadError,
                              aio.CancelledError))

    async def _run_on(self, conn, sql: str,
                      params: tuple = ()) -> list[tuple]:
        sql = qualify_etl_schema(sql)
        if not params:
            result = await conn.query(sql)
        else:
            # extended protocol: SERVER-side binding — no client-side
            # quoting on the correctness/security path
            texts = []
            for v in params:
                if v is None:
                    texts.append(None)
                    continue
                t = str(v)
                if "\x00" in t:
                    # real PG rejects NUL in text; fail typed and early
                    # (the sqlite-backed fake would silently accept it)
                    raise EtlError(ErrorKind.STORE_SERIALIZATION_FAILED,
                                   "NUL byte in store value")
                texts.append(t)
            result = await conn.query_params(
                to_dollar_params(sql, len(params)), texts)
        return [tuple(r) for r in result.rows]

    async def _run(self, sql: str, params: tuple = ()) -> list[tuple]:
        slot = await self._acquire()
        try:
            rows = await self._run_on(slot.conn, sql, params)
        except BaseException as e:
            await self._release(slot, self._is_broken(e))
            raise
        await self._release(slot, False)
        return rows

    async def _txn(self, statements: list[tuple[str, tuple]]) -> None:
        # pin ONE connection for the whole transaction: concurrent store
        # callers ride other pool slots and can never join this
        # BEGIN..COMMIT
        slot = await self._acquire()
        broken = False
        try:
            await self._run_on(slot.conn, "BEGIN")
            try:
                for sql, params in statements:
                    await self._run_on(slot.conn, sql, params)
            except BaseException as e:
                broken = self._is_broken(e)
                if not broken:
                    try:
                        await self._run_on(slot.conn, "ROLLBACK")
                    except Exception:
                        broken = True
                raise
            await self._run_on(slot.conn, "COMMIT")
        except BaseException as e:
            broken = broken or self._is_broken(e)
            await self._release(slot, broken)
            raise
        await self._release(slot, False)

    async def close(self) -> None:
        """Close idle connections now; checked-out connections close at
        their _release (they must not be yanked mid-statement). The queue
        stays alive so blocked acquirers wake and fail typed rather than
        hanging."""
        if self._free is None:
            return
        self._connected = False
        drained: list[_PoolSlot] = []
        while not self._free.empty():
            drained.append(self._free.get_nowait())
        for slot in drained:
            if slot.conn is not None:
                try:
                    await slot.conn.close()
                except Exception:
                    pass
                slot.conn = None
        for slot in drained:
            self._free.put_nowait(slot)
