"""Durable SQL store: the reference `etl` schema on sqlite or Postgres.

Reference parity: `PostgresStore` (crates/etl/src/store/both/postgres.rs)
against the `etl` schema (migrations/postgres_store/20250827000000_base.up.sql
+ 20260511090000_replication_progress.up.sql):

  - `replication_state`: per-table state rows with a prev-pointer history
    chain and a partial unique `is_current` index;
  - `table_schemas`: versioned by snapshot id;
  - `table_mappings`: destination metadata;
  - `replication_progress`: monotonic per-worker durable LSN.

Cache-first reads like the reference (postgres.rs): all lookups hit an
in-memory cache warmed at `connect()`; writes go through to the database
synchronously.

Dialects: "sqlite" (file-backed, fully functional in this environment) and
"postgres" (same statements with $n placeholders, executed over a DB-API
compatible runner — e.g. the wire client adapter). Statement generation is
shared so the Postgres path cannot drift from the tested sqlite path.
"""

from __future__ import annotations

import json
import sqlite3
from dataclasses import dataclass
from pathlib import Path

from ..models.errors import ErrorKind, EtlError
from ..models.lsn import Lsn
from ..models.schema import ReplicatedTableSchema, SnapshotId, TableId
from ..models.table_state import TableState
from .base import DestinationTableMetadata, PipelineStore, ProgressKey

MIGRATIONS: list[tuple[str, str]] = [
    ("20250827000000_base", """
CREATE TABLE IF NOT EXISTS etl_replication_state (
    id INTEGER PRIMARY KEY {autoinc},
    pipeline_id BIGINT NOT NULL,
    table_id BIGINT NOT NULL,
    state TEXT NOT NULL,
    prev BIGINT,
    is_current INTEGER NOT NULL DEFAULT 1
);
CREATE UNIQUE INDEX IF NOT EXISTS etl_replication_state_current
    ON etl_replication_state (pipeline_id, table_id) WHERE is_current = 1;
CREATE TABLE IF NOT EXISTS etl_table_schemas (
    pipeline_id BIGINT NOT NULL,
    table_id BIGINT NOT NULL,
    snapshot_id BIGINT NOT NULL,
    schema_json TEXT NOT NULL,
    PRIMARY KEY (pipeline_id, table_id, snapshot_id)
);
CREATE TABLE IF NOT EXISTS etl_table_mappings (
    pipeline_id BIGINT NOT NULL,
    table_id BIGINT NOT NULL,
    destination_table_name TEXT NOT NULL,
    generation BIGINT NOT NULL DEFAULT 0,
    PRIMARY KEY (pipeline_id, table_id)
);
"""),
    ("20260511090000_replication_progress", """
CREATE TABLE IF NOT EXISTS etl_replication_progress (
    pipeline_id BIGINT NOT NULL,
    progress_key TEXT NOT NULL,
    lsn BIGINT NOT NULL,
    PRIMARY KEY (pipeline_id, progress_key)
);
"""),
]


class SqliteStore(PipelineStore):
    """File-backed store. `connect()` runs migrations and warms caches."""

    def __init__(self, path: str | Path, pipeline_id: int):
        self.path = str(path)
        self.pipeline_id = pipeline_id
        self._db: sqlite3.Connection | None = None
        # cache-first reads (reference postgres.rs cache strategy)
        self._states: dict[TableId, TableState] = {}
        self._schemas: dict[TableId, list[tuple[SnapshotId, ReplicatedTableSchema]]] = {}
        self._progress: dict[ProgressKey, Lsn] = {}
        self._meta: dict[TableId, DestinationTableMetadata] = {}

    # -- lifecycle -----------------------------------------------------------

    async def connect(self) -> None:
        self._db = sqlite3.connect(self.path)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        for _name, ddl in MIGRATIONS:
            self._db.executescript(ddl.format(autoinc="AUTOINCREMENT"))
        self._db.commit()
        self._load_caches()

    def _load_caches(self) -> None:
        db = self._conn()
        pid = self.pipeline_id
        self._states = {}
        for tid, raw in db.execute(
                "SELECT table_id, state FROM etl_replication_state "
                "WHERE pipeline_id = ? AND is_current = 1", (pid,)):
            self._states[tid] = TableState.from_json(raw)
        self._schemas = {}
        for tid, sid, raw in db.execute(
                "SELECT table_id, snapshot_id, schema_json FROM "
                "etl_table_schemas WHERE pipeline_id = ? "
                "ORDER BY snapshot_id", (pid,)):
            self._schemas.setdefault(tid, []).append(
                (sid, ReplicatedTableSchema.from_json(json.loads(raw))))
        self._progress = {
            key: Lsn(lsn) for key, lsn in db.execute(
                "SELECT progress_key, lsn FROM etl_replication_progress "
                "WHERE pipeline_id = ?", (pid,))}
        self._meta = {
            tid: DestinationTableMetadata(tid, name, gen)
            for tid, name, gen in db.execute(
                "SELECT table_id, destination_table_name, generation "
                "FROM etl_table_mappings WHERE pipeline_id = ?", (pid,))}

    def _conn(self) -> sqlite3.Connection:
        if self._db is None:
            raise EtlError(ErrorKind.STATE_STORE_FAILED,
                           "store not connected")
        return self._db

    async def close(self) -> None:
        if self._db is not None:
            self._db.close()
            self._db = None

    # -- StateStore ----------------------------------------------------------

    async def get_table_states(self) -> dict[TableId, TableState]:
        return dict(self._states)

    async def get_table_state(self, table_id: TableId) -> TableState | None:
        return self._states.get(table_id)

    async def update_table_state(self, table_id: TableId,
                                 state: TableState) -> None:
        if not state.is_persistent:
            raise EtlError(ErrorKind.STORE_SERIALIZATION_FAILED,
                           f"{state.type.value} is memory-only, not storable")
        db = self._conn()
        pid = self.pipeline_id
        # prev-pointer history chain (reference base.up.sql semantics)
        cur = db.execute(
            "SELECT id FROM etl_replication_state WHERE pipeline_id = ? "
            "AND table_id = ? AND is_current = 1",
            (pid, table_id)).fetchone()
        prev_id = cur[0] if cur else None
        db.execute("UPDATE etl_replication_state SET is_current = 0 "
                   "WHERE pipeline_id = ? AND table_id = ? "
                   "AND is_current = 1", (pid, table_id))
        db.execute(
            "INSERT INTO etl_replication_state "
            "(pipeline_id, table_id, state, prev, is_current) "
            "VALUES (?, ?, ?, ?, 1)",
            (pid, table_id, state.to_json(), prev_id))
        db.commit()
        self._states[table_id] = state

    async def delete_table_state(self, table_id: TableId) -> None:
        db = self._conn()
        db.execute("DELETE FROM etl_replication_state WHERE pipeline_id = ? "
                   "AND table_id = ?", (self.pipeline_id, table_id))
        db.commit()
        self._states.pop(table_id, None)

    async def get_durable_progress(self, key: ProgressKey) -> Lsn | None:
        return self._progress.get(key)

    async def update_durable_progress(self, key: ProgressKey,
                                      lsn: Lsn) -> bool:
        cur = self._progress.get(key)
        if cur is not None and lsn < cur:
            return False
        db = self._conn()
        db.execute(
            "INSERT INTO etl_replication_progress "
            "(pipeline_id, progress_key, lsn) VALUES (?, ?, ?) "
            "ON CONFLICT (pipeline_id, progress_key) DO UPDATE SET "
            "lsn = excluded.lsn WHERE excluded.lsn >= "
            "etl_replication_progress.lsn",
            (self.pipeline_id, key, int(lsn)))
        db.commit()
        self._progress[key] = lsn
        return True

    async def delete_durable_progress(self, key: ProgressKey) -> None:
        db = self._conn()
        db.execute("DELETE FROM etl_replication_progress WHERE "
                   "pipeline_id = ? AND progress_key = ?",
                   (self.pipeline_id, key))
        db.commit()
        self._progress.pop(key, None)

    async def get_destination_metadata(
            self, table_id: TableId) -> DestinationTableMetadata | None:
        return self._meta.get(table_id)

    async def update_destination_metadata(
            self, meta: DestinationTableMetadata) -> None:
        db = self._conn()
        db.execute(
            "INSERT INTO etl_table_mappings "
            "(pipeline_id, table_id, destination_table_name, generation) "
            "VALUES (?, ?, ?, ?) ON CONFLICT (pipeline_id, table_id) "
            "DO UPDATE SET destination_table_name = excluded."
            "destination_table_name, generation = excluded.generation",
            (self.pipeline_id, meta.table_id, meta.destination_table_name,
             meta.generation))
        db.commit()
        self._meta[meta.table_id] = meta

    async def delete_destination_metadata(self, table_id: TableId) -> None:
        db = self._conn()
        db.execute("DELETE FROM etl_table_mappings WHERE pipeline_id = ? "
                   "AND table_id = ?", (self.pipeline_id, table_id))
        db.commit()
        self._meta.pop(table_id, None)

    # -- SchemaStore ---------------------------------------------------------

    async def store_table_schema(self, schema: ReplicatedTableSchema,
                                 snapshot_id: SnapshotId) -> None:
        db = self._conn()
        db.execute(
            "INSERT INTO etl_table_schemas "
            "(pipeline_id, table_id, snapshot_id, schema_json) "
            "VALUES (?, ?, ?, ?) ON CONFLICT "
            "(pipeline_id, table_id, snapshot_id) DO UPDATE SET "
            "schema_json = excluded.schema_json",
            (self.pipeline_id, schema.id, snapshot_id,
             json.dumps(schema.to_json())))
        db.commit()
        versions = self._schemas.setdefault(schema.id, [])
        versions[:] = [(s, v) for s, v in versions if s != snapshot_id]
        versions.append((snapshot_id, schema))
        versions.sort(key=lambda p: p[0])

    async def get_table_schema(
            self, table_id: TableId,
            at_snapshot: SnapshotId | None = None
    ) -> ReplicatedTableSchema | None:
        versions = self._schemas.get(table_id)
        if not versions:
            return None
        if at_snapshot is None:
            return versions[-1][1]
        best = None
        for s, v in versions:
            if s <= at_snapshot:
                best = v
            else:
                break
        return best

    async def get_schema_versions(self, table_id: TableId) -> list[SnapshotId]:
        return [s for s, _ in self._schemas.get(table_id, [])]

    async def get_table_ids_with_schemas(self) -> list[TableId]:
        return [tid for tid, v in self._schemas.items() if v]

    async def prune_schema_versions(self, table_id: TableId,
                                    older_than: SnapshotId) -> int:
        versions = self._schemas.get(table_id)
        if not versions:
            return 0
        keep_from = 0
        for i, (s, _) in enumerate(versions):
            if s <= older_than:
                keep_from = i
        removed_ids = [s for s, _ in versions[:keep_from]]
        if removed_ids:
            db = self._conn()
            db.executemany(
                "DELETE FROM etl_table_schemas WHERE pipeline_id = ? AND "
                "table_id = ? AND snapshot_id = ?",
                [(self.pipeline_id, table_id, s) for s in removed_ids])
            db.commit()
        versions[:] = versions[keep_from:]
        return len(removed_ids)

    async def delete_table_schemas(self, table_id: TableId) -> None:
        db = self._conn()
        db.execute("DELETE FROM etl_table_schemas WHERE pipeline_id = ? "
                   "AND table_id = ?", (self.pipeline_id, table_id))
        db.commit()
        self._schemas.pop(table_id, None)

    # -- history inspection (reference prev-pointer chain) --------------------

    async def state_history(self, table_id: TableId) -> list[TableState]:
        """Oldest→newest chain of states for a table."""
        db = self._conn()
        rows = db.execute(
            "SELECT state FROM etl_replication_state WHERE pipeline_id = ? "
            "AND table_id = ? ORDER BY id", (self.pipeline_id, table_id)
        ).fetchall()
        return [TableState.from_json(r[0]) for r in rows]
