"""Store traits: state, schema, and cleanup interfaces.

Reference parity:
  - `StateStore` (crates/etl/src/store/state/base.rs:25-139): table
    replication states, monotonic durable progress LSN per worker,
    destination table metadata.
  - `SchemaStore` (crates/etl/src/store/schema/base.rs:19-69): table schemas
    versioned by `SnapshotId` (the LSN of the DDL message creating the
    version) with `get ≤ snapshot` semantics and pruning.
  - `TableStateLifecycleStore` (store/lifecycle.rs): compound operations
    spanning both (prepare-for-copy, reset, delete).

Contracts the implementations must uphold:
  - `update_durable_progress` is MONOTONIC: attempts to move the LSN
    backwards are ignored (reference state/base.rs:81-89).
  - Memory-only table states (SyncWait/Catchup) must never be persisted;
    `update_table_state` raises on them.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass

from ..models.errors import ErrorKind, EtlError
from ..models.lsn import Lsn
from ..models.schema import ReplicatedTableSchema, SnapshotId, TableId
from ..models.table_state import TableState
from ..sharding.shardmap import ShardAssignment

# a worker's durable-progress key: the apply worker uses the pipeline slot
# name, table-sync workers their per-table slot name (reference progress
# rows keyed by slot)
ProgressKey = str


@dataclass(frozen=True)
class DestinationTableMetadata:
    """What the destination knows about a table (name mapping + generation
    counter for truncate-versioned tables, reference table_mappings rows +
    BigQuery `table_N` successors)."""

    table_id: TableId
    destination_table_name: str
    generation: int = 0


class StateStore(abc.ABC):
    @abc.abstractmethod
    async def get_table_states(self) -> dict[TableId, TableState]: ...

    @abc.abstractmethod
    async def get_table_state(self, table_id: TableId) -> TableState | None: ...

    @abc.abstractmethod
    async def update_table_state(self, table_id: TableId,
                                 state: TableState) -> None: ...

    @abc.abstractmethod
    async def delete_table_state(self, table_id: TableId) -> None: ...

    @abc.abstractmethod
    async def get_durable_progress(self, key: ProgressKey) -> Lsn | None: ...

    @abc.abstractmethod
    async def update_durable_progress(self, key: ProgressKey,
                                      lsn: Lsn) -> bool:
        """Monotonic; returns False (and stores nothing) on regression."""

    @abc.abstractmethod
    async def delete_durable_progress(self, key: ProgressKey) -> None: ...

    # -- shard-assignment surface (docs/sharding.md) --------------------------
    # Concrete defaults rather than abstract methods: third-party and
    # test stores that never shard keep working unchanged; the memory and
    # sql backends override both with real persistence.

    async def get_shard_assignment(self) -> "ShardAssignment | None":
        """The authoritative (epoch, shard_count) record, or None when
        the pipeline has never been sharded."""
        return None

    async def update_shard_assignment(self,
                                      assignment: ShardAssignment) -> None:
        """Persist the assignment. Epochs are MONOTONIC: storing an
        assignment whose epoch is lower than the current record's is a
        typed error (a stale coordinator must never roll the fleet
        back)."""
        raise EtlError(
            ErrorKind.STATE_STORE_FAILED,
            f"{type(self).__name__} does not persist shard assignments")

    # -- autoscale decision-journal surface (docs/autoscale.md) ---------------
    # Same stance as the shard surface: concrete defaults so third-party
    # and test stores that never autoscale keep working unchanged; the
    # memory and sql backends override both with real persistence. The
    # journal is one small JSON document (etl_tpu/autoscale/controller.py
    # AutoscaleJournal shape) rewritten atomically per decision.

    async def get_autoscale_journal(self) -> "dict | None":
        """The persisted autoscale decision journal, or None when no
        controller has ever run against this pipeline."""
        return None

    async def update_autoscale_journal(self, journal: dict) -> None:
        """Persist the journal document. Decision ids inside it are
        MONOTONIC; storing a journal whose latest decision id is lower
        than the current record's is a typed error (a stale controller
        must never rewind the decision history)."""
        raise EtlError(
            ErrorKind.STATE_STORE_FAILED,
            f"{type(self).__name__} does not persist autoscale journals")

    @abc.abstractmethod
    async def get_destination_metadata(
        self, table_id: TableId) -> DestinationTableMetadata | None: ...

    @abc.abstractmethod
    async def update_destination_metadata(
        self, meta: DestinationTableMetadata) -> None: ...

    @abc.abstractmethod
    async def delete_destination_metadata(self, table_id: TableId) -> None: ...


class SchemaStore(abc.ABC):
    @abc.abstractmethod
    async def store_table_schema(self, schema: ReplicatedTableSchema,
                                 snapshot_id: SnapshotId) -> None: ...

    @abc.abstractmethod
    async def get_table_schema(
        self, table_id: TableId,
        at_snapshot: SnapshotId | None = None
    ) -> ReplicatedTableSchema | None:
        """Latest version with snapshot_id ≤ at_snapshot (or overall latest
        when at_snapshot is None) — reference schema/base.rs `get ≤`."""

    @abc.abstractmethod
    async def get_schema_versions(self, table_id: TableId) -> list[SnapshotId]: ...

    @abc.abstractmethod
    async def get_table_ids_with_schemas(self) -> "list[TableId]":
        """Tables that have at least one stored schema version (the
        cleanup task's iteration set)."""

    @abc.abstractmethod
    async def prune_schema_versions(self, table_id: TableId,
                                    older_than: SnapshotId) -> int:
        """Drop versions strictly older than the newest one ≤ `older_than`
        (keeping that one: it is still the decode view for `older_than`)."""

    @abc.abstractmethod
    async def delete_table_schemas(self, table_id: TableId) -> None: ...


class PipelineStore(StateStore, SchemaStore, abc.ABC):
    """The full store facade a pipeline needs (reference capabilities.rs).

    Compound lifecycle ops (reference store/lifecycle.rs):"""

    async def prepare_table_for_copy(self, table_id: TableId) -> None:
        """Reset to DataSync and drop schema versions — the crash-consistent
        pre-copy reset (reference table_sync/mod.rs:225-241)."""
        await self.update_table_state(table_id, TableState.data_sync())
        await self.delete_table_schemas(table_id)

    async def reset_table(self, table_id: TableId) -> None:
        """Full-resync reset. Destination metadata is deliberately KEPT: it
        is the marker telling the next copy attempt to drop the (still
        populated) destination table first — deleting it here would make an
        invalidated-slot resync duplicate every existing row."""
        await self.update_table_state(table_id, TableState.init())
        await self.delete_table_schemas(table_id)

    async def purge_table(self, table_id: TableId) -> None:
        await self.delete_table_state(table_id)
        await self.delete_table_schemas(table_id)
        await self.delete_destination_metadata(table_id)
