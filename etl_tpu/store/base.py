"""Store traits: state, schema, and cleanup interfaces.

Reference parity:
  - `StateStore` (crates/etl/src/store/state/base.rs:25-139): table
    replication states, monotonic durable progress LSN per worker,
    destination table metadata.
  - `SchemaStore` (crates/etl/src/store/schema/base.rs:19-69): table schemas
    versioned by `SnapshotId` (the LSN of the DDL message creating the
    version) with `get ≤ snapshot` semantics and pruning.
  - `TableStateLifecycleStore` (store/lifecycle.rs): compound operations
    spanning both (prepare-for-copy, reset, delete).

Contracts the implementations must uphold:
  - `update_durable_progress` is MONOTONIC: attempts to move the LSN
    backwards are ignored (reference state/base.rs:81-89).
  - Memory-only table states (SyncWait/Catchup) must never be persisted;
    `update_table_state` raises on them.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Sequence

from ..models.errors import ErrorKind, EtlError
from ..models.lsn import Lsn
from ..models.schema import ReplicatedTableSchema, SnapshotId, TableId
from ..models.table_state import TableState
from ..sharding.shardmap import ShardAssignment

# a worker's durable-progress key: the apply worker uses the pipeline slot
# name, table-sync workers their per-table slot name (reference progress
# rows keyed by slot)
ProgressKey = str


#: dead-letter entry lifecycle states (docs/dead-letter.md): `dead` =
#: parked awaiting operator action; `replayed` = re-delivered through
#: the destination seam (kept for audit); `discarded` = operator chose
#: to drop the row permanently (kept for audit).
DLQ_STATUS_DEAD = "dead"
DLQ_STATUS_REPLAYED = "replayed"
DLQ_STATUS_DISCARDED = "discarded"


@dataclass(frozen=True)
class DeadLetterEntry:
    """One poison row parked on the durable dead-letter surface.

    Identity is `(table_id, commit_lsn, tx_ordinal, change_type)` — the
    row's WAL coordinates — so a re-streamed batch that re-isolates the
    same poison row after a crash UPSERTS (attempts += 1) instead of
    duplicating, which is what makes both the isolation protocol and
    `replay` idempotent. `payload` is the dlq-codec JSON of the decoded
    row (etl_tpu/dlq/codec.py): enough to rebuild the event and push it
    back through `Destination.write_event_batches`."""

    entry_id: int  # store-assigned, monotonic per pipeline
    table_id: TableId
    commit_lsn: int
    tx_ordinal: int
    change_type: int  # models.event.ChangeType value
    payload: str  # dlq-codec JSON of the decoded row (+ old image)
    error_kind: str  # ErrorKind.name at isolation time
    detail: str  # the triggering error's detail, truncated
    attempts: int = 1  # write attempts that found this row poison
    status: str = DLQ_STATUS_DEAD
    # best-effort column attribution: replicated column names the
    # classified error detail names, comma-joined in schema order
    # (runtime/poison.py attribute_poison_columns); "" = unattributed
    columns: str = ""
    # store-stamped unix seconds of the last append/status transition —
    # the compaction clock (`python -m etl_tpu.dlq compact`)
    updated_at: int = 0

    def key(self) -> tuple:
        return (self.table_id, self.commit_lsn, self.tx_ordinal,
                self.change_type)

    def describe(self) -> dict:
        return {
            "entry_id": self.entry_id, "table_id": self.table_id,
            "commit_lsn": self.commit_lsn, "tx_ordinal": self.tx_ordinal,
            "change_type": self.change_type, "error_kind": self.error_kind,
            "detail": self.detail, "attempts": self.attempts,
            "status": self.status, "columns": self.columns,
            "updated_at": self.updated_at,
        }


@dataclass(frozen=True)
class QuarantineRecord:
    """A table parked out of the streaming path: its events bypass the
    destination and append straight to the dead-letter surface until an
    operator replays + unquarantines (docs/dead-letter.md)."""

    table_id: TableId
    since_lsn: int  # commit LSN of the flush that tripped the budget
    poison_rows: int  # dead-lettered rows that funded the budget
    parked_events: int = 0  # events parked since quarantine began
    reason: str = ""

    def to_json(self) -> dict:
        return {"table_id": self.table_id, "since_lsn": self.since_lsn,
                "poison_rows": self.poison_rows,
                "parked_events": self.parked_events, "reason": self.reason}

    @classmethod
    def from_json(cls, doc: dict) -> "QuarantineRecord":
        return cls(table_id=int(doc["table_id"]),
                   since_lsn=int(doc["since_lsn"]),
                   poison_rows=int(doc.get("poison_rows", 0)),
                   parked_events=int(doc.get("parked_events", 0)),
                   reason=str(doc.get("reason", "")))


@dataclass(frozen=True)
class DestinationTableMetadata:
    """What the destination knows about a table (name mapping + generation
    counter for truncate-versioned tables, reference table_mappings rows +
    BigQuery `table_N` successors)."""

    table_id: TableId
    destination_table_name: str
    generation: int = 0


class StateStore(abc.ABC):
    @abc.abstractmethod
    async def get_table_states(self) -> dict[TableId, TableState]: ...

    @abc.abstractmethod
    async def get_table_state(self, table_id: TableId) -> TableState | None: ...

    @abc.abstractmethod
    async def update_table_state(self, table_id: TableId,
                                 state: TableState) -> None: ...

    @abc.abstractmethod
    async def delete_table_state(self, table_id: TableId) -> None: ...

    @abc.abstractmethod
    async def get_durable_progress(self, key: ProgressKey) -> Lsn | None: ...

    @abc.abstractmethod
    async def update_durable_progress(self, key: ProgressKey,
                                      lsn: Lsn) -> bool:
        """Monotonic; returns False (and stores nothing) on regression."""

    @abc.abstractmethod
    async def delete_durable_progress(self, key: ProgressKey) -> None: ...

    # -- shard-assignment surface (docs/sharding.md) --------------------------
    # Concrete defaults rather than abstract methods: third-party and
    # test stores that never shard keep working unchanged; the memory and
    # sql backends override both with real persistence.

    async def get_shard_assignment(self) -> "ShardAssignment | None":
        """The authoritative (epoch, shard_count) record, or None when
        the pipeline has never been sharded."""
        return None

    async def update_shard_assignment(self,
                                      assignment: ShardAssignment) -> None:
        """Persist the assignment. Epochs are MONOTONIC: storing an
        assignment whose epoch is lower than the current record's is a
        typed error (a stale coordinator must never roll the fleet
        back)."""
        raise EtlError(
            ErrorKind.STATE_STORE_FAILED,
            f"{type(self).__name__} does not persist shard assignments")

    # -- autoscale decision-journal surface (docs/autoscale.md) ---------------
    # Same stance as the shard surface: concrete defaults so third-party
    # and test stores that never autoscale keep working unchanged; the
    # memory and sql backends override both with real persistence. The
    # journal is one small JSON document (etl_tpu/autoscale/controller.py
    # AutoscaleJournal shape) rewritten atomically per decision.

    async def get_autoscale_journal(self) -> "dict | None":
        """The persisted autoscale decision journal, or None when no
        controller has ever run against this pipeline."""
        return None

    async def update_autoscale_journal(self, journal: dict) -> None:
        """Persist the journal document. Decision ids inside it are
        MONOTONIC; storing a journal whose latest decision id is lower
        than the current record's is a typed error (a stale controller
        must never rewind the decision history)."""
        raise EtlError(
            ErrorKind.STATE_STORE_FAILED,
            f"{type(self).__name__} does not persist autoscale journals")

    # -- fleet spec / actuation-journal surface (docs/fleet.md) ---------------
    # Same stance again: concrete defaults so stores that never run under
    # a fleet coordinator keep working unchanged; the memory and sql
    # backends override with real persistence. The spec is one JSON
    # document (etl_tpu/fleet/spec.py FleetSpec shape) rewritten
    # atomically per edit; journals are one small JSON document per
    # PIPELINE (etl_tpu/fleet/journal.py ActuationJournal shape) so two
    # pipelines' actuations never contend on one row.

    async def get_fleet_spec(self) -> "dict | None":
        """The persisted desired-state fleet spec, or None when no
        operator has ever submitted one."""
        return None

    async def update_fleet_spec(self, spec: dict) -> None:
        """Persist the spec document. Spec versions are MONOTONIC;
        storing a spec whose version is lower than the current record's
        is a typed error (a stale operator or partitioned coordinator
        must never roll the fleet's desired state back)."""
        raise EtlError(
            ErrorKind.STATE_STORE_FAILED,
            f"{type(self).__name__} does not persist fleet specs")

    async def get_fleet_journal(self, pipeline_id: int) -> "dict | None":
        """One pipeline's persisted actuation journal, or None when the
        reconciler has never actuated it."""
        return None

    async def get_fleet_journals(self) -> "dict[int, dict]":
        """Every persisted actuation journal keyed by pipeline id — the
        successor coordinator's resume scan."""
        return {}

    async def update_fleet_journal(self, pipeline_id: int,
                                   journal: dict) -> None:
        """Persist one pipeline's journal document. Decision ids inside
        it are MONOTONIC; storing a journal whose next_id is lower than
        the current record's is a typed error (a stale coordinator must
        never rewind the actuation history)."""
        raise EtlError(
            ErrorKind.STATE_STORE_FAILED,
            f"{type(self).__name__} does not persist fleet journals")

    # -- dead-letter / quarantine surface (docs/dead-letter.md) ---------------
    # Concrete defaults like the shard and autoscale surfaces: stores
    # that never see poison keep working unchanged — READS return empty
    # (so the apply loop and CLI degrade to "no DLQ"), WRITES raise a
    # typed error (the isolation protocol then re-raises the original
    # poison error instead of silently dropping rows). The memory and
    # sql backends override all of them with real persistence.

    async def append_dead_letters(
            self, entries: "Sequence[DeadLetterEntry]") -> "list[int]":
        """Persist poison rows; returns assigned entry ids. MUST be an
        idempotent keyed upsert on `DeadLetterEntry.key()` (attempts
        accumulate) — a crash between bisection and ack re-streams the
        batch and re-appends the same rows."""
        raise EtlError(
            ErrorKind.STATE_STORE_FAILED,
            f"{type(self).__name__} does not persist dead letters")

    async def list_dead_letters(
            self, table_id: "TableId | None" = None,
            status: "str | None" = DLQ_STATUS_DEAD
    ) -> "list[DeadLetterEntry]":
        """Entries in id order, optionally filtered by table and status
        (None = every status)."""
        return []

    async def get_dead_letter(self,
                              entry_id: int) -> "DeadLetterEntry | None":
        return None

    async def set_dead_letter_status(self, entry_id: int,
                                     status: str) -> None:
        """dead → replayed/discarded transitions (operator CLI)."""
        raise EtlError(
            ErrorKind.STATE_STORE_FAILED,
            f"{type(self).__name__} does not persist dead letters")

    async def purge_dead_letters(self, older_than_s: float,
                                 statuses: "Sequence[str]" = (
                                     DLQ_STATUS_REPLAYED,
                                     DLQ_STATUS_DISCARDED)) -> int:
        """TTL compaction: delete entries in `statuses` whose last
        append/status transition is older than `older_than_s` seconds.
        Returns the number purged. Entries still `dead` are the
        zero-loss ledger and MUST NOT be offered for expiry; a store
        with no DLQ surface compacts nothing."""
        return 0

    async def get_quarantined_tables(self
                                     ) -> "dict[TableId, QuarantineRecord]":
        return {}

    async def set_table_quarantine(
            self, table_id: TableId,
            record: "QuarantineRecord | None") -> None:
        """Persist (record) or lift (None) a table's quarantine."""
        raise EtlError(
            ErrorKind.STATE_STORE_FAILED,
            f"{type(self).__name__} does not persist quarantine records")

    @abc.abstractmethod
    async def get_destination_metadata(
        self, table_id: TableId) -> DestinationTableMetadata | None: ...

    @abc.abstractmethod
    async def update_destination_metadata(
        self, meta: DestinationTableMetadata) -> None: ...

    @abc.abstractmethod
    async def delete_destination_metadata(self, table_id: TableId) -> None: ...


class SchemaStore(abc.ABC):
    @abc.abstractmethod
    async def store_table_schema(self, schema: ReplicatedTableSchema,
                                 snapshot_id: SnapshotId) -> None: ...

    @abc.abstractmethod
    async def get_table_schema(
        self, table_id: TableId,
        at_snapshot: SnapshotId | None = None
    ) -> ReplicatedTableSchema | None:
        """Latest version with snapshot_id ≤ at_snapshot (or overall latest
        when at_snapshot is None) — reference schema/base.rs `get ≤`."""

    @abc.abstractmethod
    async def get_schema_versions(self, table_id: TableId) -> list[SnapshotId]: ...

    @abc.abstractmethod
    async def get_table_ids_with_schemas(self) -> "list[TableId]":
        """Tables that have at least one stored schema version (the
        cleanup task's iteration set)."""

    @abc.abstractmethod
    async def prune_schema_versions(self, table_id: TableId,
                                    older_than: SnapshotId) -> int:
        """Drop versions strictly older than the newest one ≤ `older_than`
        (keeping that one: it is still the decode view for `older_than`)."""

    @abc.abstractmethod
    async def delete_table_schemas(self, table_id: TableId) -> None: ...


class PipelineStore(StateStore, SchemaStore, abc.ABC):
    """The full store facade a pipeline needs (reference capabilities.rs).

    Compound lifecycle ops (reference store/lifecycle.rs):"""

    async def prepare_table_for_copy(self, table_id: TableId) -> None:
        """Reset to DataSync and drop schema versions — the crash-consistent
        pre-copy reset (reference table_sync/mod.rs:225-241)."""
        await self.update_table_state(table_id, TableState.data_sync())
        await self.delete_table_schemas(table_id)

    async def reset_table(self, table_id: TableId) -> None:
        """Full-resync reset. Destination metadata is deliberately KEPT: it
        is the marker telling the next copy attempt to drop the (still
        populated) destination table first — deleting it here would make an
        invalidated-slot resync duplicate every existing row."""
        await self.update_table_state(table_id, TableState.init())
        await self.delete_table_schemas(table_id)

    async def purge_table(self, table_id: TableId) -> None:
        await self.delete_table_state(table_id)
        await self.delete_table_schemas(table_id)
        await self.delete_destination_metadata(table_id)
