"""Benchmark harness: the reference etl-benchmarks surface.

Modes (reference crates/etl-benchmarks/src/{table_copy,table_streaming}.rs):
  decode           WAL records/sec decoded, TPU vs CPU (bench.py default)
  table_copy       full-pipeline initial copy: rows/s, MiB/s, phase timings
  table_streaming  CDC through the pipeline: producer + end-to-end events/s
  wide_row         100-column mixed-type decode (BASELINE.json config)

Each mode emits a JSON report; `python -m etl_tpu.benchmarks.compare A B`
diffs two reports (reference `cargo x benchmark-compare`).
"""

from __future__ import annotations

import asyncio
import statistics
import time


async def _wait_background_compiles(timeout_s: float = 240.0) -> None:
    """Poll engine.background_compiles_inflight() to zero before opening a
    measured window, failing loudly instead of hanging the bench when a
    build wedges (or a spawn failure leaks a key)."""
    from ..ops import engine as _engine

    deadline = time.monotonic() + timeout_s
    while _engine.background_compiles_inflight():
        if time.monotonic() >= deadline:
            raise TimeoutError(
                "background host-program compiles still in flight after "
                f"{timeout_s:.0f}s; refusing to open a measured window")
        await asyncio.sleep(0.05)


def _median(xs):
    return statistics.median(xs)


def _pipeline_metrics() -> dict:
    """Snapshot of the decode-pipeline stage metrics (ops/pipeline.py):
    per-stage totals + the overlap counters. Benches report the DELTA over
    their measured window (snapshot before and after, subtract)."""
    from ..telemetry.metrics import (
        ETL_DECODE_DISPATCH_SECONDS, ETL_DECODE_FETCH_SECONDS,
        ETL_DECODE_PACK_SECONDS, ETL_DECODE_PIPELINE_OVERLAP_SECONDS_TOTAL,
        ETL_DECODE_PIPELINE_PACK_SECONDS_TOTAL, registry)

    out = {}
    for key, name in (("pack", ETL_DECODE_PACK_SECONDS),
                      ("dispatch", ETL_DECODE_DISPATCH_SECONDS),
                      ("fetch", ETL_DECODE_FETCH_SECONDS)):
        count, total = registry.get_histogram(name)
        out[f"{key}_batches"] = count
        out[f"{key}_seconds"] = total
    out["overlap_seconds"] = registry.get_counter(
        ETL_DECODE_PIPELINE_OVERLAP_SECONDS_TOTAL)
    out["pipeline_pack_seconds"] = registry.get_counter(
        ETL_DECODE_PIPELINE_PACK_SECONDS_TOTAL)
    return out


def _admission_metrics() -> dict:
    """Snapshot of the fair batch-admission scheduler and mesh-sharded
    decode counters (ops/pipeline.AdmissionScheduler, ops/engine mesh
    path). Per-tenant labels roll up via sum_* — benches report the delta
    over their measured window."""
    from ..telemetry.metrics import (
        ETL_DECODE_ADMISSION_BYPASS_GRANTS_TOTAL,
        ETL_DECODE_ADMISSION_GRANTS_TOTAL,
        ETL_DECODE_ADMISSION_STARVATION_GRANTS_TOTAL,
        ETL_DECODE_ADMISSION_WAIT_SECONDS, ETL_DECODE_MESH_BATCHES_TOTAL,
        ETL_DECODE_MESH_PADDED_ROWS_TOTAL, ETL_DECODE_MESH_ROWS_TOTAL,
        registry)

    waits, wait_seconds = registry.sum_histogram(
        ETL_DECODE_ADMISSION_WAIT_SECONDS)
    return {
        "admission_grants": registry.sum_counter(
            ETL_DECODE_ADMISSION_GRANTS_TOTAL),
        "admission_starvation_grants": registry.sum_counter(
            ETL_DECODE_ADMISSION_STARVATION_GRANTS_TOTAL),
        "admission_bypass_grants": registry.sum_counter(
            ETL_DECODE_ADMISSION_BYPASS_GRANTS_TOTAL),
        "admission_waits": waits,
        "admission_wait_seconds": wait_seconds,
        "mesh_batches": registry.get_counter(ETL_DECODE_MESH_BATCHES_TOTAL),
        "mesh_rows": registry.get_counter(ETL_DECODE_MESH_ROWS_TOTAL),
        "mesh_padded_rows": registry.get_counter(
            ETL_DECODE_MESH_PADDED_ROWS_TOTAL),
    }


def _filter_metrics() -> dict:
    """Snapshot of the fused publication-row-filter counters (ops/engine
    filtered completion): rows compacted out of decode output and the
    bytes the packed-result fetch actually moved. Benches report the
    delta over their measured window — the fetched-bytes delta is the
    MEASURED evidence behind the "fetch scales with selectivity" claim,
    not an assumption."""
    from ..telemetry.metrics import (ETL_DECODE_FETCHED_BYTES_TOTAL,
                                     ETL_DECODE_ROWS_FILTERED_TOTAL,
                                     registry)

    return {
        "decode_rows_filtered": registry.get_counter(
            ETL_DECODE_ROWS_FILTERED_TOTAL),
        "decode_fetched_bytes": registry.get_counter(
            ETL_DECODE_FETCHED_BYTES_TOTAL),
    }


def _compile_metrics() -> dict:
    """Snapshot of the program-store counters (ops/program_store.py).
    Benches report the delta over their measured window: nonzero
    programs_compiled inside a window means the warmup missed a
    signature and the window paid an XLA build — the cost the canonical
    layout cache + prewarm exist to make visible and then kill."""
    from ..telemetry.metrics import (ETL_COMPILE_CACHE_HITS_TOTAL,
                                     ETL_COMPILE_CACHE_MISSES_TOTAL,
                                     ETL_PROGRAMS_COMPILED_TOTAL, registry)

    return {
        "programs_compiled":
            registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL),
        "compile_cache_hits_memory": registry.get_counter(
            ETL_COMPILE_CACHE_HITS_TOTAL, {"layer": "memory"}),
        "compile_cache_hits_disk": registry.get_counter(
            ETL_COMPILE_CACHE_HITS_TOTAL, {"layer": "disk"}),
        "compile_cache_misses": registry.sum_counter(
            ETL_COMPILE_CACHE_MISSES_TOTAL),
    }


# ---------------------------------------------------------------------------
# table_copy (reference table_copy.rs:74-183)
# ---------------------------------------------------------------------------


async def run_table_copy(n_rows: int = 1_000_000, samples: int = 3,
                         engine: str = "tpu",
                         destination: str = "null") -> dict:
    """Initial-copy throughput. 1M rows (reference table_copy.rs seeds
    1M-row pgbench tables): at 100k rows the ~0.1s state-machine handoff
    latency — not copy throughput — dominates the window."""
    from ..config import BatchConfig, BatchEngine, PipelineConfig
    from ..destinations import MemoryDestination
    from ..destinations.base import Destination, WriteAck
    from ..models import ColumnSchema, Oid, TableName, TableSchema
    from ..models.table_state import TableStateType
    from ..postgres.codec.copy_text import encode_copy_row
    from ..postgres.fake import FakeDatabase, FakeSource
    from ..runtime import Pipeline
    from ..store import NotifyingStore

    TID = 16384
    rows = [[str(i), str(i % 100), str(i * 7 % 10**9), "x" * 64]
            for i in range(n_rows)]
    copy_bytes = sum(len(encode_copy_row(r)) + 1 for r in rows)
    schema_def = TableSchema(
        TID, TableName("public", "bench_copy"),
        (ColumnSchema("id", Oid.INT8, nullable=False,
                      primary_key_ordinal=1),
         ColumnSchema("bucket", Oid.INT4),
         ColumnSchema("val", Oid.INT8),
         ColumnSchema("filler", Oid.TEXT)))

    class CopyCountDestination(Destination):
        """Counts copied rows; resolving batch.num_rows forces the decode,
        so device/host decode stays on the measured path — the reference
        null-destination stance (etl-benchmarks), matching
        run_table_streaming."""

        def __init__(self):
            self.rows_delivered = 0

        async def startup(self):
            return None

        async def write_table_rows(self, schema, batch):
            self.rows_delivered += batch.num_rows
            return WriteAck.durable()

        async def write_events(self, events):
            return WriteAck.durable()

        async def drop_table(self, table_id, schema=None):
            return None

        async def truncate_table(self, table_id):
            return None

    # warmup OFF the clock: backend init (~6s on a tunnel-attached chip)
    # and the per-(schema, row-bucket) decode-program compiles are one-time
    # process costs a steady-state pipeline has already paid
    from ..models.schema import ReplicatedTableSchema
    from ..ops.engine import DeviceDecoder
    from ..ops.staging import stage_copy_chunk

    if engine == "tpu":
        warm_schema = ReplicatedTableSchema.with_all_columns(schema_def)
        warm_dec = DeviceDecoder(warm_schema)
        # every row bucket a partition flush can stage (the 8 MiB batch
        # threshold lands ~98k-row chunks in the 131072 bucket); 131_071
        # not 131_072 — the exact bucket size would route to the DEVICE
        # path (n_rows ≥ device_min_rows) while in-window chunks stay
        # under it and need the HOST program for that bucket
        warm_lines = [encode_copy_row(r) for r in rows[:131_071]]
        for k in (512, 4096, 16_384, 65_536, 131_071):
            chunk = b"\n".join(warm_lines[:min(k, len(warm_lines))]) + b"\n"
            warm_dec.decode(stage_copy_chunk(chunk, 4))

    results = []
    for _ in range(samples):
        db = FakeDatabase()
        db.create_table(schema_def, rows=rows)
        db.create_publication("pub", [TID])
        store = NotifyingStore()
        dest = CopyCountDestination() if destination == "null" \
            else MemoryDestination()
        pipeline = Pipeline(
            config=PipelineConfig(
                pipeline_id=1, publication_name="pub",
                batch=BatchConfig(max_fill_ms=40,
                                  batch_engine=BatchEngine(engine))),
            store=store, destination=dest,
            source_factory=lambda: FakeSource(db))
        t0 = time.perf_counter()
        await pipeline.start()
        t_started = time.perf_counter()
        await asyncio.wait_for(store.notify_on(TID, TableStateType.READY), 300)
        t_copied = time.perf_counter()
        await pipeline.shutdown_and_wait()
        t_done = time.perf_counter()
        results.append({
            "pipeline_start_ms": (t_started - t0) * 1000,
            "copy_wait_ms": (t_copied - t_started) * 1000,
            "shutdown_ms": (t_done - t_copied) * 1000,
            "total_ms": (t_done - t0) * 1000,
            "rows_per_second": n_rows / (t_copied - t_started),
            "mib_per_second":
                copy_bytes / (1 << 20) / (t_copied - t_started),
        })
    agg = {k: _median([r[k] for r in results]) for k in results[0]}
    return {"mode": "table_copy", "rows": n_rows, "samples": samples,
            "engine": engine, "destination": destination,
            **{k: round(v, 2) for k, v in agg.items()}}


# ---------------------------------------------------------------------------
# table_streaming (reference table_streaming.rs:86-118)
# ---------------------------------------------------------------------------


async def run_table_streaming(n_events: int = 500_000, tx_size: int = 500,
                              engine: str = "tpu",
                              destination: str = "null",
                              max_fill_ms: int = 30,
                              arrival_rate: int | None = None) -> dict:
    """CDC throughput + p50 end-to-end replication lag.

    destination='null' counts delivered rows without materializing
    per-row Python objects (reference etl-benchmarks null destination
    mode) — it still RESOLVES every decoded batch, so the device decode
    is on the measured path; 'memory' exercises full row expansion.
    The default fill window (30 ms, measured optimum in a 5-80 ms sweep)
    keeps one flush in flight continuously: the XLA host-backend decode
    executes on its own thread pool, so steady small flushes overlap
    decode/resolve with WAL intake where a large window would alternate
    idle-accumulate and burst-decode phases on this single-core host.

    arrival_rate=None produces as fast as possible (drain-style: the
    throughput number is the headline, lag measures queue depth under
    saturation). arrival_rate=N paces production to N events/s in 10 ms
    ticks — the lag percentiles then measure real end-to-end latency at
    that offered load (the BASELINE.md "p50 end-to-end replication lag"
    reading; see run_lag_vs_rate).
    """
    from ..config import BatchConfig, BatchEngine, PipelineConfig
    from ..destinations import MemoryDestination
    from ..destinations.base import Destination, WriteAck
    from ..models import (ColumnSchema, InsertEvent, Oid, TableName,
                          TableSchema)
    from ..models.event import DecodedBatchEvent
    from ..models.table_state import TableStateType
    from ..postgres.fake import FakeDatabase, FakeSource
    from ..runtime import Pipeline
    from ..store import NotifyingStore

    TID = 16385
    db = FakeDatabase()
    db.create_table(TableSchema(
        TID, TableName("public", "bench_stream"),
        (ColumnSchema("id", Oid.INT8, nullable=False, primary_key_ordinal=1),
         ColumnSchema("v", Oid.INT4),
         ColumnSchema("note", Oid.TEXT))))
    db.create_publication("pub", [TID])
    store = NotifyingStore()

    # p50 end-to-end replication lag (a named BASELINE metric): per-event
    # lag = destination arrival − source commit of its transaction
    commit_times: dict[int, float] = {}
    arrivals: list[tuple[int, float]] = []

    class NullDestination(Destination):
        """Counts delivered rows; resolves (but never row-expands) decoded
        batches — the reference null-destination stance."""

        def __init__(self):
            self.rows_delivered = 0

        async def startup(self):
            return None

        async def write_table_rows(self, schema, batch):
            return WriteAck.durable()

        async def write_events(self, events):
            import numpy as np

            now = time.perf_counter()
            for e in events:
                if isinstance(e, DecodedBatchEvent):
                    self.rows_delivered += e.batch.num_rows  # forces decode
                    for lsn in np.unique(e.commit_lsns).tolist():
                        arrivals.append((int(lsn), now))
                elif isinstance(e, InsertEvent):
                    self.rows_delivered += 1
                    arrivals.append((int(e.commit_lsn), now))
            return WriteAck.durable()

        async def drop_table(self, table_id, schema=None):
            return None

        async def truncate_table(self, table_id):
            return None

    class LagMeasuringDestination(MemoryDestination):
        rows_delivered = property(lambda self: sum(
            1 for e in self.events if isinstance(e, InsertEvent)))

        async def write_events(self, events):
            from ..destinations.base import expand_batch_events

            ack = await super().write_events(events)
            now = time.perf_counter()
            for e in expand_batch_events(events):
                if isinstance(e, InsertEvent):
                    arrivals.append((int(e.commit_lsn), now))
            return ack

    dest = NullDestination() if destination == "null" \
        else LagMeasuringDestination()
    pipeline = Pipeline(
        config=PipelineConfig(
            pipeline_id=1, publication_name="pub",
            batch=BatchConfig(max_fill_ms=max_fill_ms,
                              batch_engine=BatchEngine(engine))),
        store=store, destination=dest,
        source_factory=lambda: FakeSource(db))
    await pipeline.start()
    await asyncio.wait_for(store.notify_on(TID, TableStateType.READY), 60)

    # warmup: drive transactions through the full path so the per-schema
    # jit compiles of the host-vectorized decode program (a one-time cost,
    # like the decode bench's warmup) land outside the measured window.
    # The decode program is keyed by (row bucket, field-width signature),
    # so the waves are sized to touch every ROW_BUCKET a measured flush
    # can land in (1024 / 4096 / 16384) and encode the SAME value shapes
    # as the measured payloads (a different field width would compile a
    # different program and the warmup would warm nothing).
    from ..postgres.codec.pgoutput import encode_insert as _enc

    def _payload(i: int) -> bytes:
        return _enc(TID, [str(i).encode(), str(i % 97).encode(),
                          b"note-%d" % i])

    async def wait_delivered_at_least(n: int) -> None:
        while dest.rows_delivered < n:
            if pipeline._apply_task is not None \
                    and pipeline._apply_task.done():
                pipeline._apply_task.result()  # surface the pipeline error
                raise RuntimeError("pipeline stopped during warmup")
            await asyncio.sleep(0.02)

    # each wave is awaited to delivery before the next starts so waves
    # can't coalesce into one run (which would warm only the largest
    # bucket); sizes land in buckets 256 / 1024 / 4096 / 16384 — runs
    # seal at RUN_SEAL_ROWS so no measured flush can stage beyond 16384
    warmup_rows = 0
    w = 0
    for wave in (200, 800, 3000, 13000):
        tx = db.transaction()
        for _ in range(wave):
            tx.insert_preencoded(TID, _payload(w))
            w += 1
        await tx.commit()
        warmup_rows += wave
        await asyncio.wait_for(wait_delivered_at_least(warmup_rows), 120)
    # the streaming decoders compile cold host programs on BACKGROUND
    # threads (engine.nonblocking_compile) and serve the triggering
    # batches from the oracle — wait the builds out so the measured
    # window runs the warm programs, not the transient fallback
    await _wait_background_compiles()
    arrivals.clear()
    commit_times.clear()
    # baseline BEFORE production starts: measured rows deliver concurrently
    # with the producer loop, so a later capture would double-count them
    base_delivered = dest.rows_delivered

    # payload encode happens OFF the clock: the reference bench's producer
    # is a separate Postgres server, not a Python encoder stealing the
    # pipeline's only core — the measured window covers walsender framing
    # + wire + pipeline, which is the system under test
    from ..postgres.codec.pgoutput import encode_insert
    payloads = [encode_insert(TID, [str(i).encode(), str(i % 97).encode(),
                                    b"note-%d" % i])
                for i in range(n_events)]

    # ALSO off the clock: the device decode programs for the mega-seal
    # buckets backlog growth can reach. Saturation drains grow seals
    # 16384 → 65536 → 262144 (runtime/assembler.MEGA_SEAL_ROWS); on a
    # real accelerator each unwarmed (bucket, widths) program costs a
    # 10-40s compile that would otherwise land mid-window. Staging the
    # MEASURED payloads keeps the width signature identical.
    import jax as _jax

    if engine == "tpu" and _jax.default_backend() != "cpu" \
            and arrival_rate is None and n_events >= 65_536:
        from ..models.schema import ReplicatedTableSchema as _RTS
        from ..ops.engine import DeviceDecoder as _DD
        from ..ops.wal import concat_payloads as _concat
        from ..ops.wal import stage_wal_batch as _stage

        _wdec = _DD(_RTS.with_all_columns(db.tables[TID].schema))
        for _bucket in (65_536, 131_072, 262_144):
            if _bucket > len(payloads):
                break
            _buf, _offs, _lens = _concat(payloads[:_bucket])
            _wal = _stage(_buf, _offs, _lens, 3)
            _wdec.decode(_wal.staged)

    from ..telemetry.metrics import (ETL_DECODE_ROUTED_DEVICE_ROWS_TOTAL,
                                     ETL_DECODE_ROUTED_HOST_ROWS_TOTAL,
                                     ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL,
                                     registry as _registry)

    def _routed():
        return {k: _registry.get_counter(n) for k, n in (
            ("device", ETL_DECODE_ROUTED_DEVICE_ROWS_TOTAL),
            ("host", ETL_DECODE_ROUTED_HOST_ROWS_TOTAL),
            ("oracle", ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL))}

    routed0 = _routed()
    stages0 = _pipeline_metrics()
    adm0 = _admission_metrics()
    filt0 = _filter_metrics()
    comp0 = _compile_metrics()
    # row-materialization gate input: zero constructions over the measured
    # window = the egress path stayed columnar fetch-to-wire (the smoke
    # gate asserts this on the null destination; 'memory' exercises the
    # row-expansion shim and reports its cost honestly)
    from ..telemetry.metrics import publish_table_rows_constructed

    rows_constructed0 = publish_table_rows_constructed()

    t_prod0 = time.perf_counter()
    produced = 0
    if arrival_rate:
        tick = 0.01
        per_tick = max(1, int(arrival_rate * tick))
        next_t = t_prod0
        while produced < n_events:
            tx = db.transaction()
            for _ in range(min(per_tick, n_events - produced)):
                tx.insert_preencoded(TID, payloads[produced])
                produced += 1
            lsn = await tx.commit()
            commit_times[int(lsn)] = time.perf_counter()
            next_t += tick
            delay = next_t - time.perf_counter()
            if delay > 0:
                await asyncio.sleep(delay)
    else:
        while produced < n_events:
            tx = db.transaction()
            for _ in range(min(tx_size, n_events - produced)):
                tx.insert_preencoded(TID, payloads[produced])
                produced += 1
            lsn = await tx.commit()
            commit_times[int(lsn)] = time.perf_counter()
    t_prod1 = time.perf_counter()

    def delivered():
        return dest.rows_delivered - base_delivered

    async def wait_delivered():
        while delivered() < n_events:
            if pipeline._apply_task is not None \
                    and pipeline._apply_task.done():
                pipeline._apply_task.result()  # surface the pipeline error
                raise RuntimeError("pipeline stopped before delivering")
            await asyncio.sleep(0.02)

    await asyncio.wait_for(wait_delivered(), timeout=300)
    t_e2e = time.perf_counter()
    await pipeline.shutdown_and_wait()
    t_drain = time.perf_counter()
    # decode routing over the measured window: under saturation the
    # backlog signal grows seals past the measured device threshold, so
    # the device share reports how much of the steady-state data plane
    # actually ran on the accelerator (VERDICT r4 #1c — a host-only
    # steady state can no longer hide behind the throughput number)
    routed1 = _routed()
    routed = {k: routed1[k] - routed0[k] for k in routed1}
    routed_total = sum(routed.values())
    stages1 = _pipeline_metrics()
    stages = {k: stages1[k] - stages0[k] for k in stages1}
    adm1 = _admission_metrics()
    adm = {k: adm1[k] - adm0[k] for k in adm1}
    filt1 = _filter_metrics()
    filt = {k: filt1[k] - filt0[k] for k in filt1}
    comp1 = _compile_metrics()
    comp = {k: comp1[k] - comp0[k] for k in comp1}
    pack_s = stages["pipeline_pack_seconds"]
    lags_ms = [(t - commit_times[lsn]) * 1000 for lsn, t in arrivals
               if lsn in commit_times]
    lags_ms.sort()

    def pct(p):
        return lags_ms[min(len(lags_ms) - 1,
                           int(p * len(lags_ms)))] if lags_ms else None

    return {
        "mode": "table_streaming", "events": n_events, "engine": engine,
        "destination": destination, "arrival_rate": arrival_rate,
        "producer_events_per_second":
            round(n_events / (t_prod1 - t_prod0)),
        "end_to_end_events_per_second":
            round(n_events / (t_e2e - t_prod0)),
        "end_to_end_with_shutdown_events_per_second":
            round(n_events / (t_drain - t_prod0)),
        "throughput_events": delivered(),
        "decode_rows_device": int(routed["device"]),
        "decode_rows_host": int(routed["host"]),
        "decode_rows_oracle": int(routed["oracle"]),
        "device_decoded_share":
            round(routed["device"] / routed_total, 3) if routed_total else 0.0,
        # decode pipeline stage activity over the measured window: the
        # overlap ratio is the share of pack time that ran concurrently
        # with another batch in flight (the three-stage scheduler's win)
        "decode_pack_seconds": round(stages["pack_seconds"], 4),
        "decode_dispatch_seconds": round(stages["dispatch_seconds"], 4),
        "decode_fetch_seconds": round(stages["fetch_seconds"], 4),
        "decode_overlap_seconds": round(stages["overlap_seconds"], 4),
        "decode_overlap_ratio":
            round(stages["overlap_seconds"] / pack_s, 3) if pack_s else 0.0,
        # fair-admission + mesh activity over the measured window: a lone
        # stream should see zero wait time (uncontended grants), and
        # mesh_* stay zero off-mesh — nonzero padded_rows/mesh_rows is
        # the padding waste the operator tunes batch sizes against
        "admission_grants": int(adm["admission_grants"]),
        "admission_starvation_grants":
            int(adm["admission_starvation_grants"]),
        "admission_wait_seconds": round(adm["admission_wait_seconds"], 4),
        "mesh_batches": int(adm["mesh_batches"]),
        "mesh_padded_rows": int(adm["mesh_padded_rows"]),
        # fused row-filter activity over the measured window (zero on
        # unfiltered publications): filtered rows never reach the fetch
        # path, and fetched_bytes is the link traffic the packed-result
        # fetches actually moved
        "decode_rows_filtered": int(filt["decode_rows_filtered"]),
        "decode_fetched_bytes": int(filt["decode_fetched_bytes"]),
        # program-store activity over the measured window: nonzero
        # programs_compiled means the window paid an XLA build the
        # warmup should have absorbed — warmup cost stops hiding
        "programs_compiled": int(comp["programs_compiled"]),
        "compile_cache_hits_memory":
            int(comp["compile_cache_hits_memory"]),
        "compile_cache_hits_disk": int(comp["compile_cache_hits_disk"]),
        "compile_cache_misses": int(comp["compile_cache_misses"]),
        "replication_lag_p50_ms":
            round(pct(0.50), 2) if lags_ms else None,
        "replication_lag_p95_ms":
            round(pct(0.95), 2) if lags_ms else None,
        "replication_lag_max_ms": round(lags_ms[-1], 2) if lags_ms else None,
        "table_rows_constructed":
            publish_table_rows_constructed() - rows_constructed0,
    }


async def run_lag_vs_rate(engine: str = "tpu",
                          fractions: tuple = (0.25, 0.5, 0.75),
                          probe_events: int = 60_000,
                          max_fill_ms: int = 5,
                          per_rate_cap: int = 240_000) -> dict:
    """p50/p95 end-to-end replication lag at fixed offered loads.

    The drain-style streaming bench saturates the pipeline, so its lag
    percentiles measure queue depth, not latency. This mode first probes
    the sustainable maximum, then replays at 25/50/75% of it with paced
    production and reports real lag per rate (BASELINE.md names "p50
    end-to-end replication lag" as a headline metric; reference gauges:
    crates/etl/src/observability.rs:46-50). The fill window is 5 ms — a
    lag-oriented batching config, reported in the output; the reference
    default (10 s, pipeline.rs:52-68) optimizes throughput instead and
    would floor every percentile at the batch deadline.
    """
    probe = await run_table_streaming(n_events=probe_events, engine=engine,
                                      max_fill_ms=max_fill_ms)
    max_rate = probe["end_to_end_events_per_second"]
    rows = []
    for f in fractions:
        rate = max(1000, int(max_rate * f))
        # ~3 s of paced traffic per rate, bounded for bench wall-clock
        # (smoke tests pass a small per_rate_cap — the paced replay
        # scales with the MEASURED host rate, not probe_events)
        n = min(max(int(rate * 3), 3000), per_rate_cap)
        out = await run_table_streaming(n_events=n, engine=engine,
                                        max_fill_ms=max_fill_ms,
                                        arrival_rate=rate)
        rows.append({
            "fraction": f,
            # the 1000 ev/s floor can raise the rate above f*max on slow
            # hosts — report the load actually offered, not the request
            "effective_fraction": round(rate / max_rate, 3) if max_rate
            else None,
            "target_rate": rate, "events": n,
            "p50_ms": out["replication_lag_p50_ms"],
            "p95_ms": out["replication_lag_p95_ms"],
            "max_ms": out["replication_lag_max_ms"],
        })
    return {
        "mode": "lag_vs_rate", "engine": engine,
        "max_events_per_second": max_rate,
        "max_fill_ms": max_fill_ms,
        "rates": rows,
    }


# ---------------------------------------------------------------------------
# workload matrix (ISSUE 7: per-profile CDC throughput beyond insert-CDC)
# ---------------------------------------------------------------------------


async def run_workload_streaming(profile: str = "update_heavy_default",
                                 seed: int = 7, steps: int | None = None,
                                 engine: str = "tpu",
                                 target_ops: int = 3_000,
                                 verify_timeout_s: float = 240.0) -> dict:
    """CDC throughput for ONE workload profile (etl_tpu/workloads) through
    the full pipeline, with end-state verification: the destination's
    reconstructed final view must equal the generator's committed source
    truth (the same collapse rules the chaos invariant checker applies) —
    a throughput number over silently-wrong deliveries would be worse
    than no number.

    The memory destination is deliberate: non-insert profiles need the
    delivered events retained for verification, and every profile pays
    the same row-expansion cost, so per-profile numbers stay comparable.
    `steps` defaults to whatever reaches ~`target_ops` row ops for the
    profile's transaction shape."""
    from ..config import BatchConfig, BatchEngine, PipelineConfig
    from ..models.table_state import TableStateType
    from ..postgres.fake import FakeSource
    from ..runtime import Pipeline
    from ..store import NotifyingStore
    from ..workloads import WorkloadGenerator, get_profile

    p = get_profile(profile)
    gen = WorkloadGenerator(p, seed=seed)
    db = gen.build_db()
    store = NotifyingStore()
    from ..chaos.runner import TracingDestination

    dest = TracingDestination()
    pipeline = Pipeline(
        config=PipelineConfig(
            pipeline_id=1, publication_name="pub",
            batch=BatchConfig(max_fill_ms=30,
                              batch_engine=BatchEngine(engine))),
        store=store, destination=dest,
        source_factory=lambda: FakeSource(db))
    async def wait_delivered():
        # `delivered()` reconstructs the destination's full final view —
        # O(events × columns) of synchronous work ON the event loop — so
        # run it only when the event stream has QUIESCED (no new events
        # across a poll interval); while deliveries are still flowing the
        # wait costs nothing but a length check (a 20 ms reconstruct
        # cadence measurably starved the apply loop on the 120-column
        # profile)
        seen = -1
        while True:
            n = len(dest.events)
            if n == seen and gen.delivered(dest):
                return
            seen = n
            if pipeline._apply_task is not None \
                    and pipeline._apply_task.done():
                pipeline._apply_task.result()
                raise RuntimeError("pipeline stopped before delivering")
            await asyncio.sleep(0.1)

    try:
        # start + READY wait inside the try: a copy-path regression that
        # keeps a table from READY must still shut the pipeline down, not
        # leak its tasks past asyncio.run()
        await pipeline.start()
        for tid in gen.table_ids:
            await asyncio.wait_for(
                store.notify_on(tid, TableStateType.READY), 120)
        # warmup OFF the clock: the decode engine compiles one program per
        # (schema, row bucket, width signature) — on the 120-column mix a
        # single compile costs tens of seconds on the host backend, so an
        # unwarmed window measures XLA compile amortization, not throughput
        # (the same stance as run_table_streaming's warmup waves)
        # the warmup wait keeps the full budget regardless of
        # verify_timeout_s: a slow first delivery is compile/stall
        # headroom, not the end-state verification the knob bounds
        warm_target = max(100, target_ops // 5)
        while gen.row_ops < warm_target:
            await gen.run_tx(db)
        await asyncio.wait_for(wait_delivered(), timeout=240)
        # wait out background host-program builds (see
        # run_table_streaming's warmup) so the measured window runs warm
        # programs
        await _wait_background_compiles()

        # explicit `steps` runs exactly that many generator steps (the
        # smoke slice); otherwise step until ~target_ops row ops
        # committed — ops per step vary wildly across profiles (a DDL
        # backfill updates every live row), so a step-count heuristic
        # alone would run away
        ops0 = gen.row_ops
        t0 = time.perf_counter()
        steps_run = 0
        while (steps_run < steps if steps is not None
               else gen.row_ops - ops0 < target_ops):
            await gen.run_tx(db)
            steps_run += 1
        t_prod = time.perf_counter()
        # wait_delivered only returns once gen.delivered(dest) held on
        # the quiesced stream; recomputing the O(events x columns)
        # reconstruction here would just repeat it
        try:
            await asyncio.wait_for(wait_delivered(),
                                   timeout=verify_timeout_s)
            verified = True
        except asyncio.TimeoutError:
            # the stream either quiesced with a destination view that
            # never matched the generator's committed truth, or stalled
            # outright — both are delivery correctness failures the
            # caller gates on, not harness errors worth a traceback
            verified = False
        t_done = time.perf_counter()
    finally:
        # guard: wait() asserts a started pipeline, and a start() that
        # raised mid-way has nothing for shutdown_and_wait to join
        if pipeline._apply_task is not None:
            await pipeline.shutdown_and_wait()
    measured = gen.row_ops - ops0
    return {
        "profile": profile,
        "seed": seed,
        "steps": steps_run,
        "row_ops": measured,
        "warmup_ops": ops0,
        "producer_events_per_second":
            round(measured / max(t_prod - t0, 1e-9)),
        "events_per_second": round(measured / max(t_done - t0, 1e-9)),
        "verified": bool(verified),
        "expected_rows": sum(len(v) for v in gen.expected.values()),
    }


async def run_workload_matrix(profiles=None, seed: int = 7,
                              engine: str = "tpu",
                              target_ops: int = 3_000) -> dict:
    """`run_workload_streaming` across the whole profile catalog (or a
    selected subset): the per-workload throughput matrix published as
    `workload_floors` in BENCH_FLOOR.json."""
    from ..workloads import profile_names

    names = list(profiles) if profiles else profile_names()
    rows = {}
    ok = True
    for name in names:
        out = await run_workload_streaming(name, seed=seed, engine=engine,
                                           target_ops=target_ops)
        rows[name] = out
        ok = ok and out["verified"]
    return {
        "mode": "workload_matrix", "engine": engine, "seed": seed,
        "profiles": rows,
        "events_per_second": {n: r["events_per_second"]
                              for n, r in rows.items()},
        "all_verified": bool(ok),
    }


async def run_multi_pipeline(profiles=None, seed: int = 7,
                             engine: str = "tpu",
                             target_ops: int = 1_000,
                             admission_capacity: int = 0,
                             verify_timeout_s: float = 240.0) -> dict:
    """N concurrent replication streams — one full Pipeline per workload
    profile (the tenancy mix) — sharing ONE device set through the fair
    batch-admission scheduler (ops/pipeline.AdmissionScheduler): the
    one-device-set-serves-many-streams shape. Every stream runs the whole
    path (fake walsender → apply loop → pipelined decode → memory
    destination) with end-state verification, so the aggregate number
    can't hide a tenant whose deliveries went wrong while the others
    kept the scheduler busy.

    Reports per-stream and AGGREGATE events/s over one shared measured
    window, the scheduler's per-tenant grant/weight stats captured while
    the tenants were still registered, the admission wait/grant counter
    deltas, and whether the scheduler drained clean (no tickets or
    tenants left after shutdown — the leak half of the chaos satellite,
    asserted here on the happy path)."""
    from ..chaos.runner import TracingDestination
    from ..config import BatchConfig, BatchEngine, PipelineConfig
    from ..models.table_state import TableStateType
    from ..ops.pipeline import global_admission, reset_global_admission
    from ..postgres.fake import FakeSource
    from ..runtime import Pipeline
    from ..store import NotifyingStore
    from ..workloads import WorkloadGenerator, get_profile

    # default mix pairs a small-flush tenant with a 512-row-transaction
    # tenant: giant_tx flushes cross the host-XLA row threshold, so the
    # run provably takes admission tickets (sub-threshold flushes decode
    # on the per-row oracle, which holds no device capacity by design)
    names = list(profiles) if profiles \
        else ["insert_heavy", "giant_tx"]
    # fresh process-wide scheduler: THIS run's capacity knob wins, and a
    # previous bench/test can't leave a different capacity behind
    reset_global_admission()

    streams = []
    for i, name in enumerate(names):
        label = name if names.count(name) == 1 else f"{name}-{i}"
        gen = WorkloadGenerator(get_profile(name), seed=seed + i)
        db = gen.build_db()
        pipeline = Pipeline(
            config=PipelineConfig(
                pipeline_id=i + 1, publication_name="pub",
                batch=BatchConfig(max_fill_ms=30,
                                  batch_engine=BatchEngine(engine),
                                  admission_capacity=admission_capacity)),
            store=(store := NotifyingStore()),
            destination=(dest := TracingDestination()),
            source_factory=lambda db=db: FakeSource(db))
        streams.append({"label": label, "gen": gen, "db": db,
                        "store": store, "dest": dest, "pipeline": pipeline})

    async def wait_verified(s) -> None:
        # same quiesce-then-reconstruct stance as run_workload_streaming:
        # the O(events × columns) final-view rebuild runs only when the
        # stream stops moving, so verification can't starve the loop
        seen = -1
        while True:
            n = len(s["dest"].events)
            if n == seen and s["gen"].delivered(s["dest"]):
                return
            seen = n
            task = s["pipeline"]._apply_task
            if task is not None and task.done():
                task.result()
                raise RuntimeError(
                    f"stream {s['label']} stopped before delivering")
            await asyncio.sleep(0.1)

    started = []
    verified: dict[str, bool] = {}
    try:
        for s in streams:
            await s["pipeline"].start()
            started.append(s)
        await asyncio.gather(*(
            asyncio.wait_for(
                s["store"].notify_on(tid, TableStateType.READY), 120)
            for s in streams for tid in s["gen"].table_ids))

        # warmup off the clock (per-schema decode-program compiles — the
        # same stance as every other harness mode), CONCURRENTLY: the
        # warmup traffic itself runs through the shared scheduler
        async def warm(s) -> None:
            warm_target = max(60, target_ops // 5)
            while s["gen"].row_ops < warm_target:
                await s["gen"].run_tx(s["db"])
            # full budget regardless of verify_timeout_s (the
            # run_workload_streaming stance): a slow first delivery is
            # compile/stall headroom, not the end-state verification
            # the knob bounds
            await asyncio.wait_for(wait_verified(s), 240)

        await asyncio.gather(*(warm(s) for s in streams))
        await _wait_background_compiles()

        adm0 = _admission_metrics()
        ops0 = {s["label"]: s["gen"].row_ops for s in streams}
        t0 = time.perf_counter()

        async def produce(s) -> None:
            base = s["gen"].row_ops
            while s["gen"].row_ops - base < target_ops:
                await s["gen"].run_tx(s["db"])

        await asyncio.gather(*(produce(s) for s in streams))
        t_prod = time.perf_counter()

        async def settle(s) -> None:
            try:
                await asyncio.wait_for(wait_verified(s), verify_timeout_s)
                verified[s["label"]] = True
            except asyncio.TimeoutError:
                verified[s["label"]] = False

        await asyncio.gather(*(settle(s) for s in streams))
        t_done = time.perf_counter()
        # tenant stats BEFORE shutdown deregisters them
        sched = global_admission(admission_capacity or None)
        sched_stats = sched.stats()
        adm1 = _admission_metrics()
    finally:
        for s in started:
            if s["pipeline"]._apply_task is not None:
                await s["pipeline"].shutdown_and_wait()

    adm = {k: adm1[k] - adm0[k] for k in adm1}
    per_stream = {}
    total_ops = 0
    for s in streams:
        measured = s["gen"].row_ops - ops0[s["label"]]
        total_ops += measured
        per_stream[s["label"]] = {
            "profile": s["gen"].profile.name,
            "row_ops": measured,
            "events_per_second": round(measured / max(t_done - t0, 1e-9)),
            "verified": bool(verified.get(s["label"], False)),
        }
    drained = sched.stats()
    return {
        "mode": "multi_pipeline", "engine": engine, "seed": seed,
        "streams": len(streams),
        "per_stream": per_stream,
        "aggregate_row_ops": total_ops,
        "aggregate_events_per_second":
            round(total_ops / max(t_done - t0, 1e-9)),
        "producer_events_per_second":
            round(total_ops / max(t_prod - t0, 1e-9)),
        "all_verified": all(per_stream[k]["verified"] for k in per_stream),
        "admission_capacity": sched_stats["capacity"],
        "admission_tenants": sched_stats["tenants"],
        "admission_grants": int(adm["admission_grants"]),
        "admission_starvation_grants":
            int(adm["admission_starvation_grants"]),
        "admission_bypass_grants": int(adm["admission_bypass_grants"]),
        "admission_wait_seconds": round(adm["admission_wait_seconds"], 4),
        "scheduler_drained": drained["in_flight"] == 0
                             and not drained["tenants"],
    }


async def run_sharded_processes(shards: int = 2,
                                profile: str = "insert_heavy",
                                seed: int = 7, tables: int = 8,
                                target_ops: int = 2_000,
                                engine: str = "tpu",
                                timeout_s: float = 600.0) -> dict:
    """K shard replicators as K OS PROCESSES (benchmarks/shard_worker.py)
    — separate interpreters, GILs, and XLA runtimes, the pod resource
    model — each replaying the identical publication WAL (the workload
    generator's byte-identical `(profile, seed)` contract) and applying
    only its ShardMap slice. The parent asserts the slices cover every
    table exactly once, every worker's slice verifies, and reports the
    aggregate events/s (sum of per-worker rates over their concurrent
    measured windows — the same aggregation run_multi_pipeline uses).

    `shards=1` spawns ONE unsharded worker over the same workload: the
    single-apply-loop baseline the acceptance bar compares against."""
    import json as _json
    import os
    import sys as _sys

    from ..sharding import ShardMap
    from ..workloads import get_profile

    get_profile(profile)  # fail fast on a typo'd profile name
    specs = []
    if shards <= 1:
        specs.append({"shard": None, "shard_count": 1})
    else:
        part = ShardMap(shards).partition(range(16384, 16384 + tables))
        if any(not owned for owned in part.values()):
            raise ValueError(
                f"degenerate shard map over {tables} tables: "
                f"{ {s: len(v) for s, v in part.items()} }")
        specs = [{"shard": s, "shard_count": shards}
                 for s in range(shards)]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")

    async def spawn(spec: dict):
        spec = dict(spec, profile=profile, seed=seed, tables=tables,
                    target_ops=target_ops, engine=engine)
        proc = await asyncio.create_subprocess_exec(
            _sys.executable, "-m", "etl_tpu.benchmarks.shard_worker",
            _json.dumps(spec),
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE, env=env)
        try:
            out, err = await asyncio.wait_for(proc.communicate(),
                                              timeout_s)
        except asyncio.TimeoutError:
            proc.kill()
            await proc.wait()
            raise TimeoutError(
                f"shard worker {spec.get('shard')} did not finish in "
                f"{timeout_s:.0f}s")
        lines = out.decode().strip().splitlines()
        if proc.returncode != 0 or not lines:
            raise RuntimeError(
                f"shard worker {spec.get('shard')} failed "
                f"(rc={proc.returncode}): {err.decode()[-400:]}")
        return _json.loads(lines[-1])

    results = await asyncio.gather(*(spawn(s) for s in specs))

    owned_union: list = []
    for r in results:
        owned_union.extend(r["owned_table_ids"])
    expected_ids = list(range(16384, 16384 + tables))
    union_ok = sorted(owned_union) == expected_ids if shards > 1 \
        else results[0]["owned_table_ids"] == expected_ids
    return {
        "mode": "sharded", "engine": engine, "seed": seed,
        "profile": profile, "shards": max(1, shards), "tables": tables,
        "per_shard": {str(r["shard"]): r for r in results},
        "tables_per_shard": {str(r["shard"]): r["tables"]
                             for r in results},
        "aggregate_row_events": sum(r["delivered_row_events"]
                                    for r in results),
        "aggregate_events_per_second": sum(r["events_per_second"]
                                           for r in results),
        "all_verified": all(r["verified"] for r in results),
        "union_covers_all_tables": bool(union_ok),
    }


# ---------------------------------------------------------------------------
# egress (per-destination encoder isolation: ColumnarBatch → wire bytes)
# ---------------------------------------------------------------------------


def _egress_batch(n_rows: int, egress: "str | None" = None):
    """A decode-engine-shaped ColumnarBatch (dense ints + Arrow strings)
    on the pgbench-CDC column mix, produced through the REAL staging +
    decode path so the encoders see production column storage. With
    `egress` set the decode fuses the wire-encoding stage and the batch
    carries `device_egress` buffers (ops/egress.py)."""
    from ..models import (ColumnSchema, Oid, ReplicatedTableSchema,
                          TableName, TableSchema)
    from ..ops.engine import DeviceDecoder
    from ..ops.wal import concat_payloads, stage_wal_batch
    from ..postgres.codec.pgoutput import encode_insert

    tid = 16390
    schema = ReplicatedTableSchema.with_all_columns(TableSchema(
        tid, TableName("public", "bench_egress"),
        (ColumnSchema("id", Oid.INT8, nullable=False, primary_key_ordinal=1),
         ColumnSchema("bucket", Oid.INT4),
         ColumnSchema("val", Oid.FLOAT8),
         ColumnSchema("note", Oid.TEXT))))
    payloads = [encode_insert(tid, [str(i).encode(), str(i % 97).encode(),
                                    (b"%d.5" % i), b"note-%d" % i])
                for i in range(n_rows)]
    buf, offs, lens = concat_payloads(payloads)
    wal = stage_wal_batch(buf, offs, lens, 4)
    batch = DeviceDecoder(schema, egress=egress).decode(wal.staged)
    return schema, batch


def run_egress(n_rows: int = 16_384, n_iters: int = 5,
               device: bool = False) -> dict:
    """Measure each destination encoder in ISOLATION (ColumnarBatch →
    wire bytes): rows/s and bytes/s for the BigQuery proto encoder, the
    ClickHouse TSV renderer, and the Parquet row-group writer — so an
    egress regression names the guilty encoder instead of hiding inside
    the end-to-end streaming number. Floors: BENCH_FLOOR.json
    `egress_floors` (rows/s, min over encoders asserted by --smoke).

    `device=True` additionally measures the device-resident egress seam
    (ISSUE 17): batches decoded WITH the fused wire-encoding stage run
    through the piece-assembly fast paths splicing the device-rendered
    buffers — and the produced bytes are compared against the columnar
    oracles (`*_identical`, gated by --smoke: byte identity is the
    contract that lets the fast path exist at all)."""
    import io

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ..destinations import bq_proto
    from ..destinations.clickhouse import render_batch_tsv_columnar
    from ..destinations.util import (CHANGE_SEQUENCE_COLUMN,
                                     CHANGE_TYPE_COLUMN, change_type_arrow,
                                     change_type_batch,
                                     sequence_number_arrow,
                                     sequence_number_batch)

    schema, batch = _egress_batch(n_rows)
    cts = np.zeros(n_rows, dtype=np.int64)
    lsns = np.arange(n_rows, dtype=np.uint64) + (1 << 40)
    txos = np.arange(n_rows, dtype=np.uint64)
    ords = np.arange(n_rows, dtype=np.uint64)

    def timed(fn):
        times = []
        nbytes = 0
        for _ in range(n_iters):
            t0 = time.perf_counter()
            nbytes = fn()
            times.append(time.perf_counter() - t0)
        # min over iters: shared-host noise is one-sided (bench.py policy)
        dt = min(times)
        return round(n_rows / dt), round(nbytes / dt)

    def bq():
        labels = change_type_batch(cts).tolist()
        seqs = sequence_number_batch(lsns, txos, ords)
        rows = bq_proto.encode_batch(schema, batch, labels, seqs)
        return sum(len(r) for r in rows)

    def clickhouse():
        labels = [t.decode() for t in change_type_batch(cts).tolist()]
        seqs = [s.decode()
                for s in sequence_number_batch(lsns, txos, ords)]
        return len(render_batch_tsv_columnar(schema, batch, labels, seqs))

    def parquet():
        rb = batch.to_arrow()
        rb = rb.append_column(CHANGE_TYPE_COLUMN, change_type_arrow(cts))
        rb = rb.append_column(CHANGE_SEQUENCE_COLUMN,
                              sequence_number_arrow(lsns, txos, ords))
        sink = io.BytesIO()
        pq.write_table(pa.Table.from_batches([rb]), sink)
        return sink.tell()

    def snowpipe():
        # NDJSON line encoding only — zstd compression is a C library
        # pass-through unchanged by the columnar refactor (and absent on
        # this container); the Python-cost part the floor guards is the
        # per-row dict + json.dumps the columnar encoder eliminated
        from ..destinations.snowflake import (encode_batch_ndjson,
                                              offset_token_batch)

        labels = ["insert"] * n_rows
        seqs = offset_token_batch(lsns, txos)
        lines = encode_batch_ndjson(schema, batch, labels, seqs)
        return sum(len(ln) for ln in lines)

    out: dict = {"mode": "egress", "rows": n_rows, "iters": n_iters}
    for name, fn in (("bq_proto", bq), ("clickhouse_tsv", clickhouse),
                     ("parquet", parquet), ("snowpipe_ndjson", snowpipe)):
        rps, bps = timed(fn)
        out[f"{name}_rows_per_sec"] = rps
        out[f"{name}_bytes_per_sec"] = bps
    if device:
        out.update(_run_egress_device(n_rows, n_iters, timed,
                                      lsns, txos, ords))
    return out


def _run_egress_device(n_rows: int, n_iters: int, timed, lsns, txos,
                       ords) -> dict:
    """The device-egress half of run_egress: decode once WITH the fused
    wire-encoding stage (blocking compile — bench, not streaming), then
    time the destination fast paths splicing the attached buffers and
    gate their bytes against the columnar oracles."""
    from ..destinations.clickhouse import (render_batch_tsv_columnar,
                                           render_batch_tsv_fast)
    from ..destinations.snowflake import (encode_batch_ndjson,
                                          encode_batch_ndjson_fast,
                                          offset_token_batch)
    from ..destinations.util import (sequence_number_batch,
                                     sequence_number_buffer)
    from ..ops.egress import ENCODER_JSON, ENCODER_TSV

    out: dict = {}
    seq_buf = sequence_number_buffer(lsns, txos, ords)
    seq_strs = [s.decode() for s in sequence_number_batch(lsns, txos,
                                                          ords)]
    schema, tsv_batch = _egress_batch(n_rows, egress=ENCODER_TSV)
    dev_tsv = tsv_batch.device_egress
    out["device_tsv_attached"] = dev_tsv is not None

    used = {"tsv": False, "json": False}

    def tsv():
        body, used_device = render_batch_tsv_fast(
            schema, tsv_batch, "UPSERT", seq_buf, egress=dev_tsv)
        used["tsv"] = used_device
        return len(body)

    rps, bps = timed(tsv)
    out["device_tsv_rows_per_sec"] = rps
    out["device_tsv_bytes_per_sec"] = bps
    out["device_tsv_used_device"] = used["tsv"]
    body, _ = render_batch_tsv_fast(schema, tsv_batch, "UPSERT", seq_buf,
                                    egress=dev_tsv)
    out["device_tsv_identical"] = body == render_batch_tsv_columnar(
        schema, tsv_batch, "UPSERT", seq_strs)

    _, json_batch = _egress_batch(n_rows, egress=ENCODER_JSON)
    dev_json = json_batch.device_egress
    out["device_json_attached"] = dev_json is not None
    ops = ["insert"] * n_rows
    seqs = offset_token_batch(lsns, txos)

    def ndjson():
        lines, used_device = encode_batch_ndjson_fast(
            schema, json_batch, ops, seqs, egress=dev_json)
        used["json"] = used_device
        return sum(len(ln) for ln in lines)

    rps, bps = timed(ndjson)
    out["device_json_rows_per_sec"] = rps
    out["device_json_bytes_per_sec"] = bps
    out["device_json_used_device"] = used["json"]
    lines, _ = encode_batch_ndjson_fast(schema, json_batch, ops, seqs,
                                        egress=dev_json)
    out["device_json_identical"] = lines == encode_batch_ndjson(
        schema, json_batch, ops, seqs)
    return out


# ---------------------------------------------------------------------------
# coldstart (ISSUE 12): restart-to-first-durable-batch, cold vs warm cache
# ---------------------------------------------------------------------------


def run_coldstart(n_tables: int = 3, rows_per_tx: int = 800,
                  txs_per_table: int = 2,
                  cache_dir: "str | None" = None) -> dict:
    """Two replicator lifetimes (subprocesses — jax program caches are
    process state, so cold vs warm MUST be separate processes) against
    one program-cache dir: the cold start compiles, the warm restart
    loads. Gates (asserted by --smoke):

      - warm restart compiles ZERO fresh XLA programs and serves its
        first durable batch off cached programs (no oracle rows);
      - the cold start's compile count proves canonicalization — the
        permuted-column tables share ONE layout, so compiles are bounded
        by the prewarm bucket count, not tables × buckets.

    Wall-clock numbers (start / first-durable / total) are recorded, not
    gated, on this CPU container: the XLA builds they eliminate are
    seconds here and tens of seconds on wide schemas."""
    import json as _json
    import os
    import shutil
    import subprocess
    import sys
    import tempfile

    owned = cache_dir is None
    if owned:
        cache_dir = tempfile.mkdtemp(prefix="etl-coldstart-cache-")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    def one_run() -> dict:
        proc = subprocess.run(
            [sys.executable, "-m", "etl_tpu.benchmarks.coldstart_worker",
             "--cache-dir", cache_dir, "--tables", str(n_tables),
             "--rows-per-tx", str(rows_per_tx),
             "--txs-per-table", str(txs_per_table)],
            capture_output=True, text=True, timeout=600, env=env, cwd=repo)
        if proc.returncode != 0:
            raise RuntimeError(
                f"coldstart worker failed: {proc.stderr[-1500:]}")
        return _json.loads(proc.stdout.strip().splitlines()[-1])

    try:
        cold = one_run()
        warm = one_run()
    finally:
        if owned:
            shutil.rmtree(cache_dir, ignore_errors=True)
    buckets = cold["prewarm_buckets"]  # emitted by the worker, so the
    #                                    gate can never drift from its
    #                                    PREWARM_BUCKETS tuple
    failures = []
    if warm["programs_compiled"] != 0:
        failures.append(f"warm restart compiled "
                        f"{warm['programs_compiled']} programs (want 0)")
    if warm["cache_hits_disk"] < 1:
        failures.append("warm restart never loaded a program from disk")
    if warm["oracle_rows"] != 0:
        failures.append(f"warm restart decoded {warm['oracle_rows']} rows "
                        "on the oracle (first batch not served from "
                        "cached programs)")
    if warm["host_rows"] <= 0:
        failures.append("warm restart routed nothing to the host program")
    if cold["programs_compiled"] > buckets:
        failures.append(
            f"cold start compiled {cold['programs_compiled']} programs "
            f"for {n_tables} tables — canonicalization should bound it "
            f"by the {buckets} prewarm buckets")
    if cold["canonical_layouts"] != 1:
        failures.append(f"{cold['canonical_layouts']} canonical layouts "
                        f"for {n_tables} same-multiset tables (want 1)")
    return {
        "mode": "coldstart", "ok": not failures, "failures": failures,
        "cold": cold, "warm": warm,
        "warm_zero_compiles": warm["programs_compiled"] == 0,
        "warm_first_durable_seconds": warm["first_durable_seconds"],
        "cold_first_durable_seconds": cold["first_durable_seconds"],
        "cold_oracle_rows_during_warmup": cold["oracle_rows"],
    }


# ---------------------------------------------------------------------------
# wide_row (BASELINE.json config: 100-col mixed types)
# ---------------------------------------------------------------------------


def run_wide_row(n_rows: int = 16_384, n_iters: int = 5,
                 engine: str = "xla") -> dict:
    import random

    from ..models import (ColumnSchema, Oid, ReplicatedTableSchema,
                          TableName, TableSchema)
    from ..ops import DeviceDecoder, stage_tuples
    from ..postgres.codec.pgoutput import TUPLE_NULL, TUPLE_TEXT, TupleData

    rng = random.Random(11)
    kinds = [Oid.INT8, Oid.INT4, Oid.NUMERIC, Oid.TEXT, Oid.TIMESTAMPTZ,
             Oid.DATE, Oid.BOOL, Oid.FLOAT8, Oid.JSONB, Oid.UUID]
    oids = [kinds[i % len(kinds)] for i in range(100)]
    cols = tuple(ColumnSchema(f"c{i}", oid) for i, oid in enumerate(oids))
    schema = ReplicatedTableSchema.with_all_columns(TableSchema(
        9, TableName("public", "wide"), cols))

    def text_for(oid):
        if oid == Oid.INT8:
            return str(rng.randrange(-10**12, 10**12))
        if oid == Oid.INT4:
            return str(rng.randrange(-10**9, 10**9))
        if oid == Oid.NUMERIC:
            return f"{rng.randrange(0, 10**8)}.{rng.randrange(0, 100):02d}"
        if oid == Oid.TEXT:
            return "text-" + str(rng.randrange(10**6))
        if oid == Oid.TIMESTAMPTZ:
            return "2024-05-01 12:34:56.789+00"
        if oid == Oid.DATE:
            return "2024-05-01"
        if oid == Oid.BOOL:
            return rng.choice(["t", "f"])
        if oid == Oid.FLOAT8:
            return f"{rng.uniform(-1e6, 1e6):.6f}"
        if oid == Oid.JSONB:
            return '{"k": %d}' % rng.randrange(1000)
        return "a0eebc99-9c0b-4ef8-bb6d-6bb9bd380a11"

    tuples = []
    for _ in range(n_rows):
        vals = []
        for oid in oids:
            if rng.random() < 0.05:
                vals.append(None)
            else:
                vals.append(text_for(oid).encode())
        tuples.append(TupleData(
            [TUPLE_NULL if v is None else TUPLE_TEXT for v in vals], vals))

    staged = stage_tuples(tuples, 100)
    # this mode MEASURES THE DEVICE PATH by definition — pin the routing
    # so the production DEVICE_MIN_ROWS (tuned for streaming flushes)
    # can't silently reroute the benchmark to the host backend
    dec = DeviceDecoder(schema, use_pallas=(engine == "pallas"),
                        device_min_rows=1)
    dec.decode(staged)  # warmup
    times = []
    for _ in range(n_iters):
        t0 = time.perf_counter()
        dec.decode(staged)
        times.append(time.perf_counter() - t0)
    rps = n_rows / _median(times)
    # a failed pallas compile silently falls back to XLA mid-warmup —
    # report the engine that actually ran
    ran = "pallas" if dec.use_pallas and engine == "pallas" else "xla"
    return {"mode": "wide_row", "rows": n_rows, "columns": 100,
            "engine": ran,
            "rows_per_second": round(rps),
            "cells_per_second": round(rps * 100)}


# ---------------------------------------------------------------------------
# selectivity (fused publication row filtering, ROADMAP item 4)
# ---------------------------------------------------------------------------


def _filtered_batches_identical(a, b) -> bool:
    """Byte-level equality of two compacted decode outputs, INCLUDING the
    survivor row mapping — a filter that dropped the right count but the
    wrong rows must fail here."""
    import numpy as np

    if a.num_rows != b.num_rows:
        return False
    sa = getattr(a, "source_rows", None)
    sb = getattr(b, "source_rows", None)
    if (sa is None) != (sb is None):
        return False
    if sa is not None and not np.array_equal(sa, sb):
        return False
    for ca, cb in zip(a.columns, b.columns):
        if not np.array_equal(ca.validity, cb.validity):
            return False
        if ca.is_dense and cb.is_dense:
            if not np.array_equal(ca.data[ca.validity],
                                  cb.data[cb.validity]):
                return False
        else:
            for i in range(a.num_rows):
                if ca.validity[i] and ca.value(i) != cb.value(i):
                    return False
    return True


def run_selectivity(n_rows: int = 16_384, n_iters: int = 5,
                    keep_fractions=(0.1, 0.5, 0.9),
                    fetch_slack: float = 0.11) -> dict:
    """Fused-filter decode matrix: both device engines (XLA jnp.where-mask
    twin and the Pallas fused kernel) across publication-filter
    selectivities, against the host oracle.

    Per selectivity: rows/s for each engine (filtered, compacted output),
    byte identity Pallas == XLA == host-oracle on the compacted batch AND
    the survivor mapping, and the MEASURED fetched-bytes ratio vs the
    unfiltered program — gated at (selectivity + fetch_slack), where the
    slack covers the keep-mask (1 bit/row), the survivor-count words and
    the fetch-slice bucket granularity (max(R/16, 256) rows,
    staging.slice_rows). Wall-clock speedup vs the unfiltered decode is
    recorded, NOT gated, on CPU containers (PR 8 precedent: only real
    TPU hardware turns fetch-link savings into throughput)."""
    import numpy as np

    from ..models import (ColumnSchema, Oid, ReplicatedTableSchema,
                          TableName, TableSchema)
    from ..ops.engine import DeviceDecoder
    from ..ops.predicate import parse_row_filter
    from ..ops.wal import concat_payloads, stage_wal_batch
    from ..postgres.codec.pgoutput import encode_insert
    from ..telemetry.metrics import (ETL_DECODE_FETCHED_BYTES_TOTAL,
                                     registry)

    table = TableSchema(
        16384, TableName("public", "filter_bench"),
        (ColumnSchema("id", Oid.INT8, nullable=False, primary_key_ordinal=1),
         ColumnSchema("v", Oid.INT4),
         ColumnSchema("note", Oid.TEXT)))
    rng = np.random.RandomState(11)
    vals = rng.randint(-1_000_000, 1_000_000, size=n_rows)
    payloads = [encode_insert(16384, [str(i).encode(),
                                      str(int(v)).encode(),
                                      b"n-%d" % i])
                for i, v in enumerate(vals)]
    buf, offs, lens = concat_payloads(payloads)

    def stage():
        return stage_wal_batch(buf, offs, lens, 3).staged

    def fetched_delta(dec, staged):
        b0 = registry.get_counter(ETL_DECODE_FETCHED_BYTES_TOTAL)
        batch = dec.decode(staged)
        return batch, registry.get_counter(
            ETL_DECODE_FETCHED_BYTES_TOTAL) - b0

    def best_rate(dec):
        times = []
        for _ in range(n_iters):
            s = stage()
            t0 = time.perf_counter()
            dec.decode(s)
            times.append(time.perf_counter() - t0)
        return n_rows / min(times)

    plain = ReplicatedTableSchema.with_all_columns(table)
    base_dec = DeviceDecoder(plain, device_min_rows=1, mesh=None)
    _, unfiltered_bytes = fetched_delta(base_dec, stage())
    unfiltered_rate = best_rate(base_dec)

    out = {"mode": "selectivity", "rows": n_rows,
           "unfiltered_rows_per_sec": round(unfiltered_rate),
           "unfiltered_fetched_bytes": int(unfiltered_bytes),
           "fetch_slack": fetch_slack,
           "points": []}
    all_ok = True
    for keep in keep_fractions:
        threshold = int(-1_000_000 + 2_000_000 * keep)
        sql = f"v < {threshold}"
        rts = ReplicatedTableSchema.with_all_columns(table) \
            .with_row_predicate(parse_row_filter(sql))
        xla = DeviceDecoder(rts, device_min_rows=1, mesh=None)
        pallas = DeviceDecoder(rts, device_min_rows=1, mesh=None,
                               use_pallas=True)
        # host oracle reference: every row through the per-row CPU
        # decode, the filter applied over decoded values (host_keep)
        oracle = DeviceDecoder(rts, device_min_rows=10**9,
                               host_min_rows=10**9, mesh=None)
        bx, filtered_bytes = fetched_delta(xla, stage())
        bp = pallas.decode(stage())
        bo = oracle.decode(stage())
        identical = _filtered_batches_identical(bx, bp) \
            and _filtered_batches_identical(bx, bo)
        measured_keep = bx.num_rows / n_rows
        ratio = filtered_bytes / unfiltered_bytes if unfiltered_bytes else 0
        fetch_ok = ratio <= measured_keep + fetch_slack
        xla_rate = best_rate(xla)
        point = {
            "row_filter": sql,
            "target_keep": keep,
            "measured_keep": round(measured_keep, 4),
            "survivors": bx.num_rows,
            "xla_rows_per_sec": round(xla_rate),
            "pallas_rows_per_sec": round(best_rate(pallas)),
            # recorded NOT gated on CPU (the fetch link this optimizes
            # is the TPU tunnel; the host backend has no transfer cost)
            "xla_speedup_vs_unfiltered":
                round(xla_rate / unfiltered_rate, 3),
            "filtered_fetched_bytes": int(filtered_bytes),
            "fetched_bytes_ratio": round(ratio, 4),
            "fetch_reduction_ok": bool(fetch_ok),
            "engines_and_oracle_identical": bool(identical),
            "pallas_engine_ran": bool(pallas.use_pallas),
        }
        all_ok = all_ok and identical and fetch_ok
        out["points"].append(point)
    out["ok"] = bool(all_ok)
    return out


async def _ack_latency_run(write_window: int, ack_ms: float,
                           n_events: int, tx_size: int,
                           max_size_bytes: int, max_fill_ms: int,
                           engine: str = "cpu") -> dict:
    """One full-pipeline CDC run against a destination whose every ack
    turns durable `ack_ms` later (destinations/delay.py). The producer
    pre-commits the whole workload, so the run measures BACKLOG DRAIN
    throughput with size-bounded batches: at window=1 each batch's ack
    round trip serializes the next dispatch (the `batch_size / ack_rtt`
    ceiling), at window=K the round trips overlap. Engine defaults to
    the CPU per-tuple path: the bench isolates ACK PIPELINING, and at
    the deliberately small batch sizes the latency model needs, the
    device engine's per-sealed-run machinery (staging + admission + a
    program call per ~threshold bytes) would dominate the measurement
    on this host. Every delivered row folds into a BATCH-BOUNDARY-
    INDEPENDENT digest (per-row records concatenate identically however
    flushes were split) — the byte-identity evidence across window
    depths."""
    import hashlib

    import numpy as np

    from ..config import BatchConfig, BatchEngine, PipelineConfig
    from ..destinations import DelayedAckDestination
    from ..destinations.base import Destination, WriteAck
    from ..models import (ColumnSchema, InsertEvent, Oid, TableName,
                          TableSchema)
    from ..models.event import DecodedBatchEvent
    from ..models.table_state import TableStateType
    from ..postgres.codec.pgoutput import encode_insert
    from ..postgres.fake import FakeDatabase, FakeSource
    from ..runtime import Pipeline
    from ..store import NotifyingStore
    from ..telemetry.metrics import (
        ETL_DESTINATION_ACK_BUSY_SECONDS_TOTAL,
        ETL_DESTINATION_ACK_OVERLAP_SECONDS_TOTAL, registry)

    TID = 16391
    db = FakeDatabase()
    # all-dense columns: the delivery digest covers full content via
    # column byte concatenation, no per-row Python on the measured path
    db.create_table(TableSchema(
        TID, TableName("public", "bench_ack"),
        (ColumnSchema("id", Oid.INT8, nullable=False, primary_key_ordinal=1),
         ColumnSchema("v", Oid.INT4))))
    db.create_publication("pub", [TID])
    store = NotifyingStore()

    def _digest_batch(digest, e) -> int:
        """Sync helper (host-side numpy — the batch is already resolved
        to host arrays): PER-ROW interleaving (column_stack) keeps the
        digest independent of how flushes were split — concatenating row
        records across batches yields the same byte stream at every
        window depth."""
        batch = e.batch
        fields = [np.asarray(e.change_types),
                  np.asarray(e.commit_lsns),
                  np.asarray(e.tx_ordinals)]
        for c in batch.columns:
            valid = np.asarray(c.validity)
            fields.append(valid)
            fields.append(np.where(valid, np.asarray(c.data), 0))
        digest.update(np.column_stack(
            [f.astype(np.uint64) for f in fields]).tobytes())
        return batch.num_rows

    def _digest_row(digest, e) -> int:
        """CPU engine: per-row events; same per-row record shape as one
        column_stack row, so the digest stays comparable across window
        depths (not engines)."""
        digest.update(np.asarray(
            [1, int(e.commit_lsn), e.tx_ordinal,
             1, int(e.row.values[0]), 1, int(e.row.values[1])],
            dtype=np.uint64).tobytes())
        return 1

    class DigestingDestination(Destination):
        def __init__(self):
            self.rows_delivered = 0
            self.digest = hashlib.sha256()

        async def startup(self):
            return None

        async def write_table_rows(self, schema, batch):
            return WriteAck.durable()

        async def write_events(self, events):
            for e in events:
                if isinstance(e, DecodedBatchEvent):
                    self.rows_delivered += _digest_batch(self.digest, e)
                elif isinstance(e, InsertEvent):
                    self.rows_delivered += _digest_row(self.digest, e)
            return WriteAck.durable()

        async def drop_table(self, table_id, schema=None):
            return None

        async def truncate_table(self, table_id):
            return None

    inner = DigestingDestination()
    dest = DelayedAckDestination(inner, ack_ms / 1000.0)
    labels = {"path": "apply"}
    busy0 = registry.get_counter(ETL_DESTINATION_ACK_BUSY_SECONDS_TOTAL,
                                 labels)
    overlap0 = registry.get_counter(
        ETL_DESTINATION_ACK_OVERLAP_SECONDS_TOTAL, labels)
    pipeline = Pipeline(
        config=PipelineConfig(
            pipeline_id=1, publication_name="pub",
            batch=BatchConfig(max_size_bytes=max_size_bytes,
                              max_fill_ms=max_fill_ms,
                              batch_engine=BatchEngine(engine),
                              write_window=write_window)),
        store=store, destination=dest,
        source_factory=lambda: FakeSource(db))
    await pipeline.start()
    await asyncio.wait_for(store.notify_on(TID, TableStateType.READY), 60)

    # warmup OFF the clock: one tx through the full path compiles the
    # host decode programs for the buckets this run stages into
    n_warm = 8
    tx = db.transaction()
    for i in range(n_warm):
        tx.insert_preencoded(TID, encode_insert(
            TID, [str(10**7 + i).encode(), b"0"]))
    await tx.commit()
    while inner.rows_delivered < n_warm:
        await asyncio.sleep(0.01)
    await _wait_background_compiles()
    inner.rows_delivered = 0
    inner.digest = hashlib.sha256()

    payloads = [encode_insert(TID, [str(i).encode(), str(i % 97).encode()])
                for i in range(n_events)]
    t0 = time.perf_counter()
    produced = 0
    while produced < n_events:
        tx = db.transaction()
        for _ in range(min(tx_size, n_events - produced)):
            tx.insert_preencoded(TID, payloads[produced])
            produced += 1
        await tx.commit()
    while inner.rows_delivered < n_events:
        if pipeline._apply_task is not None and pipeline._apply_task.done():
            pipeline._apply_task.result()
            raise RuntimeError("pipeline stopped before delivering")
        await asyncio.sleep(0.002)
    # durability barrier: every delayed ack must resolve (delivery alone
    # would flatter the windowed run, which by design has acks pending)
    while dest.pending > 0:
        await asyncio.sleep(0.002)
    elapsed = time.perf_counter() - t0
    await pipeline.shutdown_and_wait()

    busy = registry.get_counter(ETL_DESTINATION_ACK_BUSY_SECONDS_TOTAL,
                                labels) - busy0
    overlap = registry.get_counter(
        ETL_DESTINATION_ACK_OVERLAP_SECONDS_TOTAL, labels) - overlap0
    return {
        "write_window": write_window,
        "events_per_second": round(n_events / elapsed),
        "elapsed_seconds": round(elapsed, 4),
        "acks_issued": dest.acks_issued,
        "max_acks_pending": dest.max_pending,
        "delivery_digest": inner.digest.hexdigest(),
        "ack_busy_seconds": round(busy, 4),
        "ack_overlap_seconds": round(overlap, 4),
        "ack_overlap_ratio": round(overlap / busy, 3) if busy else 0.0,
    }


async def run_ack_latency(ack_ms: float = 20.0, n_events: int = 2000,
                          tx_size: int = 20, max_size_bytes: int = 2048,
                          max_fill_ms: int = 10,
                          write_window: "int | None" = None) -> dict:
    """The windowed-ack A/B gate (ISSUE 14): the SAME deterministic
    backlog drained through the default write window and through a
    forced window=1 run. GATES (caller applies the speedup floor):
    byte-identical delivery (order + content digests equal), window=1
    never holds more than one ack in flight, the windowed run provably
    overlaps (max pending ≥ 2, overlap ratio > 0)."""
    from ..config import BatchConfig

    window = write_window or BatchConfig().write_window
    windowed = await _ack_latency_run(window, ack_ms, n_events, tx_size,
                                      max_size_bytes, max_fill_ms)
    serial = await _ack_latency_run(1, ack_ms, n_events, tx_size,
                                    max_size_bytes, max_fill_ms)
    speedup = windowed["events_per_second"] \
        / max(serial["events_per_second"], 1)
    failures = []
    if windowed["delivery_digest"] != serial["delivery_digest"]:
        failures.append("windowed delivery is not byte-identical to the "
                        "window=1 run")
    if serial["max_acks_pending"] > 1:
        failures.append(
            f"window=1 held {serial['max_acks_pending']} acks in flight "
            f"(must be ≤ 1 — the one-in-flight contract)")
    if windowed["max_acks_pending"] < 2:
        failures.append("the windowed run never overlapped two acks")
    if windowed["ack_overlap_seconds"] <= 0:
        failures.append("the windowed run recorded zero overlap seconds")
    return {
        "mode": "ack_latency",
        "ack_latency_ms": ack_ms,
        "events": n_events,
        "max_size_bytes": max_size_bytes,
        "windowed": windowed,
        "window1": serial,
        "ack_window_speedup": round(speedup, 3),
        "failures": failures,
        "ok": not failures,
    }


async def _run_poison_pass(profile, seed: int, target_ops: int,
                           poisoned: bool,
                           verify_timeout_s: float = 120.0) -> dict:
    """One streamed-CDC measurement for the poison gate: the same
    (profile, seed) workload through the full pipeline, either clean
    (poison_rate=0, plain destination, view==truth verification) or
    poisoned (PoisonRejectingDestination + isolation live, union
    verification: delivered ∪ dead-lettered == committed truth)."""
    from dataclasses import replace as _replace

    from ..chaos.invariants import reconstruct_final_view, view_matches
    from ..chaos.runner import RecordingStore, TracingDestination
    from ..config import (BatchConfig, BatchEngine, PipelineConfig,
                          PoisonConfig)
    from ..destinations import PoisonRejectingDestination
    from ..dlq.codec import decode_cell
    from ..models.table_state import TableStateType
    from ..postgres.fake import FakeSource
    from ..runtime import Pipeline
    from ..runtime import poison as poison_mod
    from ..workloads import WorkloadGenerator

    if not poisoned:
        profile = _replace(profile, poison_rate=0.0)
    gen = WorkloadGenerator(profile, seed=seed)
    db = gen.build_db()
    store = RecordingStore()
    inner = TracingDestination()
    dest = PoisonRejectingDestination(inner) if poisoned else inner
    pipeline = Pipeline(
        config=PipelineConfig(
            pipeline_id=1, publication_name="pub",
            batch=BatchConfig(max_fill_ms=30,
                              batch_engine=BatchEngine("tpu")),
            # budget high enough that quarantine never trips: the gate
            # measures bisection + DLQ cost on a flowing stream, not the
            # (cheaper) parking path
            poison=PoisonConfig(budget_rows=1_000_000)),
        store=store, destination=dest,
        source_factory=lambda: FakeSource(db))

    async def settled() -> bool:
        if not poisoned:
            return view_matches(inner, gen.table_ids, gen.expected)
        entries = await store.list_dead_letters(status=None)
        import json as _json

        dlq: dict = {tid: {} for tid in gen.table_ids}
        for e in sorted(entries, key=lambda e: (e.commit_lsn,
                                                e.tx_ordinal)):
            doc = _json.loads(e.payload)
            values = tuple(decode_cell(v) for v in doc["values"])
            dlq.setdefault(e.table_id, {})[values[0]] = values
        view = reconstruct_final_view(inner, gen.table_ids)
        for tid in gen.table_ids:
            for pk, values in gen.expected[tid].items():
                if view[tid].get(pk) != values \
                        and dlq[tid].get(pk) != values:
                    return False
        return True

    async def wait_settled(timeout: float) -> bool:
        deadline = time.perf_counter() + timeout
        seen = -1
        while True:
            n = len(inner.events)
            if n == seen and await settled():
                return True
            seen = n
            if pipeline._apply_task is not None \
                    and pipeline._apply_task.done():
                pipeline._apply_task.result()
                raise RuntimeError("pipeline stopped before delivering")
            if time.perf_counter() >= deadline:
                return False
            await asyncio.sleep(0.1)

    poison_mod.reset_isolation_trace()
    try:
        await pipeline.start()
        for tid in gen.table_ids:
            await asyncio.wait_for(
                store.notify_on(tid, TableStateType.READY), 120)
        warm_target = max(100, target_ops // 5)
        while gen.row_ops < warm_target:
            await gen.run_tx(db)
        if not await wait_settled(240):
            raise RuntimeError("warmup never settled")
        await _wait_background_compiles()
        ops0 = gen.row_ops
        t0 = time.perf_counter()
        while gen.row_ops - ops0 < target_ops:
            await gen.run_tx(db)
        verified = await wait_settled(verify_timeout_s)
        t_done = time.perf_counter()
    finally:
        if pipeline._apply_task is not None:
            await pipeline.shutdown_and_wait()
    measured = gen.row_ops - ops0
    traces = list(poison_mod.ISOLATION_TRACE)
    probe_writes = sum(t["probe_writes"] for t in traces)
    probe_bound = sum(
        poison_mod.bisection_bound(t["rows"], t["tables"],
                                   t["poison_rows"]) for t in traces)
    dlq_entries = len(await store.list_dead_letters(status=None)) \
        if poisoned else 0
    return {
        "events_per_second": round(measured / max(t_done - t0, 1e-9)),
        "row_ops": measured,
        "verified": bool(verified),
        "poison_rows_committed": sum(len(v)
                                     for v in gen.poison_pks.values()),
        "dlq_entries": dlq_entries,
        "isolations": len(traces),
        "probe_writes": probe_writes,
        "probe_bound": probe_bound,
        "bound_ok": probe_writes <= probe_bound,
    }


async def run_poison_streaming(rate: float = 0.001, seed: int = 7,
                               target_ops: int = 3_000) -> dict:
    """The poison-resilience gate (bench.py --poison): the SAME seeded
    insert-CDC workload measured twice — clean, and with `rate` of rows
    poisoned against a rejecting destination with isolation live. GATES
    (caller applies floors): the poisoned rate must hold ≥
    poison_ratio_floor of the clean rate, the isolation probe writes
    must stay within the bisection bound, and BOTH runs must verify
    (clean: view == truth; poisoned: delivered ∪ dead-lettered ==
    truth, every poison row accounted)."""
    from dataclasses import replace as _replace

    from ..workloads import get_profile

    profile = _replace(get_profile("poison_rows"), poison_rate=rate)
    clean = await _run_poison_pass(profile, seed, target_ops,
                                   poisoned=False)
    poisoned = await _run_poison_pass(profile, seed, target_ops,
                                      poisoned=True)
    ratio = poisoned["events_per_second"] \
        / max(1, clean["events_per_second"])
    failures = []
    if not clean["verified"]:
        failures.append("clean pass failed end-state verification")
    if not poisoned["verified"]:
        failures.append("poisoned pass failed the union invariant "
                        "(delivered ∪ dead-lettered != committed truth)")
    if not poisoned["bound_ok"]:
        failures.append(
            f"bisection writes {poisoned['probe_writes']} exceeded the "
            f"bound {poisoned['probe_bound']}")
    if poisoned["poison_rows_committed"] == 0:
        failures.append("seed committed no poison rows — the gate "
                        "measured nothing; raise target_ops or rate")
    elif poisoned["dlq_entries"] == 0:
        failures.append("poison rows committed but none dead-lettered")
    return {
        "mode": "poison",
        "seed": seed,
        "poison_rate": rate,
        "clean": clean,
        "poisoned": poisoned,
        "clean_events_per_second": clean["events_per_second"],
        "poisoned_events_per_second": poisoned["events_per_second"],
        "poison_throughput_ratio": round(ratio, 3),
        "failures": failures,
        "ok": not failures,
    }


async def _exactly_once_table(tid: int):
    from ..models import ColumnSchema, Oid, TableName, TableSchema
    from ..postgres.fake import FakeDatabase

    db = FakeDatabase()
    db.create_table(TableSchema(
        tid, TableName("public", "bench_eo"),
        (ColumnSchema("id", Oid.INT8, nullable=False,
                      primary_key_ordinal=1),
         ColumnSchema("v", Oid.INT4))))
    db.create_publication("pub", [tid])
    return db


async def _exactly_once_drain(transactional: bool, n_events: int,
                              tx_size: int, max_size_bytes: int,
                              max_fill_ms: int) -> dict:
    """One full-pipeline CDC backlog drain into either the plain memory
    sink or the transactional one (write_event_batches_committed +
    coordinate bookkeeping on every flush) — the A/B legs of the
    exactly-once overhead ratio. CPU per-tuple engine for the same
    reason as the ack-latency bench: the gate isolates the SEAM's
    per-flush cost (CommitRange derivation, coordinate dedup filter,
    high-water accounting), which the device engine's per-run machinery
    would drown at these batch sizes."""
    from ..config import BatchConfig, BatchEngine, PipelineConfig
    from ..destinations import (MemoryDestination,
                                TransactionalMemoryDestination)
    from ..models.table_state import TableStateType
    from ..postgres.codec.pgoutput import encode_insert
    from ..postgres.fake import FakeSource
    from ..runtime import Pipeline
    from ..store import NotifyingStore

    TID = 16401
    db = await _exactly_once_table(TID)
    store = NotifyingStore()
    dest = TransactionalMemoryDestination() if transactional \
        else MemoryDestination()
    pipeline = Pipeline(
        config=PipelineConfig(
            pipeline_id=1, publication_name="pub",
            batch=BatchConfig(max_size_bytes=max_size_bytes,
                              max_fill_ms=max_fill_ms,
                              batch_engine=BatchEngine("cpu"))),
        store=store, destination=dest,
        source_factory=lambda: FakeSource(db))
    await pipeline.start()
    await asyncio.wait_for(store.notify_on(TID, TableStateType.READY), 60)

    n_warm = 8
    tx = db.transaction()
    for i in range(n_warm):
        tx.insert_preencoded(TID, encode_insert(
            TID, [str(10**7 + i).encode(), b"0"]))
    await tx.commit()
    while len(dest.events) < n_warm:
        await asyncio.sleep(0.01)
    await _wait_background_compiles()
    dest.events.clear()  # coordinates (high_water) survive; content reset

    payloads = [encode_insert(TID, [str(i).encode(), str(i % 97).encode()])
                for i in range(n_events)]
    t0 = time.perf_counter()
    produced = 0
    while produced < n_events:
        tx = db.transaction()
        for _ in range(min(tx_size, n_events - produced)):
            tx.insert_preencoded(TID, payloads[produced])
            produced += 1
        await tx.commit()
    while len(dest.events) < n_events:
        if pipeline._apply_task is not None and pipeline._apply_task.done():
            pipeline._apply_task.result()
            raise RuntimeError("pipeline stopped before delivering")
        await asyncio.sleep(0.002)
    elapsed = time.perf_counter() - t0
    await pipeline.shutdown_and_wait()
    out = {
        "transactional": transactional,
        "events_per_second": round(n_events / elapsed),
        "elapsed_seconds": round(elapsed, 4),
        "rows_delivered": len(dest.events),
    }
    if transactional:
        out["uncoordinated_writes"] = dest.uncoordinated_writes
        out["high_water"] = list(dest.high_water)
    return out


async def _exactly_once_restart_leg(n_events: int, tx_size: int,
                                    max_size_bytes: int,
                                    max_fill_ms: int) -> dict:
    """The recovery-trim leg: hard-kill a pipeline mid-backlog against
    the transactional sink, measure the unacked suffix (sink rows whose
    WAL coordinates lie beyond the store's durable progress at the kill
    instant), restart, and finish. The caller gates: zero duplicates,
    zero loss, and re-streamed-already-applied rows (the sink's
    coordinate-dedup counter) bounded by that suffix — the exactly-once
    analogue of `re-stream <= unacked window`."""
    from ..chaos.runner import _hard_kill
    from ..config import BatchConfig, BatchEngine, PipelineConfig
    from ..destinations import TransactionalMemoryDestination
    from ..destinations.base import event_coordinate
    from ..models.table_state import TableStateType
    from ..postgres.codec.pgoutput import encode_insert
    from ..postgres.fake import FakeSource
    from ..postgres.slots import apply_slot_name
    from ..runtime import Pipeline
    from ..store import NotifyingStore

    TID = 16402
    db = await _exactly_once_table(TID)
    store = NotifyingStore()
    dest = TransactionalMemoryDestination()

    def make_pipeline():
        return Pipeline(
            config=PipelineConfig(
                pipeline_id=1, publication_name="pub",
                batch=BatchConfig(max_size_bytes=max_size_bytes,
                                  max_fill_ms=max_fill_ms,
                                  batch_engine=BatchEngine("cpu"))),
            store=store, destination=dest,
            source_factory=lambda: FakeSource(db))

    def row_events() -> list:
        # the CPU engine delivers Begin/Commit/Relation envelopes too;
        # the dup/loss arithmetic counts data rows only
        return [e for e in dest.events
                if getattr(e, "row", None) is not None]

    def distinct_rows() -> int:
        return len({e.row.values[0] for e in row_events()})

    pipeline = make_pipeline()
    await pipeline.start()
    await asyncio.wait_for(store.notify_on(TID, TableStateType.READY), 60)
    payloads = [encode_insert(TID, [str(i).encode(), str(i % 97).encode()])
                for i in range(n_events)]
    produced = 0
    while produced < n_events // 2:
        tx = db.transaction()
        for _ in range(min(tx_size, n_events // 2 - produced)):
            tx.insert_preencoded(TID, payloads[produced])
            produced += 1
        await tx.commit()
    # kill once the drain is verifiably mid-flight: some rows applied,
    # the rest still streaming — the classic write-vs-progress gap
    kill_after = max(1, n_events // 8)
    deadline = time.perf_counter() + 60
    while len(row_events()) < kill_after:
        if time.perf_counter() >= deadline:
            raise RuntimeError("drain never reached the kill window")
        await asyncio.sleep(0.002)
    await _hard_kill(pipeline)
    durable = int(await store.get_durable_progress(apply_slot_name(1))
                  or 0)
    suffix = sum(1 for e in dest.events
                 if (c := event_coordinate(e)) is not None
                 and c[0] > durable)
    applied_at_kill = len(row_events())

    pipeline = make_pipeline()
    await pipeline.start()
    while produced < n_events:
        tx = db.transaction()
        for _ in range(min(tx_size, n_events - produced)):
            tx.insert_preencoded(TID, payloads[produced])
            produced += 1
        await tx.commit()
    deadline = time.perf_counter() + 120
    while distinct_rows() < n_events:
        if pipeline._apply_task is not None and pipeline._apply_task.done():
            pipeline._apply_task.result()
            raise RuntimeError("pipeline stopped before delivering")
        if time.perf_counter() >= deadline:
            raise RuntimeError(
                f"recovery leg never delivered: {distinct_rows()}"
                f"/{n_events}")
        await asyncio.sleep(0.005)
    await pipeline.shutdown_and_wait()
    return {
        "rows_applied_at_kill": applied_at_kill,
        "durable_lsn_at_kill": durable,
        "unacked_suffix_rows": suffix,
        "restreamed_deduped_rows": dest.dedup_skipped_rows,
        "duplicate_rows": len(row_events()) - distinct_rows(),
        "rows_delivered": distinct_rows(),
        "recover_calls": dest.recover_calls,
        "uncoordinated_writes": dest.uncoordinated_writes,
    }


async def run_exactly_once(n_events: int = 3_000, tx_size: int = 40,
                           max_size_bytes: int = 4096,
                           max_fill_ms: int = 10,
                           repeats: int = 3) -> dict:
    """The exactly-once overhead + recovery-trim gate (bench.py
    --exactly-once, ISSUE 19): the SAME deterministic CDC backlog
    drained into the plain memory sink and into the transactional one
    (coordinate range recorded atomically with every flush). GATES
    (caller applies exactly_once_ratio_floor): the transactional drain
    must hold >= floor of the plain rate, every CDC write must route
    through the committed seam (zero uncoordinated writes), and the
    hard-kill restart leg must deliver every row exactly once with its
    re-streamed-already-applied rows bounded by the unacked suffix at
    the kill. Each timed drain is best-of-`repeats`, A/B interleaved:
    a single ~0.2s pass on this shared-host container carries 30-40%
    scheduler noise, far above the coordination overhead under test."""
    plain = txn = None
    for _ in range(max(1, repeats)):
        p = await _exactly_once_drain(False, n_events, tx_size,
                                      max_size_bytes, max_fill_ms)
        t = await _exactly_once_drain(True, n_events, tx_size,
                                      max_size_bytes, max_fill_ms)
        if plain is None or p["events_per_second"] > \
                plain["events_per_second"]:
            plain = p
        if txn is None or t["events_per_second"] > \
                txn["events_per_second"]:
            txn = t
    leg = await _exactly_once_restart_leg(n_events, tx_size,
                                          max_size_bytes, max_fill_ms)
    ratio = txn["events_per_second"] / max(1, plain["events_per_second"])
    failures = []
    if txn["uncoordinated_writes"]:
        failures.append(
            f"{txn['uncoordinated_writes']} CDC write(s) bypassed the "
            f"transactional seam in the drain leg")
    if leg["uncoordinated_writes"]:
        failures.append(
            f"{leg['uncoordinated_writes']} CDC write(s) bypassed the "
            f"transactional seam in the restart leg")
    if leg["duplicate_rows"]:
        failures.append(
            f"exactly-once violated across the hard kill: "
            f"{leg['duplicate_rows']} duplicate row(s) reached the sink")
    if leg["rows_delivered"] < n_events:
        failures.append(
            f"loss across the hard kill: {leg['rows_delivered']}"
            f"/{n_events} rows delivered")
    if leg["restreamed_deduped_rows"] > leg["unacked_suffix_rows"]:
        failures.append(
            f"re-stream exceeded the unacked suffix: "
            f"{leg['restreamed_deduped_rows']} already-applied rows "
            f"re-delivered vs {leg['unacked_suffix_rows']} unacked at "
            f"the kill — recovery did not trim the resume point")
    if leg["recover_calls"] < 1:
        failures.append("the restart never queried the sink high-water "
                        "mark")
    return {
        "mode": "exactly_once",
        "events": n_events,
        "plain": plain,
        "transactional": txn,
        "restart": leg,
        "plain_events_per_second": plain["events_per_second"],
        "transactional_events_per_second": txn["events_per_second"],
        "exactly_once_overhead_ratio": round(ratio, 3),
        "failures": failures,
        "ok": not failures,
    }
