"""Benchmark harness: the reference etl-benchmarks surface.

Modes (reference crates/etl-benchmarks/src/{table_copy,table_streaming}.rs):
  decode           WAL records/sec decoded, TPU vs CPU (bench.py default)
  table_copy       full-pipeline initial copy: rows/s, MiB/s, phase timings
  table_streaming  CDC through the pipeline: producer + end-to-end events/s
  wide_row         100-column mixed-type decode (BASELINE.json config)

Each mode emits a JSON report; `python -m etl_tpu.benchmarks.compare A B`
diffs two reports (reference `cargo x benchmark-compare`).
"""

from __future__ import annotations

import asyncio
import statistics
import time


def _median(xs):
    return statistics.median(xs)


# ---------------------------------------------------------------------------
# table_copy (reference table_copy.rs:74-183)
# ---------------------------------------------------------------------------


async def run_table_copy(n_rows: int = 100_000, samples: int = 3,
                         engine: str = "tpu") -> dict:
    from ..config import BatchConfig, BatchEngine, PipelineConfig
    from ..destinations import MemoryDestination
    from ..models import ColumnSchema, Oid, TableName, TableSchema
    from ..models.table_state import TableStateType
    from ..postgres.fake import FakeDatabase, FakeSource
    from ..runtime import Pipeline
    from ..store import NotifyingStore

    TID = 16384
    rows = [[str(i), str(i % 100), str(i * 7 % 10**9), "x" * 64]
            for i in range(n_rows)]
    bytes_estimate = sum(len("\t".join(r)) + 1 for r in rows[:1000]) \
        * (n_rows / min(1000, max(1, n_rows)))

    results = []
    for _ in range(samples):
        db = FakeDatabase()
        db.create_table(TableSchema(
            TID, TableName("public", "bench_copy"),
            (ColumnSchema("id", Oid.INT8, nullable=False,
                          primary_key_ordinal=1),
             ColumnSchema("bucket", Oid.INT4),
             ColumnSchema("val", Oid.INT8),
             ColumnSchema("filler", Oid.TEXT))), rows=rows)
        db.create_publication("pub", [TID])
        store = NotifyingStore()
        pipeline = Pipeline(
            config=PipelineConfig(
                pipeline_id=1, publication_name="pub",
                batch=BatchConfig(max_fill_ms=40,
                                  batch_engine=BatchEngine(engine))),
            store=store, destination=MemoryDestination(),
            source_factory=lambda: FakeSource(db))
        t0 = time.perf_counter()
        await pipeline.start()
        t_started = time.perf_counter()
        await asyncio.wait_for(store.notify_on(TID, TableStateType.READY), 300)
        t_copied = time.perf_counter()
        await pipeline.shutdown_and_wait()
        t_done = time.perf_counter()
        results.append({
            "pipeline_start_ms": (t_started - t0) * 1000,
            "copy_wait_ms": (t_copied - t_started) * 1000,
            "shutdown_ms": (t_done - t_copied) * 1000,
            "total_ms": (t_done - t0) * 1000,
            "rows_per_second": n_rows / (t_copied - t_started),
            "estimated_mib_per_second":
                bytes_estimate / (1 << 20) / (t_copied - t_started),
        })
    agg = {k: _median([r[k] for r in results]) for k in results[0]}
    return {"mode": "table_copy", "rows": n_rows, "samples": samples,
            "engine": engine, **{k: round(v, 2) for k, v in agg.items()}}


# ---------------------------------------------------------------------------
# table_streaming (reference table_streaming.rs:86-118)
# ---------------------------------------------------------------------------


async def run_table_streaming(n_events: int = 100_000, tx_size: int = 500,
                              engine: str = "tpu",
                              destination: str = "null",
                              max_fill_ms: int = 150) -> dict:
    """CDC throughput + p50 end-to-end replication lag.

    destination='null' counts delivered rows without materializing
    per-row Python objects (reference etl-benchmarks null destination
    mode) — it still RESOLVES every decoded batch, so the device decode
    is on the measured path; 'memory' exercises full row expansion.
    The default fill window (150 ms) lets sustained CDC accumulate
    device-scale runs, engaging the batch engine the way a WAL burst
    does in production.
    """
    from ..config import BatchConfig, BatchEngine, PipelineConfig
    from ..destinations import MemoryDestination
    from ..destinations.base import Destination, WriteAck
    from ..models import (ColumnSchema, InsertEvent, Oid, TableName,
                          TableSchema)
    from ..models.event import DecodedBatchEvent
    from ..models.table_state import TableStateType
    from ..postgres.fake import FakeDatabase, FakeSource
    from ..runtime import Pipeline
    from ..store import NotifyingStore

    TID = 16385
    db = FakeDatabase()
    db.create_table(TableSchema(
        TID, TableName("public", "bench_stream"),
        (ColumnSchema("id", Oid.INT8, nullable=False, primary_key_ordinal=1),
         ColumnSchema("v", Oid.INT4),
         ColumnSchema("note", Oid.TEXT))))
    db.create_publication("pub", [TID])
    store = NotifyingStore()

    # p50 end-to-end replication lag (a named BASELINE metric): per-event
    # lag = destination arrival − source commit of its transaction
    commit_times: dict[int, float] = {}
    arrivals: list[tuple[int, float]] = []

    class NullDestination(Destination):
        """Counts delivered rows; resolves (but never row-expands) decoded
        batches — the reference null-destination stance."""

        def __init__(self):
            self.rows_delivered = 0

        async def startup(self):
            return None

        async def write_table_rows(self, schema, batch):
            return WriteAck.durable()

        async def write_events(self, events):
            now = time.perf_counter()
            for e in events:
                if isinstance(e, DecodedBatchEvent):
                    self.rows_delivered += e.batch.num_rows  # forces decode
                    for lsn in set(int(x) for x in e.commit_lsns):
                        arrivals.append((lsn, now))
                elif isinstance(e, InsertEvent):
                    self.rows_delivered += 1
                    arrivals.append((int(e.commit_lsn), now))
            return WriteAck.durable()

        async def drop_table(self, table_id, schema=None):
            return None

        async def truncate_table(self, table_id):
            return None

    class LagMeasuringDestination(MemoryDestination):
        rows_delivered = property(lambda self: sum(
            1 for e in self.events if isinstance(e, InsertEvent)))

        async def write_events(self, events):
            from ..destinations.base import expand_batch_events

            ack = await super().write_events(events)
            now = time.perf_counter()
            for e in expand_batch_events(events):
                if isinstance(e, InsertEvent):
                    arrivals.append((int(e.commit_lsn), now))
            return ack

    dest = NullDestination() if destination == "null" \
        else LagMeasuringDestination()
    pipeline = Pipeline(
        config=PipelineConfig(
            pipeline_id=1, publication_name="pub",
            batch=BatchConfig(max_fill_ms=max_fill_ms,
                              batch_engine=BatchEngine(engine))),
        store=store, destination=dest,
        source_factory=lambda: FakeSource(db))
    await pipeline.start()
    await asyncio.wait_for(store.notify_on(TID, TableStateType.READY), 60)

    # warmup: one transaction through the full path so the per-schema jit
    # compile of the host-vectorized decode program (a one-time cost, like
    # the decode bench's warmup) lands outside the measured window
    warmup_rows = tx_size
    tx = db.transaction()
    for i in range(warmup_rows):
        tx.insert(TID, [str(-1 - i), "0", "warmup"])
    await tx.commit()

    async def wait_warmup():
        while dest.rows_delivered < warmup_rows:
            if pipeline._apply_task is not None \
                    and pipeline._apply_task.done():
                pipeline._apply_task.result()  # surface the pipeline error
                raise RuntimeError("pipeline stopped during warmup")
            await asyncio.sleep(0.02)

    await asyncio.wait_for(wait_warmup(), timeout=120)
    arrivals.clear()
    commit_times.clear()
    # baseline BEFORE production starts: measured rows deliver concurrently
    # with the producer loop, so a later capture would double-count them
    base_delivered = dest.rows_delivered

    # payload encode happens OFF the clock: the reference bench's producer
    # is a separate Postgres server, not a Python encoder stealing the
    # pipeline's only core — the measured window covers walsender framing
    # + wire + pipeline, which is the system under test
    from ..postgres.codec.pgoutput import encode_insert
    payloads = [encode_insert(TID, [str(i).encode(), str(i % 97).encode(),
                                    b"note-%d" % i])
                for i in range(n_events)]

    t_prod0 = time.perf_counter()
    produced = 0
    while produced < n_events:
        tx = db.transaction()
        for _ in range(min(tx_size, n_events - produced)):
            tx.insert_preencoded(TID, payloads[produced])
            produced += 1
        lsn = await tx.commit()
        commit_times[int(lsn)] = time.perf_counter()
    t_prod1 = time.perf_counter()

    def delivered():
        return dest.rows_delivered - base_delivered

    async def wait_delivered():
        while delivered() < n_events:
            if pipeline._apply_task is not None \
                    and pipeline._apply_task.done():
                pipeline._apply_task.result()  # surface the pipeline error
                raise RuntimeError("pipeline stopped before delivering")
            await asyncio.sleep(0.02)

    await asyncio.wait_for(wait_delivered(), timeout=300)
    t_e2e = time.perf_counter()
    await pipeline.shutdown_and_wait()
    t_drain = time.perf_counter()
    # NOTE: CDC flush runs are far below DeviceDecoder.DEVICE_MIN_ROWS, so
    # this mode measures the host decode path for both engines (the hybrid
    # threshold routes small runs to the CPU oracle by design); the device
    # path is measured by the decode and wide_row modes.
    lags_ms = [(t - commit_times[lsn]) * 1000 for lsn, t in arrivals
               if lsn in commit_times]
    lags_ms.sort()

    def pct(p):
        return lags_ms[min(len(lags_ms) - 1,
                           int(p * len(lags_ms)))] if lags_ms else None

    return {
        "mode": "table_streaming", "events": n_events, "engine": engine,
        "destination": destination,
        "producer_events_per_second":
            round(n_events / (t_prod1 - t_prod0)),
        "end_to_end_events_per_second":
            round(n_events / (t_e2e - t_prod0)),
        "end_to_end_with_shutdown_events_per_second":
            round(n_events / (t_drain - t_prod0)),
        "throughput_events": delivered(),
        "replication_lag_p50_ms":
            round(pct(0.50), 2) if lags_ms else None,
        "replication_lag_p95_ms":
            round(pct(0.95), 2) if lags_ms else None,
        "replication_lag_max_ms": round(lags_ms[-1], 2) if lags_ms else None,
    }


# ---------------------------------------------------------------------------
# wide_row (BASELINE.json config: 100-col mixed types)
# ---------------------------------------------------------------------------


def run_wide_row(n_rows: int = 16_384, n_iters: int = 5,
                 engine: str = "xla") -> dict:
    import random

    from ..models import (ColumnSchema, Oid, ReplicatedTableSchema,
                          TableName, TableSchema)
    from ..ops import DeviceDecoder, stage_tuples
    from ..postgres.codec.pgoutput import TUPLE_NULL, TUPLE_TEXT, TupleData

    rng = random.Random(11)
    kinds = [Oid.INT8, Oid.INT4, Oid.NUMERIC, Oid.TEXT, Oid.TIMESTAMPTZ,
             Oid.DATE, Oid.BOOL, Oid.FLOAT8, Oid.JSONB, Oid.UUID]
    oids = [kinds[i % len(kinds)] for i in range(100)]
    cols = tuple(ColumnSchema(f"c{i}", oid) for i, oid in enumerate(oids))
    schema = ReplicatedTableSchema.with_all_columns(TableSchema(
        9, TableName("public", "wide"), cols))

    def text_for(oid):
        if oid == Oid.INT8:
            return str(rng.randrange(-10**12, 10**12))
        if oid == Oid.INT4:
            return str(rng.randrange(-10**9, 10**9))
        if oid == Oid.NUMERIC:
            return f"{rng.randrange(0, 10**8)}.{rng.randrange(0, 100):02d}"
        if oid == Oid.TEXT:
            return "text-" + str(rng.randrange(10**6))
        if oid == Oid.TIMESTAMPTZ:
            return "2024-05-01 12:34:56.789+00"
        if oid == Oid.DATE:
            return "2024-05-01"
        if oid == Oid.BOOL:
            return rng.choice(["t", "f"])
        if oid == Oid.FLOAT8:
            return f"{rng.uniform(-1e6, 1e6):.6f}"
        if oid == Oid.JSONB:
            return '{"k": %d}' % rng.randrange(1000)
        return "a0eebc99-9c0b-4ef8-bb6d-6bb9bd380a11"

    tuples = []
    for _ in range(n_rows):
        vals = []
        for oid in oids:
            if rng.random() < 0.05:
                vals.append(None)
            else:
                vals.append(text_for(oid).encode())
        tuples.append(TupleData(
            [TUPLE_NULL if v is None else TUPLE_TEXT for v in vals], vals))

    staged = stage_tuples(tuples, 100)
    dec = DeviceDecoder(schema, use_pallas=(engine == "pallas"))
    dec.decode(staged)  # warmup
    times = []
    for _ in range(n_iters):
        t0 = time.perf_counter()
        dec.decode(staged)
        times.append(time.perf_counter() - t0)
    rps = n_rows / _median(times)
    # a failed pallas compile silently falls back to XLA mid-warmup —
    # report the engine that actually ran
    ran = "pallas" if dec.use_pallas and engine == "pallas" else "xla"
    return {"mode": "wide_row", "rows": n_rows, "columns": 100,
            "engine": ran,
            "rows_per_second": round(rps),
            "cells_per_second": round(rps * 100)}
