"""Coldstart measurement worker: one replicator lifetime as a subprocess.

`bench.py --coldstart` (harness.run_coldstart) runs this module twice
against one program-cache directory: the first run is the COLD start
(every decode program is a fresh XLA build, kicked to background threads
while rows decode on the host oracle), the second is the WARM restart
(Pipeline.start's prewarm loads the serialized executables from disk
before the apply loop sees traffic). Each run prints one JSON line:

  start_seconds            Pipeline.start wall clock (prewarm included)
  first_durable_seconds    start() begin → first rows durable at the
                           destination (restart-to-first-durable-batch)
  total_seconds            start() begin → full workload delivered
  programs_compiled        etl_programs_compiled_total (the gate: a warm
                           restart must report 0)
  cache_hits_disk/memory, cache_misses, background_compiles
  oracle_rows/host_rows    decode routing during the run — the oracle
                           share IS the cost of an unwarmed cache
  canonical_layouts        distinct canonical layouts (N tables → O(1))

The tables deliberately share one canonical layout under permuted column
orders, so the cold run's compile count proves canonicalization (one
program per row bucket, not per table) and the warm run proves
persistence (zero programs, disk hits only). Schemas are pre-stored in
the state store before start — the store state a real restart inherits.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

#: covers every row bucket a paced flush can stage into, so a warm
#: restart can never fall off the cache onto a fresh build; the emitted
#: `prewarm_buckets` count is what run_coldstart bounds the cold run's
#: compile count by (canonical layouts make it buckets, not tables ×
#: buckets)
PREWARM_BUCKETS = (256, 1024, 4096)


async def run(cache_dir: str, n_tables: int, rows_per_tx: int,
              txs_per_table: int) -> dict:
    from ..config import BatchConfig, BatchEngine, PipelineConfig
    from ..destinations.base import Destination, WriteAck
    from ..models import (ColumnSchema, Oid, ReplicatedTableSchema,
                          TableName, TableSchema)
    from ..models.event import DecodedBatchEvent
    from ..models.table_state import TableStateType
    from ..ops.engine import background_compiles_inflight
    from ..postgres.codec.pgoutput import encode_insert
    from ..postgres.fake import FakeDatabase, FakeSource
    from ..runtime import Pipeline
    from ..store import NotifyingStore
    from ..telemetry.metrics import (
        ETL_COMPILE_CACHE_HITS_TOTAL, ETL_COMPILE_CACHE_MISSES_TOTAL,
        ETL_DECODE_BACKGROUND_COMPILES_TOTAL,
        ETL_DECODE_CANONICAL_LAYOUTS, ETL_DECODE_ROUTED_HOST_ROWS_TOTAL,
        ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL, ETL_PROGRAMS_COMPILED_TOTAL,
        registry)

    # one kind mix, column order rotated per table: every table resolves
    # to the SAME canonical layout (the sharing the cold compile count
    # gates on)
    kinds = [Oid.INT8, Oid.INT4, Oid.FLOAT8, Oid.INT4, Oid.TIMESTAMP,
             Oid.INT8, Oid.NUMERIC, Oid.INT4]
    db = FakeDatabase()
    tids = []
    for t in range(n_tables):
        tid = 17000 + t
        rot = kinds[t % len(kinds):] + kinds[: t % len(kinds)]
        cols = [ColumnSchema("id", Oid.INT8, nullable=False,
                             primary_key_ordinal=1)]
        cols += [ColumnSchema(f"c{i}", o) for i, o in enumerate(rot)]
        db.create_table(TableSchema(tid, TableName("public", f"cold_{t}"),
                                    tuple(cols)))
        tids.append(tid)
    db.create_publication("pub", tids)

    store = NotifyingStore()
    # the restart contract: schemas already live in the SchemaStore (a
    # real store survives the process), so prewarm has layouts to warm
    for tid in tids:
        await store.store_table_schema(
            ReplicatedTableSchema.with_all_columns(db.tables[tid].schema), 0)

    delivered = [0]
    first_durable = [None]
    t0 = time.perf_counter()

    class CountingDestination(Destination):
        async def startup(self):
            return None

        async def write_table_rows(self, schema, batch):
            return WriteAck.durable()

        async def write_events(self, events):
            for e in events:
                if isinstance(e, DecodedBatchEvent):
                    delivered[0] += e.batch.num_rows  # forces decode
            if delivered[0] and first_durable[0] is None:
                first_durable[0] = time.perf_counter() - t0
            return WriteAck.durable()

        async def drop_table(self, table_id, schema=None):
            return None

        async def truncate_table(self, table_id):
            return None

    def counters():
        return {
            "programs_compiled":
                registry.get_counter(ETL_PROGRAMS_COMPILED_TOTAL),
            "cache_hits_disk": registry.get_counter(
                ETL_COMPILE_CACHE_HITS_TOTAL, {"layer": "disk"}),
            "cache_hits_memory": registry.get_counter(
                ETL_COMPILE_CACHE_HITS_TOTAL, {"layer": "memory"}),
            "cache_misses_absent": registry.get_counter(
                ETL_COMPILE_CACHE_MISSES_TOTAL, {"reason": "absent"}),
            "cache_misses_invalid": registry.get_counter(
                ETL_COMPILE_CACHE_MISSES_TOTAL, {"reason": "invalid"}),
            "background_compiles": registry.get_counter(
                ETL_DECODE_BACKGROUND_COMPILES_TOTAL),
            "oracle_rows": registry.get_counter(
                ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL),
            "host_rows": registry.get_counter(
                ETL_DECODE_ROUTED_HOST_ROWS_TOTAL),
        }

    dest = CountingDestination()
    pipeline = Pipeline(
        config=PipelineConfig(
            pipeline_id=1, publication_name="pub",
            batch=BatchConfig(max_fill_ms=30,
                              batch_engine=BatchEngine.TPU,
                              program_cache_dir=cache_dir,
                              prewarm_row_buckets=PREWARM_BUCKETS)),
        store=store, destination=dest,
        source_factory=lambda: FakeSource(db))
    await pipeline.start()
    start_seconds = time.perf_counter() - t0
    for tid in tids:
        await asyncio.wait_for(
            store.notify_on(tid, TableStateType.READY), 60)

    total = 0
    for round_i in range(txs_per_table):
        for tid in tids:
            tx = db.transaction()
            for i in range(rows_per_tx):
                row = [str(total + i).encode(), b"7", b"1.5", b"9",
                       b"2026-01-01 00:00:00", b"42", b"3.14", b"11"]
                # rotate values to match each table's rotated kinds
                t = tid - 17000
                r = t % 8
                tx.insert_preencoded(tid, encode_insert(
                    tid, [str(total + i).encode()] + row[r:] + row[:r]))
            lsn = await tx.commit()
            total += rows_per_tx
            # paced: await delivery each tx so flush sizes stay inside
            # the prewarmed buckets and the run measures steady decode,
            # not producer/consumer queue dynamics
            deadline = time.monotonic() + 60
            while delivered[0] < total:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"delivery stalled at "
                                       f"{delivered[0]}/{total}")
                await asyncio.sleep(0.01)
    total_seconds = time.perf_counter() - t0

    # the cold run's background builds must land (and persist) before
    # exit, or the warm run would have nothing to load
    deadline = time.monotonic() + 240
    while background_compiles_inflight() > 0:
        if time.monotonic() > deadline:
            raise TimeoutError("background compiles never finished")
        await asyncio.sleep(0.05)
    await pipeline.shutdown_and_wait()

    out = counters()
    out.update({
        "start_seconds": round(start_seconds, 3),
        "first_durable_seconds": round(first_durable[0], 3)
        if first_durable[0] is not None else None,
        "total_seconds": round(total_seconds, 3),
        "rows_delivered": delivered[0],
        "canonical_layouts":
            registry.get_gauge(ETL_DECODE_CANONICAL_LAYOUTS),
        "tables": n_tables,
        "prewarm_buckets": len(PREWARM_BUCKETS),
    })
    return out


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--cache-dir", required=True)
    p.add_argument("--tables", type=int, default=3)
    p.add_argument("--rows-per-tx", type=int, default=800)
    p.add_argument("--txs-per-table", type=int, default=2)
    args = p.parse_args()
    out = asyncio.run(run(args.cache_dir, args.tables, args.rows_per_tx,
                          args.txs_per_table))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
