"""Benchmark comparison: diff two JSON reports.

Reference parity: `cargo x benchmark-compare`
(crates/xtask/src/commands/benchmark_compare.rs) — CI compares reports
run-over-run instead of asserting absolute thresholds.

Usage: python -m etl_tpu.benchmarks.compare old.json new.json [--fail-pct N]
"""

from __future__ import annotations

import argparse
import json
import sys

HIGHER_IS_BETTER = ("per_second", "throughput", "value")
LOWER_IS_BETTER = ("_ms",)


def compare(old: dict, new: dict) -> "tuple[list[str], float]":
    lines = []
    worst_regression = 0.0
    for key in sorted(set(old) | set(new)):
        ov, nv = old.get(key), new.get(key)
        if not isinstance(ov, (int, float)) or not isinstance(nv, (int, float)) \
                or isinstance(ov, bool):
            continue
        if ov == 0:
            continue
        delta_pct = (nv - ov) / abs(ov) * 100
        direction = ""
        if delta_pct != 0:
            if any(t in key for t in HIGHER_IS_BETTER):
                direction = "better" if delta_pct > 0 else "worse"
            elif any(t in key for t in LOWER_IS_BETTER):
                direction = "better" if delta_pct < 0 else "worse"
        lines.append(f"{key}: {ov:g} -> {nv:g} ({delta_pct:+.1f}%"
                     + (f", {direction}" if direction else "") + ")")
        if direction == "worse":
            worst_regression = max(abs(delta_pct), worst_regression)
    return lines, worst_regression


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="etl_tpu.benchmarks.compare")
    p.add_argument("old")
    p.add_argument("new")
    p.add_argument("--fail-pct", type=float, default=None,
                   help="exit 1 if any 'worse' metric regresses more than N%%")
    args = p.parse_args(argv)
    old = json.load(open(args.old))
    new = json.load(open(args.new))
    lines, worst = compare(old, new)
    for line in lines:
        print(line)
    if args.fail_pct is not None and worst and worst > args.fail_pct:
        print(f"REGRESSION: worst {worst:.1f}% > {args.fail_pct}%")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
