"""One shard replicator as its own OS process — the bench pod model.

`python -m etl_tpu.benchmarks.shard_worker '<spec json>'` runs ONE
pipeline (shard-scoped or unsharded) against its own fake source replica
and prints a single JSON result line. The parent (`bench.py --sharded K`
via `harness.run_sharded_processes`) launches K of these concurrently:
separate interpreters, separate GILs, separate XLA runtimes — the same
resource split as K replicator pods, which is the whole point of
horizontal scale-out (an in-process K-way run shares one GIL and one
event loop and measures nothing).

Faithfulness contract: every worker replays the IDENTICAL publication
WAL — the workload generator's byte-identical `(profile, seed)` replay
contract (docs/workloads.md) makes K private FakeDatabase replicas
indistinguishable from K connections to one source. A sharded worker
applies only its ShardMap slice and verifies that slice against the
generator's committed truth; the parent asserts the slices cover every
table. The store is a per-process MemoryStore: this bench measures
decode/apply capacity — shared-store semantics (ownership fences, epoch
refusal, rebalancing) are covered by the chaos scenario and
tests/test_sharding.py.

Reported `events_per_second` counts ROW EVENTS DELIVERED at this
worker's destination over its measured window (produce start → slice
verified), so the K-shard aggregate and the single-shard baseline count
the same units.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import sys
import time


def _row_events(dest) -> int:
    from ..models.event import DeleteEvent, InsertEvent, UpdateEvent

    return sum(1 for e in dest.events
               if isinstance(e, (InsertEvent, UpdateEvent, DeleteEvent)))


async def run_worker(spec: dict) -> dict:
    from ..chaos.invariants import view_matches
    from ..chaos.runner import TracingDestination
    from ..config import BatchConfig, BatchEngine, PipelineConfig
    from ..models.table_state import TableStateType
    from ..postgres.fake import FakeSource
    from ..runtime import Pipeline
    from ..sharding import ShardMap
    from ..store import NotifyingStore
    from ..workloads import WorkloadGenerator, get_profile
    from .harness import _wait_background_compiles

    shard = spec.get("shard")  # None = unsharded baseline
    shard_count = int(spec.get("shard_count", 1))
    prof = dataclasses.replace(get_profile(spec.get("profile",
                                                    "insert_heavy")),
                               tables=int(spec.get("tables", 8)))
    gen = WorkloadGenerator(prof, seed=int(spec.get("seed", 7)))
    db = gen.build_db()
    owned = gen.table_ids if shard is None else \
        ShardMap(shard_count).tables_for_shard(gen.table_ids, shard)
    store = NotifyingStore()
    dest = TracingDestination()
    pipeline = Pipeline(
        config=PipelineConfig(
            pipeline_id=1, publication_name="pub",
            batch=BatchConfig(max_fill_ms=30,
                              batch_engine=BatchEngine(
                                  spec.get("engine", "tpu"))),
            lag_sample_interval_s=0,
            shard=shard, shard_count=shard_count),
        store=store, destination=dest,
        source_factory=lambda: FakeSource(db))

    def delivered() -> bool:
        return view_matches(dest, owned,
                            {tid: gen.expected[tid] for tid in owned})

    async def wait_verified() -> None:
        seen = -1
        while True:
            n = len(dest.events)
            if n == seen and delivered():
                return
            seen = n
            if pipeline._apply_task is not None \
                    and pipeline._apply_task.done():
                pipeline._apply_task.result()
                raise RuntimeError("pipeline stopped before delivering")
            await asyncio.sleep(0.1)

    target_ops = int(spec.get("target_ops", 2_000))
    verify_timeout_s = float(spec.get("verify_timeout_s", 240.0))
    try:
        await pipeline.start()
        for tid in owned:
            await asyncio.wait_for(
                store.notify_on(tid, TableStateType.READY), 120)
        warm_target = max(100, target_ops // 5)
        while gen.row_ops < warm_target:
            await gen.run_tx(db)
        await asyncio.wait_for(wait_verified(), 240)
        await _wait_background_compiles()

        ops0 = gen.row_ops
        ev0 = _row_events(dest)
        t0 = time.perf_counter()
        while gen.row_ops - ops0 < target_ops:
            await gen.run_tx(db)
        t_prod = time.perf_counter()
        try:
            await asyncio.wait_for(wait_verified(), verify_timeout_s)
            verified = True
        except asyncio.TimeoutError:
            verified = False
        t_done = time.perf_counter()
        ev1 = _row_events(dest)
    finally:
        if pipeline._apply_task is not None:
            await pipeline.shutdown_and_wait()

    window = max(t_done - t0, 1e-9)
    return {
        "shard": shard, "shard_count": shard_count,
        "profile": prof.name, "tables": len(owned),
        "owned_table_ids": list(owned),
        "committed_ops": gen.row_ops - ops0,
        "delivered_row_events": ev1 - ev0,
        "produce_seconds": round(t_prod - t0, 4),
        "window_seconds": round(window, 4),
        "events_per_second": round((ev1 - ev0) / window),
        "verified": bool(verified),
    }


def main(argv: "list[str] | None" = None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    if len(args) != 1:
        print(json.dumps({"error": "usage: shard_worker '<spec json>'"}))
        return 2
    import os

    if os.environ.get("JAX_PLATFORMS") is None:
        os.environ["JAX_PLATFORMS"] = "cpu"  # never touch the tunnel
    spec = json.loads(args[0])
    out = asyncio.run(run_worker(spec))
    print(json.dumps(out))
    return 0 if out.get("verified") else 1


if __name__ == "__main__":
    sys.exit(main())
