"""Lane-packed (transposed) field parsers for the Pallas kernel.

The row-major parsers in ops/parsers.py operate on `[R, L]` byte
matrices whose minor (lane) dimension is the field width L = 1-12 —
under Mosaic every intermediate pads L to 128 lanes, wasting >90% of
the VPU (the measured 18x loss vs XLA, VERDICT r3 #8). This module is
the lane-packed redesign: each field byte POSITION is one full `[R]`
vector (R = the Pallas block's row count, a multiple of 128), so every
vector op runs on fully-populated lanes and the per-position work is a
short static Python loop over the field width.

Semantics are transcribed 1:1 from parsers.py (same component names,
same ok conditions, same CPU-fallback boundaries); the differential
suites run both engines over the same inputs and must agree bit-for-bit.
Scalar helpers (pow10 select chain, civil-date math, limb range checks)
are shared by import so the two conventions cannot drift on the math.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..models.pgtypes import CellKind
from .parsers import (COLON, D0, DASH, DOT, MINUS, PLUS, SPACE,
                      _days_from_civil_dev, _int_range_ok,
                      _nibble_to_ascii, pow10)


def _row(rows, i):
    """rows[i], or a zero vector past the gathered width (parsers.py
    indexes into the zero-padded [R, L] matrix; the transposed form must
    read the same zeros)."""
    return rows[i] if 0 <= i < len(rows) else jnp.zeros_like(rows[0])


def _at(rows, q):
    """Per-row dynamic position read: rows[q[r]][r] — the transposed
    take_along_axis, lowered as a select chain (Mosaic has no sublane
    gather)."""
    out = jnp.zeros_like(q)
    for i in range(len(rows)):
        out = jnp.where(q == i, rows[i], out)
    return out


def _true(v):
    return jnp.ones_like(v, dtype=bool)


# -- integers ---------------------------------------------------------------


def _digit_limbs_lanes(rows, lengths, start, n_limbs: int = 3):
    L = len(rows)
    all_digits = _true(lengths)
    limbs = [jnp.zeros_like(lengths) for _ in range(n_limbs)]
    for i in range(L):
        d = rows[i] - D0
        in_range = (start <= i) & (i < lengths)
        is_digit = (d >= 0) & (d <= 9)
        all_digits &= ~(in_range & ~is_digit)
        r = lengths - 1 - i
        w = pow10(r % 9)
        dd = jnp.where(in_range & is_digit, d, 0)
        k = r // 9
        for kk in range(n_limbs):
            limbs[kk] = limbs[kk] + jnp.where(in_range & (k == kk),
                                              dd * w, 0)
    return limbs, all_digits


def parse_int_lanes(rows, lengths):
    neg = rows[0] == MINUS
    plus = rows[0] == PLUS
    start = (neg | plus).astype(jnp.int32)
    limbs, all_digits = _digit_limbs_lanes(rows, lengths, start)
    ndigits = lengths - start
    ok = all_digits & (ndigits >= 1) & (ndigits <= 27) \
        & (lengths <= len(rows))
    return neg, limbs[0], limbs[1], limbs[2], ndigits, ok


def parse_bool_lanes(rows, lengths):
    t = rows[0] == ord("t")
    f = rows[0] == ord("f")
    ok = (lengths == 1) & (t | f)
    return t, ok


# -- date / time ------------------------------------------------------------


def _fixed2_lanes(rows, p):
    return (_row(rows, p) - D0) * 10 + (_row(rows, p + 1) - D0)


def parse_date_lanes(rows, lengths):
    def dig(i):
        return _row(rows, i) - D0

    y = dig(0) * 1000 + dig(1) * 100 + dig(2) * 10 + dig(3)
    m = _fixed2_lanes(rows, 5)
    dd = _fixed2_lanes(rows, 8)
    digits_ok = _true(lengths)
    for i in (0, 1, 2, 3, 5, 6, 8, 9):
        digits_ok &= (dig(i) >= 0) & (dig(i) <= 9)
    ok = (lengths == 10) & digits_ok \
        & (_row(rows, 4) == DASH) & (_row(rows, 7) == DASH) \
        & (m >= 1) & (m <= 12) & (dd >= 1) & (dd <= 31) & (y >= 1)
    days = _days_from_civil_dev(y, m, dd)
    return jnp.where(ok, days, 0), ok


def _parse_hms_at_lanes(rows, lengths, base: int):
    L = len(rows)
    hh = _fixed2_lanes(rows, base)
    mm = _fixed2_lanes(rows, base + 3)
    ss = _fixed2_lanes(rows, base + 6)
    sep_ok = (_row(rows, base + 2) == COLON) \
        & (_row(rows, base + 5) == COLON)
    digits_ok = _true(lengths)
    for i in (base, base + 1, base + 3, base + 4, base + 6, base + 7):
        d = _row(rows, i) - D0
        digits_ok &= (d >= 0) & (d <= 9)
    if base + 8 < L:
        has_dot = (lengths > base + 8) & (rows[base + 8] == DOT)
    else:
        has_dot = jnp.zeros_like(lengths, dtype=bool)

    # fractional digits: contiguous run starting at base+9, max 6
    frac_start = base + 9
    running = _true(lengths)
    run = jnp.zeros_like(lengths)
    for k in range(6):
        i = frac_start + k
        d = _row(rows, i) - D0
        in_window = (i < L) & (i < lengths)
        this = in_window & (d >= 0) & (d <= 9)
        running &= this
        run = run + running.astype(jnp.int32)
    run = jnp.where(has_dot, run, 0)
    us = jnp.zeros_like(lengths)
    for k in range(6):
        i = frac_start + k
        d = _row(rows, i) - D0
        in_window = (i < L) & (i < lengths)
        frac_digit = in_window & (d >= 0) & (d <= 9)
        us = us + jnp.where(frac_digit & (k < run), d * 10 ** (5 - k), 0)
    frac_ok = ~has_dot | (run >= 1)
    end = base + 8 + jnp.where(has_dot, 1 + run, 0)
    sec = (hh * 60 + mm) * 60 + ss
    ok = sep_ok & digits_ok & frac_ok & (hh <= 23) & (mm <= 59) & (ss <= 59)
    return sec, us, end, ok


def parse_time_lanes(rows, lengths):
    sec, us, end, ok = _parse_hms_at_lanes(rows, lengths, 0)
    ok = ok & (end == lengths)
    ms = sec * 1000 + us // 1000
    return ms, us % 1000, ok


def _parse_tz_at_lanes(rows, lengths, p):
    sign_b = _at(rows, p)
    neg = sign_b == MINUS
    sign_ok = neg | (sign_b == PLUS)
    d1, d2 = _at(rows, p + 1) - D0, _at(rows, p + 2) - D0
    hh = d1 * 10 + d2
    hh_ok = (d1 >= 0) & (d1 <= 9) & (d2 >= 0) & (d2 <= 9)
    has_min = (lengths > p + 3) & (_at(rows, p + 3) == COLON)
    m1, m2 = _at(rows, p + 4) - D0, _at(rows, p + 5) - D0
    mm = jnp.where(has_min, m1 * 10 + m2, 0)
    mm_ok = ~has_min | ((m1 >= 0) & (m1 <= 9) & (m2 >= 0) & (m2 <= 9))
    has_sec = has_min & (lengths > p + 6) & (_at(rows, p + 6) == COLON)
    s1, s2 = _at(rows, p + 7) - D0, _at(rows, p + 8) - D0
    ss = jnp.where(has_sec, s1 * 10 + s2, 0)
    ss_ok = ~has_sec | ((s1 >= 0) & (s1 <= 9) & (s2 >= 0) & (s2 <= 9))
    end = p + 3 + jnp.where(has_min, 3, 0) + jnp.where(has_sec, 3, 0)
    off = hh * 3600 + mm * 60 + ss
    off = jnp.where(neg, -off, off)
    return off, end, sign_ok & hh_ok & mm_ok & ss_ok & (hh <= 15)


def parse_timestamp_lanes(rows, lengths, with_tz: bool):
    days, date_ok = parse_date_lanes(rows[:10], jnp.full_like(lengths, 10))
    space_ok = _row(rows, 10) == SPACE
    sec, us, end, hms_ok = _parse_hms_at_lanes(rows, lengths, 11)
    if with_tz:
        tz, tz_end, tz_ok = _parse_tz_at_lanes(rows, lengths, end)
        ok = date_ok & space_ok & hms_ok & tz_ok & (tz_end == lengths)
    else:
        tz = jnp.zeros_like(sec)
        ok = date_ok & space_ok & hms_ok & (end == lengths)
    ok = ok & (lengths >= 19)
    ms = sec * 1000 + us // 1000
    return days, ms, us % 1000, tz, ok


# -- float ------------------------------------------------------------------


def parse_float_lanes(rows, lengths):
    L = len(rows)

    def match(lit: bytes):
        ok = lengths == len(lit)
        for i, ch in enumerate(lit):
            ok = ok & (_row(rows, i) == ch)
        return ok

    is_nan = match(b"NaN")
    is_pinf = match(b"Infinity")
    is_ninf = match(b"-Infinity")
    special = (is_nan * 1 + is_pinf * 2 + is_ninf * 3).astype(jnp.int32)

    neg = rows[0] == MINUS
    start = (neg | (rows[0] == PLUS)).astype(jnp.int32)

    # first 'e'/'E' position (argmax over axis 1 in the row-major form)
    e_pos = lengths
    has_e = jnp.zeros_like(lengths, dtype=bool)
    for i in reversed(range(L)):
        is_e_i = ((rows[i] == ord("e")) | (rows[i] == ord("E"))) \
            & (i < lengths)
        e_pos = jnp.where(is_e_i, i, e_pos)
        has_e = has_e | is_e_i
    # first '.' before the exponent
    dot_pos = e_pos
    has_dot = jnp.zeros_like(lengths, dtype=bool)
    n_dots = jnp.zeros_like(lengths)
    for i in reversed(range(L)):
        is_dot_i = (rows[i] == DOT) & (i < lengths) & (i < e_pos)
        dot_pos = jnp.where(is_dot_i, i, dot_pos)
        has_dot = has_dot | is_dot_i
        n_dots = n_dots + is_dot_i.astype(jnp.int32)

    frac_count = jnp.where(has_dot, e_pos - dot_pos - 1,
                           0).astype(jnp.int32)
    mant_valid = _true(lengths)
    n_mant = jnp.zeros_like(lengths)
    limb0 = jnp.zeros_like(lengths)
    limb1 = jnp.zeros_like(lengths)
    running_zero = _true(lengths)
    lead_zero_run = jnp.zeros_like(lengths)
    for i in range(L):
        d = rows[i] - D0
        is_digit = (d >= 0) & (d <= 9)
        is_dot_i = (rows[i] == DOT) & (i < lengths) & (i < e_pos)
        mant_sel = (start <= i) & (i < e_pos) & ~is_dot_i
        mant_valid &= ~(mant_sel & ~is_digit)
        n_mant = n_mant + mant_sel.astype(jnp.int32)
        r = jnp.where(i < dot_pos,
                      (dot_pos - 1 - i) + frac_count,
                      e_pos - 1 - i)
        w = pow10(r % 9)
        dd = jnp.where(mant_sel & is_digit, d, 0)
        limb0 = limb0 + jnp.where(mant_sel & (r // 9 == 0), dd * w, 0)
        limb1 = limb1 + jnp.where(mant_sel & (r // 9 == 1), dd * w, 0)
        # leading-zero run among mantissa digits (non-mantissa = neutral)
        running_zero &= jnp.where(mant_sel, d == 0, True)
        lead_zero_run = lead_zero_run \
            + (running_zero & mant_sel).astype(jnp.int32)

    # explicit exponent after 'e'
    exp_start = e_pos + 1
    exp_neg = has_e & (_at(rows, exp_start) == MINUS)
    exp_sign = has_e & (exp_neg | (_at(rows, exp_start) == PLUS))
    exp_d_start = exp_start + exp_sign.astype(jnp.int32)
    exp_valid = ~has_e | (lengths > exp_d_start)
    exp_val = jnp.zeros_like(lengths)
    for i in range(L):
        d = rows[i] - D0
        is_digit = (d >= 0) & (d <= 9)
        exp_sel = (exp_d_start <= i) & (i < lengths)
        exp_valid &= ~(exp_sel & ~is_digit)
        re = lengths - 1 - i
        ew = pow10(re % 9)
        exp_val = exp_val + jnp.where(exp_sel & is_digit & (re // 9 == 0),
                                      d * ew, 0)
    exp_val = jnp.where(exp_neg, -exp_val, exp_val)
    exp_val = jnp.where(has_e, exp_val, 0)

    sig = n_mant - lead_zero_run
    exp_adj = exp_val - frac_count
    fast = (sig <= 15) & (jnp.abs(exp_adj) <= 22) & (n_mant >= 1) \
        & (n_mant <= 18) & (n_dots <= 1) & mant_valid & exp_valid
    ok = fast | (special > 0)
    return neg, limb0, limb1, exp_adj, special, ok


# -- dispatch ---------------------------------------------------------------


def parse_column_lanes(kind, rows, lengths):
    """Transposed parse_column: `rows` is a tuple of int32[R] vectors
    (one per field byte position); returns ({component: int32[R]}, ok)."""
    if kind is CellKind.BOOL:
        t, ok = parse_bool_lanes(rows, lengths)
        return {"v": t.astype(jnp.int32)}, ok
    if kind in (CellKind.I16, CellKind.I32, CellKind.U32):
        neg, l0, l1, l2, nd, ok = parse_int_lanes(rows, lengths)
        ok = ok & _int_range_ok(kind, neg, l0, l1, l2, nd)
        v = l1 * jnp.int32(1_000_000_000) + l0
        return {"v": jnp.where(neg, -v, v)}, ok
    if kind is CellKind.I64:
        neg, l0, l1, l2, nd, ok = parse_int_lanes(rows, lengths)
        ok = ok & _int_range_ok(kind, neg, l0, l1, l2, nd)
        return {"neg": neg.astype(jnp.int32), "l0": l0, "l1": l1,
                "l2": l2}, ok
    if kind in (CellKind.F32, CellKind.F64):
        neg, l0, l1, ea, sp, ok = parse_float_lanes(rows, lengths)
        return {"neg": neg.astype(jnp.int32), "l0": l0, "l1": l1,
                "ea": ea, "sp": sp}, ok
    if kind is CellKind.DATE:
        days, ok = parse_date_lanes(rows, lengths)
        return {"days": days}, ok
    if kind is CellKind.TIME:
        ms, us, ok = parse_time_lanes(rows, lengths)
        return {"ms": ms, "us": us}, ok
    if kind in (CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ):
        days, ms, us, tz, ok = parse_timestamp_lanes(
            rows, lengths, with_tz=kind is CellKind.TIMESTAMPTZ)
        return {"days": days, "ms": ms - tz * 1000, "us": us}, ok
    raise AssertionError(kind)


def unpack_nibbles_lanes(packed_rows, width: int):
    """Transposed unpack_nibbles: packed_rows is W/2 int32[R] vectors of
    nibble pairs; returns W ASCII int32[R] vectors (position k from the
    high nibble of row k, position k + W/2 from the low nibble)."""
    his = [_nibble_to_ascii((p >> 4) & 0xF) for p in packed_rows]
    los = [_nibble_to_ascii(p & 0xF) for p in packed_rows]
    return his + los
