"""Host↔HBM staging: ragged WAL/COPY field bytes → fixed-shape device arrays.

This is the host half of the TPU decode engine. It converts ragged inputs —
pgoutput TupleData values or raw COPY text chunks — into the dense layout the
device kernels consume:

    data     uint8[capacity]      concatenated field bytes (zero-padded)
    offsets  int32[R, C]          start of each field in `data`
    lengths  int32[R, C]          field byte length
    nulls    bool[R, C]           SQL NULL ('n' tuple kind / COPY \\N)
    toast    bool[R, C]           TOAST-unchanged ('u' tuple kind)

Row counts are bucketed to powers of two so jit caches stay small; column
count C is static per schema. The COPY path is fully vectorized numpy
(the memchr/SIMD analogue of reference codec/table_row.rs:13-53); rows
containing escape sequences are flagged for the CPU fallback decoder.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from ..models.errors import ErrorKind, EtlError
from ..postgres.codec.pgoutput import (TUPLE_NULL, TUPLE_TEXT,
                                       TUPLE_UNCHANGED_TOAST, TupleData)

ROW_BUCKETS = (256, 1024, 4096, 16384, 65536, 131072, 262144)


def pad_to_multiple(n: int, multiple: int) -> int:
    """Round `n` up to a multiple of `multiple` (≥1). The mesh decode path
    pads row capacity with all-NULL rows so `sp` sharding engages on
    buckets the device count doesn't divide evenly."""
    if multiple <= 1:
        return n
    return -(-n // multiple) * multiple


def bucket_rows(n: int) -> int:
    """Row-capacity bucket for `n` rows. Staging call sites don't know
    the mesh, so mesh-divisibility padding happens at pack time
    (engine._pack_stage via pad_to_multiple) — sharded dispatch never
    silently rejects a bucket the device count doesn't divide."""
    for b in ROW_BUCKETS:
        if n <= b:
            return b
    return ((n + ROW_BUCKETS[-1] - 1) // ROW_BUCKETS[-1]) * ROW_BUCKETS[-1]


def bucket_pow2(n: int, lo: int = 8, hi: int = 2048) -> int:
    b = lo
    while b < n and b < hi:
        b *= 2
    return b


def bucket_width(n: int, hi: int = 2048) -> int:
    """Field-width bucket: multiples of 4 up to 32 (tight — upload bytes are
    precious over the device link), then powers of two."""
    if n <= 32:
        return max(4, (n + 3) & ~3)
    return bucket_pow2(n, lo=64, hi=hi)


@dataclass
class StagedBatch:
    """Fixed-shape staging of `n_rows` ragged rows × C fields."""

    data: np.ndarray  # uint8[cap]
    offsets: np.ndarray  # int32[R, C]
    lengths: np.ndarray  # int32[R, C]
    nulls: np.ndarray  # bool[R, C]
    toast: np.ndarray  # bool[R, C]
    n_rows: int  # valid rows (R may be larger: bucketed)
    cpu_fallback_rows: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.int64))
    # rows needing the exact CPU decoder (escapes, oversized fields)
    copy_escapes: bool = False  # True: field bytes may carry COPY escapes
    # False: the caller forbids publication row-filter compaction on this
    # batch (the assembler clears it for runs carrying updates/deletes or
    # old tuples — client-side filtering covers insert/COPY streams; U/D
    # row-filter transforms are the PG15 walsender's job, docs/decode-
    # pipeline.md). Copy chunks and insert runs keep the default.
    allow_row_filter: bool = True
    _maxlens: np.ndarray | None = field(default=None, repr=False,
                                        compare=False)

    @property
    def row_capacity(self) -> int:
        return self.offsets.shape[0]

    @property
    def n_cols(self) -> int:
        return self.offsets.shape[1]

    def field_bytes(self, row: int, col: int) -> bytes | None:
        """Raw bytes of one field (CPU fallback path)."""
        if self.nulls[row, col] or self.toast[row, col]:
            return None
        off, ln = int(self.offsets[row, col]), int(self.lengths[row, col])
        return self.data[off : off + ln].tobytes()

    def max_field_len(self, col: int) -> int:
        if self.n_rows == 0:
            return 0
        if self._maxlens is None:
            # one pass over all columns, cached: _widths/_specs/_complete
            # each consult per-column maxima on the hot path
            object.__setattr__(self, "_maxlens",
                               self.lengths[: self.n_rows].max(axis=0))
        return int(self._maxlens[col])

    def gather_rows(self, rows: np.ndarray) -> "StagedBatch":
        """Row-compacted view over the SAME data buffer: the per-row
        bookkeeping arrays gather by `rows` (survivor indices from the
        fused filter's in-program compaction), so the host completion —
        object columns, validity, CPU fixup — runs against the compacted
        index space with zero byte copies."""
        fb = self.cpu_fallback_rows
        if len(fb):
            fb = np.flatnonzero(np.isin(rows, fb)).astype(np.int64)
        return StagedBatch(
            self.data, self.offsets[rows], self.lengths[rows],
            self.nulls[rows], self.toast[rows], len(rows),
            cpu_fallback_rows=fb, copy_escapes=self.copy_escapes,
            allow_row_filter=False)


#: fetch-slice granularity: survivor counts bucket to multiples of
#: max(R/16, 256) so the filtered fetch compiles at most ~16 slice
#: programs per (capacity, layout) while bounding pad slack at ~1/16 of
#: the batch (the "pad slack" term in the bench.py --selectivity gate)
def slice_rows(n: int, capacity: int) -> int:
    if n <= 0:
        return 0
    step = max(256, capacity // 16)
    return min(capacity, -(-n // step) * step)


def stage_tuples(tuples: Sequence[TupleData], n_cols: int) -> StagedBatch:
    """Stage decoded pgoutput tuples. (The zero-copy path that never builds
    TupleData lives in the native framer; this is the portable version.)"""
    n = len(tuples)
    cap_rows = bucket_rows(n)
    offsets = np.zeros((cap_rows, n_cols), dtype=np.int32)
    lengths = np.zeros((cap_rows, n_cols), dtype=np.int32)
    nulls = np.zeros((cap_rows, n_cols), dtype=np.bool_)
    toast = np.zeros((cap_rows, n_cols), dtype=np.bool_)
    nulls[n:, :] = True  # padding rows are all-NULL

    chunks: list[bytes] = []
    pos = 0
    for i, tup in enumerate(tuples):
        if len(tup) != n_cols:
            raise EtlError(ErrorKind.SCHEMA_MISMATCH,
                           f"tuple {i} has {len(tup)} cols, expected {n_cols}")
        for j, (kind, val) in enumerate(zip(tup.kinds, tup.values)):
            if kind == TUPLE_NULL:
                nulls[i, j] = True
            elif kind == TUPLE_UNCHANGED_TOAST:
                toast[i, j] = True
            elif kind != TUPLE_TEXT:
                # binary tuple format is never requested in START_REPLICATION;
                # staging it as text would silently corrupt values
                raise EtlError(ErrorKind.UNSUPPORTED_TYPE,
                               f"tuple {i} col {j}: binary format not enabled")
            else:
                assert val is not None
                offsets[i, j] = pos
                lengths[i, j] = len(val)
                chunks.append(val)
                pos += len(val)
    data = np.frombuffer(b"".join(chunks), dtype=np.uint8) if chunks else \
        np.zeros(0, dtype=np.uint8)
    return StagedBatch(data, offsets, lengths, nulls, toast, n)


def synthetic_staged_batch(n_cols: int, row_capacity: int) -> StagedBatch:
    """An all-NULL staged batch at an exact row capacity: the program-
    store prewarm path decodes one through the engine's own dispatch
    stage so the warmed key, shapes, and dtypes can never drift from
    what production batches of that (schema, bucket) signature use."""
    return StagedBatch(
        np.zeros(0, dtype=np.uint8),
        np.zeros((row_capacity, n_cols), dtype=np.int32),
        np.zeros((row_capacity, n_cols), dtype=np.int32),
        np.ones((row_capacity, n_cols), dtype=np.bool_),
        np.zeros((row_capacity, n_cols), dtype=np.bool_),
        row_capacity)


_NULL_FIELD_BYTES = (92, 78)  # "\\N"


def stage_copy_chunk(chunk: bytes, n_cols: int) -> StagedBatch:
    """Stage a chunk of COPY text rows (newline-terminated) with a fully
    vectorized delimiter scan. Rows whose fields contain backslash escapes
    (other than a bare \\N null) are routed to `cpu_fallback_rows`."""
    if not chunk:
        return StagedBatch(np.zeros(0, np.uint8), np.zeros((0, n_cols), np.int32),
                           np.zeros((0, n_cols), np.int32),
                           np.zeros((0, n_cols), np.bool_),
                           np.zeros((0, n_cols), np.bool_), 0)
    if not chunk.endswith(b"\n"):
        chunk += b"\n"
    data = np.frombuffer(chunk, dtype=np.uint8)
    is_tab = data == 9
    is_nl = data == 10
    delim_pos = np.flatnonzero(is_tab | is_nl)
    nl_pos = np.flatnonzero(is_nl)
    n_rows = len(nl_pos)
    # each row must contribute exactly n_cols delimiters (C-1 tabs + 1 nl)
    if len(delim_pos) != n_rows * n_cols:
        raise EtlError(
            ErrorKind.COPY_FORMAT_INVALID,
            f"COPY chunk: {len(delim_pos)} delimiters for {n_rows} rows × "
            f"{n_cols} cols")
    ends = delim_pos.reshape(n_rows, n_cols)
    if not np.array_equal(ends[:, -1], nl_pos):
        raise EtlError(ErrorKind.COPY_FORMAT_INVALID,
                       "COPY chunk: ragged rows (tab/newline mismatch)")
    starts = np.empty_like(ends)
    starts[:, 0] = np.concatenate(([0], nl_pos[:-1] + 1))
    starts[:, 1:] = ends[:, :-1] + 1
    lengths = (ends - starts).astype(np.int32)
    offsets = starts.astype(np.int32)

    # NULL detection: field == b"\\N"
    first = data[np.minimum(starts, len(data) - 1)]
    second = data[np.minimum(starts + 1, len(data) - 1)]
    nulls = (lengths == 2) & (first == _NULL_FIELD_BYTES[0]) \
        & (second == _NULL_FIELD_BYTES[1])

    # escape detection per row: any backslash in the row span that is not
    # a \N (chunks with no backslash at all — the common case — skip the
    # cumsum, which costs ~5ms/MiB on the copy hot path)
    is_bs = data == 92
    if is_bs.any():
        bs_cum = np.concatenate(([0], np.cumsum(is_bs)))
        row_start = starts[:, 0]
        row_end = ends[:, -1]
        bs_in_row = bs_cum[row_end] - bs_cum[row_start]
        nulls_in_row = nulls.sum(axis=1)
        fallback = np.flatnonzero(bs_in_row != nulls_in_row)
    else:
        fallback = np.zeros(0, dtype=np.int64)

    cap_rows = bucket_rows(n_rows)
    if cap_rows != n_rows:
        pad = cap_rows - n_rows

        def padrc(a, fill=0):
            return np.concatenate([a, np.full((pad, n_cols), fill, a.dtype)])

        offsets = padrc(offsets)
        lengths = padrc(lengths)
        nulls = padrc(nulls, True)
    toast = np.zeros((cap_rows, n_cols), dtype=np.bool_)
    lengths = np.where(nulls, 0, lengths)
    return StagedBatch(data, offsets, lengths, nulls, toast, n_rows,
                       cpu_fallback_rows=fallback, copy_escapes=True)


# ---------------------------------------------------------------------------
# staging arenas: reusable pack buffers
# ---------------------------------------------------------------------------


class ArenaLease:
    """The set of pool buffers one in-flight decode holds. `take` hands
    out a pooled (or fresh) array; `release` returns every taken buffer to
    the pool at once — called by the pipeline's fetch stage after the
    device result lands, the earliest point reuse cannot race the
    host→device copy of the batch that packed into them."""

    __slots__ = ("_pool", "_taken", "_released")

    def __init__(self, pool: "StagingArenaPool"):
        self._pool = pool
        self._taken: list[np.ndarray] = []
        self._released = False

    def take(self, shape: tuple, dtype) -> np.ndarray:
        a = self._pool._take(shape, dtype)
        self._taken.append(a)
        return a

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._pool._give_back(self._taken)
        self._taken = []

    def __enter__(self) -> "ArenaLease":
        return self

    def __exit__(self, *exc) -> None:
        # context-manager form: `with pool.lease() as lease:` releases on
        # every path — the shape etl-lint's arena-lease-leak rule treats
        # as inherently safe
        self.release()


class StagingArenaPool:
    """Preallocated pack-buffer pool, bucketed by (shape, dtype).

    The pack stage writes the byte matrix + lengths (+ nibble bad flags)
    for every batch; with per-batch `np.empty` the allocator churns tens of
    MB per dispatch on the hot loop. Pack shapes are already coarse — row
    capacities are bucketed (ROW_BUCKETS) and gather widths are bucketed
    (bucket_width) — so a handful of arenas per (row_capacity, widths)
    signature covers a steady-state stream, and the bounded in-flight
    window (ops/pipeline.py) caps how many are ever out at once.

    The C packers overwrite every row up to capacity (zero-padding each
    field to its width — framer.c keeps device inputs deterministic), so a
    reused dirty buffer is safe without re-zeroing.
    """

    def __init__(self, max_per_bucket: int = 4):
        self.max_per_bucket = max_per_bucket
        self._lock = threading.Lock()
        self._free: dict[tuple, list[np.ndarray]] = {}
        # buffers handed out and not yet returned: the chaos subsystem's
        # arena-leak invariant reads this before/after a scenario run
        self.outstanding = 0

    def lease(self) -> ArenaLease:
        return ArenaLease(self)

    def _take(self, shape: tuple, dtype) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            bucket = self._free.get(key)
            arr = bucket.pop() if bucket else None
            self.outstanding += 1
        from ..telemetry.metrics import (ETL_STAGING_ARENA_REQUESTS_TOTAL,
                                         registry)

        registry.counter_inc(ETL_STAGING_ARENA_REQUESTS_TOTAL, 1.0,
                             {"result": "hit" if arr is not None else "miss"})
        return arr if arr is not None else np.empty(shape, dtype=dtype)

    def _give_back(self, arrays: list[np.ndarray]) -> None:
        with self._lock:
            self.outstanding -= len(arrays)
            for a in arrays:
                key = (a.shape, a.dtype.str)
                bucket = self._free.setdefault(key, [])
                if len(bucket) < self.max_per_bucket:
                    bucket.append(a)

    def stats(self) -> dict:
        with self._lock:
            return {"buckets": len(self._free),
                    "free_arrays": sum(len(v) for v in self._free.values()),
                    "free_bytes": sum(a.nbytes for v in self._free.values()
                                      for a in v),
                    "outstanding": self.outstanding}


#: process-wide pool shared by every decode pipeline (arenas are keyed by
#: exact shape, so cross-table sharing is free and the bound is global)
ARENA_POOL = StagingArenaPool()
