"""Three-stage pipelined decode scheduler.

Serial `decode_async` still runs `_pack_host` — the numpy/C gather — on
the dispatch path, so per batch the host pack, the device compute, and
the result fetch serialize and the accelerator idles between dispatches.
This module overlaps them:

    submit(decoder, staged)            consumer (in submit order)
        │                                      ▲
        ▼                                      │ fetch: _PendingDecode
    [ pack worker thread ]                     │ .result() — unpack,
    1. route (device/host/oracle)              │ combines, CPU fixup;
    2. acquire in-flight window slot           │ releases the arena and
    3. PACK into a pooled staging arena        │ the window slot
    4. DISPATCH the jitted program ────────────┘
       (device computes while the worker
        packs the NEXT batch)

  - pack — `DeviceDecoder._pack_stage` on a dedicated worker thread,
    writing into reusable preallocated arenas (staging.ARENA_POOL,
    bucketed by (row_capacity, widths) via exact buffer shape) instead of
    fresh np.empty per batch;
  - dispatch — `DeviceDecoder._dispatch_stage`; the jitted program is
    built with donate_argnums on the packed buffers (TPU/GPU) so XLA
    reuses device memory across batches;
  - fetch — `_PendingDecode.result()` completion, driven by the caller
    in submit order and bounded by an in-flight window
    (runtime/backpressure.InFlightWindow, default 3; shrinks to 1 under
    memory pressure) so host arenas + device buffers stay capped.

One worker thread per pipeline keeps dispatch order == submit order, so
call sites (runtime/copy.py per copy partition, runtime/assembler.py per
apply loop) drain completions strictly in order with no cross-stream
deadlock: the oldest submitted batch is always packed/dispatched before
any younger batch can hold a window slot.

Telemetry: per-stage histograms (pack/dispatch/fetch seconds), the
overlap counters (seconds of pack time concurrent with another batch in
flight — the pipelining win itself), and arena reuse hits
(telemetry/metrics.py).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import TYPE_CHECKING

from ..analysis.annotations import admission_path, hot_loop
from .staging import ARENA_POOL, StagedBatch, StagingArenaPool

if TYPE_CHECKING:  # import cycle: runtime -> ops at module import time
    from ..runtime.backpressure import MemoryMonitor
    from .engine import DeviceDecoder

#: default bounded in-flight window: 3 batches ≈ one packing, one on the
#: device, one streaming back — deeper windows only add memory (the
#: device serializes program executions anyway)
DEFAULT_WINDOW = 3


# ---------------------------------------------------------------------------
# fair batch admission: N pipelines sharing one device set / mesh
# ---------------------------------------------------------------------------


class TenantAdmission:
    """One tenant's (pipeline's) handle on a shared AdmissionScheduler.

    Exactly ONE thread — the owning pipeline's pack/dispatch worker —
    calls `acquire`; `release` may come from whichever thread drains the
    fetch. `close` releases every ticket the tenant still holds and
    deregisters it: a crashed/abandoned pipeline can never strand shared
    device capacity behind handles nobody will drain."""

    __slots__ = ("_sched", "name", "_lag_bytes", "_monitor", "_pass",
                 "_held", "_grants", "_wait_since", "_closed")

    def __init__(self, sched: "AdmissionScheduler", name: str,
                 lag_bytes, monitor):
        self._sched = sched
        self.name = name
        self._lag_bytes = lag_bytes  # () -> lag in bytes, or None
        self._monitor = monitor  # MemoryMonitor | None
        self._pass = 0.0  # stride-scheduling virtual time
        self._held = 0
        self._grants = 0
        self._wait_since: float | None = None
        self._closed = False

    @property
    def held(self) -> int:
        return self._held

    @property
    def closed(self) -> bool:
        return self._closed

    def acquire(self, bypass=None) -> None:
        self._sched._acquire(self, bypass)

    def release(self) -> None:
        self._sched._release(self)

    def close(self) -> None:
        self._sched._close_tenant(self)


class AdmissionScheduler:
    """Fair batch admission across N decode pipelines sharing one device
    set (single chip or an 'sp' mesh): at most `capacity` device/host
    batches are in flight across ALL tenants, and when tenants contend
    the grant order is weighted stride scheduling.

      weight   = 1 + lag_bytes / lag_scale_bytes (clamped to max_weight):
                 a tenant whose replication stream is behind (the
                 SlotLagMetrics / apply-loop flush-lag shape) gets
                 proportionally more batch admissions, so one device set
                 drains the laggard first instead of round-robining;
      stride   = 1 / weight; on every grant the tenant's virtual pass
                 advances by its stride and the scheduler picks the
                 waiter with the minimum pass — proportional share with
                 no tenant ever starved (a weight-1 tenant still lands
                 every max_weight'th grant);
      aging    = a waiter past `starvation_s` is granted next regardless
                 of pass (counted as a starvation grant): even a
                 pathological lag provider (stuck at +∞ for one tenant)
                 cannot lock another tenant out for longer than the
                 deadline;
      idle cap = a tenant's pass is floored to the global virtual time
                 when it starts waiting, so a long-idle tenant gets its
                 fair share going forward, not an unbounded burst of
                 back-credit.

    Memory pressure rides the existing machinery: when ANY registered
    tenant's MemoryMonitor reports pressure the effective capacity drops
    to 1 (the same stance as InFlightWindow — RSS is process-level, so
    one pressured monitor throttles every tenant). The `bypass` valve
    mirrors InFlightWindow.acquire's: when the caller's consumer is
    blocked on a batch that cannot dispatch until this acquire returns,
    the scheduler overshoots capacity instead of deadlocking.

    Purely passive (a Condition, no threads of its own): shutdown cannot
    leak tasks — the chaos leak probe asserts in_flight and waiters
    return to zero once the sharing pipelines close."""

    _POLL_S = 0.05
    STRIDE = 1.0

    def __init__(self, capacity: int, *,
                 lag_scale_bytes: float = 64 * 1024 * 1024,
                 max_weight: float = 32.0,
                 starvation_s: float = 0.5):
        if capacity < 1:
            raise ValueError("admission capacity must be >= 1")
        self.capacity = capacity
        self._lag_scale = max(1.0, float(lag_scale_bytes))
        self._max_weight = max(1.0, float(max_weight))
        self._starvation_s = starvation_s
        self._cond = threading.Condition()
        self._tenants: list[TenantAdmission] = []
        self._held_total = 0
        self._vt = 0.0  # global virtual time (max pass ever granted)
        # per-tenant SLO weight inputs (etl_tpu/autoscale feeds these):
        # a static business-priority multiplier composed WITH the dynamic
        # lag weight — lag says who is behind right now, the SLO says
        # whose backlog costs more per second. Keys match tenant names
        # exactly or as a prefix ("cdc" covers "cdc-0", "cdc-1", …).
        self._slo_weights: dict[str, float] = {}

    def set_slo_weight(self, tenant: str, weight: float) -> None:
        """Install (or update) one tenant's SLO weight. `tenant` is an
        exact tenant name or a prefix; `weight` is clamped to
        [1/max_weight, max_weight] so one tenant can neither zero itself
        out nor starve the fleet past the aging valve's reach."""
        lo = 1.0 / self._max_weight
        with self._cond:
            self._slo_weights[tenant] = min(max(float(weight), lo),
                                            self._max_weight)
            self._cond.notify_all()

    @admission_path
    def _slo_for(self, name: str) -> float:
        """Exact-name match wins; otherwise the LONGEST prefix match
        (tenant names carry per-stream suffixes the operator's config
        cannot know: "cdc-3", "copy-16384-2"). Caller holds the lock or
        tolerates a stale read — weights only drift, never tear."""
        w = self._slo_weights.get(name)
        if w is not None:
            return w
        best_len = -1
        best = 1.0
        for prefix, weight in self._slo_weights.items():
            if name.startswith(prefix) and len(prefix) > best_len:
                best_len = len(prefix)
                best = weight
        return best

    def register(self, name: str, lag_bytes=None,
                 monitor: "MemoryMonitor | None" = None) -> TenantAdmission:
        """New tenant. `lag_bytes` is read at every grant decision — pass
        the live replication-lag reader (e.g. the apply loop's
        received−durable delta), not a snapshot."""
        t = TenantAdmission(self, name, lag_bytes, monitor)
        with self._cond:
            self._tenants.append(t)
            n_tenants = len(self._tenants)
        from ..telemetry.metrics import ETL_DECODE_ADMISSION_TENANTS, registry

        registry.gauge_set(ETL_DECODE_ADMISSION_TENANTS, n_tenants)
        return t

    @property
    def effective_capacity(self) -> int:
        if any(t._monitor is not None and t._monitor.pressure
               for t in self._tenants):
            return 1
        return self.capacity

    @property
    def in_flight(self) -> int:
        return self._held_total

    @property
    def waiters(self) -> int:
        with self._cond:
            return sum(1 for t in self._tenants
                       if t._wait_since is not None)

    @admission_path
    def _weight(self, tenant: TenantAdmission) -> float:
        slo = self._slo_for(tenant.name)
        if tenant._lag_bytes is None:
            return max(slo, 1.0 / self._max_weight)
        try:
            lag = max(0.0, float(tenant._lag_bytes()))
        except Exception:  # a dying lag reader must not kill admission
            lag = 0.0
        return min(max(slo * (1.0 + lag / self._lag_scale),
                       1.0 / self._max_weight), self._max_weight)

    @admission_path
    def _pick(self, now: float) -> "tuple[TenantAdmission, bool] | None":
        """Next waiter to admit: aged-out waiter (FIFO among starved)
        first, else minimum virtual pass. Caller holds the lock."""
        waiters = [t for t in self._tenants if t._wait_since is not None]
        if not waiters:
            return None
        starved = [t for t in waiters
                   if now - t._wait_since >= self._starvation_s]
        if starved:
            return min(starved, key=lambda t: t._wait_since), True
        return min(waiters, key=lambda t: t._pass), False

    @admission_path
    def _acquire(self, tenant: TenantAdmission, bypass=None) -> None:
        from ..telemetry.metrics import (
            ETL_DECODE_ADMISSION_BYPASS_GRANTS_TOTAL,
            ETL_DECODE_ADMISSION_GRANTS_TOTAL,
            ETL_DECODE_ADMISSION_IN_FLIGHT,
            ETL_DECODE_ADMISSION_STARVATION_GRANTS_TOTAL,
            ETL_DECODE_ADMISSION_WAIT_SECONDS, ETL_DECODE_ADMISSION_WAITERS,
            registry)

        t0 = time.perf_counter()
        starved_grant = False
        bypass_grant = False
        granted = False
        with self._cond:
            if tenant._closed:
                raise RuntimeError(
                    f"admission tenant {tenant.name!r} is closed")
            tenant._wait_since = time.monotonic()
            # idle cap: fair share from NOW, no banked burst credit
            tenant._pass = max(tenant._pass, self._vt)
            registry.gauge_set(
                ETL_DECODE_ADMISSION_WAITERS,
                sum(1 for t in self._tenants if t._wait_since is not None))
            try:
                while True:
                    if bypass is not None and bypass():
                        bypass_grant = True
                        break
                    if tenant._closed:
                        raise RuntimeError(
                            f"admission tenant {tenant.name!r} closed "
                            f"while waiting")
                    if self._held_total < self.effective_capacity:
                        picked = self._pick(time.monotonic())
                        if picked is not None and picked[0] is tenant:
                            starved_grant = picked[1]
                            break
                    # poll tick: pressure transitions, lag drift, and the
                    # bypass predicate are all re-read without signalling
                    self._cond.wait(timeout=self._POLL_S)
            finally:
                tenant._wait_since = None
                # this waiter is done (granted, closed, or raising) —
                # re-derive the gauge from live state so it can't stick
                # at a stale count
                registry.gauge_set(
                    ETL_DECODE_ADMISSION_WAITERS,
                    sum(1 for t in self._tenants
                        if t._wait_since is not None))
            if not tenant._closed:
                self._vt = max(self._vt, tenant._pass)
                tenant._pass += self.STRIDE / self._weight(tenant)
                tenant._held += 1
                tenant._grants += 1
                self._held_total += 1
                granted = True
            held_total = self._held_total
            # a freed-then-granted slot may leave capacity for the next
            # waiter; wake the others to re-pick
            self._cond.notify_all()
        # grant telemetry only for REAL grants: a tenant closed during
        # the wait wakes without a ticket, and counting it would skew
        # the per-tenant fairness evidence the bench reports
        if granted:
            labels = {"pipeline": tenant.name}
            registry.counter_inc(ETL_DECODE_ADMISSION_GRANTS_TOTAL,
                                 labels=labels)
            if starved_grant:
                registry.counter_inc(
                    ETL_DECODE_ADMISSION_STARVATION_GRANTS_TOTAL,
                    labels=labels)
            if bypass_grant:
                registry.counter_inc(
                    ETL_DECODE_ADMISSION_BYPASS_GRANTS_TOTAL, labels=labels)
            registry.histogram_observe(ETL_DECODE_ADMISSION_WAIT_SECONDS,
                                       time.perf_counter() - t0, labels)
        registry.gauge_set(ETL_DECODE_ADMISSION_IN_FLIGHT, held_total)

    @admission_path
    def _release(self, tenant: TenantAdmission) -> None:
        from ..telemetry.metrics import (ETL_DECODE_ADMISSION_IN_FLIGHT,
                                         registry)

        with self._cond:
            if tenant._held <= 0:
                return  # ticket already reclaimed by close()
            tenant._held -= 1
            self._held_total = max(0, self._held_total - 1)
            held_total = self._held_total
            self._cond.notify_all()
        registry.gauge_set(ETL_DECODE_ADMISSION_IN_FLIGHT, held_total)

    @admission_path
    def _close_tenant(self, tenant: TenantAdmission) -> None:
        with self._cond:
            if tenant._closed:
                return
            tenant._closed = True
            self._held_total = max(0, self._held_total - tenant._held)
            tenant._held = 0
            if tenant in self._tenants:
                self._tenants.remove(tenant)
            n_tenants = len(self._tenants)
            held_total = self._held_total
            self._cond.notify_all()
        from ..telemetry.metrics import (ETL_DECODE_ADMISSION_IN_FLIGHT,
                                         ETL_DECODE_ADMISSION_TENANTS,
                                         registry)

        registry.gauge_set(ETL_DECODE_ADMISSION_TENANTS, n_tenants)
        registry.gauge_set(ETL_DECODE_ADMISSION_IN_FLIGHT, held_total)

    def stats(self) -> dict:
        with self._cond:
            return {
                "capacity": self.capacity,
                "effective_capacity": self.effective_capacity,
                "in_flight": self._held_total,
                "waiters": sum(1 for t in self._tenants
                               if t._wait_since is not None),
                "tenants": {t.name: {"held": t._held, "grants": t._grants,
                                     "weight": round(self._weight(t), 3)}
                            for t in self._tenants},
            }


_GLOBAL_ADMISSION: "AdmissionScheduler | None" = None
_GLOBAL_ADMISSION_LOCK = threading.Lock()


def reset_global_admission() -> None:
    """Drop the process-wide scheduler so the NEXT global_admission()
    caller fixes a fresh capacity (bench harness / test isolation). Only
    safe with no production pipelines running: live tenants keep their
    seats on the old scheduler object until they close, so a reset under
    traffic splits capacity accounting across two schedulers."""
    global _GLOBAL_ADMISSION
    with _GLOBAL_ADMISSION_LOCK:
        _GLOBAL_ADMISSION = None


def global_admission(capacity: int | None = None) -> AdmissionScheduler:
    """The process-wide scheduler every production decode pipeline
    registers with — one device set serving many replication streams
    (apply loops, table-sync catchups, copy partitions). The FIRST caller
    fixes the capacity; `None` defaults to max(4, 2 × device count) — two
    in-flight batches per device keeps the mesh fed while one batch
    streams back, and the floor keeps single-device hosts pipelined.
    Uncontended tenants are never throttled below their own in-flight
    window, so a lone pipeline behaves exactly as before."""
    global _GLOBAL_ADMISSION
    with _GLOBAL_ADMISSION_LOCK:
        if _GLOBAL_ADMISSION is None:
            if capacity is None or capacity <= 0:
                try:
                    import jax

                    n_dev = max(1, len(jax.devices()))
                except Exception:
                    n_dev = 1
                capacity = max(4, 2 * n_dev)
            _GLOBAL_ADMISSION = AdmissionScheduler(capacity)
        return _GLOBAL_ADMISSION


class _Interval:
    """[start, end) of one batch's in-flight (dispatch→fetch) span;
    end None while still in flight."""

    __slots__ = ("start", "end")

    def __init__(self, start: float):
        self.start = start
        self.end: float | None = None


class PipelinedDecode:
    """Handle for one submitted batch; duck-compatible with
    `_PendingDecode` (`.result()`), so DecodedBatchEvent and destination
    writers consume it unchanged. `result()` may be called out of submit
    order — completion state is per-handle — but in-order draining is
    what keeps the window from stalling the worker."""

    __slots__ = ("_pipe", "_future", "_done", "_exc", "_windowed",
                 "_demanded", "_admitted")

    def __init__(self, pipe: "DecodePipeline"):
        self._pipe = pipe
        self._future: Future = Future()
        self._done = None
        self._exc: BaseException | None = None
        self._windowed = False  # device/host route holds a window slot
        self._demanded = False  # a consumer is blocked on this handle
        self._admitted = False  # holds a shared admission ticket

    def abandon(self) -> None:
        """Discard a handle that will never be consumed (a hard-killed
        apply loop's flushed-but-undelivered window entries): return the
        pooled resources — staging arena, window slot, admission ticket —
        without paying the fetch. Completed handles already returned
        them in `_fetch`; a handle still packing releases via a
        done-callback the moment the worker resolves it; a handle whose
        worker errored released in the worker's except path. After
        abandon, `result()` is forbidden (the arena may be re-leased and
        dirtied by another batch) — consumers of an abandoned handle are
        gone by construction."""
        if self._done is not None or self._exc is not None:
            return  # fetched (or failed): resources already returned
        self._exc = RuntimeError("decode handle abandoned")

        def _release(fut) -> None:
            if fut.exception() is not None:
                return  # worker error path released window/admission
            value = fut.result()
            if len(value) == 2:
                return  # oracle route: no pooled resources held
            _pending, arena, iv = value
            pipe = self._pipe
            with pipe._lock:
                iv.end = time.perf_counter()
                if iv in pipe._inflight:
                    pipe._inflight.remove(iv)
            arena.release()
            if self._admitted:
                self._admitted = False
                pipe._admission.release()
            if self._windowed:
                self._windowed = False
                pipe.window.release()

        # runs immediately if already resolved, else on the worker
        # thread when pack/dispatch completes — either way exactly once
        self._future.add_done_callback(_release)

    def result(self):
        """Complete the batch (idempotent). A failed fetch is permanent:
        the first attempt already returned the arena to the pool, so a
        retry could read buffers another batch has dirtied — re-raise the
        recorded failure instead of re-completing."""
        if self._done is None:
            if self._exc is not None:
                raise self._exc
            try:
                self._done = self._pipe._fetch(self)
            except BaseException as e:
                self._exc = e
                raise
        return self._done


class DecodePipeline:
    """The scheduler: one pack/dispatch worker thread + a bounded
    in-flight window + stage telemetry. Decoder-agnostic per submit, so
    one pipeline serves every table of an apply loop."""

    def __init__(self, *, window: int = DEFAULT_WINDOW,
                 monitor: "MemoryMonitor | None" = None,
                 arena_pool: StagingArenaPool | None = None,
                 name: str = "decode", heartbeat=None,
                 admission: "TenantAdmission | None" = None):
        from ..runtime.backpressure import InFlightWindow

        # supervision.Heartbeat | None: the worker thread publishes
        # liveness + a completed-batch progress token; a frozen token
        # with batches in flight is a device-side stall the supervisor
        # escalates (host-oracle degrade)
        self._hb = heartbeat
        # TenantAdmission | None: this pipeline's seat at the shared
        # AdmissionScheduler. Ownership transfers here — close() closes
        # it, releasing any tickets still held by undrained handles
        self._admission = admission
        self.window = InFlightWindow(max(1, window), monitor)
        self.pool = arena_pool if arena_pool is not None else ARENA_POOL
        # gauge label: several pipelines coexist (one per copy partition
        # + the apply loop's); unlabeled globals would last-writer-win
        self._name = name
        self._jobs: "queue.SimpleQueue" = queue.SimpleQueue()
        self._closed = False
        self._lock = threading.Lock()  # interval list + overlap counters
        self._inflight: list[_Interval] = []
        # handles submitted but not yet dispatched: the window's liveness
        # valve — a consumer blocked on one of these means the worker must
        # overshoot the window instead of deadlocking against it
        self._undispatched: list[PipelinedDecode] = []
        self._pack_seconds = 0.0
        self._overlap_seconds = 0.0
        self._published_pack = 0.0
        self._published_overlap = 0.0
        self._submitted = 0
        self._completed = 0
        self._worker = threading.Thread(
            target=self._run, name=f"etl-{name}-pipeline", daemon=True)
        self._worker.start()

    # -- producer side ------------------------------------------------------

    def submit(self, decoder: "DeviceDecoder",
               staged: StagedBatch) -> PipelinedDecode:
        """Schedule route→pack→dispatch on the worker; returns at once.
        The worker blocks on the in-flight window, not the caller — the
        submit queue itself is unbounded, bounded in practice by the
        caller's own batching (flush windows / COPY chunk thresholds)."""
        if self._closed:
            raise RuntimeError("decode pipeline is closed")
        handle = PipelinedDecode(self)
        self._submitted += 1
        with self._lock:
            self._undispatched.append(handle)
        self._jobs.put((decoder, staged, handle))
        return handle

    def _demand_waiting(self) -> bool:
        with self._lock:
            return any(h._demanded for h in self._undispatched)

    @property
    def in_flight(self) -> int:
        return len(self.window)

    @property
    def effective_window(self) -> int:
        return self.window.effective_limit

    def close(self) -> None:
        """Stop the worker. Handles already packed/dispatched stay
        resolvable; jobs still queued fail fast with RuntimeError (their
        events are re-streamed on resume — at-least-once). Close also
        opens the window's bypass so a worker blocked on slots held by
        abandoned handles (a failed copy partition that will never drain
        them) runs the queue down and exits instead of leaking the
        thread and everything queued behind it."""
        if not self._closed:
            self._closed = True
            self._jobs.put(None)
        if self._admission is not None:
            # deregister from the shared scheduler and reclaim any
            # tickets still held by undrained handles: an abandoned
            # pipeline must not strand shared device capacity. Handles
            # still resolvable after close release into the closed
            # tenant, which is a guarded no-op.
            self._admission.close()
        if self._hb is not None:
            self._hb.close()
            self._hb = None

    # -- worker side --------------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._jobs.get()
            if item is None:
                return
            decoder, staged, handle = item
            try:
                if self._closed:
                    raise RuntimeError(
                        "decode pipeline closed before this batch packed")
                self._process(decoder, staged, handle)
            # worker THREAD, not a coroutine: no asyncio cancellation can
            # land here; every failure must reach the consumer's result().
            # Not a retry spin either: the loop blocks on _jobs.get(), so
            # a failing batch is reported once, not hammered
            except BaseException as e:  # etl-lint: ignore[cancellation-swallow,unbounded-retry]
                if handle._admitted:
                    handle._admitted = False
                    self._admission.release()
                if handle._windowed:
                    handle._windowed = False
                    self.window.release()
                handle._future.set_exception(e)
            finally:
                with self._lock:
                    if handle in self._undispatched:
                        self._undispatched.remove(handle)
                hb = self._hb
                if hb is not None:
                    # busy while batches are in flight: a frozen
                    # completed-count past the stall deadline then reads
                    # as a device-side stall
                    hb.beat(progress=("completed", self._completed),
                            busy=len(self.window) > 0)

    @hot_loop
    def _process(self, decoder: "DeviceDecoder", staged: StagedBatch,
                 handle: PipelinedDecode) -> None:
        """Pack + dispatch one batch on the worker thread. @hot_loop: runs
        once per batch on the dispatch path — fetches belong to _fetch."""
        from ..chaos import failpoints
        from ..models.errors import ErrorKind, EtlError
        from ..telemetry.metrics import (
            ETL_DECODE_DEVICE_OOM_FALLBACKS_TOTAL,
            ETL_DECODE_DISPATCH_SECONDS, ETL_DECODE_PACK_SECONDS,
            ETL_DECODE_PIPELINE_IN_FLIGHT, registry)
        from .engine import _PendingDecode

        # chaos site: fires once per submitted batch at pack-stage entry
        # (before routing, so small oracle-routed batches hit it too)
        failpoints.fail_point(failpoints.PIPELINE_PACK)
        mode, specs = decoder._route(staged)
        if mode != "oracle":
            # simulated (or, one day, real) device allocation failure:
            # degrade THIS batch to the host oracle instead of failing
            # the stream — availability beats the device-decode win
            try:
                failpoints.fail_point(failpoints.ENGINE_DEVICE_OOM)
            except EtlError as e:
                if not set(e.kinds()) & {ErrorKind.DEVICE_UNAVAILABLE,
                                         ErrorKind.MEMORY_PRESSURE_ABORT}:
                    raise
                registry.counter_inc(ETL_DECODE_DEVICE_OOM_FALLBACKS_TOTAL)
                mode, specs = "oracle", ()
        if mode == "oracle":
            # no device work: nothing to overlap, no window slot — the
            # consumer's result() runs the per-row oracle as before
            handle._future.set_result(
                (_PendingDecode(decoder, staged, (), None, None), None))
            return
        # window slot held from here until the fetch completes: caps the
        # arenas + device buffers of all in-flight batches. The bypass
        # keeps the pipeline live when a consumer blocks on a handle that
        # hasn't dispatched yet (out-of-order draining) or when close()
        # fires with abandoned slots outstanding: the window overshoots
        # instead of deadlocking against its own consumer.
        self.window.acquire(
            bypass=lambda: self._closed or self._demand_waiting())
        handle._windowed = True
        if self._admission is not None and not self._admission.closed:
            # shared-capacity seat AFTER the pipeline's own window: a
            # tenant blocked on its self-imposed window must not sit on a
            # ticket other tenants could use. Same liveness valve as the
            # window — a demanded-but-undispatched handle (or close)
            # overshoots rather than deadlocking the consumer.
            self._admission.acquire(
                bypass=lambda: self._closed or self._demand_waiting())
            handle._admitted = True
        host = mode == "host"
        arena = self.pool.lease()
        t0 = time.perf_counter()
        try:
            packed = decoder._pack_stage(staged, specs, host, arena=arena)
            t1 = time.perf_counter()
            failpoints.fail_point(failpoints.PIPELINE_DISPATCH)
            packed_dev = decoder._dispatch_stage(staged, specs, packed, host)
            t2 = time.perf_counter()
        except BaseException:
            arena.release()
            raise
        pending = _PendingDecode(decoder, staged, specs, packed_dev,
                                 packed)
        iv = _Interval(t2)
        with self._lock:
            self._inflight.append(iv)
            # overlap: the part of THIS pack that ran while another batch
            # was between dispatch and fetch — nonzero means the host
            # packed batch N+1 while the device computed batch N
            overlap = 0.0
            for other in self._inflight:
                if other is iv:
                    continue
                end = other.end if other.end is not None else t1
                overlap += max(0.0, min(t1, end) - max(t0, other.start))
            self._pack_seconds += t1 - t0
            self._overlap_seconds += min(overlap, t1 - t0)
            pack_total = self._pack_seconds
            overlap_total = self._overlap_seconds
        registry.histogram_observe(ETL_DECODE_PACK_SECONDS, t1 - t0)
        registry.histogram_observe(ETL_DECODE_DISPATCH_SECONDS, t2 - t1)
        registry.gauge_set(ETL_DECODE_PIPELINE_IN_FLIGHT, len(self.window),
                           {"pipeline": self._name})
        self._publish_overlap(pack_total, overlap_total)
        handle._future.set_result((pending, arena, iv))

    def _publish_overlap(self, pack_total: float,
                         overlap_total: float) -> None:
        from ..telemetry.metrics import (
            ETL_DECODE_PIPELINE_OVERLAP_RATIO,
            ETL_DECODE_PIPELINE_OVERLAP_SECONDS_TOTAL,
            ETL_DECODE_PIPELINE_PACK_SECONDS_TOTAL, registry)

        # counters are registry-global (monotonic across pipelines):
        # publish the delta since this pipeline's last publication (only
        # the worker thread calls this, so the delta math is race-free)
        registry.counter_inc(ETL_DECODE_PIPELINE_PACK_SECONDS_TOTAL,
                             pack_total - self._published_pack)
        registry.counter_inc(ETL_DECODE_PIPELINE_OVERLAP_SECONDS_TOTAL,
                             overlap_total - self._published_overlap)
        self._published_pack = pack_total
        self._published_overlap = overlap_total
        if pack_total > 0:
            registry.gauge_set(ETL_DECODE_PIPELINE_OVERLAP_RATIO,
                               overlap_total / pack_total,
                               {"pipeline": self._name})

    # -- consumer side ------------------------------------------------------

    def _fetch(self, handle: PipelinedDecode):
        """Stage 3: wait out pack/dispatch if still running, fetch and
        complete the batch, then return the arena and window slot."""
        from ..chaos import failpoints
        from ..telemetry.metrics import (ETL_DECODE_FETCH_SECONDS,
                                         ETL_DECODE_PIPELINE_IN_FLIGHT,
                                         registry)

        handle._demanded = True  # window liveness valve, see _process
        value = handle._future.result()
        handle._demanded = False
        if len(value) == 2:  # oracle route: (pending, None)
            pending, _ = value
            t0 = time.perf_counter()
            try:
                failpoints.fail_point(failpoints.PIPELINE_FETCH)
                return pending.result()
            finally:
                with self._lock:
                    self._completed += 1
                registry.histogram_observe(ETL_DECODE_FETCH_SECONDS,
                                           time.perf_counter() - t0)
        pending, arena, iv = value
        t0 = time.perf_counter()
        try:
            failpoints.fail_point(failpoints.PIPELINE_FETCH)
            batch = pending.result()
        finally:
            now = time.perf_counter()
            with self._lock:
                iv.end = now
                if iv in self._inflight:
                    self._inflight.remove(iv)
                self._completed += 1
            hb = self._hb
            if hb is not None:
                hb.beat(progress=("completed", self._completed),
                        busy=len(self.window) > 1)
            arena.release()
            if handle._admitted:
                handle._admitted = False
                self._admission.release()
            if handle._windowed:
                handle._windowed = False
                self.window.release()
            registry.gauge_set(ETL_DECODE_PIPELINE_IN_FLIGHT,
                               len(self.window), {"pipeline": self._name})
        registry.histogram_observe(ETL_DECODE_FETCH_SECONDS, now - t0)
        return batch

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            pack = self._pack_seconds
            overlap = self._overlap_seconds
            out = {
                "submitted": self._submitted,
                "completed": self._completed,
                "in_flight": len(self.window),
                "window": self.window.limit,
                "pack_seconds_total": pack,
                "overlap_seconds_total": overlap,
                "overlap_ratio": overlap / pack if pack > 0 else 0.0,
                "arena": self.pool.stats(),
            }
        if self._admission is not None:
            out["admission"] = {"tenant": self._admission.name,
                                "held": self._admission.held,
                                "closed": self._admission.closed}
        return out
