"""Three-stage pipelined decode scheduler.

Serial `decode_async` still runs `_pack_host` — the numpy/C gather — on
the dispatch path, so per batch the host pack, the device compute, and
the result fetch serialize and the accelerator idles between dispatches.
This module overlaps them:

    submit(decoder, staged)            consumer (in submit order)
        │                                      ▲
        ▼                                      │ fetch: _PendingDecode
    [ pack worker thread ]                     │ .result() — unpack,
    1. route (device/host/oracle)              │ combines, CPU fixup;
    2. acquire in-flight window slot           │ releases the arena and
    3. PACK into a pooled staging arena        │ the window slot
    4. DISPATCH the jitted program ────────────┘
       (device computes while the worker
        packs the NEXT batch)

  - pack — `DeviceDecoder._pack_stage` on a dedicated worker thread,
    writing into reusable preallocated arenas (staging.ARENA_POOL,
    bucketed by (row_capacity, widths) via exact buffer shape) instead of
    fresh np.empty per batch;
  - dispatch — `DeviceDecoder._dispatch_stage`; the jitted program is
    built with donate_argnums on the packed buffers (TPU/GPU) so XLA
    reuses device memory across batches;
  - fetch — `_PendingDecode.result()` completion, driven by the caller
    in submit order and bounded by an in-flight window
    (runtime/backpressure.InFlightWindow, default 3; shrinks to 1 under
    memory pressure) so host arenas + device buffers stay capped.

One worker thread per pipeline keeps dispatch order == submit order, so
call sites (runtime/copy.py per copy partition, runtime/assembler.py per
apply loop) drain completions strictly in order with no cross-stream
deadlock: the oldest submitted batch is always packed/dispatched before
any younger batch can hold a window slot.

Telemetry: per-stage histograms (pack/dispatch/fetch seconds), the
overlap counters (seconds of pack time concurrent with another batch in
flight — the pipelining win itself), and arena reuse hits
(telemetry/metrics.py).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import TYPE_CHECKING

from ..analysis.annotations import hot_loop
from .staging import ARENA_POOL, StagedBatch, StagingArenaPool

if TYPE_CHECKING:  # import cycle: runtime -> ops at module import time
    from ..runtime.backpressure import MemoryMonitor
    from .engine import DeviceDecoder

#: default bounded in-flight window: 3 batches ≈ one packing, one on the
#: device, one streaming back — deeper windows only add memory (the
#: device serializes program executions anyway)
DEFAULT_WINDOW = 3


class _Interval:
    """[start, end) of one batch's in-flight (dispatch→fetch) span;
    end None while still in flight."""

    __slots__ = ("start", "end")

    def __init__(self, start: float):
        self.start = start
        self.end: float | None = None


class PipelinedDecode:
    """Handle for one submitted batch; duck-compatible with
    `_PendingDecode` (`.result()`), so DecodedBatchEvent and destination
    writers consume it unchanged. `result()` may be called out of submit
    order — completion state is per-handle — but in-order draining is
    what keeps the window from stalling the worker."""

    __slots__ = ("_pipe", "_future", "_done", "_exc", "_windowed",
                 "_demanded")

    def __init__(self, pipe: "DecodePipeline"):
        self._pipe = pipe
        self._future: Future = Future()
        self._done = None
        self._exc: BaseException | None = None
        self._windowed = False  # device/host route holds a window slot
        self._demanded = False  # a consumer is blocked on this handle

    def result(self):
        """Complete the batch (idempotent). A failed fetch is permanent:
        the first attempt already returned the arena to the pool, so a
        retry could read buffers another batch has dirtied — re-raise the
        recorded failure instead of re-completing."""
        if self._done is None:
            if self._exc is not None:
                raise self._exc
            try:
                self._done = self._pipe._fetch(self)
            except BaseException as e:
                self._exc = e
                raise
        return self._done


class DecodePipeline:
    """The scheduler: one pack/dispatch worker thread + a bounded
    in-flight window + stage telemetry. Decoder-agnostic per submit, so
    one pipeline serves every table of an apply loop."""

    def __init__(self, *, window: int = DEFAULT_WINDOW,
                 monitor: "MemoryMonitor | None" = None,
                 arena_pool: StagingArenaPool | None = None,
                 name: str = "decode", heartbeat=None):
        from ..runtime.backpressure import InFlightWindow

        # supervision.Heartbeat | None: the worker thread publishes
        # liveness + a completed-batch progress token; a frozen token
        # with batches in flight is a device-side stall the supervisor
        # escalates (host-oracle degrade)
        self._hb = heartbeat
        self.window = InFlightWindow(max(1, window), monitor)
        self.pool = arena_pool if arena_pool is not None else ARENA_POOL
        # gauge label: several pipelines coexist (one per copy partition
        # + the apply loop's); unlabeled globals would last-writer-win
        self._name = name
        self._jobs: "queue.SimpleQueue" = queue.SimpleQueue()
        self._closed = False
        self._lock = threading.Lock()  # interval list + overlap counters
        self._inflight: list[_Interval] = []
        # handles submitted but not yet dispatched: the window's liveness
        # valve — a consumer blocked on one of these means the worker must
        # overshoot the window instead of deadlocking against it
        self._undispatched: list[PipelinedDecode] = []
        self._pack_seconds = 0.0
        self._overlap_seconds = 0.0
        self._published_pack = 0.0
        self._published_overlap = 0.0
        self._submitted = 0
        self._completed = 0
        self._worker = threading.Thread(
            target=self._run, name=f"etl-{name}-pipeline", daemon=True)
        self._worker.start()

    # -- producer side ------------------------------------------------------

    def submit(self, decoder: "DeviceDecoder",
               staged: StagedBatch) -> PipelinedDecode:
        """Schedule route→pack→dispatch on the worker; returns at once.
        The worker blocks on the in-flight window, not the caller — the
        submit queue itself is unbounded, bounded in practice by the
        caller's own batching (flush windows / COPY chunk thresholds)."""
        if self._closed:
            raise RuntimeError("decode pipeline is closed")
        handle = PipelinedDecode(self)
        self._submitted += 1
        with self._lock:
            self._undispatched.append(handle)
        self._jobs.put((decoder, staged, handle))
        return handle

    def _demand_waiting(self) -> bool:
        with self._lock:
            return any(h._demanded for h in self._undispatched)

    @property
    def in_flight(self) -> int:
        return len(self.window)

    @property
    def effective_window(self) -> int:
        return self.window.effective_limit

    def close(self) -> None:
        """Stop the worker. Handles already packed/dispatched stay
        resolvable; jobs still queued fail fast with RuntimeError (their
        events are re-streamed on resume — at-least-once). Close also
        opens the window's bypass so a worker blocked on slots held by
        abandoned handles (a failed copy partition that will never drain
        them) runs the queue down and exits instead of leaking the
        thread and everything queued behind it."""
        if not self._closed:
            self._closed = True
            self._jobs.put(None)
        if self._hb is not None:
            self._hb.close()
            self._hb = None

    # -- worker side --------------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._jobs.get()
            if item is None:
                return
            decoder, staged, handle = item
            try:
                if self._closed:
                    raise RuntimeError(
                        "decode pipeline closed before this batch packed")
                self._process(decoder, staged, handle)
            # worker THREAD, not a coroutine: no asyncio cancellation can
            # land here; every failure must reach the consumer's result().
            # Not a retry spin either: the loop blocks on _jobs.get(), so
            # a failing batch is reported once, not hammered
            except BaseException as e:  # etl-lint: ignore[cancellation-swallow,unbounded-retry]
                if handle._windowed:
                    handle._windowed = False
                    self.window.release()
                handle._future.set_exception(e)
            finally:
                with self._lock:
                    if handle in self._undispatched:
                        self._undispatched.remove(handle)
                hb = self._hb
                if hb is not None:
                    # busy while batches are in flight: a frozen
                    # completed-count past the stall deadline then reads
                    # as a device-side stall
                    hb.beat(progress=("completed", self._completed),
                            busy=len(self.window) > 0)

    @hot_loop
    def _process(self, decoder: "DeviceDecoder", staged: StagedBatch,
                 handle: PipelinedDecode) -> None:
        """Pack + dispatch one batch on the worker thread. @hot_loop: runs
        once per batch on the dispatch path — fetches belong to _fetch."""
        from ..chaos import failpoints
        from ..models.errors import ErrorKind, EtlError
        from ..telemetry.metrics import (
            ETL_DECODE_DEVICE_OOM_FALLBACKS_TOTAL,
            ETL_DECODE_DISPATCH_SECONDS, ETL_DECODE_PACK_SECONDS,
            ETL_DECODE_PIPELINE_IN_FLIGHT, registry)
        from .engine import _PendingDecode

        # chaos site: fires once per submitted batch at pack-stage entry
        # (before routing, so small oracle-routed batches hit it too)
        failpoints.fail_point(failpoints.PIPELINE_PACK)
        mode, specs = decoder._route(staged)
        if mode != "oracle":
            # simulated (or, one day, real) device allocation failure:
            # degrade THIS batch to the host oracle instead of failing
            # the stream — availability beats the device-decode win
            try:
                failpoints.fail_point(failpoints.ENGINE_DEVICE_OOM)
            except EtlError as e:
                if not set(e.kinds()) & {ErrorKind.DEVICE_UNAVAILABLE,
                                         ErrorKind.MEMORY_PRESSURE_ABORT}:
                    raise
                registry.counter_inc(ETL_DECODE_DEVICE_OOM_FALLBACKS_TOTAL)
                mode, specs = "oracle", ()
        if mode == "oracle":
            # no device work: nothing to overlap, no window slot — the
            # consumer's result() runs the per-row oracle as before
            handle._future.set_result(
                (_PendingDecode(decoder, staged, (), None, None), None))
            return
        # window slot held from here until the fetch completes: caps the
        # arenas + device buffers of all in-flight batches. The bypass
        # keeps the pipeline live when a consumer blocks on a handle that
        # hasn't dispatched yet (out-of-order draining) or when close()
        # fires with abandoned slots outstanding: the window overshoots
        # instead of deadlocking against its own consumer.
        self.window.acquire(
            bypass=lambda: self._closed or self._demand_waiting())
        handle._windowed = True
        host = mode == "host"
        arena = self.pool.lease()
        t0 = time.perf_counter()
        try:
            packed = decoder._pack_stage(staged, specs, host, arena=arena)
            t1 = time.perf_counter()
            failpoints.fail_point(failpoints.PIPELINE_DISPATCH)
            packed_dev = decoder._dispatch_stage(staged, specs, packed, host)
            t2 = time.perf_counter()
        except BaseException:
            arena.release()
            raise
        pending = _PendingDecode(decoder, staged, specs, packed_dev,
                                 packed.bad_rows)
        iv = _Interval(t2)
        with self._lock:
            self._inflight.append(iv)
            # overlap: the part of THIS pack that ran while another batch
            # was between dispatch and fetch — nonzero means the host
            # packed batch N+1 while the device computed batch N
            overlap = 0.0
            for other in self._inflight:
                if other is iv:
                    continue
                end = other.end if other.end is not None else t1
                overlap += max(0.0, min(t1, end) - max(t0, other.start))
            self._pack_seconds += t1 - t0
            self._overlap_seconds += min(overlap, t1 - t0)
            pack_total = self._pack_seconds
            overlap_total = self._overlap_seconds
        registry.histogram_observe(ETL_DECODE_PACK_SECONDS, t1 - t0)
        registry.histogram_observe(ETL_DECODE_DISPATCH_SECONDS, t2 - t1)
        registry.gauge_set(ETL_DECODE_PIPELINE_IN_FLIGHT, len(self.window),
                           {"pipeline": self._name})
        self._publish_overlap(pack_total, overlap_total)
        handle._future.set_result((pending, arena, iv))

    def _publish_overlap(self, pack_total: float,
                         overlap_total: float) -> None:
        from ..telemetry.metrics import (
            ETL_DECODE_PIPELINE_OVERLAP_RATIO,
            ETL_DECODE_PIPELINE_OVERLAP_SECONDS_TOTAL,
            ETL_DECODE_PIPELINE_PACK_SECONDS_TOTAL, registry)

        # counters are registry-global (monotonic across pipelines):
        # publish the delta since this pipeline's last publication (only
        # the worker thread calls this, so the delta math is race-free)
        registry.counter_inc(ETL_DECODE_PIPELINE_PACK_SECONDS_TOTAL,
                             pack_total - self._published_pack)
        registry.counter_inc(ETL_DECODE_PIPELINE_OVERLAP_SECONDS_TOTAL,
                             overlap_total - self._published_overlap)
        self._published_pack = pack_total
        self._published_overlap = overlap_total
        if pack_total > 0:
            registry.gauge_set(ETL_DECODE_PIPELINE_OVERLAP_RATIO,
                               overlap_total / pack_total,
                               {"pipeline": self._name})

    # -- consumer side ------------------------------------------------------

    def _fetch(self, handle: PipelinedDecode):
        """Stage 3: wait out pack/dispatch if still running, fetch and
        complete the batch, then return the arena and window slot."""
        from ..chaos import failpoints
        from ..telemetry.metrics import (ETL_DECODE_FETCH_SECONDS,
                                         ETL_DECODE_PIPELINE_IN_FLIGHT,
                                         registry)

        handle._demanded = True  # window liveness valve, see _process
        value = handle._future.result()
        handle._demanded = False
        if len(value) == 2:  # oracle route: (pending, None)
            pending, _ = value
            t0 = time.perf_counter()
            try:
                failpoints.fail_point(failpoints.PIPELINE_FETCH)
                return pending.result()
            finally:
                with self._lock:
                    self._completed += 1
                registry.histogram_observe(ETL_DECODE_FETCH_SECONDS,
                                           time.perf_counter() - t0)
        pending, arena, iv = value
        t0 = time.perf_counter()
        try:
            failpoints.fail_point(failpoints.PIPELINE_FETCH)
            batch = pending.result()
        finally:
            now = time.perf_counter()
            with self._lock:
                iv.end = now
                if iv in self._inflight:
                    self._inflight.remove(iv)
                self._completed += 1
            hb = self._hb
            if hb is not None:
                hb.beat(progress=("completed", self._completed),
                        busy=len(self.window) > 1)
            arena.release()
            if handle._windowed:
                handle._windowed = False
                self.window.release()
            registry.gauge_set(ETL_DECODE_PIPELINE_IN_FLIGHT,
                               len(self.window), {"pipeline": self._name})
        registry.histogram_observe(ETL_DECODE_FETCH_SECONDS, now - t0)
        return batch

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            pack = self._pack_seconds
            overlap = self._overlap_seconds
            return {
                "submitted": self._submitted,
                "completed": self._completed,
                "in_flight": len(self.window),
                "window": self.window.limit,
                "pack_seconds_total": pack,
                "overlap_seconds_total": overlap,
                "overlap_ratio": overlap / pack if pack > 0 else 0.0,
                "arena": self.pool.stats(),
            }
