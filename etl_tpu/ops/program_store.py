"""Canonical-layout program store: every compiled decode program, one
subsystem — layout canonicalization, AOT disk persistence, and startup
prewarm.

Three layers, one key space (ops/engine._SHARED_FN_CACHE keys):

1. **Canonicalization** (`canonical_plan`). The decode program traced by
   `bitpack.parse_and_pack` is a pure function of the *sequence* of
   `(kind, gather_width, bit_width)` triples — the `col_index` slot in
   engine specs only selects which staged column feeds each byte-matrix
   slot, host-side, at pack time (the fused row-filter path is the one
   exception and is excluded below). So N tables whose column vectors
   are the same multiset compile ONE program instead of N:

     - *index erasure*: program specs carry positional indices, never
       staged column positions — two single-int4 tables share whatever
       columns sit around that int4;
     - *sort*: dense columns are packed in (kind, width, bit-width)
       order, so column ORDER stops mattering (DDL churn that drops and
       re-adds a column lands back on the same program);
     - *count padding*: each (kind, width, bit-width) group's column
       count rounds up to a small bucket ladder (≤1.5× steps), with the
       padded "phantom" slots packed as all-NULL columns — adding one
       column to a 5-int table stays inside the 6-slot program.

   The pack stage gathers real columns into their canonical slots and
   zeroes the phantom slots (zero length = NULL to the parsers, never a
   fallback candidate), and completion unpacks each real column from its
   canonical slot — the decoded ColumnarBatch is byte-identical to the
   exact layout's because column outputs are indexed by schema position,
   not slot position (proved the same way Pallas==XLA is:
   tests/test_program_store.py byte-identity matrix). Fused-row-filter
   programs skip canonicalization: the predicate evaluator is bound to
   staged column indices and is per-table anyway (its fingerprint is in
   the key).

2. **Disk persistence** (`acquire`/`try_load`/`save`). With a cache dir
   configured (`BatchConfig.program_cache_dir` or
   $ETL_TPU_PROGRAM_CACHE_DIR), cache misses AOT-compile
   (`jit(...).lower(args).compile()`) and serialize the executable
   (jax.experimental.serialize_executable) to
   `<dir>/<version-tag>/<fingerprint>.prog`; a restarted process loads
   the executable instead of re-paying the XLA build (measured: a ~32 s
   120-column build loads back in well under a second). The version tag
   hashes jaxlib/jax versions, the backend, the decode-source hash, and
   the host CPU feature flags — the XLA:CPU failure mode that sank the
   old `jax_compilation_cache_dir` attempt (AOT results recorded against
   different machine features hard-hang on reload) can only be hit by
   byte-sharing a dir across heterogeneous machines, and the tag keeps
   those populations in separate subdirectories. Writes are atomic
   (tmp + rename), so concurrent processes can share a dir; a corrupted
   or stale file is deleted and treated as a miss — degrade is always a
   clean rebuild, never a crash.

3. **Prewarm** (`warm_host_programs` / Pipeline.start). At startup the
   pipeline enumerates the SchemaStore's table schemas, resolves their
   canonical layouts, and warms the deduped host-program keys through
   the SAME `engine._host_fn_ready` machinery the nonblocking streaming
   decoders use: disk hits load synchronously (a warm restart reaches
   its first durable batch with ZERO fresh XLA builds — gated in
   bench.py --coldstart/--smoke via the compile counter), cold keys
   compile on background threads while batches decode on the host
   oracle. One API, three callers: pipeline prewarm, the streaming
   decoders' nonblocking first touch, and the chaos restart scenarios.
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import logging
import os
import pickle
import threading
import time

log = logging.getLogger("etl_tpu.ops.program_store")

# ---------------------------------------------------------------------------
# canonicalization
# ---------------------------------------------------------------------------

#: module switch for tests / emergency opt-out ($ETL_TPU_CANONICAL_LAYOUTS=0)
CANONICALIZE = os.environ.get("ETL_TPU_CANONICAL_LAYOUTS", "1") != "0"

#: per-(kind, width, bit-width) column-count ladder: ≤1.5× steps bound the
#: phantom-slot waste at 50% of a group's columns (host programs don't
#: care; on the device path upload bytes are the binding resource, and
#: the same ladder keeps the trade explicit)
_COUNT_BUCKETS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128, 192,
                  256)

#: the C packers index at most 256 slots per row; a canonical layout that
#: would pad past this falls back to sort + index erasure only
MAX_SLOTS = 256


def pad_count(n: int) -> int:
    for b in _COUNT_BUCKETS:
        if n <= b:
            return b
    return n


@dataclasses.dataclass(frozen=True)
class CanonicalPlan:
    """How one exact spec tuple maps onto its canonical program layout.

    specs:      canonical program specs (positional col indices) — what
                the jit key and `build_device_program` see
    slot_of:    dense position j (engine `_dense` order) → canonical slot
    pack_dense: per canonical slot, the dense position whose staged
                column feeds it; phantom slots name their group's first
                real member as a pack DONOR (same kind and width, so the
                nibble packer's alphabet scan sees a byte subset of what
                the real slot already scanned) and are zeroed after the
                pack
    phantom_slots: slots that are padding (zero-length ⇒ all-NULL)
    identity:   True when slots == dense positions and nothing is padded
                (the pack path then skips the permutation machinery;
                index erasure in `specs` still applies)
    """

    specs: tuple
    slot_of: tuple
    pack_dense: tuple
    phantom_slots: tuple
    identity: bool

    @property
    def n_slots(self) -> int:
        return len(self.specs)


_PLAN_CACHE: dict = {}
_PLAN_LOCK = threading.Lock()
#: distinct canonical layouts (spec tuples) seen this process — the
#: etl_decode_canonical_layouts gauge; its size vs tables-seen is the
#: sharing ratio canonicalization buys
_LAYOUTS_SEEN: set = set()


def _identity_plan(specs: tuple) -> CanonicalPlan:
    n = len(specs)
    pos = tuple(range(n))
    return CanonicalPlan(tuple((j, k, w, bw) for j, (_, k, w, bw)
                               in enumerate(specs)),
                         pos, pos, (), True)


def canonical_plan(specs: tuple) -> CanonicalPlan:
    """The canonical layout for one exact engine spec tuple
    ((col_index, kind, gather_width, bit_width), ...). Pure and cached —
    safe from any thread."""
    cached = _PLAN_CACHE.get(specs)
    if cached is not None:
        return cached
    n = len(specs)
    if not CANONICALIZE or n == 0:
        plan = _identity_plan(specs)
    else:
        triple = lambda j: (specs[j][1].name, specs[j][2], specs[j][3])
        order = sorted(range(n), key=lambda j: (*triple(j), j))
        groups: list = []  # (kind, w, bw, [dense positions])
        for j in order:
            t = triple(j)
            if groups and groups[-1][0] == t:
                groups[-1][1].append(j)
            else:
                groups.append([t, [j]])
        padded = sum(pad_count(len(members)) for _, members in groups)
        pad = padded <= MAX_SLOTS
        slot_of = [0] * n
        cspecs: list = []
        pack_dense: list = []
        phantom: list = []
        for (_, members) in groups:
            j0 = members[0]
            _, kind, w, bw = specs[j0]
            count = pad_count(len(members)) if pad else len(members)
            for i in range(count):
                slot = len(cspecs)
                cspecs.append((slot, kind, w, bw))
                if i < len(members):
                    slot_of[members[i]] = slot
                    pack_dense.append(members[i])
                else:
                    pack_dense.append(j0)  # donor: same (kind, w, bw)
                    phantom.append(slot)
        identity = not phantom and slot_of == list(range(n))
        plan = CanonicalPlan(tuple(cspecs), tuple(slot_of),
                             tuple(pack_dense), tuple(phantom), identity)
    with _PLAN_LOCK:
        _PLAN_CACHE[specs] = plan
        _LAYOUTS_SEEN.add(plan.specs)
        n_layouts = len(_LAYOUTS_SEEN)
    from ..telemetry.metrics import ETL_DECODE_CANONICAL_LAYOUTS, registry

    registry.gauge_set(ETL_DECODE_CANONICAL_LAYOUTS, n_layouts)
    return plan


# ---------------------------------------------------------------------------
# disk persistence
# ---------------------------------------------------------------------------

_CACHE_FORMAT_VERSION = 1
_DIR_LOCK = threading.Lock()
_CONFIGURED: list = [None]  # [str | None]; None = fall back to env


def configure(cache_dir: "str | None") -> None:
    """Set (or clear) the process-wide program cache directory.
    `Pipeline.start` calls this from `BatchConfig.program_cache_dir`;
    None restores the $ETL_TPU_PROGRAM_CACHE_DIR / disabled default."""
    with _DIR_LOCK:
        _CONFIGURED[0] = cache_dir


def active_dir() -> "str | None":
    with _DIR_LOCK:
        configured = _CONFIGURED[0]
    if configured is not None:
        return configured
    return os.environ.get("ETL_TPU_PROGRAM_CACHE_DIR") or None


_SOURCE_MODULES = ("bitpack.py", "parsers.py", "parsers_lanes.py",
                   "pallas_kernel.py", "engine.py", "predicate.py",
                   "staging.py")
_VERSION_TAG: list = []  # lazy singleton


def _cpu_features() -> str:
    """Hash of the host CPU's feature flags: the XLA:CPU AOT pitfall this
    guards (machine features recorded at compile time vs the execution
    host) is exactly a cross-machine mismatch, so the flags ride the
    version tag and heterogeneous hosts sharing a cache dir use separate
    subdirectories instead of hanging each other."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    return hashlib.sha256(
                        " ".join(sorted(line.split(":", 1)[1].split()))
                        .encode()).hexdigest()[:16]
    except OSError:
        pass
    import platform

    return platform.machine() or "unknown"


def _source_hash() -> str:
    h = hashlib.sha256()
    base = os.path.dirname(os.path.abspath(__file__))
    for name in _SOURCE_MODULES:
        try:
            with open(os.path.join(base, name), "rb") as f:
                h.update(name.encode())
                h.update(f.read())
        except OSError:
            h.update(f"missing:{name}".encode())
    return h.hexdigest()[:16]


def version_tag() -> str:
    """Subdirectory name under the cache dir; changes whenever anything
    that could make a serialized executable wrong changes — jax/jaxlib
    version, backend, the decode-program source, the host CPU features.
    Stale populations are simply never read again (wipe the dir to
    reclaim space, OPERATIONS.md runbook)."""
    if not _VERSION_TAG:
        import jax
        import jaxlib

        raw = "|".join((
            f"v{_CACHE_FORMAT_VERSION}", jax.__version__,
            jaxlib.__version__, jax.default_backend(), _source_hash(),
            _cpu_features()))
        _VERSION_TAG.append(hashlib.sha256(raw.encode()).hexdigest()[:16])
    return _VERSION_TAG[0]


def _stable_repr(obj) -> str:
    """Deterministic, process-independent rendering of a program-cache
    key (tuples, enums, primitives). Enum identity uses class+name, never
    the interpreter-dependent default repr."""
    if isinstance(obj, enum.Enum):
        return f"{type(obj).__name__}.{obj.name}"
    if isinstance(obj, tuple):
        return "(" + ",".join(_stable_repr(x) for x in obj) + ")"
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return repr(obj)
    return repr(obj)


def fingerprint(key: tuple) -> str:
    return hashlib.sha256(_stable_repr(key).encode()).hexdigest()[:32]


def _path_for(key: tuple, cache_dir: str) -> str:
    return os.path.join(cache_dir, version_tag(), fingerprint(key) + ".prog")


def _serialize_mod():
    try:
        from jax.experimental import serialize_executable
        return serialize_executable
    except Exception:  # jax without the module: persistence disabled
        return None


def save(key: tuple, compiled) -> bool:
    """Serialize one AOT-compiled executable to the cache dir. Atomic
    (tmp + rename) so concurrent processes sharing the dir can never
    observe a torn file. Best-effort: any failure logs and returns
    False — persistence never breaks decode."""
    cache_dir = active_dir()
    se = _serialize_mod()
    if cache_dir is None or se is None:
        return False
    try:
        payload, in_tree, out_tree = se.serialize(compiled)
        blob = pickle.dumps({
            "format": _CACHE_FORMAT_VERSION, "key": _stable_repr(key),
            "payload": payload, "in_tree": in_tree, "out_tree": out_tree,
        })
        path = _path_for(key, cache_dir)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        return True
    except Exception:
        log.warning("failed to persist compiled program (decode continues "
                    "with the in-memory copy)", exc_info=True)
        return False


def try_load(key: tuple, record_absent: bool = True):
    """Load the serialized executable for `key`, or None. A present-but-
    unreadable file (corruption, version skew inside a tag dir, a
    partial write from a dead process) is DELETED and reported as an
    invalid miss — the caller rebuilds cleanly. `record_absent=False`
    suppresses the absent-miss counter for PRE-probes whose miss path
    leads straight into `acquire` (which probes — and counts — again);
    invalid misses always count, they are actionable events."""
    cache_dir = active_dir()
    se = _serialize_mod()
    if cache_dir is None or se is None:
        return None
    from ..telemetry.metrics import (ETL_COMPILE_CACHE_HITS_TOTAL,
                                     ETL_COMPILE_CACHE_LOAD_SECONDS,
                                     ETL_COMPILE_CACHE_MISSES_TOTAL,
                                     registry)

    path = _path_for(key, cache_dir)
    if not os.path.exists(path):
        if record_absent:
            registry.counter_inc(ETL_COMPILE_CACHE_MISSES_TOTAL,
                                 labels={"reason": "absent"})
        return None
    t0 = time.perf_counter()
    try:
        with open(path, "rb") as f:
            data = pickle.load(f)
        if data.get("format") != _CACHE_FORMAT_VERSION \
                or data.get("key") != _stable_repr(key):
            raise ValueError("program cache entry does not match its key")
        fn = se.deserialize_and_load(data["payload"], data["in_tree"],
                                     data["out_tree"])
    except Exception:
        log.warning("corrupt/stale program cache entry %s; deleting and "
                    "rebuilding", path, exc_info=True)
        try:
            os.unlink(path)
        except OSError:
            pass
        registry.counter_inc(ETL_COMPILE_CACHE_MISSES_TOTAL,
                             labels={"reason": "invalid"})
        return None
    registry.counter_inc(ETL_COMPILE_CACHE_HITS_TOTAL,
                         labels={"layer": "disk"})
    registry.histogram_observe(ETL_COMPILE_CACHE_LOAD_SECONDS,
                               time.perf_counter() - t0)
    return fn


def acquire(key: tuple, builder, example_args: "tuple | None" = None):
    """Resolve a program-cache miss: disk load if possible, else build
    and compile — and persist the executable for the next process.

    `builder()` returns the jitted callable exactly as the engine builds
    it today; `example_args` are the actual dispatch arrays (their
    shapes/dtypes/placement ARE the jit signature, so the AOT lowering
    can never drift from what the call sites pass). Every path counts
    one program build in etl_programs_compiled_total — the counter the
    warm-restart gates assert stays at zero. AOT or serialization
    failures (e.g. a Mosaic rejection, which must surface at the CALL
    site where engine's pallas fallback handles it) degrade to the plain
    jitted callable, memory-only."""
    from ..telemetry.metrics import ETL_PROGRAMS_COMPILED_TOTAL, registry

    fn = try_load(key)
    if fn is not None:
        return fn
    jitted = builder()
    registry.counter_inc(ETL_PROGRAMS_COMPILED_TOTAL)
    if active_dir() is None or example_args is None \
            or _serialize_mod() is None:
        return jitted
    try:
        lowered = jitted.lower(*example_args)
        compiled = lowered.compile()
    except Exception:
        # compile errors must surface at the call (engine routes Mosaic
        # rejections to the XLA fallback there; real errors propagate)
        return jitted
    problems = persist_contract_violations(key, jitted, lowered,
                                           example_args)
    if problems:
        # the executable still serves THIS process (decode must not
        # regress on a lint result), but it is never persisted: a
        # prewarm on a later process would otherwise load the poisoned
        # program straight from disk with no compile step left to catch
        # it. Fixing the program re-enables persistence on next build.
        log.warning(
            "compiled decode program %s violates IR persist contracts "
            "(%s); serving it memory-only, NOT caching to disk",
            fingerprint(key), "; ".join(problems))
        return compiled
    save(key, compiled)
    return compiled


def persist_contract_violations(key: tuple, jitted, lowered,
                                example_args) -> list:
    """The AOT-persist gate (etl-lint IR tier, satellite of the
    `--programs` pass): the no-host-callback and donation-verified
    contracts, evaluated on the program about to be cached to disk.
    Expected donation is inferred from the cache key — host programs
    (key[-1] is True) never declare donation; device programs declare it
    exactly when the backend supports it (engine._donation_supported).
    Returns human-readable violation strings; analyzer errors return []
    (the gate must never block decode or persistence on its own bug)."""
    try:
        import jax

        from ..analysis.ir import contracts
        from .engine import _donation_supported

        problems = []
        jaxpr = jitted.trace(*example_args).jaxpr
        for detail, _msg in contracts.check_host_callback(jaxpr):
            problems.append(f"ir-host-callback: {detail}")
        declared = (not key[-1]) and _donation_supported()
        for detail, _msg in contracts.check_donation(
                lowered.as_text(), declared, jax.default_backend()):
            problems.append(f"ir-donation: {detail}")
        return problems
    except Exception:
        log.warning("IR persist-contract check failed; persisting "
                    "unchecked", exc_info=True)
        return []


# ---------------------------------------------------------------------------
# observed signatures (PR 11 leftover)
# ---------------------------------------------------------------------------
#
# SchemaStore enumeration prewarms the layouts the STORE knows about, at
# the configured row buckets. The workload's actual program population
# is broader: backlog growth seals mega buckets (65536/262144) the
# default buckets never name, and fused-filter programs are per-table.
# Every host dispatch records its key here (first sighting per process;
# one small atomic file per version tag), and `prewarm_pipeline` folds
# the recorded signatures into its enumeration — a restart prewarms
# what the workload actually used, not just what the store implies.

_OBSERVED_FILE = "observed_sigs.pkl"
_OBSERVED_LOCK = threading.Lock()
_OBSERVED_SEEN: set = set()
#: newest-last cap: a pathological signature churn (unbounded DDL
#: variety) ages out the oldest recordings instead of growing the file
_OBSERVED_MAX = 256


def _observed_path(cache_dir: str) -> str:
    return os.path.join(cache_dir, version_tag(), _OBSERVED_FILE)


def load_observed() -> list:
    """The recorded observed signatures (program-cache keys), oldest
    first. Corruption degrades to an empty list + file deletion — the
    same never-fatal stance as the executable cache."""
    cache_dir = active_dir()
    if not cache_dir:
        return []
    path = _observed_path(cache_dir)
    if not os.path.exists(path):
        return []
    try:
        with open(path, "rb") as f:
            data = pickle.load(f)
        if data.get("format") != _CACHE_FORMAT_VERSION:
            raise ValueError("observed-signature file format mismatch")
        return [k for k in data.get("keys", []) if isinstance(k, tuple)]
    except Exception:
        log.warning("corrupt observed-signature file %s; deleting",
                    path, exc_info=True)
        try:
            os.unlink(path)
        except OSError:
            pass
        return []


def record_observed(key: tuple) -> None:
    """Persist one observed host-program signature. Called by the
    engine's dispatch stage per host dispatch: the disarmed cost is one
    set lookup; the first sighting per process pays a small read-merge-
    write of the signature file (atomic tmp+rename — best-effort across
    processes, last-writer-wins). No cache dir = no-op."""
    cache_dir = active_dir()
    if cache_dir is None:
        return
    with _OBSERVED_LOCK:
        if key in _OBSERVED_SEEN:
            return
        _OBSERVED_SEEN.add(key)
    try:
        path = _observed_path(cache_dir)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with _OBSERVED_LOCK:
            merged = [k for k in load_observed() if k != key] + [key]
            merged = merged[-_OBSERVED_MAX:]
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "wb") as f:
                pickle.dump({"format": _CACHE_FORMAT_VERSION,
                             "keys": merged}, f)
            os.replace(tmp, path)
    except Exception:
        log.warning("failed to record observed program signature "
                    "(prewarm coverage only; decode continues)",
                    exc_info=True)


def warm_observed_signatures() -> dict:
    """Disk-load the executable of every recorded observed signature not
    already warm in memory. Synchronous — run on an executor. A recorded
    signature whose .prog was wiped/evicted stays cold here and compiles
    via the nonblocking first touch like any other (no decoder exists to
    build from a bare key)."""
    from .engine import _shared_fn_get, _shared_fn_put

    keys = load_observed()
    ready = 0
    missing = 0
    for key in keys:
        if _shared_fn_get(key) is not None:
            ready += 1
            continue
        fn = try_load(key, record_absent=False)
        if fn is not None:
            _shared_fn_put(key, fn)
            ready += 1
        else:
            missing += 1
    return {"observed": len(keys), "observed_ready": ready,
            "observed_missing": missing}


# ---------------------------------------------------------------------------
# prewarm
# ---------------------------------------------------------------------------

#: row-capacity buckets the pipeline prewarm warms per canonical layout:
#: the streaming seal cap's bucket plus the mid-size bucket CDC flushes
#: most often land in. Callers override per deployment
#: (BatchConfig.prewarm_row_buckets).
PREWARM_ROW_BUCKETS = (4096, 16384)


def warm_host_programs(schemas, row_buckets=None, wait: bool = False) -> dict:
    """Warm the host-backend decode programs for `schemas` (deduped by
    canonical layout × row bucket). Synchronous — run it on an executor
    from async code. Disk hits load inline (fast); cold keys kick the
    engine's nonblocking background compile unless `wait`, which
    compiles inline (the chaos runner uses it to seed a cache dir
    deterministically). Returns {"layouts", "ready", "building"}."""
    from .engine import (DeviceDecoder, _host_fn_ready, _shared_fn_get,
                         _host_fn_key)
    from .staging import synthetic_staged_batch

    # note: a key already warm IN MEMORY is counted ready and skipped —
    # nothing new is persisted for it (the in-memory callable may be a
    # lazy jit, which cannot be serialized after the fact). Callers that
    # need a guaranteed DISK seed (the chaos runner, the persistence
    # tests) clear the in-process cache first.
    buckets = tuple(row_buckets) if row_buckets else PREWARM_ROW_BUCKETS
    seen: set = set()
    ready = 0
    building = 0
    for schema in schemas:
        try:
            dec = DeviceDecoder(schema, mesh=None, telemetry=False,
                                device_min_rows=1 << 30,
                                nonblocking_compile=True)
            specs = dec._host_specs()
            if not specs:
                continue
            n_cols = len(schema.replicated_columns)
            for bucket in buckets:
                key = _host_fn_key(bucket, specs, None)
                if key in seen:
                    continue
                seen.add(key)
                if _shared_fn_get(key) is not None:
                    ready += 1
                    continue
                staged = synthetic_staged_batch(n_cols, bucket)
                if wait:
                    value, _ = dec._device_call(staged, specs, host=True)
                    import jax

                    jax.block_until_ready(value)
                    ready += 1
                elif _host_fn_ready(dec, staged, specs):
                    ready += 1
                else:
                    building += 1
        except Exception:
            log.warning("program prewarm failed for %s; its first batches "
                        "decode on the oracle",
                        getattr(schema, "name", schema), exc_info=True)
    return {"layouts": len(seen), "ready": ready, "building": building}


async def prewarm_pipeline(store, batch_config) -> dict:
    """`Pipeline.start`'s program prewarm: enumerate the SchemaStore's
    table schemas and warm their canonical host-program layouts before
    the apply loop sees traffic. Runs on the default executor — never on
    the event loop (the r5-advisor / etl-lint rule the autotune prewarm
    already follows). A fresh pipeline (no stored schemas yet) is a
    no-op; a restarted one reaches its first durable batch on cached
    programs."""
    import asyncio

    if batch_config.program_cache_dir:
        # the store is PROCESS-global (the admission-capacity stance:
        # the first pipeline to configure a dir fixes it); a co-resident
        # pipeline asking for a different dir is a config conflict —
        # keep the first and say so rather than silently re-routing the
        # first pipeline's programs
        current = active_dir()
        if current and current != batch_config.program_cache_dir:
            log.warning(
                "program cache dir already configured to %s for this "
                "process; ignoring %s (the store is process-global — "
                "the first pipeline to configure it wins)",
                current, batch_config.program_cache_dir)
        else:
            configure(batch_config.program_cache_dir)
    prewarm = batch_config.prewarm_programs
    if prewarm is None:
        prewarm = bool(batch_config.program_cache_dir)
    if not prewarm:
        return {}
    schemas = []
    try:
        for tid in await store.get_table_ids_with_schemas():
            s = await store.get_table_schema(tid)
            if s is not None:
                schemas.append(s)
    except Exception:
        log.warning("program prewarm: schema enumeration failed; decode "
                    "warms lazily", exc_info=True)
        return {}
    loop = asyncio.get_running_loop()

    def _warm() -> dict:
        stats = warm_host_programs(schemas,
                                   batch_config.prewarm_row_buckets) \
            if schemas else {"layouts": 0, "ready": 0, "building": 0}
        # fold in the OBSERVED signatures recorded by previous
        # incarnations: the row buckets the workload actually sealed
        # (mega-seal growth, odd flush sizes) and fused-filter programs,
        # neither of which the SchemaStore enumeration can name
        stats.update(warm_observed_signatures())
        return stats

    stats = await loop.run_in_executor(None, _warm)
    log.info("program prewarm: %d schemas -> %s", len(schemas), stats)
    return stats


def reset_for_tests() -> None:
    """Clear the plan cache / layout gauge inputs and the observed-
    signature process guard (tests only; compiled programs live in
    engine._SHARED_FN_CACHE and are untouched)."""
    with _PLAN_LOCK:
        _PLAN_CACHE.clear()
        _LAYOUTS_SEEN.clear()
    with _OBSERVED_LOCK:
        _OBSERVED_SEEN.clear()
