"""Device-side field parsers: fixed-shape jax programs over gathered bytes.

Each parser consumes a byte matrix `[R, L]` (one field per row, left-aligned,
zero-padded — produced by `gather_fields`) plus per-row lengths, and emits
int32 component arrays + an `ok` mask. All arithmetic is int32: TPU int64 and
float64 are emulated, so multi-word values (int8, timestamps, float mantissas)
leave the device as 9-digit base-10^9 limbs that the host combines exactly
with vectorized numpy (see ops/engine.py). Rows with `ok == False` are
re-decoded by the CPU oracle (mixed batches partition, they never fail —
SURVEY §7 build plan item 5).

Float fast-path note: a field is device-decodable iff its mantissa has ≤ 15
significant digits and the decimal-point adjustment |e| ≤ 22 — then
`m * 10^e` / `m / 10^-e` is a single correctly-rounded f64 operation on
host, bit-identical to strtod (classic exact fast path). Everything else
(17-digit shortest-roundtrip doubles, huge exponents) falls back to CPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

D0 = ord("0")
MINUS = ord("-")
PLUS = ord("+")
DOT = ord(".")
COLON = ord(":")
DASH = ord("-")
SPACE = ord(" ")

# 10^k for k in 0..8 (int32-safe)
POW10 = np.array([10**k for k in range(9)], dtype=np.int32)


def pow10(e: jax.Array) -> jax.Array:
    """10**clip(e,0,8) as a select chain — no constant-array gather, so the
    same code lowers under both XLA and Pallas (Pallas kernels cannot
    capture constant arrays)."""
    out = jnp.ones_like(e)
    acc = 1
    for k in range(1, 9):
        acc *= 10
        out = jnp.where(e >= k, acc, out)
    return out


def gather_fields(data: jax.Array, offsets: jax.Array, lengths: jax.Array,
                  width: int) -> jax.Array:
    """Gather each row's field bytes into an int32 `[R, width]` matrix,
    left-aligned, zero beyond the field length. `data` is uint8[cap]."""
    idx = offsets[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
    raw = jnp.take(data, jnp.clip(idx, 0, data.shape[0] - 1), axis=0,
                   mode="clip").astype(jnp.int32)
    mask = jnp.arange(width, dtype=jnp.int32)[None, :] < lengths[:, None]
    return jnp.where(mask, raw, 0)


def _digit_limbs(bmat: jax.Array, lengths: jax.Array, start: jax.Array,
                 n_limbs: int = 3):
    """Base-10^9 limb accumulation of digits in positions [start, length).

    Returns (limbs: list of int32[R] little-endian by 10^9 word, all_digits:
    bool[R] — every position in range held an ASCII digit)."""
    R, L = bmat.shape
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_range = (pos >= start[:, None]) & (pos < lengths[:, None])
    d = bmat - D0
    is_digit = (d >= 0) & (d <= 9)
    # NOT a bool-armed where: Mosaic (pallas) lowers bool selects via an
    # i8→i1 truncation it rejects; pure i1 logical ops lower everywhere
    all_digits = ~(in_range & ~is_digit).any(axis=1)
    r = lengths[:, None] - 1 - pos  # digit position from the right
    weight = pow10(r % 9)
    dd = jnp.where(in_range & is_digit, d, 0)
    limbs = []
    for k in range(n_limbs):
        sel = in_range & (r // 9 == k)
        limbs.append(jnp.where(sel, dd * weight, 0).sum(axis=1,
                                                        dtype=jnp.int32))
    return limbs, all_digits


def parse_int(bmat: jax.Array, lengths: jax.Array):
    """Signed decimal integer → (neg, limb0, limb1, limb2, ndigits, ok).
    Handles up to 27 digits; int8's 19 fits with headroom."""
    neg = bmat[:, 0] == MINUS
    plus = bmat[:, 0] == PLUS
    start = (neg | plus).astype(jnp.int32)
    limbs, all_digits = _digit_limbs(bmat, lengths, start)
    ndigits = lengths - start
    ok = all_digits & (ndigits >= 1) & (ndigits <= 27) \
        & (lengths <= bmat.shape[1])
    return neg, limbs[0], limbs[1], limbs[2], ndigits, ok


def parse_bool(bmat: jax.Array, lengths: jax.Array):
    t = bmat[:, 0] == ord("t")
    f = bmat[:, 0] == ord("f")
    ok = (lengths == 1) & (t | f)
    return t, ok


def _fixed2(bmat: jax.Array, p: int) -> jax.Array:
    return (bmat[:, p] - D0) * 10 + (bmat[:, p + 1] - D0)


def _days_from_civil_dev(y: jax.Array, m: jax.Array, d: jax.Array) -> jax.Array:
    """Device version of codec.text.days_from_civil (y >= 0 after shift)."""
    y = y - (m <= 2)
    era = y // 400
    yoe = y - era * 400
    doy = (153 * (m + jnp.where(m > 2, -3, 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def parse_date(bmat: jax.Array, lengths: jax.Array):
    """'YYYY-MM-DD' → (days_since_epoch, ok). BC dates (trailing ' BC') and
    5+ digit years fall back to CPU."""
    d = bmat - D0
    y = d[:, 0] * 1000 + d[:, 1] * 100 + d[:, 2] * 10 + d[:, 3]
    m = _fixed2(bmat, 5)
    dd = _fixed2(bmat, 8)
    digits_ok = ((d[:, [0, 1, 2, 3, 5, 6, 8, 9]] >= 0)
                 & (d[:, [0, 1, 2, 3, 5, 6, 8, 9]] <= 9)).all(axis=1)
    ok = (lengths == 10) & digits_ok \
        & (bmat[:, 4] == DASH) & (bmat[:, 7] == DASH) \
        & (m >= 1) & (m <= 12) & (dd >= 1) & (dd <= 31) & (y >= 1)
    days = _days_from_civil_dev(y, m, dd)
    return jnp.where(ok, days, 0), ok


def _parse_hms_at(bmat: jax.Array, lengths: jax.Array, base: int):
    """HH:MM:SS[.ffffff] starting at column `base`. Returns
    (sec_of_day, us, end_pos, ok)."""
    R, L = bmat.shape
    d = bmat - D0
    hh = _fixed2(bmat, base)
    mm = _fixed2(bmat, base + 3)
    ss = _fixed2(bmat, base + 6)
    sep_ok = (bmat[:, base + 2] == COLON) & (bmat[:, base + 5] == COLON)
    base_digits = jnp.stack([d[:, base], d[:, base + 1], d[:, base + 3],
                             d[:, base + 4], d[:, base + 6], d[:, base + 7]],
                            axis=1)
    digits_ok = ((base_digits >= 0) & (base_digits <= 9)).all(axis=1)
    has_dot = (lengths > base + 8) & (bmat[:, base + 8] == DOT) \
        if base + 8 < L else jnp.zeros(R, dtype=bool)

    # fractional digits: contiguous run starting at base+9, max 6
    frac_start = base + 9
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    is_digit = (d >= 0) & (d <= 9)
    in_frac_window = (pos >= frac_start) & (pos < frac_start + 6) \
        & (pos < lengths[:, None])
    frac_digit = in_frac_window & is_digit
    # run length = index of first non-digit within the window
    run = jnp.where(
        has_dot,
        jnp.sum(jnp.cumprod(jnp.where(in_frac_window, frac_digit, 1),
                            axis=1) * in_frac_window, axis=1),
        0).astype(jnp.int32)
    k = pos - frac_start  # 0-based frac index
    scale = pow10(jnp.clip(5 - k, 0, 8))
    us = jnp.where(frac_digit & (k < run[:, None]), d * scale, 0) \
        .sum(axis=1, dtype=jnp.int32)
    frac_ok = ~has_dot | (run >= 1)  # no bool-armed select (Mosaic)
    end = base + 8 + jnp.where(has_dot, 1 + run, 0)
    sec = (hh * 60 + mm) * 60 + ss
    # hh == 24 ("24:00:00") exists in PG but needs the CPU clamp path
    ok = sep_ok & digits_ok & frac_ok & (hh <= 23) & (mm <= 59) & (ss <= 59)
    return sec, us, end, ok


def parse_time(bmat: jax.Array, lengths: jax.Array):
    """'HH:MM:SS[.ffffff]' → (ms_of_day, us_rem, ok)."""
    sec, us, end, ok = _parse_hms_at(bmat, lengths, 0)
    ok = ok & (end == lengths)
    ms = sec * 1000 + us // 1000
    return ms, us % 1000, ok


def _parse_tz_at(bmat: jax.Array, lengths: jax.Array, p: jax.Array):
    """±HH[:MM[:SS]] at per-row position p. Returns (offset_sec, end, ok)."""
    R, L = bmat.shape

    def at(q):
        return jnp.take_along_axis(bmat, jnp.clip(q, 0, L - 1)[:, None],
                                   axis=1)[:, 0]

    sign_b = at(p)
    neg = sign_b == MINUS
    sign_ok = neg | (sign_b == PLUS)
    d1, d2 = at(p + 1) - D0, at(p + 2) - D0
    hh = d1 * 10 + d2
    hh_ok = (d1 >= 0) & (d1 <= 9) & (d2 >= 0) & (d2 <= 9)
    has_min = (lengths > p + 3) & (at(p + 3) == COLON)
    m1, m2 = at(p + 4) - D0, at(p + 5) - D0
    mm = jnp.where(has_min, m1 * 10 + m2, 0)
    mm_ok = ~has_min | ((m1 >= 0) & (m1 <= 9) & (m2 >= 0) & (m2 <= 9))
    has_sec = has_min & (lengths > p + 6) & (at(p + 6) == COLON)
    s1, s2 = at(p + 7) - D0, at(p + 8) - D0
    ss = jnp.where(has_sec, s1 * 10 + s2, 0)
    ss_ok = ~has_sec | ((s1 >= 0) & (s1 <= 9) & (s2 >= 0) & (s2 <= 9))
    end = p + 3 + jnp.where(has_min, 3, 0) + jnp.where(has_sec, 3, 0)
    off = hh * 3600 + mm * 60 + ss
    off = jnp.where(neg, -off, off)
    # PG never renders offsets beyond ±15:59:59; larger hh would overflow
    # the packed-transport ms budget (bitpack._MS_TZ_ZZ_BITS) with ok=1,
    # silently corrupting instead of falling back — bound it here
    return off, end, sign_ok & hh_ok & mm_ok & ss_ok & (hh <= 15)


def parse_timestamp(bmat: jax.Array, lengths: jax.Array, with_tz: bool):
    """'YYYY-MM-DD HH:MM:SS[.ffffff][±TZ]' →
    (days, ms_of_day, us_rem, tz_sec, ok)."""
    days, date_ok = parse_date(bmat, jnp.full_like(lengths, 10))
    space_ok = bmat[:, 10] == SPACE
    sec, us, end, hms_ok = _parse_hms_at(bmat, lengths, 11)
    if with_tz:
        tz, tz_end, tz_ok = _parse_tz_at(bmat, lengths, end)
        ok = date_ok & space_ok & hms_ok & tz_ok & (tz_end == lengths)
    else:
        tz = jnp.zeros_like(sec)
        ok = date_ok & space_ok & hms_ok & (end == lengths)
    ok = ok & (lengths >= 19)
    ms = sec * 1000 + us // 1000
    return days, ms, us % 1000, tz, ok


def parse_float(bmat: jax.Array, lengths: jax.Array):
    """Decimal float text → (neg, limb0, limb1, exp_adj, special, ok).

    `special`: 0 normal, 1 NaN, 2 +Inf, 3 -Inf. Device-ok only on the exact
    fast path (≤15 sig digits, |exp_adj| ≤ 22, optional e-exponent) — host
    computes sign * (limb1*1e9 + limb0) * 10^exp_adj with one rounding."""
    R, L = bmat.shape
    d = bmat - D0
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_len = pos < lengths[:, None]

    # specials: NaN / Infinity / -Infinity (per-position scalar compares —
    # no captured constant arrays, Pallas-compatible)
    def match(lit: bytes):
        ok = lengths == len(lit)
        for i, ch in enumerate(lit):
            ok = ok & (bmat[:, i] == ch)
        return ok

    is_nan = match(b"NaN")
    is_pinf = match(b"Infinity")
    is_ninf = match(b"-Infinity")
    special = (is_nan * 1 + is_pinf * 2 + is_ninf * 3).astype(jnp.int32)

    neg = bmat[:, 0] == MINUS
    start = (neg | (bmat[:, 0] == PLUS)).astype(jnp.int32)

    is_e = ((bmat == ord("e")) | (bmat == ord("E"))) & in_len
    has_e = is_e.any(axis=1)
    e_pos = jnp.where(has_e, jnp.argmax(is_e, axis=1),
                      lengths).astype(jnp.int32)
    is_dot = (bmat == DOT) & in_len & (pos < e_pos[:, None])
    has_dot = is_dot.any(axis=1)
    dot_pos = jnp.where(has_dot, jnp.argmax(is_dot, axis=1),
                        e_pos).astype(jnp.int32)
    n_dots = is_dot.sum(axis=1)

    # mantissa digits: [start, e_pos) excluding the dot
    is_digit = (d >= 0) & (d <= 9)
    mant_sel = (pos >= start[:, None]) & (pos < e_pos[:, None]) \
        & ~is_dot
    mant_valid = ~(mant_sel & ~is_digit).any(axis=1)
    n_mant = mant_sel.sum(axis=1).astype(jnp.int32)
    # digit position from the right within the mantissa (dot removed):
    # digits after the dot keep index; digits before shift by frac count
    frac_count = jnp.where(has_dot, e_pos - dot_pos - 1, 0).astype(jnp.int32)
    before_dot = pos < dot_pos[:, None]
    # index from right among mantissa digits
    r = jnp.where(before_dot,
                  (dot_pos[:, None] - 1 - pos) + frac_count[:, None],
                  e_pos[:, None] - 1 - pos)
    weight = pow10(r % 9)
    dd = jnp.where(mant_sel & is_digit, d, 0)
    limb0 = jnp.where(mant_sel & (r // 9 == 0), dd * weight, 0) \
        .sum(axis=1, dtype=jnp.int32)
    limb1 = jnp.where(mant_sel & (r // 9 == 1), dd * weight, 0) \
        .sum(axis=1, dtype=jnp.int32)

    # explicit exponent after 'e'
    exp_start = e_pos + 1
    def at(q):
        return jnp.take_along_axis(bmat, jnp.clip(q, 0, L - 1)[:, None],
                                   axis=1)[:, 0]
    exp_neg = has_e & (at(exp_start) == MINUS)
    exp_sign = has_e & (exp_neg | (at(exp_start) == PLUS))
    exp_d_start = exp_start + exp_sign.astype(jnp.int32)
    exp_sel = (pos >= exp_d_start[:, None]) & in_len
    exp_valid = ~(exp_sel & ~is_digit).any(axis=1) \
        & (~has_e | (lengths > exp_d_start))
    re = lengths[:, None] - 1 - pos
    eweight = pow10(re % 9)
    exp_val = jnp.where(exp_sel & is_digit & (re // 9 == 0), d * eweight, 0) \
        .sum(axis=1, dtype=jnp.int32)
    exp_val = jnp.where(exp_neg, -exp_val, exp_val)
    exp_val = jnp.where(has_e, exp_val, 0)

    # significant digits (ignore leading zeros)
    lead_zero_run = jnp.sum(
        jnp.cumprod(jnp.where(mant_sel, (d == 0) & mant_sel, 1), axis=1)
        * mant_sel, axis=1).astype(jnp.int32)
    sig = n_mant - lead_zero_run
    exp_adj = exp_val - frac_count

    # n_mant ≤ 18: the two limbs hold 18 digits; a 19+-digit mantissa can
    # still have ≤ 15 *significant* digits (trailing zeros / leading zeros
    # straddling the limb boundary) and would silently truncate otherwise
    fast = (sig <= 15) & (jnp.abs(exp_adj) <= 22) & (n_mant >= 1) \
        & (n_mant <= 18) & (n_dots <= 1) & mant_valid & exp_valid
    ok = fast | (special > 0)
    return neg, limb0, limb1, exp_adj, special, ok


# ---------------------------------------------------------------------------
# Shared per-kind column dispatch — single source of truth for the engine
# (single-chip packed program) and parallel/mesh.py (sharded step).
# ---------------------------------------------------------------------------

from ..models.pgtypes import CellKind  # noqa: E402  (bottom import by design)

# packed int32 component names per kind, in emit order
COLUMN_COMPONENTS: dict = {
    CellKind.BOOL: ("v",),
    CellKind.I16: ("v",), CellKind.I32: ("v",), CellKind.U32: ("v",),
    CellKind.I64: ("neg", "l0", "l1", "l2"),
    CellKind.F32: ("neg", "l0", "l1", "ea", "sp"),
    CellKind.F64: ("neg", "l0", "l1", "ea", "sp"),
    CellKind.DATE: ("days",),
    CellKind.TIME: ("ms", "us"),
    CellKind.TIMESTAMP: ("days", "ms", "us"),  # tz folded into ms
    CellKind.TIMESTAMPTZ: ("days", "ms", "us"),
}


def _int_range_ok(kind, neg, l0, l1, l2, ndigits):
    """Exact range check on base-10^9 limbs (values may wrap int32/int64
    after combine, so bounds must be checked limb-wise on device)."""
    if kind is CellKind.I16:
        ok = (ndigits <= 5) & (l1 == 0) & (l2 == 0)
        v = l0  # ≤ 99999, no wrap
        return ok & ((neg & (v <= 32768)) | (~neg & (v <= 32767)))
    if kind is CellKind.I32:
        ok = (ndigits <= 10) & (l2 == 0)
        in_range = (l1 < 2) | ((l1 == 2)
                               & ((neg & (l0 <= 147_483_648))
                                  | (~neg & (l0 <= 147_483_647))))
        return ok & in_range
    if kind is CellKind.U32:
        ok = (ndigits <= 10) & (l2 == 0) & ~neg
        return ok & ((l1 < 4) | ((l1 == 4) & (l0 <= 294_967_295)))
    if kind is CellKind.I64:
        ok = ndigits <= 19
        hi = jnp.where(neg, 1, 0)  # |min| = 9223372036854775808
        at_cap = (l2 == 9) & ((l1 > 223_372_036)
                              | ((l1 == 223_372_036)
                                 & (l0 > 854_775_807 + hi)))
        return ok & ~((l2 > 9) | at_cap)
    raise AssertionError(kind)


def parse_column(kind, bmat: jax.Array, lengths: jax.Array):
    """Parse one column's gathered bytes → ({component: int32[R]}, ok[R]).
    Component names follow COLUMN_COMPONENTS[kind]."""
    if kind is CellKind.BOOL:
        t, ok = parse_bool(bmat, lengths)
        return {"v": t.astype(jnp.int32)}, ok
    if kind in (CellKind.I16, CellKind.I32, CellKind.U32):
        neg, l0, l1, l2, nd, ok = parse_int(bmat, lengths)
        ok = ok & _int_range_ok(kind, neg, l0, l1, l2, nd)
        v = l1 * jnp.int32(1_000_000_000) + l0  # wrap impossible once ok
        return {"v": jnp.where(neg, -v, v)}, ok
    if kind is CellKind.I64:
        neg, l0, l1, l2, nd, ok = parse_int(bmat, lengths)
        ok = ok & _int_range_ok(kind, neg, l0, l1, l2, nd)
        return {"neg": neg.astype(jnp.int32), "l0": l0, "l1": l1, "l2": l2}, ok
    if kind in (CellKind.F32, CellKind.F64):
        neg, l0, l1, ea, sp, ok = parse_float(bmat, lengths)
        return {"neg": neg.astype(jnp.int32), "l0": l0, "l1": l1, "ea": ea,
                "sp": sp}, ok
    if kind is CellKind.DATE:
        days, ok = parse_date(bmat, lengths)
        return {"days": days}, ok
    if kind is CellKind.TIME:
        ms, us, ok = parse_time(bmat, lengths)
        return {"ms": ms, "us": us}, ok
    if kind in (CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ):
        days, ms, us, tz, ok = parse_timestamp(
            bmat, lengths, with_tz=kind is CellKind.TIMESTAMPTZ)
        return {"days": days, "ms": ms - tz * 1000, "us": us}, ok
    raise AssertionError(kind)


def _nibble_to_ascii(code: jax.Array) -> jax.Array:
    """Symbol code → ASCII (framer.c alphabet) via select chain (no
    constant-array gather; Pallas-compatible)."""
    out = ord("0") + code  # digits 0-9
    out = jnp.where(code == 10, ord("-"), out)
    out = jnp.where(code == 11, ord("+"), out)
    out = jnp.where(code == 12, ord("."), out)
    out = jnp.where(code == 13, ord(":"), out)
    out = jnp.where(code == 14, ord(" "), out)
    out = jnp.where(code == 15, 0, out)
    return out


def unpack_nibbles(packed: jax.Array, width: int) -> jax.Array:
    """u8[R, W/2] planar nibble pairs → int32[R, W] ASCII bytes: byte k
    carries symbol k (high nibble) and symbol k + W/2 (low nibble), so
    reassembly is one lane concatenation (Mosaic-friendly — no interleave
    reshape)."""
    p = packed.astype(jnp.int32)
    hi = (p >> 4) & 0xF
    lo = p & 0xF
    return _nibble_to_ascii(jnp.concatenate([hi, lo], axis=1))
