"""Publication row-filter predicate IR + the three evaluators it drives.

PG15 publications carry a per-table WHERE clause (`pg_publication_tables.
rowfilter`) that the reference relies on the walsender to evaluate at send
time. etl_tpu compiles the same predicate into the decode program instead:
the BASELINE target puts "type coercion, publication row/column filtering,
and row→columnar transpose" inside the device kernels, so filtered rows
are compacted out IN the fused parse+pack step and never reach the HBM
output buffers or the device→host fetch link (ops/bitpack.compact_packed).
That buys two things the walsender-side filter cannot: PG14 sources (no
server-side row filters) gain filtering, and the publisher sheds the
per-row WHERE evaluation entirely (the fake source's
`server_row_filtering = False` offload mode models this deployment).

One IR, three consumers — all compiled from the same tree so they cannot
drift:

  - `CompiledRowFilter.device_keep`: jnp over the PARSED int32 components
    the decode program already has in registers (ops/parsers.parse_column
    output — identical dict shape in the row-major XLA and lane-packed
    Pallas conventions, so one evaluator serves both engines). SQL
    three-valued logic: a row is published iff the predicate evaluates
    TRUE; NULL-involved comparisons are unknown and drop the row.
  - `CompiledRowFilter.host_keep`: vectorized numpy over a decoded
    ColumnarBatch — the host-oracle reference the differential suites and
    the post-fixup re-evaluation use.
  - `RowFilter.compile_texts`: per-row python over wire-text values — the
    fake walsender's WHERE-clause evaluator and the workload generator's
    committed-truth filter.

Supported grammar (the reference's row filters allow only simple
expressions over replicated columns — transaction.rs:661): comparisons
`col {=,<>,!=,<,<=,>,>=} literal`, `col IS [NOT] NULL`, AND/OR/NOT,
parentheses. Literals: numbers, 'quoted strings' (dates/timestamps/uuids
parse per the column's type), TRUE/FALSE.

Device evaluation engages only when every referenced column is a
device-parsed kind with an exact int32-component comparison
(DEVICE_CMP_KINDS); floats/NUMERIC/text predicates fall back to
`host_keep` over the decoded batch — correct, just without the
fetch-bytes win. Compilation happens ONCE at decoder construction
(etl-lint rule 13 flags `compile_row_filter`/`parse_row_filter` inside
@hot_loop functions: a per-batch compile would re-lower the jit program
per flush).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable

import numpy as np

from ..models.pgtypes import CellKind, Oid
from ..models.schema import ReplicatedTableSchema, TableSchema

# kinds with an exact device-side comparison over parsed int32 components
DEVICE_CMP_KINDS = frozenset({
    CellKind.BOOL, CellKind.I16, CellKind.I32, CellKind.U32, CellKind.I64,
    CellKind.DATE, CellKind.TIME, CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ,
})

# representative OID per kind for literal coercion through the SAME text
# parser the decode oracle uses (postgres/codec/text.parse_cell_text), so
# a literal and a column value can never round-trip differently
_KIND_OID = {
    CellKind.BOOL: Oid.BOOL, CellKind.I16: Oid.INT2, CellKind.I32: Oid.INT4,
    CellKind.U32: Oid.OID, CellKind.I64: Oid.INT8, CellKind.F32: Oid.FLOAT4,
    CellKind.F64: Oid.FLOAT8, CellKind.NUMERIC: Oid.NUMERIC,
    CellKind.DATE: Oid.DATE, CellKind.TIME: Oid.TIME,
    CellKind.TIMESTAMP: Oid.TIMESTAMP, CellKind.TIMESTAMPTZ: Oid.TIMESTAMPTZ,
    CellKind.STRING: Oid.TEXT,
}

_CMP_OPS = ("eq", "ne", "lt", "le", "gt", "ge")
_OP_TOKEN = {"=": "eq", "<>": "ne", "!=": "ne", "<": "lt", "<=": "le",
             ">": "gt", ">=": "ge"}
_OP_SQL = {"eq": "=", "ne": "<>", "lt": "<", "le": "<=", "gt": ">",
           "ge": ">="}


class RowFilterError(ValueError):
    """Unparseable / unsupported publication row filter."""


# ---------------------------------------------------------------------------
# IR nodes
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Cmp:
    op: str  # one of _CMP_OPS
    column: str
    value: Any  # python literal (int | float | str | bool)

    def __post_init__(self):
        if self.op not in _CMP_OPS:
            raise RowFilterError(f"bad comparison op {self.op!r}")


@dataclasses.dataclass(frozen=True)
class NullTest:
    column: str
    negated: bool  # True = IS NOT NULL


@dataclasses.dataclass(frozen=True)
class And:
    items: tuple


@dataclasses.dataclass(frozen=True)
class Or:
    items: tuple


@dataclasses.dataclass(frozen=True)
class Not:
    item: Any


def _walk_columns(node, out: set) -> None:
    if isinstance(node, (Cmp, NullTest)):
        out.add(node.column)
    elif isinstance(node, (And, Or)):
        for it in node.items:
            _walk_columns(it, out)
    elif isinstance(node, Not):
        _walk_columns(node.item, out)
    else:
        raise RowFilterError(f"bad IR node {node!r}")


def _node_json(node) -> dict:
    if isinstance(node, Cmp):
        return {"cmp": node.op, "col": node.column, "value": node.value}
    if isinstance(node, NullTest):
        return {"null_test": node.column, "negated": node.negated}
    if isinstance(node, And):
        return {"and": [_node_json(i) for i in node.items]}
    if isinstance(node, Or):
        return {"or": [_node_json(i) for i in node.items]}
    if isinstance(node, Not):
        return {"not": _node_json(node.item)}
    raise RowFilterError(f"bad IR node {node!r}")


def _node_from_json(d: dict):
    if "cmp" in d:
        return Cmp(d["cmp"], d["col"], d["value"])
    if "null_test" in d:
        return NullTest(d["null_test"], bool(d.get("negated", False)))
    if "and" in d:
        return And(tuple(_node_from_json(i) for i in d["and"]))
    if "or" in d:
        return Or(tuple(_node_from_json(i) for i in d["or"]))
    if "not" in d:
        return Not(_node_from_json(d["not"]))
    raise RowFilterError(f"bad IR json {d!r}")


def _node_sql(node) -> str:
    if isinstance(node, Cmp):
        v = node.value
        if isinstance(v, bool):
            lit = "TRUE" if v else "FALSE"
        elif isinstance(v, (int, float)):
            lit = repr(v)
        else:
            lit = "'" + str(v).replace("'", "''") + "'"
        return f'"{node.column}" {_OP_SQL[node.op]} {lit}'
    if isinstance(node, NullTest):
        return f'"{node.column}" IS {"NOT " if node.negated else ""}NULL'
    if isinstance(node, And):
        return "(" + " AND ".join(_node_sql(i) for i in node.items) + ")"
    if isinstance(node, Or):
        return "(" + " OR ".join(_node_sql(i) for i in node.items) + ")"
    if isinstance(node, Not):
        return f"(NOT {_node_sql(node.item)})"
    raise RowFilterError(f"bad IR node {node!r}")


def _fingerprint(node) -> tuple:
    if isinstance(node, Cmp):
        return ("cmp", node.op, node.column, repr(node.value))
    if isinstance(node, NullTest):
        return ("null", node.column, node.negated)
    if isinstance(node, And):
        return ("and",) + tuple(_fingerprint(i) for i in node.items)
    if isinstance(node, Or):
        return ("or",) + tuple(_fingerprint(i) for i in node.items)
    if isinstance(node, Not):
        return ("not", _fingerprint(node.item))
    raise RowFilterError(f"bad IR node {node!r}")


class RowFilter:
    """The schema-attachable IR root: a predicate tree plus the SQL text it
    came from (kept for COPY WHERE pushdown and catalog round-trips).
    Hashable/immutable — it rides inside ReplicatedTableSchema and the
    decode program cache keys via `fingerprint()`."""

    __slots__ = ("root", "sql")

    def __init__(self, root, sql: str | None = None):
        _walk_columns(root, set())  # validates the tree shape
        self.root = root
        self.sql = sql if sql is not None else _node_sql(root)

    def __eq__(self, other) -> bool:
        return isinstance(other, RowFilter) \
            and self.fingerprint() == other.fingerprint()

    def __hash__(self) -> int:
        return hash(self.fingerprint())

    def __repr__(self) -> str:
        return f"RowFilter({self.sql!r})"

    def fingerprint(self) -> tuple:
        return _fingerprint(self.root)

    def referenced_columns(self) -> list[str]:
        out: set = set()
        _walk_columns(self.root, out)
        return sorted(out)

    def to_json(self) -> dict:
        return {"sql": self.sql, "ir": _node_json(self.root)}

    @classmethod
    def from_json(cls, d: dict) -> "RowFilter":
        return cls(_node_from_json(d["ir"]), d.get("sql"))

    # -- per-row python evaluators (fake walsender / workload truth) --------

    def _compile_py(self, schema: TableSchema, cell) -> Callable:
        """Shared Kleene walker over one row; `cell(row, i, oid)` returns
        the parsed python value or None (NULL)."""
        idx = {c.name: (i, c.type_oid) for i, c in enumerate(schema.columns)}
        for name in self.referenced_columns():
            if name not in idx:
                raise RowFilterError(
                    f"row filter references unknown column {name!r}")
        root = self.root

        def ev(node, row) -> "bool | None":  # Kleene: True/False/None
            if isinstance(node, NullTest):
                i, oid = idx[node.column]
                is_null = cell(row, i, oid) is None
                return (not is_null) if node.negated else is_null
            if isinstance(node, Cmp):
                i, oid = idx[node.column]
                v = cell(row, i, oid)
                if v is None:
                    return None
                kind = kind_for(oid)
                if kind in _KIND_OID:
                    # dense domain: dates/timestamps compare as
                    # days/µs, which also orders the PgSpecial values
                    # (BC, ±infinity) python objects cannot
                    from ..models.table_row import _to_dense

                    return _py_cmp(node.op, _to_dense(kind, v),
                                   _dense_literal(kind, node.value))
                return _py_cmp(node.op, v,
                               _coerce_literal(node.value, kind, oid))
            if isinstance(node, And):
                vals = [ev(i2, row) for i2 in node.items]
                if any(v is False for v in vals):
                    return False
                return None if any(v is None for v in vals) else True
            if isinstance(node, Or):
                vals = [ev(i2, row) for i2 in node.items]
                if any(v is True for v in vals):
                    return True
                return None if any(v is None for v in vals) else False
            if isinstance(node, Not):
                v = ev(node.item, row)
                return None if v is None else (not v)
            raise RowFilterError(f"bad IR node {node!r}")

        def allows(row) -> bool:
            return ev(root, row) is True

        return allows

    def compile_texts(self, schema: TableSchema) -> Callable:
        """Per-row evaluator over the table's FULL column order in wire
        text form (the shape FakeDatabase row filters receive). Values
        parse through the oracle text codec, so the verdicts are exactly
        the host_keep/device_keep verdicts."""
        from ..postgres.codec.text import parse_cell_text

        def cell(row, i, oid):
            text = row[i]
            return None if text is None else parse_cell_text(text, oid)

        return self._compile_py(schema, cell)

    def compile_values(self, schema: TableSchema) -> Callable:
        """Per-row evaluator over ALREADY-DECODED python values (the
        parse_cell_text domain) — the reference-consumer form the
        differential suites cross-check delivery against."""
        def cell(row, i, oid):
            return row[i]

        return self._compile_py(schema, cell)


def kind_for(oid: int) -> CellKind:
    from ..models.pgtypes import kind_for_oid

    return kind_for_oid(oid)


# ---------------------------------------------------------------------------
# SQL-subset parser
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"\s*(?:"
    r"(?P<num>-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)"
    r"|(?P<str>'(?:[^']|'')*')"
    r"|(?P<qid>\"(?:[^\"]|\"\")*\")"
    r"|(?P<op><=|>=|<>|!=|=|<|>)"
    r"|(?P<lp>\()|(?P<rp>\))"
    r"|(?P<word>[A-Za-z_][A-Za-z_0-9$]*)"
    r")")


def _tokenize(sql: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            if sql[pos:].strip() == "":
                break
            raise RowFilterError(f"cannot tokenize row filter at {sql[pos:]!r}")
        pos = m.end()
        for name in ("num", "str", "qid", "op", "lp", "rp", "word"):
            v = m.group(name)
            if v is not None:
                out.append((name, v))
                break
    return out


def parse_row_filter(sql: str) -> RowFilter:
    """Parse a publication row filter's SQL text into the IR. Raises
    RowFilterError on anything outside the supported subset — callers
    treat that as "no client-side filter" (the server may still filter).
    PG wraps catalog rowfilter text in parens; they parse transparently."""
    toks = _tokenize(sql)
    pos = [0]

    def peek():
        return toks[pos[0]] if pos[0] < len(toks) else (None, None)

    def take():
        t = peek()
        pos[0] += 1
        return t

    def is_word(t, w):
        return t[0] == "word" and t[1].upper() == w

    def parse_or():
        items = [parse_and()]
        while is_word(peek(), "OR"):
            take()
            items.append(parse_and())
        return items[0] if len(items) == 1 else Or(tuple(items))

    def parse_and():
        items = [parse_not()]
        while is_word(peek(), "AND"):
            take()
            items.append(parse_not())
        return items[0] if len(items) == 1 else And(tuple(items))

    def parse_not():
        if is_word(peek(), "NOT"):
            take()
            return Not(parse_not())
        return parse_primary()

    def parse_literal():
        kind, v = take()
        if kind == "num":
            return float(v) if ("." in v or "e" in v.lower()) else int(v)
        if kind == "str":
            return v[1:-1].replace("''", "'")
        if kind == "word":
            u = v.upper()
            if u == "TRUE":
                return True
            if u == "FALSE":
                return False
        raise RowFilterError(f"expected literal, got {v!r}")

    def parse_primary():
        kind, v = take()
        if kind == "lp":
            inner = parse_or()
            if take()[0] != "rp":
                raise RowFilterError("unbalanced parens in row filter")
            return inner
        if kind == "qid":
            col = v[1:-1].replace('""', '"')
        elif kind == "word":
            if v.upper() in ("TRUE", "FALSE", "NOT", "AND", "OR"):
                raise RowFilterError(f"unsupported expression at {v!r}")
            col = v
        else:
            raise RowFilterError(f"expected column reference, got {v!r}")
        nkind, nv = peek()
        if nkind == "word" and nv.upper() == "IS":
            take()
            negated = False
            if is_word(peek(), "NOT"):
                take()
                negated = True
            if not is_word(peek(), "NULL"):
                raise RowFilterError("expected NULL after IS [NOT]")
            take()
            return NullTest(col, negated)
        if nkind != "op":
            raise RowFilterError(f"expected operator after column {col!r}")
        take()
        return Cmp(_OP_TOKEN[nv], col, parse_literal())

    root = parse_or()
    if pos[0] != len(toks):
        raise RowFilterError(
            f"trailing tokens in row filter: {toks[pos[0]:]!r}")
    return RowFilter(root, sql)


# ---------------------------------------------------------------------------
# literal coercion (shared by every evaluator)
# ---------------------------------------------------------------------------


def _coerce_literal(value: Any, kind: CellKind, oid: int) -> Any:
    """Literal → the python value domain parse_cell_text produces for the
    column, so comparisons run same-typed."""
    from ..postgres.codec.text import parse_cell_text

    if kind is CellKind.BOOL:
        if isinstance(value, bool):
            return value
        return parse_cell_text(str(value), oid)
    if kind in (CellKind.I16, CellKind.I32, CellKind.U32, CellKind.I64):
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return parse_cell_text(str(value), oid)
        if isinstance(value, float) and value != int(value):
            raise RowFilterError(
                f"non-integral literal {value!r} for integer column")
        return int(value)
    if kind in (CellKind.F32, CellKind.F64):
        return float(value)
    if isinstance(value, str):
        return parse_cell_text(value, oid)
    return parse_cell_text(str(value), oid)


def _py_cmp(op: str, a: Any, b: Any) -> bool:
    if op == "eq":
        return a == b
    if op == "ne":
        return a != b
    if op == "lt":
        return a < b
    if op == "le":
        return a <= b
    if op == "gt":
        return a > b
    return a >= b


def _dense_literal(kind: CellKind, value: Any) -> "int | float | bool":
    """Literal in the DENSE domain (what Column.data and the device
    components encode): days for DATE, µs for TIME/TIMESTAMP[TZ]."""
    from ..models.table_row import _to_dense

    oid = _KIND_OID[kind]
    return _to_dense(kind, _coerce_literal(value, kind, oid))


# ---------------------------------------------------------------------------
# compiled form: device + host evaluators for one schema
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _ColBinding:
    index: int  # position among replicated columns
    kind: CellKind
    oid: int


class CompiledRowFilter:
    """One RowFilter bound to one schema's replicated-column view.
    Compiled ONCE at DeviceDecoder construction (never per batch —
    etl-lint rule 13 enforces this): binding resolves column names to
    replicated indices, coerces every literal, and decides device
    eligibility, so the per-batch work is pure array math."""

    __slots__ = ("filter", "cols", "device_supported", "_root")

    def __init__(self, rf: RowFilter, schema: ReplicatedTableSchema):
        self.filter = rf
        by_name = {c.name: _ColBinding(i, c.kind, c.type_oid)
                   for i, c in enumerate(schema.replicated_columns)}
        cols: dict[str, _ColBinding] = {}
        for name in rf.referenced_columns():
            b = by_name.get(name)
            if b is None:
                raise RowFilterError(
                    f"row filter references column {name!r} absent from "
                    f"the replicated view of {schema.name}")
            cols[name] = b
        self.cols = cols
        self._root = rf.root
        # EVERY literal must coerce NOW, through the same path its
        # evaluator will use — a PG-valid filter the client codec cannot
        # represent ('v > 0.5' on an int column, an ISO-'T' timestamp
        # literal) must fail HERE as RowFilterError, so the decoder's
        # construction-time catch degrades to unfiltered decode with a
        # loud warning instead of raising per batch inside host_keep or
        # dying with an uncaught codec error
        try:
            self._walk_literals(rf.root)
        except RowFilterError:
            raise
        except Exception as e:  # parse_cell_text raises EtlError etc.
            raise RowFilterError(
                f"row filter literal outside the client envelope: {e}") \
                from e
        self.device_supported = all(b.kind in DEVICE_CMP_KINDS
                                    for b in cols.values())

    def _walk_literals(self, node) -> None:
        if isinstance(node, Cmp):
            b = self.cols[node.column]
            if b.kind in _KIND_OID:
                _dense_literal(b.kind, node.value)
                _coerce_literal(node.value, b.kind, _KIND_OID[b.kind])
        elif isinstance(node, (And, Or)):
            for i in node.items:
                self._walk_literals(i)
        elif isinstance(node, Not):
            self._walk_literals(node.item)

    def fingerprint(self) -> tuple:
        return self.filter.fingerprint()

    @property
    def referenced_indices(self) -> tuple[int, ...]:
        return tuple(sorted(b.index for b in self.cols.values()))

    # -- device evaluator ----------------------------------------------------

    def device_keep(self, colmap: dict, row_flags):
        """keep mask for the fused device program.

        `colmap`: replicated column index → (comps dict, ok bool[R],
        is_null bool[R]) for every referenced column — the SAME parsed
        int32 component vectors both the XLA and the lane-packed Pallas
        conventions produce, so one evaluator serves both engines.
        `row_flags`: int32[R] — 0 dead (bucket/mesh padding), 1 live,
        2 live + host-side force-keep (escapes / nibble-flagged /
        oversized or TOASTed referenced field: the device values are
        untrustworthy, the host re-evaluates after oracle fixup).

        keep = live & (TRUE | force-keep | not-device-evaluable); rows the
        device cannot judge are conservatively kept and re-judged on host.
        """
        import jax.numpy as jnp

        t, f = self._dev_node(self._root, colmap)
        unevaluable = None
        for b in self.cols.values():
            comps, ok, is_null = colmap[b.index]
            bad = (~ok) & (~is_null)
            unevaluable = bad if unevaluable is None else (unevaluable | bad)
        live = row_flags > 0
        force = row_flags > 1
        keep = t | force
        if unevaluable is not None:
            keep = keep | unevaluable
        return keep & live

    def _dev_node(self, node, colmap):
        """(is_true, is_false) bool[R] pair — Kleene three-valued logic;
        neither set = unknown (a NULL-involved comparison)."""
        import jax.numpy as jnp

        if isinstance(node, NullTest):
            _, _, is_null = colmap[self.cols[node.column].index]
            t = (~is_null) if node.negated else is_null
            return t, ~t
        if isinstance(node, Cmp):
            b = self.cols[node.column]
            comps, ok, is_null = colmap[b.index]
            res = _device_cmp(b.kind, node.op, comps,
                              _dense_literal(b.kind, node.value))
            known = ~is_null
            return known & res, known & ~res
        if isinstance(node, And):
            ts, fs = zip(*(self._dev_node(i, colmap) for i in node.items))
            t = ts[0]
            for x in ts[1:]:
                t = t & x
            f = fs[0]
            for x in fs[1:]:
                f = f | x
            return t, f
        if isinstance(node, Or):
            ts, fs = zip(*(self._dev_node(i, colmap) for i in node.items))
            t = ts[0]
            for x in ts[1:]:
                t = t | x
            f = fs[0]
            for x in fs[1:]:
                f = f & x
            return t, f
        if isinstance(node, Not):
            t, f = self._dev_node(node.item, colmap)
            return f, t
        raise RowFilterError(f"bad IR node {node!r}")

    # -- host evaluator ------------------------------------------------------

    def host_keep(self, batch) -> np.ndarray:
        """keep bool[n] over a decoded ColumnarBatch — the oracle the
        device path must agree with bit-for-bit on evaluable rows. Dense
        referenced columns compare vectorized in the dense domain;
        object/Arrow columns (NUMERIC/text/uuid/…) fall back to per-row
        python over parse-exact values. TOAST-unchanged referenced cells
        keep the row (the value is unknowable client-side; only non-insert
        streams can carry them and those are not filtered client-side)."""
        n = batch.num_rows
        t, f = self._host_node(self._root, batch, n)
        keep = t
        toast_any = None
        for b in self.cols.values():
            c = batch.columns[b.index]
            if c.toast_unchanged is not None:
                toast_any = c.toast_unchanged if toast_any is None \
                    else (toast_any | c.toast_unchanged)
        if toast_any is not None:
            keep = keep | toast_any
        return keep

    def _host_values(self, b: _ColBinding, batch, n: int):
        """(comparable value array/list, present bool[n])."""
        c = batch.columns[b.index]
        present = np.asarray(c.validity[:n], dtype=bool)
        if c.toast_unchanged is not None:
            present = present & ~np.asarray(c.toast_unchanged[:n], dtype=bool)
        if c.is_dense:
            return np.asarray(c.data[:n]), present
        vals = [c.value(i) if present[i] else None for i in range(n)]
        return vals, present

    def _host_node(self, node, batch, n: int):
        if isinstance(node, NullTest):
            b = self.cols[node.column]
            _, present = self._host_values(b, batch, n)
            t = ~present if not node.negated else present
            return t, ~t
        if isinstance(node, Cmp):
            b = self.cols[node.column]
            vals, present = self._host_node_cmp_inputs(b, batch, n)
            if isinstance(vals, np.ndarray):
                lit = _dense_literal(b.kind, node.value)
                with np.errstate(invalid="ignore"):
                    res = _np_cmp(node.op, vals, lit)
            else:
                lit = _coerce_literal(node.value, b.kind, _KIND_OID[b.kind]) \
                    if b.kind in _KIND_OID else node.value
                res = np.fromiter(
                    (bool(_py_cmp(node.op, v, lit)) if v is not None
                     else False for v in vals), dtype=bool, count=n)
            return present & res, present & ~res
        if isinstance(node, And):
            ts, fs = zip(*(self._host_node(i, batch, n) for i in node.items))
            return np.logical_and.reduce(ts), np.logical_or.reduce(fs)
        if isinstance(node, Or):
            ts, fs = zip(*(self._host_node(i, batch, n) for i in node.items))
            return np.logical_or.reduce(ts), np.logical_and.reduce(fs)
        if isinstance(node, Not):
            t, f = self._host_node(node.item, batch, n)
            return f, t
        raise RowFilterError(f"bad IR node {node!r}")

    def _host_node_cmp_inputs(self, b: _ColBinding, batch, n: int):
        return self._host_values(b, batch, n)


def _np_cmp(op: str, a: np.ndarray, b) -> np.ndarray:
    if op == "eq":
        return a == b
    if op == "ne":
        return a != b
    if op == "lt":
        return a < b
    if op == "le":
        return a <= b
    if op == "gt":
        return a > b
    return a >= b


# -- device comparisons per kind --------------------------------------------


def _limbs_of(mag: int) -> tuple[int, int, int]:
    return mag % 10**9, (mag // 10**9) % 10**9, mag // 10**18


def _lex3(gt_hi, eq_hi, gt_mid, eq_mid, gt_lo):
    """a > b over a 3-component lexicographic compare, given per-component
    gt/eq masks (hi → lo)."""
    return gt_hi | (eq_hi & (gt_mid | (eq_mid & gt_lo)))


def _device_cmp(kind: CellKind, op: str, comps: dict, lit):
    """Exact comparison of parsed device components against a dense-domain
    literal, int32-safe (multi-word values compare limb-lexicographic —
    combining first would overflow int32)."""
    import jax.numpy as jnp

    if op == "ne":
        return ~_device_cmp(kind, "eq", comps, lit)
    if op == "le":
        return ~_device_cmp(kind, "gt", comps, lit)
    if op == "ge":
        return ~_device_cmp(kind, "lt", comps, lit)

    if kind is CellKind.BOOL:
        v = comps["v"]
        b = jnp.int32(1 if lit else 0)
        if op == "eq":
            return v == b
        if op == "lt":
            return v < b
        return v > b
    if kind is CellKind.U32:
        # the parsed component wraps uint32 values into int32; compare in
        # sign-flipped space (a <u b  ⇔  (a ^ 2^31) <s (b ^ 2^31))
        v = comps["v"]
        lit = int(lit)
        if lit < 0 or lit > 2**32 - 1:
            return _const_mask(v, op, lit, lit > 0)
        biased = (lit ^ 0x8000_0000) & 0xFFFF_FFFF
        b = jnp.int32(biased - 2**32 if biased >= 2**31 else biased)
        vb = v ^ jnp.int32(-2**31)
        if op == "eq":
            return vb == b
        if op == "lt":
            return vb < b
        return vb > b
    if kind in (CellKind.I16, CellKind.I32, CellKind.DATE):
        v = comps["v"] if kind is not CellKind.DATE else comps["days"]
        lit = int(lit)
        # constant-fold literals outside the kind's representable range
        # (int32 compare would wrap): v < 10**12 is simply always true
        info_lo, info_hi = -(2**31), 2**31 - 1
        if lit < info_lo or lit > info_hi:
            return _const_mask(v, op, lit, lit > 0)
        b = jnp.int32(lit)
        if op == "eq":
            return v == b
        if op == "lt":
            return v < b
        return v > b
    if kind is CellKind.I64:
        neg = comps["neg"] > 0
        l0, l1, l2 = comps["l0"], comps["l1"], comps["l2"]
        nonzero = (l0 > 0) | (l1 > 0) | (l2 > 0)
        sign_neg = neg & nonzero  # "-0" is 0
        lit = int(lit)
        lneg = lit < 0
        c0, c1, c2 = _limbs_of(abs(lit))
        if c2 >= 10**9:
            # |literal| beyond any parseable int8 text — constant fold
            return _const_mask(l0, op, lit, not lneg)
        c0, c1, c2 = (jnp.int32(c0), jnp.int32(c1), jnp.int32(c2))
        mag_eq = (l0 == c0) & (l1 == c1) & (l2 == c2)
        mag_gt = _lex3(l2 > c2, l2 == c2, l1 > c1, l1 == c1, l0 > c0)
        if op == "eq":
            return mag_eq & (sign_neg == lneg)
        # value > lit
        if op == "gt":
            if lneg:
                return (~sign_neg) | (sign_neg & ~mag_gt & ~mag_eq)
            return (~sign_neg) & mag_gt
        # value < lit
        if lneg:
            return sign_neg & mag_gt
        return sign_neg | ((~sign_neg) & ~mag_gt & ~mag_eq)
    if kind is CellKind.TIME:
        ms, us = comps["ms"], comps["us"]
        lit = int(lit)
        lms, lus = lit // 1000, lit % 1000
        lms_j, lus_j = jnp.int32(lms), jnp.int32(lus)
        if op == "eq":
            return (ms == lms_j) & (us == lus_j)
        if op == "gt":
            return (ms > lms_j) | ((ms == lms_j) & (us > lus_j))
        return (ms < lms_j) | ((ms == lms_j) & (us < lus_j))
    if kind in (CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ):
        days, ms, us = comps["days"], comps["ms"], comps["us"]
        # tz folding can push ms out of [0, 86_400_000); one borrow/carry
        # renormalizes (|tz| ≤ 16h < 1 day)
        day_ms = 86_400_000
        borrow = ms < 0
        carry = ms >= day_ms
        days_n = days - borrow.astype(jnp.int32) + carry.astype(jnp.int32)
        ms_n = ms + jnp.where(borrow, day_ms, 0) - jnp.where(carry, day_ms, 0)
        lit = int(lit)
        ld, rem = divmod(lit, 86_400_000_000)
        lms, lus = rem // 1000, rem % 1000
        if abs(ld) > 4_000_000:  # beyond any parseable date
            return _const_mask(days, op, lit, ld > 0)
        ld_j, lms_j, lus_j = jnp.int32(ld), jnp.int32(lms), jnp.int32(lus)
        eq = (days_n == ld_j) & (ms_n == lms_j) & (us == lus_j)
        gt = _lex3(days_n > ld_j, days_n == ld_j, ms_n > lms_j,
                   ms_n == lms_j, us > lus_j)
        if op == "eq":
            return eq
        if op == "gt":
            return gt
        return ~gt & ~eq
    raise RowFilterError(f"kind {kind} has no device comparison")


def _const_mask(ref, op: str, lit, lit_is_big_positive: bool):
    """Comparison against a literal no in-range value can reach: fold to a
    constant mask of the right shape."""
    import jax.numpy as jnp

    if op == "eq":
        return jnp.zeros_like(ref, dtype=bool)
    # lit far above every value: v < lit true, v > lit false (and mirrored)
    if lit_is_big_positive:
        val = op == "lt"
    else:
        val = op == "gt"
    return jnp.full_like(ref, val, dtype=bool)


# ---------------------------------------------------------------------------
# convenience
# ---------------------------------------------------------------------------


def compile_row_filter(rf: "RowFilter | str",
                       schema: ReplicatedTableSchema) -> CompiledRowFilter:
    """Bind a RowFilter (or its SQL text) to a schema. Call at decoder
    construction only — never per batch (etl-lint rule 13)."""
    if isinstance(rf, str):
        rf = parse_row_filter(rf)
    return CompiledRowFilter(rf, schema)
