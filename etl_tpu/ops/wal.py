"""WAL batch staging: framed pgoutput messages → device-ready StagedBatch.

The zero-copy pipeline: XLogData payloads are concatenated once into a
single buffer; the native framer (etl_tpu/native) emits absolute field
offsets into that buffer; this module groups rows numpy-vectorized and the
whole buffer ships to the device for decode. Non-row messages
(Begin/Commit/Relation/Truncate/Message) are returned by index for the
host apply loop to decode with the CPU codec (they are rare and carry
control semantics).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..models.errors import ErrorKind, EtlError
from ..models.event import ChangeType
from ..native import (FLAG_BINARY, FLAG_NULL, FLAG_TOAST, FramedBatch,
                      frame_pgoutput)
from .staging import StagedBatch, bucket_rows


@dataclass
class WalBatch:
    """One framed batch of row changes for a single table."""

    staged: StagedBatch  # per row: new tuple (I/U) or old/key tuple (D)
    change_types: np.ndarray  # uint8[n] of ChangeType
    msg_index: np.ndarray  # int64[n] — original message index of each row
    old_staged: StagedBatch | None  # old/key tuples for U rows that sent one
    old_rows: np.ndarray  # int64[k] — row indices old_staged corresponds to
    old_is_key: np.ndarray  # bool[k] — True: 'K' key tuple, False: 'O' full
    delete_is_key: np.ndarray  # bool[n] — DELETE row i carried a 'K' tuple
    non_row_indices: np.ndarray  # int64[] messages for host decode
    relids: np.ndarray  # int32[n] per-row relation oid
    bad_from: int  # -1, or first malformed message index (rest unframed)


def _staged_from(framed: FramedBatch, rows: np.ndarray, off: np.ndarray,
                 ln: np.ndarray, flag: np.ndarray) -> StagedBatch:
    n = len(rows)
    cap = bucket_rows(n) if n else 0
    n_cols = off.shape[1]
    contiguous = n > 0 and int(rows[-1]) - int(rows[0]) == n - 1
    if contiguous and cap == n:
        # common fast path (a full bucket of row messages): slice views
        # into the framed arrays, no copies
        lo, hi = int(rows[0]), int(rows[0]) + n
        f = flag[lo:hi]
        return StagedBatch(framed.buf, off[lo:hi], ln[lo:hi],
                           f == FLAG_NULL, f == FLAG_TOAST, n,
                           cpu_fallback_rows=_binary_fallback(f))
    offsets = np.zeros((cap, n_cols), dtype=np.int32)
    lengths = np.zeros((cap, n_cols), dtype=np.int32)
    nulls = np.ones((cap, n_cols), dtype=np.bool_)
    toast = np.zeros((cap, n_cols), dtype=np.bool_)
    fallback = np.zeros(0, dtype=np.int64)
    if n:
        src = slice(int(rows[0]), int(rows[0]) + n) if contiguous else rows
        offsets[:n] = off[src]
        lengths[:n] = ln[src]
        f = flag[src]
        nulls[:n] = f == FLAG_NULL
        toast[:n] = f == FLAG_TOAST
        fallback = _binary_fallback(f)
    return StagedBatch(framed.buf, offsets, lengths, nulls, toast, n,
                       cpu_fallback_rows=fallback)


def _binary_fallback(flags: np.ndarray) -> np.ndarray:
    if (flags == FLAG_BINARY).any():
        # binary tuple format is never requested; decoding it as text (in
        # either the device or the CPU-fixup path) would corrupt values
        raise EtlError(ErrorKind.UNSUPPORTED_TYPE,
                       "binary tuple format not enabled in START_REPLICATION")
    return np.zeros(0, dtype=np.int64)


def stage_wal_batch(buf: bytes | np.ndarray, msg_off: np.ndarray,
                    msg_len: np.ndarray, n_cols: int) -> WalBatch:
    """Frame and stage one batch of pgoutput messages (single-table run —
    the apply loop splits runs at relation boundaries, mirroring the
    reference's per-table batching between barriers,
    bigquery/core.rs:956-978)."""
    framed, bad = frame_pgoutput(buf, msg_off, msg_len, n_cols)
    n_msgs = framed.n_msgs
    upto = n_msgs if bad < 0 else bad
    kind = framed.kind[:upto]
    is_i = kind == ord("I")
    is_u = kind == ord("U")
    is_d = kind == ord("D")
    is_row = is_i | is_u | is_d
    row_idx = np.flatnonzero(is_row)
    non_row = np.flatnonzero(~is_row & (kind != 0))

    change = np.empty(len(row_idx), dtype=np.uint8)
    change[is_i[row_idx]] = ChangeType.INSERT
    change[is_u[row_idx]] = ChangeType.UPDATE
    change[is_d[row_idx]] = ChangeType.DELETE

    # main tuple: new for I/U, old for D (no copies when the batch has no
    # deletes — the common insert/update-heavy case)
    d_rows = np.flatnonzero(is_d)
    if len(d_rows):
        off = framed.new_off.copy()
        ln = framed.new_len.copy()
        fl = framed.new_flag.copy()
        off[d_rows] = framed.old_off[d_rows]
        ln[d_rows] = framed.old_len[d_rows]
        fl[d_rows] = framed.old_flag[d_rows]
    else:
        off, ln, fl = framed.new_off, framed.new_len, framed.new_flag
    staged = _staged_from(framed, row_idx, off, ln, fl)

    # old tuples for updates that sent one
    u_with_old = np.flatnonzero(is_u & (framed.old_kind[:upto] != 0))
    if len(u_with_old):
        old_staged = _staged_from(framed, u_with_old, framed.old_off,
                                  framed.old_len, framed.old_flag)
        # map message index → row position
        msg_to_row = np.full(upto, -1, dtype=np.int64)
        msg_to_row[row_idx] = np.arange(len(row_idx))
        old_rows = msg_to_row[u_with_old]
        old_is_key = framed.old_kind[u_with_old] == ord("K")
    else:
        old_staged = None
        old_rows = np.zeros(0, dtype=np.int64)
        old_is_key = np.zeros(0, dtype=np.bool_)

    delete_is_key = is_d[row_idx] & (framed.old_kind[row_idx] == ord("K"))

    return WalBatch(
        staged=staged, change_types=change,
        msg_index=row_idx.astype(np.int64), old_staged=old_staged,
        old_rows=old_rows, old_is_key=old_is_key,
        delete_is_key=delete_is_key,
        non_row_indices=non_row.astype(np.int64),
        relids=framed.relid[row_idx], bad_from=bad)


def concat_payloads(payloads: list[bytes]) -> tuple[bytes, np.ndarray, np.ndarray]:
    """Concatenate message payloads, returning (buf, msg_off, msg_len)."""
    lens = np.fromiter((len(p) for p in payloads), dtype=np.int32,
                       count=len(payloads))
    offs = np.zeros(len(payloads), dtype=np.int64)
    np.cumsum(lens[:-1], out=offs[1:])
    return b"".join(payloads), offs, lens
