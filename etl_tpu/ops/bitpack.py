"""Bit-packed device→host result transport.

The device link is latency- and fetch-bandwidth-bound (~40 MB/s out of the
chip vs ~1.5 GB/s in, measured on the target), so the decode program's
output layout is the binding resource of the whole pipeline. Instead of one
int32 lane per parsed component (16 B/row for a 3-int column schema), each
row's components are packed into the fewest 32-bit words that their
*maximum possible magnitudes* allow — and those maxima are known on the
host before dispatch, because a decimal field of `d` text characters can
encode at most `10^d - 1`: the per-column byte widths the host already
computes for the gather bound every component's bit width statically.

Layout (per row): for each dense column in spec order — 1 ok bit, then
each nonzero-width component (parsers.COLUMN_COMPONENTS order), signed
components zigzag-encoded. Fields straddle word boundaries; total width
rounds up to whole uint32 words. The device emits `uint32[n_words, R]`
(one fetch), the host unpacks with vectorized shifts — a few numpy ops per
component.

Components whose width bound is 0 bits (e.g. the high limb of a bigint
column whose longest text is 9 chars) are omitted entirely and substituted
as zeros on the host.

Reference parity note: the reference returns parsed values in-process
(codec/text.rs), so it has no transport layer to compare; this module is
where the TPU build pays for — and wins back — the host↔device boundary.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..models.pgtypes import CellKind
from . import parsers

# hard magnitude caps per (kind, component): the parser's ok-check bounds
# the value independently of text width (e.g. an I32 is range-checked), so
# bits never exceed these even for huge gather widths
_DAYS_ZZ_BITS = 23  # year 1..9999 → days ∈ [-719162, 2932896]; zigzag max
#                     = 5,865,792 < 2^23 — 22 bits would corrupt late dates
_MS_BITS = 27  # 0..86_399_999 ms of day
_MS_TZ_ZZ_BITS = 29  # ms shifted by ±16h tz → zigzag
_US_BITS = 10  # 0..999


def _zz_bits(vmax: int) -> int:
    """Bits for zigzag(v), |v| ≤ vmax (zigzag(-m) = 2m-1, zigzag(m) = 2m)."""
    return max(1, (2 * vmax).bit_length())


def _dec_bits(digits: int) -> int:
    """Bits for a non-negative decimal of `digits` chars."""
    if digits <= 0:
        return 0
    return (10**digits - 1).bit_length()


def component_bits(kind: CellKind, comp: str, width: int) -> tuple[int, bool]:
    """(bits, zigzag) for one component given the column's max text width.
    bits == 0 means the component is statically zero and is not packed."""
    d = width  # max text chars ⇒ max decimal digits (sign char only shrinks)
    if kind is CellKind.BOOL:
        return 1, False
    if kind is CellKind.I16:
        return min(_zz_bits(10**min(d, 5) - 1), _zz_bits(32768)), True
    if kind is CellKind.I32:
        if d >= 10:
            return 32, True  # zigzag(int32) always fits 32 bits
        return _zz_bits(10**d - 1), True
    if kind is CellKind.U32:
        return min(_dec_bits(d), 32), False
    if kind is CellKind.I64:
        if comp == "neg":
            return 1, False
        if comp == "l0":
            return _dec_bits(min(d, 9)), False
        if comp == "l1":
            return _dec_bits(min(max(d - 9, 0), 9)), False
        if comp == "l2":
            # ok requires ≤ 19 digits ⇒ top limb ≤ 9
            return (4 if d > 18 else 0), False
    if kind in (CellKind.F32, CellKind.F64):
        if comp == "neg":
            return 1, False
        if comp == "l0":
            return _dec_bits(min(d, 9)), False
        if comp == "l1":
            # mantissa digit count is capped by the parser's fast path (18);
            # limb1 holds digits 9..17 from the right
            return _dec_bits(min(max(d - 9, 0), 9)), False
        if comp == "ea":
            return _zz_bits(22), True  # |exp_adj| ≤ 22 when ok
        if comp == "sp":
            return 2, False
    if kind is CellKind.DATE:
        return _DAYS_ZZ_BITS, True
    if kind is CellKind.TIME:
        return (_MS_BITS, False) if comp == "ms" else (_US_BITS, False)
    if kind is CellKind.TIMESTAMP:
        if comp == "days":
            return _DAYS_ZZ_BITS, True
        return (_MS_BITS, False) if comp == "ms" else (_US_BITS, False)
    if kind is CellKind.TIMESTAMPTZ:
        if comp == "days":
            return _DAYS_ZZ_BITS, True
        return (_MS_TZ_ZZ_BITS, True) if comp == "ms" else (_US_BITS, False)
    raise AssertionError((kind, comp))


def saturation_width(kind: CellKind) -> int:
    """Text width beyond which the layout stops changing — bit widths are
    clamped here so drifting field lengths (e.g. suppressed trailing
    fractional-second zeros) don't multiply jit signatures for programs
    that would lower identically."""
    if kind is CellKind.BOOL:
        return 1
    if kind in (CellKind.DATE, CellKind.TIME, CellKind.TIMESTAMP,
                CellKind.TIMESTAMPTZ):
        return 1  # layout is fixed for these kinds
    if kind is CellKind.I16:
        return 5
    if kind in (CellKind.I32, CellKind.U32):
        return 10
    if kind is CellKind.I64:
        return 19
    if kind in (CellKind.F32, CellKind.F64):
        return 18
    raise AssertionError(kind)


@dataclasses.dataclass(frozen=True)
class FieldSlot:
    comp: str  # component name, or "ok"
    bit_off: int
    bits: int
    zigzag: bool


@dataclasses.dataclass(frozen=True)
class BitLayout:
    """Static packing plan for one (specs, widths) signature."""

    slots: tuple[tuple[FieldSlot, ...], ...]  # per dense column
    n_words: int
    kinds: tuple[CellKind, ...]

    @property
    def total_bits(self) -> int:
        return sum(s.bits for col in self.slots for s in col)


def layout_for_specs(specs: tuple[tuple[int, CellKind, int, int], ...]
                     ) -> BitLayout:
    """THE projection from engine 4-tuple specs (col, kind, gather_width,
    bit_width) to the packed layout. Every site that touches the packed
    words — the XLA program, the Pallas kernel, the host completion, the
    driver entry — must derive the layout through this one function;
    disagreement silently misreads columns."""
    return build_layout(tuple((i, k, bw) for i, k, _, bw in specs))


def build_layout(specs: tuple[tuple[int, CellKind, int], ...]) -> BitLayout:
    """specs: (col_index, kind, max_text_width) per dense column — the same
    tuple that keys the jit cache, so the layout is static per program."""
    cols: list[tuple[FieldSlot, ...]] = []
    off = 0
    for _, kind, width in specs:
        slots = [FieldSlot("ok", off, 1, False)]
        off += 1
        for comp in parsers.COLUMN_COMPONENTS[kind]:
            bits, zz = component_bits(kind, comp, width)
            if bits == 0:
                continue
            slots.append(FieldSlot(comp, off, bits, zz))
            off += bits
        cols.append(tuple(slots))
    return BitLayout(tuple(cols), max(1, -(-off // 32)),
                     tuple(k for _, k, _ in specs))


def pack_device(layout: BitLayout, columns) -> jnp.ndarray:
    """Pack per-column (ok, comps) into uint32[n_words, R] on device.

    `columns`: list aligned with layout.slots of (ok_bool[R], comps dict
    name→int32[R]). Pure elementwise uint32 shifts/ors — fuses into the
    parse program, nothing extra materializes in HBM.
    """
    R = columns[0][0].shape[0]
    words = [jnp.zeros(R, dtype=jnp.uint32) for _ in range(layout.n_words)]
    for (ok, comps), slots in zip(columns, layout.slots):
        for s in slots:
            if s.comp == "ok":
                v = ok.astype(jnp.uint32)
            else:
                raw = comps[s.comp].astype(jnp.int32)
                if s.zigzag:
                    raw = (raw << 1) ^ (raw >> 31)
                v = raw.astype(jnp.uint32)
            if s.bits < 32:
                v = v & jnp.uint32((1 << s.bits) - 1)
            w, sh = divmod(s.bit_off, 32)
            words[w] = words[w] | (v << sh)
            if sh + s.bits > 32:
                words[w + 1] = words[w + 1] | (v >> (32 - sh))
    return jnp.stack(words, axis=0)


def compact_packed(words, keep, n_shards: int):
    """In-program row compaction: scatter the kept rows of
    `words` uint32[n_words, R] to the FRONT of their shard block via an
    exclusive prefix sum over the keep mask — filtered rows never reach
    the HBM output buffer positions the host fetches.

    Shard-local by construction: rows reshape to [n_shards, R/n_shards]
    exactly along the mesh's block sharding, the cumsum runs inside each
    shard, and every kept row's destination stays inside its own block —
    zero cross-device collectives on the forward path, matching the
    unfiltered program's contract. Single-device callers pass n_shards=1
    (one global block).

    Returns (words_compacted, keep_mask uint32[⌈R/32⌉] — the keep bits
    packed 32/word, little bit order, counts int32[n_shards]). The host
    reconstructs survivor row indices from the mask (compaction is
    stable, so survivors are exactly the set bit positions in ascending
    order) at 1 BIT per staged row of fetch — against 32 bits a rowid
    vector would cost. On a single device the words fetch is then sized
    to the survivor count (engine._complete_filtered): fetched bytes
    scale with selectivity, not batch size."""
    R = keep.shape[0]
    rps = R // n_shards
    k2 = keep.astype(jnp.int32).reshape(n_shards, rps)
    pos = jnp.cumsum(k2, axis=1) - k2  # exclusive prefix sum, shard-local
    counts = k2.sum(axis=1, dtype=jnp.int32)
    # dropped rows scatter to index rps, which mode="drop" discards. The
    # scatter is BATCHED per shard block (vmap over the leading shard
    # axis) with block-LOCAL destination indices: GSPMD partitions the
    # batched scatter along 'sp' with no communication. The previous
    # formulation scattered through a single GLOBAL dest vector, which
    # the partitioner could not prove block-diagonal — it all-gathered
    # the full words array around the scatter on every mesh dispatch
    # (caught by the etl-lint ir-collective contract).
    dest_local = jnp.where(k2 > 0, pos, rps)
    w3 = words.reshape(words.shape[0], n_shards, rps).transpose(1, 0, 2)
    blocks = jax.vmap(
        lambda w, d: jnp.zeros_like(w).at[:, d].set(w, mode="drop"))(
            w3, dest_local)
    words_c = blocks.transpose(1, 0, 2).reshape(words.shape)
    pad = (-R) % 32
    bits = keep
    if pad:
        bits = jnp.concatenate(
            [keep, jnp.zeros((pad,), dtype=keep.dtype)])
    bits32 = bits.astype(jnp.uint32).reshape(-1, 32)
    mask = (bits32 << jnp.arange(32, dtype=jnp.uint32)[None, :]) \
        .sum(axis=1, dtype=jnp.uint32)
    return words_c, mask, counts


def unpack_keep_mask(mask: np.ndarray, n_rows: int) -> np.ndarray:
    """Host half of compact_packed's mask transport: set-bit positions →
    survivor row indices, ascending (== compaction order)."""
    bits = np.unpackbits(np.ascontiguousarray(mask).view(np.uint8),
                         bitorder="little")[:n_rows]
    return np.flatnonzero(bits).astype(np.int64)


def parse_and_pack(bmat, lengths, specs, nibble: bool,
                   n_shards: int | None = None,
                   pred=None, row_flags=None):
    """THE device program body shared by the XLA path and the Pallas
    kernel: per-column parse (parsers.parse_column) + bit-pack
    (pack_device). One definition — a divergence between the two lowering
    paths would silently corrupt columns.

    With `n_shards` (the mesh path: rows block-sharded over 'sp'), also
    returns int32[n_shards] per-shard counts of fallback-CANDIDATE rows —
    rows where some nonempty field failed its device parse — reduced ON
    DEVICE inside each row shard (the reshape groups rows exactly along
    the block sharding, so XLA keeps the reduction shard-local). Zero-
    length fields are not failures (NULL / TOAST / the all-NULL padding
    rows pad_to_multiple appends), so padding never inflates the counts.
    The host aggregates these for shard-health telemetry only: the exact
    per-row fallback set still comes from the unpacked ok bits masked by
    host-side validity (a zero-length field of a non-null row IS a real
    fallback there, invisible to this length-gated device mask).

    With `pred` (a predicate.CompiledRowFilter — the fused publication
    row filter), the predicate evaluates over the ALREADY-PARSED int32
    components (no re-parse, no extra HBM traffic: the values are in
    registers between parse and pack) and survivors compact to the front
    of their shard block (`compact_packed`). `row_flags` uint8[R] carries
    the host's per-row disposition (0 dead padding / 1 live / 2 live +
    force-keep). Returns (words_compacted, keep_mask,
    counts[, shard_bad]).
    The XLA path and the Pallas kernel share the predicate evaluator and
    the compaction epilogue, so the two engines' compacted outputs are
    byte-identical by construction — `jnp.where`-mask evaluation here is
    the differential twin of the in-kernel keep computation."""
    layout = layout_for_specs(specs)
    columns = []
    row_ok = None
    colmap: dict = {}
    ref_cols = frozenset(pred.referenced_indices) if pred is not None \
        else frozenset()
    w_off = 0
    for j, (col_idx, kind, width, _bw) in enumerate(specs):
        if nibble:
            packed = bmat[:, w_off // 2 : (w_off + width) // 2]
            b = parsers.unpack_nibbles(packed, width)
        else:
            b = bmat[:, w_off : w_off + width].astype(jnp.int32)
        w_off += width
        comp, ok = parsers.parse_column(kind, b, lengths[:, j])
        columns.append((ok, comp))
        if col_idx in ref_cols:
            colmap[col_idx] = (comp, ok, lengths[:, j] == 0)
        if n_shards is not None:
            col_ok = ok | (lengths[:, j] == 0)
            row_ok = col_ok if row_ok is None else (row_ok & col_ok)
    words = pack_device(layout, columns)
    if pred is not None:
        keep = pred.device_keep(colmap, row_flags.astype(jnp.int32))
        words_c, mask, counts = compact_packed(words, keep, n_shards or 1)
        if n_shards is None:
            return words_c, mask, counts
    if n_shards is None:
        return words
    nonempty = (lengths > 0).any(axis=1)
    bad = jnp.zeros_like(nonempty) if row_ok is None \
        else ((~row_ok) & nonempty)
    shard_bad = bad.reshape(n_shards, -1).sum(axis=1, dtype=jnp.int32)
    if pred is not None:
        return words_c, mask, counts, shard_bad
    return words, shard_bad


def unpack_host(layout: BitLayout, words: np.ndarray, col: int,
                n: int) -> tuple[np.ndarray, list[np.ndarray]]:
    """Extract (ok bool[n], components as int64[n] in COLUMN_COMPONENTS
    order, zeros substituted for omitted ones) for dense column `col` from
    fetched uint32[n_words, R]."""
    kind = layout.kinds[col]
    slots = {s.comp: s for s in layout.slots[col]}

    def get(s: FieldSlot) -> np.ndarray:
        w, sh = divmod(s.bit_off, 32)
        if sh + s.bits <= 32:
            v = (words[w, :n] >> np.uint32(sh)).astype(np.uint64)
        else:
            v = ((words[w, :n].astype(np.uint64) >> np.uint64(sh))
                 | (words[w + 1, :n].astype(np.uint64) << np.uint64(32 - sh)))
        v &= np.uint64((1 << s.bits) - 1)
        u = v.astype(np.int64)
        if s.zigzag:
            u = (u >> 1) ^ -(u & 1)
        return u

    ok = get(slots["ok"]).astype(np.bool_)
    comps = []
    for name in parsers.COLUMN_COMPONENTS[kind]:
        s = slots.get(name)
        comps.append(get(s) if s is not None
                     else np.zeros(n, dtype=np.int64))
    return ok, comps
