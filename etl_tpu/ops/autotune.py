"""Measured device break-even for decode routing.

`DeviceDecoder.DEVICE_MIN_ROWS` started life as a constant tuned by hand
for one tunnel-attached chip (VERDICT r4 weak #1: "hardcoded, not
measured"). This module measures the two quantities that constant was
standing in for, once per process:

  - the accelerator round trip: wall time of dispatch + compute + fetch
    for a trivial jitted program at two payload sizes, solved as
    ``t(n) = fixed_s + n / bytes_per_s`` (captures the link latency AND
    its bandwidth — on a tunnel-attached chip both are large and flap);
  - the host-XLA decode rate, normalized per dense column, from a real
    decode of a synthetic 4-int-column staged batch on the host CPU
    backend (the competing path for mid-size batches).

`DeviceDecoder` then solves, per schema, for the row count where the
device path starts winning:

    R / host_rows_per_s  >=  fixed_s + R * bytes_per_row / bytes_per_s

No separate accelerator (CPU-only hosts, the test mesh) → `measure()`
returns None and callers keep the static default; the routing question
is moot there because "device" and "host" are the same backend.

Reference parity: the reference has no analogue — its NCCL path is
always-on. The measured threshold is what makes "decode on TPU" honest
on hardware where the chip sits behind a high-latency link.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import threading
import time

import numpy as np

log = logging.getLogger("etl_tpu.ops.autotune")

# probe payload sizes for the round-trip fit: far enough apart that the
# bandwidth term is observable over the fixed cost on both fast (PCIe)
# and slow (tunnel) links
_PROBE_SMALL = 256 * 1024
_PROBE_LARGE = 8 * 1024 * 1024
_PROBE_REPS = 3

# synthetic host-rate probe: 4 int64 columns × one mid-size bucket
_HOST_PROBE_ROWS = 16_384
_HOST_PROBE_COLS = 4

# never route batches this small to a separate device, whatever the
# probe says — guards against a probe run during a lucky link window
_FLOOR_ROWS = 4096


@dataclasses.dataclass(frozen=True)
class DeviceCostModel:
    """Per-process measurement of the decode routing trade."""

    fixed_s: float  # device dispatch+fetch fixed cost (seconds)
    bytes_per_s: float  # effective host↔device link bandwidth
    host_col_rows_per_s: float  # host-XLA decode rate × dense columns
    backend: str

    def device_min_rows(self, n_dense: int, bytes_per_row: float,
                        default: int) -> int:
        """Smallest row count where the device round trip beats the host
        path for a schema with `n_dense` device-parsed columns moving
        `bytes_per_row` over the link (upload + packed fetch)."""
        if n_dense <= 0:
            return default
        host_s_per_row = n_dense / self.host_col_rows_per_s
        link_s_per_row = bytes_per_row / self.bytes_per_s
        margin = host_s_per_row - link_s_per_row
        if margin <= 0:
            # the link can't even stream the bytes as fast as the host
            # decodes — the device never wins on throughput alone; batches
            # still go at the static default (huge batches overlap enough
            # dispatches for pipelining to change the picture)
            return default
        want = int(self.fixed_s / margin) + 1
        return max(_FLOOR_ROWS, want)


_MEASURED: "list[DeviceCostModel | None] | None" = None
# `measure()` runs on the event loop (first decoder built mid-stream)
# AND in prewarm's executor thread; the lock makes the probe
# single-flight — the loser of the race waits for the winner's model
# instead of re-running a multi-second probe and tearing `_MEASURED`
_MEASURE_LOCK = threading.Lock()


def _fit_round_trip(device) -> tuple[float, float]:
    """min-of-reps wall time for a trivial program at two sizes → solve
    t(n) = a + n/bw. min not mean: link noise is one-sided (same
    reasoning as bench.py's peak-window policy)."""
    import jax

    fn = jax.jit(lambda x: x + np.uint8(1))

    def timed(n: int) -> float:
        buf = np.zeros(n, dtype=np.uint8)
        # warm this shape's program + transfer path
        np.asarray(fn(jax.device_put(buf, device)))
        best = float("inf")
        for _ in range(_PROBE_REPS):
            t0 = time.perf_counter()
            np.asarray(fn(jax.device_put(buf, device)))
            best = min(best, time.perf_counter() - t0)
        return best

    t_small, t_large = timed(_PROBE_SMALL), timed(_PROBE_LARGE)
    bw = (_PROBE_LARGE - _PROBE_SMALL) / max(t_large - t_small, 1e-9)
    fixed = max(t_small - _PROBE_SMALL / bw, 1e-6)
    return fixed, bw


def _measure_host_rate() -> float:
    """Host-XLA decode rate on a synthetic staged batch, in
    column-rows/second (schemas scale it by their dense column count)."""
    from ..models import (ColumnSchema, Oid, ReplicatedTableSchema,
                          TableName, TableSchema)
    from .engine import DeviceDecoder
    from .staging import stage_copy_chunk

    schema = ReplicatedTableSchema.with_all_columns(TableSchema(
        1, TableName("etl", "autotune_probe"),
        tuple(ColumnSchema(f"c{i}", Oid.INT8)
              for i in range(_HOST_PROBE_COLS))))
    line = b"\t".join(str(1234567 + i).encode()
                      for i in range(_HOST_PROBE_COLS))
    chunk = (line + b"\n") * _HOST_PROBE_ROWS
    staged = stage_copy_chunk(chunk, _HOST_PROBE_COLS)
    # device_min_rows above the probe size pins the host path; mesh=None
    # keeps the probe off any multi-device routing; telemetry=False keeps
    # the warm+reps probe decodes out of the routed-rows counters — the
    # device-share honesty metric must reflect real traffic only
    dec = DeviceDecoder(schema, device_min_rows=1 << 30, mesh=None,
                        telemetry=False)
    dec.decode(staged)  # compile + warm
    best = float("inf")
    for _ in range(_PROBE_REPS):
        t0 = time.perf_counter()
        dec.decode(staged)
        best = min(best, time.perf_counter() - t0)
    return _HOST_PROBE_ROWS * _HOST_PROBE_COLS / best


def measure(force: bool = False) -> DeviceCostModel | None:
    """Probe once per process (a few seconds, dominated by the trivial
    program's compile); None when there is no separate accelerator.
    Single-flight under `_MEASURE_LOCK`: safe to race from the loop and
    prewarm's executor thread."""
    global _MEASURED
    if _MEASURED is not None and not force:
        return _MEASURED[0]
    with _MEASURE_LOCK:
        if _MEASURED is not None and not force:
            return _MEASURED[0]
        import jax

        backend = jax.default_backend()
        if backend == "cpu":
            _MEASURED = [None]
            return None
        try:
            device = jax.devices()[0]
            fixed, bw = _fit_round_trip(device)
            host_rate = _measure_host_rate()
            model = DeviceCostModel(fixed_s=fixed, bytes_per_s=bw,
                                    host_col_rows_per_s=host_rate,
                                    backend=backend)
            log.info(
                "device cost model: fixed=%.1fms bw=%.1fMB/s host=%.2fM "
                "col-rows/s (%s)", fixed * 1e3, bw / 1e6, host_rate / 1e6,
                backend)
        except Exception:
            log.warning("device probe failed; keeping static routing",
                        exc_info=True)
            model = None
        _MEASURED = [model]
        return model


async def prewarm() -> DeviceCostModel | None:
    """Measure from async code WITHOUT blocking the event loop.

    `measure()` jit-compiles a probe program and moves 2x8 MiB over the
    host<->device link — seconds of wall time on a tunnel-attached chip.
    The round-5 advisor caught it running synchronously inside the apply
    loop when the first `DeviceDecoder` was constructed mid-stream
    (engine.py device_min_rows resolution), stalling keepalives for every
    table. `Pipeline.start()` awaits this before spawning workers, so the
    per-process cache is hot by the time any decoder is built on the loop.
    """
    if _MEASURED is not None:
        return _MEASURED[0]
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, measure)


def resolve_device_min_rows(n_dense: int, bytes_per_row: float,
                            default: int) -> int:
    """The measured routing threshold for one schema, or `default` when
    no measurement is possible."""
    model = measure()
    if model is None:
        return default
    return model.device_min_rows(n_dense, bytes_per_row, default)
