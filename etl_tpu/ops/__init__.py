"""TPU decode engine: staging, device parsers, the batch decoder."""

from .engine import DEVICE_KINDS, DeviceDecoder
from .staging import (StagedBatch, bucket_pow2, bucket_rows,
                      stage_copy_chunk, stage_tuples)
