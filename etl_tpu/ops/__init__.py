"""TPU decode engine: staging, device parsers, the batch decoder, and the
three-stage pipelined decode scheduler."""

from .engine import DEVICE_KINDS, DeviceDecoder
from .pipeline import (AdmissionScheduler, DecodePipeline, TenantAdmission,
                       global_admission, reset_global_admission)
from .predicate import (CompiledRowFilter, RowFilter, RowFilterError,
                        compile_row_filter, parse_row_filter)
from .staging import (ARENA_POOL, StagedBatch, StagingArenaPool, bucket_pow2,
                      bucket_rows, stage_copy_chunk, stage_tuples)
