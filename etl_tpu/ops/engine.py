"""DeviceDecoder: the TPU decode engine (`batch_engine=tpu`).

Pipeline per batch (north star in BASELINE.json):

  StagedBatch (host, ops/staging.py)
    → host pack: vectorized numpy gather of all dense-column field bytes
      into ONE [R, ΣW] byte matrix (minimizes host↔device transfer: only
      bytes the device parses are uploaded, in one array)
    → device: one jitted program per (row-bucket, width-signature) parsing
      every dense column (ops/parsers.py) and emitting ONE packed int32
      [K, R] result matrix + a per-row ok-bitfield row (single fetch —
      the tunnel/PCIe round trip is latency-bound, so transfer count
      matters more than bytes)
    → host: exact numpy combines into int64/f64 columns
    → CPU-oracle fallback decode for flagged rows (escapes, BC dates,
      17-digit floats, oversized fields) — mixed batches partition,
      they never fail
    → ColumnarBatch (typed columnar + validity + TOAST masks)

`decode_async` dispatches without blocking so the host stages batch N+1
while the device works on batch N (the software-pipelining analogue of the
reference's one-in-flight flush, apply.rs:1956-2023).

Object-typed columns (text, uuid, json, bytea, numeric-as-text, arrays,
intervals) are materialized host-side — strings via a vectorized Arrow
gather, no per-row Python objects.

Reference parity: replaces the per-tuple `parse_cell_from_postgres_text`
hot loop (crates/etl/src/postgres/codec/text.rs) behind the same batching
boundary the reference flushes at (apply.rs:1910-1948).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..models.pgtypes import CellKind
from ..models.schema import ReplicatedTableSchema
from ..models.table_row import Column, ColumnarBatch, dense_dtype
from ..postgres.codec.text import parse_cell_text
from . import parsers
from .staging import StagedBatch, bucket_pow2, bucket_width

# kinds parsed on device; everything else is host-object
DEVICE_KINDS = frozenset({
    CellKind.BOOL, CellKind.I16, CellKind.I32, CellKind.U32, CellKind.I64,
    CellKind.F32, CellKind.F64, CellKind.DATE, CellKind.TIME,
    CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ,
})

_MIN_WIDTH = {
    CellKind.DATE: 16,
    CellKind.TIME: 16,
    CellKind.TIMESTAMP: 32,
    CellKind.TIMESTAMPTZ: 64,
    CellKind.F32: 16,
    CellKind.F64: 32,
}
MAX_FIELD_WIDTH = 2048  # beyond this a field goes to CPU fallback

# packed output rows per kind = its component count (parsers.COLUMN_COMPONENTS)
_PACK_ROWS = {k: len(v) for k, v in parsers.COLUMN_COMPONENTS.items()}


@dataclasses.dataclass(frozen=True)
class _ColSpec:
    index: int  # position among replicated columns
    kind: CellKind


def build_device_program(specs: tuple[tuple[int, CellKind, int], ...]):
    """The (unjitted) single-chip forward step for one width-signature.

    Inputs:  bmat u8[R, ΣW] packed field bytes, lengths i32[R, n_dense]
    Output:  packed i32[K, R]: row 0 is the ok-bitfield (bit j = dense col j
             parsed clean), then each column's value rows (_PACK_ROWS).
    """

    def fn(bmat, lengths):
        lengths = lengths.astype(jnp.int32)
        R = bmat.shape[0]
        rows = []
        okbits = jnp.zeros(R, dtype=jnp.int32)
        w_off = 0
        for j, (col_idx, kind, width) in enumerate(specs):
            b = bmat[:, w_off : w_off + width].astype(jnp.int32)
            w_off += width
            comp, ok = parsers.parse_column(kind, b, lengths[:, j])
            rows += [comp[k] for k in parsers.COLUMN_COMPONENTS[kind]]
            okbits = okbits | (ok.astype(jnp.int32) << j)
        return jnp.stack([okbits] + rows, axis=0)

    return fn


def _build_device_fn(specs):
    return jax.jit(build_device_program(specs))


def _combine(kind: CellKind, rows: np.ndarray) -> np.ndarray:
    """Exact host-side combine of packed device rows (ordered per
    parsers.COLUMN_COMPONENTS) into the column dtype."""
    if kind is CellKind.BOOL:
        return rows[0].astype(np.bool_)
    if kind in (CellKind.I16, CellKind.I32, CellKind.U32):
        return rows[0].astype(dense_dtype(kind))
    if kind is CellKind.I64:
        neg, l0, l1, l2 = rows
        v = (l2.astype(np.int64) * 10**18 + l1.astype(np.int64) * 10**9
             + l0.astype(np.int64))
        return np.where(neg != 0, -v, v)
    if kind in (CellKind.F32, CellKind.F64):
        neg, l0, l1, ea, sp = rows
        m = (l1.astype(np.int64) * 10**9 + l0.astype(np.int64)) \
            .astype(np.float64)
        ea = ea.astype(np.int64)
        v = np.where(ea >= 0, m * np.power(10.0, np.clip(ea, 0, 22)),
                     m / np.power(10.0, np.clip(-ea, 0, 22)))
        v = np.where(neg != 0, -v, v)
        v = np.where(sp == 1, np.nan, v)
        v = np.where(sp == 2, np.inf, v)
        v = np.where(sp == 3, -np.inf, v)
        return v.astype(dense_dtype(kind))
    if kind is CellKind.DATE:
        return rows[0].astype(np.int32)
    if kind is CellKind.TIME:
        return rows[0].astype(np.int64) * 1000 + rows[1].astype(np.int64)
    if kind in (CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ):
        days, ms, us = rows
        return (days.astype(np.int64) * 86_400_000_000
                + ms.astype(np.int64) * 1000 + us.astype(np.int64))
    raise AssertionError(kind)


class _PendingDecode:
    """Handle for an in-flight device decode; `result()` completes it."""

    __slots__ = ("_decoder", "_staged", "_widths", "_packed", "_done")

    def __init__(self, decoder: "DeviceDecoder", staged: StagedBatch,
                 widths: tuple[int, ...], packed):
        self._decoder = decoder
        self._staged = staged
        self._widths = widths
        self._packed = packed
        self._done: ColumnarBatch | None = None

    def result(self) -> ColumnarBatch:
        if self._done is None:
            self._done = self._decoder._complete(self._staged, self._widths,
                                                 self._packed)
        return self._done


class DeviceDecoder:
    """Schema-bound batch decoder. jit caches are per-instance, keyed by
    (row_capacity, width-signature)."""

    def __init__(self, schema: ReplicatedTableSchema, *,
                 numeric_mode: str = "text"):
        self.schema = schema
        cols = schema.replicated_columns
        self._numeric_mode = numeric_mode
        self._dense: list[_ColSpec] = []
        self._object: list[_ColSpec] = []
        for i, c in enumerate(cols):
            kind = c.kind
            if kind is CellKind.NUMERIC and numeric_mode == "f64":
                kind = CellKind.F64
            if kind in DEVICE_KINDS:
                self._dense.append(_ColSpec(i, kind))
            else:
                self._object.append(_ColSpec(i, kind))
        if len(self._dense) > 31:
            # ok-bitfield packs into one int32 row; extraordinarily wide
            # tables spill the tail columns to the host-object path
            for spec in self._dense[31:]:
                self._object.append(spec)
            self._dense = self._dense[:31]
        self._fn_cache: dict[tuple, Callable] = {}

    # -- internals ----------------------------------------------------------

    def _widths(self, staged: StagedBatch) -> tuple[int, ...]:
        out = []
        for spec in self._dense:
            need = max(staged.max_field_len(spec.index),
                       _MIN_WIDTH.get(spec.kind, 4))
            out.append(bucket_width(need, hi=MAX_FIELD_WIDTH))
        return tuple(out)

    def _pack_host(self, staged: StagedBatch, widths: tuple[int, ...]):
        """Vectorized gather of all dense fields into one byte matrix."""
        R = staged.row_capacity
        total_w = sum(widths)
        ldtype = np.uint8 if max(widths, default=0) <= 255 else np.int32
        bmat = np.zeros((R, total_w), dtype=np.uint8)
        lengths = np.zeros((R, len(self._dense)), dtype=ldtype)
        data = staged.data
        n = len(data)
        w_off = 0
        for j, (spec, w) in enumerate(zip(self._dense, widths)):
            offs = staged.offsets[:, spec.index].astype(np.int64)
            lens = np.minimum(staged.lengths[:, spec.index], w)
            lengths[:, j] = lens
            idx = offs[:, None] + np.arange(w, dtype=np.int64)[None, :]
            np.clip(idx, 0, max(n - 1, 0), out=idx)
            if n:
                g = data[idx]
                mask = np.arange(w, dtype=np.int32)[None, :] < lens[:, None]
                bmat[:, w_off : w_off + w] = np.where(mask, g, 0)
            w_off += w
        return bmat, lengths

    def _device_call(self, staged: StagedBatch, widths: tuple[int, ...]):
        key = (staged.row_capacity, widths)
        fn = self._fn_cache.get(key)
        if fn is None:
            specs = tuple((s.index, s.kind, w)
                          for s, w in zip(self._dense, widths))
            fn = _build_device_fn(specs)
            self._fn_cache[key] = fn
        bmat, lengths = self._pack_host(staged, widths)
        return fn(bmat, lengths)  # async dispatch

    def _gather_string_arrow(self, staged: StagedBatch, spec: _ColSpec,
                             valid: np.ndarray):
        """Vectorized scatter-gather of a string column into an Arrow array:
        no per-row Python objects — the columnar-native fast path."""
        import pyarrow as pa

        n = staged.n_rows
        offs = staged.offsets[:n, spec.index].astype(np.int32)
        lens = np.where(valid[:n], staged.lengths[:n, spec.index], 0) \
            .astype(np.int32)
        total = int(lens.sum())
        arrow_offsets = np.zeros(n + 1, dtype=np.int32)
        np.cumsum(lens, out=arrow_offsets[1:])
        if total:
            starts_rep = np.repeat(offs, lens)
            prefix_rep = np.repeat(arrow_offsets[:-1], lens)
            idx = np.arange(total, dtype=np.int32)
            idx -= prefix_rep
            idx += starts_rep
            values = staged.data[idx]
        else:
            values = np.zeros(0, dtype=np.uint8)
        validity = pa.array(valid[:n]).buffers()[1]
        # py_buffer over the ndarrays directly — no tobytes() copies
        return pa.StringArray.from_buffers(
            n, pa.py_buffer(arrow_offsets), pa.py_buffer(values), validity)

    def _decode_object_column(self, staged: StagedBatch, spec: _ColSpec,
                              valid: np.ndarray) -> Any:
        col = self.schema.replicated_columns[spec.index]
        n = staged.n_rows
        if spec.kind is CellKind.STRING and not staged.copy_escapes:
            return self._gather_string_arrow(staged, spec, valid)
        out: list[Any] = [None] * n
        offs = staged.offsets[:, spec.index]
        lens = staged.lengths[:, spec.index]
        data = staged.data
        if spec.kind is CellKind.STRING:
            # COPY path may carry escapes → per-row decode (escaped rows are
            # already routed to cpu_fallback_rows and fixed up afterwards)
            for i in np.flatnonzero(valid[:n]):
                out[i] = data[offs[i] : offs[i] + lens[i]].tobytes().decode("utf-8")
        else:
            oid = col.type_oid
            for i in np.flatnonzero(valid[:n]):
                text = data[offs[i] : offs[i] + lens[i]].tobytes().decode("utf-8")
                out[i] = parse_cell_text(text, oid)
        return out

    def _cpu_fixup(self, staged: StagedBatch, rows: np.ndarray,
                   columns: list[Column]) -> None:
        """Re-decode flagged rows with the CPU oracle and patch columns."""
        from ..models.table_row import _to_dense  # late: avoid cycle
        from ..postgres.codec.copy_text import unescape_copy_field

        cols = self.schema.replicated_columns
        for c in columns:
            if c.is_arrow and rows.size:
                c.data = c.data.to_pylist()  # rare: fixup needs mutability
        for i in rows:
            for j, col in enumerate(cols):
                c = columns[j]
                raw = staged.field_bytes(int(i), j)
                if raw is None:
                    continue
                if staged.copy_escapes:
                    raw = unescape_copy_field(raw)
                value = parse_cell_text(raw.decode("utf-8"), col.type_oid)
                if c.is_dense:
                    try:
                        c.data[i] = _to_dense(c.schema.kind, value) \
                            if value is not None else 0
                    except (OverflowError, ValueError) as e:
                        # value doesn't fit the column's declared type —
                        # corrupt data, same as a Rust i32 parse failure
                        from ..models.errors import ErrorKind, EtlError

                        raise EtlError(
                            ErrorKind.ROW_CONVERSION_FAILED,
                            f"row {i} col {col.name}: value out of range "
                            f"for {col.type_name}: {value!r}") from e
                else:
                    c.data[i] = value
                c.validity[i] = value is not None

    def _complete(self, staged: StagedBatch, widths: tuple[int, ...],
                  packed) -> ColumnarBatch:
        n = staged.n_rows
        cols = self.schema.replicated_columns
        valid_full = ~staged.nulls & ~staged.toast
        packed_np = np.asarray(packed) if packed is not None else None

        columns: list[Column] = [None] * len(cols)  # type: ignore[list-item]
        fallback = set(int(r) for r in staged.cpu_fallback_rows)
        for spec, w in zip(self._dense, widths):
            if staged.max_field_len(spec.index) > w:
                too_big = staged.lengths[:n, spec.index] > w
                fallback.update(np.flatnonzero(too_big).tolist())

        row_off = 1  # row 0 = ok bitfield
        okbits = packed_np[0] if packed_np is not None else None
        for j, spec in enumerate(self._dense):
            k = _PACK_ROWS[spec.kind]
            rows = packed_np[row_off : row_off + k]
            row_off += k
            valid = valid_full[:n, spec.index].copy()
            ok = (okbits >> j) & 1
            bad = (ok[:n] == 0) & valid
            if bad.any():
                fallback.update(np.flatnonzero(bad).tolist())
            data = _combine(spec.kind, rows[:, :n]).copy()
            toast_col = staged.toast[:n, spec.index]
            columns[spec.index] = Column(
                cols[spec.index], data, valid,
                toast_col if toast_col.any() else None)

        for spec in self._object:
            valid = valid_full[:, spec.index]
            toast_col = staged.toast[:n, spec.index]
            data_list = self._decode_object_column(
                staged, spec,
                valid & ~np.isin(np.arange(staged.row_capacity),
                                 list(fallback)) if fallback else valid)
            columns[spec.index] = Column(
                cols[spec.index], data_list, valid[:n].copy(),
                toast_col if toast_col.any() else None)

        if fallback:
            rows_arr = np.asarray(sorted(r for r in fallback if r < n),
                                  dtype=np.int64)
            self._cpu_fixup(staged, rows_arr, columns)
        return ColumnarBatch(self.schema, columns)

    # -- public -------------------------------------------------------------

    def decode_async(self, staged: StagedBatch) -> _PendingDecode:
        """Dispatch the device work and return immediately; stage the next
        batch while this one is in flight."""
        cols = self.schema.replicated_columns
        if len(cols) != staged.n_cols:
            raise ValueError(
                f"staged batch has {staged.n_cols} cols, schema expects "
                f"{len(cols)}")
        widths = self._widths(staged)
        packed = self._device_call(staged, widths) if self._dense else None
        return _PendingDecode(self, staged, widths, packed)

    def decode(self, staged: StagedBatch) -> ColumnarBatch:
        return self.decode_async(staged).result()
