"""DeviceDecoder: the TPU decode engine (`batch_engine=tpu`).

Pipeline per batch (north star in BASELINE.json):

  StagedBatch (host, ops/staging.py)
    → host pack: vectorized numpy gather of all dense-column field bytes
      into ONE [R, ΣW] byte matrix (minimizes host↔device transfer: only
      bytes the device parses are uploaded, in one array)
    → device: one jitted program per (row-bucket, width-signature) parsing
      every dense column (ops/parsers.py) and emitting ONE bit-packed
      uint32[n_words, R] result (ops/bitpack.py: per row, each column's
      ok bit + components at text-width-bounded offsets — the
      device→host link is both latency-bound and ~40 MB/s, so transfer
      count AND bytes are the binding resources)
    → host: exact numpy combines into int64/f64 columns
    → CPU-oracle fallback decode for flagged rows (escapes, BC dates,
      17-digit floats, oversized fields) — mixed batches partition,
      they never fail
    → ColumnarBatch (typed columnar + validity + TOAST masks)

`decode_async` dispatches without blocking so the host stages batch N+1
while the device works on batch N (the software-pipelining analogue of the
reference's one-in-flight flush, apply.rs:1956-2023).

Object-typed columns (text, uuid, json, bytea, numeric-as-text, arrays,
intervals) are materialized host-side — strings via a vectorized Arrow
gather, no per-row Python objects.

Reference parity: replaces the per-tuple `parse_cell_from_postgres_text`
hot loop (crates/etl/src/postgres/codec/text.rs) behind the same batching
boundary the reference flushes at (apply.rs:1910-1948).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.annotations import dispatch_stage, hot_loop
from ..models.pgtypes import CellKind
from ..models.schema import ReplicatedTableSchema
from ..models.table_row import Column, ColumnarBatch, dense_dtype
from ..postgres.codec.text import parse_cell_text
from . import parsers
from .staging import (ArenaLease, StagedBatch, bucket_pow2, bucket_width,
                      pad_to_multiple)

# NOTE on the persistent compilation cache: enabling the GLOBAL
# jax_compilation_cache_dir here was tried and REVERTED — the XLA:CPU
# backend round-trips AOT results whose recorded machine features
# (+prefer-no-scatter/+prefer-no-gather) don't match the execution host,
# and reloading them hard-hangs the process inside the jitted call (GIL
# held, faulthandler can't even fire). Decode-program persistence now
# lives in ops/program_store.py instead: per-program AOT serialization
# under OUR OWN key (canonical layout + backend + mesh fingerprint +
# engine flag) inside a version-tag subdirectory that hashes the host
# CPU's feature flags — the cross-machine mismatch that caused the hang
# can only land in a different subdirectory. Compile count is bounded
# twice over: coarse row buckets (staging.ROW_BUCKETS) and canonical
# layouts (N tables share O(1) programs).

# kinds parsed on device; everything else is host-object
DEVICE_KINDS = frozenset({
    CellKind.BOOL, CellKind.I16, CellKind.I32, CellKind.U32, CellKind.I64,
    CellKind.F32, CellKind.F64, CellKind.DATE, CellKind.TIME,
    CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ,
})

# minimum gather widths: enough for the parsers' static column indexing
# (clipped gathers make larger fields safe — they fall back via the
# oversize check); kept tight because upload bytes are the binding
# resource on the device link
_MIN_WIDTH = {
    CellKind.DATE: 16,
    CellKind.TIME: 16,
    CellKind.TIMESTAMP: 32,
    CellKind.TIMESTAMPTZ: 32,
    CellKind.F32: 16,
    CellKind.F64: 16,
}
MAX_FIELD_WIDTH = 2048  # beyond this a field goes to CPU fallback

# fixed gather widths for the HOST-backend program: wide enough for every
# in-range text of the kind (longer → CPU fallback, same as the device
# oversize rule), so the jit signature is data-INDEPENDENT — one compile
# per (schema, row bucket) instead of one per drifting width signature.
# Host memory traffic is cheap; only the device link makes widths precious.
_HOST_WIDTH = {
    CellKind.BOOL: 4,
    CellKind.I16: 8,          # "-32768"
    CellKind.I32: 12,         # "-2147483648"
    CellKind.U32: 12,
    CellKind.I64: 20,         # "-9223372036854775808"
    CellKind.F32: 32,         # "-1.7976931348623157e+308" is 24
    CellKind.F64: 32,
    CellKind.DATE: 16,
    CellKind.TIME: 16,        # "HH:MM:SS.ffffff"
    CellKind.TIMESTAMP: 32,   # date + space + time = 26
    CellKind.TIMESTAMPTZ: 36, # + "+15:59:59"
}

def round_up_even(n: int) -> int:
    return (n + 1) & ~1

# kinds whose text always fits the 15-symbol nibble alphabet (framer.c):
# digits, sign, dot, colon, space. BOOL ('t'/'f') doesn't; neither do
# floats — PG prints |v| ≥ 1e15 or < 1e-4 in exponent form ('5e-05'),
# which would flag whole rows for CPU fallback, so float columns keep the
# raw byte path.
_NIBBLE_KINDS = frozenset({
    CellKind.I16, CellKind.I32, CellKind.U32, CellKind.I64,
    CellKind.DATE, CellKind.TIME,
    CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ,
})


@dataclasses.dataclass(frozen=True)
class _ColSpec:
    index: int  # position among replicated columns
    kind: CellKind


@dataclasses.dataclass
class _PackedInputs:
    """Output of the pack stage, input of the dispatch stage.
    `row_capacity` may exceed the staged capacity (mesh padding rows,
    zeroed); the fn-cache key and device shapes use it. `row_flags`
    (uint8[row_capacity], fused-filter dispatches only) carries the
    host's per-row disposition: 0 dead padding / 1 live / 2 live +
    force-keep (escapes, nibble-flagged, oversized or TOASTed
    predicate-referenced field — device values untrustworthy, the host
    re-evaluates those survivors after oracle fixup)."""

    bmat: np.ndarray
    lengths: np.ndarray
    nibble: bool
    bad_rows: np.ndarray | None
    row_capacity: int
    use_mesh: bool
    row_flags: np.ndarray | None = None
    filtered: bool = False
    # the canonical layout this batch packed into (program_store.
    # canonical_plan): dispatch keys and builds the program from
    # plan.specs, completion unpacks each real column from its canonical
    # slot. None on the fused-filter path (predicates bind staged column
    # indices, so those programs stay exact).
    plan: "object | None" = None
    # in-flight device egress output (ops/egress.py): (ebytes, elens,
    # EgressPlan) attached by the dispatch stage when the decoder has a
    # wire encoder bound; completion fetches and indexes it per schema
    # column. None = no device egress for this batch (cold program,
    # filtered dispatch, non-renderable layout) — destinations fall back
    # to the host twins.
    egress: "tuple | None" = None


def build_device_program(specs: tuple[tuple[int, CellKind, int, int], ...],
                         nibble: bool = False,
                         n_shards: int | None = None,
                         pred=None):
    """The (unjitted) single-chip forward step for one width-signature.

    Inputs:  bmat u8[R, ΣW] packed field bytes (or u8[R, ΣW/2] nibble pairs
             when `nibble` — two 4-bit symbols per byte, unpacked on device
             through a 16-entry table back to ASCII so the parsers are
             identical), lengths i32[R, n_dense]
    Output:  uint32[n_words, R] bit-packed per ops/bitpack.build_layout —
             each row's ok bits + components in the fewest words their
             text-width-bounded magnitudes allow. ONE array, minimal
             bytes: the device→host fetch link (latency-bound AND ~40MB/s)
             is the binding resource of the whole decode pipeline.
             With `n_shards` (the mesh-sharded path) the program ALSO
             returns int32[n_shards] per-shard fallback-candidate counts,
             reduced on device inside each row shard (bitpack.
             parse_and_pack) — 4 bytes per shard of extra fetch, and the
             host learns shard health without unpacking anything.
             With `pred` (predicate.CompiledRowFilter) the program takes a
             third input (row_flags uint8[R]) and returns the FUSED
             coerce→filter→pack result: (words_compacted, keep_mask,
             counts[, shard_bad]) — survivors compacted to the front of
             their shard block so the host fetch is sized by the survivor
             count, not the batch size.

    specs: (col_index, kind, gather_width, bit_width) per dense column.
    """
    from .bitpack import parse_and_pack

    if pred is not None:
        def fn(bmat, lengths, row_flags):
            return parse_and_pack(bmat, lengths.astype(jnp.int32), specs,
                                  nibble, n_shards=n_shards, pred=pred,
                                  row_flags=row_flags)

        return fn

    def fn(bmat, lengths):
        return parse_and_pack(bmat, lengths.astype(jnp.int32), specs, nibble,
                              n_shards=n_shards)

    return fn


# jitted decode programs shared across ALL DeviceDecoder instances (one
# is created per table and per copy partition; without sharing, each
# re-pays the 10-40s XLA/Mosaic compile for an identical program).
# Bounded LRU: long-running processes with schema churn must not pin
# executables for dropped tables forever — past the cap the
# least-RECENTLY-USED entry is evicted (hits refresh recency via
# move_to_end, so a hot program can't be popped by churn in cold ones;
# worst case: a rare recompile, never a leak). The lock covers lookup and
# eviction: the pipeline's dispatch stage runs on worker threads, and a
# torn OrderedDict relink would corrupt the cache for every decoder.
_SHARED_FN_CACHE: "OrderedDict[tuple, Callable]" = OrderedDict()
_SHARED_FN_CACHE_MAX = 64
_SHARED_FN_LOCK = threading.Lock()


def _shared_fn_get(key: tuple) -> Callable | None:
    with _SHARED_FN_LOCK:
        fn = _SHARED_FN_CACHE.get(key)
        if fn is not None:
            _SHARED_FN_CACHE.move_to_end(key)
        return fn


def _shared_fn_put(key: tuple, fn: Callable) -> None:
    with _SHARED_FN_LOCK:
        _SHARED_FN_CACHE[key] = fn
        _SHARED_FN_CACHE.move_to_end(key)
        while len(_SHARED_FN_CACHE) > _SHARED_FN_CACHE_MAX:
            _SHARED_FN_CACHE.popitem(last=False)


# keys whose host program is compiling on a background thread right now:
# `_route` keeps sending matching batches to the oracle until the compile
# lands, so neither the triggering batch nor its followers block on jax's
# per-signature compile lock
_BG_COMPILE_KEYS: set = set()
# keys whose background build raised: decode stays on the oracle for the
# stream's lifetime rather than respawning a doomed compile thread (and
# re-logging) on every subsequent batch of that signature
_BG_COMPILE_FAILED: set = set()
_BG_COMPILE_LOCK = threading.Lock()


def _host_fn_key(row_capacity: int, specs: tuple,
                 pred_fp: "tuple | None" = None) -> tuple:
    """The module-level program-cache key of the HOST decode path for one
    (row bucket, specs) signature: host packs force nibble compression
    off, never shard on the mesh, and never select pallas. `pred_fp` is
    the fused row filter's fingerprint (None = unfiltered program — a
    different output STRUCTURE, so the keys must never collide). The
    dispatch stage builds its keys through this same helper, so the probe
    in `_host_fn_ready` can never drift from the cache it is probing.
    Callers pass EXACT specs; the key carries their CANONICAL layout
    (program_store.canonical_plan) so every schema that shares a layout
    shares the key. The engine flag stays the LAST element
    (routing-proof tests key on key[-1])."""
    if pred_fp is None and specs:
        from . import program_store

        specs = program_store.canonical_plan(specs).specs
    return (row_capacity, specs, False, None, False, pred_fp, True)


def _host_fn_ready(decoder: "DeviceDecoder", staged: "StagedBatch",
                   specs: tuple) -> bool:
    """True when the host program for this (bucket, specs) is compiled and
    callable without blocking. On a cold key, start the build+compile on a
    background thread (executing the decoder's own dispatch path against
    the triggering batch, so the key and shapes match exactly) and report
    not ready."""
    pred = decoder._device_filter_for(staged)
    key = _host_fn_key(staged.row_capacity, specs,
                       pred.fingerprint() if pred is not None else None)
    with _BG_COMPILE_LOCK:
        if key in _BG_COMPILE_KEYS or key in _BG_COMPILE_FAILED:
            return False
        if _shared_fn_get(key) is not None:
            return True
    # disk probe BEFORE conceding to the oracle: a restarted process
    # finds the executable the previous incarnation compiled and loads
    # it inline (sub-second even for wide schemas) — the warm-restart
    # path that makes restart cost I/O, not XLA (ops/program_store.py).
    # record_absent=False: a miss here flows into the background
    # build's acquire(), which probes and counts the same key again
    from . import program_store

    fn = program_store.try_load(key, record_absent=False)
    if fn is not None:
        _shared_fn_put(key, fn)
        return True
    with _BG_COMPILE_LOCK:
        if key in _BG_COMPILE_KEYS or key in _BG_COMPILE_FAILED:
            return False
        _BG_COMPILE_KEYS.add(key)

    def work() -> None:
        try:
            value, _ = decoder._device_call(staged, specs, host=True)
            jax.block_until_ready(value)
        except Exception:
            import logging

            with _BG_COMPILE_LOCK:
                _BG_COMPILE_FAILED.add(key)
            logging.getLogger("etl_tpu.ops").warning(
                "background host-program compile failed; batches of this "
                "signature keep decoding on the oracle", exc_info=True)
        finally:
            with _BG_COMPILE_LOCK:
                _BG_COMPILE_KEYS.discard(key)

    from ..telemetry.metrics import (ETL_DECODE_BACKGROUND_COMPILES_TOTAL,
                                     registry)

    registry.counter_inc(ETL_DECODE_BACKGROUND_COMPILES_TOTAL)
    # non-daemon: a daemon thread killed mid-XLA-build at interpreter
    # teardown aborts the whole process from C++ ("terminate called
    # without an active exception"); non-daemon means process exit joins
    # an in-flight compile instead — rare in practice, compiles happen in
    # a stream's first seconds
    try:
        threading.Thread(target=work, name="etl-decode-bg-compile",
                         daemon=False).start()
    except RuntimeError:
        # thread limit / interpreter shutdown: work()'s finally never runs,
        # so release the key here and pin the signature to the oracle
        # rather than raising into the decode path
        with _BG_COMPILE_LOCK:
            _BG_COMPILE_KEYS.discard(key)
            _BG_COMPILE_FAILED.add(key)
    return False


def background_compiles_inflight() -> int:
    """How many host-program builds are currently running on background
    threads. Bench warmups poll this to zero before opening a measured
    window — otherwise the window measures the transient oracle-fallback
    period instead of the warm steady state."""
    with _BG_COMPILE_LOCK:
        return len(_BG_COMPILE_KEYS)


def _donation_supported() -> bool:
    """Buffer donation is implemented on TPU/GPU only; on the CPU backend
    jax warns per call and keeps both buffers alive, so donating there
    buys nothing and spams logs."""
    return jax.default_backend() in ("tpu", "gpu")


_ACCEL_BACKEND: "bool | None" = None


def accelerator_backend() -> bool:
    """True when jax's default backend is a real accelerator (TPU/GPU).
    Cached — the backend choice is fixed per process. Gates policies that
    only pay off with a device across the transfer link: backlog
    mega-batching grows seals to clear the DEVICE routing threshold, but
    on the host-CPU backend every grown bucket is a fresh multi-hundred-ms
    XLA compile and a larger host program — measured 5× WORSE end-to-end
    streaming (~41k vs ~200k ev/s) than staying at the standard seal."""
    global _ACCEL_BACKEND
    if _ACCEL_BACKEND is None:
        _ACCEL_BACKEND = jax.default_backend() in ("tpu", "gpu")
    return _ACCEL_BACKEND


def _build_device_fn(specs, nibble: bool = False, use_pallas: bool = False,
                     mesh=None, donate: bool = False, pred=None):
    # donate_argnums on the packed inputs: XLA reuses the uploaded bmat /
    # lengths device buffers for scratch or output, so a steady pipelined
    # stream stops accumulating one dead input buffer per in-flight batch
    # in HBM. Host-side numpy arenas are unaffected (the donated buffer is
    # the DEVICE copy), so arena reuse stays safe.
    kw = {"donate_argnums": (0, 1)} if donate else {}
    if mesh is not None:
        # multi-chip: rows sharded over the 'sp' axis, the SAME program —
        # decode is elementwise over rows, so XLA partitions it with no
        # cross-device collectives on the forward path; the bit-packed
        # output keeps its row shards until the host fetch gathers them,
        # and the per-shard fallback-candidate counts stay sharded too
        # (one i32 per device). The packed staging buffers are donated
        # (TPU/GPU) exactly as on the single-device path — donation is
        # per-shard, so each device reuses its own input block. The fused
        # row filter compacts PER SHARD (bitpack.compact_packed reshapes
        # exactly along the block sharding), so survivor scatter stays
        # shard-local too; rowids and per-shard survivor counts come back
        # row-sharded.
        from jax.sharding import NamedSharding, PartitionSpec as P

        rows_sharded = NamedSharding(mesh, P("sp", None))
        out_sharded = NamedSharding(mesh, P(None, "sp"))
        shard_red = NamedSharding(mesh, P("sp"))
        if pred is not None:
            rows_1d = NamedSharding(mesh, P("sp"))
            return jax.jit(
                build_device_program(specs, nibble, n_shards=mesh.size,
                                     pred=pred),
                in_shardings=(rows_sharded, rows_sharded, rows_1d),
                out_shardings=(out_sharded, rows_1d, shard_red, shard_red),
                **kw)
        return jax.jit(build_device_program(specs, nibble,
                                            n_shards=mesh.size),
                       in_shardings=(rows_sharded, rows_sharded),
                       out_shardings=(out_sharded, shard_red), **kw)
    if use_pallas:
        from .pallas_kernel import build_pallas_program

        return jax.jit(build_pallas_program(specs, nibble, pred=pred), **kw)
    return jax.jit(build_device_program(specs, nibble, pred=pred), **kw)


def program_example_avals(specs, row_capacity: int, nibble: bool = False,
                          pred=None) -> tuple:
    """ShapeDtypeStructs matching exactly what the dispatch stage passes
    for one (specs, row bucket) signature: bmat u8[R, ΣW] (halved under
    nibble packing), lengths u8/i32[R, n] per the pack stage's dtype rule,
    plus the row_flags u8[R] disposition vector on the fused-filter path.
    The IR lint tier lowers programs from these instead of staging real
    batches — shapes/dtypes ARE the jit signature, so the lowering can
    never drift from what production dispatches compile."""
    widths = tuple(w for _, _, w, _ in specs)
    total_w = sum(widths)
    bmat = jax.ShapeDtypeStruct(
        (row_capacity, total_w // 2 if nibble else total_w), np.uint8)
    ldtype = np.uint8 if max(widths, default=0) <= 255 else np.int32
    lengths = jax.ShapeDtypeStruct((row_capacity, len(specs)), ldtype)
    if pred is not None:
        return (bmat, lengths,
                jax.ShapeDtypeStruct((row_capacity,), np.uint8))
    return (bmat, lengths)


def lower_program(specs, row_capacity: int, *, nibble: bool = False,
                  use_pallas: bool = False, mesh=None, donate: bool = False,
                  pred=None):
    """Lower one decode program WITHOUT compiling it to an executable:
    returns (jitted, example_avals, jax.stages.Lowered). This is the IR
    tier's single entry into the engine — the same `_build_device_fn`
    constructor every dispatch path uses, so the jaxpr/StableHLO the
    contracts inspect is the jaxpr/StableHLO production compiles."""
    fn = _build_device_fn(specs, nibble, use_pallas, mesh=mesh,
                          donate=donate, pred=pred)
    avals = program_example_avals(specs, row_capacity, nibble, pred)
    return fn, avals, fn.lower(*avals)


def _combine(kind: CellKind, rows: np.ndarray) -> np.ndarray:
    """Exact host-side combine of packed device rows (ordered per
    parsers.COLUMN_COMPONENTS) into the column dtype."""
    if kind is CellKind.BOOL:
        return rows[0].astype(np.bool_)
    if kind in (CellKind.I16, CellKind.I32, CellKind.U32):
        return rows[0].astype(dense_dtype(kind))
    if kind is CellKind.I64:
        neg, l0, l1, l2 = rows
        v = (l2.astype(np.int64) * 10**18 + l1.astype(np.int64) * 10**9
             + l0.astype(np.int64))
        return np.where(neg != 0, -v, v)
    if kind in (CellKind.F32, CellKind.F64):
        neg, l0, l1, ea, sp = rows
        m = (l1.astype(np.int64) * 10**9 + l0.astype(np.int64)) \
            .astype(np.float64)
        ea = ea.astype(np.int64)
        v = np.where(ea >= 0, m * np.power(10.0, np.clip(ea, 0, 22)),
                     m / np.power(10.0, np.clip(-ea, 0, 22)))
        v = np.where(neg != 0, -v, v)
        v = np.where(sp == 1, np.nan, v)
        v = np.where(sp == 2, np.inf, v)
        v = np.where(sp == 3, -np.inf, v)
        return v.astype(dense_dtype(kind))
    if kind is CellKind.DATE:
        return rows[0].astype(np.int32)
    if kind is CellKind.TIME:
        return rows[0].astype(np.int64) * 1000 + rows[1].astype(np.int64)
    if kind in (CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ):
        days, ms, us = rows
        return (days.astype(np.int64) * 86_400_000_000
                + ms.astype(np.int64) * 1000 + us.astype(np.int64))
    raise AssertionError(kind)


class _PendingDecode:
    """Handle for an in-flight device decode; `result()` completes it.
    The device→host copy of the packed result is started at construction
    (`copy_to_host_async`), so the transfer rides the link while the host
    stages and packs the next batches — `result()` mostly finds the bytes
    already landed. Mesh-sharded dispatches carry a tuple; every value
    starts its host copy here — EXCEPT the fused-filter single-device
    case, where only the 4-byte survivor COUNT pre-fetches: the packed
    words and rowids are fetched at `result()` as a count-sized slice, so
    the device→host link carries survivor bytes, not batch bytes (the
    fetch-reduction half of the fused-filter win)."""

    __slots__ = ("_decoder", "_staged", "_specs", "_packed", "_meta",
                 "_done")

    def __init__(self, decoder: "DeviceDecoder", staged: StagedBatch,
                 specs: tuple, packed, meta: "_PackedInputs | None" = None):
        self._decoder = decoder
        self._staged = staged
        self._specs = specs
        self._packed = packed
        self._meta = meta
        self._done: ColumnarBatch | None = None
        filtered = meta is not None and meta.filtered
        if filtered and not meta.use_mesh and isinstance(packed, tuple):
            # keep mask (1 bit/row) + counts only; the words fetch is a
            # count-sized device slice at result()
            values = packed[1:3]
        else:
            values = packed if isinstance(packed, tuple) else (packed,)
        for v in values:
            if v is not None:
                try:
                    v.copy_to_host_async()
                except AttributeError:
                    pass  # non-jax array (tests may inject numpy)
        if meta is not None and meta.egress is not None:
            # wire bytes + lengths ride the link alongside the packed
            # words; completion finds them landed
            for v in meta.egress[:2]:
                try:
                    v.copy_to_host_async()
                except AttributeError:
                    pass

    @property
    def survivors(self) -> "np.ndarray | None":
        """Original staged-row indices of the rows the completed batch
        kept, or None for an unfiltered decode. Valid after result()."""
        batch = self.result()
        return getattr(batch, "source_rows", None)

    def result(self) -> ColumnarBatch:
        if self._done is None:
            self._done = self._decoder._complete(
                self._staged, self._specs, self._packed,
                self._meta.bad_rows if self._meta is not None else None,
                meta=self._meta)
        return self._done


_HOST_CPU_DEVICE: list = []  # lazy singleton: [device] | [None]


def _host_cpu_device():
    """The host CPU backend's device, or None when unavailable. Present
    even when the default backend is a TPU — XLA's CPU client is built in,
    so the SAME decode program can execute host-side for batches too small
    to amortize the accelerator round trip."""
    if not _HOST_CPU_DEVICE:
        try:
            _HOST_CPU_DEVICE.append(jax.local_devices(backend="cpu")[0])
        except Exception:
            _HOST_CPU_DEVICE.append(None)
    return _HOST_CPU_DEVICE[0]


# -- supervision degrade hook -------------------------------------------------

# monotonic deadline until which EVERY decoder routes to the host oracle
# (supervision escalation after repeated device-side stalls); process-
# global on purpose: a sick device link is a process-level condition,
# like the per-process autotune cost model
_ORACLE_FORCED_UNTIL = 0.0


def force_host_oracle(duration_s: float) -> None:
    """Route all decode batches to the host oracle for `duration_s`."""
    import time

    global _ORACLE_FORCED_UNTIL
    _ORACLE_FORCED_UNTIL = time.monotonic() + duration_s


def clear_forced_oracle() -> None:
    global _ORACLE_FORCED_UNTIL
    _ORACLE_FORCED_UNTIL = 0.0


def host_oracle_forced() -> bool:
    import time

    return _ORACLE_FORCED_UNTIL > 0.0 \
        and time.monotonic() < _ORACLE_FORCED_UNTIL


class DeviceDecoder:
    """Schema-bound batch decoder. Jitted programs live in the
    module-level _SHARED_FN_CACHE keyed by (row_capacity, specs, nibble,
    mesh, pallas, host) — shared across instances; each decoder keeps a
    record of the keys it used (`_fn_cache`) for compile-count tests."""

    # below this row count the device round trip (latency-bound) loses to
    # the host paths; small CDC flushes decode on host, WAL bursts and
    # copy partitions go to the device. Measured on the tunnel-attached
    # chip (fixed ~45-80 ms round trip): host-CPU XLA sustains 1.7-3.5M
    # rows/s from 8k to 64k rows while the device manages 0.1-1.4M at
    # those sizes — the crossover sits above 10^5 rows, so mid-size
    # streaming flushes must stay on host
    DEVICE_MIN_ROWS = 131_072

    # CDC flush runs (hundreds of rows between commit barriers) are far
    # below DEVICE_MIN_ROWS; at/above this row count they run the SAME
    # XLA decode program on the host CPU backend — one vectorized dispatch
    # instead of a per-row Python oracle pass (~100× on the streaming hot
    # path). Below it, dispatch overhead loses to the oracle.
    HOST_MIN_ROWS = 64

    # below this row count a multi-device mesh buys nothing (per-shard
    # work too small vs dispatch overhead); batches at/above it shard rows
    # across 'sp' (SURVEY §7: data-parallel decode across ragged batches)
    MESH_MIN_ROWS = 65_536

    def __init__(self, schema: ReplicatedTableSchema, *,
                 numeric_mode: str = "text", use_pallas: bool = False,
                 device_min_rows: int | None = None,
                 host_min_rows: int | None = None,
                 mesh: "object | str | None" = "auto",
                 mesh_min_rows: int | None = None,
                 telemetry: bool = True,
                 nonblocking_compile: bool = False,
                 egress: "str | None" = None):
        self.schema = schema
        self.use_pallas = use_pallas
        # wire encoder name (ops/egress.py ENCODER_*) when the bound
        # destination consumes device-rendered text; decoded batches then
        # carry `device_egress` buffers next to their columns
        self.egress = egress
        # streaming decoders (assembler / copy) must never block a worker
        # on a first-touch XLA build: a 120-column host program compiles
        # for tens of seconds (measured 32s on this container), which
        # freezes apply progress past the stall deadline and sends the
        # supervision watchdog into a cancel→re-stream→re-wedge loop.
        # With nonblocking_compile the cold (bucket, specs) batch decodes
        # on the oracle while the program compiles on a background
        # thread; warm batches route to the host program as usual.
        self.nonblocking_compile = nonblocking_compile
        # telemetry=False keeps synthetic decodes (the autotune host-rate
        # probe) out of the routed-rows/decode counters so the device-share
        # metric reflects real replication traffic only
        self._telemetry = telemetry
        self.host_min_rows = self.HOST_MIN_ROWS \
            if host_min_rows is None else host_min_rows
        if mesh == "auto":
            from ..parallel.mesh import default_decode_mesh

            mesh = default_decode_mesh()
        self.mesh = mesh  # jax.sharding.Mesh | None
        self.mesh_min_rows = self.MESH_MIN_ROWS \
            if mesh_min_rows is None else mesh_min_rows
        cols = schema.replicated_columns
        self._numeric_mode = numeric_mode
        self._dense: list[_ColSpec] = []
        self._object: list[_ColSpec] = []
        for i, c in enumerate(cols):
            kind = c.kind
            if kind is CellKind.NUMERIC and numeric_mode == "f64":
                kind = CellKind.F64
            if kind in DEVICE_KINDS:
                self._dense.append(_ColSpec(i, kind))
            else:
                self._object.append(_ColSpec(i, kind))
        if len(self._dense) > 250:
            # the C packer handles 256 columns; beyond 250 dense device
            # columns the tail spills to the host-object path (the byte
            # matrix for such tables is bounded by the batch size budget,
            # not the column count)
            for spec in self._dense[250:]:
                self._object.append(spec)
            self._dense = self._dense[:250]
        # publication row filter: compiled ONCE here (etl-lint rule 13
        # flags compile_row_filter on @hot_loop paths — a per-batch
        # compile would re-bind literals and re-trace per flush). An
        # unparseable/unbindable filter degrades to None with a warning:
        # the batch then decodes unfiltered, which is only correct when
        # the server still filters — the pipeline logs loudly so the
        # offload deployment can't silently deliver excluded rows.
        self._row_filter = None
        rf = getattr(schema, "row_predicate", None)
        if rf is not None:
            from .predicate import RowFilterError, compile_row_filter

            try:
                self._row_filter = compile_row_filter(rf, schema)
            except RowFilterError:
                import logging

                logging.getLogger("etl_tpu.ops").warning(
                    "row filter %r on %s is outside the client-side "
                    "envelope; decoding UNFILTERED (server-side filtering "
                    "must cover this table)", getattr(rf, "sql", rf),
                    schema.name, exc_info=True)
        # record of the programs THIS decoder used (tests pin per-
        # decoder compile-count invariants on it); the fns themselves
        # live in the module-level _SHARED_FN_CACHE
        self._fn_cache: dict[tuple, Callable] = {}
        # computed eagerly: a decoder is shared between the event loop
        # and warm_host_programs' executor thread, and an init-before-
        # spawn write is the one publication order that needs no lock
        # (the lazy fill here was the concurrency tier's first real
        # unsynchronized-shared-mutation finding)
        self._host_specs_cache: tuple = self._compute_host_specs()
        if device_min_rows is not None:
            self.device_min_rows = device_min_rows
        else:
            # measured, not hardcoded (VERDICT r4 #1a): solve the
            # host-vs-device crossover from the probed link cost model
            # and this schema's actual per-row traffic (gather widths up,
            # packed words down). Falls back to the static default when
            # no separate accelerator exists or the probe failed.
            # Pipeline.start() awaits autotune.prewarm() before spawning
            # workers, so this resolve hits the per-process cache when a
            # decoder is built on the event loop (the r5 advisor caught
            # the unwarmed probe stalling the apply loop for seconds).
            from . import autotune
            from .bitpack import layout_for_specs

            specs = self._host_specs()
            up = sum(w for _, _, w, _ in specs) + len(specs)
            down = layout_for_specs(specs).n_words * 4 if specs else 0
            self.device_min_rows = autotune.resolve_device_min_rows(
                len(self._dense), float(up + down), self.DEVICE_MIN_ROWS)

    # -- internals ----------------------------------------------------------

    def _widths(self, staged: StagedBatch) -> tuple[int, ...]:
        out = []
        for spec in self._dense:
            need = max(staged.max_field_len(spec.index),
                       _MIN_WIDTH.get(spec.kind, 4))
            out.append(bucket_width(need, hi=MAX_FIELD_WIDTH))
        return tuple(out)

    def _specs(self, staged: StagedBatch,
               widths: tuple[int, ...]) -> tuple:
        """(col_index, kind, gather_width, bit_width) per dense column.
        bit_width bounds the packed-output field sizes from the column's
        ACTUAL max text length (bucketed to even, clamped at the kind's
        layout-saturation width so jit signatures stay few) — tighter than
        the gather width, and every bit saved is fetch bandwidth on the
        device link."""
        from .bitpack import saturation_width

        out = []
        for spec, w in zip(self._dense, widths):
            bw = round_up_even(
                min(max(staged.max_field_len(spec.index), 1), w,
                    saturation_width(spec.kind)))
            out.append((spec.index, spec.kind, w, bw))
        return tuple(out)

    def _compute_host_specs(self) -> tuple:
        from .bitpack import saturation_width

        out = []
        for spec in self._dense:
            w = _HOST_WIDTH[spec.kind]
            bw = round_up_even(min(w, saturation_width(spec.kind)))
            out.append((spec.index, spec.kind, w, bw))
        return tuple(out)

    def _host_specs(self) -> tuple:
        """Data-independent specs for the host-CPU program (fixed gather
        widths per kind, bit widths at saturation): the signature never
        drifts with field lengths, so each (schema, row bucket) compiles
        exactly once. Computed at construction — see __init__."""
        return self._host_specs_cache

    def _can_nibble(self, widths: tuple[int, ...]) -> bool:
        return (all(s.kind in _NIBBLE_KINDS for s in self._dense)
                and all(w % 2 == 0 and w <= 255 for w in widths)
                and len(self._dense) > 0)

    def _pack_host(self, staged: StagedBatch, widths: tuple[int, ...],
                   allow_nibble: bool = True,
                   arena: "ArenaLease | None" = None,
                   row_capacity: int | None = None,
                   cols: "list[int] | None" = None,
                   phantom: tuple = ()):
        """Gather all dense fields into one byte matrix: nibble-packed C
        fast path (halves the upload) when the column mix allows, raw C
        pass otherwise, numpy as the last resort. Returns
        (bmat, lengths, nibble, bad_rows). The host-backend path packs raw
        (allow_nibble=False): there is no upload to halve, and skipping the
        nibble probe avoids a second compiled program per schema.

        `arena` supplies reusable preallocated buffers (ops/pipeline.py's
        pack stage); safe because every pack path overwrites all rows up
        to capacity. `row_capacity` > staged.row_capacity allocates mesh
        padding rows, zeroed after the pack (the C packers only write the
        staged capacity).

        `cols` is the staged column index feeding each byte-matrix slot
        (default: self._dense order — the exact layout). Canonical
        layouts pass their slot permutation plus `phantom` pad-slot
        indices: phantom slots pack a same-(kind,width) DONOR column
        through the C fast path (so the nibble alphabet scan sees only
        bytes a real slot already scanned) and are zeroed to all-NULL
        here, making padding invisible to the parsers and the fallback
        machinery."""
        from ..native import pack_bmat, pack_bmat_nibble

        cap = staged.row_capacity
        R = cap if row_capacity is None else row_capacity
        if cols is None:
            cols = [s.index for s in self._dense]

        def buf(shape, dtype):
            return arena.take(shape, dtype) if arena is not None \
                else np.empty(shape, dtype=dtype)

        def zero_tail(*arrays):
            if R > cap:
                for a in arrays:
                    a[cap:] = 0

        def zero_phantoms(bmat, lengths, nibble: bool):
            if not phantom:
                return
            w_off = 0
            offs = []
            for w in widths:
                offs.append(w_off)
                w_off += w
            for j in phantom:
                lengths[:, j] = 0
                o, w = offs[j], widths[j]
                if nibble:
                    bmat[:, o // 2 : (o + w) // 2] = 0
                else:
                    bmat[:, o : o + w] = 0

        total_w = sum(widths)
        ldtype = np.uint8 if max(widths, default=0) <= 255 else np.int32
        if allow_nibble and ldtype is np.uint8 and self._can_nibble(widths):
            bmat = buf((R, total_w // 2), np.uint8)
            lengths = buf((R, len(cols)), np.uint8)
            bad = buf((R,), np.uint8)
            if pack_bmat_nibble(
                    staged.data, np.ascontiguousarray(staged.offsets),
                    np.ascontiguousarray(staged.lengths),
                    cols, list(widths), bmat,
                    lengths, bad):
                zero_tail(bmat, lengths, bad)
                zero_phantoms(bmat, lengths, True)
                return bmat, lengths, True, bad
        bmat = buf((R, total_w), np.uint8)
        lengths = buf((R, len(cols)), ldtype)
        if ldtype is np.uint8 and pack_bmat(
                staged.data, np.ascontiguousarray(staged.offsets),
                np.ascontiguousarray(staged.lengths),
                cols, list(widths), bmat, lengths):
            zero_tail(bmat, lengths)
            zero_phantoms(bmat, lengths, False)
            return bmat, lengths, False, None
        bmat[:] = 0
        lengths[:] = 0
        data = staged.data
        n = len(data)
        phantom_set = frozenset(phantom)
        w_off = 0
        for j, (col, w) in enumerate(zip(cols, widths)):
            if j in phantom_set:
                w_off += w  # already zero (all-NULL padding slot)
                continue
            offs = staged.offsets[:, col].astype(np.int64)
            lens = np.minimum(staged.lengths[:, col], w)
            lengths[:cap, j] = lens
            idx = offs[:, None] + np.arange(w, dtype=np.int64)[None, :]
            np.clip(idx, 0, max(n - 1, 0), out=idx)
            if n:
                g = data[idx]
                mask = np.arange(w, dtype=np.int32)[None, :] < lens[:, None]
                bmat[:cap, w_off : w_off + w] = np.where(mask, g, 0)
            w_off += w
        return bmat, lengths, False, None

    def _device_filter_for(self, staged: StagedBatch):
        """The CompiledRowFilter to FUSE into this batch's device/host-XLA
        program, or None. Requires: a filter, a batch the caller allows
        filtering on (insert/COPY streams only — runtime/assembler clears
        the flag for runs carrying updates/deletes), and a predicate whose
        every referenced column is device-parsed with an exact int32
        comparison. Anything else falls back to `_host_filter_for`'s
        post-decode mask — correct, just without the fetch-bytes win."""
        rf = self._row_filter
        if rf is None or not staged.allow_row_filter \
                or not rf.device_supported:
            return None
        dense_idx = frozenset(s.index for s in self._dense)
        if not frozenset(rf.referenced_indices) <= dense_idx:
            return None
        return rf

    def _host_filter_for(self, staged: StagedBatch):
        """The filter to apply host-side AFTER an unfiltered decode (the
        oracle route, and device routes whose predicate is outside the
        device envelope)."""
        rf = self._row_filter
        if rf is None or not staged.allow_row_filter:
            return None
        return rf

    def _row_flags(self, staged: StagedBatch, specs: tuple,
                   pred, bad_rows, row_capacity: int) -> np.ndarray:
        """Per-row disposition vector for the fused filter program:
        0 dead (bucket/mesh padding), 1 live, 2 live + force-keep. Force-
        keep marks rows whose predicate-referenced device values cannot be
        trusted (COPY escapes, nibble-alphabet violations, oversized or
        TOASTed referenced fields): the device keeps them unconditionally
        and the host re-evaluates after oracle fixup, so the compacted
        output equals the host oracle bit for bit."""
        n = staged.n_rows
        flags = np.zeros(row_capacity, dtype=np.uint8)
        flags[:n] = 1
        force = np.zeros(n, dtype=bool)
        fb = staged.cpu_fallback_rows
        if len(fb):
            force[fb[fb < n]] = True
        if bad_rows is not None:
            force |= bad_rows[:n].astype(bool)
        ref = pred.referenced_indices
        widths = {i: w for i, _, w, _ in specs}
        for j in ref:
            if staged.max_field_len(j) > widths[j]:
                force |= staged.lengths[:n, j] > widths[j]
            toast_col = staged.toast[:n, j]
            if toast_col.any():
                force |= toast_col
        flags[:n][force] = 2
        return flags

    def _use_mesh(self, row_capacity: int) -> bool:
        # no divisibility requirement: the pack stage pads row capacity up
        # to a mesh.size multiple with all-NULL rows (staging.pad_to_
        # multiple), so odd buckets shard instead of silently falling back
        # to single-device dispatch
        return (self.mesh is not None
                and row_capacity >= self.mesh_min_rows)

    # -- pipeline stages (ops/pipeline.py runs pack on a worker thread,
    # -- dispatch immediately after; _device_call composes them for the
    # -- serial decode()/decode_async() path) -------------------------------

    def _pack_stage(self, staged: StagedBatch, specs: tuple,
                    host: bool = False,
                    arena: "ArenaLease | None" = None) -> "_PackedInputs":
        """Stage 1: host gather of all dense fields into (possibly pooled)
        staging buffers. Pure numpy/C — no jax calls, safe on any thread.
        Unfiltered batches pack into their CANONICAL layout (sorted
        slots + all-NULL phantom padding, program_store.canonical_plan)
        so the dispatch stage keys a shared program; the fused-filter
        path packs the exact layout (its predicate binds staged column
        indices)."""
        pred = self._device_filter_for(staged)
        plan = None
        cols = None
        phantom: tuple = ()
        if pred is None and specs:
            from . import program_store

            plan = program_store.canonical_plan(specs)
            widths = tuple(w for _, _, w, _ in plan.specs)
            if not plan.identity:
                cols = [self._dense[p].index for p in plan.pack_dense]
                phantom = plan.phantom_slots
        else:
            widths = tuple(w for _, _, w, _ in specs)
        use_mesh = not host and self._use_mesh(staged.row_capacity)
        cap = pad_to_multiple(staged.row_capacity, self.mesh.size) \
            if use_mesh else staged.row_capacity
        bmat, lengths, nibble, bad_rows = self._pack_host(
            staged, widths, allow_nibble=not host, arena=arena,
            row_capacity=cap, cols=cols, phantom=phantom)
        row_flags = None
        if pred is not None:
            row_flags = self._row_flags(staged, specs, pred, bad_rows, cap)
        return _PackedInputs(bmat, lengths, nibble, bad_rows, cap, use_mesh,
                             row_flags=row_flags, filtered=pred is not None,
                             plan=plan)

    @dispatch_stage
    @hot_loop
    def _dispatch_stage(self, staged: StagedBatch, specs: tuple,
                        packed: "_PackedInputs", host: bool = False):
        """Stage 2: start the device program on the packed inputs and
        return the in-flight device value. @dispatch_stage: the host-path
        `jax.device_put` is a committed UPLOAD riding the pipeline, not a
        sync point — fetches still belong at `_PendingDecode.result()`."""
        bmat, lengths = packed.bmat, packed.lengths
        # pspecs: what the PROGRAM is built from — the canonical layout
        # when the pack stage resolved one, the exact specs otherwise
        # (fused-filter dispatches). `specs` stays the exact per-real-
        # column view the completion path reasons about.
        pspecs = packed.plan.specs if packed.plan is not None else specs
        widths = tuple(w for _, _, w, _ in pspecs)
        if host:
            # committed CPU placement: jit compiles/executes this call on
            # the host CPU backend — same program, no accelerator round
            # trip (pallas is TPU-lowered, so host always takes the XLA
            # build; jit caches per input placement)
            dev = _host_cpu_device()
            bmat = jax.device_put(bmat, dev)
            lengths = jax.device_put(lengths, dev)
        if self.use_pallas and not host:
            from .pallas_kernel import MAX_TOTAL_WIDTH, pallas_supported

            if not pallas_supported(pspecs):
                # wide schemas overflow the Mosaic compiler's appetite
                # for the unrolled parse chain (MAX_TOTAL_WIDTH) — take
                # the XLA program without a doomed remote-compile
                # attempt. Flipping the FLAG (not silently routing)
                # keeps bench/harness engine labels honest: they report
                # which engine actually ran via use_pallas.
                import logging

                logging.getLogger("etl_tpu.ops").info(
                    "schema too wide for the pallas kernel "
                    "(total gather width %d > %d); using the XLA program",
                    sum(widths), MAX_TOTAL_WIDTH)
                self.use_pallas = False
        # the program cache is MODULE-level: decoders are created per
        # table and per copy partition, and identical (bucket, specs)
        # programs across instances must not recompile — the engine flag
        # rides in the key, so a pallas fallback just stops selecting
        # the pallas entries instead of clearing anything. The mesh slot
        # holds a canonical FINGERPRINT (axis names, shape, device ids —
        # parallel/mesh.mesh_cache_key), never the Mesh object: equal
        # meshes recreated across decoders share the program, while
        # decoders on different meshes (or mesh vs none) can never
        # collide on the same (specs, nibble) signature — the sharded
        # program returns (packed, shard_bad), a different output
        # STRUCTURE than the single-device array
        from ..parallel.mesh import mesh_cache_key

        pallas = self.use_pallas and not host
        pred = self._device_filter_for(staged) if packed.filtered else None
        pred_fp = pred.fingerprint() if pred is not None else None
        key = _host_fn_key(packed.row_capacity, specs, pred_fp) if host else \
            (packed.row_capacity, pspecs, packed.nibble,
             mesh_cache_key(self.mesh) if packed.use_mesh else None,
             pallas, pred_fp, False)
        if host:
            # observed-signature recording (ops/program_store.py): the
            # (canonical layout, row bucket) signatures a workload
            # ACTUALLY dispatched persist next to the executables, so a
            # restarted pipeline prewarms them — mega-seal buckets and
            # filtered programs the SchemaStore enumeration can't name.
            # Disarmed cost (no cache dir / already seen): one set probe.
            from . import program_store

            program_store.record_observed(key)
        row_flags = packed.row_flags
        if pred is not None and host:
            row_flags = jax.device_put(row_flags, dev)
        fn = _shared_fn_get(key)
        if fn is None:
            # miss: ops/program_store resolves it — disk load when a
            # cache dir is configured (warm restarts compile NOTHING),
            # else build + AOT compile + persist; the example args pin
            # the lowering to exactly what this call passes
            from . import program_store

            def _builder():
                return _build_device_fn(
                    pspecs, packed.nibble, pallas,
                    mesh=self.mesh if packed.use_mesh else None,
                    donate=not host and _donation_supported(), pred=pred)

            args = (bmat, lengths) if pred is None \
                else (bmat, lengths, row_flags)
            fn = program_store.acquire(key, _builder, args)
            _shared_fn_put(key, fn)
        elif self._telemetry:
            from ..telemetry.metrics import (ETL_COMPILE_CACHE_HITS_TOTAL,
                                             registry)

            registry.counter_inc(ETL_COMPILE_CACHE_HITS_TOTAL,
                                 labels={"layer": "memory"})
        self._fn_cache[key] = fn
        if packed.use_mesh and self._telemetry:
            from ..telemetry.metrics import (
                ETL_DECODE_MESH_BATCHES_TOTAL, ETL_DECODE_MESH_PAD_WASTE_RATIO,
                ETL_DECODE_MESH_PADDED_ROWS_TOTAL, ETL_DECODE_MESH_ROWS_TOTAL,
                ETL_DECODE_MESH_SHARDS, registry)

            registry.gauge_set(ETL_DECODE_MESH_SHARDS, self.mesh.size)
            registry.counter_inc(ETL_DECODE_MESH_BATCHES_TOTAL)
            registry.counter_inc(ETL_DECODE_MESH_ROWS_TOTAL,
                                 packed.row_capacity)
            # MESH padding only (cap − bucket capacity): bucket padding
            # below staged.row_capacity exists identically on the
            # single-device path and must not read as mesh waste
            pad = packed.row_capacity - staged.row_capacity
            if pad:
                registry.counter_inc(ETL_DECODE_MESH_PADDED_ROWS_TOTAL, pad)
            rows_total = registry.get_counter(ETL_DECODE_MESH_ROWS_TOTAL)
            pad_total = registry.get_counter(ETL_DECODE_MESH_PADDED_ROWS_TOTAL)
            registry.gauge_set(ETL_DECODE_MESH_PAD_WASTE_RATIO,
                               pad_total / rows_total if rows_total else 0.0)
        try:
            if pred is not None:
                out = fn(bmat, lengths, row_flags)  # async dispatch
            else:
                out = fn(bmat, lengths)  # async dispatch
        except Exception:
            # host calls never run pallas — an error there is real, not a
            # Mosaic rejection; misrouting it would disable pallas AND send
            # the small batch on the accelerator round trip
            if host or not self.use_pallas:
                raise
            # Mosaic rejects some byte-wise lowerings on current libtpu
            # (interleave reshape, narrow truncations) — fall back to the
            # XLA program permanently for this decoder; the packed inputs
            # are engine-independent, so no re-pack
            import logging

            logging.getLogger("etl_tpu.ops").warning(
                "pallas kernel failed to compile; falling back to XLA",
                exc_info=True)
            self.use_pallas = False
            return self._dispatch_stage(staged, specs, packed, host)
        if self.egress is not None and pred is None and specs:
            # stage 2b: the egress program renders wire text from the
            # decode output's device-resident words. Unfiltered batches
            # only (compacted words re-index rows) and never fatal — a
            # cold program, an un-renderable layout or any failure just
            # ships the batch without device egress.
            words = out[0] if isinstance(out, tuple) else out
            packed.egress = self._egress_stage(words, pspecs, packed, host)
        return out

    def _egress_stage(self, words, pspecs: tuple,
                      packed: "_PackedInputs", host: bool):
        try:
            from . import egress as egress_mod
            from . import program_store

            plan = egress_mod.plan_for_specs(pspecs, self.egress)
            if plan is None:
                return None
            from ..parallel.mesh import mesh_cache_key

            mesh = self.mesh if packed.use_mesh else None
            key = egress_mod.egress_fn_key(
                packed.row_capacity, pspecs, self.egress,
                mesh_cache_key(mesh) if mesh is not None else None)

            def _builder():
                return egress_mod.build_egress_fn(pspecs, plan, mesh=mesh)

            fn = egress_mod.egress_fn_ready(
                key, _builder, (words,),
                blocking=not self.nonblocking_compile)
            if fn is None:
                return None
            self._fn_cache[key] = fn
            if host:
                # observed-signature recording, same as decode host
                # dispatches: a restarted pipeline prewarms the egress
                # programs the workload actually used
                program_store.record_observed(key)
            ebytes, elens = fn(words)  # async dispatch
            if self._telemetry:
                from ..telemetry.metrics import (
                    ETL_EGRESS_DEVICE_BATCHES_TOTAL, registry)

                registry.counter_inc(ETL_EGRESS_DEVICE_BATCHES_TOTAL)
            return (ebytes, elens, plan)
        except Exception:
            import logging

            logging.getLogger("etl_tpu.ops").warning(
                "device egress dispatch failed; batch ships without "
                "wire buffers", exc_info=True)
            return None

    def _device_call(self, staged: StagedBatch, specs: tuple,
                     host: bool = False):
        packed = self._pack_stage(staged, specs, host)
        return self._dispatch_stage(staged, specs, packed, host), packed

    def _gather_string_arrow(self, staged: StagedBatch, spec: _ColSpec,
                             valid: np.ndarray):
        """Vectorized scatter-gather of a string column into an Arrow array:
        no per-row Python objects — the columnar-native fast path."""
        import pyarrow as pa

        from ..native import gather_string

        n = staged.n_rows
        lens = np.where(valid[:n], staged.lengths[:n, spec.index], 0)
        total = int(lens.sum())
        if total == 0:  # all-null/empty: both buffers must still be defined
            return pa.StringArray.from_buffers(
                n, pa.py_buffer(np.zeros(n + 1, dtype=np.int32)),
                pa.py_buffer(np.zeros(0, dtype=np.uint8)),
                pa.array(valid[:n]).buffers()[1] if n else None)
        arrow_offsets = np.empty(n + 1, dtype=np.int32)
        values = np.empty(total, dtype=np.uint8)
        wrote = gather_string(
            staged.data, np.ascontiguousarray(staged.offsets[:n]),
            np.ascontiguousarray(staged.lengths[:n]),
            np.ascontiguousarray(valid[:n], dtype=np.uint8), spec.index,
            arrow_offsets, values)
        if wrote != total:
            # numpy fallback (no native lib)
            offs = staged.offsets[:n, spec.index].astype(np.int32)
            lens32 = lens.astype(np.int32)
            arrow_offsets[0] = 0
            np.cumsum(lens32, out=arrow_offsets[1:])
            if total:
                starts_rep = np.repeat(offs, lens32)
                prefix_rep = np.repeat(arrow_offsets[:-1], lens32)
                idx = np.arange(total, dtype=np.int32)
                idx -= prefix_rep
                idx += starts_rep
                values = staged.data[idx]
        validity = pa.array(valid[:n]).buffers()[1]
        # py_buffer over the ndarrays directly — no tobytes() copies
        return pa.StringArray.from_buffers(
            n, pa.py_buffer(arrow_offsets), pa.py_buffer(values), validity)

    # object kinds whose Postgres text IS the exact destination form
    # (Arrow/numeric-as-text stance, models/table_row.to_arrow): keep them
    # as Arrow text columns, parse to Python objects only on value() access
    _LAZY_TEXT_KINDS = frozenset({
        CellKind.STRING, CellKind.NUMERIC, CellKind.UUID, CellKind.JSON,
        CellKind.TIMETZ, CellKind.INTERVAL,
    })

    def _decode_object_column(self, staged: StagedBatch, spec: _ColSpec,
                              valid: np.ndarray) -> Any:
        col = self.schema.replicated_columns[spec.index]
        n = staged.n_rows
        if spec.kind in self._LAZY_TEXT_KINDS:
            # safe on the COPY path too: stage_copy_chunk routes every row
            # containing a backslash beyond bare-\N nulls to
            # cpu_fallback_rows, and the caller masks those out of `valid`
            # — the remaining rows' raw bytes ARE the exact text (the
            # per-row Python loop here measured 10× the whole decode)
            return self._gather_string_arrow(staged, spec, valid)
        # STRING never reaches here: it is in _LAZY_TEXT_KINDS, so the
        # Arrow-gather path above always returns first
        out: list[Any] = [None] * n
        offs = staged.offsets[:, spec.index]
        lens = staged.lengths[:, spec.index]
        data = staged.data
        oid = col.type_oid
        for i in np.flatnonzero(valid[:n]):
            text = data[offs[i] : offs[i] + lens[i]].tobytes().decode("utf-8")
            out[i] = parse_cell_text(text, oid)
        return out

    def _cpu_fixup(self, staged: StagedBatch, rows: np.ndarray,
                   columns: list[Column]) -> None:
        """Re-decode flagged rows with the CPU oracle and patch columns."""
        from ..models.table_row import _to_dense  # late: avoid cycle
        from ..postgres.codec.copy_text import unescape_copy_field

        cols = self.schema.replicated_columns
        for c in columns:
            if c.is_arrow and rows.size:
                # rare: fixup needs mutability — densify, PARSING lazy text
                # so the column's value type stays consistent across rows
                if c.lazy_text_oid is not None:
                    oid = c.lazy_text_oid
                    c.data = [None if v is None else parse_cell_text(v, oid)
                              for v in c.data.to_pylist()]
                    c.lazy_text_oid = None
                else:
                    c.data = c.data.to_pylist()
        for i in rows:
            for j, col in enumerate(cols):
                c = columns[j]
                raw = staged.field_bytes(int(i), j)
                if raw is None:
                    continue
                if staged.copy_escapes:
                    raw = unescape_copy_field(raw)
                value = parse_cell_text(raw.decode("utf-8"), col.type_oid)
                if c.is_dense:
                    try:
                        c.data[i] = _to_dense(c.schema.kind, value) \
                            if value is not None else 0
                    except (OverflowError, ValueError) as e:
                        # value doesn't fit the column's declared type —
                        # corrupt data, same as a Rust i32 parse failure
                        from ..models.errors import ErrorKind, EtlError

                        raise EtlError(
                            ErrorKind.ROW_CONVERSION_FAILED,
                            f"row {i} col {col.name}: value out of range "
                            f"for {col.type_name}: {value!r}") from e
                else:
                    c.data[i] = value
                c.validity[i] = value is not None

    def _assemble(self, staged: StagedBatch, specs: tuple, packed_np,
                  bad_rows=None,
                  plan=None) -> "tuple[ColumnarBatch, np.ndarray]":
        """Shared completion core: fetched packed words (+ the staged
        bookkeeping they index) → typed columns + CPU fixup. For a fused-
        filter decode `staged` is the COMPACTED view (staging.gather_rows)
        and `packed_np` the count-sized slice, so every index here —
        including the fallback rows returned for the caller's post-fixup
        predicate re-check — lives in the compacted space. With `plan`
        (the canonical layout the batch packed into) the words carry
        canonical slot order: each real column unpacks from
        plan.slot_of[j] and the phantom padding slots are never read —
        column outputs index by schema position, so the decoded batch is
        byte-identical to the exact layout's."""
        from .bitpack import layout_for_specs, unpack_host

        n = staged.n_rows
        cols = self.schema.replicated_columns
        valid_full = ~staged.nulls & ~staged.toast

        columns: list[Column] = [None] * len(cols)  # type: ignore[list-item]
        fallback = set(int(r) for r in staged.cpu_fallback_rows)
        if packed_np is None and self._dense:
            # small batch: every row goes to the oracle once; skip the
            # per-column width/ok machinery entirely
            fallback.update(range(n))
        if bad_rows is not None:
            # nibble pack flagged bytes outside the symbol alphabet
            fallback.update(np.flatnonzero(bad_rows[:n]).tolist())
        if packed_np is not None:
            for spec, (_, _, w, _) in zip(self._dense, specs):
                if staged.max_field_len(spec.index) > w:
                    too_big = staged.lengths[:n, spec.index] > w
                    fallback.update(np.flatnonzero(too_big).tolist())

        pspecs = plan.specs if plan is not None else specs
        layout = layout_for_specs(pspecs) if packed_np is not None else None
        for j, spec in enumerate(self._dense):
            valid = valid_full[:n, spec.index].copy()
            toast_col = staged.toast[:n, spec.index]
            if packed_np is None:
                # small batch: host decode of every row via the oracle
                data = np.zeros(n, dtype=dense_dtype(spec.kind))
            else:
                slot = plan.slot_of[j] if plan is not None else j
                ok, comps = unpack_host(layout, packed_np, slot, n)
                bad = ~ok & valid
                if bad.any():
                    fallback.update(np.flatnonzero(bad).tolist())
                data = _combine(spec.kind, comps)
            columns[spec.index] = Column(
                cols[spec.index], data, valid,
                toast_col if toast_col.any() else None)

        for spec in self._object:
            valid = valid_full[:, spec.index]
            toast_col = staged.toast[:n, spec.index]
            data_list = self._decode_object_column(
                staged, spec,
                valid & ~np.isin(np.arange(staged.row_capacity),
                                 list(fallback)) if fallback else valid)
            lazy_oid = None
            if spec.kind in self._LAZY_TEXT_KINDS \
                    and spec.kind is not CellKind.STRING:
                lazy_oid = cols[spec.index].type_oid
            columns[spec.index] = Column(
                cols[spec.index], data_list, valid[:n].copy(),
                toast_col if toast_col.any() else None,
                lazy_text_oid=lazy_oid)

        from ..telemetry.metrics import (
            ETL_DEVICE_DECODE_FALLBACK_ROWS_TOTAL, registry)

        rows_arr = np.zeros(0, dtype=np.int64)
        if fallback:
            rows_arr = np.asarray(sorted(r for r in fallback if r < n),
                                  dtype=np.int64)
            self._cpu_fixup(staged, rows_arr, columns)
            if self._telemetry:
                registry.counter_inc(ETL_DEVICE_DECODE_FALLBACK_ROWS_TOTAL,
                                     len(rows_arr))
        return ColumnarBatch(self.schema, columns), rows_arr

    def _shard_health(self, shard_bad) -> None:
        from ..telemetry.metrics import (
            ETL_DECODE_MESH_FALLBACK_CANDIDATE_ROWS_TOTAL,
            ETL_DECODE_MESH_SHARD_FALLBACK_CANDIDATES, registry)

        sb = np.asarray(shard_bad)
        total_bad = float(sb.sum())
        if total_bad:
            registry.counter_inc(
                ETL_DECODE_MESH_FALLBACK_CANDIDATE_ROWS_TOTAL, total_bad)
        # last-batch shard-health snapshot: a single sick shard (one
        # device corrupting its block) shows up here as skew
        for s in range(sb.shape[0]):
            registry.gauge_set(ETL_DECODE_MESH_SHARD_FALLBACK_CANDIDATES,
                               float(sb[s]), {"shard": str(s)})

    def _filter_telemetry(self, n_in: int, n_out: int,
                          fetched_bytes: float) -> None:
        from ..telemetry.metrics import (ETL_DECODE_FETCHED_BYTES_TOTAL,
                                         ETL_DECODE_FILTER_SELECTIVITY,
                                         ETL_DECODE_ROWS_FILTERED_TOTAL,
                                         registry)

        if not self._telemetry:
            return
        if fetched_bytes:
            registry.counter_inc(ETL_DECODE_FETCHED_BYTES_TOTAL,
                                 float(fetched_bytes))
        if n_in > n_out:
            registry.counter_inc(ETL_DECODE_ROWS_FILTERED_TOTAL,
                                 n_in - n_out)
        if n_in and self._row_filter is not None:
            registry.gauge_set(ETL_DECODE_FILTER_SELECTIVITY, n_out / n_in)

    def _complete(self, staged: StagedBatch, specs: tuple,
                  packed, bad_rows=None,
                  meta: "_PackedInputs | None" = None) -> ColumnarBatch:
        import time as _time

        from ..telemetry.metrics import (ETL_DEVICE_DECODE_ROWS_TOTAL,
                                         ETL_DEVICE_DECODE_SECONDS, registry)

        _t0 = _time.perf_counter()
        n = staged.n_rows
        if self._telemetry:
            # n = staged.n_rows: bucket- and mesh-padding tail rows are
            # excluded from every error/telemetry counter by construction
            registry.counter_inc(ETL_DEVICE_DECODE_ROWS_TOTAL, n)
        if meta is not None and meta.filtered and packed is not None:
            batch = self._complete_filtered(staged, specs, packed,
                                            bad_rows, meta)
        else:
            shard_bad = None
            if isinstance(packed, tuple):
                # mesh-sharded dispatch: (packed words, per-shard fallback-
                # candidate counts reduced on device). The counts are HOST-
                # aggregated into shard-health telemetry; the exact
                # fallback set still comes from the unpacked ok bits, so
                # sharded and single-device decodes stay byte-identical.
                packed, shard_bad = packed
            packed_np = np.asarray(packed) if packed is not None else None
            if shard_bad is not None and self._telemetry:
                self._shard_health(shard_bad)
            batch, fixups = self._assemble(
                staged, specs, packed_np, bad_rows,
                plan=meta.plan if meta is not None else None)
            fetched = packed_np.nbytes if packed_np is not None else 0.0
            host_rf = self._host_filter_for(staged)
            if meta is not None and meta.egress is not None \
                    and host_rf is None:
                # attach the device-rendered wire buffers; `fixups` (the
                # oracle-patched rows) become the untrusted set whose
                # lines destinations re-render per value. Host-filtered
                # batches skip the attach: take() re-indexes rows.
                from . import egress as egress_mod

                try:
                    batch.device_egress = egress_mod.materialize(
                        meta.egress, meta.plan, self._dense, n, fixups)
                except Exception:
                    import logging

                    logging.getLogger("etl_tpu.ops").warning(
                        "egress materialization failed; batch ships "
                        "without wire buffers", exc_info=True)
            if host_rf is not None:
                # predicate outside the device envelope (or an oracle-
                # routed batch): the same filter applies host-side over
                # the decoded batch — correct, without the fetch win
                keep = host_rf.host_keep(batch)
                surv = np.flatnonzero(keep).astype(np.int64)
                batch = batch.take(surv)
                batch.source_rows = surv
                self._filter_telemetry(n, len(surv), fetched)
            elif self._telemetry and fetched:
                from ..telemetry.metrics import \
                    ETL_DECODE_FETCHED_BYTES_TOTAL

                registry.counter_inc(ETL_DECODE_FETCHED_BYTES_TOTAL,
                                     float(fetched))
        # completion time (fetch wait + unpack + combines + object cols);
        # dispatch/transfer overlap is deliberately excluded
        if self._telemetry:
            registry.histogram_observe(ETL_DEVICE_DECODE_SECONDS,
                                       _time.perf_counter() - _t0)
        return batch

    def _complete_filtered(self, staged: StagedBatch, specs: tuple,
                           packed, bad_rows,
                           meta: "_PackedInputs") -> ColumnarBatch:
        """Completion of a fused coerce→filter→pack dispatch: fetch the
        survivor count + the 1-bit-per-row keep mask, fetch a count-sized
        slice of the compacted words (single device — fetched bytes scale
        with selectivity; the mesh path fetches its row-sharded words
        whole and slices per shard block on host), then run the normal
        completion against the COMPACTED staged view. Fallback
        bookkeeping lives in the compacted index space throughout;
        force-kept and fixed-up survivors get one exact host
        re-evaluation so the final batch is byte-identical to the host
        oracle."""
        from .bitpack import unpack_keep_mask
        from .staging import slice_rows

        pred = self._device_filter_for(staged)
        mesh_shards = self.mesh.size if meta.use_mesh else None
        if mesh_shards is not None:
            words_d, mask_d, counts_d, shard_bad_d = packed
            if self._telemetry:
                self._shard_health(shard_bad_d)
        else:
            words_d, mask_d, counts_d = packed
        counts = np.asarray(counts_d)
        mask_np = np.asarray(mask_d)
        R = meta.row_capacity
        fetched = float(counts.nbytes + mask_np.nbytes)
        survivors = unpack_keep_mask(mask_np, R)
        if mesh_shards is None:
            S = int(counts[0])
            Sb = slice_rows(S, R)
            if Sb:
                # count-sized device slice: the only words bytes that
                # ever cross the link are the survivors' (+ the slice
                # bucket's pad slack)
                words_np = np.asarray(words_d[:, :Sb])
                fetched += words_np.nbytes
                words_np = words_np[:, :S]
            else:
                words_np = np.zeros((words_d.shape[0], 0), dtype=np.uint32)
        else:
            words_full = np.asarray(words_d)
            fetched += words_full.nbytes
            rps = R // mesh_shards
            parts = [np.arange(s * rps, s * rps + int(counts[s]),
                               dtype=np.int64)
                     for s in range(mesh_shards) if counts[s] > 0]
            sel = np.concatenate(parts) if parts \
                else np.zeros(0, dtype=np.int64)
            words_np = words_full[:, sel]
            S = len(sel)
        assert len(survivors) == S, (len(survivors), S)
        cstaged = staged.gather_rows(survivors)
        cbad = bad_rows[survivors] if bad_rows is not None else None
        batch, fixup_rows = self._assemble(cstaged, specs, words_np, cbad)
        # exact arbitration for rows the device could not judge: force-
        # kept rows (escapes / nibble / oversize / TOAST on a referenced
        # field) and every fixed-up row re-evaluate on their DECODED
        # values; rows the re-check rejects compact out host-side
        suspect = np.zeros(S, dtype=bool)
        if meta.row_flags is not None and S:
            suspect |= meta.row_flags[survivors] > 1
        if len(fixup_rows):
            suspect[fixup_rows] = True
        if suspect.any():
            keep_h = pred.host_keep(batch)
            final = ~suspect | keep_h
            if not final.all():
                sel2 = np.flatnonzero(final).astype(np.int64)
                batch = batch.take(sel2)
                survivors = survivors[sel2]
        batch.source_rows = survivors
        self._filter_telemetry(staged.n_rows, len(survivors), fetched)
        return batch

    # -- public -------------------------------------------------------------

    def _route(self, staged: StagedBatch) -> tuple[str, tuple]:
        """Pick the decode path for this batch: ("device"|"host"|"oracle",
        specs). Owns the routed-rows telemetry so the pipelined and serial
        entry points count identically."""
        cols = self.schema.replicated_columns
        if len(cols) != staged.n_cols:
            raise ValueError(
                f"staged batch has {staged.n_cols} cols, schema expects "
                f"{len(cols)}")
        from ..telemetry.metrics import (
            ETL_DECODE_ROUTED_DEVICE_ROWS_TOTAL,
            ETL_DECODE_ROUTED_HOST_ROWS_TOTAL,
            ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL, registry)

        if host_oracle_forced():
            # supervision escalation (supervisor._detected): repeated
            # device-side stalls park EVERY batch on the host oracle
            # until the degrade cooldown lapses — availability beats the
            # device-decode win, same stance as the per-batch OOM
            # fallback in ops/pipeline._process
            if self._telemetry:
                registry.counter_inc(ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL,
                                     staged.n_rows)
            return "oracle", ()
        if self._dense and staged.n_rows >= self.device_min_rows:
            if self._telemetry:
                registry.counter_inc(ETL_DECODE_ROUTED_DEVICE_ROWS_TOTAL,
                                     staged.n_rows)
            return "device", self._specs(staged, self._widths(staged))
        if self._dense and staged.n_rows >= self.host_min_rows \
                and _host_cpu_device() is not None:
            specs = self._host_specs()
            if self.nonblocking_compile \
                    and not _host_fn_ready(self, staged, specs):
                # cold program: decode THIS batch on the oracle while the
                # build runs on a background thread — a synchronous
                # first-touch compile here (tens of seconds on wide
                # schemas) would freeze apply progress past the stall
                # deadline and spiral the watchdog into restarts
                if self._telemetry:
                    registry.counter_inc(ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL,
                                         staged.n_rows)
                return "oracle", ()
            if self._telemetry:
                registry.counter_inc(ETL_DECODE_ROUTED_HOST_ROWS_TOTAL,
                                     staged.n_rows)
            return "host", specs
        if self._telemetry:
            registry.counter_inc(ETL_DECODE_ROUTED_ORACLE_ROWS_TOTAL,
                                 staged.n_rows)
        return "oracle", ()

    @hot_loop
    def decode_async(self, staged: StagedBatch) -> _PendingDecode:
        """Dispatch the device work and return immediately; stage the next
        batch while this one is in flight. @hot_loop: dispatch-only — the
        fetch happens at `_PendingDecode.result()` on the consumer.
        (ops/pipeline.DecodePipeline runs the same route→pack→dispatch
        chain with the pack stage on a worker thread and pooled arenas.)"""
        mode, specs = self._route(staged)
        if mode == "oracle":
            return _PendingDecode(self, staged, (), None, None)
        value, packed = self._device_call(staged, specs,
                                          host=mode == "host")
        return _PendingDecode(self, staged, specs, value, packed)

    def decode(self, staged: StagedBatch) -> ColumnarBatch:
        return self.decode_async(staged).result()
