"""Pallas TPU kernel variant of the decode program — lane-packed.

The XLA path (ops/engine.build_device_program) fuses well; this kernel
exists to (a) control VMEM blocking explicitly and (b) get full VPU
lane utilization out of the byte-wise parse chain. Round-3's kernel ran
the row-major [R, L] program body and lost 18x to XLA: Mosaic padded
every 1-12-lane-wide per-column intermediate to 128 lanes, wasting >90%
of the VPU (VERDICT r3 #8). This version is the lane-packed redesign
that docstring implied:

- inputs arrive TRANSPOSED ([W, R] bytes, [C, R] lengths — XLA lays
  out the transpose once, outside the kernel);
- each field byte position is a full [R] vector (R = block rows, a
  multiple of 128), so every parse op runs on fully-populated lanes;
- the per-position work is a static Python loop over the field width
  (ops/parsers_lanes.py — semantics transcribed 1:1 from parsers.py,
  shared scalar helpers, covered by the same differential suites).

`DeviceDecoder(use_pallas=True)` selects it; `bench.py` measures BOTH
engines every run and the headline takes whichever is faster. If the
kernel fails to compile the decoder logs and falls back to the XLA
program permanently for that instance (engine._device_call).

Falls back to interpret mode off-TPU so the differential tests cover
the same code path on CPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..models.pgtypes import CellKind
from .parsers_lanes import parse_column_lanes, unpack_nibbles_lanes

# Block row count. Lane-packed VMEM footprint is the [W, blk] byte block
# plus [R]-vector temporaries — far below the row-major version's
# 13.6 KB/row, so blocks can be larger; 2048 keeps the whole block +
# temporaries comfortably inside the 16 MB scoped limit even at 62
# dense columns.
DEFAULT_BLOCK_ROWS = 2048

# The fully-unrolled parse chain crashes the Mosaic compiler
# (tpu_compile_helper exit 1) once the kernel body grows past ~150
# unrolled byte POSITIONS (sum of column widths — nibble packing halves
# the gathered bytes but not the positions, so the cap is width-based)
# — measured on v5e: 12 x 12-byte int columns (144 positions) compile,
# 14 (168) kill the compiler. Wide schemas take the XLA program instead:
# engine._device_call consults pallas_supported BEFORE building and
# flips the decoder's use_pallas flag, so no doomed remote-compile
# attempt happens and engine labels stay honest.
MAX_TOTAL_WIDTH = 144


def pallas_supported(specs) -> bool:
    if jax.default_backend() != "tpu":
        return True  # interpret mode — no Mosaic, nothing to crash
    return sum(w for _, _, w, _ in specs) <= MAX_TOTAL_WIDTH


def build_pallas_program(specs: tuple[tuple[int, CellKind, int, int], ...],
                         nibble: bool = False,
                         block_rows: int = DEFAULT_BLOCK_ROWS,
                         interpret: bool | None = None):
    """Same contract as engine.build_device_program, lowered via Pallas."""
    from .bitpack import layout_for_specs, pack_device

    layout = layout_for_specs(specs)
    k_out = layout.n_words
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    total_w = sum(w for _, _, w, _ in specs)
    w_in = total_w // 2 if nibble else total_w

    def kernel(bmat_ref, len_ref, out_ref):
        columns = []
        w_off = 0
        for j, (_col_idx, kind, width, _bw) in enumerate(specs):
            if nibble:
                packed = [bmat_ref[w_off // 2 + i, :].astype(jnp.int32)
                          for i in range(width // 2)]
                rows = unpack_nibbles_lanes(packed, width)
            else:
                rows = [bmat_ref[w_off + i, :].astype(jnp.int32)
                        for i in range(width)]
            w_off += width
            lengths = len_ref[j, :].astype(jnp.int32)
            comp, ok = parse_column_lanes(kind, rows, lengths)
            columns.append((ok, comp))
        out_ref[:, :] = pack_device(layout, columns)

    def fn(bmat, lengths):
        R = bmat.shape[0]
        blk = min(block_rows, R)
        assert R % blk == 0, (R, blk)
        grid = (R // blk,)
        # transpose OUTSIDE the kernel: one XLA layout pass, then every
        # kernel read of a byte position is a contiguous [blk] vector
        bmat_t = bmat.T
        lengths_t = lengths.T
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((w_in, blk), lambda i: (0, i)),
                pl.BlockSpec((lengths.shape[1], blk), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((k_out, blk), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((k_out, R), jnp.uint32),
            interpret=interpret,
        )(bmat_t, lengths_t)

    return fn
