"""Pallas TPU kernel variant of the decode program — lane-packed.

The XLA path (ops/engine.build_device_program) fuses well; this kernel
exists to (a) control VMEM blocking explicitly and (b) get full VPU
lane utilization out of the byte-wise parse chain. Round-3's kernel ran
the row-major [R, L] program body and lost 18x to XLA: Mosaic padded
every 1-12-lane-wide per-column intermediate to 128 lanes, wasting >90%
of the VPU (VERDICT r3 #8). This version is the lane-packed redesign
that docstring implied:

- inputs arrive TRANSPOSED ([W, R] bytes, [C, R] lengths — XLA lays
  out the transpose once, outside the kernel);
- each field byte position is a full [R] vector (R = block rows, a
  multiple of 128), so every parse op runs on fully-populated lanes;
- the per-position work is a static Python loop over the field width
  (ops/parsers_lanes.py — semantics transcribed 1:1 from parsers.py,
  shared scalar helpers, covered by the same differential suites).

`DeviceDecoder(use_pallas=True)` selects it; `bench.py` measures BOTH
engines every run and the headline takes whichever is faster. If the
kernel fails to compile the decoder logs and falls back to the XLA
program permanently for that instance (engine._device_call).

Falls back to interpret mode off-TPU so the differential tests cover
the same code path on CPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..models.pgtypes import CellKind
from .parsers_lanes import parse_column_lanes, unpack_nibbles_lanes

# Block row count. Lane-packed VMEM footprint is the [W, blk] byte block
# plus [R]-vector temporaries — far below the row-major version's
# 13.6 KB/row, so blocks can be larger; 2048 keeps the whole block +
# temporaries comfortably inside the 16 MB scoped limit even at 62
# dense columns.
DEFAULT_BLOCK_ROWS = 2048

# The fully-unrolled parse chain crashes the Mosaic compiler
# (tpu_compile_helper exit 1) once the kernel body grows past ~150
# unrolled byte POSITIONS (sum of column widths — nibble packing halves
# the gathered bytes but not the positions, so the cap is width-based)
# — measured on v5e: 12 x 12-byte int columns (144 positions) compile,
# 14 (168) kill the compiler. Wide schemas take the XLA program instead:
# engine._device_call consults pallas_supported BEFORE building and
# flips the decoder's use_pallas flag, so no doomed remote-compile
# attempt happens and engine labels stay honest.
MAX_TOTAL_WIDTH = 144


def pallas_supported(specs) -> bool:
    if jax.default_backend() != "tpu":
        return True  # interpret mode — no Mosaic, nothing to crash
    return sum(w for _, _, w, _ in specs) <= MAX_TOTAL_WIDTH


def build_pallas_program(specs: tuple[tuple[int, CellKind, int, int], ...],
                         nibble: bool = False,
                         block_rows: int = DEFAULT_BLOCK_ROWS,
                         interpret: bool | None = None,
                         pred=None):
    """Same contract as engine.build_device_program, lowered via Pallas.

    With `pred` (predicate.CompiledRowFilter) the kernel is the FUSED
    coerce→filter→pack step: the publication row filter evaluates inside
    the kernel body over the parsed [R]-lane component vectors (the same
    `predicate.device_keep` evaluator the XLA twin uses — comps dicts
    have the identical shape in both conventions), the keep bits mask the
    packed words in-register so filtered rows' values never reach the HBM
    output block, and a second (1, blk) output carries the keep bits out.
    The row-compaction epilogue (`bitpack.compact_packed` — an in-block
    exclusive prefix-sum scatter) runs as XLA ops over the kernel's
    outputs: cross-block survivor destinations depend on every earlier
    block's count, which a grid-parallel kernel cannot know, so the
    scatter lives outside the grid while the per-row verdicts stay fused
    in-kernel. Output structure matches the XLA twin exactly:
    (words_compacted, keep_mask, counts)."""
    from .bitpack import compact_packed, layout_for_specs, pack_device

    layout = layout_for_specs(specs)
    k_out = layout.n_words
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    total_w = sum(w for _, _, w, _ in specs)
    w_in = total_w // 2 if nibble else total_w
    ref_cols = frozenset(pred.referenced_indices) if pred is not None \
        else frozenset()

    def parse_block(bmat_ref, len_ref):
        columns = []
        colmap = {}
        w_off = 0
        for j, (col_idx, kind, width, _bw) in enumerate(specs):
            if nibble:
                packed = [bmat_ref[w_off // 2 + i, :].astype(jnp.int32)
                          for i in range(width // 2)]
                rows = unpack_nibbles_lanes(packed, width)
            else:
                rows = [bmat_ref[w_off + i, :].astype(jnp.int32)
                        for i in range(width)]
            w_off += width
            lengths = len_ref[j, :].astype(jnp.int32)
            comp, ok = parse_column_lanes(kind, rows, lengths)
            columns.append((ok, comp))
            if col_idx in ref_cols:
                colmap[col_idx] = (comp, ok, lengths == 0)
        return columns, colmap

    def kernel(bmat_ref, len_ref, out_ref):
        columns, _ = parse_block(bmat_ref, len_ref)
        out_ref[:, :] = pack_device(layout, columns)

    def kernel_filtered(bmat_ref, len_ref, flags_ref, out_ref, keep_ref):
        columns, colmap = parse_block(bmat_ref, len_ref)
        keep = pred.device_keep(colmap, flags_ref[0, :].astype(jnp.int32))
        keep_i = keep.astype(jnp.int32)
        # mask in-register: a filtered row's packed words never reach the
        # HBM output block — the epilogue scatter only moves survivors
        out_ref[:, :] = pack_device(layout, columns) \
            * keep_i[None, :].astype(jnp.uint32)
        keep_ref[:, :] = keep_i[None, :]

    def fn(bmat, lengths, row_flags=None):
        R = bmat.shape[0]
        blk = min(block_rows, R)
        assert R % blk == 0, (R, blk)
        grid = (R // blk,)
        # transpose OUTSIDE the kernel: one XLA layout pass, then every
        # kernel read of a byte position is a contiguous [blk] vector
        bmat_t = bmat.T
        lengths_t = lengths.T
        if pred is None:
            return pl.pallas_call(
                kernel,
                grid=grid,
                in_specs=[
                    pl.BlockSpec((w_in, blk), lambda i: (0, i)),
                    pl.BlockSpec((lengths.shape[1], blk), lambda i: (0, i)),
                ],
                out_specs=pl.BlockSpec((k_out, blk), lambda i: (0, i)),
                out_shape=jax.ShapeDtypeStruct((k_out, R), jnp.uint32),
                interpret=interpret,
            )(bmat_t, lengths_t)
        words, keep = pl.pallas_call(
            kernel_filtered,
            grid=grid,
            in_specs=[
                pl.BlockSpec((w_in, blk), lambda i: (0, i)),
                pl.BlockSpec((lengths.shape[1], blk), lambda i: (0, i)),
                pl.BlockSpec((1, blk), lambda i: (0, i)),
            ],
            out_specs=[
                pl.BlockSpec((k_out, blk), lambda i: (0, i)),
                pl.BlockSpec((1, blk), lambda i: (0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((k_out, R), jnp.uint32),
                jax.ShapeDtypeStruct((1, R), jnp.int32),
            ],
            interpret=interpret,
        )(bmat_t, lengths_t, row_flags.reshape(1, R))
        # compaction epilogue: in-block prefix-sum scatter of survivors
        return compact_packed(words, keep[0] > 0, 1)

    return fn
