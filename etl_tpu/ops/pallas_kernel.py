"""Pallas TPU kernel variant of the decode program.

The XLA path (ops/engine.build_device_program) already fuses well; this
kernel exists to (a) control VMEM blocking explicitly — each grid step
parses a row block entirely in VMEM, streaming bmat blocks in and packed
result blocks out without materializing any [R, W] intermediate in HBM —
and (b) serve as the template for fusing more of the pipeline (validity
masks, filtering) as column counts grow. `DeviceDecoder(use_pallas=True)`
selects it; `bench.py --mode decode` measures BOTH engines every run and
reports both numbers. XLA stays the production default: current libtpu's
Mosaic rejects some byte-wise lowerings, and when the kernel fails to
compile the decoder logs and falls back to the XLA program permanently
for that instance (engine._device_call), so pallas can only win the
bench headline when it genuinely compiles and measures faster.

Falls back to interpret mode off-TPU so the differential tests cover the
same code path on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..models.pgtypes import CellKind
from . import parsers

DEFAULT_BLOCK_ROWS = 4096


def build_pallas_program(specs: tuple[tuple[int, CellKind, int, int], ...],
                         nibble: bool = False,
                         block_rows: int = DEFAULT_BLOCK_ROWS,
                         interpret: bool | None = None):
    """Same contract as engine.build_device_program, lowered via Pallas."""
    from .bitpack import layout_for_specs

    layout = layout_for_specs(specs)
    k_out = layout.n_words
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def kernel(bmat_ref, len_ref, out_ref):
        from .bitpack import parse_and_pack

        bmat = bmat_ref[:, :]
        lengths = len_ref[:, :].astype(jnp.int32)
        out_ref[:, :] = parse_and_pack(bmat, lengths, specs, nibble)

    def fn(bmat, lengths):
        R = bmat.shape[0]
        blk = min(block_rows, R)
        assert R % blk == 0, (R, blk)
        grid = (R // blk,)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((blk, bmat.shape[1]), lambda i: (i, 0)),
                pl.BlockSpec((blk, lengths.shape[1]), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((k_out, blk), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((k_out, R), jnp.uint32),
            interpret=interpret,
        )(bmat, lengths)

    return fn
