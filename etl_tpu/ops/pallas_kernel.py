"""Pallas TPU kernel variant of the decode program.

The XLA path (ops/engine.build_device_program) already fuses well; this
kernel exists to (a) control VMEM blocking explicitly — each grid step
parses a row block entirely in VMEM, streaming bmat blocks in and packed
result blocks out without materializing any [R, W] intermediate in HBM —
and (b) serve as the template for fusing more of the pipeline (validity
masks, filtering) as column counts grow. `DeviceDecoder(use_pallas=True)`
selects it; `bench.py --mode decode` measures BOTH engines every run and
reports both numbers. XLA stays the production default BY MEASUREMENT
(v5e, 262k-row pgbench batches): the XLA-fused program sustains ~1.47M
rec/s while this kernel does ~98k — Mosaic lowers the byte-wise parse
chain onto 128-lane-padded vectors at 1-12 useful lanes each, wasting
>90% of the VPU, and the 256-step grid serializes what XLA fuses into
one pass. If the kernel fails to compile the decoder logs and falls
back to the XLA program permanently for that instance
(engine._device_call), so pallas can only win the bench headline when
it genuinely compiles and measures faster.

Falls back to interpret mode off-TPU so the differential tests cover the
same code path on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..models.pgtypes import CellKind
from . import parsers

# Scoped-VMEM bound, measured on v5e (16 MB scoped limit): the kernel's
# per-column byte slices are 1-12 lanes wide and Mosaic pads every
# intermediate to 128 lanes, so the parse chain costs ~13.6 KB/row of
# VMEM. 1024 rows/block ≈ 13.9 MB compiles; 2048 (27.8 MB) and the old
# 4096 (55.6 MB) are rejected with a vmem-stack OOM at AOT time.
DEFAULT_BLOCK_ROWS = 1024


def build_pallas_program(specs: tuple[tuple[int, CellKind, int, int], ...],
                         nibble: bool = False,
                         block_rows: int = DEFAULT_BLOCK_ROWS,
                         interpret: bool | None = None):
    """Same contract as engine.build_device_program, lowered via Pallas."""
    from .bitpack import layout_for_specs

    layout = layout_for_specs(specs)
    k_out = layout.n_words
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def kernel(bmat_ref, len_ref, out_ref):
        from .bitpack import parse_and_pack

        bmat = bmat_ref[:, :]
        lengths = len_ref[:, :].astype(jnp.int32)
        out_ref[:, :] = parse_and_pack(bmat, lengths, specs, nibble)

    def fn(bmat, lengths):
        R = bmat.shape[0]
        blk = min(block_rows, R)
        assert R % blk == 0, (R, blk)
        grid = (R // blk,)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((blk, bmat.shape[1]), lambda i: (i, 0)),
                pl.BlockSpec((blk, lengths.shape[1]), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((k_out, blk), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((k_out, R), jnp.uint32),
            interpret=interpret,
        )(bmat, lengths)

    return fn
