"""Device-resident wire egress: render destination-ready text ON DEVICE.

The decode pipeline's last host stage — turning typed columns into wire
bytes (ClickHouse TSV fields, Snowpipe NDJSON values) — costs more than
the decode itself on the streaming path: per-batch numpy `astype("U21")`
round trips, per-value `str()` loops, and a Python `"\\t".join` per row.
This module moves the fixed-width, integer-arithmetic part of that work
into a SECOND jitted program that consumes the decode program's packed
`uint32[n_words, R]` words while they are still device-resident and
emits, per rendered column, left-aligned ASCII bytes plus per-row
lengths:

    egress(words) -> (ebytes uint8[R, sum(widths)], elens int32[R, n])

Renderable kinds are the ones whose canonical Postgres text is pure
integer arithmetic — bools, the int family (minimal decimal, the same
digits `str(int)` produces), dates and timestamps (civil-from-days,
`YYYY-MM-DD[ HH:MM:SS.ffffff]`, always 6 fractional digits like
`np.datetime_as_string(unit="us")`). Floats stay host-side (shortest
`repr` is not vectorizable) and strings ride Arrow buffers the staging
layer already gathers zero-copy.

Correctness stance: the program renders only TRUSTED rows — rows the
decode path itself verified (`ok` bits, no oversize, no nibble flag).
Everything else (NULLs, TOAST, specials like `infinity` — which can
never even appear in the packed words, the 23-bit zigzag day field
excludes the sentinels — and fallback rows) is rendered host-side by the
existing per-value oracle and spliced in whole, so the assembled wire
bytes are byte-identical to the host columnar encoders by construction.
The host twins in this module (`int_text_fixed` & co.) produce the same
buffers from a decoded `ColumnarBatch` when no device buffer landed, so
destinations have ONE fast assembly path with two byte-identical buffer
sources.

All device arithmetic is int32/uint32 (the ir-widening contract bans
64-bit creep); the program is elementwise along rows, so the mesh path
shards it over 'sp' with zero collectives and no donation (the decode
program's words stay alive for the normal unpack fetch).
"""

from __future__ import annotations

import dataclasses
import logging
import threading

import numpy as np

from ..models.pgtypes import CellKind

log = logging.getLogger("etl_tpu.ops")

#: encoder names destinations declare via `Destination.egress_encoder`
ENCODER_TSV = "tsv"    # ClickHouse TSV fields (clickhouse.render_value)
ENCODER_JSON = "json"  # Snowpipe NDJSON values (snowflake JSON texts)

#: left-aligned output byte width per renderable kind (worst-case text)
_FIELD_WIDTH = {
    CellKind.BOOL: 5,          # "false"
    CellKind.I16: 6,           # "-32768"
    CellKind.I32: 11,          # "-2147483648"
    CellKind.U32: 10,          # "4294967295"
    CellKind.I64: 20,          # "-9223372036854775808"
    CellKind.DATE: 10,         # "YYYY-MM-DD"
    CellKind.TIMESTAMP: 26,    # "YYYY-MM-DD HH:MM:SS.ffffff"
    CellKind.TIMESTAMPTZ: 26,
}

#: max decimal digits of the magnitude per int-family kind
_MAX_DIGITS = {CellKind.I16: 5, CellKind.I32: 10, CellKind.U32: 10}

_INT_KINDS = frozenset({CellKind.I16, CellKind.I32, CellKind.U32})

#: kinds each encoder can render on device. TSV covers the temporals
#: (ClickHouse wants "YYYY-MM-DD HH:MM:SS.ffffff" — exactly the civil
#: rendering); NDJSON keeps temporals host-side (snowflake's JSON text
#: goes through the generic `json.dumps(encode_value(...))` path whose
#: quoting/format is not worth re-specifying on device).
ENCODER_KINDS = {
    ENCODER_TSV: frozenset({
        CellKind.BOOL, CellKind.I16, CellKind.I32, CellKind.U32,
        CellKind.I64, CellKind.DATE, CellKind.TIMESTAMP,
        CellKind.TIMESTAMPTZ,
    }),
    ENCODER_JSON: frozenset({
        CellKind.BOOL, CellKind.I16, CellKind.I32, CellKind.U32,
        CellKind.I64,
    }),
}

#: widest schema slice the egress program renders: past this the unrolled
#: per-digit selects bloat the program for columns the host renders
#: about as fast anyway (the win concentrates in the common narrow CDC
#: schemas)
EGRESS_MAX_COLS = 32


@dataclasses.dataclass(frozen=True)
class EgressPlan:
    """Static render plan for one (canonical specs, encoder) signature.
    `slots` are canonical slot indices into the pspecs the decode
    program packed — completion maps real schema columns onto them
    through the canonical plan's `slot_of`, exactly like column unpack."""

    encoder: str
    slots: tuple[int, ...]
    kinds: tuple[CellKind, ...]
    widths: tuple[int, ...]

    @property
    def total_width(self) -> int:
        return sum(self.widths)

    @property
    def offsets(self) -> tuple[int, ...]:
        out, off = [], 0
        for w in self.widths:
            out.append(off)
            off += w
        return tuple(out)


def plan_for_specs(pspecs: tuple, encoder: str) -> "EgressPlan | None":
    """The render plan for a packed layout, or None when the encoder is
    unknown, nothing in the layout is device-renderable, or the schema
    is too wide to be worth unrolling."""
    kinds_ok = ENCODER_KINDS.get(encoder)
    if kinds_ok is None or not pspecs:
        return None
    slots, kinds, widths = [], [], []
    for j, (_, kind, _, _) in enumerate(pspecs):
        if kind in kinds_ok:
            slots.append(j)
            kinds.append(kind)
            widths.append(_FIELD_WIDTH[kind])
    if not slots or len(slots) > EGRESS_MAX_COLS:
        return None
    return EgressPlan(encoder, tuple(slots), tuple(kinds), tuple(widths))


# ---------------------------------------------------------------------------
# the device program
# ---------------------------------------------------------------------------

def _slot_fields(layout, slot: int) -> dict:
    return {s.comp: s for s in layout.slots[slot]}


def _extract(words, slot) -> "object":
    """Raw uint32[R] field bytes of one packed slot (pre-zigzag) —
    the jnp mirror of bitpack.unpack_host's shift/mask math."""
    import jax.numpy as jnp

    w, sh = divmod(slot.bit_off, 32)
    v = words[w] >> sh
    if sh + slot.bits > 32:
        v = v | (words[w + 1] << (32 - sh))
    if slot.bits < 32:
        v = v & jnp.uint32((1 << slot.bits) - 1)
    return v


def _signed(raw):
    """Zigzag-decode a raw field to int32."""
    import jax.numpy as jnp

    u1 = (raw & jnp.uint32(1)).astype(jnp.int32)
    return (raw >> 1).astype(jnp.int32) ^ (-u1)


def _plain(raw):
    import jax.numpy as jnp

    return raw.astype(jnp.int32)


def _field_value(words, fields: dict, name: str, n_rows: int):
    """Decoded int32[R] component (zeros when the layout omitted it)."""
    import jax.numpy as jnp

    s = fields.get(name)
    if s is None:
        return jnp.zeros((n_rows,), dtype=jnp.int32)
    raw = _extract(words, s)
    return _signed(raw) if s.zigzag else _plain(raw)


def _digits_to_bytes(digit_at, nd, neg, width: int):
    """Left-aligned minimal-decimal bytes from a digit extractor.
    `digit_at(k)` returns the int32 digit at power-of-ten index `k`
    (k may be out of range for short numbers — extractors clip)."""
    import jax.numpy as jnp

    L = nd + neg
    out = []
    for p in range(width):
        k = nd - 1 - p + neg
        core = 48 + digit_at(k)
        if p == 0:
            core = jnp.where(neg > 0, jnp.int32(45), core)  # '-'
        out.append(jnp.where(p < L, core, 0).astype(jnp.uint8))
    return out, L


def _render_u32_family(mag, neg, width: int, max_digits: int):
    """mag uint32[R], neg int32[R] in {0,1} → minimal decimal."""
    import jax.numpy as jnp

    nd = jnp.ones(mag.shape, dtype=jnp.int32)
    for k in range(1, max_digits):
        nd = nd + (mag >= jnp.uint32(10 ** k)).astype(jnp.int32)
    p10 = jnp.array([10 ** i for i in range(max_digits)], dtype=jnp.uint32)

    def digit_at(k):
        kc = jnp.clip(k, 0, max_digits - 1)
        return ((mag // p10[kc]) % 10).astype(jnp.int32)

    return _digits_to_bytes(digit_at, nd, neg, width)


def _limb_digits(limb, hi: int):
    """Digit count of a base-10^9 limb (1..9), uint32 input."""
    import jax.numpy as jnp

    nd = jnp.ones(limb.shape, dtype=jnp.int32)
    for k in range(1, hi):
        nd = nd + (limb >= jnp.uint32(10 ** k)).astype(jnp.int32)
    return nd


def _render_i64(neg, l0, l1, l2, width: int):
    """Minimal decimal of a base-10^9 limbed int64 magnitude. The pack
    layout bounds l2 <= 9 (a 19-digit magnitude's top limb), so digit 18
    is l2 itself."""
    import jax.numpy as jnp

    nd = jnp.where(
        l2 > 0, jnp.int32(19),
        jnp.where(l1 > 0, 9 + _limb_digits(l1, 9), _limb_digits(l0, 9)))
    p10 = jnp.array([10 ** i for i in range(9)], dtype=jnp.uint32)

    def digit_at(k):
        kc = jnp.clip(k, 0, 18)
        d0 = (l0 // p10[jnp.clip(kc, 0, 8)]) % 10
        d1 = (l1 // p10[jnp.clip(kc - 9, 0, 8)]) % 10
        return jnp.where(kc < 9, d0.astype(jnp.int32),
                         jnp.where(kc < 18, d1.astype(jnp.int32),
                                   (l2 % 10).astype(jnp.int32)))

    return _digits_to_bytes(digit_at, nd, neg.astype(jnp.int32), width)


_TRUE = (116, 114, 117, 101, 0)    # "true\0"
_FALSE = (102, 97, 108, 115, 101)  # "false"


def _render_bool(v, width: int):
    import jax.numpy as jnp

    t = v > 0
    out = [jnp.where(t, jnp.uint8(_TRUE[p]), jnp.uint8(_FALSE[p]))
           for p in range(width)]
    return out, jnp.where(t, jnp.int32(4), jnp.int32(5))


def _civil(days):
    """Howard Hinnant's civil_from_days, all int32. Trusted rows carry
    days for years 1..9999 (the parser's ok range), so z stays positive
    and every floor division is over non-negative operands."""
    z = days + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    import jax.numpy as jnp

    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2).astype(jnp.int32)
    return y, m, d


def _date_bytes(y, m, d) -> list:
    import jax.numpy as jnp

    def u8(x):
        return x.astype(jnp.uint8)

    def c(ch):
        return jnp.full(y.shape, ch, dtype=jnp.uint8)

    return [u8(48 + (y // 1000) % 10), u8(48 + (y // 100) % 10),
            u8(48 + (y // 10) % 10), u8(48 + y % 10), c(45),
            u8(48 + m // 10), u8(48 + m % 10), c(45),
            u8(48 + d // 10), u8(48 + d % 10)]


def _render_date(days, width: int):
    import jax.numpy as jnp

    y, m, d = _civil(days)
    return _date_bytes(y, m, d), jnp.full(days.shape, width,
                                          dtype=jnp.int32)


def _render_timestamp(days, ms, us, width: int):
    """`YYYY-MM-DD HH:MM:SS.ffffff` — np.datetime_as_string(unit='us')
    with 'T' already a space. TIMESTAMPTZ rows arrive with ms shifted by
    the zone offset (possibly negative / >= a day): normalize into
    [0, 86_400_000) and carry whole days first."""
    import jax.numpy as jnp

    day_adj = ms // 86_400_000  # floor division: -1/0/+1
    ms = ms - day_adj * 86_400_000
    days = days + day_adj
    y, m, d = _civil(days)
    hh = ms // 3_600_000
    mi = (ms // 60_000) % 60
    ss = (ms // 1_000) % 60
    frac = (ms % 1_000) * 1_000 + us

    def u8(x):
        return x.astype(jnp.uint8)

    def c(ch):
        return jnp.full(days.shape, ch, dtype=jnp.uint8)

    out = _date_bytes(y, m, d)
    out.append(c(32))  # ' '
    out += [u8(48 + hh // 10), u8(48 + hh % 10), c(58),
            u8(48 + mi // 10), u8(48 + mi % 10), c(58),
            u8(48 + ss // 10), u8(48 + ss % 10), c(46)]
    for p in (100_000, 10_000, 1_000, 100, 10, 1):
        out.append(u8(48 + (frac // p) % 10))
    return out, jnp.full(days.shape, width, dtype=jnp.int32)


def build_egress_program(pspecs: tuple, plan: EgressPlan):
    """The (unjitted) render body: words uint32[n_words, R] →
    (ebytes uint8[R, total_width], elens int32[R, n_rendered])."""
    from . import bitpack

    layout = bitpack.layout_for_specs(pspecs)

    def fn(words):
        import jax.numpy as jnp

        n_rows = words.shape[1]
        bufs, lens = [], []
        for slot, kind, width in zip(plan.slots, plan.kinds, plan.widths):
            fields = _slot_fields(layout, slot)

            def get(name, fields=fields):
                return _field_value(words, fields, name, n_rows)

            if kind is CellKind.BOOL:
                bs, L = _render_bool(get("v"), width)
            elif kind in _INT_KINDS:
                s = fields["v"]
                raw = _extract(words, s)
                if s.zigzag:
                    mag = (raw >> 1) + (raw & jnp.uint32(1))
                    neg = (raw & jnp.uint32(1)).astype(jnp.int32)
                else:
                    mag, neg = raw, jnp.zeros((n_rows,), dtype=jnp.int32)
                bs, L = _render_u32_family(mag, neg, width,
                                           _MAX_DIGITS[kind])
            elif kind is CellKind.I64:
                raws = {}
                for name in ("neg", "l0", "l1", "l2"):
                    s = fields.get(name)
                    raws[name] = _extract(words, s) if s is not None \
                        else jnp.zeros((n_rows,), dtype=jnp.uint32)
                bs, L = _render_i64(raws["neg"].astype(jnp.int32),
                                    raws["l0"], raws["l1"], raws["l2"],
                                    width)
            elif kind is CellKind.DATE:
                bs, L = _render_date(get("days"), width)
            elif kind in (CellKind.TIMESTAMP, CellKind.TIMESTAMPTZ):
                bs, L = _render_timestamp(get("days"), get("ms"),
                                          get("us"), width)
            else:  # pragma: no cover — plan_for_specs filters kinds
                raise AssertionError(kind)
            bufs.append(jnp.stack(bs, axis=1))
            lens.append(L)
        return (jnp.concatenate(bufs, axis=1),
                jnp.stack(lens, axis=1).astype(jnp.int32))

    return fn


def build_egress_fn(pspecs: tuple, plan: EgressPlan, mesh=None):
    """Jit the render body. On the mesh path the words arrive sharded
    over rows on axis 1 (the decode program's output spec) and both
    outputs leave row-sharded on axis 0 — elementwise along rows, so the
    partitioner keeps every shard local (the ir-collective contract
    holds for egress programs too). No donation: the words buffer is
    still the decode fetch's source."""
    import jax

    body = build_egress_program(pspecs, plan)
    if mesh is None:
        return jax.jit(body)
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    return jax.jit(
        body,
        in_shardings=(NamedSharding(mesh, P(None, "sp")),),
        out_shardings=(NamedSharding(mesh, P("sp", None)),
                       NamedSharding(mesh, P("sp", None))))


def lower_egress_program(pspecs: tuple, encoder: str, row_capacity: int,
                         mesh=None):
    """(jitted, example_avals, lowered) for one egress program — the IR
    tier's lowering entry (analysis/ir/runner.py), built through the
    SAME constructor production dispatch uses so the verified artifact
    is the shipped one. Raises ValueError when the layout has no
    renderable fields under `encoder`."""
    import jax
    import jax.numpy as jnp

    from .bitpack import layout_for_specs

    plan = plan_for_specs(pspecs, encoder)
    if plan is None:
        raise ValueError(f"no egress plan for encoder {encoder!r} over "
                         f"{len(pspecs)} specs")
    fn = build_egress_fn(pspecs, plan, mesh=mesh)
    n_words = layout_for_specs(pspecs).n_words
    avals = (jax.ShapeDtypeStruct((n_words, row_capacity), jnp.uint32),)
    return fn, avals, fn.lower(*avals)


def egress_fn_key(row_capacity: int, pspecs: tuple, encoder: str,
                  mesh_fp) -> tuple:
    """Module program-cache key for one egress program. Same tuple
    arity/ordering as decode keys so the program store, the observed-
    signature recorder and the warm-restart path handle it unchanged;
    the ("egress", encoder) marker rides the pred_fp slot (decode keys
    hold None or a predicate fingerprint there — never a 2-tuple
    starting with "egress", so the spaces cannot collide). key[-1] True:
    the persist contract expects NO donation, which is exactly this
    program's stance on every backend."""
    return (row_capacity, pspecs, False, mesh_fp, False,
            ("egress", encoder), True)


# background-compile bookkeeping, mirroring engine._BG_COMPILE_KEYS: a
# cold egress program must never block a streaming dispatch — batches
# simply ship without device egress (destinations fall back to the host
# twins) until the compile lands
_EGRESS_BG_KEYS: set = set()
_EGRESS_BG_FAILED: set = set()
_EGRESS_BG_LOCK = threading.Lock()


def egress_fn_ready(key: tuple, builder, example_args: tuple,
                    blocking: bool = False):
    """The egress program for `key`, or None while it compiles in the
    background. Memory → disk → (inline when `blocking`, else
    background thread) — the same ladder as the decode host path."""
    from . import program_store
    from .engine import _shared_fn_get, _shared_fn_put

    fn = _shared_fn_get(key)
    if fn is not None:
        return fn
    with _EGRESS_BG_LOCK:
        if key in _EGRESS_BG_FAILED:
            return None
        building = key in _EGRESS_BG_KEYS
    if building:
        return None
    fn = program_store.try_load(key, record_absent=False)
    if fn is not None:
        _shared_fn_put(key, fn)
        return fn
    if blocking:
        try:
            fn = program_store.acquire(key, builder, example_args)
        except Exception:
            with _EGRESS_BG_LOCK:
                _EGRESS_BG_FAILED.add(key)
            log.warning("egress program build failed; wire encoding "
                        "stays on the host twins", exc_info=True)
            return None
        _shared_fn_put(key, fn)
        return fn
    with _EGRESS_BG_LOCK:
        if key in _EGRESS_BG_KEYS or key in _EGRESS_BG_FAILED:
            return None
        _EGRESS_BG_KEYS.add(key)

    def work() -> None:
        try:
            import jax

            f = program_store.acquire(key, builder, example_args)
            jax.block_until_ready(f(*example_args))
            _shared_fn_put(key, f)
        except Exception:
            with _EGRESS_BG_LOCK:
                _EGRESS_BG_FAILED.add(key)
            log.warning("background egress-program compile failed; wire "
                        "encoding stays on the host twins", exc_info=True)
        finally:
            with _EGRESS_BG_LOCK:
                _EGRESS_BG_KEYS.discard(key)

    try:
        # non-daemon for the same reason as the decode background
        # compile: a daemon thread killed mid-XLA-build aborts the
        # process from C++ at interpreter teardown
        threading.Thread(target=work, name="etl-egress-bg-compile",
                         daemon=False).start()
    except RuntimeError:
        with _EGRESS_BG_LOCK:
            _EGRESS_BG_KEYS.discard(key)
            _EGRESS_BG_FAILED.add(key)
    return None


def reset_for_tests() -> None:
    with _EGRESS_BG_LOCK:
        _EGRESS_BG_KEYS.clear()
        _EGRESS_BG_FAILED.clear()


# ---------------------------------------------------------------------------
# fetched-egress transport
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DeviceEgress:
    """Wire-ready text buffers riding a decoded batch
    (`ColumnarBatch.device_egress`). `fields` maps SCHEMA column index →
    (bytes uint8[n, W] left-aligned, lens int32[n]); `untrusted` lists
    row indices whose device bytes must not be used (fallback rows,
    oracle fixups) — destinations render those rows per-value and splice
    whole lines."""

    encoder: str
    n_rows: int
    fields: dict
    untrusted: np.ndarray

    def field(self, col_index: int):
        return self.fields.get(col_index)

    @classmethod
    def concat(cls, parts: list) -> "DeviceEgress | None":
        """Merge per-event-batch egress into one buffer set for a
        coalesced run. All-or-nothing: one part without device buffers
        (or a field-set/encoder mismatch) drops the merged fast path —
        correctness never depends on egress being present."""
        if not parts or any(p is None for p in parts):
            return None
        enc = parts[0].encoder
        keys = set(parts[0].fields)
        if any(p.encoder != enc or set(p.fields) != keys for p in parts):
            return None
        fields: dict = {}
        for k in keys:
            fields[k] = (
                np.concatenate([p.fields[k][0] for p in parts], axis=0),
                np.concatenate([p.fields[k][1] for p in parts]))
        untr, off = [], 0
        for p in parts:
            if p.untrusted.size:
                untr.append(p.untrusted + off)
            off += p.n_rows
        return cls(enc, off, fields,
                   np.concatenate(untr) if untr
                   else np.zeros(0, dtype=np.int64))


def materialize(egress_out: tuple, plan, dense, n: int,
                untrusted) -> "DeviceEgress | None":
    """Fetch an egress dispatch's outputs and index them by schema
    column. `plan` is the batch's canonical pack plan (None = identity):
    real column j rendered from canonical slot plan.slot_of[j], the
    mirror of `_assemble`'s unpack mapping."""
    ebytes_d, elens_d, eplan = egress_out
    ebytes = np.asarray(ebytes_d)
    elens = np.asarray(elens_d)
    pos_of = {s: i for i, s in enumerate(eplan.slots)}
    offs = eplan.offsets
    fields: dict = {}
    for j, spec in enumerate(dense):
        slot = plan.slot_of[j] if plan is not None else j
        i = pos_of.get(slot)
        if i is None or eplan.kinds[i] is not spec.kind:
            continue
        o, w = offs[i], eplan.widths[i]
        fields[spec.index] = (ebytes[:n, o:o + w], elens[:n, i])
    if not fields:
        return None
    untr = np.asarray(untrusted, dtype=np.int64) \
        if untrusted is not None else np.zeros(0, dtype=np.int64)
    return DeviceEgress(eplan.encoder, n, fields, untr)


# ---------------------------------------------------------------------------
# host twins + vectorized line assembly
# ---------------------------------------------------------------------------
#
# piece = ("const", bytes-as-uint8[k])                same bytes every row
#       | ("fixed", buf uint8[n, W], lens int32[n])   left-aligned
#       | ("var",   values uint8[total], offsets int64[n+1])
#
# A destination builds one piece per wire token (field text, separator,
# JSON key, metadata column) and `assemble_rows` scatters them into one
# contiguous buffer with two cumsums and one fancy-index store per piece
# — no per-row Python.

def const_piece(b: bytes) -> tuple:
    return ("const", np.frombuffer(b, dtype=np.uint8))


def fixed_piece(buf: np.ndarray, lens: np.ndarray) -> tuple:
    return ("fixed", buf, lens)


def var_from_texts(items: list) -> tuple:
    """Variable piece from per-row bytes (the host per-value path)."""
    n = len(items)
    lens = np.fromiter((len(b) for b in items), dtype=np.int64, count=n)
    offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens, out=offs[1:])
    total = int(offs[-1])
    values = np.frombuffer(b"".join(items), dtype=np.uint8) if total \
        else np.zeros(0, dtype=np.uint8)
    return ("var", values, offs)


def patch_rows_fixed(buf: np.ndarray, lens: np.ndarray, rows: np.ndarray,
                     text: bytes) -> tuple:
    """Overwrite `rows` of a fixed piece with a short constant (NULL
    markers). Copies first: device-fetched buffers are read-only and
    lens views may be shared across columns."""
    if rows.size == 0:
        return buf, lens
    nb = np.frombuffer(text, dtype=np.uint8)
    buf = np.array(buf, copy=True)
    lens = np.array(lens, dtype=np.int64, copy=True)
    buf[rows, :nb.size] = nb
    lens[rows] = nb.size
    return buf, lens


def int_text_fixed(arr: np.ndarray) -> tuple:
    """Host twin of the device int renderers: same digits as str(int)."""
    a = np.asarray(arr)
    n = a.shape[0]
    if n == 0:
        return np.zeros((0, 21), dtype=np.uint8), np.zeros(0, np.int64)
    codes = np.ascontiguousarray(a.astype("U21")).view(np.uint32) \
        .reshape(n, 21)
    return codes.astype(np.uint8), \
        np.count_nonzero(codes, axis=1).astype(np.int64)


def bool_text_fixed(flags: np.ndarray) -> tuple:
    t = np.frombuffer(b"true\x00", dtype=np.uint8)
    f = np.frombuffer(b"false", dtype=np.uint8)
    m = np.asarray(flags).astype(bool)
    return np.where(m[:, None], t, f), np.where(m, 4, 5).astype(np.int64)


def date_text_fixed(days: np.ndarray) -> tuple:
    """Host twin of the device DATE renderer (in-range rows only —
    callers mask specials/out-of-range rows to the per-value oracle,
    same as the columnar encoders do)."""
    n = np.asarray(days).shape[0]
    if n == 0:
        return np.zeros((0, 10), dtype=np.uint8), np.zeros(0, np.int64)
    s = np.datetime_as_string(np.asarray(days).astype("M8[D]"), unit="D")
    codes = np.ascontiguousarray(s.astype("U10")).view(np.uint32) \
        .reshape(n, 10)
    return codes.astype(np.uint8), np.full(n, 10, dtype=np.int64)


def timestamp_text_fixed(micros: np.ndarray) -> tuple:
    """Host twin of the device TIMESTAMP renderer: always 6 fractional
    digits, 'T' replaced by a space — np.datetime_as_string(unit='us')
    exactly as the ClickHouse columnar encoder renders it."""
    n = np.asarray(micros).shape[0]
    if n == 0:
        return np.zeros((0, 26), dtype=np.uint8), np.zeros(0, np.int64)
    s = np.char.replace(
        np.datetime_as_string(np.asarray(micros, dtype=np.int64)
                              .astype("M8[us]"), unit="us"), "T", " ")
    codes = np.ascontiguousarray(s.astype("U26")).view(np.uint32) \
        .reshape(n, 26)
    return codes.astype(np.uint8), np.full(n, 26, dtype=np.int64)


def assemble_rows(n: int, pieces: list,
                  override: "dict | None" = None) -> tuple:
    """Scatter `pieces` into one contiguous byte buffer, one row per
    line. `override` maps row index → full replacement bytes for that
    row (the oracle-rendered untrusted/special rows) — overridden rows
    take NO bytes from any piece. Returns (out uint8[total],
    row_offsets int64[n+1])."""
    m = len(pieces)
    L = np.zeros((n, m), dtype=np.int64)
    for j, p in enumerate(pieces):
        if p[0] == "const":
            L[:, j] = p[1].size
        elif p[0] == "fixed":
            L[:, j] = p[2]
        else:
            L[:, j] = p[2][1:] - p[2][:-1]
    if override:
        rows = np.fromiter(override.keys(), dtype=np.int64,
                           count=len(override))
        L[rows, :] = 0
    row_len = L.sum(axis=1)
    if override:
        for r, b in override.items():
            row_len[r] = len(b)
    starts = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(row_len, out=starts[1:])
    out = np.empty(int(starts[-1]), dtype=np.uint8)
    within = np.zeros(n, dtype=np.int64)
    for j, p in enumerate(pieces):
        lj = L[:, j]
        dst0 = starts[:-1] + within
        if p[0] == "const":
            c = p[1]
            if c.size:
                live = np.flatnonzero(lj) if override else None
                d = dst0[live] if live is not None else dst0
                idx = d[:, None] + np.arange(c.size, dtype=np.int64)
                out[idx.reshape(-1)] = np.tile(c, d.size)
        else:
            tot = int(lj.sum())
            if tot:
                cum_excl = np.cumsum(lj) - lj
                pos = np.arange(tot, dtype=np.int64) \
                    - np.repeat(cum_excl, lj)
                dst = np.repeat(dst0, lj) + pos
                if p[0] == "fixed":
                    buf = p[1]
                    w = buf.shape[1]
                    src = np.repeat(np.arange(n, dtype=np.int64) * w,
                                    lj) + pos
                    out[dst] = buf.reshape(-1)[src]
                else:
                    src = np.repeat(p[2][:-1], lj) + pos
                    out[dst] = p[1][src]
        within += lj
    if override:
        for r, b in override.items():
            if b:
                out[starts[r]:starts[r] + len(b)] = \
                    np.frombuffer(b, dtype=np.uint8)
    return out, starts
