"""Fleet chaos: 100 pipelines, one coordinator, hard kills mid-roll.

`python -m etl_tpu.chaos --fleet` — the reconcile-under-chaos proof the
fleet subsystem ships with (docs/fleet.md). One seeded run drives the
whole story, deterministic per seed:

  1. EMPTY → STEADY: a 100-pipeline FleetSpec (tenancy profiles = the
     workload-mix names, quotas that visibly clamp two tenants) lands
     on an empty simulated fleet; the reconciler must converge within
     `CONVERGE_TICKS_MAX` working ticks and the observed fleet must
     EQUAL the quota-clamped placement.
  2. SPEC EDITS: remove / add / resize in one versioned edit; converge
     again; per-pipeline delivery invariants (zero loss, dup ≤
     1 + rolls) hold through the rolls.
  3. KILL MID-ROLL, twice — the two crash windows the actuation
     journal distinguishes:
       - crash BEFORE actuation: the coordinator dies after persisting
         the pending record, before the runtime verb ran. The
         successor's resume must RE-DRIVE the verb (observed ≠ target)
         and settle it.
       - crash AFTER actuation: the coordinator dies after the runtime
         verb landed, before the settle write. The successor must
         settle from OBSERVATION alone — zero runtime calls.
     After each kill: a second resume() must find nothing (idempotent),
     and the global ledger must balance: every runtime actuation in the
     log maps 1:1 to an APPLIED journal record — `double_actuations ==
     len(actuation_log) − applied_records == 0`.
  4. SIGNAL BUS: the three policy plugins (PID lag-target, adaptive
     ack-depth, admission SLO weights) run over synthetic per-pipeline
     frames on one bus; the scenario asserts the PID recommends scale-up
     for the lagging pipeline, the ack-depth plugin retargets a live
     AckWindow from the measured histogram, and the spec's quota weights
     (boosted for the lagging tenant) reach the AdmissionScheduler.
  5. LEAK CHECKS via the list-pipelines primitive: observed ids ==
     placed ids exactly — nothing the spec dropped survives, nothing
     phantom appears, retired pipelines stay retired.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from ..autoscale.signals import ShardSignals, SignalFrame
from ..fleet import (AckDepthConfig, AdaptiveAckDepthPolicy,
                     AdmissionWeightPolicy, FleetReconciler,
                     FleetSignalBus, PidLagPolicy, SimulatedFleetRuntime,
                     seeded_fleet_spec)
from ..fleet.reconciler import place_fleet
from ..fleet.spec import PipelineSpec
from ..ops.pipeline import AdmissionScheduler
from ..runtime.ack_window import CopyAckWindow
from ..store.memory import MemoryStore

#: working-tick convergence bound — one tick applies every diffed verb,
#: so a healthy reconcile converges in ONE working tick per spec change;
#: 3 leaves room for held pipelines without masking a livelock
CONVERGE_TICKS_MAX = 3

FLEET_SIZE = 100


@dataclass
class FleetChaosRun:
    """Everything `--fleet` prints, ok iff no failure was recorded."""

    seed: int
    fleet_size: int = 0
    converge_ticks: "dict[str, int]" = field(default_factory=dict)
    actuations: int = 0
    applied_records: int = 0
    double_actuations: int = 0
    resume_modes: "list[str]" = field(default_factory=list)
    bus_actions: "dict[str, int]" = field(default_factory=dict)
    failures: "list[str]" = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.failures

    def expect(self, cond: bool, message: str) -> None:
        if not cond:
            self.failures.append(message)

    def describe(self) -> dict:
        return {
            "scenario": "fleet_reconcile_chaos",
            "seed": self.seed,
            "fleet_size": self.fleet_size,
            "converge_ticks": dict(self.converge_ticks),
            "actuations": self.actuations,
            "applied_records": self.applied_records,
            "double_actuations": self.double_actuations,
            "resume_modes": list(self.resume_modes),
            "bus_actions": dict(self.bus_actions),
            "failures": list(self.failures),
            "ok": self.ok,
        }


async def _applied_records(store) -> int:
    journals = await store.get_fleet_journals()
    return sum(1 for doc in journals.values()
               for e in doc.get("entries", [])
               if e.get("status") == "applied")


async def _pending_records(store) -> int:
    journals = await store.get_fleet_journals()
    return sum(1 for doc in journals.values()
               for e in doc.get("entries", [])
               if e.get("status") == "pending")


async def _check_steady(run: FleetChaosRun, label: str, store, runtime,
                        spec) -> None:
    """The post-convergence ledger: observed == placement (the leak
    check, through the list-pipelines primitive), zero pendings, zero
    double-actuations, per-pipeline delivery invariants."""
    observed = await runtime.list_pipelines()
    targets = place_fleet(spec)
    run.expect(observed == targets,
               f"{label}: observed fleet != placement "
               f"({len(observed)} vs {len(targets)} pipelines)")
    leaked = set(observed) - set(targets)
    run.expect(not leaked, f"{label}: leaked pipelines {sorted(leaked)}")
    run.expect(await _pending_records(store) == 0,
               f"{label}: pending journal records after convergence")
    applied = await _applied_records(store)
    run.actuations = len(runtime.actuation_log)
    run.applied_records = applied
    run.double_actuations = len(runtime.actuation_log) - applied
    run.expect(run.double_actuations == 0,
               f"{label}: {run.double_actuations} runtime actuations "
               f"not backed by an applied journal record")
    for violation in runtime.violations():
        run.failures.append(f"{label}: {violation}")


async def _kill_mid_roll(run: FleetChaosRun, *, store, runtime, spec,
                         window: str, pipeline_id: int, to_k: int,
                         label: str) -> None:
    """Hard-kill the coordinator inside one crash window of pipeline
    `pipeline_id`'s resize, then drive a successor through resume +
    converge and assert the ledger balanced."""
    edited = spec.with_edit(resize={pipeline_id: to_k})
    await store.update_fleet_spec(edited.to_json())
    blocked = asyncio.Event()

    async def hook(verb: str, pid: int) -> None:
        if pid == pipeline_id:
            blocked.set()
            await asyncio.Event().wait()  # park until cancelled

    setattr(runtime, window, hook)
    coordinator = FleetReconciler(store=store, runtime=runtime)
    task = asyncio.ensure_future(coordinator.tick())
    await asyncio.wait_for(blocked.wait(), timeout=10)
    # the pending record is already durable — that ordering IS
    # persist-then-actuate; assert it before the kill
    run.expect(await _pending_records(store) == 1,
               f"{label}: expected exactly one pending record at kill")
    task.cancel()
    try:
        await task
    except asyncio.CancelledError:
        pass
    setattr(runtime, window, None)

    successor = FleetReconciler(store=store, runtime=runtime)
    settled = await successor.resume()
    run.expect(len(settled) == 1,
               f"{label}: successor settled {len(settled)} records, "
               f"wanted 1")
    mode = "settle" if window == "post_actuate" else "redrive"
    run.resume_modes.append(f"{label}:{mode}")
    again = await successor.resume()
    run.expect(again == [],
               f"{label}: second resume() settled records — not "
               f"idempotent")
    ticks = await successor.converge()
    run.converge_ticks[label] = ticks
    run.expect(ticks <= CONVERGE_TICKS_MAX,
               f"{label}: converge took {ticks} working ticks "
               f"(max {CONVERGE_TICKS_MAX})")
    observed = await runtime.list_pipelines()
    run.expect(observed.get(pipeline_id) == to_k,
               f"{label}: pipeline {pipeline_id} at "
               f"K={observed.get(pipeline_id)}, wanted {to_k}")
    await _check_steady(run, label, store, runtime, edited)


def _drive_bus(run: FleetChaosRun, spec) -> None:
    """Phase 4: the three control loops as plugins on one bus."""
    scheduler = AdmissionScheduler(capacity=4)
    window = CopyAckWindow(limit=2)
    bus = FleetSignalBus()
    bus.bind_spec(spec)
    pid_policy = PidLagPolicy()
    # a seeded-synthetic ack histogram: 24 acks of 400ms against a 50ms
    # flush cadence wants depth ceil(0.4/0.05)+1 = 9
    depth_policy = AdaptiveAckDepthPolicy(
        window_of=lambda pid: window,
        histogram_read=lambda: (24, 24 * 0.4),
        config=AckDepthConfig())
    weight_policy = AdmissionWeightPolicy(bus, scheduler=scheduler)
    for plugin in (pid_policy, depth_policy, weight_policy):
        bus.register(plugin)

    lagging = spec.pipelines[0]
    healthy = spec.pipelines[1]
    for tick in range(1, 4):
        bus.publish(lagging.pipeline_id, SignalFrame(
            tick=tick, at_s=float(tick), shards=tuple(
                ShardSignals(shard=s, lag_bytes=256 * 1024 * 1024)
                for s in range(lagging.shard_count))))
        bus.publish(healthy.pipeline_id, SignalFrame(
            tick=tick, at_s=float(tick), shards=tuple(
                ShardSignals(shard=s, lag_bytes=1024)
                for s in range(healthy.shard_count))))
        actions = bus.step()
        for a in actions:
            run.bus_actions[a["plugin"]] = \
                run.bus_actions.get(a["plugin"], 0) + 1

    rec = pid_policy.recommendations.get(lagging.pipeline_id)
    run.expect(rec is not None and rec > lagging.shard_count,
               f"bus: PID never recommended scale-up for the lagging "
               f"pipeline (got {rec})")
    run.expect(healthy.pipeline_id not in pid_policy.recommendations,
               "bus: PID recommended a resize for the healthy pipeline")
    run.expect(window.effective_limit() == 9,
               f"bus: ack window depth {window.effective_limit()}, "
               f"wanted 9 from the measured histogram")
    lag_tenant = lagging.tenant_id
    weight = weight_policy.applied_weights.get(lag_tenant)
    base = spec.quotas.get(lag_tenant)
    base_w = base.slo_weight if base else 1.0
    run.expect(weight is not None and weight > base_w,
               f"bus: lagging tenant weight {weight} not boosted over "
               f"base {base_w}")
    # the healthy tenant's weight lands UNboosted at its quota base
    ok_tenant = healthy.tenant_id
    ok_quota = spec.quotas.get(ok_tenant)
    ok_base = ok_quota.slo_weight if ok_quota else 1.0
    got = weight_policy.applied_weights.get(ok_tenant)
    run.expect(got is not None and abs(got - ok_base) < 1e-9,
               f"bus: healthy tenant weight {got}, wanted base {ok_base}")


async def run_fleet_chaos(seed: int = 7,
                          fleet_size: int = FLEET_SIZE) -> FleetChaosRun:
    run = FleetChaosRun(seed=seed, fleet_size=fleet_size)
    store = MemoryStore()
    runtime = SimulatedFleetRuntime(seed=seed)
    spec = seeded_fleet_spec(seed, fleet_size)
    await store.update_fleet_spec(spec.to_json())

    # phase 1: empty → steady
    coordinator = FleetReconciler(store=store, runtime=runtime)
    run.expect(await coordinator.resume() == [],
               "initial resume() settled records on a fresh fleet")
    ticks = await coordinator.converge()
    run.converge_ticks["initial"] = ticks
    run.expect(ticks <= CONVERGE_TICKS_MAX,
               f"initial converge took {ticks} working ticks "
               f"(max {CONVERGE_TICKS_MAX})")
    await _check_steady(run, "initial", store, runtime, spec)

    # phase 2: one versioned edit — remove, add, resize together
    removed = [1, 2, 3]
    added = [PipelineSpec(pipeline_id=fleet_size + 100 + i,
                          tenant_id="tenant-burst", shard_count=2,
                          profile="tiny_txs") for i in range(3)]
    resized = {10: 6, 11: 1}
    spec = spec.with_edit(add=added, remove=removed, resize=resized)
    await store.update_fleet_spec(spec.to_json())
    ticks = await coordinator.converge()
    run.converge_ticks["edit"] = ticks
    run.expect(ticks <= CONVERGE_TICKS_MAX,
               f"edit converge took {ticks} working ticks "
               f"(max {CONVERGE_TICKS_MAX})")
    observed = await runtime.list_pipelines()
    for pid in removed:
        run.expect(pid not in observed,
                   f"edit: removed pipeline {pid} still running")
    for p in added:
        run.expect(observed.get(p.pipeline_id) == p.shard_count,
                   f"edit: added pipeline {p.pipeline_id} not at "
                   f"K={p.shard_count}")
    await _check_steady(run, "edit", store, runtime, spec)

    # phase 3: the two crash windows. Kill targets are pipelines of
    # UNclamped tenants (seeded K is 1..4, targets 5/6 differ for sure):
    # a quota-clamped tenant's resize can be a placement no-op, and a
    # roll that diffs to nothing has no crash window to kill in.
    await _kill_mid_roll(run, store=store, runtime=runtime, spec=spec,
                         window="pre_actuate", pipeline_id=23, to_k=6,
                         label="kill_before_actuation")
    spec = spec.with_edit(resize={23: 6})  # re-anchor to the store's truth
    await _kill_mid_roll(run, store=store, runtime=runtime, spec=spec,
                         window="post_actuate", pipeline_id=24, to_k=5,
                         label="kill_after_actuation")

    # phase 4: the signal bus plugins
    _drive_bus(run, spec.with_edit(resize={24: 5}))
    return run
