"""Dead-letter / poison-pill chaos: the availability proof for
poison isolation (runtime/poison.py, docs/dead-letter.md).

`python -m etl_tpu.chaos --dlq` runs two seeded scenarios:

  dlq_poison_quarantine — a multi-table CDC stream where table 0's
    inserts carry seeded poison rows the destination rejects with
    DESTINATION_REJECTED. The run must show: poison rows bisected out
    and parked on the durable dead-letter store (inside the probe-write
    bound), table 0 QUARANTINED once the poison budget trips (later
    events parked, counted), every OTHER table delivering its FULL
    workload while the quarantine stands, the extended zero-loss
    invariant `delivered ∪ dead-lettered == committed truth`, and the
    operator round trip: replay the DLQ through the destination seam +
    unquarantine → the destination's final view equals committed truth
    EXACTLY, and a second replay is a no-op (idempotent).

  dlq_bisection_crash — the pipeline is hard-killed (process-death
    semantics) while a bisection is mid-flight (crash armed on the
    POISON_BISECT failpoint), restarted from durable progress, and must
    reconverge: every poison row in the DLQ, survivors fully delivered,
    duplicates within budget = 1 + restarts, monotonic durable LSN, no
    leaks.

Both replay bit-identically per seed (the workload generator owns all
randomness and the crash trigger is hit-count-deterministic).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field, replace

from ..config import (BatchConfig, BatchEngine, PipelineConfig,
                      PoisonConfig, RetryConfig, SupervisionConfig)
from ..destinations import PoisonRejectingDestination
from ..dlq import DeadLetterQueue, decode_cell
from ..models.event import DeleteEvent, InsertEvent, UpdateEvent
from ..models.lsn import Lsn
from ..models.table_state import TableStateType
from ..postgres.fake import FakeSource
from ..postgres.slots import apply_slot_name
from ..runtime import poison as poison_mod
from ..workloads import WorkloadGenerator, get_profile
from . import failpoints
from .invariants import (InvariantReport, LeakProbe, _pipeline_thread_count,
                         reconstruct_final_view, view_matches)
from .runner import (RecordingStore, RestartRecord, SimulatedCrash,
                     TracingDestination, _hard_kill, _wait_until)


@dataclass
class DlqRun:
    scenario: str
    seed: int
    report: InvariantReport = field(default_factory=InvariantReport)
    restarts: list[RestartRecord] = field(default_factory=list)
    dlq_entries: int = 0
    poison_entries: int = 0
    parked_entries: int = 0
    quarantined_tables: list[int] = field(default_factory=list)
    isolations: int = 0
    probe_writes: int = 0
    probe_bound: int = 0
    replayed: int = 0
    duration_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.report.ok

    def describe(self) -> dict:
        return {
            "scenario": self.scenario, "seed": self.seed, "ok": self.ok,
            "restarts": [r.describe() for r in self.restarts],
            "dlq_entries": self.dlq_entries,
            "poison_entries": self.poison_entries,
            "parked_entries": self.parked_entries,
            "quarantined_tables": list(self.quarantined_tables),
            "isolations": self.isolations,
            "probe_writes": self.probe_writes,
            "probe_bound": self.probe_bound,
            "replayed": self.replayed,
            "invariants": self.report.describe(),
            "duration_s": round(self.duration_s, 3),
        }


def _dlq_view(entries, table_ids) -> dict:
    """{table_id: {pk: tuple(values)}} from dead-letter entries, rank-
    collapsed exactly like the destination view (a pk's newest entry by
    WAL rank wins; deletes remove)."""
    import json as _json

    ordered = sorted(entries, key=lambda e: (e.commit_lsn, e.tx_ordinal))
    view: dict = {tid: {} for tid in table_ids}
    for e in ordered:
        if e.table_id not in view:
            continue
        doc = _json.loads(e.payload)
        values = tuple(decode_cell(v) for v in doc["values"])
        pk = values[0]
        if e.change_type == 2:  # delete
            view[e.table_id].pop(pk, None)
        else:
            view[e.table_id][pk] = values
    return view


def _check_union(report: InvariantReport, expected: dict,
                 delivered_view: dict, dlq_view: dict) -> None:
    """The extended zero-loss invariant: every committed row is present
    with its final values at the destination OR on the dead-letter
    store; nothing undelivered is missing from both, nothing exists that
    the source never committed."""
    for tid, rows in expected.items():
        got = delivered_view.get(tid, {})
        dlq = dlq_view.get(tid, {})
        for pk, values in rows.items():
            if got.get(pk) == values:
                continue
            if dlq.get(pk) == values:
                continue
            report.fail(
                f"union-zero-loss: table {tid} pk={pk!r} neither "
                f"delivered ({got.get(pk)!r}) nor dead-lettered "
                f"({dlq.get(pk)!r}) with committed values {values!r}")
        for pk in got:
            if pk not in rows:
                report.fail(f"union-zero-loss: table {tid} pk={pk!r} "
                            f"delivered but never committed")


def _check_common(run: DlqRun, *, gen, store, inner, leak_probe,
                  dup_budget: int) -> None:
    """Duplication, monotonic-LSN, and leak checks shared by both
    scenarios (the zero-loss half is the union check — quarantined
    tables deliberately under-deliver to the destination)."""
    counts: dict = {}
    for e in inner.events:
        if not isinstance(e, (InsertEvent, UpdateEvent, DeleteEvent)):
            continue
        row = e.old_row if isinstance(e, DeleteEvent) else e.row
        key = (e.schema.id, int(e.commit_lsn), e.tx_ordinal,
               type(e).__name__, row.values[0])
        counts[key] = counts.get(key, 0) + 1
    for key, n in counts.items():
        if n > dup_budget:
            run.report.fail(f"bounded-dup: event {key} delivered {n}x, "
                            f"budget {dup_budget}")
    for key, lsns in store.progress_log.items():
        for a, b in zip(lsns, lsns[1:]):
            if b < a:
                run.report.fail(f"monotonic-lsn: progress key {key!r} "
                                f"regressed {a} -> {b}")
    if _pipeline_thread_count() > leak_probe.pipeline_threads:
        run.report.fail("no-leaks: decode-pipeline worker threads leaked")
    from ..ops.staging import ARENA_POOL

    if ARENA_POOL.outstanding > leak_probe.arenas_outstanding:
        run.report.fail("no-leaks: staging arena leases leaked")


def _check_probe_bound(run: DlqRun) -> None:
    """The bisection cost bound: per isolation, probe writes must stay
    within one split probe per table + 2·⌈log₂ rows⌉ per poison row
    (quarantine parking costs zero probes)."""
    total = bound = 0
    for t in poison_mod.ISOLATION_TRACE:
        b = poison_mod.bisection_bound(t["rows"], t["tables"],
                                       t["poison_rows"])
        total += t["probe_writes"]
        bound += b
        if t["probe_writes"] > b:
            run.report.fail(
                f"bisection-bound: isolation over {t['rows']} rows / "
                f"{t['tables']} tables found {t['poison_rows']} poison "
                f"rows with {t['probe_writes']} probe writes, bound {b}")
    run.probe_writes = total
    run.probe_bound = bound
    run.isolations = len(poison_mod.ISOLATION_TRACE)


def _make_config(budget_rows: int, window_s: float = 300.0,
                 fill_ms: int = 25) -> PipelineConfig:
    return PipelineConfig(
        pipeline_id=1, publication_name="pub",
        batch=BatchConfig(max_size_bytes=8 * 1024, max_fill_ms=fill_ms,
                          batch_engine=BatchEngine("tpu")),
        apply_retry=RetryConfig(max_attempts=10, initial_delay_ms=15,
                                max_delay_ms=120),
        table_retry=RetryConfig(max_attempts=10, initial_delay_ms=15,
                                max_delay_ms=120),
        supervision=SupervisionConfig(
            check_interval_s=0.25, stall_deadline_s=10.0,
            hang_deadline_s=25.0, restart_backoff_s=1.0),
        poison=PoisonConfig(budget_rows=budget_rows, window_s=window_s),
        wal_sender_timeout_ms=60_000,
        lag_sample_interval_s=0)


async def _collect_dlq(run: DlqRun, store) -> list:
    entries = await store.list_dead_letters(status=None)
    run.dlq_entries = len(entries)
    run.poison_entries = sum(1 for e in entries
                             if e.error_kind != "quarantine")
    run.parked_entries = sum(1 for e in entries
                             if e.error_kind == "quarantine")
    run.quarantined_tables = sorted(await store.get_quarantined_tables())
    return entries


async def run_dlq_poison(seed: int = 7, steps: int = 22,
                         budget_rows: int = 3) -> DlqRun:
    """Scenario 1: poison rows mid-stream → bisection → DLQ →
    quarantine; survivors deliver everything; replay + unquarantine
    restores exact committed truth."""
    failpoints.disarm_all()
    poison_mod.reset_isolation_trace()
    run = DlqRun(scenario="dlq_poison_quarantine", seed=seed)
    t_start = time.monotonic()
    leak_probe = LeakProbe.capture()
    # a poison rate high enough to trip the budget inside the run; the
    # profile's control-group tables (1, 2) stay clean
    profile = replace(get_profile("poison_rows"), poison_rate=0.30,
                      rows_per_tx=6)
    gen = WorkloadGenerator(profile, seed=seed)
    db = gen.build_db()
    store = RecordingStore()
    inner = TracingDestination()
    dest = PoisonRejectingDestination(inner)
    config = _make_config(budget_rows=budget_rows)
    poisoned_tid = gen.table_ids[0]
    survivors = gen.table_ids[1:]

    from ..runtime import Pipeline

    pipeline = Pipeline(config=config, store=store, destination=dest,
                        source_factory=lambda: FakeSource(db))

    async def settled() -> bool:
        """Survivor tables fully delivered AND the union invariant holds
        for the poisoned table (every committed row delivered or
        dead-lettered)."""
        if not view_matches(inner, survivors,
                            {t: gen.expected[t] for t in survivors}):
            return False
        entries = await store.list_dead_letters(status=None)
        dlq = _dlq_view(entries, [poisoned_tid])[poisoned_tid]
        view = reconstruct_final_view(inner, [poisoned_tid])[poisoned_tid]
        for pk, values in gen.expected[poisoned_tid].items():
            if view.get(pk) != values and dlq.get(pk) != values:
                return False
        return True

    try:
        await pipeline.start()
        await _wait_until(
            lambda: all(
                (st := store._states.get(tid)) is not None
                and st.type is TableStateType.READY
                for tid in gen.table_ids), 30.0, "tables never ready")
        while gen.tx_index < steps:
            await gen.run_tx(db)
        deadline = time.monotonic() + 30.0
        while not await settled():
            if time.monotonic() >= deadline:
                run.report.fail("stream never settled: survivors "
                                "undelivered or poison rows missing "
                                "from the DLQ")
                break
            await asyncio.sleep(0.05)
        await pipeline.shutdown_and_wait()
    except Exception as e:
        run.report.fail(f"scenario crashed: {e!r}")
    finally:
        failpoints.release_stalls()
        from ..ops import engine

        engine.clear_forced_oracle()
        await _hard_kill(pipeline)
        await dest.shutdown()

    entries = await _collect_dlq(run, store)
    n_poison_committed = len(gen.poison_pks[poisoned_tid])
    if n_poison_committed < budget_rows:
        run.report.fail(
            f"seed produced only {n_poison_committed} poison rows — "
            f"cannot trip budget {budget_rows}; pick another seed")
    if run.poison_entries < min(budget_rows, n_poison_committed):
        run.report.fail(
            f"only {run.poison_entries} poison rows dead-lettered of "
            f"{n_poison_committed} committed (budget {budget_rows})")
    if poisoned_tid not in run.quarantined_tables:
        run.report.fail(f"table {poisoned_tid} never quarantined despite "
                        f"{run.poison_entries} poison rows over budget "
                        f"{budget_rows}")
    if run.parked_entries == 0:
        run.report.fail("no events parked during quarantine — the "
                        "quarantine never actually parked traffic")
    if not view_matches(inner, survivors,
                        {t: gen.expected[t] for t in survivors}):
        run.report.fail("survivor tables did not deliver their full "
                        "workload during quarantine")
    _check_union(run.report, gen.expected,
                 reconstruct_final_view(inner, gen.table_ids),
                 _dlq_view(entries, gen.table_ids))
    _check_probe_bound(run)
    _check_common(run, gen=gen, store=store, inner=inner,
                  leak_probe=leak_probe, dup_budget=1)

    # operator round trip: replay the DLQ through the destination seam
    # (the "fixed destination" is the unwrapped inner), lift the
    # quarantine, and the final view must equal committed truth EXACTLY
    dlq = DeadLetterQueue(store)
    result = await dlq.replay(inner)
    run.replayed = len(result["replayed"])
    if result["skipped"]:
        run.report.fail(f"replay skipped entries: {result['skipped']}")
    if not await dlq.unquarantine(poisoned_tid):
        run.report.fail("unquarantine found no record to lift")
    if await store.get_quarantined_tables():
        run.report.fail("quarantine record survived the lift")
    if not view_matches(inner, gen.table_ids, gen.expected):
        run.report.fail("replay + unquarantine did not restore the "
                        "exact committed truth at the destination")
    # idempotence: a second replay must be a no-op (every entry already
    # `replayed`) and must not change the final view
    events_before = len(inner.events)
    again = await dlq.replay(inner)
    if again["replayed"]:
        run.report.fail(f"second replay re-delivered "
                        f"{len(again['replayed'])} entries — not "
                        f"idempotent")
    if len(inner.events) != events_before \
            or not view_matches(inner, gen.table_ids, gen.expected):
        run.report.fail("second replay changed the destination view")
    run.duration_s = time.monotonic() - t_start
    return run


async def run_dlq_bisection_crash(seed: int = 7, steps: int = 16,
                                  crash_after_probes: int = 3) -> DlqRun:
    """Scenario 2: hard-kill mid-bisection (crash armed on the
    POISON_BISECT failpoint), restart from durable progress, reconverge
    within the dup budget."""
    failpoints.disarm_all()
    poison_mod.reset_isolation_trace()
    run = DlqRun(scenario="dlq_bisection_crash", seed=seed)
    t_start = time.monotonic()
    leak_probe = LeakProbe.capture()
    # budget high enough that quarantine never trips: this scenario is
    # about crash recovery of the bisection itself
    profile = replace(get_profile("poison_rows"), poison_rate=0.10,
                      rows_per_tx=6)
    gen = WorkloadGenerator(profile, seed=seed)
    db = gen.build_db()
    store = RecordingStore()
    inner = TracingDestination()
    dest = PoisonRejectingDestination(inner)
    config = _make_config(budget_rows=10_000)
    poisoned_tid = gen.table_ids[0]

    crashed = asyncio.Event()
    hits = [0]

    def crash_action() -> None:
        """Process-death trigger at the (crash_after_probes+1)-th probe
        write — and every later one: once tripped, no in-process retry
        can make progress (each re-isolation dies at its first probe),
        so the recovery under test is the RESTARTED pipeline's, exactly
        like a real crash."""
        hits[0] += 1
        if hits[0] > crash_after_probes:
            crashed.set()
            raise SimulatedCrash("hard kill mid-bisection")

    failpoints.arm(failpoints.POISON_BISECT, crash_action)

    from ..runtime import Pipeline

    def make_pipeline():
        return Pipeline(config=config, store=store, destination=dest,
                        source_factory=lambda: FakeSource(db))

    async def settled() -> bool:
        entries = await store.list_dead_letters(status=None)
        dlq = _dlq_view(entries, [poisoned_tid])[poisoned_tid]
        view = reconstruct_final_view(inner, gen.table_ids)
        for tid in gen.table_ids:
            for pk, values in gen.expected[tid].items():
                if view[tid].get(pk) != values \
                        and dlq.get(pk) != values:
                    return False
        return True

    pipeline = make_pipeline()
    try:
        await pipeline.start()
        await _wait_until(
            lambda: all(
                (st := store._states.get(tid)) is not None
                and st.type is TableStateType.READY
                for tid in gen.table_ids), 30.0, "tables never ready")
        while gen.tx_index < steps:
            await gen.run_tx(db)
        await _wait_until(crashed.is_set, 30.0,
                          "the bisection crash never fired — no "
                          "isolation reached the armed probe")
        # hard-kill with the bisection mid-flight: probes already
        # delivered some healthy halves, the DLQ may hold a subset —
        # durable progress never covered the failing flush, so the
        # restart re-streams and re-isolates (idempotent appends)
        await _hard_kill(pipeline)
        failpoints.disarm(failpoints.POISON_BISECT)
        resume = await store.get_durable_progress(apply_slot_name(1))
        run.restarts.append(RestartRecord(
            kind="crash", resume_lsn=int(resume or Lsn.ZERO),
            at_tx=gen.tx_index))
        pipeline = make_pipeline()
        await pipeline.start()
        deadline = time.monotonic() + 30.0
        while not await settled():
            if time.monotonic() >= deadline:
                run.report.fail("post-restart stream never reconverged "
                                "to delivered ∪ dead-lettered == "
                                "committed truth")
                break
            await asyncio.sleep(0.05)
        await pipeline.shutdown_and_wait()
    except Exception as e:
        run.report.fail(f"scenario crashed: {e!r}")
    finally:
        failpoints.disarm_all()
        from ..ops import engine

        engine.clear_forced_oracle()
        await _hard_kill(pipeline)
        await dest.shutdown()

    entries = await _collect_dlq(run, store)
    if not crashed.is_set():
        run.report.fail("crash never armed — scenario proved nothing")
    n_poison_committed = len(gen.poison_pks[poisoned_tid])
    if n_poison_committed == 0:
        run.report.fail("seed produced no poison rows")
    if run.poison_entries < n_poison_committed:
        run.report.fail(
            f"{n_poison_committed - run.poison_entries} poison rows "
            f"missing from the DLQ after crash recovery")
    _check_union(run.report, gen.expected,
                 reconstruct_final_view(inner, gen.table_ids),
                 _dlq_view(entries, gen.table_ids))
    _check_probe_bound(run)
    # budget: the crash re-streams the in-flight window once — the
    # healthy complement of the interrupted isolation may deliver twice
    _check_common(run, gen=gen, store=store, inner=inner,
                  leak_probe=leak_probe,
                  dup_budget=1 + len(run.restarts))
    run.duration_s = time.monotonic() - t_start
    return run


async def run_dlq_scenarios(seed: int = 7) -> "list[DlqRun]":
    return [await run_dlq_poison(seed=seed),
            await run_dlq_bisection_crash(seed=seed)]
