"""etl-chaos: deterministic fault injection + crash-recovery verification.

Three parts (docs/chaos.md):

  - `failpoints` — the named-site injection registry (grown from
    runtime/failpoints.py; that module is now a re-export shim);
  - `scenario` / `corpus` — seeded, reproducible fault schedules armed
    across layers (wire, decode pipeline, device, destination, store,
    hard crash→restart);
  - `runner` / `invariants` — runs a scenario against the fake walsender
    + MemoryDestination and asserts the recovery invariants: zero-loss,
    bounded duplication, monotonic durable LSN, store consistency, no
    leaked tasks / arenas / pipeline threads.

`python -m etl_tpu.chaos --seed N` replays a scenario deterministically.

Only `failpoints` is imported eagerly: the runtime package imports it at
module-import time, so the heavyweight runner/corpus (which import the
runtime back) resolve lazily to keep the import graph acyclic.
"""

from __future__ import annotations

from . import failpoints  # noqa: F401

_LAZY = {
    "FaultSpec": "scenario",
    "Scenario": "scenario",
    "InvariantReport": "invariants",
    "check_invariants": "invariants",
    "ChaosRun": "runner",
    "run_scenario": "runner",
    "SCENARIOS": "corpus",
    "get_scenario": "corpus",
}

__all__ = ["failpoints", *_LAZY]


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module 'etl_tpu.chaos' has no attribute "
                             f"{name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
